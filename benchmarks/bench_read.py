"""Degraded-read benchmark: windowed parallel reader vs round-1 serial path.

Scenario (BASELINE.md config #3): 16-drive EC 8+8, 64 MiB object, 2 drives
lost, full-object GET. The round-1 path read shards one-at-a-time in a
python loop and reconstructed per 1 MiB block via dict-based numpy
(`coder.reconstruct_block`); round 2 fans shard reads onto a thread pool,
pipelines the next window under the current decode, and reconstructs
whole windows in one batched GF-LUT (or device) matrix apply.

Run: python benchmarks/bench_read.py
"""

import os
import shutil
import sys
import tempfile
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
os.environ.setdefault("MINIO_TPU_BACKEND", "numpy")

import numpy as np

from minio_tpu.erasure import bitrot_io
from minio_tpu.erasure.set import DIGEST, ErasureSet
from minio_tpu.storage.xlstorage import XLStorage

SIZE = 64 * 1024 * 1024


def legacy_read(es, bucket, obj):
    """Round-1 _read_range: serial shard reads + per-block dict reconstruct."""
    fi, metas, _, _ = es._quorum_fileinfo(bucket, obj, "", read_data=True)
    d = fi.erasure.data_blocks
    coder = es.coder(d, fi.erasure.parity_blocks)
    sources = es._shard_sources(fi, metas)
    bad = set()
    out = []
    for part in fi.parts:
        for block_i, (data_len, per) in enumerate(coder.shard_sizes_for(part.size)):
            f_off = bitrot_io.block_offset(coder.shard_size, block_i)
            got = {}
            for idx in range(es.n):
                if len(got) >= d:
                    break
                if idx in sources and idx not in bad:
                    disk, m = sources[idx]
                    try:
                        buf = disk.read_file(
                            bucket, f"{obj}/{fi.data_dir}/part.{part.number}",
                            f_off, DIGEST + per,
                        )
                        got[idx] = bitrot_io.verify_block(buf, per)
                    except Exception:
                        bad.add(idx)
            if all(i in got for i in range(d)):
                block = b"".join(got[i] for i in range(d))[:data_len]
            else:
                rec = coder.reconstruct_block(
                    {i: np.frombuffer(v, dtype=np.uint8) for i, v in got.items()}, per
                )
                block = b"".join(rec[i].tobytes() for i in range(d))[:data_len]
            out.append(block)
    return b"".join(out)


def main():
    base = tempfile.mkdtemp(prefix="bench-read-")
    try:
        disks = [XLStorage(os.path.join(base, f"d{i}")) for i in range(16)]
        es = ErasureSet(disks, default_parity=8)
        es.make_bucket("bench")
        rng = np.random.default_rng(0)
        data = rng.integers(0, 256, size=SIZE, dtype=np.uint8).tobytes()
        es.put_object("bench", "obj", data)
        # lose the two drives holding erasure data shards 0 and 1 (worst
        # case: every block needs reconstruction; killing arbitrary drives
        # may hit parity shards, which decode as a pure pass-through)
        fi, metas, _, _ = es._quorum_fileinfo("bench", "obj", "", read_data=True)
        src = es._shard_sources(fi, metas)
        for idx in (0, 1):
            shutil.rmtree(os.path.join(src[idx][0].root, "bench"))

        t0 = time.perf_counter()
        got = legacy_read(es, "bench", "obj")
        t_legacy = time.perf_counter() - t0
        assert got == data

        for _ in range(2):  # warm page cache for fairness, take best
            t0 = time.perf_counter()
            _, it = es.get_object("bench", "obj")
            got = b"".join(it)
            t_new = time.perf_counter() - t0
        assert got == data

        mib = SIZE / 2**20
        print(f"legacy serial read: {t_legacy:.3f}s ({mib / t_legacy:.0f} MiB/s)")
        print(f"windowed parallel:  {t_new:.3f}s ({mib / t_new:.0f} MiB/s)")
        print(f"speedup: {t_legacy / t_new:.1f}x")
    finally:
        shutil.rmtree(base, ignore_errors=True)


class _SlowDisk:
    """Wraps a StorageAPI adding per-read latency (remote-drive model:
    the reference reads remote shards over HTTP at ~0.5-2 ms RTT)."""

    def __init__(self, inner, delay_s=0.001):
        self._inner = inner
        self._delay = delay_s

    def read_file(self, *a, **kw):
        time.sleep(self._delay)
        return self._inner.read_file(*a, **kw)

    def __getattr__(self, name):
        return getattr(self._inner, name)


def main_latency(delay=0.001):
    base = tempfile.mkdtemp(prefix="bench-read-lat-")
    try:
        disks = [XLStorage(os.path.join(base, f"d{i}")) for i in range(16)]
        es = ErasureSet(disks, default_parity=8)
        es.make_bucket("bench")
        rng = np.random.default_rng(0)
        data = rng.integers(0, 256, size=SIZE, dtype=np.uint8).tobytes()
        es.put_object("bench", "obj", data)
        fi, metas, _, _ = es._quorum_fileinfo("bench", "obj", "", read_data=True)
        src = es._shard_sources(fi, metas)
        for idx in (0, 1):
            shutil.rmtree(os.path.join(src[idx][0].root, "bench"))
        es.disks = [_SlowDisk(d, delay) for d in disks]

        t0 = time.perf_counter()
        got = legacy_read(es, "bench", "obj")
        t_legacy = time.perf_counter() - t0
        assert got == data
        t0 = time.perf_counter()
        _, it = es.get_object("bench", "obj")
        got = b"".join(it)
        t_new = time.perf_counter() - t0
        assert got == data
        mib = SIZE / 2**20
        ms = delay * 1e3
        print(f"[{ms:.0f}ms/read latency] legacy serial: {t_legacy:.3f}s ({mib / t_legacy:.0f} MiB/s)")
        print(f"[{ms:.0f}ms/read latency] windowed par.: {t_new:.3f}s ({mib / t_new:.0f} MiB/s)")
        print(f"[{ms:.0f}ms/read latency] speedup: {t_legacy / t_new:.1f}x")
    finally:
        shutil.rmtree(base, ignore_errors=True)


if __name__ == "__main__":
    main()
    main_latency(0.001)
    main_latency(0.002)
