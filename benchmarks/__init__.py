"""Benchmark harnesses (closed-loop load, scenario zoo, micro-benches).

Run from the repo root: ``python benchmarks/bench_load.py --quick`` or
``python -m benchmarks.scenarios --all --quick``.
"""
