"""E2E benchmark harness: the five BASELINE.md driver-tracked configs.

1. single-node 4-drive EC 2+2 PutObject (1 MiB stripe blocks)
2. 16-drive EC 8+8 PutObject + GetObject
3. degraded GetObject with 2 drives down (see also bench_read.py)
4. multipart upload, 16 MiB parts (size via --mp-gib, default 5)
5. HealObject over a 16-drive set with induced corruption

Runs against a real server process over HTTP (SigV4, streaming PUTs)
except heal, which drives the erasure set directly (the admin heal API
adds only dispatch). Prints a markdown table for PERF.md.

Usage: python benchmarks/bench_e2e.py [--mp-gib N] [--quick]
"""

import argparse
import os
import shutil
import subprocess
import sys
import tempfile
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
os.environ.setdefault("MINIO_TPU_BACKEND", "numpy")

import numpy as np

from minio_tpu.client import S3Client

MIB = 1024 * 1024


class Server:
    def __init__(self, drives, port):
        self.port = port
        self.proc = subprocess.Popen(
            [sys.executable, "-m", "minio_tpu.server",
             "--address", f"127.0.0.1:{port}"] + drives,
            env={**os.environ, "MINIO_TPU_SCAN_INTERVAL": "0",
                 "MINIO_COMPRESSION_ENABLE": "off"},
            stdout=subprocess.DEVNULL, stderr=subprocess.DEVNULL,
        )
        c = S3Client(f"127.0.0.1:{port}")
        for _ in range(150):
            try:
                if c.request("GET", "/").status == 200:
                    return
            except Exception:
                pass
            time.sleep(0.2)
        self.stop()
        raise RuntimeError("server did not come up")

    def stop(self):
        self.proc.terminate()
        self.proc.wait()


def _settle():
    """Flush dirty pages so writeback from a previous phase doesn't steal
    the single core from the phase being timed."""
    subprocess.run(["sync"], check=False)
    time.sleep(1.0)


def median_of(n, fn):
    """(median, spread) over n timed runs — VERDICT r2: best-of-N
    overstates; report median with the min..max spread."""
    times = []
    for _ in range(n):
        t0 = time.perf_counter()
        fn()
        times.append(time.perf_counter() - t0)
    times.sort()
    return times[len(times) // 2], (times[0], times[-1])


def best_of(n, fn):
    return median_of(n, fn)[0]


def _fmt(size, t, spread):
    lo, hi = spread
    return f"{size / MIB / t:.0f} MiB/s (spread {size / MIB / hi:.0f}-{size / MIB / lo:.0f})"


def bench_put_get(c, bucket, size, label, rows, repeats=5):
    body = np.random.default_rng(1).integers(0, 256, size=size, dtype=np.uint8).tobytes()

    def put():
        r = c.request("PUT", f"/{bucket}/bench-obj", body=body, unsigned_payload=True)
        assert r.status == 200, r.body

    def get():
        g = c.get_object(bucket, "bench-obj")
        assert g.status == 200 and len(g.body) == size

    _settle()
    tp, sp = median_of(repeats, put)
    _settle()
    tg, sg = median_of(repeats, get)
    rows.append((f"{label} PUT", _fmt(size, tp, sp)))
    rows.append((f"{label} GET", _fmt(size, tg, sg)))


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--mp-gib", type=float, default=5.0)
    ap.add_argument("--quick", action="store_true")
    args = ap.parse_args()
    if args.quick:
        args.mp_gib = 0.5
    obj_size = 64 * MIB if not args.quick else 16 * MIB
    rows: list[tuple[str, str]] = []
    base = tempfile.mkdtemp(prefix="bench-e2e-")
    try:
        # --- config 1: 4-drive EC 2+2 ---
        srv = Server([os.path.join(base, f"a{i}") for i in range(4)], 19601)
        try:
            c = S3Client("127.0.0.1:19601")
            assert c.make_bucket("bench4").status == 200
            bench_put_get(c, "bench4", obj_size, f"4-drive EC2+2 {obj_size // MIB}MiB", rows)
        finally:
            srv.stop()
        shutil.rmtree(base, ignore_errors=True)
        os.makedirs(base, exist_ok=True)

        # --- config 2 + 3 + 4: 16-drive EC 8+8 ---
        drives = [os.path.join(base, f"b{i}") for i in range(16)]
        srv = Server(drives, 19602)
        try:
            c = S3Client("127.0.0.1:19602")
            assert c.make_bucket("bench16").status == 200
            bench_put_get(c, "bench16", obj_size, f"16-drive EC8+8 {obj_size // MIB}MiB", rows)

            # config 4 first (healthy set), then degrade for config 3
            total = int(args.mp_gib * 1024 * MIB)
            part_sz = 16 * MIB
            nparts = total // part_sz
            part = np.random.default_rng(2).integers(0, 256, size=part_sz, dtype=np.uint8).tobytes()
            r = c.request("POST", "/bench16/mp-obj", query={"uploads": ""})
            assert r.status == 200, r.body
            upload_id = r.body.decode().split("<UploadId>")[1].split("<")[0]
            t0 = time.perf_counter()
            etags = []
            for i in range(1, nparts + 1):
                r = c.request("PUT", "/bench16/mp-obj",
                              query={"partNumber": str(i), "uploadId": upload_id},
                              body=part, unsigned_payload=True)
                assert r.status == 200, r.body
                etags.append(r.headers["etag"].strip('"'))
            xml = "<CompleteMultipartUpload>" + "".join(
                f"<Part><PartNumber>{i}</PartNumber><ETag>{e}</ETag></Part>"
                for i, e in enumerate(etags, 1)
            ) + "</CompleteMultipartUpload>"
            r = c.request("POST", "/bench16/mp-obj", query={"uploadId": upload_id},
                          body=xml.encode())
            assert r.status == 200, r.body
            dt = time.perf_counter() - t0
            rows.append((f"multipart {args.mp_gib:g} GiB / 16 MiB parts PUT",
                         f"{total / MIB / dt:.0f} MiB/s ({dt:.0f}s)"))
            c.delete_object("bench16", "mp-obj")

            # config 3: degraded GET, 2 drives down
            for d in (drives[2], drives[9]):
                shutil.rmtree(os.path.join(d, "bench16"), ignore_errors=True)

            def degraded_get():
                g = c.get_object("bench16", "bench-obj")
                assert g.status == 200 and len(g.body) == obj_size, g.status

            t = best_of(2, degraded_get)
            rows.append(("16-drive EC8+8 degraded GET (2 down)",
                         f"{obj_size / MIB / t:.0f} MiB/s"))
        finally:
            srv.stop()
        shutil.rmtree(base, ignore_errors=True)
        os.makedirs(base, exist_ok=True)

        # --- config 5: heal, 16-drive set, induced corruption ---
        from minio_tpu.erasure.set import ErasureSet
        from minio_tpu.storage.xlstorage import XLStorage

        disks = [XLStorage(os.path.join(base, f"h{i}")) for i in range(16)]
        es = ErasureSet(disks, default_parity=4)  # EC 12+4 like PERF round 1
        es.make_bucket("healb")
        hsize = obj_size
        data = np.random.default_rng(3).integers(0, 256, size=hsize, dtype=np.uint8).tobytes()
        es.put_object("healb", "obj", data)
        fi, metas, _, _ = es._quorum_fileinfo("healb", "obj", "", read_data=True)
        src = es._shard_sources(fi, metas)
        lost = src[0][0]
        shutil.rmtree(os.path.join(lost.root, "healb"))
        t0 = time.perf_counter()
        res = es.heal_object("healb", "obj")
        dt = time.perf_counter() - t0
        _, it = es.get_object("healb", "obj")
        assert b"".join(it) == data
        rows.append((f"heal 16-drive EC12+4 {hsize // MIB}MiB (1 drive lost)",
                     f"{hsize / MIB / dt:.0f} MiB/s ({dt * 1e3:.0f}ms)"))
    finally:
        shutil.rmtree(base, ignore_errors=True)

    print("\n| Config | Result |")
    print("|---|---|")
    for k, v in rows:
        print(f"| {k} | {v} |")


if __name__ == "__main__":
    main()
