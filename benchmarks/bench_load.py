"""Compatibility entry point for the closed-loop load harness.

The harness itself now lives in the scenario zoo: the shared engine in
``benchmarks/scenarios/engine.py`` and the BENCH_r07/r10 phases (mixed
closed loop, large-PUT, QoS guard, ranged segment-cache, elastic
topology) in ``benchmarks/scenarios/legacy.py``. This wrapper keeps the
historical invocation — and, critically, the exact JSON series names —
so BENCH_r07.json / BENCH_r10.json runs stay comparable release over
release:

    python benchmarks/bench_load.py                    # full run
    python benchmarks/bench_load.py --quick            # seconds (CI gate)
    python benchmarks/bench_load.py --workers 1,2      # compare pool sizes
    python benchmarks/bench_load.py --out BENCH_r07.json

Named workload profiles (small-object-storm, ml-dataloader-shuffle,
backup-restore, multi-tenant-burst) run through the zoo instead:

    python -m benchmarks.scenarios --all --quick
"""

from __future__ import annotations

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
os.environ.setdefault("MINIO_TPU_BACKEND", "numpy")

from benchmarks.scenarios.legacy import (  # noqa: E402,F401 — re-exports
    BUCKET,
    MIB,
    AsyncS3,
    HealFlood,
    Server,
    Stats,
    TopologyLoad,
    _admin,
    _poll_admin,
    _tbody,
    bench_one_worker_count,
    bench_ranged,
    bench_topology,
    main,
    ranged_round,
    run_get_loop,
    run_mixed,
    run_put_throughput,
    run_round,
    run_topology_phase,
    scrape_cache_series,
    scrape_counter,
)

if __name__ == "__main__":
    sys.exit(main())
