"""Closed-loop production load harness (round 7: many-core data plane;
round 8: ranged-GET segment-cache phases; round 10: elastic topology).

Drives a REAL server process (optionally an SO_REUSEPORT worker pool,
``MINIO_TPU_WORKERS``) with production-shaped traffic and emits the
numbers PERF.md and BENCH_r07/r08.json track:

- **Mixed closed-loop phase**: N virtual clients, each a coroutine that
  issues its next request only after the previous one completes (closed
  loop — offered load adapts to service rate instead of queueing without
  bound). Op mix GET/PUT/HEAD/LIST over a zipf-hot keyspace, with the
  background scanner/ILM running and induced heal work pending, so QoS
  admission, the cache tiers, hedged reads, and the heal plane are
  exercised TOGETHER. Reports per-class p50/p99 latency, IOPS, and
  aggregate throughput.
- **Large-PUT segment**: few concurrent 64 MiB streaming PUTs at EC 8+8
  over 16 drives — the VERDICT r5 top-gap metric (target >= 350 MiB/s
  multi-core; the single-core wall was ~200-240 MiB/s).
- **QoS guard phase**: foreground GET p99 with a background heal flood
  off vs on, at high connection counts (>= 5k full mode), plus the
  ``fg_deferred_behind_bg`` invariant read from the pool-aggregated
  metrics — the "bg must ride leftover capacity only" proof under real
  HTTP load rather than the dispatcher microbench in bench.py.
- **Ranged (segment cache) phases**: 1 MiB ranged GETs over a 64 MiB
  object — cold vs warm (memory tier and NVMe tier on separate fresh
  servers, median-of-N warm passes) vs a prefetched sequential pass;
  the mixed phase additionally carries an RGET request class so the
  segment path is exercised under production load.
- **Topology phase (round 10)**: live pool expansion -> continuous
  placement-aware rebalance with a SEEDED partition injected mid-drain
  (topology fault boundary) -> decommission -> pool removal, all under
  verifying zipf traffic: every GET is checked byte-for-byte against a
  per-key generation ledger and its ETag against the served bytes.
  Gates: zero stale bytes/etags across the set-membership changes,
  ``fg_deferred_behind_bg`` flat, the pinned hot prefix never drained,
  the partition provably bit, and ``rebalance_throughput_mibps``
  recorded (BENCH_r10.json).

Worker count and nproc are recorded in the JSON so cross-host numbers
are never compared blindly.

Usage:
    python benchmarks/bench_load.py                    # full run
    python benchmarks/bench_load.py --quick            # seconds (CI gate)
    python benchmarks/bench_load.py --workers 1,2      # compare pool sizes
    python benchmarks/bench_load.py --out BENCH_r07.json
"""

from __future__ import annotations

import argparse
import asyncio
import bisect
import json
import os
import random
import shutil
import signal
import subprocess
import sys
import tempfile
import threading
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
os.environ.setdefault("MINIO_TPU_BACKEND", "numpy")

from minio_tpu.client import S3Client  # noqa: E402
from minio_tpu.server.signature import sign_request  # noqa: E402

MIB = 1 << 20
BUCKET = "loadbkt"
UNSIGNED = "UNSIGNED-PAYLOAD"


# ---------------------------------------------------------------- server


class Server:
    """One server process (pool supervisor when workers > 1) over fresh
    local drives, EC 8+8 when 16 drives."""

    def __init__(self, base: str, port: int, drives: int, workers: int,
                 scan_interval: float, extra_env: dict | None = None):
        self.port = port
        self.drives = [os.path.join(base, f"d{i}") for i in range(drives)]
        env = dict(
            os.environ,
            MINIO_TPU_WORKERS=str(workers),
            MINIO_TPU_SCAN_INTERVAL=str(scan_interval),
            MINIO_COMPRESSION_ENABLE="off",
        )
        env.update(extra_env or {})
        # the readiness probes below assume the default control-port
        # layout (port+1000+i); scrub inherited pool identity/overrides
        # so an operator env can't silently shift the workers elsewhere
        for k in ("MINIO_TPU_WORKER_INDEX", "MINIO_TPU_WORKER_COUNT",
                  "MINIO_TPU_WORKER_PORT_BASE"):
            env.pop(k, None)
        if drives >= 16:
            # the default storage class at 16 drives is EC:4; the target
            # config is EC 8+8
            env["MINIO_STORAGE_CLASS_STANDARD"] = "EC:8"
        self.proc = subprocess.Popen(
            [sys.executable, "-m", "minio_tpu.server",
             "--address", f"127.0.0.1:{port}", *self.drives],
            env=env, stdout=subprocess.DEVNULL, stderr=subprocess.STDOUT,
        )
        # readiness must cover EVERY worker: the shared SO_REUSEPORT port
        # answers as soon as ONE worker is up, and a request landing on a
        # still-booting sibling would 503
        probes = (
            [S3Client(f"127.0.0.1:{port + 1000 + i}") for i in range(workers)]
            if workers > 1
            else [S3Client(f"127.0.0.1:{port}")]
        )
        deadline = time.time() + 120
        pending = list(probes)
        while pending and time.time() < deadline:
            still = []
            for cli in pending:
                try:
                    if cli.request("GET", "/", timeout=5).status != 200:
                        still.append(cli)
                except Exception:  # noqa: BLE001 — still booting
                    still.append(cli)
            pending = still
            if pending:
                time.sleep(0.3)
        if pending:
            self.stop()
            raise RuntimeError("server did not become ready")

    def stop(self) -> None:
        if self.proc.poll() is None:
            self.proc.send_signal(signal.SIGTERM)
            try:
                self.proc.wait(20)
            except subprocess.TimeoutExpired:
                self.proc.kill()
                self.proc.wait()


# ------------------------------------------------------------- async client


class AsyncS3:
    """Minimal SigV4 asyncio client: one aiohttp session shared by every
    virtual client (connection pool unbounded — concurrency is set by the
    closed-loop client count, not by the connector)."""

    def __init__(self, session, host: str, port: int):
        self.session = session
        self.base = f"http://{host}:{port}"
        self.host = host
        self.port = port

    def _signed(self, method: str, path: str, query: str) -> dict:
        url = f"{self.base}{path}" + (f"?{query}" if query else "")
        return sign_request(
            method, url, {"x-amz-content-sha256": UNSIGNED}, UNSIGNED,
            "minioadmin", "minioadmin", "us-east-1",
        )

    async def request(self, method: str, path: str, query: str = "",
                      body: bytes = b"", read: bool = True,
                      headers: dict | None = None):
        st, data, _ = await self.request_full(
            method, path, query, body, read, headers
        )
        return st, data

    async def request_full(self, method: str, path: str, query: str = "",
                           body: bytes = b"", read: bool = True,
                           headers: dict | None = None):
        """Like request() but also returns the response headers (the
        topology phase cross-checks ETag against the served bytes)."""
        hdrs = self._signed(method, path, query)
        if headers:
            hdrs.update(headers)  # unsigned extras (Range) are S3-legal
        url = f"{self.base}{path}" + (f"?{query}" if query else "")
        async with self.session.request(
            method, url, data=body if body else None, headers=hdrs
        ) as resp:
            data = await resp.read() if read else b""
            return resp.status, data, dict(resp.headers)


ZIPF_ALPHA = 1.1


def zipf_cdf(n: int, alpha: float = ZIPF_ALPHA) -> list[float]:
    w = [1.0 / (i + 1) ** alpha for i in range(n)]
    total = sum(w)
    acc, out = 0.0, []
    for x in w:
        acc += x / total
        out.append(acc)
    return out


class Stats:
    """Per-class latency/bytes accounting for one phase. 503 SlowDown is
    the admission plane doing its job (bounded latency instead of
    unbounded queueing) — counted separately from errors, excluded from
    the latency percentiles, and answered by the virtual client with the
    Retry-After backoff a real SDK would apply."""

    def __init__(self):
        self.lat: dict[str, list[float]] = {}
        self.bytes = 0
        self.errors = 0
        self.slowdowns = 0
        self.ops = 0

    def add(self, cls: str, dt: float, nbytes: int, status: int) -> None:
        if status == 503:
            self.slowdowns += 1
            return
        self.lat.setdefault(cls, []).append(dt)
        self.ops += 1
        self.bytes += nbytes
        if status not in (200, 206):  # 206: ranged GET partial content
            self.errors += 1

    def summary(self, wall: float) -> dict:
        def pct(xs: list[float], q: float) -> float:
            xs = sorted(xs)
            return xs[min(len(xs) - 1, int(len(xs) * q))]

        per_class = {
            cls: {
                "count": len(xs),
                "p50_ms": round(pct(xs, 0.50) * 1e3, 3),
                "p99_ms": round(pct(xs, 0.99) * 1e3, 3),
            }
            for cls, xs in sorted(self.lat.items())
        }
        return {
            "wall_s": round(wall, 2),
            "iops": round(self.ops / max(wall, 1e-9), 1),
            "throughput_mibs": round(self.bytes / MIB / max(wall, 1e-9), 1),
            "errors": self.errors,
            "slowdowns_503": self.slowdowns,
            "per_class": per_class,
        }


async def run_mixed(cli: AsyncS3, clients: int, duration: float,
                    keyspace: int, obj_kb: int, put_frac: float,
                    ranged_key: str = "", ranged_mib: int = 0) -> Stats:
    """Closed-loop mixed GET/PUT/HEAD/LIST phase over a zipf-hot keyspace,
    plus an RGET class (Range header over a large object) when
    ``ranged_key`` is set — the segment-cache path exercised under mixed
    production load, with its own p50/p99/IOPS row."""
    stats = Stats()
    cdf = zipf_cdf(keyspace)
    stop_at = time.monotonic() + duration
    body = os.urandom(obj_kb * 1024)
    rget_frac = 0.05 if ranged_key else 0.0
    ranged_blocks = max(ranged_mib, 1)

    async def one_client(cid: int) -> None:
        rng = random.Random(cid)
        while time.monotonic() < stop_at:
            r = rng.random()
            key = f"o{bisect.bisect_left(cdf, rng.random()):06d}"
            t0 = time.perf_counter()
            try:
                if r < put_frac:  # overwrite a hot key: invalidation churn
                    st, _ = await cli.request(
                        "PUT", f"/{BUCKET}/{key}", body=body, read=False
                    )
                    stats.add("PUT", time.perf_counter() - t0, len(body), st)
                elif r < put_frac + 0.60 - rget_frac:
                    st, data = await cli.request("GET", f"/{BUCKET}/{key}")
                    stats.add("GET", time.perf_counter() - t0, len(data), st)
                elif r < put_frac + 0.60:
                    off = rng.randrange(ranged_blocks) * MIB
                    st, data = await cli.request(
                        "GET", f"/{BUCKET}/{ranged_key}",
                        headers={"Range": f"bytes={off}-{off + MIB - 1}"},
                    )
                    stats.add("RGET", time.perf_counter() - t0, len(data), st)
                elif r < put_frac + 0.75:
                    st, _ = await cli.request("HEAD", f"/{BUCKET}/{key}")
                    stats.add("HEAD", time.perf_counter() - t0, 0, st)
                else:
                    st, data = await cli.request(
                        "GET", f"/{BUCKET}",
                        query="list-type=2&max-keys=50&prefix=o0",
                    )
                    stats.add("LIST", time.perf_counter() - t0, len(data), st)
                if st == 503:  # SlowDown: back off like a real SDK
                    await asyncio.sleep(1.0)
            except Exception:  # noqa: BLE001 — count, keep looping
                stats.add("ERR", time.perf_counter() - t0, 0, 599)

    t0 = time.monotonic()
    await asyncio.gather(*(one_client(i) for i in range(clients)))
    stats.wall = time.monotonic() - t0
    return stats


async def run_get_loop(cli: AsyncS3, clients: int, duration: float,
                       keyspace: int) -> Stats:
    """Hot-GET closed loop (QoS guard phase): latency under connection
    pressure, no writes."""
    stats = Stats()
    cdf = zipf_cdf(keyspace)
    stop_at = time.monotonic() + duration

    async def one_client(cid: int) -> None:
        rng = random.Random(cid * 7919)
        while time.monotonic() < stop_at:
            key = f"o{bisect.bisect_left(cdf, rng.random()):06d}"
            t0 = time.perf_counter()
            try:
                st, data = await cli.request("GET", f"/{BUCKET}/{key}")
                stats.add("GET", time.perf_counter() - t0, len(data), st)
                if st == 503:  # SlowDown: back off like a real SDK
                    await asyncio.sleep(1.0)
            except Exception:  # noqa: BLE001
                stats.add("ERR", time.perf_counter() - t0, 0, 599)

    t0 = time.monotonic()
    await asyncio.gather(*(one_client(i) for i in range(clients)))
    stats.wall = time.monotonic() - t0
    return stats


async def run_put_throughput(cli: AsyncS3, streams: int, obj_mib: int,
                             repeats: int) -> float:
    """Aggregate streaming-PUT MiB/s: `streams` concurrent large PUTs,
    `repeats` rounds each."""
    body = os.urandom(obj_mib * MIB)

    async def one(i: int) -> None:
        for r in range(repeats):
            st, _ = await cli.request(
                "PUT", f"/{BUCKET}/big-{i}-{r}", body=body, read=False
            )
            assert st == 200, f"big PUT failed: HTTP {st}"

    t0 = time.perf_counter()
    await asyncio.gather(*(one(i) for i in range(streams)))
    wall = time.perf_counter() - t0
    return streams * repeats * obj_mib / wall


# ------------------------------------------------------------ ranged GETs


async def run_ranged_pass(cli: AsyncS3, key: str, size_mib: int,
                          order: list[int], concurrency: int) -> Stats:
    """One pass of 1 MiB ranged GETs over `key` at the given offsets
    (MiB units), `concurrency` closed-loop workers draining the list."""
    stats = Stats()
    queue: list[int] = list(order)

    async def worker() -> None:
        while queue:
            off = queue.pop() * MIB
            t0 = time.perf_counter()
            try:
                st, data = await cli.request(
                    "GET", f"/{BUCKET}/{key}",
                    headers={"Range": f"bytes={off}-{off + MIB - 1}"},
                )
                stats.add("RGET", time.perf_counter() - t0, len(data), st)
                if st == 206 and len(data) != MIB:
                    stats.errors += 1
            except Exception:  # noqa: BLE001
                stats.add("ERR", time.perf_counter() - t0, 0, 599)

    t0 = time.monotonic()
    await asyncio.gather(*(worker() for _ in range(concurrency)))
    stats.wall = time.monotonic() - t0
    return stats


def _median(xs: list[float]) -> float:
    xs = sorted(xs)
    return xs[len(xs) // 2]


async def ranged_round(port: int, size_mib: int, repeats: int,
                       concurrency: int = 8) -> dict:
    """The segment-path benchmark: 1 MiB ranged GETs over one
    `size_mib` object — cold (first pass, shuffled so no sequential run
    forms), warm (repeat passes served from the segment tiers,
    median-of-`repeats`), and prefetched (a fresh sequential pass with
    read-ahead running ahead of the client; warm-up requests excluded).
    The caller picks the tier the warm passes land in via the server's
    cache env (big memory budget -> memory tier; tiny memory budget +
    disk budget -> NVMe tier)."""
    import aiohttp

    conn = aiohttp.TCPConnector(limit=0)
    timeout = aiohttp.ClientTimeout(total=300)
    async with aiohttp.ClientSession(
        connector=conn, timeout=timeout, auto_decompress=False
    ) as session:
        cli = AsyncS3(session, "127.0.0.1", port)
        body = os.urandom(size_mib * MIB)
        st, _ = await cli.request(
            "PUT", f"/{BUCKET}/r-main", body=body, read=False
        )
        assert st == 200, f"ranged preload PUT: HTTP {st}"

        order = list(range(size_mib))
        random.Random(4242).shuffle(order)  # no run -> no prefetch
        cold = await run_ranged_pass(cli, "r-main", size_mib, order, concurrency)

        warm_iops, warm_p50, warm_p99 = [], [], []
        for i in range(repeats):
            random.Random(100 + i).shuffle(order)
            w = await run_ranged_pass(
                cli, "r-main", size_mib, order, concurrency
            )
            s = w.summary(w.wall)
            warm_iops.append(s["iops"])
            warm_p50.append(s["per_class"]["RGET"]["p50_ms"])
            warm_p99.append(s["per_class"]["RGET"]["p99_ms"])

        # prefetched: fresh object, strictly sequential, single client so
        # the read-ahead (not concurrency) is what hides the misses
        st, _ = await cli.request(
            "PUT", f"/{BUCKET}/r-seq", body=body, read=False
        )
        assert st == 200
        warmup = 4
        seq = await run_ranged_pass(
            cli, "r-seq", size_mib, list(range(size_mib))[::-1], 1
        )  # reversed because workers pop() from the tail -> ascending
        seq_lat = sorted(seq.lat.get("RGET", [0.0])[warmup:])

        cold_s = cold.summary(cold.wall)
        return {
            "object_mib": size_mib,
            "concurrency": concurrency,
            "repeats": repeats,
            "cold": {
                "iops": cold_s["iops"],
                "p50_ms": cold_s["per_class"]["RGET"]["p50_ms"],
                "p99_ms": cold_s["per_class"]["RGET"]["p99_ms"],
                "errors": cold_s["errors"],
            },
            "warm": {
                "iops": _median(warm_iops),
                "p50_ms": _median(warm_p50),
                "p99_ms": _median(warm_p99),
            },
            "prefetched_seq": {
                "iops": round(
                    len(seq_lat) / max(sum(seq_lat), 1e-9), 1
                ),
                "p50_ms": round(seq_lat[len(seq_lat) // 2] * 1e3, 3),
                "p99_ms": round(
                    seq_lat[min(len(seq_lat) - 1,
                                int(len(seq_lat) * 0.99))] * 1e3, 3),
                "warmup_excluded": warmup,
            },
        }


def scrape_cache_series(port: int) -> dict:
    """Segment/prefetch counters from metrics v3 (pool-aggregated)."""
    cli = S3Client(f"127.0.0.1:{port}")
    r = cli.request("GET", "/minio/metrics/v3/api/cache")
    assert r.status == 200, f"cache metrics scrape failed: HTTP {r.status}"
    out: dict[str, float] = {}
    for line in r.body.decode().splitlines():
        if line.startswith("#") or " " not in line:
            continue
        name, val = line.rsplit(" ", 1)
        try:
            out[name] = out.get(name, 0) + float(val)
        except ValueError:
            pass
    return {
        k: v for k, v in out.items()
        if "segment" in k or "prefetch" in k
    }


def bench_ranged(cfg: argparse.Namespace) -> dict:
    """Run the ranged benchmark twice: once against a memory-budget
    server (warm passes hit the memory tier) and once against a
    tiny-memory + NVMe-budget server (warm passes promote from the disk
    tier). Each server is fresh — the two tiers are measured in
    isolation."""
    out: dict = {}
    tiers = {
        "memory": {
            "MINIO_TPU_CACHE_DISK_MB": "0",
        },
        "disk": {
            # memory can hold only a fraction of the object: warm passes
            # must come off the NVMe tier (promote-on-hit)
            "MINIO_TPU_CACHE_MEM_MB": str(max(cfg.ranged_object_mib // 4, 8)),
            "MINIO_TPU_CACHE_DISK_MB": str(cfg.ranged_object_mib * 8),
        },
    }
    for tier, env in tiers.items():
        base = tempfile.mkdtemp(prefix=f"bench-ranged-{tier}-")
        srv = Server(base, cfg.port, cfg.drives, 1,
                     scan_interval=300.0, extra_env=env)
        try:
            cli = S3Client(f"127.0.0.1:{cfg.port}")
            assert cli.make_bucket(BUCKET).status == 200
            res = asyncio.run(ranged_round(
                cfg.port, cfg.ranged_object_mib, cfg.ranged_repeats
            ))
            res["cache_env"] = env
            res["segment_series"] = scrape_cache_series(cfg.port)
            res["fg_deferred_behind_bg"] = scrape_counter(
                cfg.port, "minio_tpu_dispatch_fg_deferred_behind_bg_total"
            )
            out[tier] = res
        finally:
            srv.stop()
            shutil.rmtree(base, ignore_errors=True)
    if out["memory"]["cold"]["iops"]:
        out["speedup_warm_memory_vs_cold_iops"] = round(
            out["memory"]["warm"]["iops"] / out["memory"]["cold"]["iops"], 1
        )
    return out


# ------------------------------------------------------ topology (round 10)


def _admin(port: int, method: str, path: str, body: bytes = b"",
           query: dict | None = None, timeout: float = 60):
    cli = S3Client(f"127.0.0.1:{port}")
    return cli.request(method, f"/minio/admin/v3/{path}", body=body,
                       query=query or {}, timeout=timeout)


def _tbody(key: str, gen: int, size: int) -> bytes:
    """Deterministic content for (key, generation): a reader can verify
    every byte of every response it ever gets."""
    import hashlib as _hl

    seed = _hl.md5(f"{key}#{gen}".encode()).digest()
    return (seed * (size // len(seed) + 1))[:size]


class TopologyLoad:
    """Verifying zipf mixed load for the topology phase. Every GET is
    checked byte-for-byte against the generation ledger (and its ETag
    against the served bytes), so a single stale cache entry or lost
    update anywhere across the set-membership changes is a counted
    failure, not a silent wrong answer."""

    def __init__(self, cli: "AsyncS3", bucket: str, static_keys: list[str],
                 hot_keys: list[str], size: int, clients: int):
        self.cli = cli
        self.bucket = bucket
        self.static_keys = static_keys
        self.hot_keys = hot_keys
        self.size = size
        self.clients = clients
        self.committed = {k: 0 for k in hot_keys}  # gen ledger
        self.stop = asyncio.Event()
        self.stats = {"reads": 0, "writes": 0, "stale": 0, "etag_bad": 0,
                      "errors": 0, "slowdowns": 0}
        self.examples: list[str] = []

    def _flag(self, kind: str, msg: str) -> None:
        self.stats[kind] += 1
        if len(self.examples) < 10:
            self.examples.append(f"{kind}: {msg}")

    async def _verify_get(self, key: str, expect_gen=None) -> None:
        import hashlib as _hl

        c0 = self.committed.get(key, 0) if expect_gen is None else expect_gen
        st, data, hdrs = await self.cli.request_full(
            "GET", f"/{self.bucket}/{key}"
        )
        if st == 503:
            self.stats["slowdowns"] += 1
            await asyncio.sleep(0.5)
            return
        if st != 200:
            self._flag("errors", f"GET {key} -> HTTP {st}")
            return
        self.stats["reads"] += 1
        if key in self.committed:
            # accept the floor generation or anything newer (a racing
            # writer may land mid-GET); OLDER than the floor = stale
            for g in range(c0, self.committed[key] + 2):
                if data == _tbody(key, g, self.size):
                    break
            else:
                self._flag("stale", f"{key}: bytes match no gen >= {c0}")
                return
        else:
            if data != _tbody(key, 0, self.size):
                self._flag("stale", f"{key}: static bytes mismatch")
                return
        etag = (hdrs.get("ETag") or "").strip('"')
        if etag and "-" not in etag and etag != _hl.md5(data).hexdigest():
            self._flag("etag_bad", f"{key}: etag {etag} != md5(bytes)")

    async def _reader(self, rid: int) -> None:
        rng = random.Random(1000 + rid)
        cdf = zipf_cdf(len(self.static_keys))
        while not self.stop.is_set():
            try:
                if rng.random() < 0.3 and self.hot_keys:
                    key = rng.choice(self.hot_keys)
                else:
                    key = self.static_keys[
                        bisect.bisect_left(cdf, rng.random())
                    ]
                await self._verify_get(key)
            except Exception as e:  # noqa: BLE001 — count, keep looping
                self._flag("errors", f"reader: {type(e).__name__}: {e}")

    async def _writer(self, wid: int) -> None:
        """Overwrites its OWN slice of hot keys (one writer per key:
        the generation ledger stays a total order per key)."""
        rng = random.Random(2000 + wid)
        mine = self.hot_keys[wid::4]
        while not self.stop.is_set() and mine:
            key = rng.choice(mine)
            gen = self.committed[key] + 1
            try:
                st, _ = await self.cli.request(
                    "PUT", f"/{self.bucket}/{key}",
                    body=_tbody(key, gen, self.size), read=False,
                )
                if st == 200:
                    self.committed[key] = gen
                    self.stats["writes"] += 1
                elif st == 503:
                    self.stats["slowdowns"] += 1
                    await asyncio.sleep(0.5)
                else:
                    self._flag("errors", f"PUT {key} -> HTTP {st}")
            except Exception as e:  # noqa: BLE001
                self._flag("errors", f"writer: {type(e).__name__}: {e}")
            await asyncio.sleep(0.02)

    async def run(self) -> None:
        tasks = [
            asyncio.create_task(self._reader(i)) for i in range(self.clients)
        ] + [asyncio.create_task(self._writer(w)) for w in range(4)]
        await self.stop.wait()
        for t in tasks:
            t.cancel()
        await asyncio.gather(*tasks, return_exceptions=True)


def _poll_admin(port: int, path: str, done, query: dict | None = None,
                timeout: float = 120.0, every: float = 0.3) -> dict:
    deadline = time.time() + timeout
    last: dict = {}
    while time.time() < deadline:
        r = _admin(port, "GET", path, query=query)
        if r.status == 200:
            last = json.loads(r.body)
            if done(last):
                return last
        time.sleep(every)
    raise AssertionError(f"{path} did not converge in {timeout}s: {last}")


async def run_topology_phase(port: int, base: str, cfg) -> dict:
    """The elastic-topology proof: pool expansion -> continuous rebalance
    with a seeded partition injected mid-drain -> decommission -> pool
    removal, ALL under live verified zipf traffic. Gates: zero stale
    bytes / bad etags, fg_deferred_behind_bg flat, pinned prefix never
    drained, and a positive rebalance throughput recorded for the BENCH
    json."""
    import aiohttp

    conn = aiohttp.TCPConnector(limit=0)
    timeout = aiohttp.ClientTimeout(total=300)
    async with aiohttp.ClientSession(
        connector=conn, timeout=timeout, auto_decompress=False
    ) as session:
        cli = AsyncS3(session, "127.0.0.1", port)
        size = cfg.topo_object_kb * 1024
        static_keys = [f"stat-{i:04d}" for i in range(cfg.topo_keyspace)]
        hot_keys = [f"hot/{i:03d}" for i in range(cfg.topo_hot_keys)]

        # pin the hot prefix to pool 0 BEFORE any data lands
        r = await asyncio.to_thread(
            _admin, port, "POST", "placement/set", body=json.dumps(
            {"bucket": BUCKET, "prefix": "hot/", "mode": "pin",
             "pools": [0]}).encode())
        assert r.status == 200, f"placement/set: {r.status} {r.body[:200]}"

        sem = asyncio.Semaphore(16)

        async def put_one(key: str, gen: int) -> None:
            async with sem:
                st, _ = await cli.request(
                    "PUT", f"/{BUCKET}/{key}",
                    body=_tbody(key, gen, size), read=False,
                )
                assert st == 200, f"preload {key}: HTTP {st}"

        await asyncio.gather(*(put_one(k, 0) for k in static_keys))
        # hot keys start at gen 1 (committed ledger starts there)
        await asyncio.gather(*(put_one(k, 1) for k in hot_keys))

        fg_deferred_before = await asyncio.to_thread(
            scrape_counter, port,
            "minio_tpu_dispatch_fg_deferred_behind_bg_total"
        )

        load = TopologyLoad(cli, BUCKET, static_keys, hot_keys, size,
                            cfg.topo_clients)
        for k in hot_keys:
            load.committed[k] = 1
        load_task = asyncio.create_task(load.run())
        await asyncio.sleep(1.0)  # traffic flowing before any topology op

        # -- expansion: second pool attaches to the RUNNING server ------
        t0 = time.monotonic()
        r = await asyncio.to_thread(
            _admin, port, "POST", "pool/expand", json.dumps(
            {"spec": os.path.join(base, "x2-d{1...%d}" % cfg.topo_drives)}
        ).encode())
        assert r.status == 200, f"pool/expand: {r.status} {r.body[:300]}"
        expand = json.loads(r.body)

        # -- continuous rebalance, chaos partition mid-drain ------------
        # seeded partition armed BEFORE the mover starts: the drain's
        # first pass provably runs through it (partition-during-drain),
        # fails those moves, and must still converge once it clears
        r = await asyncio.to_thread(
            _admin, port, "POST", "fault/inject", json.dumps(
                {"boundary": "topology", "mode": "partition",
                 "target": "pool-0", "op": "move", "prob": 0.7,
                 "count": 15, "seed": 42}).encode())
        assert r.status == 200, r.body[:200]
        fault_id = json.loads(r.body)["id"]
        r = await asyncio.to_thread(
            _admin, port, "POST", "pools/rebalance", b"",
            {"threshold": str(cfg.topo_threshold_pct)})
        assert r.status == 200, r.body[:200]
        await asyncio.sleep(cfg.topo_chaos_s)  # let the partition bite
        await asyncio.to_thread(
            _admin, port, "POST", "fault/clear", b"",
            {"id": str(fault_id), "local": "true"})
        reb = await asyncio.to_thread(
            _poll_admin, port, "pools/rebalance/status",
            lambda s: s.get("state") != "running")
        rebalance_wall = time.monotonic() - t0

        # -- decommission the expanded pool, live, then detach it -------
        r = await asyncio.to_thread(
            _admin, port, "POST", "pools/decommission", b"", {"pool": "1"})
        assert r.status == 200, r.body[:200]
        decom = await asyncio.to_thread(
            _poll_admin, port, "pools/decommission/status",
            lambda s: s.get("state") in ("complete", "failed"),
            {"pool": "1"},
        )
        r = await asyncio.to_thread(
            _admin, port, "POST", "pool/remove", b"", {"pool": "1"})
        removed = r.status == 200
        # keep verified traffic running across the membership change —
        # a stale cache entry from the dead sets would be caught here
        await asyncio.sleep(cfg.topo_cooldown_s)

        load.stop.set()
        await load_task

        fg_deferred_after = await asyncio.to_thread(
            scrape_counter, port,
            "minio_tpu_dispatch_fg_deferred_behind_bg_total"
        )
        topo_metrics = await asyncio.to_thread(
            lambda: S3Client(f"127.0.0.1:{port}").request(
                "GET", "/minio/metrics/v3/api/topology"
            )
        )
        assert topo_metrics.status == 200

    out = {
        "expand": expand,
        "rebalance": {k: reb.get(k) for k in (
            "state", "moved", "moved_bytes", "failed", "skipped_pinned",
            "passes", "spread_pct", "throughput_mibps", "eta_s")},
        "rebalance_wall_s": round(rebalance_wall, 2),
        "decommission": {k: decom.get(k) for k in (
            "state", "objectsMoved", "bytesMoved", "failedObjects")},
        "pool_removed": removed,
        "load": dict(load.stats),
        "fg_deferred_behind_bg_before": fg_deferred_before,
        "fg_deferred_behind_bg_after": fg_deferred_after,
        "examples": load.examples,
    }
    # -- the gates ---------------------------------------------------------
    failures = []
    if load.stats["stale"]:
        failures.append(f"stale bytes served: {load.stats['stale']}")
    if load.stats["etag_bad"]:
        failures.append(f"etag/bytes mismatches: {load.stats['etag_bad']}")
    if fg_deferred_after != fg_deferred_before:
        failures.append(
            "fg_deferred_behind_bg moved "
            f"{fg_deferred_before} -> {fg_deferred_after}"
        )
    if reb.get("state") != "done":
        failures.append(f"rebalance ended {reb.get('state')}")
    if not reb.get("moved"):
        failures.append("rebalance moved nothing")
    if not reb.get("failed"):
        failures.append(
            "the mid-drain partition never bit a move (chaos misfire)"
        )
    if decom.get("state") != "complete":
        failures.append(f"decommission ended {decom.get('state')}")
    if not removed:
        failures.append("pool/remove refused")
    if load.stats["reads"] < 50:
        failures.append(f"too few verified reads: {load.stats['reads']}")
    out["gates_passed"] = not failures
    out["gate_failures"] = failures
    return out


def bench_topology(cfg: argparse.Namespace) -> dict:
    """Fresh single-process server (online topology changes refuse worker
    pools), expansion + chaos rebalance + decommission under verified
    live load."""
    base = tempfile.mkdtemp(prefix="bench-topo-")
    srv = Server(base, cfg.port, cfg.topo_drives, 1,
                 scan_interval=cfg.scan_interval)
    try:
        cli = S3Client(f"127.0.0.1:{cfg.port}")
        assert cli.make_bucket(BUCKET).status == 200
        out = asyncio.run(run_topology_phase(cfg.port, base, cfg))
        if out["gate_failures"]:
            print(f"TOPOLOGY GATES FAILED: {out['gate_failures']}",
                  file=sys.stderr, flush=True)
        return out
    finally:
        srv.stop()
        shutil.rmtree(base, ignore_errors=True)


# ----------------------------------------------------------- qos plumbing


def scrape_counter(port: int, series: str, path: str = "/api/qos") -> int:
    """Sum a counter across workers from the pool-aggregated metrics v3
    exposition (worker labels sum away). A failed scrape or a missing
    series raises — the guard invariant must never 'pass' because the
    measurement silently returned nothing."""
    cli = S3Client(f"127.0.0.1:{port}")
    r = cli.request("GET", f"/minio/metrics/v3{path}")
    assert r.status == 200, f"metrics scrape failed: HTTP {r.status}"
    total = 0
    seen = False
    for line in r.body.decode().splitlines():
        if line.startswith(series) and not line.startswith("#"):
            try:
                total += int(float(line.rsplit(" ", 1)[1]))
                seen = True
            except ValueError:
                pass
    assert seen, f"series {series} absent from {path} exposition"
    return total


class HealFlood:
    """Background heal/ILM flood: a thread looping admin heal sweeps
    (walks + per-object heal over the whole keyspace) while the scanner
    keeps its own cycle going — the bg pressure the QoS guard phase
    measures fg p99 against."""

    def __init__(self, port: int):
        self.cli = S3Client(f"127.0.0.1:{port}")
        self.stop = threading.Event()
        self.sweeps = 0
        self.thread = threading.Thread(target=self._loop, daemon=True)

    def _loop(self) -> None:
        while not self.stop.is_set():
            try:
                self.cli.request(
                    "POST", f"/minio/admin/v3/heal/{BUCKET}", timeout=120
                )
                self.sweeps += 1
            except Exception:  # noqa: BLE001 — flood keeps flooding
                time.sleep(0.2)

    def __enter__(self) -> "HealFlood":
        self.thread.start()
        return self

    def __exit__(self, *exc) -> None:
        self.stop.set()
        self.thread.join(timeout=150)


# ----------------------------------------------------------------- phases


async def run_round(port: int, cfg: argparse.Namespace) -> dict:
    import aiohttp

    conn = aiohttp.TCPConnector(limit=0)
    timeout = aiohttp.ClientTimeout(total=300)
    async with aiohttp.ClientSession(
        connector=conn, timeout=timeout, auto_decompress=False
    ) as session:
        cli = AsyncS3(session, "127.0.0.1", port)

        # preload the keyspace (also the heal flood's object population)
        body = os.urandom(cfg.object_kb * 1024)
        sem = asyncio.Semaphore(32)

        async def put_one(i: int) -> None:
            async with sem:
                st, _ = await cli.request(
                    "PUT", f"/{BUCKET}/o{i:06d}", body=body, read=False
                )
                assert st == 200, f"preload PUT {i}: HTTP {st}"

        t0 = time.monotonic()
        await asyncio.gather(*(put_one(i) for i in range(cfg.keyspace)))
        # one large object for the mixed phase's RGET class (the segment
        # path exercised under production load, not just in isolation)
        st, _ = await cli.request(
            "PUT", f"/{BUCKET}/rmix",
            body=os.urandom(cfg.ranged_object_mib * MIB), read=False,
        )
        assert st == 200, f"ranged preload PUT: HTTP {st}"
        preload_s = time.monotonic() - t0

        # mixed closed loop with scanner/ILM live
        mixed = await run_mixed(
            cli, cfg.clients, cfg.duration, cfg.keyspace, cfg.object_kb,
            put_frac=0.20, ranged_key="rmix",
            ranged_mib=cfg.ranged_object_mib,
        )

        # large-PUT aggregate throughput (the EC 8+8 target metric)
        put_mibs = await run_put_throughput(
            cli, cfg.put_streams, cfg.put_object_mib, cfg.put_repeats
        )

        # QoS guard: fg GET p99 with bg heal flood off vs on, at high
        # connection count; fg_deferred_behind_bg read AFTER, aggregated
        # over workers
        qos_off = await run_get_loop(
            cli, cfg.connections, cfg.qos_duration, cfg.keyspace
        )
        with HealFlood(port) as flood:
            qos_on = await run_get_loop(
                cli, cfg.connections, cfg.qos_duration, cfg.keyspace
            )
            sweeps = flood.sweeps
        deferred = scrape_counter(
            port, "minio_tpu_dispatch_fg_deferred_behind_bg_total"
        )

    off, on = qos_off.summary(qos_off.wall), qos_on.summary(qos_on.wall)
    return {
        "preload_s": round(preload_s, 1),
        "mixed": mixed.summary(mixed.wall),
        "put_streams": cfg.put_streams,
        "put_object_mib": cfg.put_object_mib,
        "put_throughput_mibs": round(put_mibs, 1),
        "qos": {
            "connections": cfg.connections,
            "fg_get_p50_ms_bg_off": off["per_class"].get("GET", {}).get("p50_ms"),
            "fg_get_p99_ms_bg_off": off["per_class"].get("GET", {}).get("p99_ms"),
            "fg_get_p50_ms_bg_on": on["per_class"].get("GET", {}).get("p50_ms"),
            "fg_get_p99_ms_bg_on": on["per_class"].get("GET", {}).get("p99_ms"),
            "fg_iops_bg_off": off["iops"],
            "fg_iops_bg_on": on["iops"],
            "errors_bg_off": off["errors"],
            "errors_bg_on": on["errors"],
            "slowdowns_bg_off": off["slowdowns_503"],
            "slowdowns_bg_on": on["slowdowns_503"],
            "heal_sweeps_during_flood": sweeps,
            "fg_deferred_behind_bg": deferred,
        },
    }


def bench_one_worker_count(workers: int, cfg: argparse.Namespace) -> dict:
    base = tempfile.mkdtemp(prefix=f"bench-load-w{workers}-")
    srv = Server(base, cfg.port, cfg.drives, workers,
                 scan_interval=cfg.scan_interval)
    try:
        cli = S3Client(f"127.0.0.1:{cfg.port}")
        assert cli.make_bucket(BUCKET).status == 200
        out = asyncio.run(run_round(cfg.port, cfg))
        out["workers"] = workers
        return out
    finally:
        srv.stop()
        shutil.rmtree(base, ignore_errors=True)


def main() -> int:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--workers", default="",
                    help="comma-separated pool sizes to compare "
                         "(default: 1,<nproc>; quick: 2)")
    ap.add_argument("--drives", type=int, default=16)
    ap.add_argument("--clients", type=int, default=512,
                    help="closed-loop clients in the mixed phase")
    ap.add_argument("--connections", type=int, default=5000,
                    help="closed-loop clients in the QoS guard phase")
    ap.add_argument("--duration", type=float, default=15.0)
    ap.add_argument("--qos-duration", type=float, default=12.0)
    ap.add_argument("--keyspace", type=int, default=512)
    ap.add_argument("--object-kb", type=int, default=256,
                    help="mixed-phase object size")
    ap.add_argument("--put-streams", type=int, default=4)
    ap.add_argument("--put-object-mib", type=int, default=64)
    ap.add_argument("--put-repeats", type=int, default=3)
    ap.add_argument("--scan-interval", type=float, default=30.0)
    ap.add_argument("--ranged-object-mib", type=int, default=64,
                    help="object size for the ranged-GET (segment cache) "
                         "phases")
    ap.add_argument("--ranged-repeats", type=int, default=5,
                    help="warm ranged passes (median reported)")
    ap.add_argument("--port", type=int, default=19801)
    ap.add_argument("--topo-drives", type=int, default=8,
                    help="drives per pool in the topology phase")
    ap.add_argument("--topo-keyspace", type=int, default=192,
                    help="static verified keys in the topology phase")
    ap.add_argument("--topo-hot-keys", type=int, default=24,
                    help="pinned hot (overwritten) keys")
    ap.add_argument("--topo-object-kb", type=int, default=128)
    ap.add_argument("--topo-clients", type=int, default=24,
                    help="verifying reader coroutines")
    ap.add_argument("--topo-threshold-pct", type=float, default=5.0)
    ap.add_argument("--topo-chaos-s", type=float, default=2.0,
                    help="seconds the mid-rebalance partition stays armed")
    ap.add_argument("--topo-cooldown-s", type=float, default=2.0,
                    help="verified traffic kept running after pool removal")
    ap.add_argument("--out", default="",
                    help="write the JSON here too (stdout always)")
    ap.add_argument("--quick", action="store_true",
                    help="seconds-long smoke (CI harness-stays-runnable "
                         "gate): tiny keyspace, short phases, one pool size")
    args = ap.parse_args()

    if args.quick:
        args.drives = min(args.drives, 8)
        args.clients = 48
        args.connections = 128
        args.duration = 3.0
        args.qos_duration = 2.5
        args.keyspace = 48
        args.object_kb = 64
        args.put_streams = 2
        args.put_object_mib = 4
        args.put_repeats = 2
        args.scan_interval = 5.0
        args.ranged_object_mib = 8
        args.ranged_repeats = 2
        args.topo_drives = 4
        args.topo_keyspace = 40
        args.topo_hot_keys = 8
        args.topo_object_kb = 32
        args.topo_clients = 8
        args.topo_chaos_s = 1.0
        args.topo_cooldown_s = 1.0
    worker_counts = [
        int(w) for w in (
            args.workers.split(",") if args.workers
            else (["2"] if args.quick
                  else ["1", str(os.cpu_count() or 1)])
        )
        if w.strip()
    ]
    # dedupe preserving order (nproc may be 1)
    worker_counts = list(dict.fromkeys(worker_counts))

    runs = []
    for w in worker_counts:
        print(f"=== round: {w} worker(s) ===", file=sys.stderr, flush=True)
        runs.append(bench_one_worker_count(w, args))

    print("=== round: ranged (segment cache) ===", file=sys.stderr,
          flush=True)
    ranged = bench_ranged(args)

    print("=== round: topology (expand/rebalance/decom under load) ===",
          file=sys.stderr, flush=True)
    topology = bench_topology(args)

    result = {
        "metric": "load_harness_closed_loop",
        "nproc": os.cpu_count(),
        "drives": args.drives,
        "ec": "8+8" if args.drives >= 16 else "default",
        "quick": bool(args.quick),
        "runs": runs,
        "ranged": ranged,
        "topology": topology,
        # the round-10 headline: mover throughput under live verified
        # traffic with a chaos partition mid-drain
        "rebalance_throughput_mibps": topology["rebalance"].get(
            "throughput_mibps", 0.0
        ),
    }
    if not topology.get("gates_passed", False):
        print(f"TOPOLOGY GATES FAILED: {topology.get('gate_failures')}",
              file=sys.stderr, flush=True)
        print(json.dumps(result))
        return 1
    by_w = {r["workers"]: r["put_throughput_mibs"] for r in runs}
    if 1 in by_w and len(by_w) > 1:
        best_w = max(w for w in by_w if w != 1)
        result["put_scaling_vs_1_worker"] = round(
            by_w[best_w] / max(by_w[1], 1e-9), 2
        )
    line = json.dumps(result)
    print(line)
    if args.out:
        with open(args.out, "w", encoding="utf-8") as fh:
            fh.write(line + "\n")
    return 0


if __name__ == "__main__":
    sys.exit(main())
