"""Regenerate minio_tpu/analysis/reference_surface.json from the
reference tree's metrics-v3 sources.

Usage::

    python scripts/gen_reference_surface.py [REFERENCE_ROOT]

REFERENCE_ROOT defaults to /root/reference. The script greps the
``cmd/metrics-v3-*.go`` descriptor files for series-name constants
(``"<name>"`` passed to NewCounterMD/NewGaugeMD, or assembled from the
``minio_<subsystem>_`` prefix conventions), buckets them into the four
pinned parity groups (api / cluster / system / drive), and rewrites the
vendored JSON in place — preserving the pin and the comment header.

When the reference tree is not mounted (the normal case in CI) it exits
0 without touching anything: the vendored JSON stays the hand-curated
pin set, and editing it by hand remains legitimate — the surface pass
hashes it into the engine digest, so any edit busts the analysis cache.
"""

from __future__ import annotations

import json
import os
import re
import sys

VENDORED = os.path.join(
    os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
    "minio_tpu", "analysis", "reference_surface.json",
)

# descriptor files -> parity group. drive series live in the system-*
# descriptor but carry the minio_system_drive_ prefix, split below.
_GROUP_BY_FILE = (
    ("metrics-v3-api-", "api"),
    ("metrics-v3-cluster-", "cluster"),
    ("metrics-v3-system-", "system"),
)

# `xxxMD = NewCounterMD(xxx, ...)` name constants: the series name is a
# quoted snake_case string in the same file
_NAME_RE = re.compile(r'"((?:[a-z0-9]+_)+[a-z0-9]+)"')


def harvest(reference_root: str) -> dict[str, set[str]] | None:
    cmd = os.path.join(reference_root, "cmd")
    if not os.path.isdir(cmd):
        return None
    groups: dict[str, set[str]] = {
        "api": set(), "cluster": set(), "system": set(), "drive": set(),
    }
    for fn in sorted(os.listdir(cmd)):
        if not (fn.startswith("metrics-v3-") and fn.endswith(".go")):
            continue
        group = next(
            (g for pre, g in _GROUP_BY_FILE if fn.startswith(pre)), None
        )
        if group is None:
            continue
        with open(os.path.join(cmd, fn), "r", encoding="utf-8",
                  errors="replace") as fh:
            src = fh.read()
        # v3 exposition prefixes every series with minio_<group-path>;
        # descriptor constants carry the tail only
        for m in _NAME_RE.finditer(src):
            tail = m.group(1)
            if tail.startswith("minio_"):
                name = tail
            else:
                continue  # tails are resolved via the full-name form only
            g = group
            if name.startswith("minio_system_drive_"):
                g = "drive"
            groups[g].add(name)
    return groups


def main() -> int:
    root = sys.argv[1] if len(sys.argv) > 1 else "/root/reference"
    harvested = harvest(root)
    if harvested is None:
        print(
            f"gen_reference_surface: {root} not mounted — vendored "
            "reference_surface.json left untouched", file=sys.stderr,
        )
        return 0
    with open(VENDORED, "r", encoding="utf-8") as fh:
        doc = json.load(fh)
    for g, names in harvested.items():
        if names:
            doc["groups"][g] = sorted(names)
    with open(VENDORED, "w", encoding="utf-8") as fh:
        json.dump(doc, fh, indent=2)
        fh.write("\n")
    print(f"wrote {VENDORED}")
    return 0


if __name__ == "__main__":
    sys.exit(main())
