"""Regenerate minio_tpu/analysis/reference_surface.json from the
reference tree's metrics-v3 sources.

Usage::

    python scripts/gen_reference_surface.py [REFERENCE_ROOT]

REFERENCE_ROOT defaults to /root/reference. The script greps the
``cmd/metrics-v3-*.go`` descriptor files for series-name constants
(``"<name>"`` passed to NewCounterMD/NewGaugeMD, or assembled from the
``minio_<subsystem>_`` prefix conventions), buckets them into the four
pinned parity groups (api / cluster / system / drive), harvests the
diagnostic admin-op names from ``cmd/admin-router.go`` into the
``admin_groups`` pin set, and rewrites the vendored JSON in place —
preserving the pin and the comment header.

When the reference tree is not mounted (the normal case in CI) it exits
0 without touching anything: the vendored JSON stays the hand-curated
pin set, and editing it by hand remains legitimate — the surface pass
hashes it into the engine digest, so any edit busts the analysis cache.
"""

from __future__ import annotations

import json
import os
import re
import sys

VENDORED = os.path.join(
    os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
    "minio_tpu", "analysis", "reference_surface.json",
)

# descriptor files -> parity group. drive series live in the system-*
# descriptor but carry the minio_system_drive_ prefix, split below.
_GROUP_BY_FILE = (
    ("metrics-v3-api-", "api"),
    ("metrics-v3-cluster-", "cluster"),
    ("metrics-v3-system-", "system"),
)

# `xxxMD = NewCounterMD(xxx, ...)` name constants: the series name is a
# quoted snake_case string in the same file
_NAME_RE = re.compile(r'"((?:[a-z0-9]+_)+[a-z0-9]+)"')

# admin-router registrations: adminRouter.Methods(...).Path(adminVersion +
# "/speedtest/drive") — harvest the op path tails
_ADMIN_OP_RE = re.compile(r'adminAPIVersionPrefix\s*\+\s*"/([a-z][a-z0-9/_-]*)"'
                          r'|adminVersion\s*\+\s*"/([a-z][a-z0-9/_-]*)"')

# the curated diagnostics subset: the reference router registers ~100
# ops; parity pins only the self-measurement plane this tree mirrors
_DIAG_OPS = frozenset({
    "speedtest", "speedtest/drive", "speedtest/net", "speedtest/object",
    "healthinfo", "inspect-data", "profile", "trace", "top/locks",
})


def harvest_admin_ops(reference_root: str) -> set[str]:
    """Diagnostic admin-op names from the reference admin router,
    intersected with the curated allowlist (the reference registers far
    more ops than this tree pins parity on)."""
    path = os.path.join(reference_root, "cmd", "admin-router.go")
    try:
        with open(path, "r", encoding="utf-8", errors="replace") as fh:
            src = fh.read()
    except OSError:
        return set()
    ops = set()
    for m in _ADMIN_OP_RE.finditer(src):
        op = (m.group(1) or m.group(2)).strip("/")
        if op in _DIAG_OPS:
            ops.add(op)
    return ops


def harvest(reference_root: str) -> dict[str, set[str]] | None:
    cmd = os.path.join(reference_root, "cmd")
    if not os.path.isdir(cmd):
        return None
    groups: dict[str, set[str]] = {
        "api": set(), "cluster": set(), "system": set(), "drive": set(),
    }
    for fn in sorted(os.listdir(cmd)):
        if not (fn.startswith("metrics-v3-") and fn.endswith(".go")):
            continue
        group = next(
            (g for pre, g in _GROUP_BY_FILE if fn.startswith(pre)), None
        )
        if group is None:
            continue
        with open(os.path.join(cmd, fn), "r", encoding="utf-8",
                  errors="replace") as fh:
            src = fh.read()
        # v3 exposition prefixes every series with minio_<group-path>;
        # descriptor constants carry the tail only
        for m in _NAME_RE.finditer(src):
            tail = m.group(1)
            if tail.startswith("minio_"):
                name = tail
            else:
                continue  # tails are resolved via the full-name form only
            g = group
            if name.startswith("minio_system_drive_"):
                g = "drive"
            groups[g].add(name)
    return groups


def main() -> int:
    root = sys.argv[1] if len(sys.argv) > 1 else "/root/reference"
    harvested = harvest(root)
    if harvested is None:
        print(
            f"gen_reference_surface: {root} not mounted — vendored "
            "reference_surface.json left untouched", file=sys.stderr,
        )
        return 0
    with open(VENDORED, "r", encoding="utf-8") as fh:
        doc = json.load(fh)
    for g, names in harvested.items():
        if names:
            doc["groups"][g] = sorted(names)
    admin_ops = harvest_admin_ops(root)
    if admin_ops:
        doc.setdefault("admin_groups", {})["diagnostics"] = sorted(admin_ops)
    with open(VENDORED, "w", encoding="utf-8") as fh:
        json.dump(doc, fh, indent=2)
        fh.write("\n")
    print(f"wrote {VENDORED}")
    return 0


if __name__ == "__main__":
    sys.exit(main())
