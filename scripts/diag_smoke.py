"""diag-smoke: the self-measurement plane end to end, in seconds.

Brings up a 2-worker SO_REUSEPORT pool the way `make bench-smoke` does,
then drives the whole diag surface over real HTTP:

* quick object speedtest (autotune ramp) + drive speedtest + netperf —
  every request must be a 200 and every node row error-free;
* healthinfo as JSON and as zip (the zip must contain healthinfo.json);
* every series the static surface manifest declares under ``/api/diag``
  must be present in the live scrape (the continuous profiler's
  attribution series included) — a diag series we document but don't
  serve fails the smoke, never passes it.

Exit status 0 only when all of that holds. Wired as `make diag-smoke`
and a check.yml step.
"""

from __future__ import annotations

import io
import json
import os
import shutil
import sys
import tempfile
import zipfile

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
os.environ.setdefault("MINIO_TPU_BACKEND", "numpy")
os.environ.setdefault("JAX_PLATFORMS", "cpu")

from benchmarks.scenarios.engine import Server, admin  # noqa: E402
from minio_tpu.client import S3Client  # noqa: E402

PORT = 19831


def fail(msg: str) -> None:
    print(f"diag-smoke: FAIL: {msg}", file=sys.stderr, flush=True)
    raise SystemExit(1)


def node_rows(payload: bytes, what: str) -> dict:
    doc = json.loads(payload)
    nodes = doc.get("nodes", {})
    if not nodes:
        fail(f"{what}: no node rows in {doc}")
    for node, row in nodes.items():
        if isinstance(row, dict) and "error" in row:
            fail(f"{what}: node {node} errored: {row['error']}")
    return doc


def declared_diag_series() -> set[str]:
    """Series names the static surface manifest declares under the
    /api/diag collector path."""
    from minio_tpu.analysis import surface

    class _PathsIndex:
        def __init__(self, root: str):
            self.root = root
            self.paths = {}
            for dirpath, _, files in os.walk(root):
                for fn in files:
                    if fn.endswith(".py"):
                        full = os.path.join(dirpath, fn)
                        self.paths[os.path.relpath(full, root)] = full

    pkg = os.path.join(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))), "minio_tpu")
    manifest = surface.extract(_PathsIndex(pkg))
    return {s["name"] for s in manifest["metrics"]
            if s["group"] == "/api/diag"}


def main() -> int:
    base = tempfile.mkdtemp(prefix="diag-smoke-")
    srv = Server(base, PORT, drives=4, workers=2, scan_interval=30.0)
    try:
        cli = S3Client(f"127.0.0.1:{PORT}")
        assert cli.make_bucket("diag-smoke").status == 200

        # -- object speedtest (quick autotune) ---------------------------
        r = admin(PORT, "POST", "speedtest",
                  query={"size": str(64 * 1024), "ops": "2"}, timeout=180)
        if r.status != 200:
            fail(f"speedtest HTTP {r.status}: {r.body[:200]}")
        doc = node_rows(r.body, "speedtest")
        for node, row in doc["nodes"].items():
            knee = row.get("knee", {})
            if not knee.get("putMiBps", 0) > 0:
                fail(f"speedtest: node {node} knee has no PUT throughput: "
                     f"{knee}")
        print(f"diag-smoke: speedtest ok ({len(doc['nodes'])} nodes)")

        # -- drive speedtest ---------------------------------------------
        r = admin(PORT, "POST", "speedtest/drive",
                  query={"sizeMiB": "1", "randCount": "4"}, timeout=120)
        if r.status != 200:
            fail(f"speedtest/drive HTTP {r.status}: {r.body[:200]}")
        doc = node_rows(r.body, "speedtest/drive")
        drives = sum(len(row.get("drives", ()))
                     for row in doc["nodes"].values())
        if drives == 0:
            fail("speedtest/drive: no drive rows")
        for row in doc["nodes"].values():
            for d in row.get("drives", ()):
                if "error" in d:
                    fail(f"speedtest/drive: drive {d.get('drive')} errored: "
                         f"{d['error']}")
        print(f"diag-smoke: drive speedtest ok ({drives} drive rows)")

        # -- netperf matrix ----------------------------------------------
        r = admin(PORT, "POST", "speedtest/net",
                  query={"size": str(256 * 1024), "count": "2", "pings": "4"},
                  timeout=120)
        if r.status != 200:
            fail(f"speedtest/net HTTP {r.status}: {r.body[:200]}")
        doc = node_rows(r.body, "speedtest/net")
        for node, row in doc["nodes"].items():
            peers = row.get("peers", {})
            if "loopback" not in peers:
                fail(f"netperf: node {node} has no loopback row: {peers}")
            for peer, cell in peers.items():
                if "error" in cell:
                    fail(f"netperf: {node} -> {peer} errored: "
                         f"{cell['error']}")
        print(f"diag-smoke: netperf ok ({len(doc['nodes'])} matrix rows)")

        # -- healthinfo: JSON + zip --------------------------------------
        r = admin(PORT, "GET", "healthinfo", timeout=60)
        if r.status != 200:
            fail(f"healthinfo HTTP {r.status}: {r.body[:200]}")
        info = json.loads(r.body)
        for key in ("version", "hardware", "topology", "breakers",
                    "sanitizer", "selftest"):
            if key not in info:
                fail(f"healthinfo: missing section {key!r}")
        if not info["selftest"]["last"]:
            fail("healthinfo: selftest.last empty after three speedtests")
        r = admin(PORT, "GET", "healthinfo", query={"format": "zip"},
                  timeout=60)
        if r.status != 200:
            fail(f"healthinfo zip HTTP {r.status}")
        with zipfile.ZipFile(io.BytesIO(r.body)) as z:
            if "healthinfo.json" not in z.namelist():
                fail(f"healthinfo zip entries: {z.namelist()}")
        print("diag-smoke: healthinfo ok (json + zip)")

        # -- every declared /api/diag series present in the live scrape --
        declared = declared_diag_series()
        if not declared:
            fail("static manifest declares no /api/diag series")
        r = cli.request("GET", "/minio/metrics/v3/api/diag")
        if r.status != 200:
            fail(f"/api/diag scrape HTTP {r.status}")
        live = set()
        for line in r.body.decode().splitlines():
            if line.startswith("# TYPE "):
                live.add(line.split()[2])
            elif line and not line.startswith("#") and " " in line:
                live.add(line.rsplit(" ", 1)[0].split("{", 1)[0])
        missing = declared - live
        if missing:
            fail(f"declared /api/diag series absent from live scrape: "
                 f"{sorted(missing)}")
        print(f"diag-smoke: /api/diag scrape ok "
              f"({len(declared)} declared series all present)")
        print("diag-smoke: PASS")
        return 0
    finally:
        srv.stop()
        shutil.rmtree(base, ignore_errors=True)


if __name__ == "__main__":
    sys.exit(main())
