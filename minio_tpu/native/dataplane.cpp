// Native streaming data plane: the PUT/GET hot path as single GIL-releasing
// passes (reference: cmd/erasure-encode.go:76-108 + cmd/bitrot-streaming.go:
// 108-133 compose the same pipeline from Go goroutines; here it is one
// C++ pass per stripe block, called via ctypes which drops the GIL).
//
// PUT:  raw stream -> md5 (etag) -> stripe split -> GF(2^8) parity (GFNI)
//       -> HighwayHash-256 per shard -> digest||block framing -> writev
// GET:  preadv shard frames -> HighwayHash verify -> window copy to output
//
// Python keeps control flow only: staged-file creation, quorum judgment,
// rename/commit, metadata. Per-drive write failures mark the shard dead and
// the pass continues (the reference's multiWriter tolerates failures down to
// write quorum, cmd/erasure-encode.go:59-65); Python reads the dead mask and
// applies quorum rules.
//
// Core scaling: every stripe block is independent (parity+hash+write), so
// the pass parallelizes by handing blocks round-robin to a small thread
// pool; md5 is inherently serial and stays PIPELINED on the feeding thread
// (it digests chunk k while workers encode/hash/write chunk k-1, so a
// single large PUT overlaps etag and parity work across cores).
// MINIO_TPU_NATIVE_THREADS: 1 (default) = inline, 0 = auto from hardware
// concurrency, malformed/negative falls back to 1.

#include <atomic>
#include <condition_variable>
#include <cstdint>
#include <cstdlib>
#include <cstring>
#include <mutex>
#include <new>
#include <thread>
#include <vector>

#include <dlfcn.h>
#include <fcntl.h>
#include <sys/uio.h>
#include <unistd.h>

// from gfhash.cpp (same shared object)
extern "C" void gf_apply_strided(const uint8_t* mat, int rows, int cols,
                                 const uint8_t* in, long in_stride,
                                 uint8_t* out, long out_stride, long n);
extern "C" void hh256(const uint8_t* key32, const uint8_t* data, long n,
                      uint8_t* out32);

// ----------------------------------------------------------------- MD5
// libcrypto's asm MD5 via dlopen (no headers needed: EVP is all-opaque);
// portable fallback below implements RFC 1321 directly.

namespace md5impl {

typedef void* (*fn_ctx_new)();
typedef void (*fn_ctx_free)(void*);
typedef const void* (*fn_md5)();
typedef int (*fn_init)(void*, const void*, void*);
typedef int (*fn_update)(void*, const void*, size_t);
typedef int (*fn_final)(void*, unsigned char*, unsigned*);

static fn_ctx_new evp_new;
static fn_ctx_free evp_free;
static fn_md5 evp_md5;
static fn_init evp_init;
static fn_update evp_update;
static fn_final evp_final;
static int evp_ready = -1;  // -1 unprobed, 0 no, 1 yes

static bool evp_probe() {
    if (evp_ready >= 0) return evp_ready == 1;
    evp_ready = 0;
    // probe every common soname: hosts shipping only libcrypto.so.1.1
    // (no dev symlink) would otherwise fall back to the ~1.4x-slower
    // portable MD5, which caps the whole PUT plane (md5 is the serial
    // stage on the feeding thread)
    void* h = dlopen("libcrypto.so.3", RTLD_LAZY | RTLD_GLOBAL);
    if (!h) h = dlopen("libcrypto.so", RTLD_LAZY | RTLD_GLOBAL);
    if (!h) h = dlopen("libcrypto.so.1.1", RTLD_LAZY | RTLD_GLOBAL);
    if (!h) return false;
    evp_new = (fn_ctx_new)dlsym(h, "EVP_MD_CTX_new");
    evp_free = (fn_ctx_free)dlsym(h, "EVP_MD_CTX_free");
    evp_md5 = (fn_md5)dlsym(h, "EVP_md5");
    evp_init = (fn_init)dlsym(h, "EVP_DigestInit_ex");
    evp_update = (fn_update)dlsym(h, "EVP_DigestUpdate");
    evp_final = (fn_final)dlsym(h, "EVP_DigestFinal_ex");
    if (evp_new && evp_free && evp_md5 && evp_init && evp_update && evp_final)
        evp_ready = 1;
    return evp_ready == 1;
}

// RFC 1321 fallback
struct Fallback {
    uint32_t a, b, c, d;
    uint64_t len;
    uint8_t tail[64];
    int ntail;
};

static const uint32_t K[64] = {
    0xd76aa478, 0xe8c7b756, 0x242070db, 0xc1bdceee, 0xf57c0faf, 0x4787c62a,
    0xa8304613, 0xfd469501, 0x698098d8, 0x8b44f7af, 0xffff5bb1, 0x895cd7be,
    0x6b901122, 0xfd987193, 0xa679438e, 0x49b40821, 0xf61e2562, 0xc040b340,
    0x265e5a51, 0xe9b6c7aa, 0xd62f105d, 0x02441453, 0xd8a1e681, 0xe7d3fbc8,
    0x21e1cde6, 0xc33707d6, 0xf4d50d87, 0x455a14ed, 0xa9e3e905, 0xfcefa3f8,
    0x676f02d9, 0x8d2a4c8a, 0xfffa3942, 0x8771f681, 0x6d9d6122, 0xfde5380c,
    0xa4beea44, 0x4bdecfa9, 0xf6bb4b60, 0xbebfbc70, 0x289b7ec6, 0xeaa127fa,
    0xd4ef3085, 0x04881d05, 0xd9d4d039, 0xe6db99e5, 0x1fa27cf8, 0xc4ac5665,
    0xf4292244, 0x432aff97, 0xab9423a7, 0xfc93a039, 0x655b59c3, 0x8f0ccc92,
    0xffeff47d, 0x85845dd1, 0x6fa87e4f, 0xfe2ce6e0, 0xa3014314, 0x4e0811a1,
    0xf7537e82, 0xbd3af235, 0x2ad7d2bb, 0xeb86d391};
static const int S[64] = {7, 12, 17, 22, 7, 12, 17, 22, 7, 12, 17, 22,
                          7, 12, 17, 22, 5, 9,  14, 20, 5, 9,  14, 20,
                          5, 9,  14, 20, 5, 9,  14, 20, 4, 11, 16, 23,
                          4, 11, 16, 23, 4, 11, 16, 23, 4, 11, 16, 23,
                          6, 10, 15, 21, 6, 10, 15, 21, 6, 10, 15, 21,
                          6, 10, 15, 21};

static void fb_block(Fallback& s, const uint8_t* p) {
    uint32_t m[16];
    std::memcpy(m, p, 64);
    uint32_t a = s.a, b = s.b, c = s.c, d = s.d;
    for (int i = 0; i < 64; i++) {
        uint32_t f;
        int g;
        if (i < 16) {
            f = (b & c) | (~b & d);
            g = i;
        } else if (i < 32) {
            f = (d & b) | (~d & c);
            g = (5 * i + 1) & 15;
        } else if (i < 48) {
            f = b ^ c ^ d;
            g = (3 * i + 5) & 15;
        } else {
            f = c ^ (b | ~d);
            g = (7 * i) & 15;
        }
        uint32_t t = d;
        d = c;
        c = b;
        uint32_t x = a + f + K[i] + m[g];
        b = b + ((x << S[i]) | (x >> (32 - S[i])));
        a = t;
    }
    s.a += a;
    s.b += b;
    s.c += c;
    s.d += d;
}

static void fb_init(Fallback& s) {
    s.a = 0x67452301;
    s.b = 0xefcdab89;
    s.c = 0x98badcfe;
    s.d = 0x10325476;
    s.len = 0;
    s.ntail = 0;
}

static void fb_update(Fallback& s, const uint8_t* p, size_t n) {
    s.len += n;
    if (s.ntail) {
        size_t take = 64 - s.ntail;
        if (take > n) take = n;
        std::memcpy(s.tail + s.ntail, p, take);
        s.ntail += (int)take;
        p += take;
        n -= take;
        if (s.ntail == 64) {
            fb_block(s, s.tail);
            s.ntail = 0;
        }
    }
    while (n >= 64) {
        fb_block(s, p);
        p += 64;
        n -= 64;
    }
    if (n) {
        std::memcpy(s.tail, p, n);
        s.ntail = (int)n;
    }
}

static void fb_final(Fallback& s, uint8_t* out16) {
    uint64_t bits = s.len * 8;
    uint8_t pad[72] = {0x80};
    size_t padlen = (s.ntail < 56) ? (size_t)(56 - s.ntail) : (size_t)(120 - s.ntail);
    fb_update(s, pad, padlen);
    uint8_t lenb[8];
    std::memcpy(lenb, &bits, 8);
    s.len -= padlen;  // fb_update bumped it; harmless but keep exact
    fb_update(s, lenb, 8);
    std::memcpy(out16, &s.a, 4);
    std::memcpy(out16 + 4, &s.b, 4);
    std::memcpy(out16 + 8, &s.c, 4);
    std::memcpy(out16 + 12, &s.d, 4);
}

struct MD5 {
    void* evp = nullptr;
    Fallback fb;

    void init() {
        if (evp_probe()) {
            evp = evp_new();
            if (evp && evp_init(evp, evp_md5(), nullptr) == 1) return;
            if (evp) evp_free(evp);
            evp = nullptr;
        }
        fb_init(fb);
    }
    void update(const uint8_t* p, size_t n) {
        if (evp)
            evp_update(evp, p, n);
        else
            fb_update(fb, p, n);
    }
    void final_(uint8_t* out16) {
        if (evp) {
            unsigned ln = 16;
            evp_final(evp, out16, &ln);
            evp_free(evp);
            evp = nullptr;
        } else {
            fb_final(fb, out16);
        }
    }
    void abort_() {
        if (evp) {
            evp_free(evp);
            evp = nullptr;
        }
    }
};

}  // namespace md5impl

extern "C" void dp_md5(const uint8_t* data, long n, uint8_t* out16) {
    md5impl::MD5 m;
    m.init();
    m.update(data, (size_t)n);
    m.final_(out16);
}

// ----------------------------------------------------------------- PUT

static const int DIGEST = 32;
static const int MAX_THREADS = 16;

// MINIO_TPU_NATIVE_THREADS, parsed strictly: a malformed or negative
// value falls back to 1 (serial — atoi would silently turn "abc" into
// auto), "0" auto-sizes to the hardware concurrency, and the pool is
// clamped to MAX_THREADS (slots are 2x threads of block_size scratch).
static int dp_parse_threads(const char* s) {
    if (!s || !*s) return 1;
    char* end = nullptr;
    long v = strtol(s, &end, 10);
    while (end && (*end == ' ' || *end == '\t')) end++;
    if (!end || *end != '\0') return 1;  // trailing junk: not a number
    if (v < 0) return 1;
    if (v == 0) {
        unsigned hc = std::thread::hardware_concurrency();
        v = hc ? (long)hc : 1;
    }
    if (v > MAX_THREADS) v = MAX_THREADS;
    return (int)v;
}

// Worker slot for the optional multi-core pipeline: one stripe block's
// padded input plus per-slot parity/digest scratch.
struct DpSlot {
    uint8_t* stripe;   // [d*per_max]
    uint8_t* parity;   // [p][per_max]
    uint8_t* digests;  // [t][32]
    long per, blockno;
    int state;  // 0 free, 1 filled, 2 stop
};

struct DpPut {
    int d, p, t;
    long block_size, per;
    uint8_t* parity_mat;  // [p][d]
    uint8_t key[32];
    int* fds;                   // [t], -1 = dead
    std::atomic<uint64_t> dead;  // bitmask by shard index
    uint8_t* buf;    // [block_size] partial-block carry
    long buffered;
    long blockno;  // next stripe block ordinal (determines file offsets)
    md5impl::MD5 md5;
    uint64_t total;
    // multi-core pipeline (MINIO_TPU_NATIVE_THREADS > 1)
    int nthreads;
    std::vector<std::thread> workers;
    std::vector<DpSlot> slots;
    std::mutex mu;
    std::condition_variable cv_work, cv_free;
    bool stopping;
};

static void dp_mark_dead(DpPut* c, int i) {
    uint64_t bit = 1ULL << i;
    if (c->dead.fetch_or(bit) & bit) return;
    // fd closed at free time (workers may race on close otherwise)
}

// pwrite the digest||shard frame for stripe block `blockno` of shard i.
// Offsets are deterministic, so blocks can complete out of order.
static void dp_write_shard(DpPut* c, int i, long blockno, const uint8_t* digest,
                           const uint8_t* shard, long n) {
    if (c->fds[i] < 0 || (c->dead.load() >> i) & 1) return;
    struct iovec iov[2];
    iov[0].iov_base = (void*)digest;
    iov[0].iov_len = DIGEST;
    iov[1].iov_base = (void*)shard;
    iov[1].iov_len = (size_t)n;
    // full blocks all share c->per; only the final tail differs
    off_t off = (off_t)blockno * (DIGEST + c->per);
    size_t want = DIGEST + (size_t)n;
    size_t done = 0;
    while (done < want) {
        ssize_t w = pwritev(c->fds[i], iov, 2, off + (off_t)done);
        if (w < 0) {
            dp_mark_dead(c, i);
            return;
        }
        done += (size_t)w;
        if (done >= want) break;
        size_t adv = (size_t)w;
        for (int k = 0; k < 2; k++) {
            if (adv >= iov[k].iov_len) {
                adv -= iov[k].iov_len;
                iov[k].iov_len = 0;
            } else {
                iov[k].iov_base = (uint8_t*)iov[k].iov_base + adv;
                iov[k].iov_len -= adv;
                adv = 0;
            }
        }
    }
}

// parity + hash + frame-write for one padded stripe held in `stripe`.
static void dp_process_stripe(DpPut* c, const uint8_t* stripe, long per,
                              long blockno, uint8_t* parity, uint8_t* digests) {
    gf_apply_strided(c->parity_mat, c->p, c->d, stripe, per, parity, per, per);
    for (int i = 0; i < c->d; i++)
        hh256(c->key, stripe + (long)i * per, per, digests + (long)i * DIGEST);
    for (int i = 0; i < c->p; i++)
        hh256(c->key, parity + (long)i * per, per,
              digests + (long)(c->d + i) * DIGEST);
    for (int i = 0; i < c->d; i++)
        dp_write_shard(c, i, blockno, digests + (long)i * DIGEST,
                       stripe + (long)i * per, per);
    for (int i = 0; i < c->p; i++)
        dp_write_shard(c, c->d + i, blockno,
                       digests + (long)(c->d + i) * DIGEST,
                       parity + (long)i * per, per);
}

static void dp_worker(DpPut* c) {
    for (;;) {
        DpSlot* s = nullptr;
        {
            std::unique_lock<std::mutex> lk(c->mu);
            c->cv_work.wait(lk, [&] {
                if (c->stopping) return true;
                for (auto& sl : c->slots)
                    if (sl.state == 1) return true;
                return false;
            });
            for (auto& sl : c->slots)
                if (sl.state == 1) {
                    sl.state = 3;  // claimed
                    s = &sl;
                    break;
                }
            if (!s) {
                if (c->stopping) return;
                continue;
            }
        }
        dp_process_stripe(c, s->stripe, s->per, s->blockno, s->parity,
                          s->digests);
        {
            std::lock_guard<std::mutex> lk(c->mu);
            s->state = 0;
        }
        c->cv_free.notify_one();
    }
}

// Encode + hash + write one stripe block: `data` holds `dlen` real bytes.
static void dp_put_block(DpPut* c, const uint8_t* data, long dlen, long per) {
    long blockno = c->blockno++;
    if (c->nthreads > 1) {
        DpSlot* s = nullptr;
        {
            std::unique_lock<std::mutex> lk(c->mu);
            c->cv_free.wait(lk, [&] {
                for (auto& sl : c->slots)
                    if (sl.state == 0) return true;
                return false;
            });
            for (auto& sl : c->slots)
                if (sl.state == 0) {
                    s = &sl;
                    break;
                }
        }
        std::memcpy(s->stripe, data, (size_t)dlen);
        if ((long)c->d * per != dlen)
            std::memset(s->stripe + dlen, 0, (size_t)((long)c->d * per - dlen));
        s->per = per;
        s->blockno = blockno;
        {
            std::lock_guard<std::mutex> lk(c->mu);
            s->state = 1;
        }
        c->cv_work.notify_one();
        return;
    }
    DpSlot& s = c->slots[0];
    const uint8_t* stripe = data;
    if ((long)c->d * per != dlen) {  // needs zero padding -> scratch copy
        std::memcpy(s.stripe, data, (size_t)dlen);
        std::memset(s.stripe + dlen, 0, (size_t)((long)c->d * per - dlen));
        stripe = s.stripe;
    }
    dp_process_stripe(c, stripe, per, blockno, s.parity, s.digests);
}

static void dp_drain(DpPut* c) {
    if (c->nthreads <= 1) return;
    std::unique_lock<std::mutex> lk(c->mu);
    c->cv_free.wait(lk, [&] {
        for (auto& sl : c->slots)
            if (sl.state != 0) return false;
        return true;
    });
}

extern "C" void* dp_put_open(int d, int p, long block_size,
                             const uint8_t* parity_mat, const uint8_t* key32,
                             const char** paths) {
    DpPut* c = new (std::nothrow) DpPut();
    if (!c) return nullptr;
    c->d = d;
    c->p = p;
    c->t = d + p;
    c->block_size = block_size;
    c->per = (block_size + d - 1) / d;
    c->nthreads = dp_parse_threads(getenv("MINIO_TPU_NATIVE_THREADS"));
    c->stopping = false;
    c->parity_mat = (uint8_t*)malloc((size_t)p * d);
    c->fds = (int*)malloc(sizeof(int) * c->t);
    c->buf = (uint8_t*)malloc((size_t)block_size);
    int nslots = c->nthreads > 1 ? 2 * c->nthreads : 1;
    bool ok = c->parity_mat && c->fds && c->buf;
    if (ok) {
        c->slots.resize(nslots);
        for (auto& s : c->slots) {
            s.stripe = (uint8_t*)malloc((size_t)d * c->per);
            s.parity = (uint8_t*)malloc((size_t)p * c->per);
            s.digests = (uint8_t*)malloc((size_t)c->t * DIGEST);
            s.state = 0;
            if (!s.stripe || !s.parity || !s.digests) ok = false;
        }
    }
    if (!ok) {
        for (auto& s : c->slots) {
            free(s.stripe); free(s.parity); free(s.digests);
        }
        free(c->parity_mat); free(c->fds); free(c->buf);
        delete c;
        return nullptr;
    }
    std::memcpy(c->parity_mat, parity_mat, (size_t)p * d);
    std::memcpy(c->key, key32, 32);
    c->dead.store(0);
    c->buffered = 0;
    c->blockno = 0;
    c->total = 0;
    c->md5.init();
    for (int i = 0; i < c->t; i++) {
        c->fds[i] = open(paths[i], O_WRONLY | O_CREAT, 0644);
        if (c->fds[i] < 0) c->dead.fetch_or(1ULL << i);
    }
    if (c->nthreads > 1)
        for (int i = 0; i < c->nthreads; i++)
            c->workers.emplace_back(dp_worker, c);
    return c;
}

extern "C" int dp_put_feed(void* ctx, const uint8_t* data, long n) {
    DpPut* c = (DpPut*)ctx;
    c->md5.update(data, (size_t)n);
    c->total += (uint64_t)n;
    // drain carry buffer first
    if (c->buffered) {
        long take = c->block_size - c->buffered;
        if (take > n) take = n;
        std::memcpy(c->buf + c->buffered, data, (size_t)take);
        c->buffered += take;
        data += take;
        n -= take;
        if (c->buffered == c->block_size) {
            dp_put_block(c, c->buf, c->block_size, c->per);
            c->buffered = 0;
        }
    }
    while (n >= c->block_size) {
        dp_put_block(c, data, c->block_size, c->per);
        data += c->block_size;
        n -= c->block_size;
    }
    if (n) {
        std::memcpy(c->buf, data, (size_t)n);
        c->buffered = n;
    }
    return 0;
}

extern "C" int dp_put_alive(void* ctx) {
    DpPut* c = (DpPut*)ctx;
    uint64_t dead = c->dead.load();
    int alive = 0;
    for (int i = 0; i < c->t; i++)
        if (c->fds[i] >= 0 && !((dead >> i) & 1)) alive++;
    return alive;
}

static void dp_put_free(DpPut* c) {
    if (c->nthreads > 1) {
        {
            std::lock_guard<std::mutex> lk(c->mu);
            c->stopping = true;
        }
        c->cv_work.notify_all();
        for (auto& w : c->workers) w.join();
    }
    for (int i = 0; i < c->t; i++)
        if (c->fds[i] >= 0) close(c->fds[i]);
    for (auto& s : c->slots) {
        free(s.stripe); free(s.parity); free(s.digests);
    }
    free(c->parity_mat); free(c->fds); free(c->buf);
    delete c;
}

// Flush the tail block, fsync nothing (rename commit handles durability
// semantics like the reference), emit md5 + dead mask. Frees the context.
extern "C" int dp_put_finish(void* ctx, uint8_t* md5_out16,
                             uint64_t* dead_mask) {
    DpPut* c = (DpPut*)ctx;
    if (c->buffered) {
        long per = (c->buffered + c->d - 1) / c->d;
        dp_put_block(c, c->buf, c->buffered, per);
        c->buffered = 0;
    }
    dp_drain(c);
    c->md5.final_(md5_out16);
    *dead_mask = c->dead.load();
    dp_put_free(c);
    return 0;
}

extern "C" void dp_put_abort(void* ctx) {
    DpPut* c = (DpPut*)ctx;
    dp_drain(c);
    c->md5.abort_();
    dp_put_free(c);
}

// ----------------------------------------------------------------- GET

// Read + verify + assemble a span of stripe blocks from the d data-shard
// files. Per block k: frame at f_off[k], shard width per[k], output window
// [lo[k], hi[k]) of the concatenated data shards. Returns bytes written to
// `out`, -(k*64 + shard + 1) on the first read/verify failure (Python
// falls back and marks the shard bad), or DP_GET_ENOMEM for a resource
// failure that blames no shard.
static const long DP_GET_ENOMEM = -(1L << 40);
extern "C" long dp_get_span(const char** paths, int d, const uint8_t* key32,
                            long nblocks, const long* f_off, const long* per,
                            const long* lo, const long* hi, uint8_t* out) {
    int fds[64];
    for (int j = 0; j < d; j++) {
        fds[j] = open(paths[j], O_RDONLY);
        if (fds[j] < 0) {
            for (int k = 0; k < j; k++) close(fds[k]);
            return -(0 * 64 + j + 1);
        }
    }
    long written = 0;
    long rc = 0;
    long scratch_cap = 0;
    uint8_t* scratch = nullptr;
    uint8_t digest[DIGEST], want[DIGEST];
    for (long k = 0; k < nblocks && rc == 0; k++) {
        long pw = per[k];
        if (pw > scratch_cap) {
            free(scratch);
            scratch_cap = pw;
            scratch = (uint8_t*)malloc((size_t)scratch_cap);
            if (!scratch) { rc = DP_GET_ENOMEM; break; }  // no shard blamed
        }
        for (int j = 0; j < d; j++) {
            long s_lo = (long)j * pw, s_hi = s_lo + pw;  // shard's data window
            long c_lo = lo[k] > s_lo ? lo[k] : s_lo;
            long c_hi = hi[k] < s_hi ? hi[k] : s_hi;
            if (c_lo >= c_hi) continue;  // outside requested window
            uint8_t* dest = out + written + (c_lo - lo[k]);
            bool full = (c_lo == s_lo && c_hi == s_hi);
            struct iovec iov[2];
            iov[0].iov_base = digest;
            iov[0].iov_len = DIGEST;
            iov[1].iov_base = full ? dest : scratch;
            iov[1].iov_len = (size_t)pw;
            size_t want_n = DIGEST + (size_t)pw;
            size_t got = 0;
            off_t pos = (off_t)f_off[k];
            while (got < want_n) {
                ssize_t r = preadv(fds[j], iov, 2, pos + (off_t)got);
                if (r <= 0) { rc = -(k * 64 + j + 1); break; }
                got += (size_t)r;
                size_t adv = (size_t)r;
                for (int m = 0; m < 2; m++) {
                    if (adv >= iov[m].iov_len) {
                        adv -= iov[m].iov_len;
                        iov[m].iov_len = 0;
                    } else {
                        iov[m].iov_base = (uint8_t*)iov[m].iov_base + adv;
                        iov[m].iov_len -= adv;
                        adv = 0;
                    }
                }
            }
            if (rc) break;
            hh256(key32, full ? dest : scratch, pw, want);
            if (std::memcmp(want, digest, DIGEST) != 0) {
                rc = -(k * 64 + j + 1);
                break;
            }
            if (!full) std::memcpy(dest, scratch + (c_lo - s_lo), (size_t)(c_hi - c_lo));
        }
        if (rc == 0) written += hi[k] - lo[k];
    }
    free(scratch);
    for (int j = 0; j < d; j++) close(fds[j]);
    return rc ? rc : written;
}

// ------------------------------------------------------- checksums (CRC)
// CRC32C rides the SSE4.2 hardware instruction (implied by -mavx2);
// CRC64/NVME is table-driven. Both are exposed for the flexible-checksums
// path (utils/checksum.py), where pure-Python table loops would dominate
// the streaming PUT budget.

#include <nmmintrin.h>

extern "C" uint32_t dp_crc32c(const uint8_t* p, long n, uint32_t prev) {
    uint64_t c = prev ^ 0xFFFFFFFFu;
    long i = 0;
    for (; i + 8 <= n; i += 8) {
        uint64_t v;
        std::memcpy(&v, p + i, 8);
        c = _mm_crc32_u64(c, v);
    }
    for (; i < n; i++) c = _mm_crc32_u8((uint32_t)c, p[i]);
    return (uint32_t)c ^ 0xFFFFFFFFu;
}

static uint64_t CRC64NVME_T[256];

// ctypes calls drop the GIL, so table init must be race-free: build it
// once at load time under a static initializer (C++11 guarantees
// thread-safe static-local initialization).
static bool crc64_init() {
    const uint64_t poly = 0x9A6C9329AC4BC9B5ULL;  // reflected CRC-64/NVME
    for (int i = 0; i < 256; i++) {
        uint64_t c = (uint64_t)i;
        for (int k = 0; k < 8; k++)
            c = (c >> 1) ^ ((c & 1) ? poly : 0);
        CRC64NVME_T[i] = c;
    }
    return true;
}
static const bool crc64_ready = crc64_init();

extern "C" uint64_t dp_crc64nvme(const uint8_t* p, long n, uint64_t prev) {
    (void)crc64_ready;
    uint64_t c = prev ^ 0xFFFFFFFFFFFFFFFFULL;
    for (long i = 0; i < n; i++)
        c = CRC64NVME_T[(c ^ p[i]) & 0xFF] ^ (c >> 8);
    return c ^ 0xFFFFFFFFFFFFFFFFULL;
}
