"""Native CPU kernel bindings (ctypes over gfhash.cpp).

Builds the shared library on first import (g++ -O3 -mavx2) and caches the
.so next to the source; every entry point has a pure-Python fallback in
ops/, so an environment without a toolchain still works (slower).
"""

from __future__ import annotations

import ctypes
import os
import subprocess
import threading

import numpy as np

_HERE = os.path.dirname(os.path.abspath(__file__))
_SRC = os.path.join(_HERE, "gfhash.cpp")
_SO = os.path.join(_HERE, "gfhash.so")

_lib = None
_lock = threading.Lock()
_build_failed = False


def _build() -> bool:
    cmd = ["g++", "-O3", "-mavx2", "-shared", "-fPIC", _SRC, "-o", _SO + ".tmp"]
    try:
        subprocess.run(cmd, check=True, capture_output=True, timeout=120)
        os.replace(_SO + ".tmp", _SO)
        return True
    except (subprocess.SubprocessError, OSError):
        return False


def _load():
    global _lib, _build_failed
    if _lib is not None or _build_failed:
        return _lib
    with _lock:
        if _lib is not None or _build_failed:
            return _lib
        if os.environ.get("MINIO_TPU_NO_NATIVE") == "1":
            _build_failed = True
            return None
        try:
            needs_build = (
                not os.path.exists(_SO)
                or os.path.getmtime(_SO) < os.path.getmtime(_SRC)
            )
            if needs_build and not _build():
                _build_failed = True
                return None
            lib = ctypes.CDLL(_SO)
        except OSError:
            _build_failed = True
            return None
        u8p = ctypes.POINTER(ctypes.c_uint8)
        lib.gf_apply.argtypes = [u8p, ctypes.c_int, ctypes.c_int, u8p, u8p, ctypes.c_long]
        lib.hh256.argtypes = [u8p, u8p, ctypes.c_long, u8p]
        lib.hh256_batch.argtypes = [
            u8p, u8p, ctypes.c_long, ctypes.c_long, ctypes.c_int, u8p,
        ]
        lib.gf_encode_hash.argtypes = [
            u8p, ctypes.c_int, ctypes.c_int, u8p, u8p, ctypes.c_long, u8p, u8p,
        ]
        _lib = lib
        return _lib


def available() -> bool:
    return _load() is not None


def _ptr(a: np.ndarray):
    return a.ctypes.data_as(ctypes.POINTER(ctypes.c_uint8))


def gf_apply(mat: np.ndarray, data: np.ndarray) -> np.ndarray:
    """out[r] = XOR_c mat[r,c] * data[c] over GF(2^8). data: [cols, n]."""
    lib = _load()
    mat = np.ascontiguousarray(mat, dtype=np.uint8)
    data = np.ascontiguousarray(data, dtype=np.uint8)
    rows, cols = mat.shape
    n = data.shape[1]
    out = np.empty((rows, n), dtype=np.uint8)
    lib.gf_apply(_ptr(mat), rows, cols, _ptr(data), _ptr(out), n)
    return out


def hh256(key: bytes, data: bytes | np.ndarray) -> bytes:
    lib = _load()
    buf = np.frombuffer(data, dtype=np.uint8) if isinstance(data, (bytes, bytearray)) else np.ascontiguousarray(data, dtype=np.uint8)
    out = np.empty(32, dtype=np.uint8)
    karr = np.frombuffer(key, dtype=np.uint8)
    lib.hh256(_ptr(karr), _ptr(buf), buf.size, _ptr(out))
    return out.tobytes()


def hh256_batch(key: bytes, blocks: np.ndarray) -> np.ndarray:
    """[B, n] uint8 -> [B, 32] digests."""
    lib = _load()
    blocks = np.ascontiguousarray(blocks, dtype=np.uint8)
    b, n = blocks.shape
    out = np.empty((b, 32), dtype=np.uint8)
    karr = np.frombuffer(key, dtype=np.uint8)
    lib.hh256_batch(_ptr(karr), _ptr(blocks), n, n, b, _ptr(out))
    return out


def gf_encode_hash(
    parity_mat: np.ndarray, data: np.ndarray, key: bytes
) -> tuple[np.ndarray, np.ndarray]:
    """Fused CPU encode+hash: data [d, n] -> (parity [p, n], digests [d+p, 32])."""
    lib = _load()
    parity_mat = np.ascontiguousarray(parity_mat, dtype=np.uint8)
    data = np.ascontiguousarray(data, dtype=np.uint8)
    p, d = parity_mat.shape
    n = data.shape[1]
    parity = np.empty((p, n), dtype=np.uint8)
    digests = np.empty((d + p, 32), dtype=np.uint8)
    karr = np.frombuffer(key, dtype=np.uint8)
    lib.gf_encode_hash(
        _ptr(parity_mat), p, d, _ptr(data), _ptr(parity), n, _ptr(karr), _ptr(digests)
    )
    return parity, digests
