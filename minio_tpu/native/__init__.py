"""Native CPU kernel bindings (ctypes over gfhash.cpp).

Builds the shared library on first import (g++ -O3 -mavx2) and caches the
.so next to the source; every entry point has a pure-Python fallback in
ops/, so an environment without a toolchain still works (slower).
"""

from __future__ import annotations

import ctypes
import os
import subprocess
import threading

import numpy as np

_HERE = os.path.dirname(os.path.abspath(__file__))
_SRCS = [os.path.join(_HERE, "gfhash.cpp"), os.path.join(_HERE, "dataplane.cpp")]
_SO = os.path.join(_HERE, "gfhash.so")

_lib = None
_lock = threading.Lock()
_build_failed = False


def _build() -> bool:
    cmd = ["g++", "-O3", "-mavx2", "-shared", "-fPIC", *_SRCS,
           "-o", _SO + ".tmp", "-ldl"]
    try:
        subprocess.run(cmd, check=True, capture_output=True, timeout=120)
        os.replace(_SO + ".tmp", _SO)
        return True
    except (subprocess.SubprocessError, OSError):
        return False


def _load():
    global _lib, _build_failed
    if _lib is not None or _build_failed:
        return _lib
    with _lock:
        if _lib is not None or _build_failed:
            return _lib
        if os.environ.get("MINIO_TPU_NO_NATIVE") == "1":
            _build_failed = True
            return None
        try:
            needs_build = not os.path.exists(_SO) or any(
                os.path.getmtime(_SO) < os.path.getmtime(s) for s in _SRCS
            )
            if needs_build and not _build():
                _build_failed = True
                return None
            lib = ctypes.CDLL(_SO)
        except OSError:
            _build_failed = True
            return None
        u8p = ctypes.POINTER(ctypes.c_uint8)
        lib.gf_apply.argtypes = [u8p, ctypes.c_int, ctypes.c_int, u8p, u8p, ctypes.c_long]
        lib.hh256.argtypes = [u8p, u8p, ctypes.c_long, u8p]
        lib.hh256_batch.argtypes = [
            u8p, u8p, ctypes.c_long, ctypes.c_long, ctypes.c_int, u8p,
        ]
        lib.gf_encode_hash.argtypes = [
            u8p, ctypes.c_int, ctypes.c_int, u8p, u8p, ctypes.c_long, u8p, u8p,
        ]
        # streaming data plane (dataplane.cpp)
        ccp = ctypes.POINTER(ctypes.c_char_p)
        lp = ctypes.POINTER(ctypes.c_long)
        lib.dp_put_open.restype = ctypes.c_void_p
        lib.dp_put_open.argtypes = [
            ctypes.c_int, ctypes.c_int, ctypes.c_long, u8p, u8p, ccp,
        ]
        lib.dp_put_feed.argtypes = [ctypes.c_void_p, u8p, ctypes.c_long]
        lib.dp_put_alive.argtypes = [ctypes.c_void_p]
        lib.dp_put_alive.restype = ctypes.c_int
        lib.dp_put_finish.argtypes = [
            ctypes.c_void_p, u8p, ctypes.POINTER(ctypes.c_uint64),
        ]
        lib.dp_put_abort.argtypes = [ctypes.c_void_p]
        lib.dp_get_span.restype = ctypes.c_long
        lib.dp_get_span.argtypes = [ccp, ctypes.c_int, u8p, ctypes.c_long,
                                    lp, lp, lp, lp, u8p]
        lib.dp_md5.argtypes = [u8p, ctypes.c_long, u8p]
        lib.dp_crc32c.argtypes = [u8p, ctypes.c_long, ctypes.c_uint32]
        lib.dp_crc32c.restype = ctypes.c_uint32
        lib.dp_crc64nvme.argtypes = [u8p, ctypes.c_long, ctypes.c_uint64]
        lib.dp_crc64nvme.restype = ctypes.c_uint64
        _lib = lib
        return _lib


def available() -> bool:
    return _load() is not None


def _ptr(a: np.ndarray):
    return a.ctypes.data_as(ctypes.POINTER(ctypes.c_uint8))


def gf_apply(mat: np.ndarray, data: np.ndarray) -> np.ndarray:
    """out[r] = XOR_c mat[r,c] * data[c] over GF(2^8). data: [cols, n]."""
    lib = _load()
    mat = np.ascontiguousarray(mat, dtype=np.uint8)
    data = np.ascontiguousarray(data, dtype=np.uint8)
    rows, cols = mat.shape
    n = data.shape[1]
    out = np.empty((rows, n), dtype=np.uint8)
    lib.gf_apply(_ptr(mat), rows, cols, _ptr(data), _ptr(out), n)
    return out


def hh256(key: bytes, data: bytes | np.ndarray) -> bytes:
    lib = _load()
    buf = np.frombuffer(data, dtype=np.uint8) if isinstance(data, (bytes, bytearray)) else np.ascontiguousarray(data, dtype=np.uint8)
    out = np.empty(32, dtype=np.uint8)
    karr = np.frombuffer(key, dtype=np.uint8)
    lib.hh256(_ptr(karr), _ptr(buf), buf.size, _ptr(out))
    return out.tobytes()


def hh256_batch(key: bytes, blocks: np.ndarray) -> np.ndarray:
    """[B, n] uint8 -> [B, 32] digests."""
    lib = _load()
    blocks = np.ascontiguousarray(blocks, dtype=np.uint8)
    b, n = blocks.shape
    out = np.empty((b, 32), dtype=np.uint8)
    karr = np.frombuffer(key, dtype=np.uint8)
    lib.hh256_batch(_ptr(karr), _ptr(blocks), n, n, b, _ptr(out))
    return out


class DataplanePut:
    """Streaming native PUT: feed raw bytes, shards land framed on disk.

    One GIL-releasing C++ pass per feed: md5 -> stripe split -> GF parity
    -> HighwayHash -> digest||block framing -> writev (dataplane.cpp).
    paths are per erasure-shard-index staged files; a failing drive marks
    its shard dead and the pass continues (quorum judged by the caller).
    """

    def __init__(self, d: int, p: int, block_size: int,
                 parity_mat: np.ndarray, key: bytes, paths: list[str]):
        lib = _load()
        if lib is None:
            raise RuntimeError("native library unavailable")
        mat = np.ascontiguousarray(parity_mat, dtype=np.uint8)
        karr = np.frombuffer(key, dtype=np.uint8)
        arr = (ctypes.c_char_p * len(paths))(*[s.encode() for s in paths])
        self._lib = lib
        self._ctx = lib.dp_put_open(d, p, block_size, _ptr(mat), _ptr(karr), arr)
        if not self._ctx:
            raise MemoryError("dp_put_open failed")

    def feed(self, chunk: bytes | bytearray | memoryview) -> None:
        n = len(chunk)
        if not n:
            return
        arr = np.frombuffer(chunk, dtype=np.uint8)  # zero-copy view
        self._lib.dp_put_feed(self._ctx, _ptr(arr), n)

    def alive(self) -> int:
        return self._lib.dp_put_alive(self._ctx)

    def finish(self) -> tuple[str, int]:
        """-> (md5-hex etag, dead shard bitmask). Frees the context."""
        out = np.empty(16, dtype=np.uint8)
        mask = ctypes.c_uint64(0)
        self._lib.dp_put_finish(self._ctx, _ptr(out), ctypes.byref(mask))
        self._ctx = None
        return out.tobytes().hex(), int(mask.value)

    def abort(self) -> None:
        if self._ctx:
            self._lib.dp_put_abort(self._ctx)
            self._ctx = None

    def __del__(self):  # noqa: D105 — safety net for abandoned contexts
        try:
            self.abort()
        except Exception:  # noqa: BLE001
            pass


def dataplane_available() -> bool:
    return _load() is not None


def crc32c(data: bytes, prev: int = 0) -> int:
    arr = np.frombuffer(data, dtype=np.uint8)
    return int(_load().dp_crc32c(_ptr(arr), arr.size, prev))


def crc64nvme(data: bytes, prev: int = 0) -> int:
    arr = np.frombuffer(data, dtype=np.uint8)
    return int(_load().dp_crc64nvme(_ptr(arr), arr.size, prev))


DP_GET_ENOMEM = -(1 << 40)  # resource failure sentinel: blames no shard


def dp_get_span(paths: list[str], d: int, key: bytes, f_off: np.ndarray,
                per: np.ndarray, lo: np.ndarray, hi: np.ndarray,
                out: np.ndarray) -> int:
    """Read+verify+assemble stripe blocks from local shard files.

    Returns bytes written (== sum(hi-lo)), a negative failure code
    -(block*64 + shard + 1) on the first read/bitrot failure, or
    DP_GET_ENOMEM (no shard at fault)."""
    lib = _load()
    arr = (ctypes.c_char_p * d)(*[s.encode() for s in paths[:d]])
    karr = np.frombuffer(key, dtype=np.uint8)
    lp = ctypes.POINTER(ctypes.c_long)
    return int(lib.dp_get_span(
        arr, d, _ptr(karr), len(f_off),
        f_off.ctypes.data_as(lp), per.ctypes.data_as(lp),
        lo.ctypes.data_as(lp), hi.ctypes.data_as(lp), _ptr(out)))


def gf_encode_hash(
    parity_mat: np.ndarray, data: np.ndarray, key: bytes
) -> tuple[np.ndarray, np.ndarray]:
    """Fused CPU encode+hash: data [d, n] -> (parity [p, n], digests [d+p, 32])."""
    lib = _load()
    parity_mat = np.ascontiguousarray(parity_mat, dtype=np.uint8)
    data = np.ascontiguousarray(data, dtype=np.uint8)
    p, d = parity_mat.shape
    n = data.shape[1]
    parity = np.empty((p, n), dtype=np.uint8)
    digests = np.empty((d + p, 32), dtype=np.uint8)
    karr = np.frombuffer(key, dtype=np.uint8)
    lib.gf_encode_hash(
        _ptr(parity_mat), p, d, _ptr(data), _ptr(parity), n, _ptr(karr), _ptr(digests)
    )
    return parity, digests
