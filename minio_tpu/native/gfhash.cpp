// Native CPU kernels: GF(2^8) matrix apply + HighwayHash-256.
//
// The TPU path (ops/rs_jax.py, ops/bitrot_jax.py) is the hot plane; this
// library is the CPU fallback the reference gets from Go-assembly deps
// (klauspost/reedsolomon AVX2 and minio/highwayhash, SURVEY.md §2.9):
// variable-size stripe tails, non-TPU deployments, and drive-side verify.
//
// GF kernel: multiply-by-constant via two 16-entry nibble tables applied
// with VPSHUFB over 32-byte lanes — the standard GF(2^8) SIMD formulation.
// HighwayHash: scalar uint64 implementation of the spec (validated against
// the reference's golden chain digests through the Python tests).
//
// Build: g++ -O3 -mavx2 -shared -fPIC gfhash.cpp -o gfhash.so

#include <cstdint>
#include <cstring>

#include <cpuid.h>
#include <immintrin.h>

// ---------------------------------------------------------------- GF(2^8)

uint8_t MUL[256][256];
// GF2P8AFFINEQB matrix encoding of multiply-by-constant: matrix byte [7-i]
// holds the input-bit coefficients of output bit i (Intel SDM bit order).
uint64_t GF_AFF[256];
static bool gf_ready = false;
static bool gfni_ok = false;

static bool cpu_has_gfni() {
    unsigned a, b, c, d;
    if (!__get_cpuid_count(7, 0, &a, &b, &c, &d)) return false;
    return (c & (1u << 8)) && (b & (1u << 5));  // GFNI + AVX2
}

#if defined(__x86_64__)
__attribute__((target("gfni,avx2")))
static bool gfni_selftest() {
    // Validate the affine-matrix bit order against the table once at init;
    // any mismatch (exotic encoding quirks) silently falls back to VPSHUFB.
    alignas(32) uint8_t in[32], out[32];
    for (int i = 0; i < 32; i++) in[i] = (uint8_t)(i * 7 + 3);
    __m256i v = _mm256_load_si256((const __m256i*)in);
    __m256i m = _mm256_set1_epi64x((long long)GF_AFF[0x1D]);
    _mm256_store_si256((__m256i*)out, _mm256_gf2p8affine_epi64_epi8(v, m, 0));
    for (int i = 0; i < 32; i++)
        if (out[i] != MUL[0x1D][in[i]]) return false;
    return true;
}
#endif

static void gf_init() {
    if (gf_ready) return;
    // exp/log over poly 0x11D, generator 2
    uint8_t exp_t[512];
    int log_t[256];
    int x = 1;
    for (int i = 0; i < 255; i++) {
        exp_t[i] = (uint8_t)x;
        log_t[x] = i;
        x <<= 1;
        if (x & 0x100) x ^= 0x11D;
    }
    for (int i = 255; i < 512; i++) exp_t[i] = exp_t[i - 255];
    for (int a = 1; a < 256; a++)
        for (int b = 1; b < 256; b++)
            MUL[a][b] = exp_t[log_t[a] + log_t[b]];
    for (int c = 0; c < 256; c++) {
        uint64_t m = 0;
        for (int i = 0; i < 8; i++) {
            uint8_t row = 0;
            for (int j = 0; j < 8; j++)
                if ((MUL[c][1 << j] >> i) & 1) row |= (uint8_t)(1 << j);
            m |= (uint64_t)row << (8 * (7 - i));
        }
        GF_AFF[c] = m;
    }
#if defined(__x86_64__)
    if (cpu_has_gfni()) gfni_ok = gfni_selftest();
#endif
    gf_ready = true;
}

extern "C" int gf_has_gfni() { gf_init(); return gfni_ok ? 1 : 0; }

#if defined(__x86_64__)
// One output row over all columns with GFNI: dst ^= mat[c]*src_c, 32 B/insn.
__attribute__((target("gfni,avx2")))
static void gf_row_gfni(const uint8_t* mat_row, int cols, const uint8_t* in,
                        long in_stride, uint8_t* dst, long n) {
    long i = 0;
    for (; i + 32 <= n; i += 32) {
        __m256i acc = _mm256_setzero_si256();
        for (int c = 0; c < cols; c++) {
            uint8_t coef = mat_row[c];
            if (!coef) continue;
            __m256i v = _mm256_loadu_si256((const __m256i*)(in + (long)c * in_stride + i));
            acc = _mm256_xor_si256(acc, _mm256_gf2p8affine_epi64_epi8(
                v, _mm256_set1_epi64x((long long)GF_AFF[coef]), 0));
        }
        _mm256_storeu_si256((__m256i*)(dst + i), acc);
    }
    if (i < n) {
        for (long k = i; k < n; k++) dst[k] = 0;
        for (int c = 0; c < cols; c++) {
            const uint8_t* T = MUL[mat_row[c]];
            const uint8_t* src = in + (long)c * in_stride;
            for (long k = i; k < n; k++) dst[k] ^= T[src[k]];
        }
    }
}
#endif

// Strided GF matrix apply: out[r] = XOR_c mat[r,c]*in[c], rows independent.
// in rows are in_stride apart; out rows out_stride apart (contiguous shards).
extern "C" void gf_apply_strided(const uint8_t* mat, int rows, int cols,
                                 const uint8_t* in, long in_stride,
                                 uint8_t* out, long out_stride, long n) {
    gf_init();
#if defined(__x86_64__)
    if (gfni_ok) {
        for (int r = 0; r < rows; r++)
            gf_row_gfni(mat + (long)r * cols, cols, in, in_stride,
                        out + (long)r * out_stride, n);
        return;
    }
#endif
    for (int r = 0; r < rows; r++) {
        uint8_t* dst = out + (long)r * out_stride;
        std::memset(dst, 0, (size_t)n);
        for (int c = 0; c < cols; c++) {
            uint8_t coef = mat[r * cols + c];
            if (coef == 0) continue;
            const uint8_t* src = in + (long)c * in_stride;
            alignas(32) uint8_t lo_t[16], hi_t[16];
            for (int v = 0; v < 16; v++) {
                lo_t[v] = MUL[coef][v];
                hi_t[v] = MUL[coef][v << 4];
            }
            long i = 0;
#ifdef __AVX2__
            const __m256i vlo = _mm256_broadcastsi128_si256(
                _mm_load_si128((const __m128i*)lo_t));
            const __m256i vhi = _mm256_broadcastsi128_si256(
                _mm_load_si128((const __m128i*)hi_t));
            const __m256i mask = _mm256_set1_epi8(0x0F);
            for (; i + 32 <= n; i += 32) {
                __m256i v = _mm256_loadu_si256((const __m256i*)(src + i));
                __m256i l = _mm256_and_si256(v, mask);
                __m256i h = _mm256_and_si256(_mm256_srli_epi16(v, 4), mask);
                __m256i prod = _mm256_xor_si256(
                    _mm256_shuffle_epi8(vlo, l), _mm256_shuffle_epi8(vhi, h));
                __m256i acc = _mm256_loadu_si256((const __m256i*)(dst + i));
                _mm256_storeu_si256((__m256i*)(dst + i),
                                    _mm256_xor_si256(acc, prod));
            }
#endif
            const uint8_t* T = MUL[coef];
            for (; i < n; i++) dst[i] ^= T[src[i]];
        }
    }
}

extern "C" void gf_apply(const uint8_t* mat, int rows, int cols,
                         const uint8_t* in, uint8_t* out, long n) {
    // in: [cols][n] contiguous; out: [rows][n]; out = mat (*) in over GF.
    gf_apply_strided(mat, rows, cols, in, n, out, n, n);
}

// ------------------------------------------------------------ HighwayHash

struct HHState {
    uint64_t v0[4], v1[4], mul0[4], mul1[4];
};

static const uint64_t INIT0[4] = {0xdbe6d5d5fe4cce2fULL, 0xa4093822299f31d0ULL,
                                  0x13198a2e03707344ULL, 0x243f6a8885a308d3ULL};
static const uint64_t INIT1[4] = {0x3bd39e10cb0ef593ULL, 0xc0acf169b5f18a8cULL,
                                  0xbe5466cf34e90c6cULL, 0x452821e638d01377ULL};

static inline uint64_t rd64(const uint8_t* p) {
    uint64_t v;
    std::memcpy(&v, p, 8);
    return v;  // little-endian host
}

static void hh_reset(HHState& s, const uint8_t* key32) {
    uint64_t k[4];
    for (int i = 0; i < 4; i++) k[i] = rd64(key32 + 8 * i);
    for (int i = 0; i < 4; i++) {
        s.mul0[i] = INIT0[i];
        s.mul1[i] = INIT1[i];
        s.v0[i] = INIT0[i] ^ k[i];
        s.v1[i] = INIT1[i] ^ ((k[i] >> 32) | (k[i] << 32));
    }
}

static inline void zipper_merge_add(uint64_t v1, uint64_t v0,
                                    uint64_t& add1, uint64_t& add0) {
    add0 += (((v0 & 0x00000000ff000000ULL) | (v1 & 0x000000ff00000000ULL)) >> 24) |
            (((v0 & 0x0000ff0000000000ULL) | (v1 & 0x00ff000000000000ULL)) >> 16) |
            (v0 & 0x0000000000ff0000ULL) | ((v0 & 0x000000000000ff00ULL) << 32) |
            ((v1 & 0xff00000000000000ULL) >> 8) | (v0 << 56);
    add1 += (((v1 & 0x00000000ff000000ULL) | (v0 & 0x000000ff00000000ULL)) >> 24) |
            (v1 & 0x0000000000ff0000ULL) | ((v1 & 0x0000ff0000000000ULL) >> 16) |
            ((v1 & 0x000000000000ff00ULL) << 24) |
            ((v0 & 0x00ff000000000000ULL) >> 8) |
            ((v1 & 0x00000000000000ffULL) << 48) |
            (v0 & 0xff00000000000000ULL);
}

static void hh_update(HHState& s, const uint8_t* packet) {
    for (int i = 0; i < 4; i++) {
        uint64_t a = rd64(packet + 8 * i);
        s.v1[i] += s.mul0[i] + a;
        s.mul0[i] ^= (s.v1[i] & 0xffffffffULL) * (s.v0[i] >> 32);
        s.v0[i] += s.mul1[i];
        s.mul1[i] ^= (s.v0[i] & 0xffffffffULL) * (s.v1[i] >> 32);
    }
    zipper_merge_add(s.v1[1], s.v1[0], s.v0[1], s.v0[0]);
    zipper_merge_add(s.v1[3], s.v1[2], s.v0[3], s.v0[2]);
    zipper_merge_add(s.v0[1], s.v0[0], s.v1[1], s.v1[0]);
    zipper_merge_add(s.v0[3], s.v0[2], s.v1[3], s.v1[2]);
}

static inline uint64_t rot32(uint64_t x) { return (x >> 32) | (x << 32); }

static void hh_update_remainder(HHState& s, const uint8_t* bytes, size_t size) {
    const size_t size4 = size & 3;
    for (int i = 0; i < 4; i++) s.v0[i] += ((uint64_t)size << 32) + size;
    for (int i = 0; i < 4; i++) {
        uint32_t lo = (uint32_t)s.v1[i], hi = (uint32_t)(s.v1[i] >> 32);
        lo = (lo << size) | (lo >> (32 - size));
        hi = (hi << size) | (hi >> (32 - size));
        s.v1[i] = ((uint64_t)hi << 32) | lo;
    }
    uint8_t packet[32] = {0};
    const size_t whole = size & ~(size_t)3;
    std::memcpy(packet, bytes, whole);
    if (size & 16) {
        std::memcpy(packet + 28, bytes + size - 4, 4);
    } else if (size4) {
        const uint8_t* rem = bytes + whole;
        packet[16] = rem[0];
        packet[17] = rem[size4 >> 1];
        packet[18] = rem[size4 - 1];
    }
    hh_update(s, packet);
}

static void hh_permute_update(HHState& s) {
    uint8_t packet[32];
    uint64_t p[4] = {rot32(s.v0[2]), rot32(s.v0[3]), rot32(s.v0[0]),
                     rot32(s.v0[1])};
    std::memcpy(packet, p, 32);
    hh_update(s, packet);
}

static void modular_reduction(uint64_t a3u, uint64_t a2, uint64_t a1,
                              uint64_t a0, uint64_t& m1, uint64_t& m0) {
    uint64_t a3 = a3u & 0x3fffffffffffffffULL;
    m1 = a1 ^ ((a3 << 1) | (a2 >> 63)) ^ ((a3 << 2) | (a2 >> 62));
    m0 = a0 ^ (a2 << 1) ^ (a2 << 2);
}

#ifdef __AVX2__
// Vectorized bulk update: the four u64 lanes of each state vector map to one
// ymm register; zipper_merge is a per-128-bit-lane byte shuffle. Bit-exact
// with the scalar path (the golden chain digests cover both).
static long hh_bulk_avx2(HHState& s, const uint8_t* data, long n) {
    if (n < 32) return 0;
    __m256i v0 = _mm256_loadu_si256((const __m256i*)s.v0);
    __m256i v1 = _mm256_loadu_si256((const __m256i*)s.v1);
    __m256i mul0 = _mm256_loadu_si256((const __m256i*)s.mul0);
    __m256i mul1 = _mm256_loadu_si256((const __m256i*)s.mul1);
    const __m256i zmask = _mm256_setr_epi8(
        3, 12, 2, 5, 14, 1, 15, 0, 11, 4, 10, 13, 9, 6, 8, 7,
        3, 12, 2, 5, 14, 1, 15, 0, 11, 4, 10, 13, 9, 6, 8, 7);
    long off = 0;
    for (; off + 32 <= n; off += 32) {
        __m256i a = _mm256_loadu_si256((const __m256i*)(data + off));
        v1 = _mm256_add_epi64(v1, _mm256_add_epi64(mul0, a));
        mul0 = _mm256_xor_si256(
            mul0, _mm256_mul_epu32(v1, _mm256_srli_epi64(v0, 32)));
        v0 = _mm256_add_epi64(v0, mul1);
        mul1 = _mm256_xor_si256(
            mul1, _mm256_mul_epu32(v0, _mm256_srli_epi64(v1, 32)));
        v0 = _mm256_add_epi64(v0, _mm256_shuffle_epi8(v1, zmask));
        v1 = _mm256_add_epi64(v1, _mm256_shuffle_epi8(v0, zmask));
    }
    _mm256_storeu_si256((__m256i*)s.v0, v0);
    _mm256_storeu_si256((__m256i*)s.v1, v1);
    _mm256_storeu_si256((__m256i*)s.mul0, mul0);
    _mm256_storeu_si256((__m256i*)s.mul1, mul1);
    return off;
}
#endif

extern "C" void hh256(const uint8_t* key32, const uint8_t* data, long n,
                      uint8_t* out32) {
    HHState s;
    hh_reset(s, key32);
    long off = 0;
#ifdef __AVX2__
    off = hh_bulk_avx2(s, data, n);
#endif
    for (; off + 32 <= n; off += 32) hh_update(s, data + off);
    if (n - off) hh_update_remainder(s, data + off, (size_t)(n - off));
    for (int i = 0; i < 10; i++) hh_permute_update(s);
    uint64_t m[4];
    modular_reduction(s.v1[1] + s.mul1[1], s.v1[0] + s.mul1[0],
                      s.v0[1] + s.mul0[1], s.v0[0] + s.mul0[0], m[1], m[0]);
    modular_reduction(s.v1[3] + s.mul1[3], s.v1[2] + s.mul1[2],
                      s.v0[3] + s.mul0[3], s.v0[2] + s.mul0[2], m[3], m[2]);
    std::memcpy(out32, m, 32);
}

extern "C" void hh256_batch(const uint8_t* key32, const uint8_t* data,
                            long stride, long n, int count, uint8_t* out) {
    for (int i = 0; i < count; i++)
        hh256(key32, data + (long)i * stride, n, out + (long)i * 32);
}

// fused erasure helper: encode parity rows AND hash every shard in one call
extern "C" void gf_encode_hash(const uint8_t* parity_mat, int p, int d,
                               const uint8_t* data, uint8_t* parity, long n,
                               const uint8_t* key32, uint8_t* digests) {
    gf_apply(parity_mat, p, d, data, parity, n);
    for (int i = 0; i < d; i++)
        hh256(key32, data + (long)i * n, n, digests + (long)i * 32);
    for (int i = 0; i < p; i++)
        hh256(key32, parity + (long)i * n, n, digests + (long)(d + i) * 32);
}
