"""Batch job framework.

Mirrors /root/reference/cmd/batch-*.go: YAML job definitions (replicate,
expire; the reference adds key-rotate) submitted over the admin API run in
a background pool with progress checkpointed as objects under .minio.sys
so an interrupted job resumes after restart (batchJobInfo,
cmd/batch-handlers.go:734).
"""

from __future__ import annotations

import json
import threading
import time
import uuid
from dataclasses import dataclass, field

import yaml

SYSTEM_BUCKET = ".minio.sys"
JOBS_PREFIX = "batch-jobs"


@dataclass
class JobStatus:
    job_id: str
    job_type: str
    state: str = "queued"  # queued | running | done | failed | canceled
    objects_scanned: int = 0
    objects_acted: int = 0
    failed: int = 0
    last_object: str = ""
    started: float = 0.0
    finished: float = 0.0
    error: str = ""

    def to_dict(self) -> dict:
        return dict(self.__dict__)


class BatchJobPool:
    def __init__(self, store, bucket_meta, replication_pool=None, workers: int = 1,
                 auto_resume: bool = True, kms=None):
        self.store = store
        self.buckets = bucket_meta
        self.repl = replication_pool
        self.kms = kms
        self.jobs: dict[str, JobStatus] = {}
        self._defs: dict[str, dict] = {}
        self._cancel: set[str] = set()
        self._mu = threading.Lock()
        self._load_checkpoints()
        if auto_resume:
            # interrupted jobs (marked queued by _load_checkpoints) resume
            # from their cursor — the actual restart-resume behavior
            for job_id, st in list(self.jobs.items()):
                if st.state == "queued" and self._defs.get(job_id):
                    threading.Thread(
                        target=self._run, args=(job_id,), daemon=True,
                        name=f"batch-resume-{job_id}",
                    ).start()

    # -- persistence -------------------------------------------------------

    def _ckpt_key(self, job_id: str) -> str:
        return f"{JOBS_PREFIX}/{job_id}.json"

    def _save(self, st: JobStatus, definition: dict | None = None) -> None:
        payload = {"status": st.to_dict()}
        if definition is not None:
            payload["definition"] = definition
        try:
            self.store.put_object(
                SYSTEM_BUCKET, self._ckpt_key(st.job_id), json.dumps(payload).encode()
            )
        except Exception:  # noqa: BLE001 — checkpointing is best-effort
            pass

    def _load_checkpoints(self) -> None:
        from ..erasure.quorum import ObjectNotFound

        try:
            for raw in self.store.walk_objects(SYSTEM_BUCKET, JOBS_PREFIX + "/"):
                try:
                    _, it = self.store.get_object(SYSTEM_BUCKET, raw)
                    payload = json.loads(b"".join(it))
                    st = JobStatus(**payload["status"])
                    if st.state == "running":
                        st.state = "queued"  # interrupted: resumable
                    self.jobs[st.job_id] = st
                    self._defs[st.job_id] = payload.get("definition", {})
                except (ObjectNotFound, ValueError, KeyError):
                    continue
        except Exception:  # noqa: BLE001 — empty/first boot
            pass

    # -- API ---------------------------------------------------------------

    def start(self, yaml_text: str) -> JobStatus:
        spec = yaml.safe_load(yaml_text)
        if not isinstance(spec, dict):
            raise ValueError("job definition must be a mapping")
        if "replicate" in spec:
            job_type = "replicate"
        elif "expire" in spec:
            job_type = "expire"
        elif "keyrotate" in spec:
            job_type = "keyrotate"
        else:
            raise ValueError(
                "unsupported job type (want replicate:, expire:, or keyrotate:)"
            )
        st = JobStatus(job_id=str(uuid.uuid4())[:13], job_type=job_type)
        with self._mu:
            self.jobs[st.job_id] = st
            self._defs[st.job_id] = spec
        self._save(st, spec)
        threading.Thread(
            target=self._run, args=(st.job_id,), daemon=True,
            name=f"batch-{st.job_id}",
        ).start()
        return st

    def cancel(self, job_id: str) -> bool:
        with self._mu:
            if job_id not in self.jobs:
                return False
            self._cancel.add(job_id)
        return True

    def describe(self, job_id: str) -> JobStatus | None:
        return self.jobs.get(job_id)

    def list(self) -> list[JobStatus]:
        return sorted(self.jobs.values(), key=lambda s: -s.started)

    # -- runner ------------------------------------------------------------

    def _run(self, job_id: str) -> None:
        st = self.jobs[job_id]
        spec = self._defs[job_id]
        st.state = "running"
        st.started = st.started or time.time()
        self._save(st, spec)
        try:
            if st.job_type == "replicate":
                self._run_replicate(st, spec["replicate"])
            elif st.job_type == "keyrotate":
                self._run_keyrotate(st, spec["keyrotate"])
            else:
                self._run_expire(st, spec["expire"])
            st.state = "canceled" if job_id in self._cancel else "done"
        except Exception as e:  # noqa: BLE001
            st.state = "failed"
            st.error = str(e)
        st.finished = time.time()
        self._save(st, spec)

    def _iter_objects(self, st: JobStatus, bucket: str, prefix: str):
        """Resumes after st.last_object (the checkpoint cursor)."""
        n = 0
        for raw in self.store.walk_objects(bucket, prefix):
            if st.job_id in self._cancel:
                return
            if st.last_object and raw <= st.last_object:
                continue
            yield raw
            st.last_object = raw
            n += 1
            if n % 100 == 0:
                self._save(st, self._defs[st.job_id])

    def _run_replicate(self, st: JobStatus, spec: dict) -> None:
        src = spec.get("source", {})
        tgt = spec.get("target", {})
        bucket = src.get("bucket", "")
        prefix = src.get("prefix", "")
        from ..client import S3Client

        cli = S3Client(
            tgt.get("endpoint", ""),
            tgt.get("credentials", {}).get("accessKey", "minioadmin"),
            tgt.get("credentials", {}).get("secretKey", "minioadmin"),
        )
        tbucket = tgt.get("bucket", bucket)
        for raw in self._iter_objects(st, bucket, prefix):
            st.objects_scanned += 1
            try:
                oi, it = self.store.get_object(bucket, raw)
                r = cli.put_object(tbucket, raw, b"".join(it))
                if r.status == 200:
                    st.objects_acted += 1
                else:
                    st.failed += 1
            except Exception:  # noqa: BLE001
                st.failed += 1

    def _run_expire(self, st: JobStatus, spec: dict) -> None:
        bucket = spec.get("bucket", "")
        prefix = spec.get("prefix", "")
        older_than = _parse_duration(spec.get("rules", [{}])[0].get("olderThan", "0s")
                                     if spec.get("rules") else spec.get("olderThan", "0s"))
        cutoff = time.time() - older_than
        versioned = self.buckets.get(bucket).versioning if self.buckets else False
        for raw in self._iter_objects(st, bucket, prefix):
            st.objects_scanned += 1
            try:
                oi = self.store.get_object_info(bucket, raw)
                if oi.mod_time / 1e9 <= cutoff:
                    self.store.delete_object(bucket, raw, versioned=versioned)
                    st.objects_acted += 1
            except Exception:  # noqa: BLE001
                st.failed += 1


    def _run_keyrotate(self, st: JobStatus, spec: dict) -> None:
        """Re-encrypt SSE-S3/SSE-KMS objects at rest under fresh object
        keys (reference cmd/batch-rotate.go). Plaintext objects skip;
        only the LATEST version of each object rotates (older versions
        keep their keys, as a new version is written on versioned
        buckets)."""
        from ..crypto import sse as ssemod
        from ..server import transforms

        if self.kms is None:
            raise RuntimeError("key rotation requires a configured KMS")
        bucket = spec.get("bucket", "")
        prefix = spec.get("prefix", "")
        for raw in self._iter_objects(st, bucket, prefix):
            st.objects_scanned += 1
            try:
                # metadata-only probe first: fetching the body of a skipped
                # object would abandon a never-started read iterator and
                # leak its namespace read lock until the TTL
                oi = self.store.get_object_info(bucket, raw)
                algo = oi.user_defined.get(ssemod.META_ALGO, "")
                if algo not in ("SSE-S3", "SSE-KMS"):
                    continue  # SSE-C needs the customer key; plaintext skips
                oi, it = self.store.get_object(bucket, raw)
                plain = transforms.decode_full(
                    b"".join(it), oi.user_defined, {}, bucket, raw, self.kms
                )
                if algo == "SSE-KMS":
                    hdr = {"x-amz-server-side-encryption": "aws:kms"}
                    key_id = oi.user_defined.get(ssemod.META_KMS_KEY_ID, "")
                    if key_id:  # keep the object's recorded KMS key
                        hdr["x-amz-server-side-encryption-aws-kms-key-id"] = key_id
                else:
                    hdr = {"x-amz-server-side-encryption": "AES256"}
                tr = transforms.encode_for_store(
                    plain, raw, oi.content_type or "", hdr, None, self.kms, bucket
                )
                meta = {
                    k: v for k, v in oi.user_defined.items()
                    # strip crypto/compression internals (re-derived below)
                    # but KEEP stored client checksums: the plaintext is
                    # unchanged by rotation
                    if not k.startswith("x-minio-internal-")
                    or k.startswith("x-minio-internal-checksum-")
                }
                if oi.content_type:
                    meta["content-type"] = oi.content_type
                meta.update(tr.metadata)
                versioned = (
                    self.buckets.get(bucket).versioning if self.buckets else False
                )
                self.store.put_object(
                    bucket, raw, tr.data, meta, versioned=versioned
                )
                st.objects_acted += 1
            except Exception:  # noqa: BLE001
                st.failed += 1


def _parse_duration(s: str) -> float:
    s = str(s).strip()
    units = {"s": 1, "m": 60, "h": 3600, "d": 86400, "w": 604800}
    if s and s[-1] in units:
        return float(s[:-1]) * units[s[-1]]
    return float(s or 0)
