"""Batch jobs: YAML-driven replicate/expire with checkpointed progress."""
