"""ILM: bucket lifecycle configuration and evaluation."""
