"""Bucket lifecycle (ILM): config parsing and expiry evaluation.

Mirrors the reference's lifecycle engine (/root/reference/internal/bucket/
lifecycle + cmd/bucket-lifecycle.go): rules with prefix/tag filters drive
current-version expiry, noncurrent-version expiry, and expired
delete-marker cleanup. Evaluation runs inside the data scanner
(cmd/data-scanner.go applyLifecycle); transitions to remote tiers parse
and validate but are executed by the (future) tiering worker.
"""

from __future__ import annotations

import time
import xml.etree.ElementTree as ET
from dataclasses import dataclass, field
from datetime import datetime, timezone

DAY = 24 * 3600

ACTION_NONE = "none"
ACTION_DELETE = "delete"  # expire current version (adds marker if versioned)
ACTION_DELETE_VERSION = "delete-version"  # hard-delete a noncurrent version
ACTION_DELETE_MARKER = "delete-marker"  # remove an expired delete marker
ACTION_TRANSITION = "transition"  # move data to a warm tier


@dataclass
class Rule:
    rule_id: str = ""
    status: str = "Enabled"
    prefix: str = ""
    tags: dict[str, str] = field(default_factory=dict)
    expiry_days: int = 0
    expiry_date: float = 0.0
    expire_delete_marker: bool = False
    noncurrent_days: int = 0
    newer_noncurrent_versions: int = 0
    transition_days: int = 0
    transition_date: float = 0.0
    transition_tier: str = ""

    def transition_due(self, age: float, now: float) -> bool:
        if not self.transition_tier:
            return False
        if self.transition_date:
            return now >= self.transition_date
        return age >= self.transition_days * DAY

    @property
    def enabled(self) -> bool:
        return self.status == "Enabled"

    def matches(self, key: str, tags: dict[str, str] | None = None) -> bool:
        if self.prefix and not key.startswith(self.prefix):
            return False
        if self.tags:
            have = tags or {}
            for k, v in self.tags.items():
                if have.get(k) != v:
                    return False
        return True


def parse_lifecycle(xml_text: str) -> list[Rule]:
    if not xml_text:
        return []
    root = ET.fromstring(xml_text)
    rules: list[Rule] = []
    for rel in root:
        if not rel.tag.endswith("Rule"):
            continue
        r = Rule()
        for el in rel:
            t = el.tag.split("}")[-1]
            if t == "ID":
                r.rule_id = el.text or ""
            elif t == "Status":
                r.status = el.text or "Enabled"
            elif t == "Prefix":
                r.prefix = el.text or ""
            elif t == "Filter":
                for sub in el.iter():
                    st = sub.tag.split("}")[-1]
                    if st == "Prefix" and sub.text:
                        r.prefix = sub.text
                    elif st == "Tag":
                        k = v = ""
                        for kv in sub:
                            if kv.tag.endswith("Key"):
                                k = kv.text or ""
                            elif kv.tag.endswith("Value"):
                                v = kv.text or ""
                        if k:
                            r.tags[k] = v
            elif t == "Expiration":
                for sub in el:
                    st = sub.tag.split("}")[-1]
                    if st == "Days" and sub.text:
                        r.expiry_days = int(sub.text)
                    elif st == "Date" and sub.text:
                        r.expiry_date = datetime.fromisoformat(
                            sub.text.replace("Z", "+00:00")
                        ).timestamp()
                    elif st == "ExpiredObjectDeleteMarker":
                        r.expire_delete_marker = (sub.text or "").lower() == "true"
            elif t == "NoncurrentVersionExpiration":
                for sub in el:
                    st = sub.tag.split("}")[-1]
                    if st == "NoncurrentDays" and sub.text:
                        r.noncurrent_days = int(sub.text)
                    elif st == "NewerNoncurrentVersions" and sub.text:
                        r.newer_noncurrent_versions = int(sub.text)
            elif t == "Transition":
                for sub in el:
                    st = sub.tag.split("}")[-1]
                    if st == "Days" and sub.text:
                        r.transition_days = int(sub.text)
                    elif st == "Date" and sub.text:
                        r.transition_date = datetime.fromisoformat(
                            sub.text.replace("Z", "+00:00")
                        ).timestamp()
                    elif st == "StorageClass" and sub.text:
                        r.transition_tier = sub.text
        rules.append(r)
    return rules


def validate_lifecycle(xml_text: str) -> None:
    rules = parse_lifecycle(xml_text)
    if not rules:
        raise ValueError("no lifecycle rules")
    for r in rules:
        if not (
            r.expiry_days or r.expiry_date or r.expire_delete_marker
            or r.noncurrent_days or r.transition_days or r.transition_tier
        ):
            raise ValueError(f"rule {r.rule_id!r} has no action")


@dataclass
class ObjectState:
    key: str
    mod_time_ns: int
    is_latest: bool
    delete_marker: bool
    num_versions: int = 1
    successor_mod_time_ns: int = 0  # when a newer version superseded this
    noncurrent_rank: int = 0  # 1 = newest noncurrent version
    tags: dict[str, str] = field(default_factory=dict)


def eval_action(rules: list[Rule], obj: ObjectState, now: float | None = None) -> str:
    """Lifecycle decision for one version (reference lifecycle.Eval)."""
    now = time.time() if now is None else now
    for r in rules:
        if not r.enabled or not r.matches(obj.key, obj.tags):
            continue
        if obj.is_latest and obj.delete_marker and r.expire_delete_marker:
            # marker with no remaining real versions underneath
            if obj.num_versions <= 1:
                return ACTION_DELETE_MARKER
        if not obj.is_latest:
            since = obj.successor_mod_time_ns / 1e9 or obj.mod_time_ns / 1e9
            if r.noncurrent_days and now - since >= r.noncurrent_days * DAY:
                # NewerNoncurrentVersions: the N newest noncurrent versions
                # are retained regardless of age
                if (
                    r.newer_noncurrent_versions
                    and obj.noncurrent_rank <= r.newer_noncurrent_versions
                ):
                    continue
                return ACTION_DELETE_VERSION
            continue
        if obj.delete_marker:
            continue
        age = now - obj.mod_time_ns / 1e9
        if r.expiry_days and age >= r.expiry_days * DAY:
            return ACTION_DELETE
        if r.expiry_date and now >= r.expiry_date:
            return ACTION_DELETE
        if r.transition_due(age, now):
            return ACTION_TRANSITION
    return ACTION_NONE


def transition_tier_for(rules: list[Rule], obj: ObjectState, now: float | None = None) -> str:
    """The tier a matching Transition rule names (after eval_action said
    ACTION_TRANSITION)."""
    now = time.time() if now is None else now
    age = now - obj.mod_time_ns / 1e9
    for r in rules:
        if r.enabled and r.matches(obj.key, obj.tags) and r.transition_due(age, now):
            return r.transition_tier
    return ""
