"""Warm-tier backends for ILM transitions.

Mirrors the reference's tier config + warm backends
(/root/reference/cmd/tier.go, cmd/warm-backend-minio.go,
cmd/warm-backend-s3.go): a named remote S3-compatible endpoint where
transitioned object data lives. The tier registry persists in the
backend; transitioned objects carry the tier name + remote key in their
metadata and are read through (or restored) on demand.
"""

from __future__ import annotations

import json
import threading
import time
import uuid
from dataclasses import dataclass

from ..client import S3Client

SYSTEM_BUCKET = ".minio.sys"
TIERS_KEY = "config/tiers.json"

# object metadata markers (internal; stripped from client responses)
TRANSITION_TIER_META = "x-minio-internal-transition-tier"
TRANSITION_KEY_META = "x-minio-internal-transitioned-key"
RESTORE_EXPIRY_META = "x-minio-internal-restore-expiry"


@dataclass
class Tier:
    name: str
    endpoint: str
    access_key: str
    secret_key: str
    bucket: str
    prefix: str = ""
    # "minio"/"s3" share the S3 wire protocol; "azure" = Blob REST with
    # SharedKey (access_key=account, secret_key=account key); "gcs" = JSON
    # API with a service-account JWT (secret_key=the SA JSON) — the same
    # four families as the reference's warm backends (cmd/warm-backend-*.go)
    tier_type: str = "minio"

    def client(self):
        # cached per Tier: the GCS backend holds an OAuth token that must
        # survive across operations (one JWT exchange per hour, not per op)
        c = getattr(self, "_client", None)
        if c is not None:
            return c
        if self.tier_type == "azure":
            from .warm_backends import AzureWarmClient

            c = AzureWarmClient(self.endpoint, self.access_key, self.secret_key)
        elif self.tier_type == "gcs":
            from .warm_backends import GCSWarmClient

            c = GCSWarmClient(self.endpoint, self.secret_key)
        else:
            c = S3Client(self.endpoint, self.access_key, self.secret_key)
        self._client = c
        return c

    def remote_key(self, bucket: str, obj: str) -> str:
        """Unique per transition epoch: a later re-transition of a changed
        object must not collide with stale tier data."""
        return f"{self.prefix}{bucket}/{obj}/{uuid.uuid4()}"

    def to_dict(self) -> dict:
        # private state (the cached client) must not persist to tiers.json
        return {k: v for k, v in self.__dict__.items() if not k.startswith("_")}


def is_transitioned(user_defined: dict) -> bool:
    return bool(user_defined.get(TRANSITION_TIER_META))


class TierRegistry:
    """Named warm tiers persisted in the backend (reference cmd/tier.go)."""

    def __init__(self, store):
        self.store = store
        self._tiers: dict[str, Tier] = {}
        self._loaded = False
        self._mu = threading.Lock()

    def _load(self) -> None:
        if self._loaded:
            return
        with self._mu:
            if self._loaded:
                return
            from ..erasure.quorum import BucketNotFound, ObjectNotFound

            try:
                _, it = self.store.get_object(SYSTEM_BUCKET, TIERS_KEY)
                self._tiers = {
                    name: Tier(**d) for name, d in json.loads(b"".join(it)).items()
                }
            except (ObjectNotFound, BucketNotFound):
                self._tiers = {}
            self._loaded = True

    def _persist(self) -> None:
        self.store.put_object(
            SYSTEM_BUCKET, TIERS_KEY,
            json.dumps({n: t.to_dict() for n, t in self._tiers.items()}).encode(),
        )

    def set(self, t: Tier) -> None:
        self._load()
        with self._mu:
            self._tiers[t.name] = t
            self._persist()

    def remove(self, name: str) -> None:
        self._load()
        with self._mu:
            self._tiers.pop(name, None)
            self._persist()

    def get(self, name: str) -> Tier | None:
        self._load()
        return self._tiers.get(name)

    def list(self) -> list[Tier]:
        self._load()
        return list(self._tiers.values())


# ---- tier garbage collection (reference cmd/tier-sweeper.go + the tier
# journal): when a transitioned version's local stub is deleted or
# overwritten, its warm-tier data must be swept or it is orphaned forever.

JOURNAL_KEY = "config/tier-journal.json"
_journal_mu = threading.Lock()
# cached entry count so metrics scrapes don't pay a store read per scrape;
# local mutations refresh it immediately, and a TTL re-reads the shared
# journal so OTHER nodes' additions surface too (the journal object is
# cluster-shared, the cache is per-process)
_journal_count: int | None = None
_journal_count_ts = 0.0
JOURNAL_CACHE_TTL = 60.0


def _journal_load(store) -> list[dict]:
    from ..erasure.quorum import BucketNotFound, ObjectNotFound

    try:
        _, it = store.get_object(SYSTEM_BUCKET, JOURNAL_KEY)
        return json.loads(b"".join(it))
    except (ObjectNotFound, BucketNotFound, ValueError):
        return []


def _journal_save(store, entries: list[dict]) -> None:
    store.put_object(SYSTEM_BUCKET, JOURNAL_KEY, json.dumps(entries).encode())


def journal_add(store, tier_name: str, remote_key: str) -> None:
    """Persist a failed sweep for retry (the reference's tierJournal)."""
    global _journal_count, _journal_count_ts
    with _journal_mu:
        entries = _journal_load(store)
        entries.append({"tier": tier_name, "key": remote_key})
        _journal_save(store, entries)
        _journal_count = len(entries)
        _journal_count_ts = time.monotonic()


def journal_size(store) -> int:
    """Entry count for metrics: cached with a TTL — local mutations
    refresh it instantly, and the periodic re-read picks up entries other
    nodes journaled into the shared object."""
    global _journal_count, _journal_count_ts
    with _journal_mu:
        now = time.monotonic()
        if _journal_count is None or now - _journal_count_ts > JOURNAL_CACHE_TTL:
            _journal_count = len(_journal_load(store))
            _journal_count_ts = now
        return _journal_count


def retry_journal(tiers: "TierRegistry") -> int:
    """Retry journaled sweeps (scanner-driven). Returns entries remaining.

    The journal lock is NOT held across the remote deletes — a down tier
    endpoint means minutes of cumulative timeouts, and journal_add sits on
    the client write path."""
    with _journal_mu:
        entries = _journal_load(tiers.store)
    if not entries:
        return 0
    resolved = []  # entries to drop: swept, or tier deconfigured
    for e in entries:
        t = tiers.get(e.get("tier", ""))
        if t is None:
            resolved.append(e)  # tier gone: nothing to sweep anymore
            continue
        try:
            r = t.client().delete_object(t.bucket, e["key"])
            if r.status not in (200, 204, 404):
                raise OSError(f"tier delete status {r.status}")
            resolved.append(e)
        except Exception:  # noqa: BLE001 — keep for the next cycle
            pass
    global _journal_count, _journal_count_ts
    with _journal_mu:
        # re-read: new failures may have been journaled while we swept
        current = _journal_load(tiers.store)
        left = [e for e in current if e not in resolved]
        _journal_save(tiers.store, left)
        _journal_count = len(left)
        _journal_count_ts = time.monotonic()
        return len(left)


def sweep_remote(tiers: "TierRegistry", user_defined: dict | None) -> None:
    """Delete a removed version's data from its warm tier. Best-effort
    direct delete; failures land in the persisted journal and are retried
    by the scanner (reference deletes via the tier journal exclusively —
    we inline the common case and journal only failures)."""
    ud = user_defined or {}
    name = ud.get(TRANSITION_TIER_META, "")
    rkey = ud.get(TRANSITION_KEY_META, "")
    if not name or not rkey:
        return
    t = tiers.get(name)
    if t is None:
        return
    try:
        r = t.client().delete_object(t.bucket, rkey)
        if r.status not in (200, 204, 404):
            raise OSError(f"tier delete status {r.status}")
    except Exception:  # noqa: BLE001 — journal for scanner retry
        try:
            journal_add(tiers.store, name, rkey)
        except Exception:  # noqa: BLE001 — journaling is best-effort too
            pass
