"""Azure Blob and GCS warm-tier backends, dependency-free.

Mirrors the reference's warm backends (/root/reference/cmd/warm-backend-
azure.go, warm-backend-gcs.go) without their SDKs: Azure Blob speaks the
Blob service REST API with SharedKey request signing; GCS speaks the JSON
API with an OAuth2 service-account JWT grant (RS256 via cryptography).
Both expose the same three-method surface the tier machinery drives
(put_object/get_object/delete_object returning S3Response), so
`Tier.client()` can hand back any backend interchangeably.

The endpoint is always explicit (no hardcoded cloud hosts): production
points at the real services, tests at loopback fakes that verify the
auth material byte-for-byte.
"""

from __future__ import annotations

import base64
import hashlib
import hmac
import http.client
import json
import threading
import time
import urllib.parse
from email.utils import formatdate

from ..client import S3Response

AZURE_API_VERSION = "2021-08-06"
GCS_SCOPE = "https://www.googleapis.com/auth/devstorage.read_write"


def _split_endpoint(endpoint: str) -> tuple[str, int, bool]:
    ep = endpoint
    tls = ep.startswith("https://")
    if "://" in ep:
        ep = ep.split("://", 1)[1]
    host, _, port = ep.partition(":")
    return host, int(port) if port else (443 if tls else 80), tls


def _http(host: str, port: int, tls: bool, timeout: float = 30.0):
    cls = http.client.HTTPSConnection if tls else http.client.HTTPConnection
    return cls(host, port, timeout=timeout)


class AzureWarmClient:
    """Azure Blob over raw REST with SharedKey signing.

    `account` is the storage account name, `key` its base64 access key;
    `container` maps to the tier bucket. Signing follows the published
    SharedKey canonicalization: the 12 standard headers, then lowercase
    sorted x-ms-* headers, then /account/path plus sorted query params.
    """

    def __init__(self, endpoint: str, account: str, key: str):
        self.host, self.port, self.tls = _split_endpoint(endpoint)
        self.account = account
        self.key = base64.b64decode(key)

    def _sign(self, verb: str, path: str, headers: dict[str, str],
              query: dict[str, str], content_length: int) -> str:
        std = {k.lower(): v for k, v in headers.items()}
        canon_headers = "".join(
            f"{k}:{std[k]}\n" for k in sorted(std) if k.startswith("x-ms-")
        )
        canon_resource = f"/{self.account}{path}"
        for qk in sorted(query):
            canon_resource += f"\n{qk.lower()}:{query[qk]}"
        string_to_sign = "\n".join([
            verb,
            std.get("content-encoding", ""),
            std.get("content-language", ""),
            str(content_length) if content_length else "",
            std.get("content-md5", ""),
            std.get("content-type", ""),
            "",  # Date: empty because x-ms-date is set
            std.get("if-modified-since", ""),
            std.get("if-match", ""),
            std.get("if-none-match", ""),
            std.get("if-unmodified-since", ""),
            std.get("range", ""),
        ]) + "\n" + canon_headers + canon_resource
        sig = base64.b64encode(
            hmac.new(self.key, string_to_sign.encode(), hashlib.sha256).digest()
        ).decode()
        return f"SharedKey {self.account}:{sig}"

    def _request(self, verb: str, container: str, key: str,
                 body: bytes = b"", query: dict[str, str] | None = None,
                 extra: dict[str, str] | None = None) -> S3Response:
        query = query or {}
        path = "/" + urllib.parse.quote(f"{container}/{key}")
        headers = {
            "x-ms-date": formatdate(usegmt=True),
            "x-ms-version": AZURE_API_VERSION,
        }
        if extra:
            headers.update(extra)
        if verb == "PUT":
            headers.setdefault("x-ms-blob-type", "BlockBlob")
            headers.setdefault("Content-Type", "application/octet-stream")
        headers["Authorization"] = self._sign(verb, path, headers, query, len(body))
        qs = urllib.parse.urlencode(query)
        conn = _http(self.host, self.port, self.tls)
        try:
            conn.request(verb, path + (f"?{qs}" if qs else ""), body=body,
                         headers=headers)
            resp = conn.getresponse()
            return S3Response(resp.status, dict(resp.getheaders()), resp.read())
        finally:
            conn.close()

    # -- the tier surface --------------------------------------------------

    def put_object(self, container: str, key: str, data: bytes,
                   headers: dict | None = None) -> S3Response:
        return self._request("PUT", container, key, body=data, extra=headers)

    def get_object(self, container: str, key: str, query: dict | None = None,
                   headers: dict | None = None) -> S3Response:
        # Range passes through as the standard header (signed)
        return self._request("GET", container, key, query=query or {},
                             extra=headers)

    def delete_object(self, container: str, key: str,
                      version_id: str = "") -> S3Response:
        r = self._request("DELETE", container, key)
        if r.status == 202:  # Azure answers Accepted; callers expect S3 codes
            return S3Response(204, r.headers, r.body)
        return r


class GCSWarmClient:
    """GCS JSON API over raw REST with a service-account JWT grant.

    `credentials` is the service-account JSON (dict or string) with
    client_email / private_key / token_uri. An RS256-signed JWT is
    exchanged at token_uri for a bearer token, cached until expiry.
    """

    def __init__(self, endpoint: str, credentials: dict | str):
        self.host, self.port, self.tls = _split_endpoint(endpoint)
        creds = json.loads(credentials) if isinstance(credentials, str) else credentials
        self.client_email = creds["client_email"]
        self.private_key_pem = creds["private_key"].encode()
        self.token_uri = creds["token_uri"]
        self._token = ""
        self._token_exp = 0.0
        self._mu = threading.Lock()

    @staticmethod
    def _b64url(data: bytes) -> bytes:
        return base64.urlsafe_b64encode(data).rstrip(b"=")

    def _fresh_token(self) -> str:
        from cryptography.hazmat.primitives import hashes, serialization
        from cryptography.hazmat.primitives.asymmetric import padding

        now = int(time.time())
        header = self._b64url(json.dumps({"alg": "RS256", "typ": "JWT"}).encode())
        claims = self._b64url(json.dumps({
            "iss": self.client_email, "scope": GCS_SCOPE,
            "aud": self.token_uri, "iat": now, "exp": now + 3600,
        }).encode())
        signing_input = header + b"." + claims
        pkey = serialization.load_pem_private_key(self.private_key_pem, password=None)
        sig = pkey.sign(signing_input, padding.PKCS1v15(), hashes.SHA256())
        assertion = (signing_input + b"." + self._b64url(sig)).decode()
        body = urllib.parse.urlencode({
            "grant_type": "urn:ietf:params:oauth:grant-type:jwt-bearer",
            "assertion": assertion,
        }).encode()
        u = urllib.parse.urlparse(self.token_uri)
        conn = _http(u.hostname, u.port or (443 if u.scheme == "https" else 80),
                     u.scheme == "https")
        try:
            conn.request("POST", u.path or "/", body=body, headers={
                "Content-Type": "application/x-www-form-urlencoded"})
            resp = conn.getresponse()
            data = json.loads(resp.read())
            if resp.status != 200 or "access_token" not in data:
                raise OSError(f"gcs token exchange failed: HTTP {resp.status}")
        finally:
            conn.close()
        self._token_exp = now + int(data.get("expires_in", 3600)) - 60
        return data["access_token"]

    def _bearer(self) -> str:
        with self._mu:
            if time.time() >= self._token_exp:
                self._token = self._fresh_token()
            return self._token

    def _request(self, verb: str, path: str, body: bytes = b"",
                 query: dict[str, str] | None = None,
                 extra: dict[str, str] | None = None) -> S3Response:
        headers = {"Authorization": f"Bearer {self._bearer()}"}
        if extra:
            headers.update(extra)
        if body:
            headers.setdefault("Content-Type", "application/octet-stream")
        qs = urllib.parse.urlencode(query or {})
        conn = _http(self.host, self.port, self.tls)
        try:
            conn.request(verb, path + (f"?{qs}" if qs else ""), body=body,
                         headers=headers)
            resp = conn.getresponse()
            return S3Response(resp.status, dict(resp.getheaders()), resp.read())
        finally:
            conn.close()

    @staticmethod
    def _obj_path(bucket: str, key: str) -> str:
        return (f"/storage/v1/b/{urllib.parse.quote(bucket, safe='')}"
                f"/o/{urllib.parse.quote(key, safe='')}")

    # -- the tier surface --------------------------------------------------

    def put_object(self, bucket: str, key: str, data: bytes,
                   headers: dict | None = None) -> S3Response:
        path = f"/upload/storage/v1/b/{urllib.parse.quote(bucket, safe='')}/o"
        return self._request("POST", path, body=data,
                             query={"uploadType": "media", "name": key},
                             extra=headers)

    def get_object(self, bucket: str, key: str, query: dict | None = None,
                   headers: dict | None = None) -> S3Response:
        q = {"alt": "media"}
        q.update(query or {})
        return self._request("GET", self._obj_path(bucket, key), query=q,
                             extra=headers)

    def delete_object(self, bucket: str, key: str,
                      version_id: str = "") -> S3Response:
        return self._request("DELETE", self._obj_path(bucket, key))
