"""Site replication: active-active sync of buckets, bucket metadata, and
IAM across independent clusters (reference cmd/site-replication.go:200,
SiteReplicationSys.Init at :232).

Design (smaller surface than the reference's 6.3k LoC, same semantics):

- A site group is a list of peers {name, endpoint, credentials}; every
  site stores the full list plus which entry is itself. The admin `add`
  call lands on one site, which identifies itself by deployment id,
  pushes a `join` to every other site, then runs the initial sync.
- Bucket creates/deletes, bucket metadata (policy, tags, lifecycle,
  versioning, ...) and the IAM snapshot (users, service accounts,
  groups, policies) propagate asynchronously through a retry queue to
  every peer's internal `site-replication/apply` admin endpoint. Peers
  apply without re-propagating (the origin already fans out to all).
- Objects ride the EXISTING bucket-replication plane: joining a site
  group wires every bucket with a remote target + rule per peer; the
  replica marker header breaks active-active loops.
"""

from __future__ import annotations

import json
import queue
import threading
import time
from dataclasses import dataclass, field

from ..client import S3Client

SYSTEM_BUCKET = ".minio.sys"
CONFIG_KEY = "config/site-replication.json"


@dataclass
class SitePeer:
    name: str
    endpoint: str
    access_key: str
    secret_key: str
    deployment_id: str = ""

    def client(self) -> S3Client:
        return S3Client(self.endpoint, self.access_key, self.secret_key)

    def to_dict(self) -> dict:
        return dict(self.__dict__)


@dataclass
class _SyncItem:
    kind: str
    payload: dict
    attempts: int = 0
    pending: list[str] = field(default_factory=list)  # peer names left


class SiteReplicationSys:
    """Per-server site replication controller (owned by the S3 server)."""

    def __init__(self, server):
        self.server = server
        self.name = ""
        self.peers: list[SitePeer] = []  # includes self
        self._q: "queue.Queue[_SyncItem]" = queue.Queue(maxsize=10000)
        self.stats = {"synced": 0, "failed": 0, "queued": 0}
        self._loaded = False
        self._worker_started = False
        self._iam_pending = False
        self._mu = threading.Lock()

    # -- config ------------------------------------------------------------

    @property
    def enabled(self) -> bool:
        self.load()
        return bool(self.name and len(self.peers) > 1)

    def others(self) -> list[SitePeer]:
        return [p for p in self.peers if p.name != self.name]

    def load(self) -> None:
        if self._loaded:
            return
        with self._mu:
            if self._loaded:
                return
            from ..erasure.quorum import BucketNotFound, ObjectNotFound

            try:
                _, it = self.server.store.get_object(SYSTEM_BUCKET, CONFIG_KEY)
                doc = json.loads(b"".join(it))
                self.name = doc.get("name", "")
                self.peers = [SitePeer(**p) for p in doc.get("peers", [])]
            except (ObjectNotFound, BucketNotFound):
                pass
            self._loaded = True
        if self.enabled:
            self._ensure_worker()

    def save(self) -> None:
        self.server.store.put_object(
            SYSTEM_BUCKET, CONFIG_KEY,
            json.dumps(
                {"name": self.name, "peers": [p.to_dict() for p in self.peers]}
            ).encode(),
        )

    def deployment_id(self) -> str:
        store = self.server.store
        pools = getattr(store, "pools", None)
        if pools:
            store = pools[0]
        dep = getattr(store, "deployment_id", "") or ""
        if dep:
            return dep
        # store layouts without a format.json deployment id (bare sets)
        # persist one so sites can identify themselves in a group
        from ..erasure.quorum import BucketNotFound, ObjectNotFound

        try:
            _, it = self.server.store.get_object(
                SYSTEM_BUCKET, "config/deployment-id"
            )
            return b"".join(it).decode()
        except (ObjectNotFound, BucketNotFound):
            import uuid

            dep = str(uuid.uuid4())
            self.server.store.put_object(
                SYSTEM_BUCKET, "config/deployment-id", dep.encode()
            )
            return dep

    # -- group formation ---------------------------------------------------

    def add_sites(self, sites: list[dict]) -> dict:
        """Coordinator: form the group, notify the other sites, seed them."""
        peers = []
        my_dep = self.deployment_id()
        my_name = ""
        for s in sites:
            peer = SitePeer(
                name=s["name"], endpoint=s["endpoint"],
                access_key=s["accessKey"], secret_key=s["secretKey"],
            )
            info = self._peer_info(peer)
            peer.deployment_id = info.get("deploymentID", "")
            if peer.deployment_id and peer.deployment_id == my_dep:
                my_name = peer.name
            peers.append(peer)
        if not my_name:
            raise ValueError("none of the given sites is this cluster")
        if len({p.name for p in peers}) != len(peers):
            raise ValueError("duplicate site names")
        # join every OTHER site first; only a fully-joined group is saved
        # locally (a half-formed group would retry-sync to absent peers
        # forever with no admin-visible breakage)
        doc = {"peers": [p.to_dict() for p in peers]}
        joined: list[SitePeer] = []
        try:
            for p in peers:
                if p.name == my_name:
                    continue
                r = p.client().request(
                    "POST", "/minio/admin/v3/site-replication/join",
                    body=json.dumps({**doc, "you": p.name}).encode(),
                )
                if r.status != 200:
                    raise RuntimeError(
                        f"site {p.name} join failed: HTTP {r.status} {r.body[:200]}"
                    )
                joined.append(p)
        except Exception:
            for p in joined:  # best-effort disband of partial joiners
                try:
                    p.client().request(
                        "POST", "/minio/admin/v3/site-replication/join",
                        body=json.dumps({"peers": [], "you": ""}).encode(),
                    )
                except Exception:  # noqa: BLE001
                    pass
            raise
        # group membership commits under _mu: `load` (lazy, any handler
        # thread) and `join` write the same pair (miniovet races pass)
        with self._mu:
            self.name, self.peers = my_name, peers
        self.save()
        self._ensure_worker()
        self.initial_sync()
        return {"success": True, "name": my_name,
                "sites": [p.name for p in peers]}

    def join(self, doc: dict) -> None:
        """Peer side of group formation (empty peers = disband)."""
        if not isinstance(doc, dict) or "peers" not in doc or "you" not in doc:
            raise ValueError("malformed join document")
        peers = [
            SitePeer(
                name=p["name"], endpoint=p["endpoint"],
                access_key=p["access_key"], secret_key=p["secret_key"],
                deployment_id=p.get("deployment_id", ""),
            )
            for p in doc["peers"]
        ]
        with self._mu:
            self.name = doc["you"]
            self.peers = peers
        self.save()
        if not peers:
            return  # disbanded
        self._ensure_worker()
        # wire existing buckets for object replication toward the others
        for bucket in self._local_buckets():
            self.wire_bucket(bucket)

    def _peer_info(self, peer: SitePeer) -> dict:
        r = peer.client().request("GET", "/minio/admin/v3/site-replication/info")
        if r.status != 200:
            raise RuntimeError(
                f"cannot reach site {peer.name} at {peer.endpoint}: HTTP {r.status}"
            )
        return json.loads(r.body)

    def info(self) -> dict:
        self.load()
        return {
            "enabled": self.enabled,
            "name": self.name,
            "deploymentID": self.deployment_id(),
            "sites": [
                {"name": p.name, "endpoint": p.endpoint,
                 "deploymentID": p.deployment_id}
                for p in self.peers
            ],
            "stats": dict(self.stats),
        }

    # -- outbound sync -----------------------------------------------------

    def _enqueue(self, kind: str, payload: dict) -> None:
        if not self.enabled:
            return
        if kind == "iam":
            # coalesce under the lock: frequent IAM persists (e.g. STS
            # mints) need only the latest snapshot on the wire
            with self._mu:
                if self._iam_pending:
                    return
                self._iam_pending = True
        try:
            self._q.put_nowait(
                _SyncItem(kind, payload, pending=[p.name for p in self.others()])
            )
            self._stat("queued")
        except queue.Full:
            if kind == "iam":
                with self._mu:
                    self._iam_pending = False
            self._stat("failed")

    def sync_bucket_create(self, bucket: str) -> None:
        self._enqueue("bucket-create", {"bucket": bucket})
        self.wire_bucket(bucket)

    def sync_bucket_delete(self, bucket: str) -> None:
        self._enqueue("bucket-delete", {"bucket": bucket})

    def sync_bucket_meta(self, bucket: str, bm) -> None:
        self._enqueue(
            "bucket-meta", {"bucket": bucket, "meta": _exportable_meta(bm)}
        )

    def sync_iam(self) -> None:
        self._enqueue("iam", self._iam_snapshot())

    def _iam_snapshot(self) -> dict:
        iam = self.server.iam
        with iam._lock:
            users = {
                k: u.to_dict() for k, u in iam.users.items() if not u.is_temp
            }
            from ..iam.policy import CANNED_POLICIES

            policies = {
                k: p.to_dict() for k, p in iam.policies.items()
                if k not in CANNED_POLICIES
            }
            return {
                "users": users,
                "groups": json.loads(json.dumps(iam.groups)),
                "policies": policies,
                "ldap_policy_map": dict(iam.ldap_policy_map),
            }

    def _stat(self, key: str) -> None:
        # sync counters are bumped from handler contexts and the
        # site-repl worker thread; dict += is not atomic under the GIL
        # (miniovet races pass)
        with self._mu:
            self.stats[key] += 1

    def _ensure_worker(self) -> None:
        with self._mu:
            if self._worker_started:
                return
            self._worker_started = True
            threading.Thread(
                target=self._loop, daemon=True, name="site-repl"
            ).start()

    def _loop(self) -> None:
        while True:
            item = self._q.get()
            if item.kind == "iam":
                with self._mu:
                    self._iam_pending = False
                item.payload = self._iam_snapshot()  # freshest state wins
            remaining = []
            for pname in item.pending:
                peer = next((p for p in self.others() if p.name == pname), None)
                if peer is None:
                    continue
                try:
                    r = peer.client().request(
                        "POST", "/minio/admin/v3/site-replication/apply",
                        body=json.dumps(
                            {"kind": item.kind, "payload": item.payload,
                             "origin": self.name}
                        ).encode(),
                    )
                    if r.status != 200:
                        raise RuntimeError(f"HTTP {r.status}")
                    self._stat("synced")
                except Exception:  # noqa: BLE001 — peer down: retry below
                    remaining.append(pname)
            if remaining:
                item.pending = remaining
                item.attempts += 1
                if item.attempts < 8:
                    threading.Timer(
                        min(2 ** item.attempts, 60),
                        lambda it=item: self._q.put(it),
                    ).start()
                else:
                    self._stat("failed")

    # -- inbound apply -----------------------------------------------------

    def apply(self, kind: str, payload: dict) -> None:
        """Apply a change from a peer WITHOUT re-propagating."""
        if kind == "bucket-create":
            b = payload["bucket"]
            try:
                self.server.store.make_bucket(b)
            except Exception:  # noqa: BLE001 — already exists
                pass
            self.wire_bucket(b)
        elif kind == "bucket-delete":
            b = payload["bucket"]
            try:
                if self.server.store.bucket_exists(b):
                    # may race the still-draining object-replication deletes:
                    # raising makes the origin retry with backoff
                    self.server.store.delete_bucket(b)
                self.server.buckets.drop(b)  # stale metadata must not
                # resurrect on recreate (e.g. an old public-read policy)
            except Exception:
                raise
        elif kind == "bucket-meta":
            self._apply_bucket_meta(payload["bucket"], payload["meta"])
        elif kind == "iam":
            self._apply_iam(payload)
        else:
            raise ValueError(f"unknown site sync kind {kind}")

    def _apply_bucket_meta(self, bucket: str, meta: dict) -> None:
        buckets = self.server.buckets
        bm = buckets.get(bucket)
        for k, v in meta.items():
            if k in _SYNCED_META:  # never let a peer touch local-only fields
                setattr(bm, k, v)
        buckets.set(bucket, bm, notify=False)

    def _apply_iam(self, snap: dict) -> None:
        from ..iam.policy import Policy
        from ..iam.sys import UserIdentity

        iam = self.server.iam
        with iam._lock:
            iam.applying_remote = True
            try:
                keep_temp = {
                    k: u for k, u in iam.users.items() if u.is_temp
                }
                iam.users = {
                    k: UserIdentity.from_dict(v)
                    for k, v in snap.get("users", {}).items()
                }
                iam.users.update(keep_temp)
                iam.groups = dict(snap.get("groups", {}))
                from ..iam.policy import CANNED_POLICIES

                iam.policies = dict(CANNED_POLICIES)
                for k, v in snap.get("policies", {}).items():
                    iam.policies[k] = Policy.from_dict(v)
                iam.ldap_policy_map = dict(snap.get("ldap_policy_map", {}))
                iam._persist_users()
                iam._persist_groups()
                iam._persist_policies()
                iam._save("ldap_policy_map", iam.ldap_policy_map)
            finally:
                iam.applying_remote = False

    # -- object-plane wiring ----------------------------------------------

    def wire_bucket(self, bucket: str) -> None:
        """Point this bucket's replication at every peer (same bucket name);
        the rules live in LOCAL bucket metadata and are never synced."""
        if not self.enabled or bucket.startswith(".minio.sys"):
            return
        from .replicate import RemoteTarget, parse_replication_config

        rules = []
        for p in self.others():
            arn = f"arn:minio:replication::site-{p.name}:{bucket}"
            self.server.repl_targets.set(RemoteTarget(
                arn=arn, source_bucket=bucket, endpoint=p.endpoint,
                access_key=p.access_key, secret_key=p.secret_key,
                target_bucket=bucket,
            ))
            rules.append(
                f"<Rule><ID>site-{p.name}</ID><Status>Enabled</Status>"
                f"<Priority>1</Priority><Destination><Bucket>{arn}</Bucket>"
                f"</Destination></Rule>"
            )
        bm = self.server.buckets.get(bucket)
        # preserve user-configured rules (non site-*); only our own rules
        # are replaced
        try:
            existing = parse_replication_config(bm.replication or "")
        except Exception:  # noqa: BLE001
            existing = []
        for r in existing:
            if r.rule_id.startswith("site-"):
                continue
            rules.append(
                f"<Rule><ID>{r.rule_id}</ID><Status>{r.status}</Status>"
                f"<Priority>{r.priority}</Priority>"
                + (f"<Prefix>{r.prefix}</Prefix>" if r.prefix else "")
                + f"<Destination><Bucket>{r.destination_arn}</Bucket>"
                f"</Destination></Rule>"
            )
        bm.replication = (
            "<ReplicationConfiguration>" + "".join(rules)
            + "</ReplicationConfiguration>"
        )
        self.server.buckets.set(bucket, bm, notify=False)

    def _local_buckets(self) -> list[str]:
        try:
            out = []
            for b in self.server.store.list_buckets():
                name = getattr(b, "name", b)
                if not str(name).startswith(".minio.sys"):
                    out.append(str(name))
            return out
        except Exception:  # noqa: BLE001
            return []

    def initial_sync(self) -> None:
        """Seed the freshly joined peers: buckets, their metadata, IAM, and
        a full object resync per bucket."""
        for bucket in self._local_buckets():
            self._enqueue("bucket-create", {"bucket": bucket})
            self._enqueue(
                "bucket-meta",
                {"bucket": bucket, "meta": _exportable_meta(self.server.buckets.get(bucket))},
            )
            self.wire_bucket(bucket)
        self.sync_iam()
        # objects: replay through the bucket-replication plane once the
        # create has had a moment to land on the peers
        def later():
            # miniovet: ignore[blocking] -- settle delay before resync
            # replay; later() runs on its own daemon thread
            time.sleep(1.0)
            for bucket in self._local_buckets():
                try:
                    self.server.replication.resync(bucket)
                except Exception:  # noqa: BLE001
                    pass

        threading.Thread(target=later, daemon=True).start()


# bucket metadata fields that sync across sites; `replication` stays local
# (each site's rules point at ITS peers)
_SYNCED_META = (
    "policy", "tags", "lifecycle", "notification", "encryption",
    "versioning", "object_lock", "cors", "quota",
)


def _exportable_meta(bm) -> dict:
    # ALL synced fields ship, including cleared ones — deleting a bucket
    # policy on one site must un-set it on the others
    return {f: getattr(bm, f, None) for f in _SYNCED_META}
