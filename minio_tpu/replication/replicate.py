"""Async bucket replication.

Mirrors the reference's continuous replication plane
(/root/reference/cmd/bucket-replication.go): a bucket's replication config
routes object writes/deletes to ARN-addressed remote targets; a worker
pool drains an in-memory queue with retries (the MRF analogue,
queueMRFSave :482); resync replays the whole namespace. Remote targets are
S3 endpoints driven by our own client (the reference uses minio-go).
"""

from __future__ import annotations

import json
import queue
import threading
import xml.etree.ElementTree as ET
from dataclasses import dataclass, field

from ..client import S3Client

TARGETS_KEY = "config/replication-targets.json"
SYSTEM_BUCKET = ".minio.sys"


@dataclass
class ReplicationRule:
    rule_id: str = ""
    status: str = "Enabled"
    priority: int = 0
    prefix: str = ""
    destination_arn: str = ""
    delete_replication: bool = True

    def matches(self, key: str) -> bool:
        return self.status == "Enabled" and key.startswith(self.prefix)


def parse_replication_config(xml_text: str) -> list[ReplicationRule]:
    if not xml_text:
        return []
    root = ET.fromstring(xml_text)
    rules = []
    for rel in root:
        if not rel.tag.endswith("Rule"):
            continue
        r = ReplicationRule()
        for el in rel:  # direct children only: nested Status (e.g. inside
            t = el.tag.split("}")[-1]  # DeleteMarkerReplication) must not
            if t == "ID":  # override the rule's own status
                r.rule_id = el.text or ""
            elif t == "Status":
                r.status = el.text or "Enabled"
            elif t == "Priority" and el.text:
                r.priority = int(el.text)
            elif t in ("Prefix", "Filter"):
                for sub in el.iter():
                    if sub.tag.split("}")[-1] == "Prefix" and sub.text:
                        r.prefix = sub.text
                if t == "Prefix" and el.text:
                    r.prefix = el.text
            elif t == "Destination":
                for sub in el.iter():
                    if sub.tag.split("}")[-1] == "Bucket" and sub.text:
                        r.destination_arn = sub.text
        rules.append(r)
    return sorted(rules, key=lambda r: -r.priority)


@dataclass
class RemoteTarget:
    arn: str
    source_bucket: str
    endpoint: str
    access_key: str
    secret_key: str
    target_bucket: str

    def client(self) -> S3Client:
        return S3Client(self.endpoint, self.access_key, self.secret_key)

    def to_dict(self) -> dict:
        return dict(self.__dict__)


class TargetRegistry:
    """Remote replication targets persisted in the backend
    (reference cmd/bucket-targets.go)."""

    def __init__(self, store):
        self.store = store
        self._targets: dict[str, RemoteTarget] = {}
        self._loaded = False
        self._mu = threading.Lock()

    def _load(self) -> None:
        from ..erasure.quorum import ObjectNotFound

        if self._loaded:
            return
        with self._mu:
            if self._loaded:
                return
            try:
                _, it = self.store.get_object(SYSTEM_BUCKET, TARGETS_KEY)
                data = json.loads(b"".join(it))
                self._targets = {
                    arn: RemoteTarget(**d) for arn, d in data.items()
                }
            except ObjectNotFound:
                self._targets = {}
            self._loaded = True

    def set(self, t: RemoteTarget) -> None:
        self._load()
        with self._mu:
            self._targets[t.arn] = t
            self.store.put_object(
                SYSTEM_BUCKET, TARGETS_KEY,
                json.dumps({a: x.to_dict() for a, x in self._targets.items()}).encode(),
            )

    def remove(self, arn: str) -> None:
        self._load()
        with self._mu:
            self._targets.pop(arn, None)
            self.store.put_object(
                SYSTEM_BUCKET, TARGETS_KEY,
                json.dumps({a: x.to_dict() for a, x in self._targets.items()}).encode(),
            )

    def get(self, arn: str) -> RemoteTarget | None:
        self._load()
        return self._targets.get(arn)

    def list(self, bucket: str = "") -> list[RemoteTarget]:
        self._load()
        return [
            t for t in self._targets.values()
            if not bucket or t.source_bucket == bucket
        ]


REPLICA_MARKER = "x-minio-source-replication-request"


@dataclass
class _Task:
    bucket: str
    key: str
    version_id: str
    op: str  # "put" | "delete"
    arn: str = ""  # destination (multi-target buckets fan out one task per rule)
    attempts: int = 0


class ReplicationPool:
    """Worker pool replicating object mutations to remote targets.

    `decode` (optional) inverts server-side transforms (compression/SSE) so
    replicas receive logical object bytes, mirroring the reference's
    replication which decrypts/re-encrypts per site."""

    def __init__(
        self, store, bucket_meta, targets: TargetRegistry, workers: int = 2,
        decode=None,
    ):
        self.store = store
        self.buckets = bucket_meta
        self.targets = targets
        self.decode = decode
        # one queue per worker with key-affinity: mutations of the SAME
        # object stay ordered (v1 must never land after v2 on the replica)
        self._qs: list[queue.Queue[_Task]] = [
            queue.Queue(maxsize=10000) for _ in range(workers)
        ]
        self._rules_cache: dict[str, tuple[str, list[ReplicationRule]]] = {}
        self.stats = {"replicated": 0, "deletes": 0, "failed": 0, "queued": 0}
        # per-bucket counters for the v3 /bucket/replication metrics group
        self.bucket_stats: dict[str, dict[str, int]] = {}
        self._threads = [
            threading.Thread(target=self._loop, args=(q_,), daemon=True,
                             name=f"repl-{i}")
            for i, q_ in enumerate(self._qs)
        ]
        for t in self._threads:
            t.start()

    def _queue_for(self, bucket: str, key: str) -> "queue.Queue[_Task]":
        return self._qs[hash((bucket, key)) % len(self._qs)]

    def rules_for(self, bucket: str) -> list[ReplicationRule]:
        xml_text = self.buckets.get(bucket).replication or ""
        cached = self._rules_cache.get(bucket)
        if cached and cached[0] == xml_text:
            return cached[1]
        try:
            rules = parse_replication_config(xml_text)
        except ET.ParseError:
            rules = []
        self._rules_cache[bucket] = (xml_text, rules)
        return rules

    def queue_mutation(self, bucket: str, key: str, version_id: str, op: str) -> None:
        """Called from the write path after a successful put/delete.

        Fans out one task per matching rule destination — a bucket in a
        multi-site group replicates every mutation to every peer."""
        seen: set[str] = set()
        for rule in self.rules_for(bucket):
            if rule.matches(key) and rule.destination_arn not in seen:
                seen.add(rule.destination_arn)
                try:
                    self._queue_for(bucket, key).put_nowait(
                        _Task(bucket, key, version_id, op, rule.destination_arn)
                    )
                    self.stats["queued"] += 1
                    self._bstat(bucket, "queued")
                except queue.Full:
                    self.stats["failed"] += 1
                    self._bstat(bucket, "failed")

    def resync(self, bucket: str) -> int:
        """Replay the whole bucket to its targets (reference resync)."""
        n = 0
        for raw in self.store.walk_objects(bucket):
            self.queue_mutation(bucket, raw, "", "put")
            n += 1
        return n

    def drain(self, timeout: float = 30.0) -> None:
        import time

        deadline = time.monotonic() + timeout
        while any(not q_.empty() for q_ in self._qs) and time.monotonic() < deadline:
            # miniovet: ignore[blocking] -- drain() is a blocking helper
            # for tests/shutdown; worker threads do the actual replication
            time.sleep(0.05)


    def _bstat(self, bucket: str, key: str) -> None:
        rec = self.bucket_stats.setdefault(
            bucket, {"replicated": 0, "deletes": 0, "failed": 0, "queued": 0}
        )
        rec[key] += 1

    # -- worker ------------------------------------------------------------

    def _loop(self, q_: "queue.Queue[_Task]") -> None:
        while True:
            task = q_.get()
            try:
                self._replicate(task)
            except Exception as e:  # noqa: BLE001 — retry then count as failed
                task.attempts += 1
                self.stats["last_error"] = f"{type(e).__name__}: {e}"
                if task.attempts < 3:
                    threading.Timer(
                        2 ** task.attempts, lambda: q_.put(task)
                    ).start()
                else:
                    self.stats["failed"] += 1
                    self._bstat(task.bucket, "failed")

    def _replicate(self, task: _Task) -> None:
        arn = task.arn
        if not arn:
            rules = self.rules_for(task.bucket)
            rule = next((r for r in rules if r.matches(task.key)), None)
            if rule is None:
                return
            arn = rule.destination_arn
        target = self.targets.get(arn)
        if target is None:
            raise RuntimeError(f"no target for {arn}")
        cli = target.client()
        # the marker tells the replica's server not to re-replicate (the
        # loop breaker for active-active site groups; reference marks
        # replicas with x-amz-replication-status=REPLICA the same way)
        marker = {REPLICA_MARKER: "true"}
        if task.op == "delete":
            r = cli.request(
                "DELETE", f"/{target.target_bucket}/{task.key}", headers=marker
            )
            if r.status not in (200, 204, 404):
                raise RuntimeError(f"remote delete failed: HTTP {r.status}")
            self.stats["deletes"] += 1
            self._bstat(task.bucket, "deletes")
            return
        oi, it = self.store.get_object(task.bucket, task.key, task.version_id)
        data = b"".join(it)
        if self.decode is not None:
            # invert compression/SSE so the replica stores logical bytes
            data = self.decode(oi, data, task.bucket, task.key)
        headers = {"content-type": oi.content_type, **marker}
        for k, v in oi.user_defined.items():
            if k.startswith("x-amz-meta-"):
                headers[k] = v
        r = cli.put_object(target.target_bucket, task.key, data, headers=headers)
        if r.status != 200:
            raise RuntimeError(f"remote put failed: HTTP {r.status}")
        self.stats["replicated"] += 1
        self._bstat(task.bucket, "replicated")
