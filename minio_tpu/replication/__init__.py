"""Bucket replication: remote targets, async workers, resync."""
