"""obs — span tracing from S3 entry to TPU kernel.

The deep-tracing plane mirroring the reference's multi-type tracer
(/root/reference/cmd/http-tracer.go + internal/pubsub): a per-request
trace context (the generated ``x-amz-request-id``) rides a contextvar
from ``app.py:_entry`` through QoS admission, erasure object ops, the
TPU batch dispatcher, per-disk storage calls, and the background
heal/scanner planes. Every layer publishes typed records through the
server's ``TracePubSub``; with no subscribers nothing allocates
(``span()`` returns a shared no-op singleton).

Spans are opened ONLY via the context-manager API::

    with obs.span(obs.TYPE_STORAGE, "readfile", drive=ep) as sp:
        ...
        sp.set(bytes=n)

(the ``span`` miniovet rule enforces this — an orphaned start with no
``finally`` would leak the contextvar token and corrupt the tree).
"""

from .trace import (  # noqa: F401
    NOOP_SPAN,
    TRACE_TYPES,
    TYPE_DIAG,
    TYPE_FAULT,
    TYPE_HEAL,
    TYPE_INTERNAL,
    TYPE_PLACEMENT,
    TYPE_REBALANCE,
    TYPE_S3,
    TYPE_SANITIZER,
    TYPE_SCANNER,
    TYPE_STORAGE,
    TYPE_TPU,
    Span,
    active,
    bind_context,
    current_request_id,
    new_request_id,
    publish,
    publisher,
    request_context,
    set_publisher,
    set_request,
    span,
)
from .filters import TraceFilter, parse_duration  # noqa: F401
from .pool import ContextPool  # noqa: F401
