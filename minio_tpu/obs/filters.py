"""Trace stream filters — the reference's `mc admin trace` flags.

``type=`` (comma-separated trace types), ``threshold=`` (minimum span
duration; bare numbers are seconds, `ms`/`us`/`s`/`m` suffixes accepted
like Go duration strings), ``err-only=`` (only failed spans). Filters
are attached to the subscriber so records are matched once at publish
time, before they consume queue space.
"""

from __future__ import annotations

import re

from .trace import TRACE_TYPES

_DUR_RE = re.compile(r"^\s*([0-9]*\.?[0-9]+)\s*(ns|us|µs|ms|s|m|h)?\s*$")

_UNIT_S = {
    "ns": 1e-9, "us": 1e-6, "µs": 1e-6, "ms": 1e-3,
    "s": 1.0, "m": 60.0, "h": 3600.0, None: 1.0, "": 1.0,
}


def parse_duration(text: str) -> float:
    """Duration string -> seconds. Raises ValueError on garbage."""
    m = _DUR_RE.match(text)
    if not m:
        raise ValueError(f"bad duration {text!r}")
    return float(m.group(1)) * _UNIT_S[m.group(2)]


_TRUTHY = ("on", "true", "1", "yes")


class TraceFilter:
    """Predicate over trace records built from stream query params."""

    __slots__ = ("types", "threshold_ns", "err_only")

    def __init__(self, types=None, threshold_s: float = 0.0,
                 err_only: bool = False):
        self.types = frozenset(types) if types else None
        self.threshold_ns = int(threshold_s * 1e9)
        self.err_only = err_only

    @classmethod
    def from_query(cls, q) -> "TraceFilter":
        """Build from a query mapping; unknown trace types and malformed
        thresholds raise ValueError (-> 400 InvalidArgument)."""
        types = None
        raw = q.get("type", "")
        if raw:
            types = {t.strip() for t in raw.split(",") if t.strip()}
            unknown = types - TRACE_TYPES
            if unknown:
                raise ValueError(
                    f"unknown trace type(s): {', '.join(sorted(unknown))}"
                )
        threshold = parse_duration(q.get("threshold", "0")) if q.get(
            "threshold"
        ) else 0.0
        err_only = q.get("err-only", "").lower() in _TRUTHY
        return cls(types=types, threshold_s=threshold, err_only=err_only)

    @property
    def is_noop(self) -> bool:
        return self.types is None and not self.threshold_ns and not self.err_only

    def match(self, rec: dict) -> bool:
        if self.types is not None and rec.get("type") not in self.types:
            return False
        if self.threshold_ns and rec.get("durationNs", 0) < self.threshold_ns:
            return False
        if self.err_only:
            if not rec.get("error") and rec.get("statusCode", 0) < 400:
                return False
        return True

    def to_query(self) -> dict[str, str]:
        """Round-trip back to query params (peer fan-out forwards the
        caller's filters so peers pre-filter at the source). The
        threshold goes out in integer nanoseconds — a float would render
        sub-100µs values in exponent notation, which parse_duration
        rejects."""
        out: dict[str, str] = {}
        if self.types is not None:
            out["type"] = ",".join(sorted(self.types))
        if self.threshold_ns:
            out["threshold"] = f"{self.threshold_ns}ns"
        if self.err_only:
            out["err-only"] = "on"
        return out
