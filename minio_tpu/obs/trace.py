"""Span core: contextvar-propagated request ids + typed trace spans.

Zero-cost-when-idle contract (the reference checks NumSubscribers before
building a record): ``span()`` returns the shared ``NOOP_SPAN`` singleton
— no Span object, no field dict copy, no clock read — unless a publisher
is attached AND it has subscribers. Code on the hot path may therefore
open spans unconditionally.

The request context is a ``contextvars.ContextVar`` so it survives both
``await`` hops and executor hops (``ContextPool``/``bind_context`` copy
the context across thread boundaries; storage-REST carries it in an
``x-minio-reqid`` header / grid payload field between nodes).
"""

from __future__ import annotations

import contextvars
import itertools
import os
import socket
import time
from contextlib import contextmanager

TYPE_S3 = "s3"
TYPE_INTERNAL = "internal"
TYPE_STORAGE = "storage"
TYPE_TPU = "tpu"
TYPE_HEAL = "heal"
TYPE_SCANNER = "scanner"
TYPE_FAULT = "fault"
TYPE_SANITIZER = "sanitizer"
TYPE_PLACEMENT = "placement"
TYPE_REBALANCE = "rebalance"
TYPE_DIAG = "diag"
TRACE_TYPES = frozenset(
    {TYPE_S3, TYPE_INTERNAL, TYPE_STORAGE, TYPE_TPU, TYPE_HEAL,
     TYPE_SCANNER, TYPE_FAULT, TYPE_SANITIZER, TYPE_PLACEMENT,
     TYPE_REBALANCE, TYPE_DIAG}
)

# (request_id, parent_span_id); spans nest by swapping the second slot
_CTX: contextvars.ContextVar[tuple[str, int] | None] = contextvars.ContextVar(
    "minio_tpu_trace_ctx", default=None
)

_span_ids = itertools.count(1)

# the publishing TracePubSub (server/metrics.py) — module-level because
# spans open deep in layers (dispatcher, storage wrappers) that have no
# server reference; one process serves one node
_publisher = None

NODE = socket.gethostname()


def set_publisher(pub) -> None:
    global _publisher
    _publisher = pub


def publisher():
    return _publisher


def active() -> bool:
    p = _publisher
    return p is not None and p.active


def new_request_id() -> str:
    """An ``x-amz-request-id`` value: 16 uppercase hex chars (the
    reference's mustGetRequestID is a time-based variant of the same)."""
    return os.urandom(8).hex().upper()


def set_request(request_id: str):
    """Install `request_id` as the current trace context; returns the
    token for ``reset_request``. Used at plane entries (S3 entry,
    storage-REST server side); everything below inherits via contextvar
    propagation."""
    return _CTX.set((request_id, 0))


def reset_request(token) -> None:
    _CTX.reset(token)


@contextmanager
def request_context(request_id: str):
    token = _CTX.set((request_id, 0))
    try:
        yield
    finally:
        _CTX.reset(token)


def current_request_id() -> str:
    ctx = _CTX.get()
    return ctx[0] if ctx is not None else ""


def bind_context(fn):
    """Wrap `fn` so it runs under a snapshot of the CURRENT context —
    for handing work to executors that don't propagate contextvars
    (``loop.run_in_executor`` does not)."""
    ctx = contextvars.copy_context()
    return lambda *a, **kw: ctx.run(fn, *a, **kw)


def publish(record: dict) -> None:
    """Publish a pre-built record if anyone is listening (cheap guard
    for non-span record sites like the dispatcher's batch records)."""
    p = _publisher
    if p is not None and p.active:
        p.publish(record)


class Span:
    """One timed, typed trace record; context-manager only (see the
    ``span`` miniovet rule). Publishes on exit with the error captured
    from a propagating exception; never swallows it."""

    __slots__ = (
        "trace_type", "name", "fields", "req_id", "span_id", "parent_id",
        "_t0", "_token",
    )

    def __init__(self, trace_type: str, name: str, fields: dict):
        self.trace_type = trace_type
        self.name = name
        self.fields = fields
        ctx = _CTX.get()
        self.req_id = ctx[0] if ctx is not None else ""
        self.parent_id = ctx[1] if ctx is not None else 0
        self.span_id = next(_span_ids)
        self._t0 = 0.0
        self._token = None

    def __enter__(self) -> "Span":
        self._token = _CTX.set((self.req_id, self.span_id))
        self._t0 = time.perf_counter()
        return self

    def set(self, **fields) -> None:
        self.fields.update(fields)

    def __exit__(self, exc_type, exc, tb) -> bool:
        dur = time.perf_counter() - self._t0
        if self._token is not None:
            try:
                _CTX.reset(self._token)
            except ValueError:
                # generator spans may enter and exit under different
                # context COPIES (each executor hop snapshots its own);
                # the copy dies with the task, so a failed reset leaks
                # nothing
                pass
        p = _publisher
        if p is not None and p.active:
            rec = {
                "time": time.time(),
                "type": self.trace_type,
                "name": self.name,
                "reqId": self.req_id,
                "spanId": self.span_id,
                "parentId": self.parent_id,
                "node": NODE,
                "durationNs": int(dur * 1e9),
                "error": "" if exc is None else f"{type(exc).__name__}: {exc}",
            }
            rec.update(self.fields)
            p.publish(rec)
        return False  # propagate exceptions


class _NoopSpan:
    """Shared do-nothing span for the no-subscribers path; identity is
    asserted by the zero-overhead test."""

    __slots__ = ()

    def __enter__(self) -> "_NoopSpan":
        return self

    def __exit__(self, exc_type, exc, tb) -> bool:
        return False

    def set(self, **fields) -> None:
        pass


NOOP_SPAN = _NoopSpan()


def span(trace_type: str, name: str, **fields):
    """A span of `trace_type` (one of TRACE_TYPES) for use in a ``with``
    statement. Returns NOOP_SPAN unless tracing is active."""
    p = _publisher
    if p is None or not p.active:
        return NOOP_SPAN
    return Span(trace_type, name, fields)
