"""ContextPool — a ThreadPoolExecutor that propagates contextvars.

``loop.run_in_executor`` and plain ``ThreadPoolExecutor.submit`` run the
callable in the worker's own (empty) context, which would drop the trace
request id (and any other contextvar, e.g. the QoS background marker for
code that submits from a background thread) at every thread hop. This
pool snapshots the submitter's context and runs the task inside it —
``contextvars.copy_context`` is an O(1) HAMT copy, so the idle-path cost
is negligible.
"""

from __future__ import annotations

import contextvars
from concurrent.futures import ThreadPoolExecutor


class ContextPool(ThreadPoolExecutor):
    def submit(self, fn, /, *args, **kwargs):
        ctx = contextvars.copy_context()
        return super().submit(ctx.run, fn, *args, **kwargs)
