"""Range-segment data cache: stripe-block granular tier + NVMe second tier.

The whole-object data cache (``core.DataCache``) only admits objects
below ``MINIO_TPU_CACHE_OBJECT_MAX`` — the checkpoint/training-shard
workload (ranged GETs over multi-GiB objects) paid the full
ns-lock + N-drive fan-out + decode path on every request. This module
caches those objects **per stripe block** (1 MiB, ``erasure/coder.py
BLOCK_SIZE``): cache keys are ``(set, bucket, object, versionId,
part#, block#)``, fills ride the existing bitrot-verified windowed read
path (a segment is admitted only after its stripe block decoded and
verified), and a ranged GET whose covering segments are all cached
short-circuits ``open_object`` entirely — no namespace lock, no
metadata fan-out, no shard I/O. Serving from cached verified segments
shrinks per-request GF/decode work the same way XOR-schedule program
optimization shrinks it on-chip (arXiv:2108.02692): survivor bytes are
never re-read or re-verified (the repair-bandwidth framing of
arXiv:1412.3022 applied to the serving path).

**Second tier**: a much larger disk/NVMe tier (``MINIO_TPU_CACHE_DISK_MB``
under ``MINIO_TPU_CACHE_DISK_DIR``). Memory-budget evictions demote the
coldest segments to disk files instead of dropping them; a disk hit
promotes the segment back into memory. Every segment carries a sha256
recorded at demote time and re-checked at promote time: a torn write,
bit flip, or injected fault quarantines the entry and the read falls
back to the erasure path — wrong bytes can never be served. The disk
tier sits behind the same two-touch admission policy and the same
``SetCache.invalidate_*`` choke point + grid broadcast coherence plane
as every other tier (a segment directory is stamped with the quorum
identity ``(mod_time, data_dir)`` and revalidates on epoch bumps).

Budget note: the memory side shares the process-wide
``MINIO_TPU_CACHE_MEM_MB`` budget with the whole-object tier; the disk
budget is per worker process (each SO_REUSEPORT worker keeps its own
subdirectory — segments are node-local state, like the memory tiers).
"""

from __future__ import annotations

import hashlib
import os
import tempfile
import threading
from collections import OrderedDict

from ..fault import registry as fault_registry
from .core import (
    TierStats,
    _bytes_add,
    _bytes_total,
    _int_env,
    _mem_budget,
    enabled,
)

__all__ = ["SegmentCache", "segment_cache", "segments_enabled"]


def segments_enabled() -> bool:
    return enabled() and os.environ.get("MINIO_TPU_CACHE_SEGMENTS", "1") != "0"


def disk_budget() -> int:
    """Disk-tier byte budget; 0 disables the tier."""
    return _int_env("MINIO_TPU_CACHE_DISK_MB", 0) << 20


def disk_dir() -> str:
    return os.environ.get("MINIO_TPU_CACHE_DISK_DIR", "")


def _block_size() -> int:
    from ..erasure.coder import BLOCK_SIZE

    return BLOCK_SIZE


def _admit_touches() -> int:
    return max(1, _int_env("MINIO_TPU_CACHE_ADMIT_TOUCHES", 2))


def _seg_digest(data) -> bytes:
    """Integrity stamp for demoted segment files: HighwayHash-256 (the
    same family as the bitrot plane, ~5x sha256 on this host) when the
    native plane is built, sha256 otherwise — the PURE-python
    HighwayHash fallback would cost more than the read it protects."""
    from .. import native

    if native.available():
        from ..ops.bitrot import fast_hash256

        return fast_hash256(data)
    return hashlib.sha256(data).digest()


def object_layout(fi) -> list[tuple[int, int, int, int]]:
    """(abs_offset, length, part#, block#) for every stripe block of the
    object, in byte order. Mirrors the windowed read path's plan
    (``coder.shard_sizes_for`` per part): full blocks are BLOCK_SIZE,
    each part's final block carries the remainder."""
    bs = _block_size()
    out: list[tuple[int, int, int, int]] = []
    pos = 0
    for part in fi.parts:
        full = part.size // bs
        for bi in range(full):
            out.append((pos + bi * bs, bs, part.number, bi))
        tail = part.size - full * bs
        if tail:
            out.append((pos + full * bs, tail, part.number, full))
        pos += part.size
    return out


class _Seg:
    """One cached stripe block: ``data`` (memory tier) and/or ``path`` +
    ``digest`` (disk tier) — a promoted segment keeps its verified file,
    so evicting it from memory again is free (no rewrite, no re-hash);
    dual residency counts against both budgets. ``dropped`` marks
    entries invalidated while off-lock I/O (demote write / promote read)
    was in flight, so the I/O's completion can discard instead of
    resurrect."""

    __slots__ = ("key", "size", "data", "path", "digest", "dropped")

    def __init__(self, key: tuple, size: int, data: bytes):
        self.key = key          # (dir_key, pnum, bi)
        self.size = size
        self.data = data
        self.path: str | None = None
        self.digest: bytes | None = None
        self.dropped = False


class _SegDir:
    """Per-object segment directory: the FileInfo the segments were read
    under (identity + layout source) and the live segment map."""

    __slots__ = ("fi", "stamp", "epoch", "t", "ref", "segs", "layout", "by_block")

    def __init__(self, fi, epoch: int, ref, monotonic: float):
        self.fi = fi
        self.stamp = (fi.mod_time, fi.data_dir)
        self.epoch = epoch
        self.t = monotonic
        self.ref = ref  # weakref to the owning ErasureSet (id-reuse guard)
        self.segs: dict[tuple[int, int], _Seg] = {}
        self.layout = object_layout(fi)
        self.by_block = {(p, b): (lo, ln) for lo, ln, p, b in self.layout}


class SegmentCache:
    """Process-wide range-segment cache (memory tier + optional disk
    tier). All bookkeeping is under ``_mu``; bulk I/O (demote writes,
    promote reads) happens OFF the lock with dropped-flag reconciliation
    so invalidations are never outraced by in-flight file I/O."""

    def __init__(self):
        self._mu = threading.Lock()
        self._dirs: dict[tuple, _SegDir] = {}
        # memory-tier LRU over segment keys; disk-tier LRU separate
        self._mem_lru: OrderedDict[tuple, _Seg] = OrderedDict()
        self._disk_lru: OrderedDict[tuple, _Seg] = OrderedDict()
        self._disk_bytes = 0
        self._touches: dict[tuple, tuple[int, float]] = {}
        self._dir_path: str | None = None
        self._dir_for: str | None = None  # configured root it was made under
        self._file_seq = 0
        self.stats = TierStats()
        # disk/prefetch-plane extras not covered by TierStats
        self.xstats = {
            "demotions": 0, "promotions": 0, "quarantined": 0,
            "disk_evictions": 0, "disk_hits": 0, "disk_write_errors": 0,
            "range_hits": 0, "range_misses": 0,
        }

    # -- disk-tier plumbing -------------------------------------------------

    def _disk_root_locked(self) -> str | None:
        """Lazily-created per-process spool directory, or None when the
        tier is disabled or the directory cannot be created."""
        if disk_budget() <= 0:
            return None
        root = disk_dir() or os.path.join(
            tempfile.gettempdir(), "minio-tpu-segcache"
        )
        # revalidate, don't just memoize: the configured root can change
        # (or be deleted) mid-process — tests, benches, operator re-config.
        # Stale entries pointing into a vanished dir fail their digest
        # read and quarantine; new demotions must land somewhere real.
        if (
            self._dir_path is not None
            and self._dir_for == root
            and os.path.isdir(self._dir_path)
        ):
            return self._dir_path
        # per-process subdirectory: SO_REUSEPORT workers share the knob
        # value but must never share segment files (each worker's tier is
        # invalidated by its own broadcast receiver)
        path = os.path.join(root, f"w{os.getpid()}")
        try:
            os.makedirs(path, exist_ok=True)
        except OSError:
            return None
        self._dir_path = path
        self._dir_for = root
        import atexit
        import shutil

        atexit.register(shutil.rmtree, path, ignore_errors=True)
        return path

    def _write_segment_file(self, root: str, seg: _Seg) -> str | None:
        """Demote write (OFF _mu): spool the segment's bytes; returns the
        path or None on failure. The chaos boundary injects here —
        a torn write leaves a short file that the promote-time digest
        check quarantines."""
        with self._mu:
            self._file_seq += 1
            name = f"{self._file_seq:012d}.seg"
        path = os.path.join(root, name)
        rule = fault_registry.check(
            "storage", "cache-disk", "write",
            modes=("error", "torn-write", "enospc", "latency"),
        )
        try:
            data = seg.data or b""
            if rule is not None:
                if rule.mode == "latency":
                    fault_registry.sleep_latency(rule)
                elif rule.mode == "torn-write":
                    with open(path, "wb") as fh:
                        fh.write(data[: len(data) // 2])
                    return path  # torn on disk: caught by the digest check
                else:  # error / enospc
                    raise OSError("injected cache-disk write fault")
            with open(path, "wb") as fh:
                fh.write(data)
            return path
        except OSError:
            try:
                os.unlink(path)
            except OSError:
                pass
            with self._mu:
                self.xstats["disk_write_errors"] += 1
            return None

    def _read_segment_file(self, seg: _Seg) -> bytes | None:
        """Promote read (OFF _mu) with integrity verification; any
        failure — I/O error, short file, digest mismatch, injected
        fault — quarantines the entry (the caller falls back to the
        erasure read path, so wrong bytes are structurally unservable)."""
        rule = fault_registry.check(
            "storage", "cache-disk", "read",
            modes=("error", "bitrot", "latency"),
        )
        try:
            if rule is not None:
                if rule.mode == "latency":
                    fault_registry.sleep_latency(rule)
                elif rule.mode == "error":
                    raise OSError("injected cache-disk read fault")
            with open(seg.path, "rb") as fh:  # type: ignore[arg-type]
                data = fh.read()
            if rule is not None and rule.mode == "bitrot" and data:
                buf = bytearray(data)
                buf[rule.rng.randrange(len(buf))] ^= 0xFF
                data = bytes(buf)
            if len(data) != seg.size or (
                seg.digest is not None and _seg_digest(data) != seg.digest
            ):
                self._quarantine(seg)
                return None
            return data
        except OSError:
            self._quarantine(seg)
            return None

    def _quarantine(self, seg: _Seg) -> None:
        """Drop a disk entry whose bytes can no longer be trusted."""
        with self._mu:
            if not seg.dropped:
                seg.dropped = True
                self.xstats["quarantined"] += 1
                self._disk_lru.pop(seg.key, None)
                if seg.path is not None:
                    self._disk_bytes -= seg.size
                d = self._dirs.get(seg.key[0])
                if d is not None:
                    d.segs.pop(seg.key[1:], None)
            path = seg.path
        fault_registry.emit(
            "cache.segment.quarantine", key=str(seg.key[0][1:]),
            block=str(seg.key[1:]),
        )
        self._unlink(path)

    @staticmethod
    def _unlink(path: str | None) -> None:
        if path:
            try:
                os.unlink(path)
            except OSError:
                pass

    # -- budget enforcement -------------------------------------------------

    def _evict_mem_locked(self) -> tuple[list[_Seg], list[str]]:
        """Pop memory-LRU tails past the shared byte budget; returns the
        victims for off-lock demotion (or dropping) plus orphaned disk
        paths to unlink. Dead-set directories reclaim first — nobody can
        invalidate them anymore."""
        budget = _mem_budget()
        if _bytes_total() <= budget:
            return [], []
        paths: list[str] = []
        for dk in [k for k, d in self._dirs.items() if d.ref() is None]:
            paths.extend(self._drop_dir_locked(dk))
        victims: list[_Seg] = []
        while self._mem_lru and _bytes_total() > budget:
            _, seg = self._mem_lru.popitem(last=False)
            _bytes_add(-seg.size)
            victims.append(seg)
        return victims, paths

    def demote(self, victims: list[_Seg], paths: list[str] = ()) -> None:
        """OFF every lock: write eviction victims to the disk tier
        (budget allowing) or drop them, and unlink orphaned files. A
        victim invalidated mid-write is unlinked, never resurrected.
        Callers that evict under SetCache._mu (``put`` via
        ``SetCache.segment_put``) hand the victims back out so multi-MiB
        disk writes never run under a cache-wide lock."""
        for p in paths:
            self._unlink(p)
        if not victims:
            return
        with self._mu:
            root = self._disk_root_locked()
        for seg in victims:
            with self._mu:
                if seg.path is not None and not seg.dropped:
                    # promoted earlier and the verified file was kept:
                    # demotion is free — just release the memory copy
                    seg.data = None
                    self._disk_lru.move_to_end(seg.key)
                    self.xstats["demotions"] += 1
                    continue
            path = None
            if root is not None and seg.size <= disk_budget():
                path = self._write_segment_file(root, seg)
            drop_path: str | None = None
            evict: list[str] = []
            with self._mu:
                if path is None or seg.dropped:
                    if not seg.dropped:
                        seg.dropped = True
                        d = self._dirs.get(seg.key[0])
                        if d is not None:
                            d.segs.pop(seg.key[1:], None)
                        self.stats.evictions += 1
                    drop_path = path
                else:
                    seg.digest = _seg_digest(seg.data or b"")
                    seg.path = path
                    seg.data = None
                    self._disk_lru[seg.key] = seg
                    self._disk_bytes += seg.size
                    self.xstats["demotions"] += 1
                    evict = self._evict_disk_locked()
            self._unlink(drop_path)
            for ev in evict:
                self._unlink(ev)

    def _evict_disk_locked(self) -> list[str]:
        """Disk-LRU tails past the disk budget; returns paths to unlink
        off-lock."""
        out: list[str] = []
        budget = disk_budget()
        while self._disk_lru and self._disk_bytes > budget:
            _, seg = self._disk_lru.popitem(last=False)
            self._disk_bytes -= seg.size
            if seg.path:
                out.append(seg.path)
            seg.path = None
            seg.digest = None
            self.xstats["disk_evictions"] += 1
            if seg.data is None:
                # no memory copy either: the segment is gone entirely
                seg.dropped = True
                d = self._dirs.get(seg.key[0])
                if d is not None:
                    d.segs.pop(seg.key[1:], None)
        return out

    def shed_to_budget(self) -> None:
        """Evict this tier's coldest memory segments until the SHARED
        byte budget fits again. Called by the whole-object tier when a
        fill finds the budget blown: segments overflow to the NVMe tier
        instead of the data cache evicting itself to zero. The
        accounting happens inline (the caller needs the room NOW); the
        demotion's file I/O runs on a helper thread — this path can be
        reached under SetCache._mu via data_put, which must never wait
        on disk writes."""
        with self._mu:
            victims, paths = self._evict_mem_locked()
        if victims or paths:
            _demote_pool().submit(self.demote, victims, paths)

    # -- admission ----------------------------------------------------------

    def admit(self, dir_key: tuple, monotonic: float) -> bool:
        """Two-touch admission per OBJECT (not per segment): a ranged
        object earns segment residency by being read twice, so a one-pass
        sequential scan cannot flush the tier; once admitted, every
        segment of the stream fills."""
        need = _admit_touches()
        if need <= 1:
            return True
        with self._mu:
            if dir_key in self._dirs:
                return True  # already resident: later fills extend it
            n, _ = self._touches.get(dir_key, (0, monotonic))
            n += 1
            self._touches[dir_key] = (n, monotonic)
            if len(self._touches) > 4096:
                for old in sorted(
                    self._touches, key=lambda x: self._touches[x][1]
                )[:1024]:
                    del self._touches[old]
        return n >= need

    # -- fills ---------------------------------------------------------------

    def put(self, es, bucket: str, obj: str, vid: str, fi, pnum: int,
            bi: int, data, epoch: int,
            monotonic: float) -> tuple[list[_Seg], list[str]]:
        """Insert one verified stripe block. ``data`` may be longer than
        the block's logical length (decode padding) — it is trimmed; a
        SHORT payload is rejected (partial block from a ranged native
        span). Caller (SetCache.segment_put) holds the invalidation-token
        check under ITS lock, so this method only stores — it returns any
        eviction victims + orphan paths for the caller to ``demote()``
        after releasing that lock (disk writes must not run under
        SetCache._mu)."""
        import weakref

        dk = (id(es), bucket, obj, vid)
        orphans: list[str] = []
        with self._mu:
            d = self._dirs.get(dk)
            if d is not None and (
                d.ref() is not es or d.stamp != (fi.mod_time, fi.data_dir)
            ):
                orphans = self._drop_dir_locked(dk)
                d = None
            if d is None:
                d = self._dirs[dk] = _SegDir(
                    fi, epoch, weakref.ref(es), monotonic
                )
            want = d.by_block.get((pnum, bi))
            if want is None:
                return [], orphans
            length = want[1]
            if len(data) < length:
                self.stats.rejected += 1
                return [], orphans
            if d.segs.get((pnum, bi)) is not None:
                return [], orphans  # already cached (racing fills)
            # admission snapshot: the cache owns its copy (the serving
            # plane may hand us a view of a buffer it keeps reusing) —
            # one counted copy via memoryview, never slice-then-bytes
            from ..erasure import bufpool

            bufpool.count_copy("cache-fill")
            seg = _Seg((dk, pnum, bi), length, bytes(memoryview(data)[:length]))
            d.segs[(pnum, bi)] = seg
            self._mem_lru[seg.key] = seg
            _bytes_add(length)
            self.stats.fills += 1
            victims, paths = self._evict_mem_locked()
        return victims, orphans + paths

    # -- lookups -------------------------------------------------------------

    def directory(self, es, bucket: str, obj: str, vid: str) -> _SegDir | None:
        """The object's segment directory when it belongs to this live
        set (weakref id-reuse guard) — freshness is judged by the caller
        (SetCache owns epoch/TTL policy)."""
        dk = (id(es), bucket, obj, vid)
        with self._mu:
            d = self._dirs.get(dk)
            if d is None or d.ref() is not es:
                return None
            return d

    def restamp(self, d: _SegDir, epoch: int, monotonic: float) -> None:
        with self._mu:
            d.epoch = epoch
            d.t = monotonic
            self.stats.revalidations += 1

    def covering(self, d: _SegDir, start: int, length: int):
        """The (abs_offset, length, part#, block#) rows covering
        [start, start+length), or None when the range is out of bounds."""
        import bisect

        if length <= 0 or start < 0 or start + length > d.fi.size:
            return None
        starts = [row[0] for row in d.layout]
        lo_i = bisect.bisect_right(starts, start) - 1
        hi_i = bisect.bisect_left(starts, start + length)
        return d.layout[max(lo_i, 0):hi_i]

    def read_range(self, d: _SegDir, start: int, length: int):
        """[(abs_offset, bytes)] covering the range when EVERY covering
        segment is resident (promoting disk entries back to memory on the
        way), else None — the caller falls back to the erasure path.
        Promotion failures (torn file, bitrot, injected fault) quarantine
        and miss; they can never surface wrong bytes."""
        rows = self.covering(d, start, length)
        if rows is None:
            return None
        found: list[tuple[int, _Seg, bytes | None]] = []
        with self._mu:
            for lo, ln, pnum, bi in rows:
                seg = d.segs.get((pnum, bi))
                if seg is None or seg.dropped:
                    self.stats.misses += 1
                    self.xstats["range_misses"] += 1
                    return None
                if seg.data is not None and seg.key in self._mem_lru:
                    # membership-checked: an eviction may have popped the
                    # key while seg.data awaits the off-lock demote write
                    self._mem_lru.move_to_end(seg.key, last=True)
                found.append((lo, seg, seg.data))
        # disk entries read + verify OFF the lock, then promote
        promoted: dict[tuple, bytes] = {}
        need_disk = [seg for _, seg, data in found if data is None]
        for seg in need_disk:
            data = self._read_segment_file(seg)
            if data is None:
                with self._mu:
                    self.stats.misses += 1
                    self.xstats["range_misses"] += 1
                return None
            promoted[seg.key] = data
        if need_disk:
            with self._mu:
                if any(seg.dropped for seg in need_disk):
                    # invalidated while reading: the bytes may predate an
                    # overwrite — do not serve, do not resurrect
                    self.stats.misses += 1
                    self.xstats["range_misses"] += 1
                    return None
                for seg in need_disk:
                    self.xstats["disk_hits"] += 1
                    if seg.data is not None:
                        # a concurrent reader promoted it while we were
                        # reading the file: it already occupies the
                        # budget exactly once — re-adding would leak
                        # phantom bytes into the shared counter forever
                        continue
                    # keep the verified file + digest: the next memory
                    # eviction of this segment demotes without a rewrite
                    seg.data = promoted[seg.key]
                    _bytes_add(seg.size)
                    self._mem_lru[seg.key] = seg
                    if seg.key in self._disk_lru:
                        self._disk_lru.move_to_end(seg.key)
                    self.xstats["promotions"] += 1
                victims, orphans = self._evict_mem_locked()
            self.demote(victims, orphans)
        with self._mu:
            self.stats.hits += len(rows)
            self.xstats["range_hits"] += 1
        return [
            (lo, data if data is not None else promoted[seg.key])
            for lo, seg, data in found
        ]

    def coverage(self, d: _SegDir, start: int, length: int) -> int:
        """How many leading bytes of [start, start+length) are already
        resident — the prefetcher trims its read to the uncovered tail."""
        rows = self.covering(d, start, length)
        if not rows:
            return 0
        covered = 0
        with self._mu:
            for lo, ln, pnum, bi in rows:
                seg = d.segs.get((pnum, bi))
                if seg is None or seg.dropped:
                    break
                covered = min(lo + ln, start + length) - start
        return max(covered, 0)

    # -- removal (called ONLY from the SetCache choke points) ----------------

    def _drop_dir_locked(self, dk: tuple) -> list[str]:
        d = self._dirs.pop(dk, None)
        self._touches.pop(dk, None)
        if d is None:
            return []
        paths: list[str] = []
        for seg in d.segs.values():
            seg.dropped = True
            # a segment may be resident in BOTH tiers (promoted with its
            # file kept): release each side it holds
            if seg.data is not None:
                _bytes_add(-seg.size)
                self._mem_lru.pop(seg.key, None)
            if seg.path is not None:
                self._disk_bytes -= seg.size
                self._disk_lru.pop(seg.key, None)
                paths.append(seg.path)
            self.stats.invalidations += 1
        d.segs.clear()
        return paths

    def drop_where(self, pred) -> int:
        """Invalidate every object directory whose key matches ``pred``
        (same contract as DataCache.drop_where; key = (id(es), bucket,
        obj, vid)). Disk files unlink off-lock."""
        with self._mu:
            victims = [k for k in self._dirs if pred(k)]
            paths: list[str] = []
            for k in victims:
                paths.extend(self._drop_dir_locked(k))
        for p in paths:
            self._unlink(p)
        return len(victims)

    # -- observability --------------------------------------------------------

    def snapshot(self) -> dict:
        with self._mu:
            mem_bytes = sum(s.size for s in self._mem_lru.values())
            return {
                **self.stats.snapshot(),
                **self.xstats,
                "objects": len(self._dirs),
                "entries": len(self._mem_lru) + len(self._disk_lru),
                "mem_entries": len(self._mem_lru),
                "mem_bytes": mem_bytes,
                "disk_entries": len(self._disk_lru),
                "disk_bytes": self._disk_bytes,
                "disk_budget": disk_budget(),
                "disk_dir": self._dir_path or "",
            }


_SEG = SegmentCache()

_DEMOTE_POOL = None
_DEMOTE_POOL_MU = threading.Lock()


def _demote_pool():
    """Single helper thread for off-critical-path demotion writes."""
    global _DEMOTE_POOL
    if _DEMOTE_POOL is None:
        from concurrent.futures import ThreadPoolExecutor

        with _DEMOTE_POOL_MU:
            if _DEMOTE_POOL is None:
                _DEMOTE_POOL = ThreadPoolExecutor(
                    max_workers=1, thread_name_prefix="segcache-demote"
                )
    return _DEMOTE_POOL


def segment_cache() -> SegmentCache:
    return _SEG
