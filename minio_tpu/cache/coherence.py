"""Cross-node cache coherence: invalidation broadcasts over the grid.

Every mutation that flows through the cache choke point
(``SetCache.invalidate_object``) is replayed to every peer as a small
grid RPC (``cache.invalidate`` on the muxed storage-plane websocket,
cluster/grid.py). The broadcast is **synchronous with the mutation**:
``put_object``/``delete_object`` return only after peers were told (or
the short per-peer deadline passed), so a client that saw its PUT
succeed never reads the old version from another node's cache.

Loss handling rides a per-sender **generation counter**: every broadcast
carries ``gen = n``; a receiver that observes a gap (``gen != last+1``)
knows at least one invalidation never arrived and bumps the **epoch** on
every local SetCache. Epoch-bumped entries are not dropped — they must
revalidate (one single-drive modTime check, ``core.SetCache``) before
their next serve. Between the loss and the gap detection, distributed
deployments additionally re-check entries older than
``MINIO_TPU_CACHE_REVALIDATE_S`` (default 1 s), so the worst case for a
lost broadcast is a short revalidate window, never an unbounded stale
serve. Single-node deployments skip all of this: the choke point is
authoritative and broadcasts are no-ops.
"""

from __future__ import annotations

import threading
import uuid
import weakref

import msgpack

HANDLER = "cache.invalidate"
BROADCAST_TIMEOUT_S = 2.0
# loopback SO_REUSEPORT worker peers answer in microseconds or are dead
# (crashed, supervisor restarting them); a worker outage must not cost
# every mutation the full cross-node deadline — the gen-gap epoch bump
# revalidates whatever the restarted worker missed anyway
WORKER_BROADCAST_TIMEOUT_S = 0.5
# how long a missing generation may stay missing before it is declared
# lost: concurrent broadcasts are sent on racing threads, so short
# reorder windows are NORMAL delivery, not loss
GAP_GRACE_S = 5.0

NODE_ID = uuid.uuid4().hex[:12]

_mu = threading.Lock()
_store_ref: "weakref.ref | None" = None
_peers: list[str] = []
_worker_peers: set[str] = set()  # subset of _peers: loopback pool siblings
_token = ""
_gen = 0
_last_seen: dict[str, int] = {}
_holes: dict[str, dict[int, float]] = {}  # node -> {missing gen: deadline}
_stats = {"sent": 0, "send_errors": 0, "received": 0, "gen_gaps": 0}


def attach(store) -> None:
    """Bind the node's serving object layer (called from set_store):
    received invalidations apply to THIS store's set caches."""
    global _store_ref
    with _mu:
        _store_ref = weakref.ref(store)


def configure(peers: list[str], token: str,
              worker_peers: list[str] | None = None) -> None:
    """Arm broadcasting towards cluster peers (called from server main).
    ``worker_peers`` names the subset that are loopback SO_REUSEPORT
    pool siblings — same invalidation protocol, tighter deadline."""
    global _peers, _token, _worker_peers
    with _mu:
        _peers = list(peers)
        _worker_peers = set(worker_peers or ())
        _token = token


def is_distributed() -> bool:
    return bool(_peers)


def stats() -> dict:
    with _mu:
        return dict(_stats, peers=len(_peers),
                    workerPeers=len(_worker_peers), lastGen=_gen)


def register_grid(grid) -> None:
    """Register the receive side on the node's GridServer. inline=True:
    the handler only touches in-memory dicts — it must never queue behind
    disk-bound executor work."""
    grid.register_single(HANDLER, _handle, inline=True)


def broadcast_invalidate(pool_idx: int, set_idx: int, bucket: str,
                         obj: str, kind: str = "obj") -> None:
    """Tell every peer to drop (bucket, obj) — or every key under the
    prefix, or the whole bucket (``kind``: obj|prefix|bucket) — from the
    addressed set's caches. Parallel across peers, bounded per-peer
    deadline; a dead peer costs one short timeout, is counted, and heals
    via the generation-gap epoch bump on its next received broadcast."""
    global _gen
    with _mu:
        peers, token = list(_peers), _token
        if not peers:
            return
        _gen += 1
        payload = msgpack.packb(
            [NODE_ID, _gen, pool_idx, set_idx, bucket, obj, kind]
        )

    from ..cluster.grid import shared_client

    worker_peers = _worker_peers

    def one(peer: str) -> None:
        host, _, port = peer.rpartition(":")
        deadline = (
            WORKER_BROADCAST_TIMEOUT_S if peer in worker_peers
            else BROADCAST_TIMEOUT_S
        )
        try:
            shared_client(host, int(port), token, "storage").call(
                HANDLER, payload, timeout=deadline, retry=True
            )
            with _mu:
                _stats["sent"] += 1
        except Exception:  # noqa: BLE001 — gap detection covers the loss
            with _mu:
                _stats["send_errors"] += 1

    if len(peers) == 1:
        one(peers[0])
        return
    threads = [
        threading.Thread(target=one, args=(p,), daemon=True) for p in peers
    ]
    for t in threads:
        t.start()
    for t in threads:
        t.join(BROADCAST_TIMEOUT_S * 1.5)


def _handle(payload: bytes) -> bytes:
    """Receive side: drop the key locally (no re-broadcast) and track the
    sender's generation sequence; a gap bumps every set cache's epoch."""
    node, gen, pool_idx, set_idx, bucket, obj, kind = msgpack.unpackb(
        payload, raw=False
    )
    import time as _time

    now = _time.monotonic()
    with _mu:
        _stats["received"] += 1
        last = _last_seen.get(node, 0)
        holes = _holes.setdefault(node, {})
        # a skipped generation becomes a HOLE with a grace deadline, not
        # an instant loss: concurrent broadcasts are assigned gens under
        # the sender's lock but sent on racing threads, so both
        # reorder-behind (gen <= last) and reorder-ahead (a later gen
        # arriving first) are normal delivery. Only a hole that outlives
        # the grace is a genuinely lost invalidation — that bumps the
        # epoch so pre-gap entries revalidate before serving.
        if gen > last:
            if last > 0:
                for h in range(last + 1, gen):
                    holes[h] = now + GAP_GRACE_S
            _last_seen[node] = gen
        else:
            holes.pop(gen, None)  # reordered delivery filled its hole
        expired = [h for h, dl in holes.items() if now >= dl]
        for h in expired:
            del holes[h]
        if len(holes) > 1024:  # runaway loss: treat the overflow as one
            holes.clear()
            expired.append(-1)
        gap = bool(expired)
        if gap:
            _stats["gen_gaps"] += 1
        store = _store_ref() if _store_ref is not None else None
    if store is None:
        return b""
    from .core import store_caches

    if gap:
        for c in store_caches(store):
            c.bump_epoch()
    for p in getattr(store, "pools", [store]):
        if getattr(p, "pool_index", 0) != pool_idx:
            continue
        for s in getattr(p, "sets", [p]):
            if getattr(s, "set_index", 0) != set_idx:
                continue
            cache = getattr(s, "cache", None)
            if cache is not None:
                if kind == "prefix":
                    cache.invalidate_prefix(bucket, obj, broadcast=False)
                elif kind == "bucket":
                    cache.invalidate_bucket(bucket, broadcast=False)
                else:
                    cache.invalidate_object(bucket, obj, broadcast=False)
    return b""
