"""minio_tpu.cache — the quorum-coherent caching layer.

Three tiers over the GET/HEAD hot path (see docs/CACHING.md):

- **FileInfo cache** (``core.SetCache``, one per erasure set): hot
  GET/HEAD/get_object_info skip the N-drive ``read_version`` fan-out;
  concurrent misses singleflight into one quorum read.
- **Hot-object data cache** (``core.DataCache``, process-wide byte
  budget): repeat GETs of small/hot objects are served from memory with
  etag/bitrot identity preserved.
- **Range-segment cache** (``segment.SegmentCache``, process-wide):
  objects ABOVE the whole-object size gate cache per 1 MiB stripe
  block; a ranged GET whose covering segments are resident skips
  ``open_object`` entirely. Memory evictions demote to a larger
  disk/NVMe tier (``MINIO_TPU_CACHE_DISK_MB``) with digest-verified
  promotion; sequential runs read ahead (``prefetch``) on the QoS
  background lane.
- **Listing metacache** (``erasure/listing.py``): repeated
  ``list_objects`` scans reuse recent prefix walks.

Coherence is write-through via ONE choke point
(``SetCache.invalidate_object`` — enforced by the miniovet
``cache-discipline`` rule) plus cross-node grid broadcasts with
generation-gap epoch bumps (``coherence``): a lost invalidation can only
cause a revalidate, never a stale serve.
"""

from .core import (  # noqa: F401
    SetCache,
    aggregate_stats,
    clear_store,
    data_cache,
    enabled,
    object_max,
    store_caches,
)
from . import coherence  # noqa: F401
from .segment import segment_cache, segments_enabled  # noqa: F401
