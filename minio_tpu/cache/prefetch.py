"""Sequential read-ahead for the range-segment cache.

A checkpoint/training-shard reader walks a multi-GiB object in
contiguous ranged GETs. The segment tier turns the *second* pass over a
range into memory hits; this module removes the first-pass miss for
everything after the detected run start: every ranged open
(``SetCache.segment_observe`` — the obs span layer already carries these
request ranges; this is the same signal at the same choke point) feeds a
per-(set, bucket, object, version) run detector, and once
``MINIO_TPU_CACHE_PREFETCH_MIN_RUN`` consecutive forward-contiguous
reads are seen, the next ``MINIO_TPU_CACHE_PREFETCH_SEGMENTS`` stripe
blocks are read through the normal bitrot-verified erasure path on a
dedicated single background worker — under ``qos.background_context()``
+ ``qos.prefetch_context()``, so any dispatcher work rides the
background lane (leftover batch capacity only; the
``fg_deferred_behind_bg`` guard metric stays flat) and the shared read
pool sees at most one prefetch stream at a time.

Prefetched bytes enter the cache through the same admission + token
path as foreground fills (by the time a run is detected the object has
the two touches admission wants), so coherence is unchanged: an
overwrite racing a prefetch rejects the fill via the invalidation
token, exactly as it would a foreground fill.
"""

from __future__ import annotations

import threading
import weakref
from concurrent.futures import ThreadPoolExecutor

from .. import obs
from .core import _int_env

__all__ = ["observe", "stats", "reset", "drain_for_tests"]


def prefetch_segments() -> int:
    """How many stripe blocks to read ahead; 0 disables prefetch."""
    return max(0, _int_env("MINIO_TPU_CACHE_PREFETCH_SEGMENTS", 4))


def _min_run() -> int:
    return max(2, _int_env("MINIO_TPU_CACHE_PREFETCH_MIN_RUN", 2))


_mu = threading.Lock()
# key (id(es), bucket, obj, vid) -> [last_end, run_len, prefetched_until]
_table: dict[tuple, list[int]] = {}
_inflight: set[tuple] = set()
_pool: ThreadPoolExecutor | None = None
_stats = {
    "observed": 0, "runs_detected": 0, "scheduled": 0,
    "skipped_inflight": 0, "completed": 0, "already_resident": 0,
    "errors": 0, "bytes_read": 0,
}


def _worker_pool() -> ThreadPoolExecutor:
    global _pool
    if _pool is None:
        # one worker: at most one prefetch stream competes for the shared
        # shard-read pool, and queued prefetches collapse via _inflight
        _pool = ThreadPoolExecutor(
            max_workers=1, thread_name_prefix="cache-prefetch"
        )
    return _pool


def stats() -> dict:
    with _mu:
        return dict(_stats, tracked=len(_table), inflight=len(_inflight))


def reset() -> None:
    """Test hook: forget every tracked run (stats survive)."""
    with _mu:
        _table.clear()


def drain_for_tests(timeout: float = 10.0) -> None:
    """Block until the queued prefetch work has run (tests only)."""
    ev = threading.Event()
    _worker_pool().submit(ev.set)
    ev.wait(timeout)


def observe(es, bucket: str, obj: str, vid: str, start: int,
            length: int) -> None:
    """One observed ranged read. Contiguous-forward extends the run;
    anything else restarts it. Crossing the min-run threshold schedules
    a read of the next K stripe blocks (skipping what is already
    resident and whatever an earlier prefetch already covered)."""
    from ..qos.context import in_prefetch
    from .segment import _block_size, segments_enabled

    if in_prefetch():
        return  # our own read-ahead must never extend the run it serves
    k = prefetch_segments()
    if k <= 0 or length <= 0 or not segments_enabled():
        return
    bs = _block_size()
    key = (id(es), bucket, obj, vid)
    end = start + length
    with _mu:
        _stats["observed"] += 1
        ent = _table.get(key)
        if ent is not None and 0 <= start - ent[0] <= bs:
            ent[0] = end
            ent[1] += 1
        else:
            ent = _table[key] = [end, 1, 0]
        if len(_table) > 2048:  # bounded: drop an arbitrary cold entry
            _table.pop(next(iter(_table)))
        if ent[1] < _min_run():
            return
        if ent[1] == _min_run():
            _stats["runs_detected"] += 1
        # read-ahead window: the K blocks after the observed end, block
        # aligned so fills are whole stripe blocks
        pf_start = (end // bs) * bs
        pf_end = pf_start + k * bs
        if pf_end <= ent[2]:
            return  # an earlier prefetch already covers this window
        ent[2] = pf_end
        if key in _inflight:
            _stats["skipped_inflight"] += 1
            return
        _inflight.add(key)
        _stats["scheduled"] += 1
    _worker_pool().submit(
        _prefetch, weakref.ref(es), bucket, obj, vid, pf_start,
        pf_end - pf_start, key,
    )


def _prefetch(es_ref, bucket: str, obj: str, vid: str, offset: int,
              length: int, key: tuple) -> None:
    """Worker body: read [offset, offset+length) through the normal
    erasure path with segment fills armed, discarding the bytes. Runs
    under the QoS background + prefetch contexts so it can never
    compete with foreground traffic for batch capacity."""
    from ..qos.context import background_context, prefetch_context
    from . import segment as segmod

    try:
        es = es_ref()
        if es is None:
            return
        with background_context(), prefetch_context():
            sc = segmod.segment_cache()
            d = sc.directory(es, bucket, obj, vid)
            if d is not None:
                covered = sc.coverage(d, offset, length)
                offset += covered
                length -= covered
                if length <= 0 or offset >= d.fi.size:
                    with _mu:
                        _stats["already_resident"] += 1
                    return
            with obs.span(
                obs.TYPE_INTERNAL, "cache.prefetch",
                bucket=bucket, object=obj, offset=offset, bytes=length,
            ):
                oi, h = es.open_object(bucket, obj, vid)
                try:
                    if offset >= oi.size:
                        with _mu:
                            _stats["already_resident"] += 1
                        return
                    length = min(length, oi.size - offset)
                    n = 0
                    for chunk in h.read(offset, length,
                                        close_when_done=False):
                        n += len(chunk)
                    with _mu:
                        _stats["bytes_read"] += n
                finally:
                    h.close()
        with _mu:
            _stats["completed"] += 1
    # miniovet: ignore[error-taint] -- prefetch is speculative background
    # work on the QoS bg lane: a failed read-ahead must never surface to
    # any request; failures are counted into the prefetch error series
    except Exception:  # noqa: BLE001 — read-ahead is best-effort
        with _mu:
            _stats["errors"] += 1
    finally:
        with _mu:
            _inflight.discard(key)
