"""Quorum-coherent caching core: FileInfo cache + hot-object data cache.

The GET/HEAD hot path pays two structural costs per request even for an
object read a thousand times a second: a full ``read_version`` fan-out
across all N drives to find the quorum FileInfo, and fresh per-shard
reads of the same bytes. With the coding path already device-accelerated
(PERF.md: 41.76 GiB/s fused encode+hash), this per-request I/O
orchestration is the wall — the same observation arXiv:2108.02692 makes
for CPU erasure coding. This module removes both costs for hot objects:

- **FileInfo cache** (one per ``ErasureSet``): LRU keyed by
  ``(bucket, object, version_id)`` holding the quorum-picked FileInfo
  plus the per-drive metadata list the read path needs, stamped with the
  quorum identity ``(mod_time, data_dir)``. Concurrent misses on one key
  collapse into a single quorum read (**singleflight**).
- **Hot-object data cache** (process-wide, global byte budget): whole
  objects below ``MINIO_TPU_CACHE_OBJECT_MAX`` admitted after
  ``MINIO_TPU_CACHE_ADMIT_TOUCHES`` distinct reads (inline-data objects
  immediately — their bytes were memory-resident anyway), served with
  etag/bitrot identity preserved (bytes enter the cache only after the
  erasure layer's bitrot verification, and leave stamped with the same
  FileInfo/etag they were read under).

Coherence is write-through: every local mutation funnels through ONE
choke-point API (``SetCache.invalidate_object`` /
``invalidate_bucket``) — the ``cache-discipline`` miniovet rule rejects
any other mutation of cache state from erasure/server code. Cross-node,
the choke point broadcasts over the grid (``cache/coherence.py``) with a
per-sender generation counter; a receiver that observes a sequence gap
bumps its **epoch**, after which every pre-gap entry must revalidate on
next hit — a cheap single-drive modTime check — before being served. A
lost invalidation therefore costs a revalidate, never a stale serve.
"""

from __future__ import annotations

import os
import threading
import time
import weakref
from collections import OrderedDict
from concurrent.futures import Future

from .. import obs
from ..storage.errors import StorageError

__all__ = [
    "SetCache",
    "enabled",
    "object_max",
    "data_cache",
    "aggregate_stats",
    "clear_store",
    "store_caches",
]


def enabled() -> bool:
    return os.environ.get("MINIO_TPU_CACHE", "1") != "0"


def _int_env(name: str, default: int) -> int:
    try:
        return int(os.environ.get(name, "") or default)
    except ValueError:
        return default


def object_max() -> int:
    return _int_env("MINIO_TPU_CACHE_OBJECT_MAX", 2 << 20)


def _mem_budget() -> int:
    return _int_env("MINIO_TPU_CACHE_MEM_MB", 256) << 20


def _fileinfo_entries() -> int:
    return _int_env("MINIO_TPU_CACHE_FILEINFO_ENTRIES", 4096)


def _admit_touches() -> int:
    return max(1, _int_env("MINIO_TPU_CACHE_ADMIT_TOUCHES", 2))


def _revalidate_ttl() -> float:
    try:
        return float(os.environ.get("MINIO_TPU_CACHE_REVALIDATE_S", "1"))
    except ValueError:
        return 1.0


# Global memory accounting shared by every cache tier in the process: the
# byte budget is deployment-wide, not per-set (a 32-set pool must not mean
# 32x the configured memory).
_BYTES_LOCK = threading.Lock()
_BYTES_TOTAL = 0


def _bytes_add(n: int) -> None:
    global _BYTES_TOTAL
    with _BYTES_LOCK:
        _BYTES_TOTAL += n


def _bytes_total() -> int:
    return _BYTES_TOTAL


class TierStats:
    """Counters for one cache tier; snapshot() is lock-free-read safe
    (int reads are atomic under the GIL; metrics tolerate torn windows)."""

    __slots__ = (
        "hits", "misses", "evictions", "invalidations", "revalidations",
        "singleflight_shared", "fills", "rejected",
    )

    def __init__(self):
        self.hits = 0
        self.misses = 0
        self.evictions = 0
        self.invalidations = 0
        self.revalidations = 0
        self.singleflight_shared = 0
        self.fills = 0
        self.rejected = 0

    def snapshot(self) -> dict:
        return {k: getattr(self, k) for k in self.__slots__}


class _FiEntry:
    __slots__ = ("fi", "metas", "epoch", "stamp", "t", "bytes")

    def __init__(self, fi, metas, epoch: int, nbytes: int):
        self.fi = fi
        self.metas = metas
        self.epoch = epoch
        self.stamp = (fi.mod_time, fi.data_dir)
        self.t = time.monotonic()
        self.bytes = nbytes


class _DataEntry:
    __slots__ = ("fi", "data", "epoch", "stamp", "t", "ref")

    def __init__(self, fi, data: bytes, epoch: int, ref):
        self.fi = fi
        self.data = data
        self.epoch = epoch
        self.stamp = (fi.mod_time, fi.data_dir)
        self.t = time.monotonic()
        self.ref = ref  # weakref to the owning ErasureSet (id-reuse guard)


class DataCache:
    """Process-wide hot-object cache. Keys carry the owning set's identity
    (id + weakref guard, like the listing metacache) so two stores in one
    process — in-process site pairs, test rigs — never share bytes."""

    def __init__(self):
        self._mu = threading.Lock()
        self._lru: OrderedDict[tuple, _DataEntry] = OrderedDict()
        # admission ledger: key -> (touches, last-touch time)
        self._touches: dict[tuple, tuple[int, float]] = {}
        self.stats = TierStats()

    def _key(self, es, bucket: str, obj: str, vid: str) -> tuple:
        return (id(es), bucket, obj, vid)

    def get(self, es, bucket: str, obj: str, vid: str) -> _DataEntry | None:
        k = self._key(es, bucket, obj, vid)
        with self._mu:
            ent = self._lru.get(k)
            # per-entry weakref guard: CPython may recycle id(es) for a
            # NEW ErasureSet after the old one is collected — its entries
            # must never serve another store's bytes
            if ent is None or ent.ref() is not es:
                self.stats.misses += 1
                return None
            self._lru.move_to_end(k)
        return ent  # epoch/revalidation judged by the caller (SetCache)

    def admit(self, es, bucket: str, obj: str, vid: str, inline: bool) -> bool:
        """Admission policy: objects earn residency by being re-read
        (two-touch by default) so a one-pass scan cannot flush the hot
        set; inline objects admit immediately."""
        need = 1 if inline else _admit_touches()
        if need <= 1:
            return True
        k = self._key(es, bucket, obj, vid)
        now = time.monotonic()
        with self._mu:
            n, _ = self._touches.get(k, (0, now))
            n += 1
            self._touches[k] = (n, now)
            if len(self._touches) > 4096:  # bounded ledger, oldest first
                for old in sorted(self._touches, key=lambda x: self._touches[x][1])[:1024]:
                    del self._touches[old]
        return n >= need

    def put(self, es, bucket: str, obj: str, vid: str, fi, data: bytes,
            epoch: int) -> None:
        if len(data) > object_max():
            self.stats.rejected += 1
            return
        k = self._key(es, bucket, obj, vid)
        budget = _mem_budget()
        with self._mu:
            old = self._lru.pop(k, None)
            if old is not None:
                _bytes_add(-len(old.data))
            self._lru[k] = _DataEntry(fi, data, epoch, weakref.ref(es))
            _bytes_add(len(data))
            self.stats.fills += 1
            if _bytes_total() > budget:
                # dead sets' entries can no longer be invalidated by
                # anyone — reclaim them before touching live entries
                for dk in [
                    k2 for k2, e in self._lru.items() if e.ref() is None
                ]:
                    _bytes_add(-len(self._lru.pop(dk).data))
                    self.stats.evictions += 1
        if _bytes_total() > budget:
            # the budget is shared with the segment tier, which has an
            # NVMe tier to overflow into — shed its cold segments first
            # (OUTSIDE _mu: demotion does disk I/O), or a warm segment
            # tier would starve this one to zero instead of spilling
            from . import segment as segmod

            segmod.segment_cache().shed_to_budget()
        with self._mu:
            while self._lru and _bytes_total() > budget:
                _, ev = self._lru.popitem(last=False)
                _bytes_add(-len(ev.data))
                self.stats.evictions += 1

    def touch_hit(self) -> None:
        with self._mu:
            self.stats.hits += 1

    def count_miss(self) -> None:
        # counters are bumped from every executor-pool reader thread:
        # += outside _mu is a lost update (miniovet races pass)
        with self._mu:
            self.stats.misses += 1

    def restamp(self, ent: _DataEntry, epoch: int) -> None:
        """Re-certify an entry after revalidation: the epoch/time stamps
        are written under _mu — two concurrent readers revalidating the
        same hot entry would otherwise interleave the pair."""
        with self._mu:
            ent.epoch = epoch
            ent.t = time.monotonic()
            self.stats.revalidations += 1

    def drop(self, k: tuple) -> None:
        """Internal removal (caller: SetCache choke point)."""
        with self._mu:
            ent = self._lru.pop(k, None)
            self._touches.pop(k, None)
            if ent is not None:
                _bytes_add(-len(ent.data))
                self.stats.invalidations += 1

    def drop_where(self, pred) -> int:
        with self._mu:
            victims = [k for k in self._lru if pred(k)]
            for k in victims:
                _bytes_add(-len(self._lru.pop(k).data))
                self._touches.pop(k, None)
            self.stats.invalidations += len(victims)
        return len(victims)

    def entry_count(self) -> int:
        return len(self._lru)

    def byte_count(self) -> int:
        with self._mu:
            return sum(len(e.data) for e in self._lru.values())


_DATA = DataCache()


def data_cache() -> DataCache:
    return _DATA


class SetCache:
    """Per-ErasureSet cache facade: the FileInfo tier lives here; the data
    tier delegates to the process-wide ``DataCache``; listing entries live
    in ``erasure/listing.py`` but invalidate through this choke point."""

    def __init__(self, es):
        self._es = weakref.ref(es)
        self._mu = threading.Lock()
        self._fi: OrderedDict[tuple, _FiEntry] = OrderedDict()
        self._by_obj: dict[tuple, set[tuple]] = {}  # (bucket,obj) -> keys
        self._flight: dict[tuple, Future] = {}
        self._epoch = 0
        # invalidation sequence: guards the miss->load->store window of
        # LOCK-FREE readers (get_object_info/tags hold no namespace lock,
        # so a concurrent overwrite can commit + invalidate while their
        # loader is mid-read; storing that result would poison the cache
        # with pre-overwrite metadata that nothing would ever invalidate
        # again). Every choke-point mutation bumps _inv_seq; per-object
        # marks live in _inv_keys (bounded — pruned marks collapse into
        # _inv_floor, conservatively treating them as "just invalidated").
        self._inv_seq = 0
        self._inv_keys: dict[tuple, int] = {}
        self._inv_floor = 0
        self.fi_stats = TierStats()

    # -- read path ---------------------------------------------------------

    def fileinfo(self, bucket: str, obj: str, vid: str, loader):
        """(fi, metas) for the key — from cache when fresh, else via
        ``loader()`` (the N-drive quorum read) under singleflight. Entries
        from an older epoch revalidate with a cheap metadata probe before
        being served."""
        if not enabled():
            return loader()
        key = (bucket, obj, vid)
        with self._mu:
            seq0 = self._inv_seq
            ent = self._fi.get(key)
            hit = ent is not None and self._fresh_locked(ent)
            if hit:
                self._fi.move_to_end(key)
                self.fi_stats.hits += 1
            stale = None if hit else ent
        if hit:
            # span published OUTSIDE _mu: tracing must not serialize every
            # hit across the set through the cache-wide lock
            span_lookup("fileinfo", bucket, obj, True)
            return ent.fi, ent.metas

        # revalidation AND loading both ride the singleflight: a hot key
        # going TTL-stale at N thousand req/s must cost ONE probe chain,
        # not a thundering herd of them
        def attempt():
            if stale is not None and self._revalidate(key, stale):
                # the singleflight owner runs on some pool thread while
                # the hit path bumps the same counters under _mu — take
                # it here too (miniovet races pass)
                with self._mu:
                    self.fi_stats.hits += 1
                    self.fi_stats.revalidations += 1
                span_lookup("fileinfo", bucket, obj, True)
                return stale.fi, stale.metas, False  # re-stamped in place
            span_lookup("fileinfo", bucket, obj, False)
            with self._mu:
                self.fi_stats.misses += 1
            fi, metas = loader()
            return fi, metas, True

        return self._load_singleflight(key, attempt, seq0)

    def _fresh_locked(self, ent: _FiEntry) -> bool:
        if ent.epoch != self._epoch:
            return False
        from . import coherence

        if coherence.is_distributed():
            ttl = _revalidate_ttl()
            if ttl > 0 and time.monotonic() - ent.t > ttl:
                return False
        return True

    @staticmethod
    def _stamp_live(es, key: tuple, stamp, parity: int) -> bool:
        """Cheap revalidation probe: metadata reads from ``parity + 1``
        reachable drives, ALL of which must still report the cached
        identity (mod_time, data_dir). Any committed overwrite reached
        write quorum (>= n - parity drives), so every (parity+1)-subset
        intersects it — one drive that lagged the write (down during it,
        first in iteration order) can never re-certify a stale entry by
        itself. Still far cheaper than the full N-drive quorum read."""
        bucket, obj, vid = key
        need = min(parity + 1, len(es.disks))
        seen = 0
        for disk in es.disks:
            try:
                m = disk.read_version(bucket, obj, vid, read_data=False)
            except (StorageError, OSError):
                continue  # drive unreachable: try the next voucher
            if (m.mod_time, m.data_dir) != stamp or m.deleted:
                return False  # authoritative: identity moved on
            seen += 1
            if seen >= need:
                return True
        return False  # not enough reachable drives to vouch: drop

    def _revalidate(self, key: tuple, ent: _FiEntry) -> bool:
        es = self._es()
        if es is not None and self._stamp_live(
            es, key, ent.stamp, ent.fi.erasure.parity_blocks
        ):
            with self._mu:
                cur = self._fi.get(key)
                if cur is ent:
                    ent.epoch = self._epoch
                    ent.t = time.monotonic()
            return True
        with self._mu:
            cur = self._fi.pop(key, None)
            if cur is not None:
                _bytes_add(-cur.bytes)
                self._unindex_locked(key)
                self.fi_stats.invalidations += 1
        return False

    def _load_singleflight(self, key: tuple, attempt, seq0: int):
        """``attempt() -> (fi, metas, should_store)``: the owner runs it
        (revalidate-or-quorum-load), followers share the result."""
        with self._mu:
            fut = self._flight.get(key)
            owner = fut is None
            if owner:
                fut = self._flight[key] = Future()
            else:
                self.fi_stats.singleflight_shared += 1
        if not owner:
            return fut.result()
        try:
            fi, metas, should_store = attempt()
            if should_store:
                self._store(key, fi, metas, seq0)
            fut.set_result((fi, metas))
            return fi, metas
        except BaseException as e:
            fut.set_exception(e)
            raise
        finally:
            with self._mu:
                self._flight.pop(key, None)

    def _invalidated_since_locked(self, key: tuple, seq0: int) -> bool:
        return max(
            self._inv_keys.get(key[:2], 0), self._inv_floor
        ) > seq0

    def _mark_invalidated_locked(self, bucket_obj: tuple | None) -> None:
        """Caller holds _mu. None marks EVERYTHING (bucket/prefix/clear/
        epoch-scope mutations) via the floor."""
        self._inv_seq += 1
        if bucket_obj is None:
            self._inv_floor = self._inv_seq
            self._inv_keys.clear()
            return
        self._inv_keys[bucket_obj] = self._inv_seq
        if len(self._inv_keys) > 8192:
            # pruned marks collapse into the floor: conservatively treat
            # every forgotten object as just-invalidated
            self._inv_floor = self._inv_seq
            self._inv_keys.clear()

    def _store(self, key: tuple, fi, metas, seq0: int) -> None:
        if fi.deleted:
            return  # delete markers stay uncached (cheap + churn-prone)
        nbytes = sum(
            len(m.inline_data) for m in metas
            if m is not None and m.inline_data
        )
        with self._mu:
            if self._invalidated_since_locked(key, seq0):
                # a mutation invalidated this object while the loader was
                # mid-read: its result may predate the overwrite — caching
                # it would be a permanent stale serve (lock-free HEAD/tags
                # paths have no namespace lock to exclude writers)
                return
            old = self._fi.pop(key, None)
            if old is not None:
                _bytes_add(-old.bytes)
            self._fi[key] = _FiEntry(fi, metas, self._epoch, nbytes)
            _bytes_add(nbytes)
            self._by_obj.setdefault(key[:2], set()).add(key)
            cap = _fileinfo_entries()
            budget = _mem_budget()
            while len(self._fi) > cap:
                k, ev = self._fi.popitem(last=False)
                _bytes_add(-ev.bytes)
                self._unindex_locked(k)
                self.fi_stats.evictions += 1
            # inline payloads count against the global byte budget; only
            # entries actually CARRYING bytes are worth evicting for it
            while _bytes_total() > budget:
                k = next((k for k, e in self._fi.items() if e.bytes), None)
                if k is None:
                    break
                ev = self._fi.pop(k)
                _bytes_add(-ev.bytes)
                self._unindex_locked(k)
                self.fi_stats.evictions += 1

    def _unindex_locked(self, key: tuple) -> None:
        keys = self._by_obj.get(key[:2])
        if keys is not None:
            keys.discard(key)
            if not keys:
                del self._by_obj[key[:2]]

    # -- data tier ---------------------------------------------------------

    def data_get(self, bucket: str, obj: str, vid: str):
        """(fi, bytes) when the whole object is cached and fresh."""
        if not enabled():
            return None
        es = self._es()
        if es is None:
            return None
        ent = _DATA.get(es, bucket, obj, vid)
        if ent is None:
            return None
        if ent.epoch != self._epoch or (self._needs_ttl_check(ent)):
            if not self._revalidate_data((bucket, obj, vid), ent):
                _DATA.count_miss()
                return None
            _DATA.restamp(ent, self._epoch)
        _DATA.touch_hit()
        return ent.fi, ent.data

    def _needs_ttl_check(self, ent) -> bool:
        from . import coherence

        if not coherence.is_distributed():
            return False
        ttl = _revalidate_ttl()
        return ttl > 0 and time.monotonic() - ent.t > ttl

    def _revalidate_data(self, key: tuple, ent) -> bool:
        es = self._es()
        if es is not None and self._stamp_live(
            es, key, ent.stamp, ent.fi.erasure.parity_blocks
        ):
            return True
        if es is not None:
            _DATA.drop((id(es),) + key)
        return False

    def data_admit(self, bucket: str, obj: str, vid: str, fi) -> int | None:
        """Should a full read of this object fill the data cache? Returns
        an invalidation-sequence token to pass back to ``data_put`` (the
        fill is rejected if the object was invalidated in between — e.g.
        a reader whose namespace lock TTL-expired mid-stream racing an
        overwrite), or None when the object is ineligible."""
        if not enabled():
            return None
        es = self._es()
        if es is None or fi.deleted or fi.size <= 0:
            return None
        if fi.size > object_max():
            return None
        if not fi.parts and fi.inline_data is None:
            return None  # transitioned stub: bytes live in the warm tier
        if not _DATA.admit(es, bucket, obj, vid, fi.inline_data is not None):
            return None
        with self._mu:
            return self._inv_seq

    def data_put(self, bucket: str, obj: str, vid: str, fi, data: bytes,
                 token: int) -> None:
        es = self._es()
        if es is None or not enabled():
            return
        if len(data) != fi.size:
            return  # torn fill: never cache bytes that don't match identity
        # token check and insert under ONE hold of _mu: an invalidation
        # landing between them would mark + drop BEFORE the insert and
        # the stale bytes would stick. Lock order SetCache._mu ->
        # DataCache._mu is safe (the choke points call _DATA outside
        # _mu, never the reverse); a racing invalidation now either
        # rejects the token or blocks on _mu until the entry exists to
        # be dropped.
        with self._mu:
            if self._invalidated_since_locked((bucket, obj, vid), token):
                return  # overwritten since the read began: stale bytes
            _DATA.put(es, bucket, obj, vid, fi, data, self._epoch)

    # -- segment tier (range-granular; cache/segment.py) -------------------

    def segment_open(self, bucket: str, obj: str, vid: str, range_hint):
        """Serve a ranged GET entirely from cached verified stripe-block
        segments: ``range_hint`` is the syntactically-parsed Range header
        (``("abs", start, end_or_None)`` / ``("suffix", n)``), resolved
        here against the cached FileInfo's size with the same semantics
        as the S3 layer's range parser. Returns
        ``(fi, start, length, [(abs_offset, bytes)])`` or None (miss /
        unresolvable range → the caller takes the erasure path)."""
        from . import segment as segmod

        if not segmod.segments_enabled() or range_hint is None:
            return None
        es = self._es()
        if es is None:
            return None
        sc = segmod.segment_cache()
        d = sc.directory(es, bucket, obj, vid)
        if d is None:
            return None
        key = (bucket, obj, vid)
        if d.epoch != self._epoch or self._needs_ttl_check(d):
            if not self._revalidate_segments(key, d):
                return None
            sc.restamp(d, self._epoch, time.monotonic())
        resolved = _resolve_range(range_hint, d.fi.size)
        if resolved is None:
            return None
        start, length = resolved
        rows = sc.read_range(d, start, length)
        if rows is None:
            return None
        span_lookup("segment", bucket, obj, True)
        return d.fi, start, length, rows

    def _revalidate_segments(self, key: tuple, d) -> bool:
        es = self._es()
        if es is not None and self._stamp_live(
            es, key, d.stamp, d.fi.erasure.parity_blocks
        ):
            return True
        if es is not None:
            from . import segment as segmod

            segmod.segment_cache().drop_where(
                lambda k: k == (id(es),) + key
            )
        return False

    def segment_admit(self, bucket: str, obj: str, vid: str, fi) -> int | None:
        """Should this ranged read's decoded stripe blocks fill the
        segment cache? Same token contract as ``data_admit``; only
        objects ABOVE the whole-object tier's size gate are eligible
        (below it the whole-object tier is strictly better).
        Transformed objects (SSE/compression) are excluded: their GET
        path issues multiple reads per response through one pinned
        handle, which the segment tier's range-scoped handle cannot
        honor version-stably."""
        from . import segment as segmod

        if not segmod.segments_enabled():
            return None
        es = self._es()
        if es is None or fi.deleted or fi.size <= 0:
            return None
        if fi.size <= object_max():
            return None
        if not fi.parts or fi.inline_data is not None:
            return None
        if _transformed(fi):
            return None
        if not segmod.segment_cache().admit(
            (id(es), bucket, obj, vid), time.monotonic()
        ):
            return None
        with self._mu:
            return self._inv_seq

    def segment_put(self, bucket: str, obj: str, vid: str, fi, pnum: int,
                    bi: int, data, token: int) -> None:
        """Insert one bitrot-verified decoded stripe block. Token check +
        insert under one _mu hold (same rationale as ``data_put``); disk
        demotion I/O runs after _mu is released."""
        from . import segment as segmod

        es = self._es()
        if es is None or not segmod.segments_enabled():
            return
        sc = segmod.segment_cache()
        with self._mu:
            if self._invalidated_since_locked((bucket, obj, vid), token):
                return
            victims, orphans = sc.put(
                es, bucket, obj, vid, fi, pnum, bi, data,
                self._epoch, time.monotonic(),
            )
        sc.demote(victims, orphans)

    def segment_observe(self, bucket: str, obj: str, vid: str,
                        start: int, length: int, fi) -> None:
        """Feed the sequential-read detector (cache/prefetch.py) with one
        observed request range; called from the ranged-GET read path for
        hits and misses alike. Only segment-ELIGIBLE objects are tracked
        — read-ahead over an object the tier will never admit is pure
        wasted I/O."""
        if fi.deleted or fi.size <= object_max():
            return
        if not fi.parts or fi.inline_data is not None:
            return
        if _transformed(fi):
            return  # never admitted (see segment_admit): don't read ahead
        from . import prefetch

        es = self._es()
        if es is not None:
            prefetch.observe(es, bucket, obj, vid, start, length)

    # -- choke-point mutations (the ONLY write API; see cache-discipline) --

    def invalidate_object(self, bucket: str, obj: str,
                          broadcast: bool = True) -> None:
        """Write-through invalidation for one object: every cached version
        of it (FileInfo + data tiers) drops, the bucket's listing
        metacache entries drop, and — unless this call IS a received
        broadcast — peers are told over the grid."""
        es = self._es()
        with self._mu:
            self._mark_invalidated_locked((bucket, obj))
            for key in list(self._by_obj.get((bucket, obj), ())):
                ev = self._fi.pop(key, None)
                if ev is not None:
                    _bytes_add(-ev.bytes)
                    self.fi_stats.invalidations += 1
            self._by_obj.pop((bucket, obj), None)
        if es is not None:
            _DATA.drop_where(
                lambda k: k[0] == id(es) and k[1] == bucket and k[2] == obj
            )
            from . import segment as segmod

            segmod.segment_cache().drop_where(
                lambda k: k[0] == id(es) and k[1] == bucket and k[2] == obj
            )
        from ..erasure import listing

        listing.invalidate_bucket(bucket)
        if broadcast and es is not None:
            from . import coherence

            coherence.broadcast_invalidate(
                es.pool_index, es.set_index, bucket, obj
            )

    def invalidate_prefix(self, bucket: str, prefix: str,
                          broadcast: bool = True) -> None:
        """Choke point for bulk out-of-band deletes (multipart cleanup,
        recursive prefix removals that bypass delete_object)."""
        es = self._es()
        with self._mu:
            self._mark_invalidated_locked(None)
            for key in [
                k for k in self._fi if k[0] == bucket and k[1].startswith(prefix)
            ]:
                ev = self._fi.pop(key)
                _bytes_add(-ev.bytes)
                self._unindex_locked(key)
                self.fi_stats.invalidations += 1
        if es is not None:
            _DATA.drop_where(
                lambda k: k[0] == id(es) and k[1] == bucket
                and k[2].startswith(prefix)
            )
            from . import segment as segmod

            segmod.segment_cache().drop_where(
                lambda k: k[0] == id(es) and k[1] == bucket
                and k[2].startswith(prefix)
            )
        from ..erasure import listing

        listing.invalidate_bucket(bucket)
        if broadcast and es is not None:
            from . import coherence

            coherence.broadcast_invalidate(
                es.pool_index, es.set_index, bucket, prefix, kind="prefix"
            )

    def invalidate_bucket(self, bucket: str, broadcast: bool = True) -> None:
        es = self._es()
        with self._mu:
            self._mark_invalidated_locked(None)
            for key in [k for k in self._fi if k[0] == bucket]:
                ev = self._fi.pop(key)
                _bytes_add(-ev.bytes)
                self._unindex_locked(key)
                self.fi_stats.invalidations += 1
        if es is not None:
            _DATA.drop_where(lambda k: k[0] == id(es) and k[1] == bucket)
            from . import segment as segmod

            segmod.segment_cache().drop_where(
                lambda k: k[0] == id(es) and k[1] == bucket
            )
        from ..erasure import listing

        listing.invalidate_bucket(bucket)
        if broadcast and es is not None:
            # bucket deletion/recreation must reach peers too, or they
            # keep serving cached objects of a deleted bucket
            from . import coherence

            coherence.broadcast_invalidate(
                es.pool_index, es.set_index, bucket, "", kind="bucket"
            )

    def bump_epoch(self) -> None:
        """Invalidate-by-suspicion: entries survive but must revalidate
        (cheap metadata probe) before their next serve. Used when a
        generation gap says some invalidation broadcast was lost."""
        with self._mu:
            self._epoch += 1
            self._mark_invalidated_locked(None)

    def clear(self) -> int:
        es = self._es()
        with self._mu:
            self._mark_invalidated_locked(None)
            n = len(self._fi)
            for ev in self._fi.values():
                _bytes_add(-ev.bytes)
            self._fi.clear()
            self._by_obj.clear()
        if es is not None:
            n += _DATA.drop_where(lambda k: k[0] == id(es))
            from . import segment as segmod

            n += segmod.segment_cache().drop_where(lambda k: k[0] == id(es))
        return n

    # -- observability -----------------------------------------------------

    def snapshot(self) -> dict:
        with self._mu:
            return {
                "epoch": self._epoch,
                "fileinfoEntries": len(self._fi),
                "fileinfo": self.fi_stats.snapshot(),
            }


def store_caches(store) -> list[SetCache]:
    """Every SetCache reachable from an object-layer store."""
    out = []
    for pool in getattr(store, "pools", [store]):
        for s in getattr(pool, "sets", [pool]):
            c = getattr(s, "cache", None)
            if c is not None:
                out.append(c)
    return out


def aggregate_stats(store) -> dict:
    """Combined cache stats for one store (metrics v3 /api/cache and the
    admin cache/status endpoint)."""
    from ..erasure import listing

    fi = TierStats()
    entries = 0
    epoch = 0
    for c in store_caches(store):
        snap = c.snapshot()
        entries += snap["fileinfoEntries"]
        epoch = max(epoch, snap["epoch"])
        for k, v in snap["fileinfo"].items():
            setattr(fi, k, getattr(fi, k) + v)
    from . import prefetch
    from . import segment as segmod

    return {
        "enabled": enabled(),
        "epoch": epoch,
        "bytesTotal": _bytes_total(),
        "fileinfo": {**fi.snapshot(), "entries": entries},
        "data": {
            **_DATA.stats.snapshot(),
            "entries": _DATA.entry_count(),
            "bytes": _DATA.byte_count(),
        },
        "segments": segmod.segment_cache().snapshot(),
        "prefetch": prefetch.stats(),
        "listing": listing.metacache_stats(),
    }


def clear_store(store) -> int:
    """Admin cache/clear: drop every cached entry for this store."""
    from ..erasure import listing

    n = 0
    for c in store_caches(store):
        n += c.clear()
    n += listing.clear_metacache()
    return n


def _transformed(fi) -> bool:
    """True when the object's stored bytes are SSE/compression
    transformed — those responses read through one version-pinned handle
    in multiple passes, which the segment tier must not serve."""
    try:
        from ..server import transforms

        return transforms.is_transformed(fi.metadata)
    # miniovet: ignore[error-taint] -- fail-SAFE default: any failure
    # (import cycle, malformed metadata) steers OFF the segment fast
    # path onto the full erasure read, which serves correctly regardless
    except Exception:  # noqa: BLE001 — can't tell: stay off the fast path
        return True


def _resolve_range(range_hint, size: int) -> tuple[int, int] | None:
    """Resolve a syntactically-parsed Range hint against the object size
    — the same clamping the S3 layer's ``_parse_range`` applies, so a
    segment-cache hit serves byte-identical ranges to the erasure path.
    Returns (start, length) or None when the hint is unserveable (the
    caller falls through to the real path, which raises the proper S3
    error)."""
    if size <= 0:
        return None
    kind = range_hint[0]
    if kind == "suffix":
        n = range_hint[1]
        if n <= 0:
            return None
        start, end = max(size - n, 0), size - 1
    else:
        start = range_hint[1]
        end = range_hint[2] if range_hint[2] is not None else size - 1
        if start < 0 or start >= size or start > end:
            return None
        end = min(end, size - 1)
    return start, end - start + 1


def span_lookup(kind: str, bucket: str, obj: str, hit: bool):
    """One cache record on the request's span tree (zero-alloc NOOP when
    nobody is tracing)."""
    if not obs.active():
        return
    with obs.span(
        obs.TYPE_INTERNAL, f"cache.{kind}", bucket=bucket, object=obj
    ) as sp:
        sp.set(hit=hit)
