"""Audit + server logging targets.

Mirrors the reference's logger target system (internal/logger/targets.go):
structured request audit records stream to env-configured HTTP webhooks
(MINIO_AUDIT_WEBHOOK_ENABLE_<ID>/..._ENDPOINT_<ID>) with a bounded retry
queue; console logging stays on stderr.
"""

from __future__ import annotations

import json
import os
import queue
import threading
import urllib.request


class AuditLog:
    def __init__(self):
        self.endpoints: list[tuple[str, str]] = []  # (endpoint, token)
        for k, v in os.environ.items():
            if k.startswith("MINIO_AUDIT_WEBHOOK_ENABLE_") and v in ("on", "true", "1"):
                ident = k.rsplit("_", 1)[-1].upper()
                ep = os.environ.get(f"MINIO_AUDIT_WEBHOOK_ENDPOINT_{ident}", "")
                tok = os.environ.get(f"MINIO_AUDIT_WEBHOOK_AUTH_TOKEN_{ident}", "")
                if ep:
                    self.endpoints.append((ep, tok))
        # audit-to-Kafka (reference internal/logger/target/kafka): same
        # raw Produce client the event sinks use
        self.kafka = None
        if os.environ.get("MINIO_AUDIT_KAFKA_ENABLE", "") in ("on", "true", "1"):
            brokers = os.environ.get("MINIO_AUDIT_KAFKA_BROKERS", "")
            topic = os.environ.get("MINIO_AUDIT_KAFKA_TOPIC", "minio-audit")
            if brokers:
                from ..events.kafka import KafkaTarget

                self.kafka = KafkaTarget("audit", brokers.split(",")[0].strip(), topic)
        self._q: queue.Queue = queue.Queue(maxsize=5000)
        self.stats = {"sent": 0, "failed": 0, "dropped": 0}
        if self.enabled:
            threading.Thread(target=self._loop, daemon=True, name="audit").start()

    @property
    def enabled(self) -> bool:
        return bool(self.endpoints) or self.kafka is not None

    def emit(self, record: dict) -> None:
        if not self.enabled:
            return
        try:
            self._q.put_nowait(record)
        except queue.Full:
            self.stats["dropped"] += 1

    def _loop(self) -> None:
        while True:
            rec = self._q.get()
            body = json.dumps(rec).encode()
            for ep, tok in self.endpoints:
                try:
                    req = urllib.request.Request(
                        ep, data=body,
                        headers={"Content-Type": "application/json",
                                 **({"Authorization": f"Bearer {tok}"} if tok else {})},
                    )
                    urllib.request.urlopen(req, timeout=5).read()
                    self.stats["sent"] += 1
                except Exception:  # noqa: BLE001
                    self.stats["failed"] += 1
            if self.kafka is not None:
                try:
                    self.kafka.send_raw(body)
                    self.stats["sent"] += 1
                except Exception:  # noqa: BLE001
                    self.stats["failed"] += 1


def audit_record(
    request, status: int, dur: float, access_key: str,
    rx: int = 0, tx: int = 0,
) -> dict:
    """madmin-style audit entry (reference internal/logger/audit.go).
    Carries the generated x-amz-request-id so audit rows join against
    trace streams and client-side error reports, and the bytes counted
    at write time (streamed responses would otherwise audit as 0)."""
    import time

    return {
        "version": "1",
        "time": time.strftime("%Y-%m-%dT%H:%M:%SZ", time.gmtime()),
        "requestID": request.get("_reqid", ""),
        "api": {
            "name": request.method,
            "bucket": request.match_info.get("bucket", ""),
            "object": request.match_info.get("key", ""),
            "status": "OK" if status < 400 else "Error",
            "statusCode": status,
            "rx": rx,
            "tx": tx,
            "timeToResponseNs": int(dur * 1e9),
        },
        "remoteHost": request.remote or "",
        "requestPath": request.path,
        "requestQuery": request.rel_url.raw_query_string,
        "accessKey": access_key,
    }
