"""Multipart-upload handlers: initiate, upload part, part copy,
complete, abort, list parts, list uploads.

Split from app.py (the reference's cmd/object-multipart-handlers.go)."""

from __future__ import annotations

import xml.etree.ElementTree as ET
from xml.sax.saxutils import escape

from aiohttp import web

from ..erasure import listing
from . import s3err
from .handler_utils import (
    _verify_checksum_headers,
    _bucket_sse_algo,
    _iso8601,
)


class MultipartHandlersMixin:
    async def new_multipart(self, request, bucket, key) -> web.Response:
        from ..crypto.sse import CryptoError
        from . import transforms

        bm = self.buckets.get(bucket)
        key = listing.encode_dir_object(key)
        user_defined = {}
        if request.headers.get("Content-Type"):
            user_defined["content-type"] = request.headers["Content-Type"]
        for k, v in request.headers.items():
            if k.lower().startswith("x-amz-meta-"):
                user_defined[k.lower()] = v
        if request.headers.get("x-amz-tagging"):
            user_defined[self.TAGS_META] = self._tagging_header_meta(
                request.headers["x-amz-tagging"]
            )
        sse_resp: dict[str, str] = {}
        try:
            req_headers = {k.lower(): v for k, v in request.headers.items()}
            sse = transforms.multipart_sse_init(
                req_headers, _bucket_sse_algo(bm.encryption), self.kms,
                bucket, key,
            )
        except CryptoError:
            raise s3err.InvalidArgument from None
        if sse is not None:
            sse_meta, sse_resp = sse
            user_defined.update(sse_meta)
        upload_id = await self._run(
            self.mp.new_upload, bucket, key, user_defined,
            self._parity_for_storage_class(request),
            self._family_for_storage_class(request),
        )
        xml = (
            '<?xml version="1.0" encoding="UTF-8"?>'
            '<InitiateMultipartUploadResult xmlns="http://s3.amazonaws.com/doc/2006-03-01/">'
            f"<Bucket>{escape(bucket)}</Bucket><Key>{escape(key)}</Key>"
            f"<UploadId>{upload_id}</UploadId></InitiateMultipartUploadResult>"
        )
        return web.Response(
            body=xml.encode(), content_type="application/xml", headers=sse_resp
        )

    async def put_object_part(self, request, bucket, key, body) -> web.Response:
        from ..erasure import multipart as mp_mod

        key = listing.encode_dir_object(key)
        q = request.rel_url.query
        try:
            part_number = int(q["partNumber"])
        except (KeyError, ValueError):
            raise s3err.InvalidArgument from None
        upload_id = q.get("uploadId", "")
        self._enforce_quota(bucket, self._incoming_size(request, body))
        # SSE-C uploads re-present the customer key on every part; thread
        # the request headers through as the part-transform context
        part_ctx = {k.lower(): v for k, v in request.headers.items()}
        from ..crypto.sse import CryptoError

        try:
            if body is None:
                # streaming part upload (multipart is how huge objects
                # arrive: each part flows straight into its erasure stream)
                etag = await self._run_streaming_put(
                    request,
                    lambda rd: self.mp.put_part(
                        bucket, key, upload_id, part_number, rd,
                        transform_ctx=part_ctx,
                    ),
                )
                tr = request.get("trailer_checksum_meta")
                if tr:
                    await self._run(
                        self.mp.update_part_metadata, bucket, key,
                        upload_id, part_number, tr,
                    )
            else:
                checksum_meta = _verify_checksum_headers(request.headers, body)
                checksum_meta.update(request.get("trailer_checksum_meta") or {})
                etag = await self._run(
                    self.mp.put_part, bucket, key, upload_id, part_number, body,
                    checksum_meta or None, part_ctx,
                )
        except mp_mod.UploadNotFound:
            raise s3err.NoSuchUpload from None
        except mp_mod.InvalidPart:
            raise s3err.InvalidPart from None
        except CryptoError:
            # missing/mismatched SSE-C key on an encrypted upload
            raise s3err.InvalidArgument from None
        headers = {"ETag": f'"{etag}"'}
        for hk in request.headers:
            if hk.lower().startswith("x-amz-checksum-"):
                headers[hk] = request.headers[hk]
        # trailer-mode uploads carry the checksum in the trailer, not a
        # header: echo the VERIFIED value so SDK response validation sees it
        from ..utils import checksum as _cks

        for mk, mv in (request.get("trailer_checksum_meta") or {}).items():
            algo = mk[len(_cks.META_PREFIX):]
            headers.setdefault(f"x-amz-checksum-{algo}", mv)
        return web.Response(status=200, headers=headers)

    async def upload_part_copy(self, request, bucket, key) -> web.Response:
        from ..erasure import multipart as mp_mod

        key = listing.encode_dir_object(key)
        q = request.rel_url.query
        try:
            part_number = int(q["partNumber"])
        except (KeyError, ValueError):
            raise s3err.InvalidArgument from None
        upload_id = q.get("uploadId", "")
        src_bucket, src_key, src_vid = self._parse_copy_source(
            request, request.get("access_key", "")
        )
        oi, handle = await self._run(
            self.store.open_object, src_bucket, src_key, src_vid
        )
        from . import transforms

        try:
            # any pre-read failure (412, quota) must release the source
            # namespace read lock, not wait out the 120s TTL
            self._check_copy_preconditions(request, oi)
            self._enforce_quota(
                bucket, transforms.logical_size(oi.user_defined, oi.size)
            )
            # transformed (SSE/compressed) sources must decode to logical
            # bytes: ranges apply to plaintext, and the destination part
            # re-transforms for its own upload
            logical = transforms.logical_size(oi.user_defined, oi.size)
            offset, length = 0, logical
            crange = request.headers.get("x-amz-copy-source-range", "")
            if crange.startswith("bytes="):
                try:
                    a, _, b = crange[len("bytes=") :].partition("-")
                    offset = int(a)
                    length = int(b) - offset + 1
                except ValueError:
                    raise s3err.InvalidArgument from None
                if offset < 0 or length <= 0 or offset + length > logical:
                    raise s3err.InvalidRange
            if transforms.is_transformed(oi.user_defined):
                req_headers = {k.lower(): v for k, v in request.headers.items()}
                # SSE-C sources present their key under the copy-source
                # header set; remap so the source decode sees it (and not
                # the DESTINATION upload's key riding the same request)
                src_headers = dict(req_headers)
                for _h in ("algorithm", "key", "key-md5"):
                    _v = req_headers.get(
                        "x-amz-copy-source-server-side-encryption-customer-"
                        + _h
                    )
                    src_headers.pop(
                        "x-amz-server-side-encryption-customer-" + _h, None
                    )
                    if _v:
                        src_headers[
                            "x-amz-server-side-encryption-customer-" + _h
                        ] = _v
                req_headers = src_headers

                def read_fn(off, ln):
                    return b"".join(handle.read(off, ln, close_when_done=False))

                from ..crypto.sse import CryptoError as _CryptoError

                try:
                    data = await self._run(
                        transforms.decode_range, read_fn, oi.size,
                        oi.user_defined, req_headers, src_bucket, src_key,
                        self.kms, offset, length,
                    )
                except _CryptoError:
                    # missing/wrong copy-source SSE-C key
                    raise s3err.InvalidArgument from None
            else:
                data = await self._run(
                    lambda: b"".join(handle.read(offset, length))
                )
        finally:
            handle.close()
        from ..crypto.sse import CryptoError

        try:
            # destination SSE-C headers (x-amz-server-side-encryption-
            # customer-*) ride the same request; thread them through so a
            # part copy into an SSE-C upload can seal under the upload key
            etag = await self._run(
                self.mp.put_part, bucket, key, upload_id, part_number, data,
                None, {k.lower(): v for k, v in request.headers.items()},
            )
        except mp_mod.UploadNotFound:
            raise s3err.NoSuchUpload from None
        except CryptoError:
            raise s3err.InvalidArgument from None
        xml = (
            '<?xml version="1.0" encoding="UTF-8"?>'
            f'<CopyPartResult><ETag>"{etag}"</ETag>'
            f"<LastModified>{_iso8601(oi.mod_time)}</LastModified></CopyPartResult>"
        )
        return web.Response(body=xml.encode(), content_type="application/xml")

    async def complete_multipart(self, request, bucket, key, body) -> web.Response:
        from ..erasure import multipart as mp_mod

        key = listing.encode_dir_object(key)
        upload_id = request.rel_url.query.get("uploadId", "")
        try:
            root = ET.fromstring(body)
        except ET.ParseError:
            raise s3err.MalformedXML from None
        parts = []
        part_checksums: dict[int, dict[str, str]] = {}
        for el in root:
            if el.tag.split("}")[-1] == "Part":
                n, etag = 0, ""
                cks_vals: dict[str, str] = {}
                for sub in el:
                    t = sub.tag.split("}")[-1]
                    if t == "PartNumber":
                        n = int(sub.text or "0")
                    elif t == "ETag":
                        etag = (sub.text or "").strip()
                    elif t.startswith("Checksum"):
                        cks_vals[t[len("Checksum"):].lower()] = (sub.text or "").strip()
                parts.append((n, etag))
                if cks_vals:
                    part_checksums[n] = cks_vals
        bm = self.buckets.get(bucket)
        try:
            oi = await self._run(
                self.mp.complete, bucket, key, upload_id, parts, bm.versioning,
                part_checksums or None, self._put_precond(request),
            )
        except mp_mod.UploadNotFound:
            raise s3err.NoSuchUpload from None
        except mp_mod.InvalidPartOrder:
            raise s3err.InvalidPartOrder from None
        except mp_mod.InvalidPart:
            raise s3err.InvalidPart from None
        xml = (
            '<?xml version="1.0" encoding="UTF-8"?>'
            '<CompleteMultipartUploadResult xmlns="http://s3.amazonaws.com/doc/2006-03-01/">'
            f"<Location>/{escape(bucket)}/{escape(key)}</Location>"
            f"<Bucket>{escape(bucket)}</Bucket><Key>{escape(key)}</Key>"
            f'<ETag>"{oi.etag}"</ETag></CompleteMultipartUploadResult>'
        )
        headers = {}
        if oi.version_id:
            headers["x-amz-version-id"] = oi.version_id
        from ..events import notify as ev

        self.notifier.notify(
            ev.OBJECT_CREATED_MULTIPART, bucket, listing.decode_dir_object(key),
            oi.size, oi.etag, oi.version_id, request.get("access_key", ""),
        )
        self._queue_repl(request, bucket, key, oi.version_id, "put")
        return web.Response(body=xml.encode(), content_type="application/xml", headers=headers)

    async def abort_multipart(self, request, bucket, key) -> web.Response:
        from ..erasure import multipart as mp_mod

        key = listing.encode_dir_object(key)
        upload_id = request.rel_url.query.get("uploadId", "")
        try:
            await self._run(self.mp.abort, bucket, key, upload_id)
        except mp_mod.UploadNotFound:
            raise s3err.NoSuchUpload from None
        return web.Response(status=204)

    async def list_parts(self, request, bucket, key) -> web.Response:
        from ..erasure import multipart as mp_mod

        key = listing.encode_dir_object(key)
        q = request.rel_url.query
        upload_id = q.get("uploadId", "")
        try:
            max_parts = int(q.get("max-parts", "1000"))
            marker = int(q.get("part-number-marker", "0"))
        except ValueError:
            raise s3err.InvalidArgument from None
        if max_parts < 0 or marker < 0:
            raise s3err.InvalidArgument
        max_parts = min(max_parts, 1000)
        try:
            parts, truncated = await self._run(
                self.mp.list_parts, bucket, key, upload_id, max_parts, marker
            )
        except mp_mod.UploadNotFound:
            raise s3err.NoSuchUpload from None
        items = "".join(
            f"<Part><PartNumber>{p.number}</PartNumber>"
            f'<ETag>"{p.etag}"</ETag><Size>{p.size}</Size>'
            f"<LastModified>{_iso8601(p.mod_time)}</LastModified></Part>"
            for p in parts
        )
        next_marker = (
            f"<NextPartNumberMarker>{parts[-1].number}</NextPartNumberMarker>"
            if truncated and parts
            else ""
        )
        xml = (
            '<?xml version="1.0" encoding="UTF-8"?>'
            '<ListPartsResult xmlns="http://s3.amazonaws.com/doc/2006-03-01/">'
            f"<Bucket>{escape(bucket)}</Bucket><Key>{escape(key)}</Key>"
            f"<UploadId>{upload_id}</UploadId><MaxParts>{max_parts}</MaxParts>"
            f"<PartNumberMarker>{marker}</PartNumberMarker>{next_marker}"
            f"<IsTruncated>{'true' if truncated else 'false'}</IsTruncated>"
            f"{items}</ListPartsResult>"
        )
        return web.Response(body=xml.encode(), content_type="application/xml")
    async def list_multipart_uploads(self, request, bucket) -> web.Response:
        q = request.rel_url.query
        prefix = q.get("prefix", "")
        key_marker = q.get("key-marker", "")
        uid_marker = q.get("upload-id-marker", "")
        try:
            max_uploads = min(max(int(q.get("max-uploads", "1000")), 0), 1000)
        except ValueError:
            raise s3err.InvalidArgument from None
        if max_uploads == 0:
            # an empty page with no next marker cannot progress: report it
            # as NON-truncated (same discipline as ListParts max-parts=0)
            return web.Response(
                body=(
                    '<?xml version="1.0" encoding="UTF-8"?>'
                    '<ListMultipartUploadsResult xmlns="http://s3.amazonaws.com/doc/2006-03-01/">'
                    f"<Bucket>{escape(bucket)}</Bucket><Prefix>{escape(prefix)}</Prefix>"
                    "<MaxUploads>0</MaxUploads>"
                    "<IsTruncated>false</IsTruncated></ListMultipartUploadsResult>"
                ).encode(),
                content_type="application/xml",
            )
        uploads = sorted(await self._run(self.mp.list_uploads, bucket, prefix))
        if key_marker:
            # marker semantics (cmd/erasure-multipart.go ListMultipartUploads):
            # strictly after (key_marker, uid_marker)
            uploads = [
                (k, u) for k, u in uploads
                if k > key_marker or (k == key_marker and uid_marker and u > uid_marker)
            ]
        page = uploads[:max_uploads]
        truncated = len(uploads) > len(page)
        items = "".join(
            f"<Upload><Key>{escape(k)}</Key><UploadId>{uid}</UploadId></Upload>"
            for k, uid in page
        )
        next_markers = (
            f"<NextKeyMarker>{escape(page[-1][0])}</NextKeyMarker>"
            f"<NextUploadIdMarker>{page[-1][1]}</NextUploadIdMarker>"
            if truncated and page
            else ""
        )
        xml = (
            '<?xml version="1.0" encoding="UTF-8"?>'
            '<ListMultipartUploadsResult xmlns="http://s3.amazonaws.com/doc/2006-03-01/">'
            f"<Bucket>{escape(bucket)}</Bucket><Prefix>{escape(prefix)}</Prefix>"
            f"<KeyMarker>{escape(key_marker)}</KeyMarker>"
            f"<MaxUploads>{max_uploads}</MaxUploads>{next_markers}"
            f"<IsTruncated>{'true' if truncated else 'false'}</IsTruncated>"
            f"{items}</ListMultipartUploadsResult>"
        )
        return web.Response(body=xml.encode(), content_type="application/xml")
