"""Many-core data plane: the SO_REUSEPORT worker pool.

The asyncio serving plane is single-core by construction (one event loop,
one GIL), so everything PERF.md measured so far ran on ONE core. This
module scales the accept/parse plane across processes the way nginx and
the reference's active-active deployments do: a parent **supervisor**
spawns ``MINIO_TPU_WORKERS`` child processes (0 = auto from nproc), each
running the FULL handler stack over the same drive roots and sharing one
listen port via ``SO_REUSEPORT`` — the kernel load-balances accepted
connections across workers.

A worker is just another peer:

- **Mutation serialization** rides the existing ns-lock/dsync layer:
  every worker's locker set is [its own ``LocalLocker``] + [a
  ``_RemoteLocker`` per sibling worker], so the write quorum
  (n/2+1 of all workers) serializes cross-worker writers exactly like
  cross-node writers.
- **Cache coherence** rides the existing ``cache/coherence.py``
  choke-point broadcast: sibling workers are configured as grid peers,
  so a PUT on worker A synchronously invalidates B's and C's caches
  before the client sees 200.
- **Admin fan-out** (fault inject/clear, cache clear, trace streaming,
  profiling) reaches every worker because siblings land in
  ``server.peers`` — the same list real cluster peers ride.

Each worker therefore needs an **addressable** endpoint of its own
(SO_REUSEPORT makes the shared port land on an arbitrary worker): worker
``i`` binds a loopback *control* listener on ``port_base + i`` serving
the same aiohttp app (grid, locks, storage REST, admin, metrics).

Supervision: the parent is a dumb process herder — no sockets, no store.
It forwards SIGTERM/SIGINT to the children, restarts a worker that dies
unexpectedly (throttled: a worker crashing repeatedly right after boot
takes the whole pool down rather than flapping forever), and exits when
the children are gone.

Distributed deployments keep ``MINIO_TPU_WORKERS=1`` for now: remote
peers address this node by its advertised endpoint only, and a lock RPC
landing on an arbitrary worker's table would break cross-node dsync.
The supervisor refuses the combination loudly instead of corrupting
quietly.
"""

from __future__ import annotations

import os
import signal
import subprocess
import sys
import time

# Children get these; their presence marks a process as a pool worker.
ENV_INDEX = "MINIO_TPU_WORKER_INDEX"
ENV_COUNT = "MINIO_TPU_WORKER_COUNT"
ENV_PORT_BASE = "MINIO_TPU_WORKER_PORT_BASE"

MAX_WORKERS = 64
# a worker dying this soon after spawn counts against the crash budget
CRASH_WINDOW_S = 5.0
CRASH_BUDGET = 3
# after forwarding a stop signal, workers get this long to drain before
# the supervisor escalates to SIGKILL — a wedged worker must not make
# the pool unkillable
STOP_GRACE_S = 20.0


def resolve_worker_count() -> int:
    """Requested pool size from ``MINIO_TPU_WORKERS``: 1 (default) serves
    single-process, 0 auto-sizes to the machine's cores, malformed or
    negative values refuse loudly (a typo silently serving single-core
    would defeat the whole point)."""
    raw = os.environ.get("MINIO_TPU_WORKERS", "1").strip()
    try:
        n = int(raw)
    except ValueError:
        raise SystemExit(
            f"MINIO_TPU_WORKERS={raw!r}: want a worker count "
            "(0 = auto from nproc)"
        ) from None
    if n < 0:
        raise SystemExit(f"MINIO_TPU_WORKERS={n}: want >= 0 (0 = auto)")
    if n == 0:
        n = os.cpu_count() or 1
    return min(n, MAX_WORKERS)


def worker_identity() -> tuple[int, int, int] | None:
    """(index, count, port_base) when this process is a pool worker
    (spawned by the supervisor), else None."""
    raw = os.environ.get(ENV_INDEX)
    if raw is None:
        return None
    try:
        idx = int(raw)
        count = int(os.environ.get(ENV_COUNT, "1"))
        base = int(os.environ.get(ENV_PORT_BASE, "0"))
    except ValueError:
        raise SystemExit(
            "malformed worker identity env (supervisor bug): "
            f"{ENV_INDEX}={raw!r}"
        ) from None
    if not (0 <= idx < count) or base <= 0:
        raise SystemExit(
            f"inconsistent worker identity: index={idx} count={count} "
            f"port_base={base}"
        )
    return idx, count, base


def control_port(port_base: int, index: int) -> int:
    return port_base + index


def sibling_peers(index: int, count: int, port_base: int) -> list[str]:
    """Loopback control endpoints of every OTHER worker in the pool."""
    return [
        f"127.0.0.1:{control_port(port_base, j)}"
        for j in range(count)
        if j != index
    ]


def resolve_port_base(my_port: int) -> int:
    """Control-port range start: ``MINIO_TPU_WORKER_PORT_BASE`` or the
    S3 port + 1000 (kept deterministic so every worker derives the same
    peer list without coordination)."""
    raw = os.environ.get(ENV_PORT_BASE, "").strip()
    if raw:
        try:
            base = int(raw)
        except ValueError:
            raise SystemExit(
                f"{ENV_PORT_BASE}={raw!r}: want a TCP port number"
            ) from None
    else:
        base = my_port + 1000
    if not (0 < base < 65536 - MAX_WORKERS):
        # the derived default can overflow too (--address :64600);
        # refuse loudly here rather than letting every worker crash at
        # control-listener bind until the supervisor gives up
        src = f"{ENV_PORT_BASE}={base}" if raw else (
            f"control-port base {base} (S3 port + 1000)"
        )
        raise SystemExit(
            f"{src}: out of port range; set {ENV_PORT_BASE} explicitly"
        )
    return base


def supervise(argv: list[str], workers: int, my_port: int,
              distributed: bool) -> int:
    """Run the pool: spawn `workers` children re-executing this server
    with worker identity env, restart crashers, forward signals. Returns
    the exit code for the supervisor process."""
    if distributed:
        raise SystemExit(
            f"MINIO_TPU_WORKERS={workers} with remote cluster peers is "
            "not supported yet: remote nodes address this node by one "
            "endpoint, and lock RPCs landing on an arbitrary worker "
            "would break cross-node dsync. Run 1 worker per node in "
            "distributed mode."
        )
    port_base = resolve_port_base(my_port)
    base_env = dict(os.environ)
    base_env[ENV_COUNT] = str(workers)
    base_env[ENV_PORT_BASE] = str(port_base)

    def spawn(i: int) -> subprocess.Popen:
        env = dict(base_env)
        env[ENV_INDEX] = str(i)
        return subprocess.Popen(
            [sys.executable, "-m", "minio_tpu.server", *argv], env=env
        )

    procs: dict[int, subprocess.Popen] = {i: spawn(i) for i in range(workers)}
    spawned_at: dict[int, float] = {i: time.monotonic() for i in procs}
    crashes: dict[int, int] = {i: 0 for i in procs}
    stopping = {"flag": False, "since": 0.0}

    def forward(signum, _frame):
        if not stopping["flag"]:
            stopping["since"] = time.monotonic()
        stopping["flag"] = True
        for p in procs.values():
            if p.poll() is None:
                try:
                    p.send_signal(signum)
                except OSError:
                    pass

    for sig in (signal.SIGINT, signal.SIGTERM):
        signal.signal(sig, forward)
    print(
        f"worker pool: {workers} workers on shared port {my_port} "
        f"(SO_REUSEPORT), control ports {port_base}..."
        f"{port_base + workers - 1}",
        flush=True,
    )

    rc = 0
    while procs:
        # miniovet: ignore[blocking] -- supervisor main thread; there is
        # no event loop in this process
        time.sleep(0.2)
        if (
            stopping["flag"]
            and time.monotonic() - stopping["since"] > STOP_GRACE_S
        ):
            for p in procs.values():
                if p.poll() is None:
                    try:
                        p.kill()
                    except OSError:
                        pass
        for i, p in list(procs.items()):
            code = p.poll()
            if code is None:
                continue
            if stopping["flag"]:
                del procs[i]
                continue
            # unexpected death: restart, unless it keeps dying young
            young = time.monotonic() - spawned_at[i] < CRASH_WINDOW_S
            crashes[i] = crashes[i] + 1 if young else 1
            if crashes[i] >= CRASH_BUDGET:
                print(
                    f"worker {i} exited {code} x{crashes[i]} within "
                    f"{CRASH_WINDOW_S:.0f}s of spawn; stopping the pool",
                    flush=True,
                )
                rc = 1
                forward(signal.SIGTERM, None)
                del procs[i]
                continue
            print(f"worker {i} exited {code}; restarting", flush=True)
            procs[i] = spawn(i)
            spawned_at[i] = time.monotonic()
    return rc
