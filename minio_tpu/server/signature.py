"""AWS Signature Version 4 verification (header + presigned query auth).

Implements the SigV4 algorithm the reference verifies in
/root/reference/cmd/signature-v4.go: canonical request -> string-to-sign ->
derived signing key -> HMAC-SHA256 signature comparison, including the S3
URI-encoding rules and UNSIGNED-PAYLOAD handling. Also provides sign_request
for clients/tests (the reference relies on minio-go for that side).
"""

from __future__ import annotations

import hashlib
import hmac
import urllib.parse
from dataclasses import dataclass
from datetime import datetime, timedelta, timezone

from . import s3err

SIGN_V4_ALGORITHM = "AWS4-HMAC-SHA256"
UNSIGNED_PAYLOAD = "UNSIGNED-PAYLOAD"
STREAMING_PAYLOAD = "STREAMING-AWS4-HMAC-SHA256-PAYLOAD"
STREAMING_PAYLOAD_TRAILER = "STREAMING-AWS4-HMAC-SHA256-PAYLOAD-TRAILER"
STREAMING_UNSIGNED_TRAILER = "STREAMING-UNSIGNED-PAYLOAD-TRAILER"
EMPTY_SHA256 = hashlib.sha256(b"").hexdigest()
MAX_SKEW = timedelta(minutes=15)


def uri_encode(s: str, encode_slash: bool = True) -> str:
    """AWS canonical URI encoding (unreserved chars per SigV4 spec)."""
    safe = "-_.~" + ("" if encode_slash else "/")
    return urllib.parse.quote(s, safe=safe)


def _hmac(key: bytes, msg: str) -> bytes:
    return hmac.new(key, msg.encode(), hashlib.sha256).digest()


def signing_key(secret: str, date: str, region: str, service: str = "s3") -> bytes:
    k = _hmac(("AWS4" + secret).encode(), date)
    k = _hmac(k, region)
    k = _hmac(k, service)
    return _hmac(k, "aws4_request")


def canonical_query(params: list[tuple[str, str]], skip: set[str] = frozenset()) -> str:
    enc = [
        (uri_encode(k), uri_encode(v))
        for k, v in params
        if k not in skip
    ]
    enc.sort()
    return "&".join(f"{k}={v}" for k, v in enc)


def canonical_request(
    method: str,
    raw_path: str,
    query: list[tuple[str, str]],
    headers: dict[str, str],
    signed_headers: list[str],
    payload_hash: str,
    skip_query: set[str] = frozenset(),
) -> str:
    canon_headers = "".join(
        f"{h}:{' '.join(headers.get(h, '').split())}\n" for h in signed_headers
    )
    return "\n".join(
        [
            method,
            raw_path or "/",
            canonical_query(query, skip_query),
            canon_headers,
            ";".join(signed_headers),
            payload_hash,
        ]
    )


def string_to_sign(amz_date: str, scope: str, canon_req: str) -> str:
    return "\n".join(
        [SIGN_V4_ALGORITHM, amz_date, scope, hashlib.sha256(canon_req.encode()).hexdigest()]
    )


@dataclass
class ParsedAuth:
    access_key: str
    scope_date: str
    region: str
    service: str
    signed_headers: list[str]
    signature: str

    @property
    def scope(self) -> str:
        return f"{self.scope_date}/{self.region}/{self.service}/aws4_request"


def parse_auth_header(value: str) -> ParsedAuth:
    """Parse 'AWS4-HMAC-SHA256 Credential=..., SignedHeaders=..., Signature=...'."""
    if not value.startswith(SIGN_V4_ALGORITHM):
        raise s3err.SignatureDoesNotMatch
    rest = value[len(SIGN_V4_ALGORITHM) :].strip()
    fields: dict[str, str] = {}
    for part in rest.split(","):
        part = part.strip()
        if "=" not in part:
            raise s3err.MissingFields
        k, v = part.split("=", 1)
        fields[k] = v
    try:
        cred = fields["Credential"].split("/")
        if len(cred) < 5 or cred[-1] != "aws4_request":
            raise s3err.AuthorizationHeaderMalformed
        # access keys may contain '/': scope is always the last 4 fields
        access_key = "/".join(cred[:-4])
        return ParsedAuth(
            access_key=access_key,
            scope_date=cred[-4],
            region=cred[-3],
            service=cred[-2],
            signed_headers=fields["SignedHeaders"].split(";"),
            signature=fields["Signature"],
        )
    except KeyError:
        raise s3err.MissingFields from None


class SigV4Verifier:
    """Verifies SigV4 requests against a credential lookup."""

    def __init__(self, lookup_secret, region: str = "us-east-1"):
        self.lookup_secret = lookup_secret  # access_key -> secret | None
        self.region = region

    def _check_date(self, amz_date: str) -> None:
        try:
            t = datetime.strptime(amz_date, "%Y%m%dT%H%M%SZ").replace(tzinfo=timezone.utc)
        except ValueError:
            raise s3err.AccessDenied from None
        if abs(datetime.now(timezone.utc) - t) > MAX_SKEW:
            raise s3err.RequestTimeTooSkewed

    def verify_header_auth(
        self,
        method: str,
        raw_path: str,
        query: list[tuple[str, str]],
        headers: dict[str, str],
        payload_hash: str,
    ) -> str:
        """Verify Authorization-header SigV4; returns the access key."""
        auth = parse_auth_header(headers.get("authorization", ""))
        secret = self.lookup_secret(auth.access_key)
        if secret is None:
            raise s3err.InvalidAccessKeyId
        amz_date = headers.get("x-amz-date") or headers.get("date", "")
        self._check_date(amz_date)
        if not amz_date.startswith(auth.scope_date):
            raise s3err.SignatureDoesNotMatch
        canon = canonical_request(
            method, raw_path, query, headers, auth.signed_headers, payload_hash
        )
        sts = string_to_sign(amz_date, auth.scope, canon)
        key = signing_key(secret, auth.scope_date, auth.region, auth.service)
        want = hmac.new(key, sts.encode(), hashlib.sha256).hexdigest()
        if not hmac.compare_digest(want, auth.signature):
            raise s3err.SignatureDoesNotMatch
        return auth.access_key

    def verify_presigned(
        self,
        method: str,
        raw_path: str,
        query: list[tuple[str, str]],
        headers: dict[str, str],
    ) -> str:
        """Verify X-Amz-* query-string presigned auth; returns access key."""
        q = dict(query)
        try:
            if q.get("X-Amz-Algorithm") != SIGN_V4_ALGORITHM:
                raise s3err.SignatureDoesNotMatch
            cred = q["X-Amz-Credential"].split("/")
            amz_date = q["X-Amz-Date"]
            expires = int(q.get("X-Amz-Expires", "604800"))
            signed_headers = q["X-Amz-SignedHeaders"].split(";")
            signature = q["X-Amz-Signature"]
        except KeyError:
            raise s3err.MissingFields from None
        except ValueError:
            raise s3err.InvalidArgument from None
        if len(cred) < 5 or cred[-1] != "aws4_request":
            raise s3err.AuthorizationHeaderMalformed
        if not 1 <= expires <= 604800:
            # reference enforces 1s..7d (cmd/signature-v4-parser.go)
            raise s3err.AuthorizationQueryParametersError
        access_key = "/".join(cred[:-4])
        scope_date, region, service = cred[-4], cred[-3], cred[-2]
        secret = self.lookup_secret(access_key)
        if secret is None:
            raise s3err.InvalidAccessKeyId
        try:
            t = datetime.strptime(amz_date, "%Y%m%dT%H%M%SZ").replace(tzinfo=timezone.utc)
        except ValueError:
            raise s3err.AccessDenied from None
        if datetime.now(timezone.utc) > t + timedelta(seconds=expires):
            raise s3err.ExpiredPresignRequest
        payload_hash = q.get("X-Amz-Content-Sha256", UNSIGNED_PAYLOAD)
        scope = f"{scope_date}/{region}/{service}/aws4_request"
        canon = canonical_request(
            method,
            raw_path,
            query,
            headers,
            signed_headers,
            payload_hash,
            skip_query={"X-Amz-Signature"},
        )
        sts = string_to_sign(amz_date, scope, canon)
        key = signing_key(secret, scope_date, region, service)
        want = hmac.new(key, sts.encode(), hashlib.sha256).hexdigest()
        if not hmac.compare_digest(want, signature):
            raise s3err.SignatureDoesNotMatch
        return access_key


def presign_url(
    method: str,
    url: str,
    access_key: str,
    secret_key: str,
    region: str = "us-east-1",
    expires: int = 604800,
    service: str = "s3",
) -> str:
    """Client-side: produce a presigned (query-auth) URL for ``url``."""
    u = urllib.parse.urlsplit(url)
    amz_date = datetime.now(timezone.utc).strftime("%Y%m%dT%H%M%SZ")
    scope_date = amz_date[:8]
    scope = f"{scope_date}/{region}/{service}/aws4_request"
    q = urllib.parse.parse_qsl(u.query, keep_blank_values=True)
    q += [
        ("X-Amz-Algorithm", SIGN_V4_ALGORITHM),
        ("X-Amz-Credential", f"{access_key}/{scope}"),
        ("X-Amz-Date", amz_date),
        ("X-Amz-Expires", str(expires)),
        ("X-Amz-SignedHeaders", "host"),
    ]
    canon = canonical_request(
        method, u.path or "/", q, {"host": u.netloc}, ["host"], UNSIGNED_PAYLOAD
    )
    sts = string_to_sign(amz_date, scope, canon)
    key = signing_key(secret_key, scope_date, region, service)
    sig = hmac.new(key, sts.encode(), hashlib.sha256).hexdigest()
    q.append(("X-Amz-Signature", sig))
    return urllib.parse.urlunsplit(
        (u.scheme, u.netloc, u.path, urllib.parse.urlencode(q), "")
    )


def sign_request(
    method: str,
    url: str,
    headers: dict[str, str],
    payload: bytes | str,
    access_key: str,
    secret_key: str,
    region: str = "us-east-1",
    amz_date: str | None = None,
) -> dict[str, str]:
    """Client-side signer (for tests/SDK): returns headers incl. Authorization."""
    parsed = urllib.parse.urlsplit(url)
    if amz_date is None:
        amz_date = datetime.now(timezone.utc).strftime("%Y%m%dT%H%M%SZ")
    scope_date = amz_date[:8]
    out = {k.lower(): v for k, v in headers.items()}
    out["host"] = parsed.netloc
    out["x-amz-date"] = amz_date
    if isinstance(payload, str):
        payload_hash = payload  # pre-computed / UNSIGNED-PAYLOAD
    else:
        payload_hash = hashlib.sha256(payload).hexdigest()
    out["x-amz-content-sha256"] = payload_hash
    signed_headers = sorted(out.keys())
    query = urllib.parse.parse_qsl(parsed.query, keep_blank_values=True)
    raw_path = urllib.parse.quote(urllib.parse.unquote(parsed.path), safe="/-_.~")
    canon = canonical_request(method, raw_path, query, out, signed_headers, payload_hash)
    scope = f"{scope_date}/{region}/s3/aws4_request"
    sts = string_to_sign(amz_date, scope, canon)
    key = signing_key(secret_key, scope_date, region)
    sig = hmac.new(key, sts.encode(), hashlib.sha256).hexdigest()
    out["authorization"] = (
        f"{SIGN_V4_ALGORITHM} Credential={access_key}/{scope}, "
        f"SignedHeaders={';'.join(signed_headers)}, Signature={sig}"
    )
    return out


# -- Signature V2 (deprecated AWS auth, reference cmd/signature-v2.go) -------

SIGN_V2_ALGORITHM = "AWS"

# sub-resources included in the V2 canonicalized resource, pre-sorted
V2_RESOURCE_LIST = [
    "acl", "cors", "delete", "encryption", "legal-hold", "lifecycle",
    "location", "logging", "notification", "partNumber", "policy",
    "requestPayment", "response-cache-control", "response-content-disposition",
    "response-content-encoding", "response-content-language",
    "response-content-type", "response-expires", "retention", "select",
    "select-type", "tagging", "torrent", "uploadId", "uploads", "versionId",
    "versioning", "versions", "website",
]


def _canonicalized_amz_headers_v2(headers: dict[str, str]) -> str:
    amz: dict[str, list[str]] = {}
    for k, v in headers.items():
        lk = k.lower()
        if lk.startswith("x-amz-"):
            amz.setdefault(lk, []).append(v.strip())
    return "\n".join(f"{k}:{','.join(vs)}" for k, vs in sorted(amz.items()))


def _canonicalized_resource_v2(encoded_resource: str, encoded_query: str) -> str:
    keyval: dict[str, str] = {}
    for q in encoded_query.split("&"):
        if not q:
            continue
        k, _, v = q.partition("=")
        keyval[k] = v
    parts = []
    for key in V2_RESOURCE_LIST:
        if key in keyval:
            parts.append(f"{key}={keyval[key]}" if keyval[key] else key)
    return encoded_resource + (f"?{'&'.join(parts)}" if parts else "")


def string_to_sign_v2(
    method: str,
    encoded_resource: str,
    encoded_query: str,
    headers: dict[str, str],
    expires: str = "",
) -> str:
    """V2 StringToSign (expires set -> presigned form, Date replaced)."""
    canonical_headers = _canonicalized_amz_headers_v2(headers)
    if canonical_headers:
        canonical_headers += "\n"
    date = expires or headers.get("date", "")
    return (
        "\n".join(
            [
                method,
                headers.get("content-md5", ""),
                headers.get("content-type", ""),
                date,
                canonical_headers,
            ]
        )
        + _canonicalized_resource_v2(encoded_resource, encoded_query)
    )


def _v2_signature(secret: str, sts: str) -> str:
    import base64

    return base64.b64encode(
        hmac.new(secret.encode(), sts.encode("utf-8"), hashlib.sha1).digest()
    ).decode()


def _unescape_query_v2(raw_query: str) -> str:
    """Decode each &-separated element (reference unescapeQueries: split
    FIRST, then QueryUnescape each element) — V2 canonicalization works
    on decoded values."""
    return "&".join(
        urllib.parse.unquote_plus(q) for q in raw_query.split("&") if q
    )


def sign_request_v2(
    method: str,
    url: str,
    headers: dict[str, str],
    access_key: str,
    secret_key: str,
) -> dict[str, str]:
    """Client-side V2 signer (tests / legacy SDK compatibility)."""
    from email.utils import formatdate

    parsed = urllib.parse.urlsplit(url)
    out = {k.lower(): v for k, v in headers.items()}
    out.setdefault("date", formatdate(usegmt=True))
    out["host"] = parsed.netloc
    sts = string_to_sign_v2(
        method, parsed.path, _unescape_query_v2(parsed.query), out
    )
    out["authorization"] = (
        f"{SIGN_V2_ALGORITHM} {access_key}:{_v2_signature(secret_key, sts)}"
    )
    return out


def presign_url_v2(
    method: str, url: str, access_key: str, secret_key: str, expires_in: int
) -> str:
    import time as _time

    parsed = urllib.parse.urlsplit(url)
    expires = str(int(_time.time()) + expires_in)
    sts = string_to_sign_v2(
        method, parsed.path, _unescape_query_v2(parsed.query), {}, expires
    )
    q = {
        "AWSAccessKeyId": access_key,
        "Expires": expires,
        "Signature": _v2_signature(secret_key, sts),
    }
    sep = "&" if parsed.query else "?"
    return f"{url}{sep}{urllib.parse.urlencode(q)}"


class SigV2Verifier:
    """Server-side V2 verification (header + presigned query forms)."""

    def __init__(self, lookup_secret):
        self.lookup_secret = lookup_secret

    def verify_header(
        self, method: str, raw_path: str, raw_query: str, headers: dict[str, str]
    ) -> str:
        auth = headers.get("authorization", "")
        if not auth.startswith(f"{SIGN_V2_ALGORITHM} "):
            raise s3err.AccessDenied
        try:
            access_key, got = auth[len(SIGN_V2_ALGORITHM) + 1 :].split(":", 1)
        except ValueError:
            raise s3err.InvalidArgument from None
        secret = self.lookup_secret(access_key)
        if secret is None:
            raise s3err.InvalidAccessKeyId
        if not headers.get("date") and not headers.get("x-amz-date"):
            raise s3err.MissingFields
        sts = string_to_sign_v2(
            method, raw_path, _unescape_query_v2(raw_query), headers
        )
        if not hmac.compare_digest(_v2_signature(secret, sts), got):
            raise s3err.SignatureDoesNotMatch
        return access_key

    def verify_presigned(
        self, method: str, raw_path: str, raw_query: str,
        headers: dict[str, str] | None = None,
    ) -> str:
        """Presigned V2: the string-to-sign includes the request headers
        (the reference's preSignatureV2 passes r.Header) with Expires in
        the Date slot; auth params are filtered out of the query."""
        import time as _time

        access_key = signature = expires = ""
        filtered = []
        for q in raw_query.split("&"):
            if not q:
                continue
            uq = urllib.parse.unquote_plus(q)
            k, has_eq, v = uq.partition("=")
            if k == "AWSAccessKeyId":
                access_key = v
            elif k == "Signature":
                signature = v
            elif k == "Expires":
                expires = v
            else:
                filtered.append(uq if has_eq or not k else k)
        if not access_key or not signature or not expires:
            raise s3err.MissingFields
        secret = self.lookup_secret(access_key)
        if secret is None:
            raise s3err.InvalidAccessKeyId
        try:
            if int(expires) < _time.time():
                raise s3err.ExpiredPresignRequest
        except ValueError:
            raise s3err.InvalidArgument from None
        sts = string_to_sign_v2(
            method, raw_path, "&".join(filtered), headers or {}, expires
        )
        if not hmac.compare_digest(_v2_signature(secret, sts), signature):
            raise s3err.SignatureDoesNotMatch
        return access_key
