"""S3 API error codes + XML error responses.

Mirrors the reference's APIError table (cmd/api-errors.go) for the codes the
framework serves; same XML wire shape S3 clients parse.
"""

from __future__ import annotations

from dataclasses import dataclass
from xml.sax.saxutils import escape


@dataclass(frozen=True)
class APIError(Exception):
    code: str
    description: str
    http_status: int

    def to_xml(self, resource: str = "", request_id: str = "") -> bytes:
        return (
            '<?xml version="1.0" encoding="UTF-8"?>'
            f"<Error><Code>{escape(self.code)}</Code>"
            f"<Message>{escape(self.description)}</Message>"
            f"<Resource>{escape(resource)}</Resource>"
            f"<RequestId>{escape(request_id)}</RequestId>"
            "</Error>"
        ).encode()


ERR_NONE = None

AccessDenied = APIError("AccessDenied", "Access Denied.", 403)
BadDigest = APIError("BadDigest", "The Content-Md5 you specified did not match what we received.", 400)
EntityTooLarge = APIError("EntityTooLarge", "Your proposed upload exceeds the maximum allowed object size.", 400)
IncompleteBody = APIError("IncompleteBody", "You did not provide the number of bytes specified by the Content-Length HTTP header.", 400)
InternalError = APIError("InternalError", "We encountered an internal error, please try again.", 500)
InvalidAccessKeyId = APIError("InvalidAccessKeyId", "The Access Key Id you provided does not exist in our records.", 403)
InvalidArgument = APIError("InvalidArgument", "Invalid Argument", 400)
InvalidBucketName = APIError("InvalidBucketName", "The specified bucket is not valid.", 400)
InvalidDigest = APIError("InvalidDigest", "The Content-Md5 you specified is not valid.", 400)
InvalidRange = APIError("InvalidRange", "The requested range is not satisfiable", 416)
NoSuchWebsiteConfiguration = APIError(
    "NoSuchWebsiteConfiguration",
    "The specified bucket does not have a website configuration", 404,
)
OwnershipControlsNotFoundError = APIError(
    "OwnershipControlsNotFoundError",
    "The bucket ownership controls were not found", 404,
)
InvalidTag = APIError(
    "InvalidTag", "The TagKey or TagValue you have provided is invalid", 400
)
InvalidCopyDest = APIError(
    "InvalidRequest",
    "This copy request is illegal because it is trying to copy an object "
    "to itself without changing the object's metadata, storage class, "
    "website redirect location or encryption attributes.",
    400,
)
MalformedXML = APIError("MalformedXML", "The XML you provided was not well-formed or did not validate against our published schema.", 400)
MissingContentLength = APIError("MissingContentLength", "You must provide the Content-Length HTTP header.", 411)
NoSuchBucket = APIError("NoSuchBucket", "The specified bucket does not exist", 404)
NoSuchKey = APIError("NoSuchKey", "The specified key does not exist.", 404)
NoSuchVersion = APIError("NoSuchVersion", "The specified version does not exist.", 404)
NoSuchUpload = APIError("NoSuchUpload", "The specified multipart upload does not exist. The upload ID may be invalid, or the upload may have been aborted or completed.", 404)
NotImplemented_ = APIError("NotImplemented", "A header you provided implies functionality that is not implemented", 501)
PreconditionFailed = APIError("PreconditionFailed", "At least one of the pre-conditions you specified did not hold", 412)
NotModified = APIError("NotModified", "Not Modified", 304)
SignatureDoesNotMatch = APIError("SignatureDoesNotMatch", "The request signature we calculated does not match the signature you provided. Check your key and signing method.", 403)
MethodNotAllowed = APIError("MethodNotAllowed", "The specified method is not allowed against this resource.", 405)
BucketNotEmpty = APIError("BucketNotEmpty", "The bucket you tried to delete is not empty", 409)
InvalidBucketState = APIError("InvalidBucketState", "The request is not valid with the current state of the bucket.", 409)
BucketAlreadyOwnedByYou = APIError("BucketAlreadyOwnedByYou", "Your previous request to create the named bucket succeeded and you already own it.", 409)
BucketAlreadyExists = APIError("BucketAlreadyExists", "The requested bucket name is not available. The bucket namespace is shared by all users of the system. Please select a different name and try again.", 409)
InvalidPart = APIError("InvalidPart", "One or more of the specified parts could not be found.  The part may not have been uploaded, or the specified entity tag may not match the part's entity tag.", 400)
InvalidPartOrder = APIError("InvalidPartOrder", "The list of parts was not in ascending order. The parts list must be specified in order by part number.", 400)
InvalidMaxKeys = APIError("InvalidMaxKeys", "Argument maxKeys must be an integer between 0 and 2147483647", 400)
AuthorizationHeaderMalformed = APIError("AuthorizationHeaderMalformed", "The authorization header is malformed; the region is wrong.", 400)
RequestTimeTooSkewed = APIError("RequestTimeTooSkewed", "The difference between the request time and the server's time is too large.", 403)
ExpiredPresignRequest = APIError("ExpiredPresignRequest", "Request has expired", 403)
MissingFields = APIError("MissingFields", "Missing fields in request.", 400)
AuthorizationQueryParametersError = APIError("AuthorizationQueryParametersError", "X-Amz-Expires must be between 1 and 604800 seconds", 400)
MalformedPolicy = APIError("MalformedPolicy", "Policy has invalid resource.", 400)
InvalidObjectState = APIError("InvalidObjectState", "The operation is not valid for the current state of the object.", 403)
XAmzContentSHA256Mismatch = APIError("XAmzContentSHA256Mismatch", "The provided 'x-amz-content-sha256' header does not match what was computed.", 400)
NoSuchBucketPolicy = APIError("NoSuchBucketPolicy", "The bucket policy does not exist", 404)
NoSuchTagSet = APIError("NoSuchTagSet", "The TagSet does not exist", 404)
NoSuchLifecycleConfiguration = APIError("NoSuchLifecycleConfiguration", "The lifecycle configuration does not exist", 404)
ObjectLockConfigurationNotFoundError = APIError("ObjectLockConfigurationNotFoundError", "Object Lock configuration does not exist for this bucket", 404)
ServerSideEncryptionConfigurationNotFoundError = APIError("ServerSideEncryptionConfigurationNotFoundError", "The server side encryption configuration was not found", 404)
NoSuchCORSConfiguration = APIError("NoSuchCORSConfiguration", "The CORS configuration does not exist", 404)
ReplicationConfigurationNotFoundError = APIError("ReplicationConfigurationNotFoundError", "The replication configuration was not found", 404)
NotificationNotFound = APIError("NoSuchConfiguration", "The specified configuration does not exist.", 404)
AdminBucketQuotaExceeded = APIError(
    "XMinioAdminBucketQuotaExceeded", "Bucket quota exceeded", 400
)
SlowDown = APIError(
    "SlowDown",
    "Resource requested is unreadable, please reduce your request rate",
    503,
)
