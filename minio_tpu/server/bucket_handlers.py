"""Bucket-level S3 handlers: create/delete/head, versioning, location,
sub-resource get/put/delete, ACL, listing (V1/V2/versions), events.

Split from app.py (the reference's cmd/bucket-handlers.go,
bucket-policy-handlers.go, bucket-listobjects-handlers.go)."""

from __future__ import annotations

import hashlib
import urllib.parse
import xml.etree.ElementTree as ET
from xml.sax.saxutils import escape

from aiohttp import web

from ..erasure import listing
from . import s3err
from .handler_utils import (
    BUCKET_NAME_RE,
    _iso8601,
)


class BucketHandlersMixin:
    async def list_buckets(self, request) -> web.Response:
        buckets = await self._run(self.store.list_buckets)
        items = "".join(
            f"<Bucket><Name>{escape(b.name)}</Name>"
            f"<CreationDate>{_iso8601(b.created)}</CreationDate></Bucket>"
            for b in buckets
        )
        xml = (
            '<?xml version="1.0" encoding="UTF-8"?>'
            '<ListAllMyBucketsResult xmlns="http://s3.amazonaws.com/doc/2006-03-01/">'
            "<Owner><ID>minio-tpu</ID><DisplayName>minio-tpu</DisplayName></Owner>"
            f"<Buckets>{items}</Buckets></ListAllMyBucketsResult>"
        )
        return web.Response(body=xml.encode(), content_type="application/xml")

    # -- bucket --------------------------------------------------------------

    async def put_bucket(self, request, bucket: str) -> web.Response:
        if not BUCKET_NAME_RE.match(bucket) or ".." in bucket:
            raise s3err.InvalidBucketName
        if bucket == "minio":
            # reserved (reference isReservedOrInvalidBucket): /minio/* is
            # the control plane, and a user bucket by that name would ride
            # its QoS-exempt routing
            raise s3err.InvalidBucketName
        await self._run(self.store.make_bucket, bucket)
        lock_enabled = request.headers.get("x-amz-bucket-object-lock-enabled", "") == "true"
        if lock_enabled:
            bm = self.buckets.get(bucket)
            bm.versioning = True
            bm.object_lock = "<ObjectLockConfiguration><ObjectLockEnabled>Enabled</ObjectLockEnabled></ObjectLockConfiguration>"
            await self._run(self.buckets.set, bucket, bm)
        if self.site.enabled:
            await self._run(self.site.sync_bucket_create, bucket)
        return web.Response(status=200, headers={"Location": f"/{bucket}"})

    async def head_bucket(self, request, bucket: str) -> web.Response:
        if not await self._run(self.store.bucket_exists, bucket):
            return web.Response(status=404)
        return web.Response(status=200)

    async def delete_bucket(self, request, bucket: str) -> web.Response:
        force = request.headers.get("x-minio-force-delete", "") == "true"
        # refuse non-empty buckets (cheap check: any object at all)
        res = await self._run(
            listing.list_objects, self.store, bucket, "", "", "", 1, True
        )
        if (res.objects or res.prefixes) and not force:
            raise s3err.BucketNotEmpty
        await self._run(self.store.delete_bucket, bucket, force or bool(res.objects))
        self.buckets.drop(bucket)
        if self.site.enabled:
            await self._run(self.site.sync_bucket_delete, bucket)
        return web.Response(status=204)

    async def get_bucket_location(self, request, bucket: str) -> web.Response:
        if not await self._run(self.store.bucket_exists, bucket):
            raise s3err.NoSuchBucket
        xml = (
            '<?xml version="1.0" encoding="UTF-8"?>'
            f'<LocationConstraint xmlns="http://s3.amazonaws.com/doc/2006-03-01/">{self.region}</LocationConstraint>'
        )
        return web.Response(body=xml.encode(), content_type="application/xml")

    async def get_bucket_versioning(self, request, bucket: str) -> web.Response:
        if not await self._run(self.store.bucket_exists, bucket):
            raise s3err.NoSuchBucket
        bm = self.buckets.get(bucket)
        inner = ""
        if bm.versioning:
            inner = "<Status>Enabled</Status>"
        elif bm.versioning_suspended:
            inner = "<Status>Suspended</Status>"
        xml = (
            '<?xml version="1.0" encoding="UTF-8"?>'
            f'<VersioningConfiguration xmlns="http://s3.amazonaws.com/doc/2006-03-01/">{inner}</VersioningConfiguration>'
        )
        return web.Response(body=xml.encode(), content_type="application/xml")

    async def put_bucket_versioning(self, request, bucket: str, body: bytes) -> web.Response:
        if not await self._run(self.store.bucket_exists, bucket):
            raise s3err.NoSuchBucket
        try:
            root = ET.fromstring(body)
            status = ""
            for el in root.iter():
                if el.tag.endswith("Status"):
                    status = el.text or ""
        except ET.ParseError:
            raise s3err.MalformedXML from None
        bm = self.buckets.get(bucket)
        if bm.object_lock and status != "Enabled":
            # AWS: versioning cannot be suspended on object-lock buckets
            # (retention would otherwise guard nothing)
            raise s3err.InvalidBucketState
        bm.versioning = status == "Enabled"
        bm.versioning_suspended = status == "Suspended"
        await self._run(self.buckets.set, bucket, bm)
        return web.Response(status=200)

    async def get_bucket_simple(self, request, bucket, attr, missing_err) -> web.Response:
        if not await self._run(self.store.bucket_exists, bucket):
            raise s3err.NoSuchBucket
        bm = self.buckets.get(bucket)
        val = getattr(bm, attr)
        if not val:
            if missing_err is None:
                val = '<?xml version="1.0" encoding="UTF-8"?><NotificationConfiguration/>'
            else:
                raise missing_err
        if isinstance(val, dict):
            import json

            return web.Response(body=json.dumps(val).encode(), content_type="application/json")
        return web.Response(body=val.encode() if isinstance(val, str) else val,
                            content_type="application/xml")

    async def listen_events(self, request, bucket: str) -> web.StreamResponse:
        """Real-time event firehose (reference
        cmd/listen-notification-handlers.go)."""
        import asyncio as _asyncio
        import json as _json
        import queue as _queue

        q = request.rel_url.query
        events = [e for e in q.get("events", "").split(",") if e]
        ent = self.notifier.subscribe(
            bucket, q.get("prefix", ""), q.get("suffix", ""), events
        )
        resp = web.StreamResponse(headers={"Content-Type": "application/json"})
        await resp.prepare(request)
        loop = _asyncio.get_running_loop()
        try:
            while True:
                try:
                    rec = await loop.run_in_executor(
                        self._longpoll_pool, ent[0].get, True, 1.0
                    )
                except _queue.Empty:
                    await resp.write(b" \n")  # keep-alive, like the reference
                    continue
                await resp.write(
                    _json.dumps({"Records": [rec]}).encode() + b"\n"
                )
        except (ConnectionResetError, _asyncio.CancelledError):
            pass
        finally:
            self.notifier.unsubscribe(ent)
        return resp

    async def put_bucket_simple(self, request, bucket, attr, body: bytes) -> web.Response:
        if not await self._run(self.store.bucket_exists, bucket):
            raise s3err.NoSuchBucket
        bm = self.buckets.get(bucket)
        if attr == "notification":
            try:
                self.notifier.validate_config(body.decode())
            except ValueError:
                raise s3err.InvalidArgument from None
            except ET.ParseError:
                raise s3err.MalformedXML from None
        if attr == "lifecycle":
            from ..ilm.lifecycle import validate_lifecycle

            try:
                validate_lifecycle(body.decode())
            except (ValueError, ET.ParseError):
                raise s3err.MalformedXML from None
        if attr == "cors":
            from . import cors as corsmod

            try:
                corsmod.parse_bucket_cors(body.decode())
            except (ValueError, ET.ParseError):
                raise s3err.MalformedXML from None
        if attr == "policy":
            import json

            from ..iam.policy import Policy

            try:
                doc = json.loads(body)
                pol = Policy.from_dict(doc)
            except ValueError:
                raise s3err.MalformedXML from None
            except (AttributeError, TypeError):
                # valid JSON but not policy-shaped (e.g. a list or scalar)
                raise s3err.MalformedPolicy from None
            # resource policies must name a Resource per statement — an
            # omitted Resource would otherwise match every object
            # (reference validates this at PutBucketPolicy time)
            if not pol.statements or any(not s.resources for s in pol.statements):
                raise s3err.MalformedPolicy
            setattr(bm, attr, doc)
        else:
            setattr(bm, attr, body.decode())
        await self._run(self.buckets.set, bucket, bm)
        return web.Response(status=200 if attr != "policy" else 204)

    # -- ACL / misc compat surface (reference cmd/acl-handlers.go,
    # bucket-handlers.go requestPayment/logging/policyStatus) ----------------

    def _owner_id(self) -> str:
        # deterministic canonical owner id for this deployment (the
        # reference serves a fixed owner id + "minio" display name)
        return hashlib.sha256(self.root_user.encode()).hexdigest()

    def _owner_xml(self) -> str:
        return (
            f"<Owner><ID>{self._owner_id()}</ID>"
            f"<DisplayName>minio</DisplayName></Owner>"
        )

    async def get_acl(self, request, bucket: str, key: str) -> web.Response:
        """Canned-ACL world: everything is owner FULL_CONTROL (reference
        GetBucketACLHandler / GetObjectACLHandler)."""
        if not await self._run(self.store.bucket_exists, bucket):
            raise s3err.NoSuchBucket
        if key:
            # missing objects must 404, same as a GET
            await self._run(
                self.store.get_object_info, bucket,
                listing.encode_dir_object(key),
                request.rel_url.query.get("versionId", ""),
            )
        owner = self._owner_xml()
        oid = self._owner_id()
        xml = (
            '<?xml version="1.0" encoding="UTF-8"?>'
            '<AccessControlPolicy xmlns="http://s3.amazonaws.com/doc/2006-03-01/">'
            f"{owner}<AccessControlList><Grant>"
            '<Grantee xmlns:xsi="http://www.w3.org/2001/XMLSchema-instance" '
            'xsi:type="CanonicalUser">'
            f"<ID>{oid}</ID><DisplayName>minio</DisplayName></Grantee>"
            "<Permission>FULL_CONTROL</Permission></Grant></AccessControlList>"
            "</AccessControlPolicy>"
        )
        return web.Response(body=xml.encode(), content_type="application/xml")

    async def put_acl(self, request, bucket: str, key: str, body: bytes) -> web.Response:
        """Only the private canned ACL (or an equivalent single
        FULL_CONTROL grant document) is accepted; anything else is
        NotImplemented — bucket policies are the access-control system
        (reference PutBucketACLHandler/PutObjectACLHandler)."""
        if not await self._run(self.store.bucket_exists, bucket):
            raise s3err.NoSuchBucket
        if key:
            # a missing object must 404, matching the GET side
            await self._run(
                self.store.get_object_info, bucket,
                listing.encode_dir_object(key),
                request.rel_url.query.get("versionId", ""),
            )
        canned = request.headers.get("x-amz-acl", "")
        if canned:
            if canned != "private":
                raise s3err.NotImplemented_
            return web.Response(status=200)
        try:
            root = ET.fromstring(body)
        except ET.ParseError:
            raise s3err.MalformedXML from None
        grants = [el for el in root.iter() if el.tag.split("}")[-1] == "Grant"]
        if len(grants) != 1:
            raise s3err.NotImplemented_
        perm = next(
            (el.text for el in grants[0] if el.tag.split("}")[-1] == "Permission"),
            "",
        )
        if perm != "FULL_CONTROL":
            raise s3err.NotImplemented_
        return web.Response(status=200)

    async def get_policy_status(self, request, bucket: str) -> web.Response:
        """Whether anonymous requests are allowed by the bucket policy
        (reference GetBucketPolicyStatusHandler)."""
        if not await self._run(self.store.bucket_exists, bucket):
            raise s3err.NoSuchBucket
        bm = self.buckets.get(bucket)
        public = False
        for st in (bm.policy or {}).get("Statement", []):
            principal = st.get("Principal", "")
            aws = principal.get("AWS", "") if isinstance(principal, dict) else principal
            if isinstance(aws, list):
                aws = "*" if "*" in aws else ""
            if st.get("Effect") == "Allow" and aws == "*":
                public = True
        xml = (
            '<?xml version="1.0" encoding="UTF-8"?>'
            '<PolicyStatus xmlns="http://s3.amazonaws.com/doc/2006-03-01/">'
            f"<IsPublic>{'true' if public else 'false'}</IsPublic></PolicyStatus>"
        )
        return web.Response(body=xml.encode(), content_type="application/xml")

    async def get_request_payment(self, request, bucket: str) -> web.Response:
        if not await self._run(self.store.bucket_exists, bucket):
            raise s3err.NoSuchBucket
        xml = (
            '<?xml version="1.0" encoding="UTF-8"?>'
            '<RequestPaymentConfiguration xmlns="http://s3.amazonaws.com/doc/2006-03-01/">'
            "<Payer>BucketOwner</Payer></RequestPaymentConfiguration>"
        )
        return web.Response(body=xml.encode(), content_type="application/xml")

    async def put_request_payment(self, request, bucket: str, body: bytes) -> web.Response:
        if not await self._run(self.store.bucket_exists, bucket):
            raise s3err.NoSuchBucket
        if b"Requester" in body:
            raise s3err.NotImplemented_  # only BucketOwner payment exists
        return web.Response(status=200)

    async def get_bucket_logging(self, request, bucket: str) -> web.Response:
        if not await self._run(self.store.bucket_exists, bucket):
            raise s3err.NoSuchBucket
        # access logging rides the audit/notification planes; the S3 call
        # reports it disabled, like the reference
        xml = (
            '<?xml version="1.0" encoding="UTF-8"?>'
            '<BucketLoggingStatus xmlns="http://s3.amazonaws.com/doc/2006-03-01/" />'
        )
        return web.Response(body=xml.encode(), content_type="application/xml")

    async def delete_bucket_simple(self, request, bucket, sub) -> web.Response:
        attr = {"tagging": "tags", "ownershipControls": "ownership"}.get(sub, sub)
        bm = self.buckets.get(bucket)
        setattr(bm, attr, None if attr != "tags" else {})
        await self._run(self.buckets.set, bucket, bm)
        return web.Response(status=204)

    # -- listing ---------------------------------------------------------------

    async def list_objects(self, request, bucket: str) -> web.Response:
        q = request.rel_url.query
        v2 = q.get("list-type") == "2"
        url_encode = q.get("encoding-type") == "url"
        prefix = q.get("prefix", "")
        delimiter = q.get("delimiter", "")
        try:
            max_keys = int(q.get("max-keys", "1000"))
        except ValueError:
            raise s3err.InvalidMaxKeys from None
        if v2:
            marker = q.get("continuation-token", "") or q.get("start-after", "")
        else:
            marker = q.get("marker", "")
        res = await self._run(
            listing.list_objects, self.store, bucket, prefix, marker, delimiter, max_keys
        )
        def enc(s: str) -> str:
            # encoding-type=url: keys percent-encoded so control chars in
            # names survive XML (reference s3EncodeName)
            return urllib.parse.quote(s, safe="/") if url_encode else escape(s)

        contents = "".join(
            f"<Contents><Key>{enc(o.name)}</Key>"
            f"<LastModified>{_iso8601(o.mod_time)}</LastModified>"
            f'<ETag>"{o.etag}"</ETag><Size>{o.size}</Size>'
            f"<StorageClass>STANDARD</StorageClass></Contents>"
            for o in res.objects
        )
        prefixes = "".join(
            f"<CommonPrefixes><Prefix>{enc(p)}</Prefix></CommonPrefixes>"
            for p in res.prefixes
        )
        common = (
            f"<Name>{escape(bucket)}</Name><Prefix>{enc(prefix)}</Prefix>"
            f"<MaxKeys>{max_keys}</MaxKeys>"
            f"<Delimiter>{escape(delimiter)}</Delimiter>"
            + ("<EncodingType>url</EncodingType>" if url_encode else "")
            + f"<IsTruncated>{'true' if res.is_truncated else 'false'}</IsTruncated>"
        )
        if v2:
            extra = f"<KeyCount>{len(res.objects) + len(res.prefixes)}</KeyCount>"
            if res.is_truncated:
                extra += f"<NextContinuationToken>{enc(res.next_marker)}</NextContinuationToken>"
            xml = (
                '<?xml version="1.0" encoding="UTF-8"?>'
                '<ListBucketResult xmlns="http://s3.amazonaws.com/doc/2006-03-01/">'
                f"{common}{extra}{contents}{prefixes}</ListBucketResult>"
            )
        else:
            extra = ""
            if res.is_truncated:
                extra = f"<NextMarker>{enc(res.next_marker)}</NextMarker>"
            xml = (
                '<?xml version="1.0" encoding="UTF-8"?>'
                '<ListBucketResult xmlns="http://s3.amazonaws.com/doc/2006-03-01/">'
                f"{common}{extra}{contents}{prefixes}</ListBucketResult>"
            )
        return web.Response(body=xml.encode(), content_type="application/xml")

    async def list_object_versions(self, request, bucket: str) -> web.Response:
        q = request.rel_url.query
        prefix = q.get("prefix", "")
        delimiter = q.get("delimiter", "")
        max_keys = int(q.get("max-keys", "1000"))
        marker = q.get("key-marker", "")
        vmarker = q.get("version-id-marker", "")
        res = await self._run(
            listing.list_objects,
            self.store,
            bucket,
            prefix,
            marker,
            delimiter,
            max_keys,
            True,
            vmarker,
        )
        body = []
        for o in res.objects:
            vid = o.version_id or "null"
            tag = "DeleteMarker" if o.delete_marker else "Version"
            entry = (
                f"<{tag}><Key>{escape(o.name)}</Key><VersionId>{vid}</VersionId>"
                f"<IsLatest>{'true' if o.is_latest else 'false'}</IsLatest>"
                f"<LastModified>{_iso8601(o.mod_time)}</LastModified>"
            )
            if not o.delete_marker:
                entry += f'<ETag>"{o.etag}"</ETag><Size>{o.size}</Size><StorageClass>STANDARD</StorageClass>'
            entry += f"</{tag}>"
            body.append(entry)
        prefixes = "".join(
            f"<CommonPrefixes><Prefix>{escape(p)}</Prefix></CommonPrefixes>"
            for p in res.prefixes
        )
        xml = (
            '<?xml version="1.0" encoding="UTF-8"?>'
            '<ListVersionsResult xmlns="http://s3.amazonaws.com/doc/2006-03-01/">'
            f"<Name>{escape(bucket)}</Name><Prefix>{escape(prefix)}</Prefix>"
            f"<MaxKeys>{max_keys}</MaxKeys>"
            f"<IsTruncated>{'true' if res.is_truncated else 'false'}</IsTruncated>"
            f"{''.join(body)}{prefixes}</ListVersionsResult>"
        )
        return web.Response(body=xml.encode(), content_type="application/xml")

    # -- objects ---------------------------------------------------------------
