"""Cluster profiling — the `mc admin profile` analogue.

The reference's ProfileHandler (/root/reference/cmd/admin-handlers.go:1024)
starts CPU/heap/goroutine profiles on EVERY node for a duration and
returns the bundle. The Python equivalents here:

* cpu — a statistical sampler over `sys._current_frames()` (all threads,
  ~100 Hz), emitted as collapsed stacks (flamegraph format). Unlike
  cProfile this sees every thread and adds near-zero overhead to the
  request path.
* mem — tracemalloc top allocation sites over the window.
* threads — one goroutine-dump-style stack listing per thread.

The admin handler runs the local profile and fans out to every cluster
peer in parallel, exactly like the reference's notification-system
fan-out.

On top of the on-demand profilers sits the CONTINUOUS profiler: an
always-on (knob-gated, MINIO_TPU_PROFILE_CONTINUOUS) ~19 Hz sampler
that classifies every thread's stack by owning subsystem and publishes
the counts as the metrics-v3 wall-time-attribution series under
``/api/diag`` — a scrape answers "where does this process actually
spend its time" without anyone having run a profile. 19 Hz (a prime-ish
rate, same idea as Linux perf's default 99 Hz) avoids phase-locking
with 10/20/100 Hz periodic work; at ~50 ms per sample over a handful of
threads the overhead is far below one percent. Counts are mutated and
snapshotted under one lock (the dispatcher-stats snapshot idiom) — the
runtime sanitizer sees no unguarded shared state.
"""

from __future__ import annotations

import os
import sys
import threading
import time
import traceback
from collections import Counter


def sample_cpu(duration: float, hz: float = 100.0) -> str:
    """Collapsed-stack samples of all threads for `duration` seconds."""
    stacks: Counter[str] = Counter()
    me = threading.get_ident()
    deadline = time.monotonic() + duration
    interval = 1.0 / hz
    while time.monotonic() < deadline:
        for tid, frame in sys._current_frames().items():
            if tid == me:
                continue
            parts = []
            f = frame
            while f is not None:
                code = f.f_code
                parts.append(f"{code.co_filename.rsplit('/', 1)[-1]}:{code.co_name}")
                f = f.f_back
            if parts:
                stacks[";".join(reversed(parts))] += 1
        # miniovet: ignore[blocking] -- sampler pacing; the admin profile
        # endpoint runs this whole function in a long-poll executor thread
        time.sleep(interval)
    return "\n".join(f"{s} {n}" for s, n in stacks.most_common()) + "\n"


def sample_mem(duration: float, top: int = 50) -> str:
    """Top allocation sites accumulated over the window (tracemalloc)."""
    import tracemalloc

    started_here = not tracemalloc.is_tracing()
    if started_here:
        tracemalloc.start(10)
    try:
        # miniovet: ignore[blocking] -- tracemalloc accumulation window;
        # runs in a long-poll executor thread like sample_stacks
        time.sleep(duration)
        snap = tracemalloc.take_snapshot()
        lines = []
        for st in snap.statistics("lineno")[:top]:
            lines.append(f"{st.size}B {st.count}x {st.traceback}")
        return "\n".join(lines) + "\n"
    finally:
        if started_here:
            tracemalloc.stop()


def dump_threads() -> str:
    """All-thread stack dump (the goroutine-profile analogue)."""
    out = []
    names = {t.ident: t.name for t in threading.enumerate()}
    for tid, frame in sys._current_frames().items():
        out.append(f"--- thread {tid} ({names.get(tid, '?')}) ---")
        out.append("".join(traceback.format_stack(frame)))
    return "\n".join(out)


PROFILERS = {
    "cpu": lambda dur: sample_cpu(dur),
    "mem": lambda dur: sample_mem(dur),
    "threads": lambda dur: dump_threads(),
}


def run_local(profiler_type: str, duration: float) -> str:
    fn = PROFILERS.get(profiler_type)
    if fn is None:
        raise ValueError(f"unknown profiler {profiler_type!r}")
    return fn(min(duration, 120.0))


def run_cluster(server, profiler_type: str, duration: float) -> dict:
    """Local profile + parallel fan-out to every peer's admin endpoint
    (peers authenticate us the same way any admin client would)."""
    from concurrent.futures import ThreadPoolExecutor

    results: dict[str, dict] = {}
    peers = getattr(server, "peers", []) or []

    def remote(peer: str) -> tuple[str, dict]:
        from ..client import S3Client

        host, _, port = peer.rpartition(":")
        cli = S3Client(
            f"{host}:{port}",
            access_key=server.iam.root_user,
            secret_key=server.iam.root_password,
        )
        r = cli.request(
            "POST",
            "/minio/admin/v3/profile",
            query={
                "profilerType": profiler_type,
                "duration": str(duration),
                "local": "true",  # stop the fan-out from recursing
            },
            timeout=duration + 30,  # a profile sends nothing until done
        )
        if r.status != 200:
            return peer, {"error": f"HTTP {r.status}"}
        import json

        return peer, json.loads(r.body)["nodes"]["local"]

    with ThreadPoolExecutor(max_workers=max(1, len(peers)) + 1) as pool:
        futs = {pool.submit(remote, p): p for p in peers}
        local = pool.submit(run_local, profiler_type, duration)
        for fut, peer in futs.items():
            try:
                name, data = fut.result(timeout=duration + 30)
                results[name] = data
            except Exception as e:  # noqa: BLE001 — a dead peer is a row
                results[peer] = {"error": str(e)}
        results["local"] = {profiler_type: local.result()}
    return {"nodes": results}


# -- continuous wall-time attribution ---------------------------------------

CONTINUOUS_KNOB = "MINIO_TPU_PROFILE_CONTINUOUS"
CONTINUOUS_HZ_KNOB = "MINIO_TPU_PROFILE_CONTINUOUS_HZ"

# first path fragment matched walking a stack innermost-out wins; order
# matters (dispatcher before the generic erasure bucket, listing before
# erasure — listing.py lives inside erasure/)
_SUBSYSTEM_PATTERNS = (
    ("minio_tpu/parallel/", "dispatcher"),
    ("minio_tpu/erasure/listing", "listing"),
    ("minio_tpu/erasure/", "erasure"),
    ("minio_tpu/storage/", "erasure"),
    ("minio_tpu/cache/", "cache"),
    ("minio_tpu/cluster/", "grid"),
    ("minio_tpu/server/admin", "admin"),
    ("minio_tpu/server/", "server"),
    ("minio_tpu/diag/", "diag"),
)

# innermost frames that mean the thread is PARKED, not working — samples
# there get state="waiting" so attribution separates owning-subsystem
# wall time from actual execution
_WAIT_FUNCS = frozenset(
    {"wait", "get", "select", "poll", "accept", "recv", "recv_into",
     "read", "sleep", "acquire", "epoll", "_recv_loop"}
)


def classify_stack(frame) -> tuple[str, str]:
    """(subsystem, state) for one thread's innermost frame."""
    state = "running"
    fn = frame.f_code.co_filename
    if frame.f_code.co_name in _WAIT_FUNCS and "minio_tpu" not in fn:
        state = "waiting"
    f = frame
    while f is not None:
        path = f.f_code.co_filename
        for pat, subsystem in _SUBSYSTEM_PATTERNS:
            if pat in path:
                return subsystem, state
        f = f.f_back
    return "other", state


class ContinuousProfiler:
    """The always-on sampler thread. ``snapshot()`` is the ONLY reader
    and the sampler loop the only writer, both under ``_mu`` — the
    dispatcher-stats snapshot idiom, no unguarded shared Counter."""

    def __init__(self, hz: float = 19.0):
        self.hz = max(1.0, min(hz, 250.0))
        self._mu = threading.Lock()
        self._counts: Counter[tuple[str, str]] = Counter()
        self._samples = 0
        self._stop = threading.Event()
        self._thread: threading.Thread | None = None

    def start(self) -> "ContinuousProfiler":
        self._thread = threading.Thread(
            target=self._loop, daemon=True, name="cont-profiler"
        )
        self._thread.start()
        return self

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=2.0)

    def snapshot(self) -> dict:
        with self._mu:
            return {
                "samples": self._samples,
                "counts": dict(self._counts),
                "hz": self.hz,
            }

    def _loop(self) -> None:
        me = threading.get_ident()
        interval = 1.0 / self.hz
        # Event.wait doubles as the pacing sleep and the stop signal;
        # the dedicated daemon sampler thread never serves requests
        while not self._stop.wait(interval):
            tick: Counter[tuple[str, str]] = Counter()
            for tid, frame in sys._current_frames().items():
                if tid == me:
                    continue
                tick[classify_stack(frame)] += 1
            with self._mu:
                self._counts.update(tick)
                self._samples += 1


def start_continuous_from_env() -> ContinuousProfiler | None:
    """The knob-gated boot hook (server/app.py main): returns a started
    profiler, or None when MINIO_TPU_PROFILE_CONTINUOUS=0."""
    if os.environ.get(CONTINUOUS_KNOB, "1") == "0":
        return None
    try:
        hz = float(os.environ.get(CONTINUOUS_HZ_KNOB, "19") or 19.0)
    except ValueError:
        hz = 19.0
    return ContinuousProfiler(hz).start()
