"""Cluster profiling — the `mc admin profile` analogue.

The reference's ProfileHandler (/root/reference/cmd/admin-handlers.go:1024)
starts CPU/heap/goroutine profiles on EVERY node for a duration and
returns the bundle. The Python equivalents here:

* cpu — a statistical sampler over `sys._current_frames()` (all threads,
  ~100 Hz), emitted as collapsed stacks (flamegraph format). Unlike
  cProfile this sees every thread and adds near-zero overhead to the
  request path.
* mem — tracemalloc top allocation sites over the window.
* threads — one goroutine-dump-style stack listing per thread.

The admin handler runs the local profile and fans out to every cluster
peer in parallel, exactly like the reference's notification-system
fan-out.
"""

from __future__ import annotations

import sys
import threading
import time
import traceback
from collections import Counter


def sample_cpu(duration: float, hz: float = 100.0) -> str:
    """Collapsed-stack samples of all threads for `duration` seconds."""
    stacks: Counter[str] = Counter()
    me = threading.get_ident()
    deadline = time.monotonic() + duration
    interval = 1.0 / hz
    while time.monotonic() < deadline:
        for tid, frame in sys._current_frames().items():
            if tid == me:
                continue
            parts = []
            f = frame
            while f is not None:
                code = f.f_code
                parts.append(f"{code.co_filename.rsplit('/', 1)[-1]}:{code.co_name}")
                f = f.f_back
            if parts:
                stacks[";".join(reversed(parts))] += 1
        # miniovet: ignore[blocking] -- sampler pacing; the admin profile
        # endpoint runs this whole function in a long-poll executor thread
        time.sleep(interval)
    return "\n".join(f"{s} {n}" for s, n in stacks.most_common()) + "\n"


def sample_mem(duration: float, top: int = 50) -> str:
    """Top allocation sites accumulated over the window (tracemalloc)."""
    import tracemalloc

    started_here = not tracemalloc.is_tracing()
    if started_here:
        tracemalloc.start(10)
    try:
        # miniovet: ignore[blocking] -- tracemalloc accumulation window;
        # runs in a long-poll executor thread like sample_stacks
        time.sleep(duration)
        snap = tracemalloc.take_snapshot()
        lines = []
        for st in snap.statistics("lineno")[:top]:
            lines.append(f"{st.size}B {st.count}x {st.traceback}")
        return "\n".join(lines) + "\n"
    finally:
        if started_here:
            tracemalloc.stop()


def dump_threads() -> str:
    """All-thread stack dump (the goroutine-profile analogue)."""
    out = []
    names = {t.ident: t.name for t in threading.enumerate()}
    for tid, frame in sys._current_frames().items():
        out.append(f"--- thread {tid} ({names.get(tid, '?')}) ---")
        out.append("".join(traceback.format_stack(frame)))
    return "\n".join(out)


PROFILERS = {
    "cpu": lambda dur: sample_cpu(dur),
    "mem": lambda dur: sample_mem(dur),
    "threads": lambda dur: dump_threads(),
}


def run_local(profiler_type: str, duration: float) -> str:
    fn = PROFILERS.get(profiler_type)
    if fn is None:
        raise ValueError(f"unknown profiler {profiler_type!r}")
    return fn(min(duration, 120.0))


def run_cluster(server, profiler_type: str, duration: float) -> dict:
    """Local profile + parallel fan-out to every peer's admin endpoint
    (peers authenticate us the same way any admin client would)."""
    from concurrent.futures import ThreadPoolExecutor

    results: dict[str, dict] = {}
    peers = getattr(server, "peers", []) or []

    def remote(peer: str) -> tuple[str, dict]:
        from ..client import S3Client

        host, _, port = peer.rpartition(":")
        cli = S3Client(
            f"{host}:{port}",
            access_key=server.iam.root_user,
            secret_key=server.iam.root_password,
        )
        r = cli.request(
            "POST",
            "/minio/admin/v3/profile",
            query={
                "profilerType": profiler_type,
                "duration": str(duration),
                "local": "true",  # stop the fan-out from recursing
            },
            timeout=duration + 30,  # a profile sends nothing until done
        )
        if r.status != 200:
            return peer, {"error": f"HTTP {r.status}"}
        import json

        return peer, json.loads(r.body)["nodes"]["local"]

    with ThreadPoolExecutor(max_workers=max(1, len(peers)) + 1) as pool:
        futs = {pool.submit(remote, p): p for p in peers}
        local = pool.submit(run_local, profiler_type, duration)
        for fut, peer in futs.items():
            try:
                name, data = fut.result(timeout=duration + 30)
                results[name] = data
            except Exception as e:  # noqa: BLE001 — a dead peer is a row
                results[peer] = {"error": str(e)}
        results["local"] = {profiler_type: local.result()}
    return {"nodes": results}
