"""Embedded web console — the browser UI the reference ships via
minio/console (/root/reference/cmd/common-main.go:46 embeds it; enabled
with MINIO_BROWSER). Scope here is a self-contained single-file SPA
served at /minio/console/: login with access keys, bucket + object
browsing with prefix navigation, upload/download/delete, server info and
a live metrics snapshot. All data calls are SigV4-signed IN the browser
(Web Crypto HMAC-SHA256) against the same origin's S3/admin APIs — the
page itself is static and unauthenticated, exactly like the reference's
console assets.
"""

from __future__ import annotations

CONSOLE_HTML = r"""<!DOCTYPE html>
<html lang="en">
<head>
<meta charset="utf-8">
<title>minio_tpu console</title>
<meta name="viewport" content="width=device-width, initial-scale=1">
<style>
:root { --bg:#0f1419; --panel:#1c2430; --text:#e6e6e6; --accent:#c72c48;
        --accent2:#4a9eda; --muted:#8899a6; --ok:#3fb950; --err:#f85149; }
* { box-sizing:border-box; }
body { margin:0; font:14px/1.5 system-ui,sans-serif; background:var(--bg);
       color:var(--text); }
header { display:flex; align-items:center; gap:12px; padding:10px 20px;
         background:var(--panel); border-bottom:2px solid var(--accent); }
header h1 { font-size:16px; margin:0; }
header .who { margin-left:auto; color:var(--muted); font-size:12px; }
main { max-width:1100px; margin:20px auto; padding:0 20px; }
.panel { background:var(--panel); border-radius:8px; padding:16px;
         margin-bottom:16px; }
input, button, select { font:inherit; border-radius:4px; border:1px solid
       #30363d; background:#0d1117; color:var(--text); padding:6px 10px; }
button { cursor:pointer; background:var(--accent); border:none; }
button.alt { background:var(--accent2); }
button.ghost { background:transparent; border:1px solid #30363d; }
table { width:100%; border-collapse:collapse; }
td, th { text-align:left; padding:6px 8px; border-bottom:1px solid #21262d; }
th { color:var(--muted); font-weight:500; font-size:12px; }
a { color:var(--accent2); cursor:pointer; text-decoration:none; }
.crumb { color:var(--muted); }
.err { color:var(--err); } .ok { color:var(--ok); }
pre { background:#0d1117; padding:10px; border-radius:6px; overflow:auto;
      font-size:12px; max-height:400px; }
.row { display:flex; gap:8px; align-items:center; flex-wrap:wrap; }
.tabs { display:flex; gap:4px; margin-bottom:16px; }
.tabs button { background:transparent; border:1px solid #30363d; }
.tabs button.active { background:var(--accent); border-color:var(--accent); }
</style>
</head>
<body>
<header><h1>minio_tpu console</h1><span class="who" id="who"></span>
<button class="ghost" id="logout" style="display:none">log out</button></header>
<main id="app"></main>
<script>
"use strict";
const enc = new TextEncoder();
const S = { ak:"", sk:"", token:"", region:"us-east-1" };

async function sha256hex(s){
  const d = await crypto.subtle.digest("SHA-256", typeof s==="string"?enc.encode(s):s);
  return [...new Uint8Array(d)].map(b=>b.toString(16).padStart(2,"0")).join("");
}
async function hmac(key, msg){
  const k = await crypto.subtle.importKey("raw", key, {name:"HMAC",hash:"SHA-256"}, false, ["sign"]);
  return new Uint8Array(await crypto.subtle.sign("HMAC", k, enc.encode(msg)));
}
function uriEnc(s, slash){
  return encodeURIComponent(s).replace(/[!'()*]/g, c=>"%"+c.charCodeAt(0).toString(16).toUpperCase())
    .replace(slash?/%2F/g:/$^/g, "/");
}
async function signedFetch(method, path, query, body, signal){
  const amzdate = new Date().toISOString().replace(/[-:]/g,"").replace(/\..*/,"")+"Z";
  const scopeDate = amzdate.slice(0,8);
  const host = location.host;
  const payloadHash = "UNSIGNED-PAYLOAD";
  const qp = Object.entries(query||{}).map(([k,v])=>[uriEnc(k), uriEnc(String(v))])
    .sort((a,b)=> a[0]<b[0]?-1:a[0]>b[0]?1:0);
  const canonQ = qp.map(([k,v])=>`${k}=${v}`).join("&");
  const canonPath = uriEnc(path, true);
  const headers = {host, "x-amz-content-sha256": payloadHash, "x-amz-date": amzdate};
  if (S.token) headers["x-amz-security-token"] = S.token;
  const signedHeaders = Object.keys(headers).sort().join(";");
  const canonHeaders = Object.keys(headers).sort().map(h=>`${h}:${headers[h]}\n`).join("");
  const canon = [method, canonPath, canonQ, canonHeaders, signedHeaders, payloadHash].join("\n");
  const scope = `${scopeDate}/${S.region}/s3/aws4_request`;
  const sts = ["AWS4-HMAC-SHA256", amzdate, scope, await sha256hex(canon)].join("\n");
  let key = enc.encode("AWS4"+S.sk);
  for (const part of [scopeDate, S.region, "s3", "aws4_request"]) key = await hmac(key, part);
  const sig = [...await hmac(key, sts)].map(b=>b.toString(16).padStart(2,"0")).join("");
  const auth = `AWS4-HMAC-SHA256 Credential=${S.ak}/${scope}, SignedHeaders=${signedHeaders}, Signature=${sig}`;
  const sendHeaders = {"Authorization": auth, "x-amz-content-sha256": payloadHash, "x-amz-date": amzdate};
  if (S.token) sendHeaders["x-amz-security-token"] = S.token;
  return fetch(canonPath + (canonQ?`?${canonQ}`:""), {
    method, body: body===undefined?null:body, headers: sendHeaders, signal,
  });
}
function xml(t){ return new DOMParser().parseFromString(t, "text/xml"); }
function esc(s){ const d=document.createElement("i"); d.textContent=s;
  // innerHTML escapes & < > but NOT quotes; keys land in data-* attributes
  return d.innerHTML.replace(/"/g,"&quot;").replace(/'/g,"&#39;"); }
function fmtSize(n){ if(n<1024) return n+" B"; const u=["KiB","MiB","GiB","TiB"];
  let i=-1; do { n/=1024; i++; } while(n>=1024 && i<u.length-1);
  return n.toFixed(1)+" "+u[i]; }
const app = document.getElementById("app");

function loginView(msg){
  document.getElementById("who").textContent = "";
  document.getElementById("logout").style.display = "none";
  app.innerHTML = `<div class="panel" style="max-width:380px;margin:60px auto">
    <h2>Sign in</h2>
    ${msg?`<p class="err">${esc(msg)}</p>`:""}
    <p><input id="ak" placeholder="access key" style="width:100%"></p>
    <p><input id="sk" placeholder="secret key" type="password" style="width:100%"></p>
    <p><button id="go" style="width:100%">Sign in</button></p></div>`;
  document.getElementById("go").onclick = async ()=>{
    // login = STS AssumeRole: proves the keys AND swaps them for expiring
    // session credentials, so the long-lived secret never persists (the
    // reference console keeps a session token the same way)
    S.ak = document.getElementById("ak").value.trim();
    S.sk = document.getElementById("sk").value;
    S.token = "";
    const r = await signedFetch("POST", "/", {}, "Action=AssumeRole&Version=2011-06-15&DurationSeconds=43200");
    if (r.status !== 200) { S.ak=S.sk=""; loginView(`sign-in failed (HTTP ${r.status})`); return; }
    const doc = xml(await r.text());
    S.ak = doc.querySelector("AccessKeyId").textContent;
    S.sk = doc.querySelector("SecretAccessKey").textContent;
    S.token = doc.querySelector("SessionToken").textContent;
    sessionStorage.setItem("ccreds", JSON.stringify({ak:S.ak, sk:S.sk, token:S.token}));
    mainView("buckets");
  };
}

function shell(tab, content){
  document.getElementById("who").textContent = S.ak;
  document.getElementById("logout").style.display = "";
  app.innerHTML = `<div class="tabs">
    ${["buckets","iam","watch","diagnostics","info","metrics"].map(t=>
      `<button class="${t===tab?"active":""}" data-tab="${t}">${t}</button>`).join("")}
    </div><div id="content">${content}</div>`;
  app.querySelectorAll(".tabs button").forEach(b=>
    b.onclick = ()=>mainView(b.dataset.tab));
}

async function mainView(tab){
  if (watchAbort){ watchAbort.abort(); watchAbort = null; }
  if (tab==="buckets") return bucketsView();
  if (tab==="iam") return iamView();
  if (tab==="watch") return watchView();
  if (tab==="diagnostics") return diagView();
  if (tab==="info") return infoView();
  if (tab==="metrics") return metricsView();
}

function authFailed(r){
  if (r.status===401 || r.status===403){
    sessionStorage.removeItem("ccreds"); S.ak=S.sk=S.token="";
    loginView(`session rejected (HTTP ${r.status}) — sign in again`);
    return true;
  }
  return false;
}

async function bucketsView(){
  const r = await signedFetch("GET", "/", {});
  if (authFailed(r)) return;
  if (r.status !== 200){
    shell("buckets", `<div class="panel err">ListBuckets failed: HTTP ${r.status}</div>`);
    return;
  }
  const doc = xml(await r.text());
  const names = [...doc.querySelectorAll("Bucket > Name")].map(n=>n.textContent);
  shell("buckets", `<div class="panel"><div class="row">
      <input id="newb" placeholder="new bucket name">
      <button id="mk">create bucket</button></div></div>
    <div class="panel"><table><tr><th>bucket</th><th></th></tr>
    ${names.map(n=>`<tr><td><a data-b="${esc(n)}">${esc(n)}</a></td>
      <td style="text-align:right"><button class="ghost" data-del="${esc(n)}">delete</button></td></tr>`).join("")}
    </table></div>`);
  document.getElementById("mk").onclick = async ()=>{
    const n = document.getElementById("newb").value.trim();
    if (!n) return;
    const r = await signedFetch("PUT", "/"+n, {});
    if (r.status!==200) alert("create failed: "+await r.text()); else bucketsView();
  };
  app.querySelectorAll("a[data-b]").forEach(a=> a.onclick = ()=>objectsView(a.dataset.b, ""));
  app.querySelectorAll("button[data-del]").forEach(b=> b.onclick = async ()=>{
    if (!confirm(`delete bucket ${b.dataset.del}?`)) return;
    const r = await signedFetch("DELETE", "/"+b.dataset.del, {});
    if (r.status>=300) alert("delete failed: "+await r.text()); else bucketsView();
  });
}

async function objectsView(bucket, prefix){
  const r = await signedFetch("GET", "/"+bucket,
    {"list-type":"2", "prefix":prefix, "delimiter":"/"});
  if (authFailed(r)) return;
  if (r.status !== 200){
    shell("buckets", `<div class="panel err">listing ${esc(bucket)} failed: HTTP ${r.status}</div>`);
    return;
  }
  const doc = xml(await r.text());
  const dirs = [...doc.querySelectorAll("CommonPrefixes > Prefix")].map(n=>n.textContent);
  const objs = [...doc.querySelectorAll("Contents")].map(c=>({
    key: c.querySelector("Key").textContent,
    size: +c.querySelector("Size").textContent,
    mod: c.querySelector("LastModified").textContent }));
  const crumbs = [`<a data-p="">${esc(bucket)}</a>`];
  let acc = "";
  for (const part of prefix.split("/").filter(Boolean)){
    acc += part + "/";
    crumbs.push(`<a data-p="${esc(acc)}">${esc(part)}</a>`);
  }
  shell("buckets", `<div class="panel"><div class="row">
      <a id="back">&larr; buckets</a>
      <span class="crumb">${crumbs.join(" / ")}</span>
      <span style="margin-left:auto"></span>
      <input type="file" id="file">
      <button id="up">upload</button></div></div>
    <div class="panel"><table>
      <tr><th>name</th><th>size</th><th>modified</th><th></th></tr>
      ${dirs.map(d=>`<tr><td><a data-d="${esc(d)}">${esc(d.slice(prefix.length))}</a></td>
        <td></td><td></td><td></td></tr>`).join("")}
      ${objs.filter(o=>o.key!==prefix).map(o=>`<tr>
        <td>${esc(o.key.slice(prefix.length))}</td>
        <td>${fmtSize(o.size)}</td><td>${esc(o.mod)}</td>
        <td style="text-align:right">
          <button class="alt" data-get="${esc(o.key)}">download</button>
          <button class="ghost" data-rm="${esc(o.key)}">delete</button></td>
        </tr>`).join("")}
    </table></div>`);
  document.getElementById("back").onclick = ()=>bucketsView();
  app.querySelectorAll("a[data-p]").forEach(a=> a.onclick = ()=>objectsView(bucket, a.dataset.p));
  app.querySelectorAll("a[data-d]").forEach(a=> a.onclick = ()=>objectsView(bucket, a.dataset.d));
  document.getElementById("up").onclick = async ()=>{
    const f = document.getElementById("file").files[0];
    if (!f) return;
    const r = await signedFetch("PUT", `/${bucket}/${prefix}${f.name}`, {}, f);
    if (r.status!==200) alert("upload failed: "+await r.text());
    else objectsView(bucket, prefix);
  };
  app.querySelectorAll("button[data-get]").forEach(b=> b.onclick = async ()=>{
    const r = await signedFetch("GET", `/${bucket}/${b.dataset.get}`, {});
    if (r.status!==200){ alert("download failed"); return; }
    const blob = await r.blob();
    const a = document.createElement("a");
    a.href = URL.createObjectURL(blob);
    a.download = b.dataset.get.split("/").pop();
    a.click();
    URL.revokeObjectURL(a.href);
  });
  app.querySelectorAll("button[data-rm]").forEach(b=> b.onclick = async ()=>{
    if (!confirm(`delete ${b.dataset.rm}?`)) return;
    await signedFetch("DELETE", `/${bucket}/${b.dataset.rm}`, {});
    objectsView(bucket, prefix);
  });
}

async function infoView(){
  const r = await signedFetch("GET", "/minio/admin/v3/info", {});
  const text = r.status===200 ? JSON.stringify(await r.json(), null, 2)
                              : `HTTP ${r.status} (admin:ServerInfo needed)`;
  shell("info", `<div class="panel"><h3>server info</h3><pre>${esc(text)}</pre></div>`);
}

async function metricsView(){
  const r = await signedFetch("GET", "/minio/metrics/v3", {});
  const text = r.status===200 ? await r.text()
                              : `HTTP ${r.status} (admin:Prometheus needed)`;
  shell("metrics", `<div class="panel"><h3>metrics snapshot (v3)</h3><pre>${esc(text)}</pre></div>`);
}

// ---- IAM management (users + policies) ----
async function iamView(){
  const [ur, pr] = await Promise.all([
    signedFetch("GET", "/minio/console/api/users", {}),
    signedFetch("GET", "/minio/admin/v3/list-canned-policies", {})]);
  if (authFailed(ur)) return;
  const users = ur.status===200 ? await ur.json() : null;
  const pols  = pr.status===200 ? await pr.json() : {};
  const polNames = Object.keys(pols).sort();
  const userRows = users===null
    ? `<tr><td colspan="5" class="err">listing users needs admin:ListUsers (HTTP ${ur.status})</td></tr>`
    : Object.entries(users).sort().map(([ak,u])=>`<tr>
        <td>${esc(ak)}</td><td class="${u.status==="enabled"?"ok":"err"}">${esc(u.status)}</td>
        <td>${esc(u.policyName||"")}</td><td>${esc((u.memberOf||[]).join(", "))}</td>
        <td style="text-align:right">
          <select data-attachsel="${esc(ak)}">${polNames.map(p=>`<option>${esc(p)}</option>`).join("")}</select>
          <button class="alt" data-attach="${esc(ak)}">attach</button>
          <button class="ghost" data-toggle="${esc(ak)}" data-st="${esc(u.status)}">${u.status==="enabled"?"disable":"enable"}</button>
          <button class="ghost" data-deluser="${esc(ak)}">delete</button></td></tr>`).join("");
  shell("iam", `<div class="panel"><h3>users</h3>
      <div class="row"><input id="nak" placeholder="access key">
        <input id="nsk" placeholder="secret key" type="password">
        <button id="adduser">add user</button></div>
      <table><tr><th>access key</th><th>status</th><th>policies</th><th>groups</th><th></th></tr>
      ${userRows}</table></div>
    <div class="panel"><h3>policies</h3>
      <div class="row"><input id="pname" placeholder="policy name">
        <button id="addpol">create from JSON below</button></div>
      <p><textarea id="pjson" rows="6" style="width:100%;font-family:monospace"
        placeholder='{"Version":"2012-10-17","Statement":[{"Effect":"Allow","Action":["s3:*"],"Resource":["arn:aws:s3:::*"]}]}'></textarea></p>
      <table><tr><th>name</th><th></th></tr>
      ${polNames.map(p=>`<tr><td><a data-viewpol="${esc(p)}">${esc(p)}</a></td>
        <td style="text-align:right"><button class="ghost" data-delpol="${esc(p)}">delete</button></td></tr>`).join("")}
      </table><pre id="polview" style="display:none"></pre></div>`);
  document.getElementById("adduser").onclick = async ()=>{
    const ak = document.getElementById("nak").value.trim();
    const sk = document.getElementById("nsk").value;
    if (!ak || !sk) return;
    const r = await signedFetch("PUT", "/minio/admin/v3/add-user", {accessKey:ak},
      JSON.stringify({secretKey:sk, status:"enabled"}));
    if (r.status!==200) alert("add user failed: "+await r.text()); else iamView();
  };
  document.getElementById("addpol").onclick = async ()=>{
    const n = document.getElementById("pname").value.trim();
    const j = document.getElementById("pjson").value;
    if (!n || !j) return;
    const r = await signedFetch("PUT", "/minio/admin/v3/add-canned-policy", {name:n}, j);
    if (r.status!==200) alert("create policy failed: "+await r.text()); else iamView();
  };
  app.querySelectorAll("button[data-deluser]").forEach(b=> b.onclick = async ()=>{
    if (!confirm(`delete user ${b.dataset.deluser}?`)) return;
    await signedFetch("DELETE", "/minio/admin/v3/remove-user", {accessKey:b.dataset.deluser});
    iamView();
  });
  app.querySelectorAll("button[data-toggle]").forEach(b=> b.onclick = async ()=>{
    const to = b.dataset.st==="enabled" ? "disabled" : "enabled";
    await signedFetch("PUT", "/minio/admin/v3/set-user-status",
      {accessKey:b.dataset.toggle, status:to});
    iamView();
  });
  app.querySelectorAll("button[data-attach]").forEach(b=> b.onclick = async ()=>{
    const sel = app.querySelector(`select[data-attachsel="${CSS.escape(b.dataset.attach)}"]`);
    const r = await signedFetch("PUT", "/minio/admin/v3/set-user-or-group-policy",
      {policyName:sel.value, userOrGroup:b.dataset.attach, isGroup:"false"});
    if (r.status!==200) alert("attach failed: "+await r.text()); else iamView();
  });
  app.querySelectorAll("a[data-viewpol]").forEach(a=> a.onclick = ()=>{
    const pv = document.getElementById("polview");
    pv.style.display = "";
    pv.textContent = JSON.stringify(pols[a.dataset.viewpol], null, 2);
  });
  app.querySelectorAll("button[data-delpol]").forEach(b=> b.onclick = async ()=>{
    if (!confirm(`delete policy ${b.dataset.delpol}?`)) return;
    await signedFetch("DELETE", "/minio/admin/v3/remove-canned-policy", {name:b.dataset.delpol});
    iamView();
  });
}

// ---- live watch (bucket event firehose) ----
let watchAbort = null;
async function watchView(){
  shell("watch", `<div class="panel"><div class="row">
      <input id="wb" placeholder="bucket">
      <input id="wp" placeholder="prefix (optional)">
      <input id="ws" placeholder="suffix (optional)">
      <select id="we"><option>s3:ObjectCreated:*,s3:ObjectRemoved:*</option>
        <option>s3:ObjectCreated:*</option><option>s3:ObjectRemoved:*</option>
        <option>s3:ObjectAccessed:*</option></select>
      <button id="wstart">watch</button>
      <button id="wstop" class="ghost" disabled>stop</button></div></div>
    <div class="panel"><pre id="wlog" style="max-height:500px">waiting…</pre></div>`);
  const log = document.getElementById("wlog");
  const startB = document.getElementById("wstart"), stopB = document.getElementById("wstop");
  stopB.onclick = ()=>{ if (watchAbort){ watchAbort.abort(); watchAbort=null; }
    startB.disabled=false; stopB.disabled=true; };
  startB.onclick = async ()=>{
    const b = document.getElementById("wb").value.trim();
    if (!b) return;
    startB.disabled = true; stopB.disabled = false;
    log.textContent = "";
    watchAbort = new AbortController();
    // sign the request, then re-issue it with the stream abortable
    const q = {events: document.getElementById("we").value,
               prefix: document.getElementById("wp").value,
               suffix: document.getElementById("ws").value};
    try {
      const r = await signedFetch("GET", "/"+b, q, undefined, watchAbort.signal);
      if (r.status!==200){ log.textContent = `listen failed: HTTP ${r.status}`; return; }
      const reader = r.body.getReader();
      const dec = new TextDecoder();
      let buf = "";
      for (;;){
        const {done, value} = await reader.read();
        if (done) break;
        buf += dec.decode(value, {stream:true});
        let i;
        while ((i = buf.indexOf("\n")) >= 0){
          const line = buf.slice(0, i).trim(); buf = buf.slice(i+1);
          if (!line) continue;  // keep-alive
          try {
            const rec = JSON.parse(line).Records[0];
            log.textContent += `${rec.eventTime}  ${rec.eventName}  ` +
              `${rec.s3.bucket.name}/${rec.s3.object.key}  ${rec.s3.object.size??""}\n`;
          } catch(e){ log.textContent += line + "\n"; }
          log.scrollTop = log.scrollHeight;
        }
      }
    } catch(e){ if (e.name!=="AbortError") log.textContent += "\nstream error: "+e; }
    finally { startB.disabled=false; stopB.disabled=true; }
  };
}
// ---- diagnostics (health, usage, heal, locks, scanner) ----
async function diagView(){
  shell("diagnostics", `<div class="panel">loading…</div>`);
  const get = async (p, q)=>{
    const r = await signedFetch("GET", p, q||{});
    if (r.status!==200) return `HTTP ${r.status}`;
    const t = await r.text();
    try { return JSON.stringify(JSON.parse(t), null, 2); } catch(e){ return t; }
  };
  const [live, cluster, usage, heal, scanner, locks] = await Promise.all([
    fetch("/minio/health/live").then(r=>r.status),
    fetch("/minio/health/cluster").then(r=>r.status),
    get("/minio/admin/v3/datausageinfo"),
    get("/minio/admin/v3/background-heal/status"),
    get("/minio/admin/v3/scanner/status"),
    get("/minio/admin/v3/top/locks")]);
  document.getElementById("content").innerHTML = `
    <div class="panel"><h3>health</h3>
      <p>liveness: <span class="${live===200?"ok":"err"}">${live===200?"OK":"HTTP "+live}</span>
      &nbsp; cluster (write quorum): <span class="${cluster===200?"ok":"err"}">${cluster===200?"OK":"HTTP "+cluster}</span></p></div>
    <div class="panel"><h3>data usage</h3><pre>${esc(usage)}</pre></div>
    <div class="panel"><h3>heal status</h3><pre>${esc(heal)}</pre></div>
    <div class="panel"><h3>scanner</h3><pre>${esc(scanner)}</pre></div>
    <div class="panel"><h3>top locks</h3><pre>${esc(locks)}</pre></div>`;
}

document.getElementById("logout").onclick = ()=>{
  sessionStorage.removeItem("ccreds"); S.ak=S.sk=""; loginView();
};
const saved = sessionStorage.getItem("ccreds");
if (saved){ const c = JSON.parse(saved); S.ak=c.ak; S.sk=c.sk; S.token=c.token||""; mainView("buckets"); }
else loginView();
</script>
</body>
</html>
"""


def handle_console(request):
    """GET /minio/console[/...] — serve the embedded single-page console."""
    from aiohttp import web

    return web.Response(
        body=CONSOLE_HTML.encode(),
        content_type="text/html",
        headers={
            # the page signs requests with in-memory credentials: keep it
            # un-cacheable and locked down
            "Cache-Control": "no-store",
            "Content-Security-Policy": "default-src 'self' 'unsafe-inline' blob:",
            "X-Frame-Options": "DENY",
        },
    )
