"""POST-policy form uploads: policy-document validation + signed
browser upload forms (the reference's cmd/postpolicyform.go +
PostPolicyBucketHandler)."""

from __future__ import annotations

import hashlib
from xml.sax.saxutils import escape

from aiohttp import web

from ..erasure import listing
from . import s3err, signature
from .handler_utils import (
    _parse_form_data,
    _bucket_sse_algo,
)


class PostPolicyMixin:
    async def post_policy_upload(self, request, bucket: str, body: bytes) -> web.Response:
        """POST object (browser form upload) with V4 POST-policy signature
        (reference cmd/post-policy.go)."""
        import base64
        import hmac as _hmac
        import json as _json

        ctype = request.headers.get("Content-Type", "")
        if "boundary=" not in ctype:
            raise s3err.MalformedXML
        boundary = (
            ctype.split("boundary=", 1)[1].split(";", 1)[0].strip().strip('"').encode()
        )
        fields, file_data = _parse_form_data(body, boundary)
        key = fields.get("key", "")
        if not key:
            raise s3err.InvalidArgument
        if "${filename}" in key:
            key = key.replace("${filename}", fields.get("__filename", "upload"))

        policy_b64 = fields.get("policy", "")
        ak = ""
        if policy_b64:
            cred = fields.get("x-amz-credential", "")
            sig = fields.get("x-amz-signature", "")
            parts = cred.split("/")
            if len(parts) < 5 or parts[-1] != "aws4_request":
                raise s3err.AccessDenied
            ak = "/".join(parts[:-4])
            secret = self.iam.lookup_secret(ak)
            if secret is None:
                raise s3err.InvalidAccessKeyId
            skey = signature.signing_key(secret, parts[-4], parts[-3], parts[-2])
            want = _hmac.new(skey, policy_b64.encode(), hashlib.sha256).hexdigest()
            if not _hmac.compare_digest(want, sig):
                raise s3err.SignatureDoesNotMatch
            try:
                pol = _json.loads(base64.b64decode(policy_b64))
            except ValueError:
                raise s3err.AccessDenied from None
            import datetime as _dt

            exp = pol.get("expiration", "")
            if exp:
                try:
                    t = _dt.datetime.fromisoformat(exp.replace("Z", "+00:00"))
                except ValueError:
                    raise s3err.AccessDenied from None
                if _dt.datetime.now(_dt.timezone.utc) > t:
                    raise s3err.AccessDenied
            for cond in pol.get("conditions", []):
                if isinstance(cond, dict):
                    for ck, cv in cond.items():
                        if ck == "bucket" and cv != bucket:
                            raise s3err.AccessDenied
                        if ck == "key" and cv != key:
                            raise s3err.AccessDenied
                elif isinstance(cond, list) and len(cond) == 3:
                    op, name, val = cond
                    if str(op) == "content-length-range":
                        try:
                            lo, hi = int(name), int(val)
                        except (TypeError, ValueError):
                            raise s3err.AccessDenied from None
                        if not lo <= len(file_data) <= hi:
                            raise s3err.EntityTooLarge
                        continue
                    name = str(name).lstrip("$")
                    have = {"bucket": bucket, "key": key}.get(name, fields.get(name, ""))
                    if op == "eq" and have != val:
                        raise s3err.AccessDenied
                    if op == "starts-with" and not str(have).startswith(str(val)):
                        raise s3err.AccessDenied
        self._authorize(ak, "s3:PutObject", bucket, key)
        user_defined = {
            k: v for k, v in fields.items() if k.startswith("x-amz-meta-")
        }
        ct = fields.get("Content-Type") or fields.get("content-type") or ""
        if ct:
            user_defined["content-type"] = ct
        bm = self.buckets.get(bucket)
        # same pipeline as PUT: bucket-default SSE/compression apply here too
        from ..crypto.sse import CryptoError
        from . import transforms

        try:
            tr = transforms.encode_for_store(
                file_data, key, ct, {}, _bucket_sse_algo(bm.encryption),
                self.kms, bucket,
            )
        except CryptoError:
            raise s3err.InvalidArgument from None
        if tr.metadata:
            user_defined.update(tr.metadata)
            file_data = tr.data
        oi = await self._run(
            self.store.put_object, bucket, listing.encode_dir_object(key),
            file_data, user_defined, None, bm.versioning,
        )
        from ..events import notify as ev

        self.notifier.notify(
            "s3:ObjectCreated:Post", bucket, key, oi.size, oi.etag,
            oi.version_id, ak,
        )
        self._queue_repl(request, 
            bucket, listing.encode_dir_object(key), oi.version_id, "put"
        )
        try:
            status = int(fields.get("success_action_status", "204"))
        except ValueError:
            status = 204
        if status not in (200, 201, 204):
            status = 204
        headers = {"ETag": f'"{oi.etag}"'}
        if status == 201:
            xml = (
                '<?xml version="1.0" encoding="UTF-8"?>'
                f"<PostResponse><Bucket>{escape(bucket)}</Bucket>"
                f"<Key>{escape(key)}</Key><ETag>&quot;{oi.etag}&quot;</ETag>"
                "</PostResponse>"
            )
            return web.Response(
                status=201, body=xml.encode(), content_type="application/xml",
                headers=headers,
            )
        return web.Response(status=status, headers=headers)

    # -- object lock: retention + legal hold ----------------------------------

    RETENTION_META = "x-minio-internal-retention"  # "<mode>|<iso-until>"
    LEGALHOLD_META = "x-minio-internal-legalhold"
