"""madmin wire encryption — the encrypted admin-plane framing `mc admin`
speaks.

The reference's admin handlers wrap sensitive request/response bodies
with madmin-go/v3 EncryptData/DecryptData (used throughout
/root/reference/cmd/admin-handlers-users.go:630,812,998 and
admin-handlers-config-kv.go:278), whose documented ciphertext layout is

    salt | AEAD id | nonce | sio stream
     32      1        8       ...

* key = Argon2id(password, salt, time=1, memory=64 MiB, threads=4) -> 32B,
  password being the requester's own secret key.
* AEAD id 0x00 = AES-256-GCM, 0x01 = ChaCha20-Poly1305 (the Go client
  picks by CPU support; we accept both and emit AES-256-GCM).
* The stream is secure-io/sio-go (v0.3.1) framing: seq 0 seals the
  user associated data (nil here) into a bare tag, and every fragment's
  AAD is marker || that tag — 0x00 for intermediate fragments, 0x80
  for the final one. Plaintext splits into 16 KiB fragments sealed with
  nonce = nonce8 || LE32(seq), seq starting at 1. Empty plaintext still
  seals one final fragment, so truncation and reordering are always
  detectable.

sio-go's source is not available in this environment; the framing above
is reconstructed from its published design and must hold for real
`mc admin` interop — the layout is fully documented here so a mismatch
is a one-line fix.
"""

from __future__ import annotations

import os
import struct

try:
    from cryptography.exceptions import InvalidTag
    from cryptography.hazmat.primitives.ciphers.aead import AESGCM, ChaCha20Poly1305
    from cryptography.hazmat.primitives.kdf.argon2 import Argon2id

    _HAVE_CRYPTO = True
except ImportError:  # gated dep: encrypted madmin framing unavailable;
    # the plain-JSON admin plane (our own SDK) still works
    _HAVE_CRYPTO = False

    class InvalidTag(Exception):  # type: ignore[no-redef]
        pass

AES_GCM_ID = 0x00
C20P1305_ID = 0x01
SALT_LEN = 32
NONCE_LEN = 8  # AEAD nonce (12) minus the 4-byte fragment counter
FRAGMENT = 1 << 14  # sio-go BufSize
TAG_LEN = 16
HEADER_LEN = SALT_LEN + 1 + NONCE_LEN


class MadminCryptError(Exception):
    pass


def _derive_key(password: str, salt: bytes) -> bytes:
    if not _HAVE_CRYPTO:
        raise MadminCryptError(
            "madmin encrypted framing needs the 'cryptography' package, "
            "which is not installed"
        )
    return Argon2id(
        salt=salt, length=32, iterations=1, lanes=4, memory_cost=64 * 1024
    ).derive(password.encode())


def _aead(aead_id: int, key: bytes):
    if not _HAVE_CRYPTO:
        raise MadminCryptError(
            "madmin encrypted framing needs the 'cryptography' package, "
            "which is not installed"
        )
    if aead_id == AES_GCM_ID:
        return AESGCM(key)
    if aead_id == C20P1305_ID:
        return ChaCha20Poly1305(key)
    raise MadminCryptError(f"unknown AEAD id {aead_id}")


def _aad_tag(aead, nonce: bytes) -> bytes:
    """sio-go reserves seq 0: the user associated data (nil for madmin)
    is sealed into a bare tag that becomes part of every fragment's AAD."""
    return aead.encrypt(nonce + struct.pack("<I", 0), b"", None)


def encrypt(password: str, data: bytes) -> bytes:
    salt = os.urandom(SALT_LEN)
    nonce = os.urandom(NONCE_LEN)
    aead = _aead(AES_GCM_ID, _derive_key(password, salt))
    tag = _aad_tag(aead, nonce)
    out = bytearray()
    out += salt
    out.append(AES_GCM_ID)
    out += nonce
    n_frags = max(1, -(-len(data) // FRAGMENT))
    for i in range(n_frags):
        frag = data[i * FRAGMENT : (i + 1) * FRAGMENT]
        final = i == n_frags - 1
        out += aead.encrypt(
            nonce + struct.pack("<I", i + 1),
            bytes(frag),
            bytes([0x80 if final else 0x00]) + tag,
        )
    return bytes(out)


def decrypt(password: str, blob: bytes) -> bytes:
    if len(blob) < HEADER_LEN + TAG_LEN:
        raise MadminCryptError("ciphertext too short")
    salt = blob[:SALT_LEN]
    aead_id = blob[SALT_LEN]
    nonce = blob[SALT_LEN + 1 : HEADER_LEN]
    aead = _aead(aead_id, _derive_key(password, salt))
    tag = _aad_tag(aead, nonce)
    body = blob[HEADER_LEN:]
    out = bytearray()
    step = FRAGMENT + TAG_LEN
    n_frags = max(1, -(-len(body) // step))
    for i in range(n_frags):
        frag = body[i * step : (i + 1) * step]
        final = i == n_frags - 1
        try:
            out += aead.decrypt(
                nonce + struct.pack("<I", i + 1), bytes(frag),
                bytes([0x80 if final else 0x00]) + tag,
            )
        except InvalidTag:
            # position determines finality unambiguously: an exactly
            # fragment-aligned stream makes its last FULL fragment final,
            # and an encoder that sealed n full intermediates appends a
            # 16-byte empty final fragment (ceil puts it in its own seq)
            raise MadminCryptError("decryption failed") from None
    return bytes(out)


def looks_encrypted(blob: bytes) -> bool:
    """Cheap structural test: long enough for the madmin header and the
    AEAD id byte is one of the two defined values. JSON admin bodies
    (b'{' = 0x7b at offset 32 only if...) can collide only if byte 32 is
    0x00/0x01, which printable JSON never is."""
    return len(blob) >= HEADER_LEN + TAG_LEN and blob[SALT_LEN] in (
        AES_GCM_ID,
        C20P1305_ID,
    )


def maybe_decrypt(password: str, body: bytes) -> bytes:
    """Request-side leniency: madmin clients encrypt; our own SDK/tests
    send plain JSON. Try the wire format first, fall back to plaintext."""
    if looks_encrypted(body):
        try:
            return decrypt(password, body)
        except MadminCryptError:
            pass
    return body
