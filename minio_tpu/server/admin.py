"""Admin API — the `mc admin` surface subset.

Mirrors the reference's admin router (/root/reference/cmd/admin-router.go,
admin-handlers*.go) under /minio/admin/v3/: user/group/policy management,
service accounts, server info, storage info, heal triggering. Sensitive
bodies speak the madmin wire (server/madmin.py): requests from `mc
admin`-style clients arrive encrypted with the caller's secret key and
are accepted alongside plain JSON; the responses the reference encrypts
(user listings, minted credentials, config dumps) always go out
encrypted, as madmin.DecryptData expects.
"""

from __future__ import annotations

import json
import os
import time

from aiohttp import web

from ..iam.policy import Policy
from ..iam.sys import NoSuchPolicy, NoSuchUser
from . import s3err


def _json(data, status=200) -> web.Response:
    return web.Response(
        status=status, body=json.dumps(data).encode(), content_type="application/json"
    )


def _int_q(q, name: str, default: int, lo: int | None = None, hi: int | None = None) -> int:
    """Query param as int -> 400 InvalidArgument on garbage, clamped."""
    try:
        v = int(q.get(name, str(default)))
    except ValueError:
        raise s3err.InvalidArgument from None
    if lo is not None:
        v = max(v, lo)
    if hi is not None:
        v = min(v, hi)
    return v


async def handle_admin(server, request: web.Request, access_key: str, subpath: str, body: bytes):
    """Dispatch /minio/admin/v3/<op> requests."""
    from . import madmin

    op = subpath.split("?")[0]
    q = request.rel_url.query
    m = request.method
    iam = server.iam
    secret = iam.lookup_secret(access_key) or ""
    # madmin clients (`mc admin`) encrypt sensitive bodies with the
    # requester's secret key; our own SDK sends plain JSON — accept both.
    # The Argon2id KDF costs ~100 ms + 64 MiB, so it runs off-loop.
    if body and madmin.looks_encrypted(body):
        body = await server._run(madmin.maybe_decrypt, secret, body)

    async def _json_madmin(data, status=200) -> web.Response:
        """Responses the reference wraps with madmin.EncryptData (user
        listings, minted credentials, config dumps) go out encrypted to
        the requester's key, exactly as `mc admin` expects."""
        blob = await server._run(madmin.encrypt, secret, json.dumps(data).encode())
        return web.Response(
            status=status, body=blob, content_type="application/octet-stream"
        )

    def authz(action: str):
        if not iam.is_allowed(access_key, action, ""):
            raise s3err.AccessDenied

    # -- warm tiers (reference cmd/tier.go, admin-handlers-tiers) ----------
    if op == "tier" and m == "PUT":
        authz("admin:SetTier")
        from ..ilm.tier import Tier

        try:
            d = json.loads(body)
            t = Tier(
                name=d["name"], endpoint=d["endpoint"],
                access_key=d["accessKey"], secret_key=d["secretKey"],
                bucket=d["bucket"], prefix=d.get("prefix", ""),
                tier_type=d.get("type", "minio"),
            )
        except (ValueError, KeyError, TypeError):
            raise s3err.InvalidArgument from None
        await server._run(server.tiers.set, t)
        return _json({"success": True})
    if op == "tier" and m == "GET":
        authz("admin:ListTier")
        return _json([
            {"name": t.name, "endpoint": t.endpoint, "bucket": t.bucket,
             "prefix": t.prefix, "type": t.tier_type}
            for t in server.tiers.list()
        ])
    if op == "tier" and m == "DELETE":
        authz("admin:SetTier")
        await server._run(server.tiers.remove, q.get("name", ""))
        return _json({"success": True})

    # -- bucket quota (reference cmd/admin-bucket-handlers.go
    # SetBucketQuotaConfigHandler; enforced in server/app.py) --------------
    if op == "set-bucket-quota" and m == "PUT":
        authz("admin:SetBucketQuota")
        bucket = q.get("bucket", "")
        if not bucket or not await server._run(server.store.bucket_exists, bucket):
            raise s3err.NoSuchBucket
        try:
            d = json.loads(body) if body else {}
            size = int(d.get("quota", d.get("size", 0)) or 0)
        except (ValueError, TypeError):
            raise s3err.InvalidArgument from None

        def setq():
            bm = server.buckets.get(bucket)
            bm.quota = size
            server.buckets.set(bucket, bm)

        await server._run(setq)
        return _json({"success": True})
    if op == "get-bucket-quota" and m == "GET":
        authz("admin:GetBucketQuota")
        bucket = q.get("bucket", "")
        if not bucket or not await server._run(server.store.bucket_exists, bucket):
            raise s3err.NoSuchBucket
        bm = await server._run(server.buckets.get, bucket)
        return _json({"quota": bm.quota, "size": bm.quota,
                      "quotatype": "hard" if bm.quota else ""})

    # -- site replication (reference cmd/site-replication.go) --------------
    if op == "site-replication/info" and m == "GET":
        authz("admin:SiteReplicationInfo")
        return _json(await server._run(server.site.info))
    if op == "site-replication/add" and m == "POST":
        authz("admin:SiteReplicationAdd")
        try:
            sites = json.loads(body)
            assert isinstance(sites, list) and len(sites) >= 2
        except (ValueError, AssertionError):
            raise s3err.InvalidArgument from None
        try:
            return _json(await server._run(server.site.add_sites, sites))
        except (ValueError, RuntimeError) as e:
            return _json({"error": str(e)}, status=400)
    if op == "site-replication/join" and m == "POST":
        authz("admin:SiteReplicationAdd")
        try:
            doc = json.loads(body)
            await server._run(server.site.join, doc)
        except (ValueError, KeyError, TypeError):
            # malformed or version-skewed peer request: a 400, not a 500
            raise s3err.InvalidArgument from None
        return _json({"success": True})
    if op == "site-replication/apply" and m == "POST":
        authz("admin:SiteReplicationOperation")
        try:
            doc = json.loads(body)
            await server._run(
                server.site.apply, doc.get("kind", ""), doc.get("payload", {})
            )
        except (ValueError, KeyError, TypeError):
            raise s3err.InvalidArgument from None
        return _json({"success": True})

    # -- IAM + bucket-metadata export/import (reference
    # cmd/admin-handlers.go ExportIAM/ImportIAM,
    # ExportBucketMetadata/ImportBucketMetadata: zip-of-JSON snapshots
    # that move whole deployments between clusters) ------------------------
    if op == "export-iam" and m == "GET":
        authz("admin:ExportIAMAction")
        import io
        import zipfile

        from ..iam.policy import CANNED_POLICIES

        def _build_iam_zip() -> bytes:
            # off-loop: iam._lock may be held by a pool thread mid-persist
            iam_ = server.iam
            with iam_._lock:
                users = {
                    k: u.to_dict() for k, u in iam_.users.items() if not u.is_temp
                }
                groups = json.loads(json.dumps(iam_.groups))
                policies = {
                    k: p.to_dict() for k, p in iam_.policies.items()
                    if k not in CANNED_POLICIES
                }
                ldap_map = dict(iam_.ldap_policy_map)
            buf = io.BytesIO()
            with zipfile.ZipFile(buf, "w", zipfile.ZIP_DEFLATED) as z:
                z.writestr("iam-assets/users.json", json.dumps(users, indent=2))
                z.writestr("iam-assets/groups.json", json.dumps(groups, indent=2))
                z.writestr(
                    "iam-assets/policies.json", json.dumps(policies, indent=2)
                )
                z.writestr(
                    "iam-assets/ldap-policy-map.json", json.dumps(ldap_map, indent=2)
                )
            return buf.getvalue()

        return web.Response(
            body=await server._run(_build_iam_zip), content_type="application/zip",
            headers={"Content-Disposition": "attachment; filename=iam-assets.zip"},
        )
    if op == "import-iam" and m == "PUT":
        authz("admin:ImportIAMAction")
        import io
        import zipfile

        try:
            z = zipfile.ZipFile(io.BytesIO(body))

            def _read(name: str) -> dict:
                try:
                    return json.loads(z.read(f"iam-assets/{name}"))
                except KeyError:
                    return {}

            snap = {
                "users": _read("users.json"),
                "groups": _read("groups.json"),
                "policies": _read("policies.json"),
                "ldap_policy_map": _read("ldap-policy-map.json"),
            }
        except (zipfile.BadZipFile, ValueError):
            raise s3err.InvalidArgument from None

        def _merge_iam() -> None:
            # ADDITIVE: a zip carrying only policies must not wipe users
            # (the reference's ImportIAM applies file-by-file the same way)
            from ..iam.policy import Policy
            from ..iam.sys import UserIdentity

            iam_ = server.iam
            with iam_._lock:
                for k, v in snap["users"].items():
                    iam_.users[k] = UserIdentity.from_dict(v)
                iam_.groups.update(snap["groups"])
                for k, v in snap["policies"].items():
                    iam_.policies[k] = Policy.from_dict(v)
                iam_.ldap_policy_map.update(snap["ldap_policy_map"])
                iam_._persist_users()
                iam_._persist_groups()
                iam_._persist_policies()
                iam_._save("ldap_policy_map", iam_.ldap_policy_map)

        await server._run(_merge_iam)
        if getattr(server.site, "enabled", False):
            server.site.sync_iam()  # imported identities propagate site-wide
        return _json({"success": True})
    if op == "export-bucket-metadata" and m == "GET":
        authz("admin:ExportBucketMetadataAction")
        import io
        import zipfile

        only = q.get("bucket", "")
        if only and not await server._run(server.store.bucket_exists, only):
            from ..erasure.quorum import BucketNotFound

            raise BucketNotFound(only)

        def _build_zip() -> bytes:
            # off-loop: cold bucket-metadata reads hit the erasure store
            names = (
                [only] if only
                else [b.name for b in server.store.list_buckets()]
            )
            buf = io.BytesIO()
            with zipfile.ZipFile(buf, "w", zipfile.ZIP_DEFLATED) as z:
                for name in names:
                    if name.startswith(".minio.sys"):
                        continue
                    bm = server.buckets.get(name)
                    z.writestr(f"buckets/{name}.json", bm.to_json())
            return buf.getvalue()

        blob = await server._run(_build_zip)
        return web.Response(
            body=blob, content_type="application/zip",
            headers={"Content-Disposition": "attachment; filename=bucket-metadata.zip"},
        )
    if op == "import-bucket-metadata" and m == "PUT":
        authz("admin:ImportBucketMetadataAction")
        import io
        import zipfile

        from ..replication.site import _SYNCED_META

        try:
            z = zipfile.ZipFile(io.BytesIO(body))
            entries = [
                n for n in z.namelist()
                if n.startswith("buckets/") and n.endswith(".json")
            ]
            docs = {
                n[len("buckets/"):-len(".json")]: json.loads(z.read(n))
                for n in entries
            }
        except (zipfile.BadZipFile, ValueError):
            raise s3err.InvalidArgument from None

        def _apply_buckets() -> list[str]:
            from .app import BUCKET_NAME_RE

            applied = []
            # the synced set plus export-only fields that must survive a
            # migration (suspended-versioning state, ownership controls)
            fields = _SYNCED_META + ("versioning_suspended", "ownership")
            for name, doc in docs.items():
                # zip entry names are untrusted: enforce the same bucket
                # naming rules put_bucket does, and never touch the
                # system namespace
                if (
                    not BUCKET_NAME_RE.match(name)
                    or ".." in name
                    or "/" in name
                    or name.startswith(".minio.sys")
                ):
                    continue
                if not server.store.bucket_exists(name):
                    server.store.make_bucket(name)
                bm = server.buckets.get(name)
                for f in fields:
                    if f in doc:
                        setattr(bm, f, doc[f])
                server.buckets.set(name, bm)
                applied.append(name)
            return applied

        applied = await server._run(_apply_buckets)
        return _json({"success": True, "buckets": applied})

    # -- users ------------------------------------------------------------
    if op == "add-user" and m == "PUT":
        authz("admin:CreateUser")
        try:
            d = json.loads(body)
            ak = q.get("accessKey", "")
            if not ak or not d.get("secretKey"):
                raise s3err.InvalidArgument
        except ValueError:
            raise s3err.InvalidArgument from None
        await server._run(iam.add_user, ak, d["secretKey"], d.get("status", "enabled"))
        return web.Response(status=200)
    if op == "remove-user" and m == "DELETE":
        authz("admin:DeleteUser")
        try:
            await server._run(iam.remove_user, q.get("accessKey", ""))
        except NoSuchUser:
            return _json({"error": "user not found"}, 404)
        return web.Response(status=200)
    if op == "list-users" and m == "GET":
        authz("admin:ListUsers")
        users = await server._run(iam.list_users)
        return await _json_madmin(
            {
                k: {"status": u.status, "policyName": ",".join(u.policies), "memberOf": u.groups}
                for k, u in users.items()
            }
        )
    if op == "set-user-status" and m == "PUT":
        authz("admin:EnableUser")
        try:
            await server._run(iam.set_user_status, q.get("accessKey", ""), q.get("status", "enabled"))
        except NoSuchUser:
            return _json({"error": "user not found"}, 404)
        return web.Response(status=200)

    # -- groups -----------------------------------------------------------
    if op == "update-group-members" and m == "PUT":
        authz("admin:AddUserToGroup")
        try:
            d = json.loads(body)
        except ValueError:
            raise s3err.InvalidArgument from None
        await server._run(
            iam.update_group_members,
            d.get("group", ""),
            d.get("members", []),
            d.get("isRemove", False),
        )
        return web.Response(status=200)
    if op == "groups" and m == "GET":
        authz("admin:ListGroups")
        return _json(await server._run(iam.list_groups))
    if op == "group" and m == "GET":
        authz("admin:GetGroup")
        g = iam.groups.get(q.get("group", ""))
        if g is None:
            return _json({"error": "group not found"}, 404)
        return _json({"name": q.get("group"), **g})

    # -- policies ---------------------------------------------------------
    if op == "add-canned-policy" and m == "PUT":
        authz("admin:CreatePolicy")
        try:
            pol = Policy.from_json(body)
        except (ValueError, KeyError):
            raise s3err.InvalidArgument from None
        await server._run(iam.set_policy, q.get("name", ""), pol)
        return web.Response(status=200)
    if op == "remove-canned-policy" and m == "DELETE":
        authz("admin:DeletePolicy")
        try:
            await server._run(iam.delete_policy, q.get("name", ""))
        except NoSuchPolicy:
            return _json({"error": "policy not found"}, 404)
        return web.Response(status=200)
    if op == "list-canned-policies" and m == "GET":
        authz("admin:ListUserPolicies")
        return _json({k: p.to_dict() for k, p in iam.policies.items()})
    if op == "info-canned-policy" and m == "GET":
        authz("admin:GetPolicy")
        p = iam.policies.get(q.get("name", ""))
        if p is None:
            return _json({"error": "policy not found"}, 404)
        return _json(p.to_dict())
    if op == "set-user-or-group-policy" and m == "PUT":
        authz("admin:AttachUserOrGroupPolicy")
        names = [n for n in q.get("policyName", "").split(",") if n]
        try:
            if q.get("isGroup") == "true":
                await server._run(iam.attach_policy, names, "", q.get("userOrGroup", ""))
            else:
                await server._run(iam.attach_policy, names, q.get("userOrGroup", ""), "")
        except (NoSuchUser, NoSuchPolicy) as e:
            return _json({"error": str(e)}, 404)
        return web.Response(status=200)

    # -- service accounts -------------------------------------------------
    if op == "add-service-account" and m == "PUT":
        try:
            d = json.loads(body) if body else {}
        except ValueError:
            raise s3err.InvalidArgument from None
        parent = d.get("targetUser") or access_key
        if parent != access_key:
            # minting for ANOTHER identity needs the admin grant; minting
            # for oneself does not (reference AddServiceAccount: self-ops
            # bypass the policy check, cmd/admin-handlers-users.go)
            authz("admin:CreateServiceAccount")
        # creating credentials for ANOTHER identity inherits that identity's
        # privileges — only the cluster owner may do it (else any holder of
        # admin:CreateServiceAccount could mint root-equivalent keys)
        if parent != access_key and not iam.is_owner(access_key):
            raise s3err.AccessDenied
        u = await server._run(
            iam.new_service_account,
            parent,
            d.get("policy"),
            d.get("accessKey", ""),
            d.get("secretKey", ""),
        )
        return await _json_madmin(
            {"credentials": {"accessKey": u.access_key, "secretKey": u.secret_key}}
        )

    if op == "list-service-accounts" and m == "GET":
        # reference cmd/admin-handlers-users.go ListServiceAccounts: any
        # authenticated user may manage their OWN service accounts (no
        # admin policy needed); other users' SAs need owner/admin rights
        target = q.get("user", "") or access_key
        if target != access_key:
            authz("admin:ListServiceAccounts")
            if not iam.is_owner(access_key):
                raise s3err.AccessDenied
        accounts = [
            {"accessKey": u.access_key, "parentUser": u.parent,
             "accountStatus": u.status,
             "expiration": u.expiration or None}
            for u in iam.users.values()
            if u.parent == target and not u.is_temp
        ]
        return await _json_madmin({"accounts": accounts})
    if op == "info-service-account" and m == "GET":
        sa = iam.users.get(q.get("accessKey", ""))
        if sa is None or not sa.parent or sa.is_temp:
            return _json({"error": "service account not found"}, 404)
        if sa.parent != access_key:
            authz("admin:ListServiceAccounts")
            if not iam.is_owner(access_key):
                raise s3err.AccessDenied
        return await _json_madmin({
            "parentUser": sa.parent,
            "accountStatus": sa.status,
            "impliedPolicy": not sa.session_policy,
            "policy": json.dumps(sa.session_policy) if sa.session_policy else "",
        })
    if op == "delete-service-account" and m == "DELETE":
        sa = iam.users.get(q.get("accessKey", ""))
        if sa is None or not sa.parent or sa.is_temp:
            return _json({"error": "service account not found"}, 404)
        # the parent may always revoke their own SA; anyone else needs
        # owner/admin rights (reference DeleteServiceAccount)
        if sa.parent != access_key:
            authz("admin:RemoveServiceAccount")
            if not iam.is_owner(access_key):
                raise s3err.AccessDenied
        await server._run(iam.remove_user, sa.access_key)
        return web.Response(status=204)

    # -- fault injection (chaos plane, fault/registry.py) ------------------
    if op == "fault/inject" and m == "POST":
        authz("admin:ServerUpdate")
        from .. import fault

        try:
            spec = json.loads(body) if body else {}
            rid = fault.inject(spec)
        except ValueError as e:
            return _json({"error": str(e)}, 400)
        out = {"id": rid, "rule": spec}
        if q.get("local") != "true":
            out["peers"] = await server._run(
                _admin_fanout, server, "fault/inject", body, {}
            )
        return _json(out)
    if op == "fault/clear" and m == "POST":
        authz("admin:ServerUpdate")
        from .. import fault

        rid = None
        if q.get("id"):
            try:
                rid = int(q["id"])
            except ValueError:
                raise s3err.InvalidArgument from None
        removed = fault.clear(rid)
        out = {"removed": removed}
        # rule ids are per-process counters, so an id-scoped clear is
        # meaningful only on the node that minted the id — fanning an id
        # out would clear a DIFFERENT (or no) rule on each peer while
        # reporting success. Only full clears go cluster-wide.
        if q.get("local") != "true" and rid is None:
            out["peers"] = await server._run(
                _admin_fanout, server, "fault/clear", b"", {}
            )
        return _json(out)
    if op == "fault/status" and m == "GET":
        authz("admin:OBDInfo")
        from .. import fault
        from ..parallel import dispatcher as dmod

        st = fault.status()
        ds = dmod.aggregate_stats()
        st["backendLevel"] = ds.get("backend_level", 2)
        st["demotions"] = ds.get("demotions", 0)
        st["promotions"] = ds.get("promotions", 0)
        return _json(st)

    # -- caching layer (cache/: FileInfo + data + segment + listing tiers) -
    if op == "cache/status" and m == "GET":
        authz("admin:OBDInfo")
        from .. import cache
        from ..cache import coherence as cache_coherence
        from ..cache import segment as cache_segment

        st = await server._run(cache.aggregate_stats, server.store)
        st["coherence"] = cache_coherence.stats()
        # operator-facing tier config: is the range-segment tier live,
        # and where/how big is this worker's NVMe spool
        st["segmentsEnabled"] = cache_segment.segments_enabled()
        st["segments"]["disk_enabled"] = cache_segment.disk_budget() > 0
        return _json(st)
    if op == "cache/clear" and m == "POST":
        authz("admin:ServerUpdate")
        from .. import cache

        out = {"cleared": await server._run(cache.clear_store, server.store)}
        if q.get("local") != "true":
            out["peers"] = await server._run(
                _admin_fanout, server, "cache/clear", b"", {}
            )
        return _json(out)

    # -- observability ----------------------------------------------------
    if op == "trace" and m == "GET":
        authz("admin:ServerTrace")
        return await _stream_trace(server, request)
    if op == "sanitizer/status" and m == "GET":
        # runtime sanitizer state: lock witness, access witness,
        # stall episodes, violation counters + recent ring (stackless)
        authz("admin:OBDInfo")
        from ..analysis import sanitizer

        return _json(sanitizer.status())
    if op == "datausageinfo" and m == "GET":
        authz("admin:DataUsageInfo")
        bg = server.background
        return _json(bg.usage.snapshot() if bg else {})
    if op == "background-heal/status" and m == "GET":
        authz("admin:Heal")
        bg = server.background
        return _json(
            {
                "mrfQueued": len(bg.mrf) if bg else 0,
                **(bg.stats if bg else {}),
            }
        )
    if op == "scanner/status" and m == "GET":
        authz("admin:OBDInfo")
        bg = server.background
        return _json(bg.stats if bg else {})
    if op == "inflight-requests" and m == "GET":
        # QoS observability (`mc admin top api` analogue): per-class
        # admission state, last-minute per-API latency, adaptive
        # deadlines, and the TPU dispatcher's priority-lane counters
        authz("admin:OBDInfo")
        from ..parallel import dispatcher as dmod

        snap = server.qos.snapshot()
        snap["dispatcher"] = dmod.aggregate_stats()
        return _json(snap)
    if op == "top/locks" and m == "GET":
        authz("admin:TopLocksInfo")
        # aggregate lock tables reachable from this node
        from ..cluster.locks import LocalLocker

        stats = {}
        first = server.store
        sets = getattr(getattr(first, "pools", [first])[0], "sets", [])
        if sets:
            for lk in sets[0].ns.lockers:
                if isinstance(lk, LocalLocker):
                    stats.update(lk.stats())
        return _json(stats)

    # -- replication targets ----------------------------------------------
    if op == "set-remote-target" and m == "PUT":
        authz("admin:SetBucketTarget")
        from ..replication.replicate import RemoteTarget

        try:
            d = json.loads(body)
            import uuid as _uuid

            arn = d.get("arn") or (
                f"arn:minio:replication::{str(_uuid.uuid4())[:8]}:{d['targetbucket']}"
            )
            t = RemoteTarget(
                arn=arn,
                source_bucket=d["sourcebucket"],
                endpoint=d["endpoint"],
                access_key=d["credentials"]["accessKey"],
                secret_key=d["credentials"]["secretKey"],
                target_bucket=d["targetbucket"],
            )
        except (ValueError, KeyError):
            raise s3err.InvalidArgument from None
        await server._run(server.repl_targets.set, t)
        return _json({"arn": t.arn})
    if op == "list-remote-targets" and m == "GET":
        authz("admin:GetBucketTarget")
        out = [t.to_dict() for t in server.repl_targets.list(q.get("bucket", ""))]
        for t in out:
            t.pop("secret_key", None)
        return _json(out)
    if op == "remove-remote-target" and m == "DELETE":
        authz("admin:SetBucketTarget")
        await server._run(server.repl_targets.remove, q.get("arn", ""))
        return web.Response(status=204)
    if op == "replication/status" and m == "GET":
        authz("admin:GetBucketTarget")
        return _json(server.replication.stats)
    if op == "replication/resync" and m == "POST":
        authz("admin:SetBucketTarget")
        n = await server._run(server.replication.resync, q.get("bucket", ""))
        return _json({"queued": n})

    # -- batch jobs --------------------------------------------------------
    if op == "start-job" and m == "POST":
        authz("admin:StartBatchJob")
        import yaml as _yaml

        try:
            st = await server._run(server.batch.start, body.decode())
        except (ValueError, _yaml.YAMLError) as e:
            return _json({"error": str(e)}, 400)
        return _json(st.to_dict())
    if op == "list-jobs" and m == "GET":
        authz("admin:ListBatchJobs")
        return _json([s.to_dict() for s in server.batch.list()])
    if op == "describe-job" and m == "GET":
        authz("admin:DescribeBatchJob")
        st = server.batch.describe(q.get("jobId", ""))
        return _json(st.to_dict() if st else {"error": "not found"},
                     200 if st else 404)
    if op == "cancel-job" and m == "DELETE":
        authz("admin:CancelBatchJob")
        ok = server.batch.cancel(q.get("jobId", ""))
        return web.Response(status=204 if ok else 404)

    # -- placement + live topology (placement/) ----------------------------
    if op.startswith("placement/"):
        pl = getattr(server.store, "placement", None)
        if pl is None:
            return _json({"error": "store has no placement engine"}, 400)
        if op == "placement/set" and m == "POST":
            authz("admin:ServerUpdate")
            try:
                d = json.loads(body) if body else {}
                rule = await server._run(pl.set_rule, d)
            except (ValueError, TypeError, KeyError) as e:
                return _json({"error": str(e)}, 400)
            out = {"rule": rule}
            if q.get("local") != "true":
                # rules persist through the shared object layer; peers
                # (cluster nodes AND pool workers) just re-read them
                out["peers"] = await server._run(
                    _admin_fanout, server, "placement/reload", b"", {}
                )
            return _json(out)
        if op == "placement/delete" and m == "POST":
            authz("admin:ServerUpdate")
            try:
                d = json.loads(body) if body else {}
                removed = await server._run(
                    pl.delete_rule, d.get("bucket", ""), d.get("prefix", "")
                )
            except (ValueError, TypeError) as e:
                return _json({"error": str(e)}, 400)
            out = {"removed": removed}
            if q.get("local") != "true":
                out["peers"] = await server._run(
                    _admin_fanout, server, "placement/reload", b"", {}
                )
            return _json(out)
        if op == "placement/reload" and m == "POST":
            authz("admin:ServerUpdate")
            return _json({"rules": await server._run(pl.reload)})
        if op == "placement/get" and m == "GET":
            authz("admin:ServerInfo")
            return _json(await server._run(pl.rules))
        if op == "placement/status" and m == "GET":
            authz("admin:ServerInfo")
            st = await server._run(pl.status)
            if server.pool_mgr is not None:
                st["pools"] = await server._run(server.pool_mgr.pool_usage)
            return _json(st)

    if op in ("pool/expand", "pool/remove") and m == "POST":
        authz("admin:ServerUpdate")
        if server.pool_mgr is None:
            return _json({"error": "store has no pool topology"}, 400)
        if getattr(server, "worker_count", 1) > 1 or (
            getattr(server, "peers", None) or []
        ):
            # every process must see a pool the instant it exists —
            # worker pools / clusters take the coordinated-restart path
            return _json(
                {"error": "online pool topology changes need a "
                          "single-process deployment; add/remove the "
                          "pool spec in the server args and restart"},
                400,
            )
        from ..placement import topology as topomod
        from ..storage.errors import StorageError

        if op == "pool/expand":
            try:
                d = json.loads(body) if body else {}
                spec = str(d["spec"])
                set_size = int(d.get("setSize", 0) or 0)
            except (ValueError, KeyError, TypeError):
                raise s3err.InvalidArgument from None
            bg = server.background
            try:
                out = await server._run(
                    topomod.expand_pool, server.store, spec, set_size,
                    bg.mrf.add if bg is not None else None,
                )
            except (ValueError, StorageError, OSError) as e:
                return _json({"error": str(e)}, 400)
            return _json(out)
        # pool/remove: only a pool decommissioned to completion detaches
        idx = _int_q(q, "pool", -1)
        st = server.pool_mgr.status(idx)
        if st is None or st.state != "complete":
            return _json(
                {"error": "pool must be decommissioned to completion "
                          "before removal"},
                400,
            )
        try:
            out = await server._run(
                topomod.remove_pool, server.store, idx
            )
        except ValueError as e:
            return _json({"error": str(e)}, 400)
        # decommission records key pool INDEXES: re-key them (and drop
        # the removed pool's, incl. persisted checkpoints) so a stale
        # 'complete' can never vouch for a later pool at this index
        await server._run(server.pool_mgr.reindex_after_remove, idx)
        return _json(out)

    # -- pools: decommission / rebalance ----------------------------------
    if op.startswith("pools/") and server.pool_mgr is not None:
        pm = server.pool_mgr
        if op == "pools/list" and m == "GET":
            authz("admin:ServerInfo")
            return _json(pm.pool_usage())
        if op == "pools/decommission" and m == "POST":
            authz("admin:DecommissionPool")
            try:
                st = await server._run(
                    pm.start_decommission, _int_q(q, "pool", -1)
                )
            except ValueError as e:
                return _json({"error": str(e)}, 400)
            return _json(st.to_dict())
        if op == "pools/decommission/status" and m == "GET":
            authz("admin:DecommissionPool")
            st = pm.status(_int_q(q, "pool", -1))
            return _json(st.to_dict() if st else {"state": "idle"})
        if op == "pools/decommission/cancel" and m == "POST":
            authz("admin:DecommissionPool")
            pm.cancel_decommission(_int_q(q, "pool", -1))
            return web.Response(status=200)
        if op == "pools/rebalance" and m == "POST":
            authz("admin:RebalancePool")
            thr = None
            if q.get("threshold"):
                try:
                    thr = float(q["threshold"])
                except ValueError:
                    raise s3err.InvalidArgument from None
            try:
                out = await server._run(pm.start_rebalance_continuous, thr)
            except ValueError as e:
                return _json({"error": str(e)}, 400)
            return _json(out)
        if op == "pools/rebalance/status" and m == "GET":
            authz("admin:RebalancePool")
            return _json(pm.rebalance_status())
        if op == "pools/rebalance/stop" and m == "POST":
            authz("admin:RebalancePool")
            return _json(pm.stop_rebalance())

    # -- profiling (reference cmd/admin-handlers.go:1024 ProfileHandler) ---
    if op == "profile" and m == "POST":
        authz("admin:Profiling")
        from . import profiling

        ptype = q.get("profilerType", "cpu")
        try:
            duration = min(float(q.get("duration", "5") or 5), 120.0)
        except ValueError:
            raise s3err.InvalidArgument from None
        if ptype not in profiling.PROFILERS:
            raise s3err.InvalidArgument
        if q.get("local") == "true":
            # fan-out leaf: profile this node only
            text = await server._run(profiling.run_local, ptype, duration)
            return _json({"nodes": {"local": {ptype: text}}})
        return _json(
            await server._run(profiling.run_cluster, server, ptype, duration)
        )

    # -- config KV ---------------------------------------------------------
    if op == "get-config" and m == "GET":
        authz("admin:ConfigUpdate")
        return await _json_madmin(server.config.dump())
    if op == "set-config-kv" and m == "PUT":
        authz("admin:ConfigUpdate")
        try:
            d = json.loads(body)
            await server._run(
                server.config.set, d["subsys"], d["key"], str(d["value"])
            )
        except (ValueError, KeyError) as e:
            return _json({"error": str(e)}, 400)
        return web.Response(status=200)

    # -- self-measurement plane (diag/: speedtests, netperf, healthinfo) ---
    if op == "speedtest" and m == "POST":
        # autotuning object speedtest through the real erasure path on
        # the QoS background lane (reference cmd/perf-tests.go); the
        # coordinator merges per-node rows, `local=true` leaves measure
        authz("admin:Health")
        from .. import diag

        size = _int_q(q, "size", 1 << 20, lo=4096, hi=64 << 20)
        ops_n = _int_q(q, "ops", 4, lo=1, hi=64)
        concurrency = _int_q(q, "concurrency", 0, lo=0, hi=256)
        if q.get("local") == "true":
            row = await server._run(
                diag.object_speedtest, server, size, ops_n, concurrency
            )
            return _json({"nodes": {"local": row}})
        return _json(await server._run(
            diag.run_cluster, server, "object", "speedtest",
            {"size": str(size), "ops": str(ops_n),
             "concurrency": str(concurrency)},
            lambda: diag.object_speedtest(server, size, ops_n, concurrency),
        ))
    if op == "speedtest/drive" and m == "POST":
        authz("admin:Health")
        from .. import diag

        size_mb = _int_q(q, "sizeMiB", 4, lo=1, hi=64)
        rand_count = _int_q(q, "randCount", 16, lo=1, hi=256)
        if q.get("local") == "true":
            return _json({"nodes": {"local": await server._run(
                diag.drive_speedtest, server, size_mb, rand_count)}})
        return _json(await server._run(
            diag.run_cluster, server, "drive", "speedtest/drive",
            {"sizeMiB": str(size_mb), "randCount": str(rand_count)},
            lambda: diag.drive_speedtest(server, size_mb, rand_count),
        ))
    if op == "speedtest/net" and m == "POST":
        authz("admin:Health")
        from .. import diag

        size = _int_q(q, "size", 0, lo=0, hi=64 << 20)
        count = _int_q(q, "count", 4, lo=1, hi=64)
        pings = _int_q(q, "pings", 8, lo=1, hi=256)
        if q.get("local") == "true":
            return _json({"nodes": {"local": await server._run(
                diag.run_netperf, server, size, count, pings)}})
        return _json(await server._run(
            diag.run_cluster, server, "net", "speedtest/net",
            {"size": str(size), "count": str(count), "pings": str(pings)},
            lambda: diag.run_netperf(server, size, count, pings),
        ))
    if op == "speedtest/object" and m == "POST":
        # legacy fixed-concurrency form, kept for compatibility — the
        # autotuning `speedtest` op above supersedes it
        authz("admin:Health")
        size = _int_q(q, "size", 1 << 20, lo=4096, hi=64 << 20)
        count = _int_q(q, "count", 8, lo=1, hi=32)
        return _json(await server._run(_object_speedtest, server, size, count))
    if op == "healthinfo" and m == "GET":
        authz("admin:OBDInfo")
        from ..diag import healthinfo as hinfo

        info = await server._run(hinfo.build_healthinfo, server)
        if q.get("format") == "zip":
            blob = await server._run(hinfo.healthinfo_zip, info)
            return web.Response(
                status=200, body=blob, content_type="application/zip",
                headers={"Content-Disposition":
                         'attachment; filename="healthinfo.zip"'},
            )
        return _json(info)
    if op == "inspect-data" and m == "GET":
        authz("admin:InspectData")
        from ..diag import healthinfo as hinfo

        bucket = q.get("bucket", "")
        obj = q.get("object", "")
        if not bucket or not obj:
            raise s3err.InvalidArgument
        blob = await server._run(hinfo.inspect_data, server, bucket, obj)
        return web.Response(
            status=200, body=blob, content_type="application/zip",
            headers={"Content-Disposition":
                     'attachment; filename="inspect-data.zip"'},
        )

    # -- info / heal ------------------------------------------------------
    if op == "info" and m == "GET":
        authz("admin:ServerInfo")
        return _json(await server._run(server.server_info))
    if op == "storageinfo" and m == "GET":
        authz("admin:StorageInfo")
        return _json(await server._run(server.storage_info))
    if op.startswith("heal/") or op == "heal":
        authz("admin:Heal")
        parts = op.split("/", 2)
        bucket = parts[1] if len(parts) > 1 else ""
        prefix = parts[2] if len(parts) > 2 else ""
        result = await server._run(server.heal_sweep, bucket, prefix)
        return _json(result)

    raise s3err.NotImplemented_


def server_info_payload(server) -> dict:
    pools = getattr(server.store, "pools", [server.store])
    info = {
        "mode": "online",
        "deploymentID": getattr(pools[0], "deployment_id", ""),
        "region": server.region,
        "pools": [],
        "uptime": int(time.time() - server.started_at),
        "version": "minio-tpu/0.1.0",
        "backendType": "Erasure",
        # SO_REUSEPORT pool identity: which worker answered, how many
        # serve this node (tests + debugging address workers by this)
        "workerIndex": getattr(server, "worker_index", 0),
        "workerCount": getattr(server, "worker_count", 1),
        "pid": os.getpid(),
    }
    for p in pools:
        sets = getattr(p, "sets", [p])
        info["pools"].append(
            {
                "sets": [
                    {
                        "id": s.set_index,
                        "drives": [d.endpoint for d in s.disks],
                        "parity": s.default_parity,
                    }
                    for s in sets
                ]
            }
        )
    return info


def storage_info_payload(server) -> dict:
    out = {"disks": []}
    for d in server.store.disks:
        try:
            di = d.disk_info()
            out["disks"].append(
                {
                    "endpoint": di.endpoint,
                    "total": di.total,
                    "free": di.free,
                    "used": di.used,
                    "state": "ok" if not di.error else di.error,
                }
            )
        except Exception as e:  # noqa: BLE001
            out["disks"].append({"endpoint": d.endpoint, "state": str(e)})
    return out


def _peer_trace_pump(server, peer: str, flt, sub, stop) -> None:
    """Stream one peer's trace records into `sub`'s queue (cluster
    fan-out: a single `mc admin trace`-style stream shows every node).
    Filters forward with the request so peers drop records at the
    source; `local=on` stops the fan-out from recursing."""
    import http.client as _hc
    import socket as _socket
    import urllib.parse as _up

    from .signature import sign_request

    host, _, port = peer.rpartition(":")
    qs = flt.to_query()
    qs["local"] = "on"
    path = "/minio/admin/v3/trace?" + _up.urlencode(qs)
    url = f"http://{host}:{port}{path}"
    conn = None
    try:
        signed = sign_request(
            "GET", url, {}, b"", server.root_user, server.root_pass,
            server.region,
        )
        conn = _hc.HTTPConnection(host, int(port), timeout=2.0)
        conn.request("GET", path, headers=signed)
        resp = conn.getresponse()
        if resp.status != 200:
            return
        buf = b""
        while not stop.is_set():
            try:
                chunk = resp.read1(1 << 16)
            except (_socket.timeout, TimeoutError):
                continue  # idle peer: re-check stop
            if not chunk:
                return  # peer closed its stream
            buf += chunk
            while b"\n" in buf:
                line, buf = buf.split(b"\n", 1)
                if not line.strip():
                    continue
                try:
                    rec = json.loads(line)
                except ValueError:
                    continue
                if not isinstance(rec, dict) or "type" not in rec:
                    continue  # peer's end-of-stream epitaph, not a record
                try:
                    sub.q.put_nowait(rec)
                except Exception:  # noqa: BLE001 — slow consumer: count it
                    sub.dropped += 1
    except Exception:  # noqa: BLE001 — a dead peer mutes, not kills, the stream
        pass
    finally:
        if conn is not None:
            try:
                conn.close()
            except Exception:  # noqa: BLE001 — teardown best-effort
                pass


def _admin_fanout(server, path: str, body: bytes, query: dict) -> dict:
    """Replay an admin POST on every peer's endpoint with ``local=true``
    (the same stop-the-recursion convention the profile fan-out uses);
    drives fault inject/clear and cache clear cluster-wide. Peers are
    contacted in parallel — chaos tooling must work on a chaotic
    cluster, where some peers are down and a serial 10 s connect timeout
    each would make injection itself the outage. A dead peer is a row in
    the result, not a failure."""
    from concurrent.futures import ThreadPoolExecutor

    peers = getattr(server, "peers", None) or []
    if not peers:
        return {}

    def one(peer: str) -> tuple[str, str]:
        host, _, port = peer.rpartition(":")
        try:
            from ..client import S3Client

            cli = S3Client(
                f"{host}:{port}",
                access_key=server.iam.root_user,
                secret_key=server.iam.root_password,
            )
            r = cli.request(
                "POST", f"/minio/admin/v3/{path}",
                query={**query, "local": "true"}, body=body, timeout=10,
            )
            return peer, "ok" if r.status == 200 else f"HTTP {r.status}"
        except Exception as e:  # noqa: BLE001 — a dead peer is a row
            return peer, f"error: {e}"

    with ThreadPoolExecutor(max_workers=min(len(peers), 16)) as pool:
        return dict(pool.map(one, peers))


async def _stream_trace(server, request: web.Request) -> web.StreamResponse:
    """Long-lived JSON-lines trace stream (`mc admin trace` analogue)
    with the reference tracer's filters: ``type=`` (comma-separated
    trace types), ``threshold=`` (minimum duration), ``err-only=on``.
    Unless ``local=on``, records from every cluster peer merge into the
    same stream."""
    import asyncio
    import queue as _queue
    import threading as _threading

    from .. import obs

    q = request.rel_url.query
    try:
        flt = obs.TraceFilter.from_query(q)
    except ValueError:
        raise s3err.InvalidArgument from None
    sub = server.trace.subscribe(
        filter=None if flt.is_noop else flt, label=request.remote or "trace"
    )
    stop = None
    local_only = q.get("local", "").lower() in ("on", "true", "1")
    peers = [] if local_only else (getattr(server, "peers", None) or [])
    if peers:
        stop = _threading.Event()
        for peer in peers:
            # dedicated daemon threads, NOT the long-poll pool: a pump
            # lives as long as its stream, and a few streams on a large
            # cluster would otherwise pin every pool worker and starve
            # the trace/listen waits the pool exists to serve
            _threading.Thread(
                target=_peer_trace_pump,
                args=(server, peer, flt, sub, stop),
                daemon=True, name=f"trace-pump-{peer}",
            ).start()
    resp = web.StreamResponse(headers={"Content-Type": "application/json"})
    await resp.prepare(request)
    loop = asyncio.get_running_loop()
    try:
        while True:
            try:
                rec = await loop.run_in_executor(
                    server._longpoll_pool, sub.q.get, True, 1.0
                )
            except _queue.Empty:
                continue
            await resp.write(json.dumps(rec).encode() + b"\n")
    except (ConnectionResetError, asyncio.CancelledError):
        pass
    finally:
        server.trace.unsubscribe(sub)
        if stop is not None:
            stop.set()
        try:
            # best-effort epitaph: how many records this subscriber lost
            # to its own queue overflowing (visible when the server ends
            # the stream; a vanished client just won't receive it)
            await resp.write(
                json.dumps({"dropped": sub.dropped}).encode() + b"\n"
            )
        except asyncio.CancelledError:
            raise  # client disconnect mid-write: propagate
        except Exception:  # noqa: BLE001 — client already gone
            pass
    return resp


def _object_speedtest(server, size: int, count: int) -> dict:
    """PUT+GET throughput through the full object path (reference
    cmd/perf-tests.go selfSpeedTest)."""
    import os as _os
    import time as _time

    import uuid as _uuid

    bucket = ".minio.sys"
    run_id = str(_uuid.uuid4())[:8]
    payload = _os.urandom(min(size, 64 << 20))
    t0 = _time.perf_counter()
    for i in range(count):
        server.store.put_object(bucket, f"speedtest/{run_id}-{i}", payload)
    put_dt = _time.perf_counter() - t0
    t0 = _time.perf_counter()
    for i in range(count):
        _, it = server.store.get_object(bucket, f"speedtest/{run_id}-{i}")
        for _ in it:
            pass
    get_dt = _time.perf_counter() - t0
    for i in range(count):
        try:
            server.store.delete_object(bucket, f"speedtest/{run_id}-{i}")
        except Exception:  # noqa: BLE001
            pass
    total = len(payload) * count / 2**20
    return {
        "objectSize": len(payload),
        "count": count,
        "putMiBps": round(total / put_dt, 1),
        "getMiBps": round(total / get_dt, 1),
    }
