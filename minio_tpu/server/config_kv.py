"""Dynamic config KV store.

Mirrors the reference's layered config system (internal/config/config.go +
cmd/config-current.go): subsystem-scoped key/value settings persisted in
the backend (.minio.sys/config/settings.json), readable/settable over the
admin API, and applied live for dynamic keys.
"""

from __future__ import annotations

import json
import threading

SYSTEM_BUCKET = ".minio.sys"
CONFIG_KEY = "config/settings.json"

# subsystem -> {key: default}  (mirrors /root/reference/internal/config/
# subsystem registry). Values persist and are served back; only a subset
# applies live today (scanner/heal workers) — the rest provide the
# reference's config surface so tooling round-trips cleanly.
DEFAULTS: dict[str, dict[str, str]] = {
    "scanner": {"interval": "300", "deep_verify": "off"},
    "compression": {"enable": "off", "extensions": "", "mime_types": ""},
    "heal": {"workers": "2"},
    "api": {"requests_max": "0", "cors_allow_origin": "*"},
    "storage_class": {"standard": "", "rrs": ""},
    "replication": {"workers": "2"},
    "batch": {"workers": "1"},
    "identity_openid": {
        "config_url": "", "client_id": "", "claim_name": "policy",
    },
    "identity_ldap": {
        "server_addr": "", "lookup_bind_dn": "", "lookup_bind_password": "",
        "user_dn_search_base_dn": "", "user_dn_search_filter": "",
        "group_search_base_dn": "", "group_search_filter": "",
        # TLS by default (as the reference); plaintext needs an explicit
        # server_insecure=on
        "server_insecure": "off", "tls_skip_verify": "off",
    },
    "notify_webhook": {"enable": "off", "endpoint": "", "auth_token": ""},
    "notify_nats": {"enable": "off", "address": "", "subject": "minio-events"},
    "notify_redis": {"enable": "off", "address": "", "key": "minio-events"},
    "notify_mqtt": {"enable": "off", "broker": "", "topic": "minio-events"},
    "notify_kafka": {"enable": "off", "brokers": "", "topic": "minio-events"},
    "notify_amqp": {"enable": "off", "url": "", "exchange": "", "routing_key": ""},
    "notify_nsq": {"enable": "off", "nsqd_address": "", "topic": "minio-events"},
    "notify_mysql": {
        "enable": "off", "dsn_string": "", "table": "minio_events",
        "format": "namespace",
    },
    "notify_postgres": {
        "enable": "off", "connection_string": "", "table": "minio_events",
        "format": "namespace",
    },
    "notify_elasticsearch": {
        "enable": "off", "url": "", "index": "minio-events", "format": "namespace",
    },
    "logger_webhook": {"enable": "off", "endpoint": ""},
    "audit_webhook": {"enable": "off", "endpoint": ""},
    "audit_kafka": {"enable": "off", "brokers": "", "topic": ""},
    "lambda_webhook": {"enable": "off", "endpoint": ""},
    "site": {"name": "", "region": "us-east-1"},
    "region": {"name": "us-east-1"},  # legacy alias of site.region
    "etcd": {"endpoints": ""},  # accepted, unused (no etcd federation)
    "cache": {"enable": "off", "ttl": "300"},
    "browser": {"enable": "off"},
    "ilm": {"transition_workers": "1", "expiry_workers": "1"},
    "drive": {"max_timeout": "30s"},
    "subnet": {"license": ""},  # accepted for config compat
    "callhome": {"enable": "off", "frequency": "24h"},
    "kms_kes": {
        "endpoint": "", "key_name": "", "api_key": "",
        "cert_file": "", "key_file": "", "capath": "",
    },
    "identity_tls": {"enable": "off", "skip_verify": "off"},
    "identity_plugin": {"url": "", "auth_token": "", "role_policy": ""},
    "policy_opa": {"url": "", "auth_token": ""},  # deprecated in reference
    "policy_plugin": {"url": "", "auth_token": ""},
}


class ConfigKV:
    def __init__(self, store):
        self.store = store
        self._mu = threading.Lock()
        self._kv: dict[str, dict[str, str]] = {}
        self._listeners: list = []  # callbacks(subsys, key, value)
        self._load()

    def _load(self) -> None:
        from ..erasure.quorum import ObjectNotFound

        try:
            _, it = self.store.get_object(SYSTEM_BUCKET, CONFIG_KEY)
            self._kv = json.loads(b"".join(it))
        except ObjectNotFound:
            self._kv = {}

    def _persist(self) -> None:
        self.store.put_object(
            SYSTEM_BUCKET, CONFIG_KEY, json.dumps(self._kv).encode()
        )

    def get(self, subsys: str, key: str) -> str:
        with self._mu:
            v = self._kv.get(subsys, {}).get(key)
        if v is not None:
            return v
        return DEFAULTS.get(subsys, {}).get(key, "")

    def set(self, subsys: str, key: str, value: str) -> None:
        if subsys not in DEFAULTS:
            raise KeyError(f"unknown config subsystem {subsys!r}")
        if key not in DEFAULTS[subsys]:
            raise KeyError(f"unknown key {subsys}.{key}")
        with self._mu:
            self._kv.setdefault(subsys, {})[key] = value
            self._persist()
        for cb in list(self._listeners):
            try:
                cb(subsys, key, value)
            except Exception:  # noqa: BLE001
                pass

    def dump(self) -> dict:
        out = {s: dict(kv) for s, kv in DEFAULTS.items()}
        with self._mu:
            for s, kv in self._kv.items():
                out.setdefault(s, {}).update(kv)
        return out

    def on_change(self, cb) -> None:
        self._listeners.append(cb)
