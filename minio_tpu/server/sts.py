"""STS handlers: AssumeRole — temporary credentials over the S3 endpoint.

Mirrors /root/reference/cmd/sts-handlers.go: POST / with form-encoded
Action=AssumeRole issued by a SigV4-authenticated user mints expiring
credentials + a signed session token carrying the parent identity.
"""

from __future__ import annotations

import json
import urllib.parse
from datetime import datetime, timezone
from xml.sax.saxutils import escape

from aiohttp import web

from . import s3err


async def handle_sts(server, request: web.Request, access_key: str, body: bytes):
    form = dict(urllib.parse.parse_qsl(body.decode("utf-8", "replace")))
    action = form.get("Action", "")
    if action != "AssumeRole":
        raise s3err.NotImplemented_
    if not access_key:
        raise s3err.AccessDenied
    try:
        duration = int(form.get("DurationSeconds", "3600") or "3600")
    except ValueError:
        raise s3err.InvalidArgument from None
    policy = None
    if form.get("Policy"):
        try:
            policy = json.loads(form["Policy"])
        except ValueError:
            raise s3err.MalformedXML from None
    user, token = await server._run(
        server.iam.assume_role, access_key, duration, policy
    )
    exp = datetime.fromtimestamp(user.expiration, tz=timezone.utc).strftime(
        "%Y-%m-%dT%H:%M:%SZ"
    )
    xml = (
        '<?xml version="1.0" encoding="UTF-8"?>'
        '<AssumeRoleResponse xmlns="https://sts.amazonaws.com/doc/2011-06-15/">'
        "<AssumeRoleResult><Credentials>"
        f"<AccessKeyId>{escape(user.access_key)}</AccessKeyId>"
        f"<SecretAccessKey>{escape(user.secret_key)}</SecretAccessKey>"
        f"<SessionToken>{escape(token)}</SessionToken>"
        f"<Expiration>{exp}</Expiration>"
        "</Credentials></AssumeRoleResult></AssumeRoleResponse>"
    )
    return web.Response(body=xml.encode(), content_type="application/xml")
