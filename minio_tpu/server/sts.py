"""STS handlers: AssumeRole — temporary credentials over the S3 endpoint.

Mirrors /root/reference/cmd/sts-handlers.go: POST / with form-encoded
Action=AssumeRole issued by a SigV4-authenticated user mints expiring
credentials + a signed session token carrying the parent identity.
"""

from __future__ import annotations

import json
import urllib.parse
from datetime import datetime, timezone
from xml.sax.saxutils import escape

from aiohttp import web

from . import s3err


def _credentials_xml(action: str, user, token: str) -> bytes:
    exp = datetime.fromtimestamp(user.expiration, tz=timezone.utc).strftime(
        "%Y-%m-%dT%H:%M:%SZ"
    )
    return (
        '<?xml version="1.0" encoding="UTF-8"?>'
        f'<{action}Response xmlns="https://sts.amazonaws.com/doc/2011-06-15/">'
        f"<{action}Result><Credentials>"
        f"<AccessKeyId>{escape(user.access_key)}</AccessKeyId>"
        f"<SecretAccessKey>{escape(user.secret_key)}</SecretAccessKey>"
        f"<SessionToken>{escape(token)}</SessionToken>"
        f"<Expiration>{exp}</Expiration>"
        f"</Credentials></{action}Result></{action}Response>"
    ).encode()


def _duration(form: dict) -> int:
    """DurationSeconds form param -> int, 400 on garbage (shared by all
    AssumeRole* variants)."""
    try:
        return int(form.get("DurationSeconds", "3600") or "3600")
    except ValueError:
        raise s3err.InvalidArgument from None


async def handle_sts(server, request: web.Request, access_key: str, body: bytes):
    form = dict(urllib.parse.parse_qsl(body.decode("utf-8", "replace")))
    action = form.get("Action", "")
    if action == "AssumeRoleWithWebIdentity":
        return await _web_identity(server, form)
    if action == "AssumeRoleWithLDAPIdentity":
        return await _ldap_identity(server, form)
    if action == "AssumeRoleWithCertificate":
        return await _certificate(server, request, form)
    if action != "AssumeRole":
        raise s3err.NotImplemented_
    if not access_key:
        raise s3err.AccessDenied
    duration = _duration(form)
    policy = None
    if form.get("Policy"):
        try:
            policy = json.loads(form["Policy"])
        except ValueError:
            raise s3err.MalformedXML from None
    user, token = await server._run(
        server.iam.assume_role, access_key, duration, policy
    )
    return web.Response(
        body=_credentials_xml("AssumeRole", user, token),
        content_type="application/xml",
    )


async def _certificate(server, request: web.Request, form: dict) -> web.Response:
    """mTLS STS: the verified client certificate IS the credential
    (/root/reference/cmd/sts-handlers.go:180 AssumeRoleWithCertificate).

    Requires the TLS listener (the CA-validated peer certificate arrives
    on the connection's ssl object); the certificate's CommonName names
    both the minted identity and the policy it gets — the reference's
    `parentUser = cert.Subject.CommonName` + policy-by-CN mapping.
    Gated on MINIO_IDENTITY_TLS_ENABLE like the reference's sts_tls
    config subsystem.
    """
    enabled = server.config.get("identity_tls", "enable") if hasattr(
        server, "config"
    ) else ""
    import os as _os

    if (_os.environ.get("MINIO_IDENTITY_TLS_ENABLE", enabled or "")
            .lower() not in ("on", "true", "1")):
        raise s3err.NotImplemented_
    ssl_obj = request.transport.get_extra_info("ssl_object")
    if ssl_obj is None:
        # reference: sts-handlers.go rejects non-TLS certificate STS
        raise s3err.AccessDenied
    der = ssl_obj.getpeercert(binary_form=True)
    if not der:
        raise s3err.AccessDenied
    # the handshake already chain-validated against the certs-dir CAs
    # (CERT_OPTIONAL still verifies any presented cert); here we only
    # check the leaf is client-auth capable and extract identity
    from ..crypto import x509util

    if not x509util.cert_is_client_auth(der):
        # reference rejects certs whose EKU lists neither ClientAuth nor
        # Any — a chain-valid server-only cert must not mint credentials
        raise s3err.AccessDenied
    cn = x509util.cert_common_name(der)
    if not cn:
        raise s3err.AccessDenied
    duration = _duration(form)
    if cn not in server.iam.policies:
        # reference: no policy matching the CN -> auth failure, so a
        # random-but-valid client cert can't mint credentials
        raise s3err.AccessDenied
    user, session = await server._run(
        server.iam.assume_role_certificate, cn, duration,
        x509util.cert_not_after(der),
    )
    return web.Response(
        body=_credentials_xml("AssumeRoleWithCertificate", user, session),
        content_type="application/xml",
    )


async def _ldap_identity(server, form: dict) -> web.Response:
    """Directory-backed STS: the LDAP username/password pair IS the
    credential — no SigV4 auth required
    (/root/reference/cmd/sts-handlers.go:649 AssumeRoleWithLDAPIdentity:
    lookup-bind search -> user bind -> policy map -> temp credentials)."""
    from ..iam import ldap as ldapmod

    cfg = ldapmod.from_config(server.config)
    if not cfg.enabled:
        raise s3err.NotImplemented_
    username = form.get("LDAPUsername", "")
    password = form.get("LDAPPassword", "")
    if not username or not password:
        raise s3err.InvalidArgument
    duration = _duration(form)
    try:
        user_dn, groups = await server._run(cfg.bind_user, username, password)
    except ldapmod.LDAPError:
        raise s3err.AccessDenied from None
    except (OSError, ValueError):
        # directory unreachable, or a malformed configured filter
        # template: a server-side failure, not bad credentials
        raise s3err.InternalError from None
    # stale names (policy deleted after mapping) drop out; reject only
    # when NOTHING valid remains (the reference's PolicyDBGet behavior)
    policies = [
        p
        for p in server.iam.ldap_policies_for(user_dn, groups)
        if p in server.iam.policies
    ]
    if not policies:
        raise s3err.AccessDenied
    user, session = await server._run(
        server.iam.assume_role_ldap, user_dn, groups, duration, policies
    )
    return web.Response(
        body=_credentials_xml("AssumeRoleWithLDAPIdentity", user, session),
        content_type="application/xml",
    )


async def _web_identity(server, form: dict) -> web.Response:
    """OIDC-federated STS: unauthenticated; the JWT is the credential
    (/root/reference/cmd/sts-handlers.go:62 AssumeRoleWithWebIdentity)."""
    from ..iam.oidc import OIDCError, OIDCProvider

    provider = getattr(server, "_oidc", None)
    if provider is None or not provider.enabled:
        provider = OIDCProvider()
        server._oidc = provider
    if not provider.enabled:
        raise s3err.NotImplemented_
    token = form.get("WebIdentityToken", "")
    if not token:
        raise s3err.InvalidArgument
    duration = _duration(form)
    try:
        claims = await server._run(provider.validate, token)
    except OIDCError:
        raise s3err.AccessDenied from None
    policies = provider.policies_for(claims)
    if not policies or any(p not in server.iam.policies for p in policies):
        # no grant, or a claim naming a nonexistent policy: surface the
        # misconfiguration at login rather than minting dead credentials
        raise s3err.AccessDenied
    user, session = await server._run(
        server.iam.assume_role_web_identity,
        str(claims.get("sub", "")),
        duration,
        policies,
        float(claims["exp"]),
    )
    return web.Response(
        body=_credentials_xml("AssumeRoleWithWebIdentity", user, session),
        content_type="application/xml",
    )
