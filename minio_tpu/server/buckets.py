"""BucketMetadataSys — per-bucket config persisted in the object store.

Mirrors the reference's BucketMetadataSys (/root/reference/cmd/
bucket-metadata-sys.go): bucket metadata (creation time, versioning config,
policy, tags, ...) lives as objects under the system volume and is cached
in memory; every node recovers it from the backend at boot.
"""

from __future__ import annotations

import json
import threading

from ..erasure.quorum import ObjectNotFound

SYSTEM_BUCKET = ".minio.sys"
CONFIG_PREFIX = "buckets"


class BucketMetadata:
    def __init__(self, name: str, created_ns: int = 0):
        self.name = name
        self.created_ns = created_ns
        self.versioning = False
        self.versioning_suspended = False
        self.policy: dict | None = None
        self.tags: dict[str, str] = {}
        self.quota: int = 0
        self.lifecycle: str | None = None  # raw XML, served back as stored
        self.notification: str | None = None
        self.encryption: str | None = None
        self.object_lock: str | None = None
        self.cors: str | None = None
        self.replication: str | None = None
        self.ownership: str | None = None  # OwnershipControls XML

    def to_json(self) -> bytes:
        return json.dumps(self.__dict__).encode()

    @staticmethod
    def from_json(name: str, buf: bytes) -> "BucketMetadata":
        bm = BucketMetadata(name)
        try:
            bm.__dict__.update(json.loads(buf))
        except (ValueError, TypeError):
            pass
        bm.name = name
        return bm


class BucketMetadataSys:
    def __init__(self, store):
        self.store = store  # object layer (ErasureSet / pools)
        self._cache: dict[str, BucketMetadata] = {}
        self._lock = threading.Lock()
        # post-persist hook (site replication); set to None while applying
        # a remote change to avoid echo loops
        self.on_change = None

    def peek(self, bucket: str):
        """Cache-only lookup (no storage IO): for callers on the event
        loop that must never block, e.g. CORS response decoration."""
        with self._lock:
            return self._cache.get(bucket)

    def _key(self, bucket: str) -> str:
        return f"{CONFIG_PREFIX}/{bucket}/.metadata.json"

    def get(self, bucket: str) -> BucketMetadata:
        with self._lock:
            bm = self._cache.get(bucket)
        if bm is not None:
            return bm
        try:
            _, it = self.store.get_object(SYSTEM_BUCKET, self._key(bucket))
            bm = BucketMetadata.from_json(bucket, b"".join(it))
        except ObjectNotFound:
            bm = BucketMetadata(bucket)  # never configured: defaults
        # any OTHER failure (quorum loss, IO) must propagate — silently
        # defaulting would run a versioned bucket unversioned
        with self._lock:
            self._cache[bucket] = bm
        return bm

    def set(self, bucket: str, bm: BucketMetadata, notify: bool = True) -> None:
        """notify=False for internally-applied changes (site replication
        applying a peer's update) — toggling the shared hook instead would
        race across threads and could permanently drop it."""
        self.store.put_object(SYSTEM_BUCKET, self._key(bucket), bm.to_json())
        with self._lock:
            self._cache[bucket] = bm
        if notify and self.on_change is not None:
            try:
                self.on_change(bucket, bm)
            except Exception:  # noqa: BLE001 — sync is best-effort async
                pass

    def drop(self, bucket: str) -> None:
        with self._lock:
            self._cache.pop(bucket, None)
        try:
            self.store.delete_object(SYSTEM_BUCKET, self._key(bucket))
        except Exception:  # noqa: BLE001
            pass
        # a deleted (or soon recreated) bucket must not leave listing
        # caches behind — in memory or persisted
        from ..erasure import listing as _listing

        _listing.invalidate_bucket(bucket)
        try:
            for raw in self.store.walk_objects(
                SYSTEM_BUCKET, f"{CONFIG_PREFIX}/{bucket}/.metacache/"
            ):
                try:
                    self.store.delete_object(SYSTEM_BUCKET, raw)
                except Exception:  # noqa: BLE001
                    pass
        except Exception:  # noqa: BLE001
            pass
