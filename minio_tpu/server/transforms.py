"""Object data transforms: transparent compression + server-side encryption.

Order mirrors the reference: compress first, then encrypt
(/root/reference/cmd/object-api-utils.go compression +
cmd/encryption-v1.go). Both record internal metadata so reads invert the
pipeline; logical ("actual") size is preserved for listings/HEAD.

Compression framing: sequence of [u32 plain_len][u32 comp_len][zlib bytes]
blocks over 1 MiB plaintext blocks (the reference uses S2 snappy framing;
zlib is this build's codec — the capability, not the wire format, is the
parity target).
"""

from __future__ import annotations

import os
import struct
import zlib

from ..crypto import sse as ssemod

META_COMPRESSION = "x-minio-internal-compression"
COMP_BLOCK = 1 << 20

# extensions/content-types never worth compressing
# (reference internal/config/compress defaults)
INCOMPRESSIBLE_EXT = {
    ".gz", ".bz2", ".zst", ".xz", ".zip", ".7z", ".rar",
    ".jpg", ".jpeg", ".png", ".gif", ".webp", ".mp4", ".mkv", ".mov",
    ".mp3", ".aac", ".ogg", ".parquet",
}


def compression_enabled() -> bool:
    return os.environ.get("MINIO_COMPRESSION_ENABLE", "off") in ("on", "true", "1")


def should_compress(key: str, content_type: str, size: int) -> bool:
    if not compression_enabled() or size < 4096:
        return False
    ext = os.path.splitext(key)[1].lower()
    if ext in INCOMPRESSIBLE_EXT:
        return False
    if content_type.startswith(("image/", "video/", "audio/")):
        return False
    return True


def compress(data: bytes) -> bytes:
    out = bytearray()
    for off in range(0, len(data), COMP_BLOCK):
        block = data[off : off + COMP_BLOCK]
        cb = zlib.compress(block, 1)
        out += struct.pack("<II", len(block), len(cb))
        out += cb
    return bytes(out)


def decompress(data: bytes) -> bytes:
    out = bytearray()
    off = 0
    n = len(data)
    while off < n:
        if off + 8 > n:
            raise ValueError("truncated compression frame header")
        plain_len, comp_len = struct.unpack_from("<II", data, off)
        off += 8
        block = zlib.decompress(data[off : off + comp_len])
        if len(block) != plain_len:
            raise ValueError("compression frame length mismatch")
        out += block
        off += comp_len
    return bytes(out)


class TransformResult:
    __slots__ = ("data", "metadata", "response_headers")

    def __init__(self, data: bytes, metadata: dict, response_headers: dict):
        self.data = data
        self.metadata = metadata
        self.response_headers = response_headers


def encode_for_store(
    body: bytes,
    key: str,
    content_type: str,
    headers,
    bucket_encryption_algo: str | None,
    kms: ssemod.KMS,
    bucket: str,
) -> TransformResult:
    """Apply compress-then-encrypt per request headers / bucket defaults."""
    meta: dict[str, str] = {}
    resp: dict[str, str] = {}
    data = body

    if should_compress(key, content_type, len(body)):
        compressed = compress(data)
        if len(compressed) < len(data):  # keep only when it actually helps
            meta[META_COMPRESSION] = "zlib/v1"
            meta[ssemod.META_ACTUAL_SIZE] = str(len(data))
            data = compressed

    ssec_key = ssemod.parse_ssec_headers(headers)
    sse_algo = headers.get("x-amz-server-side-encryption", "")
    if not ssec_key and not sse_algo and bucket_encryption_algo:
        sse_algo = bucket_encryption_algo  # bucket default encryption
    if ssec_key or sse_algo:
        import secrets as _secrets

        context = f"{bucket}/{key}"
        if ssec_key:
            oek, base_iv, sealed, m2, r2 = _ssec_setup(ssec_key, context)
            meta.update(m2)
            resp.update(r2)
        else:
            base_iv = _secrets.token_bytes(ssemod.NONCE_SIZE)
            oek, sealed, m2, r2 = _sse_s3_kms_setup(sse_algo, headers, kms, context)
            meta.update(m2)
            resp.update(r2)
            meta[ssemod.META_SEALED_KEY] = sealed.hex()
            meta[ssemod.META_IV] = base_iv.hex()
        meta.setdefault(ssemod.META_ACTUAL_SIZE, str(len(body)))
        data = ssemod.encrypt_stream(data, oek, base_iv)
    return TransformResult(data, meta, resp)


META_PART_SIZES = ssemod.META_PART_SIZES


def is_transformed(user_defined: dict) -> bool:
    return ssemod.META_ALGO in user_defined or META_COMPRESSION in user_defined


def part_iv(base_iv: bytes, part_number: int) -> bytes:
    """Per-part base IV: parts encrypt as independent packet streams under
    one OEK, so each needs a distinct IV (nonce reuse across parts would
    be catastrophic) bound to its part number (no part swapping)."""
    import hashlib as _hashlib

    return _hashlib.sha256(
        base_iv + part_number.to_bytes(4, "big")
    ).digest()[: ssemod.NONCE_SIZE]


def _ssec_setup(
    ssec_key: bytes, context: str
) -> tuple[bytes, bytes, bytes, dict, dict]:
    """Shared SSE-C key sealing: fresh OEK sealed under the customer key.
    Single source of truth for single PUTs and multipart initiation.
    Returns (oek, base_iv, sealed, metadata, response headers)."""
    import base64 as _b64
    import hashlib as _hashlib
    import secrets as _secrets

    base_iv = _secrets.token_bytes(ssemod.NONCE_SIZE)
    oek = _secrets.token_bytes(32)
    sealed = ssemod._aesgcm(ssec_key).encrypt(base_iv, oek, context.encode())
    key_md5 = _b64.b64encode(_hashlib.md5(ssec_key).digest()).decode()
    meta = {
        ssemod.META_ALGO: "SSE-C",
        ssemod.META_SSEC_KEY_MD5: key_md5,
        ssemod.META_SEALED_KEY: sealed.hex(),
        ssemod.META_IV: base_iv.hex(),
    }
    resp = {
        "x-amz-server-side-encryption-customer-algorithm": "AES256",
        "x-amz-server-side-encryption-customer-key-MD5": key_md5,
    }
    return oek, base_iv, sealed, meta, resp


def _sse_s3_kms_setup(
    sse_algo: str, headers, kms: ssemod.KMS, context: str
) -> tuple[bytes, bytes, dict, dict]:
    """Shared SSE-S3/SSE-KMS key generation + metadata/response headers —
    single source of truth for single PUTs and multipart initiation."""
    meta: dict[str, str] = {}
    resp: dict[str, str] = {}
    if sse_algo == "aws:kms":
        key_id = headers.get(
            "x-amz-server-side-encryption-aws-kms-key-id", kms.key_id
        )
        # seal under the REQUESTED named key, so deleting that key cuts
        # off exactly the objects encrypted with it (reference
        # cmd/encryption-v1.go newEncryptMetadata keyID plumbing)
        oek, sealed = kms.generate_key(context, key_id)
        meta[ssemod.META_ALGO] = "SSE-KMS"
        meta[ssemod.META_KMS_KEY_ID] = key_id
        resp["x-amz-server-side-encryption"] = "aws:kms"
        resp["x-amz-server-side-encryption-aws-kms-key-id"] = key_id
    else:
        oek, sealed = kms.generate_key(context)
        meta[ssemod.META_ALGO] = "SSE-S3"
        resp["x-amz-server-side-encryption"] = "AES256"
    return oek, sealed, meta, resp


def multipart_sse_init(
    headers, bucket_encryption_algo: str | None, kms: ssemod.KMS,
    bucket: str, key: str,
) -> tuple[dict, dict] | None:
    """SSE setup at CreateMultipartUpload (reference encrypts multipart
    per part under one object key, cmd/encryption-v1.go +
    cmd/erasure-multipart.go:575). Returns (upload metadata, response
    headers) or None when no encryption applies.

    SSE-C: the customer key seals a fresh OEK at initiation; every
    UploadPart must re-present the same key (AWS semantics) — the key
    itself is never stored, only its MD5 for mismatch detection."""
    import secrets as _secrets

    ssec_key = ssemod.parse_ssec_headers(headers)
    if ssec_key:
        _oek, _iv, _sealed, meta, resp = _ssec_setup(
            ssec_key, f"{bucket}/{key}"
        )
        del _oek  # re-unsealed per part from the presented key
        return meta, resp
    sse_algo = headers.get("x-amz-server-side-encryption", "")
    if not sse_algo and bucket_encryption_algo:
        sse_algo = bucket_encryption_algo
    if not sse_algo:
        return None
    base_iv = _secrets.token_bytes(ssemod.NONCE_SIZE)
    oek, sealed, meta, resp = _sse_s3_kms_setup(
        sse_algo, headers, kms, f"{bucket}/{key}"
    )
    del oek  # re-unsealed per part
    meta[ssemod.META_SEALED_KEY] = sealed.hex()
    meta[ssemod.META_IV] = base_iv.hex()
    return meta, resp


def encrypt_part(
    data: bytes, upload_meta: dict, part_number: int, kms: ssemod.KMS,
    bucket: str, key: str, headers=None,
) -> bytes:
    oek = _unseal_oek(upload_meta, headers or {}, bucket, key, kms)
    base_iv = bytes.fromhex(upload_meta[ssemod.META_IV])
    return ssemod.encrypt_stream(data, oek, part_iv(base_iv, part_number))


def encrypt_part_iter(
    chunks, upload_meta: dict, part_number: int, kms: ssemod.KMS,
    bucket: str, key: str, plain_count: list, headers=None,
):
    """Streaming variant: yields sealed packets; plain_count[0] gets the
    plaintext size when the source is exhausted (5 GiB parts must not
    buffer in RAM)."""
    oek = _unseal_oek(upload_meta, headers or {}, bucket, key, kms)
    base_iv = bytes.fromhex(upload_meta[ssemod.META_IV])
    return ssemod.encrypt_packets_iter(
        chunks, oek, part_iv(base_iv, part_number), plain_count
    )


def _part_layout(user_defined: dict) -> list[tuple[int, int, int, int]]:
    """[(part#, plain_size, plain_off, stored_off)] per completed part."""
    import json as _json

    entries = _json.loads(user_defined[META_PART_SIZES])
    out = []
    plain_off = stored_off = 0
    for num, psize in entries:
        out.append((int(num), int(psize), plain_off, stored_off))
        plain_off += int(psize)
        stored_off += ssemod.stored_size(int(psize))
    return out


def decode_range_multipart(
    read_fn, user_defined: dict, headers, bucket: str, key: str,
    kms: ssemod.KMS, start: int, length: int,
) -> bytes:
    """Ranged decrypt of an SSE multipart object: each part is its own
    packet stream; a range maps to the overlapping parts' packet runs."""
    oek = _unseal_oek(user_defined, headers, bucket, key, kms)
    base_iv = bytes.fromhex(user_defined[ssemod.META_IV])
    out = bytearray()
    end = start + length
    for num, psize, plain_off, stored_off in _part_layout(user_defined):
        if plain_off + psize <= start:
            continue
        if plain_off >= end:
            break
        lo = max(start - plain_off, 0)
        hi = min(end - plain_off, psize)
        s_off, s_len, skip = ssemod.stored_range(lo, hi - lo)
        s_len = min(s_len, ssemod.stored_size(psize) - s_off)
        stored = read_fn(stored_off + s_off, s_len)
        plain = ssemod.decrypt_packets(
            stored, oek, part_iv(base_iv, num),
            s_off // ssemod.STORED_PACKET,
        )
        out += plain[skip : skip + (hi - lo)]
    return bytes(out)


def logical_size(user_defined: dict, stored: int) -> int:
    v = user_defined.get(ssemod.META_ACTUAL_SIZE)
    return int(v) if v is not None else stored


def _unseal_oek(user_defined: dict, headers, bucket: str, key: str, kms: ssemod.KMS) -> bytes:
    algo = user_defined[ssemod.META_ALGO]
    sealed = bytes.fromhex(user_defined[ssemod.META_SEALED_KEY])
    base_iv = bytes.fromhex(user_defined[ssemod.META_IV])
    context = f"{bucket}/{key}"
    if algo == "SSE-C":
        ssec_key = ssemod.parse_ssec_headers(headers)
        if ssec_key is None:
            raise ssemod.CryptoError("object is SSE-C encrypted: key required")
        import base64 as _b64
        import hashlib as _hashlib

        if (
            _b64.b64encode(_hashlib.md5(ssec_key).digest()).decode()
            != user_defined.get(ssemod.META_SSEC_KEY_MD5)
        ):
            raise ssemod.CryptoError("SSE-C key does not match object key")
        try:
            return ssemod._aesgcm(ssec_key).decrypt(base_iv, sealed, context.encode())
        except Exception:
            raise ssemod.CryptoError("SSE-C unseal failed") from None
    kid = user_defined.get(ssemod.META_KMS_KEY_ID) or None
    try:
        return kms.unseal(sealed, context, kid)
    except ssemod.CryptoError:
        if not kid or kid == kms.key_id:
            raise
        # legacy objects (pre-keyring) recorded the requested key id in
        # metadata but sealed the OEK under the default master key — fall
        # back so an upgrade never bricks existing SSE-KMS data
        return kms.unseal(sealed, context)


def decode_full(
    stored: bytes, user_defined: dict, headers, bucket: str, key: str, kms: ssemod.KMS
) -> bytes:
    """Invert the full pipeline (decrypt then decompress)."""
    data = stored
    if META_PART_SIZES in user_defined:
        layout = _part_layout(user_defined)
        total = sum(p[1] for p in layout)

        def rf(off, ln):
            return stored[off : off + ln]

        return decode_range_multipart(
            rf, user_defined, headers, bucket, key, kms, 0, total
        )
    if ssemod.META_ALGO in user_defined:
        oek = _unseal_oek(user_defined, headers, bucket, key, kms)
        base_iv = bytes.fromhex(user_defined[ssemod.META_IV])
        data = ssemod.decrypt_stream(data, oek, base_iv)
    if user_defined.get(META_COMPRESSION) == "zlib/v1":
        data = decompress(data)
    return data


def decode_range(
    read_fn,
    stored_size: int,
    user_defined: dict,
    headers,
    bucket: str,
    key: str,
    kms: ssemod.KMS,
    start: int,
    length: int,
) -> bytes:
    """Ranged read through the transform pipeline.

    SSE-only objects map ranges to packet runs (O(range)); compressed
    objects decode fully (framing has no random access in v1)."""
    if user_defined.get(META_COMPRESSION) == "zlib/v1":
        full = decode_full(read_fn(0, stored_size), user_defined, headers, bucket, key, kms)
        return full[start : start + length]
    if META_PART_SIZES in user_defined:
        return decode_range_multipart(
            read_fn, user_defined, headers, bucket, key, kms, start, length
        )
    if ssemod.META_ALGO in user_defined:
        oek = _unseal_oek(user_defined, headers, bucket, key, kms)
        base_iv = bytes.fromhex(user_defined[ssemod.META_IV])
        s_off, s_len, skip = ssemod.stored_range(start, length)
        s_len = min(s_len, stored_size - s_off)
        stored = read_fn(s_off, s_len)
        plain = ssemod.decrypt_packets(
            stored, oek, base_iv, s_off // ssemod.STORED_PACKET
        )
        return plain[skip : skip + length]
    return read_fn(start, length)
