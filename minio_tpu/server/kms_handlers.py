"""KMS API plane: /minio/kms/v1/* — key lifecycle over the configured
backend (builtin keyring or KES).

Mirrors /root/reference/cmd/kms-router.go + kms-handlers.go: status,
metrics, apis, version, key/create, key/list, key/status, plus
key/delete and key/import (the madmin key-management surface,
/root/reference/cmd/admin-handlers.go KMSCreateKey/KMSKeyStatus lineage).
Every route is admin-authenticated and per-key authorized (the
reference's checkKMSActionAllowed: policy action + key-id resource).
"""

from __future__ import annotations

import base64
import json
import re

from aiohttp import web

from ..crypto.sse import CryptoError
from . import s3err

_KEY_NAME_RE = re.compile(r"^[A-Za-z0-9][A-Za-z0-9_.-]{0,79}$")
_PATTERN_RE = re.compile(r"^[A-Za-z0-9_.*?-]{1,80}$")


def _allowed(server, ak: str, action: str, resource: str = "") -> None:
    if not ak or not server.iam.is_allowed(ak, action, resource):
        raise s3err.AccessDenied


def _check_key_name(name: str) -> None:
    """Key names interpolate into backend URLs (KES paths): constrain the
    charset centrally so no backend ever sees path metacharacters."""
    if not _KEY_NAME_RE.match(name):
        raise s3err.InvalidArgument


def _json_resp(payload, status: int = 200) -> web.Response:
    return web.Response(
        body=json.dumps(payload).encode(), status=status,
        content_type="application/json",
    )


async def handle_kms(server, request: web.Request, ak: str, sub: str,
                     body: bytes) -> web.Response:
    """Dispatch /minio/kms/<sub> (sub includes the version prefix)."""
    q = request.rel_url.query
    m = request.method
    # strip the API version ("v1/...") like the reference's kmsAPIVersionPrefix
    parts = sub.split("/", 1)
    op = parts[1] if len(parts) == 2 else ""

    if op == "status" and m == "GET":
        _allowed(server, ak, "kms:Status")
        try:
            # io-pool: KES/MinKMS status probes remote endpoints and must
            # never block the event loop
            return _json_resp(await server._run(server.kms.status))
        except CryptoError as e:
            return _json_resp(
                {"message": str(e), "apiCode": e.api_code}, status=e.status
            )
    if op == "metrics" and m == "GET":
        _allowed(server, ak, "kms:Metrics")
        return _json_resp(server.kms.kms_metrics())
    if op == "apis" and m == "GET":
        _allowed(server, ak, "kms:API")
        return _json_resp([
            {"method": "GET", "path": "/v1/status"},
            {"method": "GET", "path": "/v1/metrics"},
            {"method": "GET", "path": "/v1/apis"},
            {"method": "GET", "path": "/v1/version"},
            {"method": "POST", "path": "/v1/key/create"},
            {"method": "POST", "path": "/v1/key/import"},
            {"method": "GET", "path": "/v1/key/list"},
            {"method": "GET", "path": "/v1/key/status"},
            {"method": "DELETE", "path": "/v1/key/delete"},
        ])
    if op == "version" and m == "GET":
        _allowed(server, ak, "kms:Version")
        return _json_resp({"version": "v1"})

    key_id = q.get("key-id", "")
    try:
        if op == "key/create" and m == "POST":
            _allowed(server, ak, "kms:CreateKey", key_id)
            if not key_id:
                raise s3err.InvalidArgument
            _check_key_name(key_id)
            await server._run(server.kms.create_key, key_id)
            return web.Response(status=200)
        if op == "key/import" and m == "POST":
            _allowed(server, ak, "kms:ImportKey", key_id)
            if not key_id:
                raise s3err.InvalidArgument
            _check_key_name(key_id)
            try:
                material = base64.b64decode(
                    json.loads(body.decode() or "{}").get("bytes", ""),
                    validate=True,
                )
            except (ValueError, UnicodeDecodeError):
                raise s3err.InvalidArgument from None
            await server._run(server.kms.create_key, key_id, material)
            return web.Response(status=200)
        if op == "key/list" and m == "GET":
            _allowed(server, ak, "kms:ListKeys")
            pattern = q.get("pattern", "*") or "*"
            if not _PATTERN_RE.match(pattern):
                raise s3err.InvalidArgument
            names = await server._run(server.kms.list_keys, pattern)
            return _json_resp([{"name": n} for n in names])
        if op == "key/status" and m == "GET":
            _allowed(server, ak, "kms:KeyStatus", key_id)
            if not key_id:
                key_id = server.kms.key_id  # default key, like the reference
            _check_key_name(key_id)
            return _json_resp(await server._run(server.kms.key_status, key_id))
        if op == "key/delete" and m == "DELETE":
            _allowed(server, ak, "kms:DeleteKey", key_id)
            if not key_id:
                raise s3err.InvalidArgument
            _check_key_name(key_id)
            await server._run(server.kms.delete_key, key_id)
            return web.Response(status=200)
    except CryptoError as e:
        # typed mapping: every CryptoError subclass carries its HTTP
        # status + API code (reference internal/kms/errors.go Error)
        return _json_resp(
            {"message": str(e), "apiCode": e.api_code}, status=e.status
        )
    raise s3err.NotImplemented_
