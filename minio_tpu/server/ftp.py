"""FTP gateway — the protocol frontend over the object layer.

Mirrors the reference's FTP server (/root/reference/cmd/ftp-server.go,
which drives the ObjectLayer directly): buckets appear as top-level
directories, objects as files. Auth checks IAM credentials; operations run
through the same store the S3 API uses, so policies on the underlying
identities still govern data access. Implements the command subset real
clients use: USER/PASS, SYST, PWD, CWD/CDUP, TYPE, PASV/EPSV, LIST/NLST,
RETR, STOR, DELE, MKD, RMD, SIZE, QUIT.

Enable with --ftp <port> on the server CLI (or serve_ftp directly).
"""

from __future__ import annotations

import asyncio
import posixpath

from ..erasure import listing, quorum


class _Session:
    def __init__(self, gw, reader, writer):
        self.gw = gw
        self.reader = reader
        self.writer = writer
        self.user = ""
        self.authed = False
        self.cwd = "/"
        self._pasv_server: asyncio.AbstractServer | None = None
        self._data_ready: asyncio.Future | None = None

    async def send(self, line: str) -> None:
        self.writer.write((line + "\r\n").encode())
        await self.writer.drain()

    # -- path helpers ------------------------------------------------------

    def _resolve(self, arg: str) -> str:
        p = arg if arg.startswith("/") else posixpath.join(self.cwd, arg)
        p = posixpath.normpath(p)
        return "/" if p == "." else p

    @staticmethod
    def _split(path: str) -> tuple[str, str]:
        parts = path.strip("/").split("/", 1)
        bucket = parts[0] if parts[0] else ""
        key = parts[1] if len(parts) > 1 else ""
        return bucket, key

    # -- data connection ---------------------------------------------------

    async def open_pasv(self) -> tuple[str, int]:
        await self.close_pasv()
        loop = asyncio.get_running_loop()
        self._data_ready = loop.create_future()

        def on_connect(r, w):
            if self._data_ready and not self._data_ready.done():
                self._data_ready.set_result((r, w))
            else:
                w.close()

        # bind wide, advertise the address the CLIENT already reached us on
        # (advertising 127.0.0.1 would break every remote client)
        self._pasv_server = await asyncio.start_server(
            on_connect, host="0.0.0.0", port=0
        )
        port = self._pasv_server.sockets[0].getsockname()[1]
        local = self.writer.get_extra_info("sockname")
        host = local[0] if local else "127.0.0.1"
        return host, port

    async def data_conn(self):
        if self._data_ready is None:
            return None
        return await asyncio.wait_for(self._data_ready, timeout=15)

    async def close_pasv(self) -> None:
        if self._pasv_server is not None:
            self._pasv_server.close()
            self._pasv_server = None
        self._data_ready = None


class FTPGateway:
    def __init__(self, server):
        self.server = server  # S3Server: store + iam

    @property
    def store(self):
        return self.server.store

    async def serve(self, host: str, port: int) -> asyncio.AbstractServer:
        return await asyncio.start_server(self._handle, host=host, port=port)

    async def _run(self, fn, *a, **kw):
        # the shared I/O pool: store calls must never ride the tiny default
        # executor (see the deadlock-by-pool note in app.py)
        loop = asyncio.get_running_loop()
        return await loop.run_in_executor(
            self.server._io_pool, lambda: fn(*a, **kw)
        )

    def _allowed(self, s: "_Session", action: str, bucket: str, key: str = "") -> bool:
        """IAM enforcement: FTP identities obey the same policies as S3."""
        from . import s3err

        try:
            self.server._authorize(s.user, action, bucket, key)
            return True
        except s3err.APIError:
            return False

    async def _handle(self, reader, writer) -> None:
        s = _Session(self, reader, writer)
        await s.send("220 minio-tpu FTP gateway ready")
        try:
            while True:
                raw = await reader.readline()
                if not raw:
                    break
                line = raw.decode("utf-8", "replace").strip()
                if not line:
                    continue
                cmd, _, arg = line.partition(" ")
                cmd = cmd.upper()
                if cmd == "QUIT":
                    await s.send("221 Bye")
                    break
                handler = getattr(self, f"_cmd_{cmd.lower()}", None)
                if handler is None:
                    await s.send("502 Command not implemented")
                    continue
                if cmd not in ("USER", "PASS", "SYST", "FEAT") and not s.authed:
                    await s.send("530 Not logged in")
                    continue
                await handler(s, arg)
        except (ConnectionResetError, asyncio.TimeoutError):
            pass
        finally:
            await s.close_pasv()
            writer.close()

    # -- auth --------------------------------------------------------------

    async def _cmd_user(self, s, arg):
        s.user = arg.strip()
        await s.send("331 Password required")

    async def _cmd_pass(self, s, arg):
        import hmac as _hmac

        secret = self.server.iam.lookup_secret(s.user)
        if secret is not None and _hmac.compare_digest(secret, arg.strip()):
            s.authed = True
            await s.send("230 Login successful")
        else:
            await s.send("530 Login incorrect")

    async def _cmd_syst(self, s, arg):
        await s.send("215 UNIX Type: L8")

    async def _cmd_feat(self, s, arg):
        await s.send("211-Features:")
        await s.send(" EPSV")
        await s.send(" SIZE")
        await s.send("211 End")

    async def _cmd_type(self, s, arg):
        await s.send("200 Type set")

    # -- navigation --------------------------------------------------------

    async def _cmd_pwd(self, s, arg):
        await s.send(f'257 "{s.cwd}" is the current directory')

    async def _cmd_cwd(self, s, arg):
        path = s._resolve(arg)
        bucket, key = s._split(path)
        if path == "/" or (
            bucket
            and await self._run(self.store.bucket_exists, bucket)
        ):
            s.cwd = path
            await s.send("250 Directory changed")
        else:
            await s.send("550 No such directory")

    async def _cmd_cdup(self, s, arg):
        s.cwd = posixpath.dirname(s.cwd.rstrip("/")) or "/"
        await s.send("250 Directory changed")

    # -- passive mode ------------------------------------------------------

    async def _cmd_pasv(self, s, arg):
        host, port = await s.open_pasv()
        h = host.replace(".", ",")
        await s.send(f"227 Entering Passive Mode ({h},{port >> 8},{port & 0xFF})")

    async def _cmd_epsv(self, s, arg):
        _, port = await s.open_pasv()
        await s.send(f"229 Entering Extended Passive Mode (|||{port}|)")

    # -- listing -----------------------------------------------------------

    async def _cmd_list(self, s, arg):
        await self._list(s, arg, long=True)

    async def _cmd_nlst(self, s, arg):
        await self._list(s, arg, long=False)

    async def _list(self, s, arg, long: bool) -> None:
        path = s._resolve(arg) if arg and not arg.startswith("-") else s.cwd
        bucket, key = s._split(path)
        action = "s3:ListBucket" if bucket else "s3:ListAllMyBuckets"
        if not self._allowed(s, action, bucket):
            await s.send("550 Permission denied")
            return
        lines = []
        try:
            if not bucket:
                for b in await self._run(self.store.list_buckets):
                    lines.append(_ls_line(b.name, 0, True) if long else b.name)
            else:
                prefix = key + "/" if key else ""
                res = await self._run(
                    listing.list_objects, self.store, bucket, prefix, "", "/", 1000
                )
                for p in res.prefixes:
                    name = p[len(prefix):].rstrip("/")
                    lines.append(_ls_line(name, 0, True) if long else name)
                for o in res.objects:
                    name = o.name[len(prefix):]
                    lines.append(_ls_line(name, o.size, False) if long else name)
        except quorum.BucketNotFound:
            await s.send("550 No such directory")
            return
        await s.send("150 Here comes the directory listing")
        conn = await s.data_conn()
        if conn is None:
            await s.send("425 Use PASV first")
            return
        _, w = conn
        w.write(("".join(line + "\r\n" for line in lines)).encode())
        await w.drain()
        w.close()
        await s.close_pasv()
        await s.send("226 Directory send OK")

    # -- files -------------------------------------------------------------

    async def _cmd_size(self, s, arg):
        bucket, key = s._split(s._resolve(arg))
        if not self._allowed(s, "s3:GetObject", bucket, key):
            await s.send("550 Permission denied")
            return
        try:
            oi = await self._run(self.store.get_object_info, bucket, key)
            await s.send(f"213 {oi.size}")
        except asyncio.CancelledError:
            raise
        except Exception:  # noqa: BLE001
            await s.send("550 No such file")

    async def _cmd_retr(self, s, arg):
        bucket, key = s._split(s._resolve(arg))
        if not self._allowed(s, "s3:GetObject", bucket, key):
            await s.send("550 Permission denied")
            return
        try:
            oi, handle = await self._run(self.store.open_object, bucket, key)
        except asyncio.CancelledError:
            raise
        except Exception:  # noqa: BLE001
            await s.send("550 No such file")
            return
        try:
            await s.send("150 Opening data connection")
            try:
                conn = await s.data_conn()
            except asyncio.TimeoutError:
                conn = None
            if conn is None:
                await s.send("425 Use PASV first")
                return
            _, w = conn
            it = handle.read()
            loop = asyncio.get_running_loop()
            sentinel = object()
            while True:
                chunk = await loop.run_in_executor(
                    self.server._io_pool, lambda: next(it, sentinel)
                )
                if chunk is sentinel:
                    break
                w.write(chunk)
                await w.drain()
            w.close()
            await s.close_pasv()
            await s.send("226 Transfer complete")
        finally:
            # never-started read generators skip their finally on GC; the
            # explicit close releases the namespace read lock immediately
            handle.close()

    MAX_STOR = 1 << 30  # same bound as the S3 PUT body limit

    async def _cmd_stor(self, s, arg):
        bucket, key = s._split(s._resolve(arg))
        if not bucket or not key:
            await s.send("553 Bad path")
            return
        if not self._allowed(s, "s3:PutObject", bucket, key):
            await s.send("550 Permission denied")
            return
        await s.send("150 Ok to send data")
        try:
            conn = await s.data_conn()
        except asyncio.TimeoutError:
            conn = None
        if conn is None:
            await s.send("425 Use PASV first")
            return
        r, w = conn
        chunks: list[bytes] = []
        total = 0
        while True:
            chunk = await r.read(1 << 20)
            if not chunk:
                break
            total += len(chunk)
            if total > self.MAX_STOR:
                w.close()
                await s.close_pasv()
                await s.send("552 Exceeded storage allocation")
                return
            chunks.append(chunk)
        w.close()
        await s.close_pasv()
        try:
            await self._run(self.store.put_object, bucket, key, b"".join(chunks))
            await s.send("226 Transfer complete")
        except asyncio.CancelledError:
            raise
        except Exception:  # noqa: BLE001
            await s.send("550 Store failed")

    async def _cmd_dele(self, s, arg):
        bucket, key = s._split(s._resolve(arg))
        if not self._allowed(s, "s3:DeleteObject", bucket, key):
            await s.send("550 Permission denied")
            return
        try:
            await self._run(self.store.delete_object, bucket, key)
            await s.send("250 Deleted")
        except asyncio.CancelledError:
            raise
        except Exception:  # noqa: BLE001
            await s.send("550 No such file")

    async def _cmd_mkd(self, s, arg):
        bucket, key = s._split(s._resolve(arg))
        action = "s3:PutObject" if key else "s3:CreateBucket"
        if not self._allowed(s, action, bucket, key):
            await s.send("550 Permission denied")
            return
        try:
            if key:
                await self._run(
                    self.store.put_object, bucket,
                    listing.encode_dir_object(key + "/"), b"",
                )
            else:
                await self._run(self.store.make_bucket, bucket)
            await s.send("257 Created")
        except asyncio.CancelledError:
            raise
        except Exception:  # noqa: BLE001
            await s.send("550 Create failed")

    async def _cmd_rmd(self, s, arg):
        bucket, key = s._split(s._resolve(arg))
        action = "s3:DeleteObject" if key else "s3:DeleteBucket"
        if not self._allowed(s, action, bucket, key):
            await s.send("550 Permission denied")
            return
        try:
            if key:
                await self._run(
                    self.store.delete_object, bucket,
                    listing.encode_dir_object(key + "/"),
                )
            else:
                await self._run(self.store.delete_bucket, bucket)
            await s.send("250 Removed")
        except asyncio.CancelledError:
            raise
        except Exception:  # noqa: BLE001
            await s.send("550 Remove failed")


def _ls_line(name: str, size: int, is_dir: bool) -> str:
    kind = "d" if is_dir else "-"
    return f"{kind}rw-r--r-- 1 minio minio {size:>12} Jan  1 00:00 {name}"
