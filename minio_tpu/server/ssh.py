"""SSH transport (RFC 4253/4252/4254 subset) for the SFTP frontend.

The reference serves SFTP through golang.org/x/crypto/ssh
(/root/reference/cmd/sftp-server.go); no SSH stack ships in this image,
so the needed subset is implemented directly on `cryptography`
primitives:

* kex  curve25519-sha256 (RFC 8731), host key ssh-ed25519 (RFC 8709)
* ciphers aes256-ctr / aes128-ctr, MAC hmac-sha2-256 (encrypt-and-MAC)
* userauth: password + publickey (ssh-ed25519)
* connection: session channels + subsystem requests with windowed flow
  control — enough for any standard sftp client

Both roles are implemented (the server, and a client used by the test
suite) over blocking sockets; the server runs a thread per connection so
per-packet crypto stays off the asyncio event loop.
"""

from __future__ import annotations

import hashlib
import hmac as hmac_mod
import os
import socket
import struct
import threading

from cryptography.hazmat.primitives.asymmetric import ed25519, x25519
from cryptography.hazmat.primitives.ciphers import Cipher, algorithms, modes
from cryptography.hazmat.primitives.serialization import (
    Encoding,
    NoEncryption,
    PrivateFormat,
    PublicFormat,
)

VERSION = b"SSH-2.0-minio_tpu_0.3"

# message numbers (RFC 4250)
MSG_DISCONNECT = 1
MSG_IGNORE = 2
MSG_UNIMPLEMENTED = 3
MSG_DEBUG = 4
MSG_SERVICE_REQUEST = 5
MSG_SERVICE_ACCEPT = 6
MSG_KEXINIT = 20
MSG_NEWKEYS = 21
MSG_KEX_ECDH_INIT = 30
MSG_KEX_ECDH_REPLY = 31
MSG_USERAUTH_REQUEST = 50
MSG_USERAUTH_FAILURE = 51
MSG_USERAUTH_SUCCESS = 52
MSG_USERAUTH_PK_OK = 60
MSG_GLOBAL_REQUEST = 80
MSG_REQUEST_SUCCESS = 81
MSG_REQUEST_FAILURE = 82
MSG_CHANNEL_OPEN = 90
MSG_CHANNEL_OPEN_CONFIRMATION = 91
MSG_CHANNEL_OPEN_FAILURE = 92
MSG_CHANNEL_WINDOW_ADJUST = 93
MSG_CHANNEL_DATA = 94
MSG_CHANNEL_EXTENDED_DATA = 95
MSG_CHANNEL_EOF = 96
MSG_CHANNEL_CLOSE = 97
MSG_CHANNEL_REQUEST = 98
MSG_CHANNEL_SUCCESS = 99
MSG_CHANNEL_FAILURE = 100

KEX_ALGO = b"curve25519-sha256"
HOSTKEY_ALGO = b"ssh-ed25519"
CIPHERS = (b"aes256-ctr", b"aes128-ctr")
MACS = (b"hmac-sha2-256",)


class SSHError(Exception):
    pass


# -- wire primitives ---------------------------------------------------------


def wstr(b: bytes | str) -> bytes:
    if isinstance(b, str):
        b = b.encode()
    return struct.pack(">I", len(b)) + b


def wu32(v: int) -> bytes:
    return struct.pack(">I", v)


def wmpint(v: int) -> bytes:
    if v == 0:
        return wstr(b"")
    b = v.to_bytes((v.bit_length() + 7) // 8, "big")
    if b[0] & 0x80:
        b = b"\x00" + b
    return wstr(b)


def wnamelist(names) -> bytes:
    return wstr(b",".join(names))


class Reader:
    def __init__(self, data: bytes):
        self.d = data
        self.p = 0

    def byte(self) -> int:
        v = self.d[self.p]
        self.p += 1
        return v

    def bool_(self) -> bool:
        return self.byte() != 0

    def u32(self) -> int:
        v = struct.unpack_from(">I", self.d, self.p)[0]
        self.p += 4
        return v

    def u64(self) -> int:
        v = struct.unpack_from(">Q", self.d, self.p)[0]
        self.p += 8
        return v

    def str_(self) -> bytes:
        n = self.u32()
        v = self.d[self.p : self.p + n]
        if len(v) != n:
            raise SSHError("truncated string")
        self.p += n
        return v

    def namelist(self) -> list[bytes]:
        s = self.str_()
        return s.split(b",") if s else []

    def rest(self) -> bytes:
        v = self.d[self.p :]
        self.p = len(self.d)
        return v


def ed25519_blob(pub: ed25519.Ed25519PublicKey) -> bytes:
    raw = pub.public_bytes(Encoding.Raw, PublicFormat.Raw)
    return wstr(HOSTKEY_ALGO) + wstr(raw)


def ed25519_sig_blob(sig: bytes) -> bytes:
    return wstr(HOSTKEY_ALGO) + wstr(sig)


# -- transport ---------------------------------------------------------------


class _Direction:
    """One direction's cipher+mac state."""

    def __init__(self, key: bytes, iv: bytes, mac_key: bytes):
        self.enc = Cipher(algorithms.AES(key), modes.CTR(iv))
        self.encryptor = self.enc.encryptor()
        self.mac_key = mac_key
        self.seq = 0


class SSHTransport:
    """One SSH connection endpoint (role 'server' or 'client')."""

    def __init__(self, sock: socket.socket, role: str,
                 host_key: ed25519.Ed25519PrivateKey | None = None):
        self.sock = sock
        self.role = role
        self.host_key = host_key
        self.session_id: bytes | None = None
        self._tx: _Direction | None = None
        self._rx: _Direction | None = None
        self._tx_seq = 0
        self._rx_seq = 0
        self._wlock = threading.Lock()
        self.remote_version = b""
        self.peer_host_key_blob: bytes | None = None

    # -- raw packet layer --------------------------------------------------

    def _read_exact(self, n: int) -> bytes:
        out = b""
        while len(out) < n:
            chunk = self.sock.recv(n - len(out))
            if not chunk:
                raise SSHError("connection closed")
            out += chunk
        return out

    def send_packet(self, payload: bytes) -> None:
        with self._wlock:
            block = 16 if self._tx else 8
            # padding so total (len+padlen+payload+padding) % block == 0
            overhead = 5
            pad = block - ((overhead + len(payload)) % block)
            if pad < 4:
                pad += block
            body = struct.pack(">IB", 1 + len(payload) + pad, pad) + payload + os.urandom(pad)
            if self._tx is None:
                self.sock.sendall(body)
            else:
                mac = hmac_mod.new(
                    self._tx.mac_key, wu32(self._tx_seq) + body, hashlib.sha256
                ).digest()
                self.sock.sendall(self._tx.encryptor.update(body) + mac)
            self._tx_seq = (self._tx_seq + 1) & 0xFFFFFFFF

    def read_packet(self) -> bytes:
        if self._rx is None:
            hdr = self._read_exact(5)
            plen, pad = struct.unpack(">IB", hdr)
            if plen > 1 << 24:
                raise SSHError("packet too large")
            body = self._read_exact(plen - 1)
            payload = body[: plen - 1 - pad]
        else:
            first = self._rx.encryptor.update(self._read_exact(16))
            plen, pad = struct.unpack(">IB", first[:5])
            if plen > 1 << 24:
                raise SSHError("packet too large")
            remaining = plen + 4 - 16
            rest = self._rx.encryptor.update(self._read_exact(remaining)) if remaining else b""
            mac = self._read_exact(32)
            body = first + rest
            want = hmac_mod.new(
                self._rx.mac_key, wu32(self._rx_seq) + body, hashlib.sha256
            ).digest()
            if not hmac_mod.compare_digest(mac, want):
                raise SSHError("bad packet MAC")
            payload = body[5 : 5 + plen - 1 - pad]
        self._rx_seq = (self._rx_seq + 1) & 0xFFFFFFFF
        return payload

    def read_msg(self) -> tuple[int, Reader]:
        while True:
            p = self.read_packet()
            t = p[0]
            if t in (MSG_IGNORE, MSG_DEBUG):
                continue
            if t == MSG_UNIMPLEMENTED:
                continue
            if t == MSG_DISCONNECT:
                r = Reader(p[1:])
                code = r.u32()
                raise SSHError(f"peer disconnected (code {code})")
            return t, Reader(p[1:])

    # -- handshake ---------------------------------------------------------

    def _exchange_versions(self) -> None:
        self.sock.sendall(VERSION + b"\r\n")
        # read until the SSH- line (clients may send banner-preceding lines
        # only server->client; be lenient anyway)
        buf = b""
        while True:
            c = self.sock.recv(1)
            if not c:
                raise SSHError("closed during version exchange")
            buf += c
            if buf.endswith(b"\n"):
                line = buf.strip()
                if line.startswith(b"SSH-"):
                    self.remote_version = line
                    return
                buf = b""
                if len(line) > 4096:
                    raise SSHError("bad version line")

    def _kexinit_payload(self) -> bytes:
        return (
            bytes([MSG_KEXINIT])
            + os.urandom(16)
            + wnamelist([KEX_ALGO])
            + wnamelist([HOSTKEY_ALGO])
            + wnamelist(CIPHERS)
            + wnamelist(CIPHERS)
            + wnamelist(MACS)
            + wnamelist(MACS)
            + wnamelist([b"none"])
            + wnamelist([b"none"])
            + wnamelist([])
            + wnamelist([])
            + b"\x00"  # first_kex_packet_follows
            + wu32(0)
        )

    @staticmethod
    def _negotiate(client_list: list[bytes], server_list: list[bytes], what: str) -> bytes:
        for c in client_list:
            if c in server_list:
                return c
        raise SSHError(f"no common {what}: {client_list} vs {server_list}")

    def handshake(self) -> None:
        self._exchange_versions()
        my_kexinit = self._kexinit_payload()
        self.send_packet(my_kexinit)
        t, r = self.read_msg()
        if t != MSG_KEXINIT:
            raise SSHError(f"expected KEXINIT, got {t}")
        peer_kexinit = bytes([MSG_KEXINIT]) + r.d
        pr = Reader(r.d)
        pr.p += 16  # cookie
        kex_algos = pr.namelist()
        hostkey_algos = pr.namelist()
        enc_cs = pr.namelist()
        enc_sc = pr.namelist()
        mac_cs = pr.namelist()
        mac_sc = pr.namelist()
        comp_cs = pr.namelist()
        comp_sc = pr.namelist()
        if self.role == "server":
            client_k, server_k = kex_algos, [KEX_ALGO]
            cipher_cs = self._negotiate(enc_cs, list(CIPHERS), "cipher c->s")
            cipher_sc = self._negotiate(enc_sc, list(CIPHERS), "cipher s->c")
            i_c, i_s = peer_kexinit, my_kexinit
        else:
            client_k, server_k = [KEX_ALGO], kex_algos
            cipher_cs = self._negotiate(list(CIPHERS), enc_cs, "cipher c->s")
            cipher_sc = self._negotiate(list(CIPHERS), enc_sc, "cipher s->c")
            i_c, i_s = my_kexinit, peer_kexinit
        self._negotiate(client_k, server_k, "kex")
        # RFC 4253 §7.1: every algorithm slot must negotiate, else a clean
        # disconnect now beats "bad packet MAC" after NEWKEYS
        self._negotiate(mac_cs, list(MACS), "mac c->s")
        self._negotiate(mac_sc, list(MACS), "mac s->c")
        self._negotiate(comp_cs, [b"none"], "compression c->s")
        self._negotiate(comp_sc, [b"none"], "compression s->c")
        if HOSTKEY_ALGO not in (hostkey_algos or [HOSTKEY_ALGO]):
            raise SSHError("no common host key algo")

        if self.role == "server":
            self._kex_server(i_c, i_s, cipher_cs, cipher_sc)
        else:
            self._kex_client(i_c, i_s, cipher_cs, cipher_sc)

    def _kex_server(self, i_c, i_s, cipher_cs, cipher_sc) -> None:
        t, r = self.read_msg()
        if t != MSG_KEX_ECDH_INIT:
            raise SSHError(f"expected ECDH_INIT, got {t}")
        q_c = r.str_()
        eph = x25519.X25519PrivateKey.generate()
        q_s = eph.public_key().public_bytes(Encoding.Raw, PublicFormat.Raw)
        shared = eph.exchange(x25519.X25519PublicKey.from_public_bytes(q_c))
        k = int.from_bytes(shared, "big")
        k_s = ed25519_blob(self.host_key.public_key())
        h = hashlib.sha256(
            wstr(self.remote_version)
            + wstr(VERSION)
            + wstr(i_c)
            + wstr(i_s)
            + wstr(k_s)
            + wstr(q_c)
            + wstr(q_s)
            + wmpint(k)
        ).digest()
        if self.session_id is None:
            self.session_id = h
        sig = self.host_key.sign(h)
        self.send_packet(
            bytes([MSG_KEX_ECDH_REPLY])
            + wstr(k_s)
            + wstr(q_s)
            + wstr(ed25519_sig_blob(sig))
        )
        self._switch_keys(k, h, cipher_cs, cipher_sc)

    def _kex_client(self, i_c, i_s, cipher_cs, cipher_sc) -> None:
        eph = x25519.X25519PrivateKey.generate()
        q_c = eph.public_key().public_bytes(Encoding.Raw, PublicFormat.Raw)
        self.send_packet(bytes([MSG_KEX_ECDH_INIT]) + wstr(q_c))
        t, r = self.read_msg()
        if t != MSG_KEX_ECDH_REPLY:
            raise SSHError(f"expected ECDH_REPLY, got {t}")
        k_s = r.str_()
        q_s = r.str_()
        sig_blob = r.str_()
        shared = eph.exchange(x25519.X25519PublicKey.from_public_bytes(q_s))
        k = int.from_bytes(shared, "big")
        h = hashlib.sha256(
            wstr(VERSION)
            + wstr(self.remote_version)
            + wstr(i_c)
            + wstr(i_s)
            + wstr(k_s)
            + wstr(q_c)
            + wstr(q_s)
            + wmpint(k)
        ).digest()
        if self.session_id is None:
            self.session_id = h
        kr = Reader(k_s)
        if kr.str_() != HOSTKEY_ALGO:
            raise SSHError("unexpected host key type")
        pub = ed25519.Ed25519PublicKey.from_public_bytes(kr.str_())
        sr = Reader(sig_blob)
        if sr.str_() != HOSTKEY_ALGO:
            raise SSHError("unexpected signature type")
        pub.verify(sr.str_(), h)  # raises InvalidSignature on mismatch
        self.peer_host_key_blob = k_s
        self._switch_keys(k, h, cipher_cs, cipher_sc)

    def _derive(self, k: int, h: bytes, letter: bytes, n: int) -> bytes:
        out = hashlib.sha256(wmpint(k) + h + letter + self.session_id).digest()
        while len(out) < n:
            out += hashlib.sha256(wmpint(k) + h + out).digest()
        return out[:n]

    def _switch_keys(self, k: int, h: bytes, cipher_cs: bytes, cipher_sc: bytes) -> None:
        self.send_packet(bytes([MSG_NEWKEYS]))
        t, _ = self.read_msg()
        if t != MSG_NEWKEYS:
            raise SSHError(f"expected NEWKEYS, got {t}")
        ks_cs = 32 if cipher_cs == b"aes256-ctr" else 16
        ks_sc = 32 if cipher_sc == b"aes256-ctr" else 16
        iv_cs = self._derive(k, h, b"A", 16)
        iv_sc = self._derive(k, h, b"B", 16)
        key_cs = self._derive(k, h, b"C", ks_cs)
        key_sc = self._derive(k, h, b"D", ks_sc)
        mac_cs = self._derive(k, h, b"E", 32)
        mac_sc = self._derive(k, h, b"F", 32)
        cs = _Direction(key_cs, iv_cs, mac_cs)
        sc = _Direction(key_sc, iv_sc, mac_sc)
        if self.role == "server":
            self._rx, self._tx = cs, sc
        else:
            self._rx, self._tx = sc, cs

    def disconnect(self) -> None:
        try:
            self.send_packet(
                bytes([MSG_DISCONNECT]) + wu32(11) + wstr("bye") + wstr("")
            )
        except OSError:
            pass
        try:
            self.sock.close()
        except OSError:
            pass


def generate_host_key() -> ed25519.Ed25519PrivateKey:
    return ed25519.Ed25519PrivateKey.generate()


def host_key_to_bytes(key: ed25519.Ed25519PrivateKey) -> bytes:
    return key.private_bytes(Encoding.Raw, PrivateFormat.Raw, NoEncryption())


def host_key_from_bytes(raw: bytes) -> ed25519.Ed25519PrivateKey:
    return ed25519.Ed25519PrivateKey.from_private_bytes(raw)


def publickey_auth_blob(
    session_id: bytes, user: str, algo: bytes, pub_blob: bytes
) -> bytes:
    """The exact bytes a publickey USERAUTH_REQUEST signature covers
    (RFC 4252 §7)."""
    return (
        wstr(session_id)
        + bytes([MSG_USERAUTH_REQUEST])
        + wstr(user)
        + wstr("ssh-connection")
        + wstr("publickey")
        + b"\x01"
        + wstr(algo)
        + wstr(pub_blob)
    )
