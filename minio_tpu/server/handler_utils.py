"""Shared request-handling helpers: route->policy-action mapping,
aws-chunked decoding, form parsing, checksum verification, time formats.

Split out of app.py so the handler mixin modules (object_handlers,
bucket_handlers, multipart_handlers, postpolicy) and the router share one
definition without circular imports.
"""

from __future__ import annotations

import re
import xml.etree.ElementTree as ET
from datetime import datetime, timezone
from email.utils import format_datetime

from . import s3err

BUCKET_NAME_RE = re.compile(r"^[a-z0-9][a-z0-9.\-]{1,61}[a-z0-9]$")

# bucket subresource -> (GET action, PUT action)
_SUBRESOURCE_ACTIONS = {
    "policy": ("s3:GetBucketPolicy", "s3:PutBucketPolicy"),
    "lifecycle": ("s3:GetLifecycleConfiguration", "s3:PutLifecycleConfiguration"),
    "tagging": ("s3:GetBucketTagging", "s3:PutBucketTagging"),
    "notification": ("s3:GetBucketNotification", "s3:PutBucketNotification"),
    "encryption": ("s3:GetEncryptionConfiguration", "s3:PutEncryptionConfiguration"),
    "object-lock": (
        "s3:GetBucketObjectLockConfiguration",
        "s3:PutBucketObjectLockConfiguration",
    ),
    "cors": ("s3:GetBucketCORS", "s3:PutBucketCORS"),
    "replication": ("s3:GetReplicationConfiguration", "s3:PutReplicationConfiguration"),
    "versioning": ("s3:GetBucketVersioning", "s3:PutBucketVersioning"),
    "acl": ("s3:GetBucketAcl", "s3:PutBucketAcl"),
    "policyStatus": ("s3:GetBucketPolicyStatus", "s3:PutBucketPolicy"),
    "requestPayment": ("s3:GetBucketRequestPayment", "s3:PutBucketRequestPayment"),
    "logging": ("s3:GetBucketLogging", "s3:PutBucketLogging"),
    "ownershipControls": (
        "s3:GetBucketOwnershipControls", "s3:PutBucketOwnershipControls",
    ),
}


class _ConsumerDone(Exception):
    """Streaming-put pump: the erasure consumer finished before EOF."""


def _restored_locally(oi) -> bool:
    """A transitioned object whose restore window is still open has its
    data back on local drives and serves the normal path."""
    import time as _time

    from ..ilm import tier as tiermod

    exp = oi.user_defined.get(tiermod.RESTORE_EXPIRY_META)
    try:
        return bool(exp) and float(exp) > _time.time()
    except (TypeError, ValueError):
        return False


def _route_action(m: str, bucket: str, key: str, q, headers) -> tuple[str, str, str]:
    """(action, bucket, key) for authorization — the request->policy-action
    mapping the reference does per-handler via checkRequestAuthType."""
    if key:
        if "retention" in q:
            return (
                "s3:GetObjectRetention" if m in ("GET", "HEAD")
                else "s3:PutObjectRetention"
            ), bucket, key
        if "legal-hold" in q:
            return (
                "s3:GetObjectLegalHold" if m in ("GET", "HEAD")
                else "s3:PutObjectLegalHold"
            ), bucket, key
        if "tagging" in q:
            return {
                "GET": "s3:GetObjectTagging",
                "PUT": "s3:PutObjectTagging",
                "DELETE": "s3:DeleteObjectTagging",
            }.get(m, "s3:*"), bucket, key
        if "acl" in q:
            return (
                "s3:GetObjectAcl" if m in ("GET", "HEAD") else "s3:PutObjectAcl"
            ), bucket, key
        if m in ("GET", "HEAD"):
            if "uploadId" in q:
                return "s3:ListMultipartUploadParts", bucket, key
            if "attributes" in q:
                return "s3:GetObjectAttributes", bucket, key
            if "versionId" in q:
                return "s3:GetObjectVersion", bucket, key
            return "s3:GetObject", bucket, key
        if m == "PUT":
            return "s3:PutObject", bucket, key
        if m == "DELETE":
            if "uploadId" in q:
                return "s3:AbortMultipartUpload", bucket, key
            if "versionId" in q:
                return "s3:DeleteObjectVersion", bucket, key
            return "s3:DeleteObject", bucket, key
        if m == "POST":
            if "select" in q:
                return "s3:GetObject", bucket, key  # Select is a READ
            if "restore" in q:
                return "s3:RestoreObject", bucket, key
            return "s3:PutObject", bucket, key
        return "s3:*", bucket, key
    # bucket level
    for sub, (get_a, put_a) in _SUBRESOURCE_ACTIONS.items():
        if sub in q:
            if m in ("GET", "HEAD"):
                return get_a, bucket, ""
            return put_a, bucket, ""
    if m == "PUT":
        return "s3:CreateBucket", bucket, ""
    if m == "DELETE":
        return "s3:DeleteBucket", bucket, ""
    if m == "POST":
        return "", bucket, ""  # multi-delete authorizes PER KEY in its handler
    if "versions" in q:
        return "s3:ListBucketVersions", bucket, ""
    if "location" in q:
        return "s3:GetBucketLocation", bucket, ""
    if "uploads" in q:
        return "s3:ListBucketMultipartUploads", bucket, ""
    return "s3:ListBucket", bucket, ""


def _route_conditions(q) -> dict[str, str]:
    return {"s3:prefix": q.get("prefix", ""), "s3:delimiter": q.get("delimiter", "")}


def classify_qos_class(bucket: str, key: str, headers=None) -> str | None:
    """Request -> admission-control class (qos/admission.py), or None for
    planes that must never throttle: health probes (throttled liveness
    checks would flap the orchestrator), metrics scrapes, the embedded
    console, and internode RPC (grid/lock/storage). Only those known
    planes are exempt — an unrecognized key under /minio/* classifies as
    ordinary s3 traffic, so the reserved bucket name can never become an
    unthrottled data lane.

    Classification runs PRE-auth (the reference's maxClients throttle
    does too), so it must never trust client-controlled signals: routing
    e.g. the replication-marker header into its own class would let any
    unauthenticated sender pick its admission pool. Request headers are
    accepted for future use but ignored today; the background class is
    fed by server-side planes (heal/scan/decommission), not by wire
    classification."""
    from ..qos.admission import CLASS_ADMIN, CLASS_S3

    if bucket == "minio":
        if key.startswith("admin/") or key.startswith("kms/"):
            return CLASS_ADMIN
        if (
            key == "console"
            or key.startswith(("console/", "health/", "metrics/v3",
                               "grid/", "lock/", "storage/"))
            or key in ("v2/metrics/cluster", "v2/metrics/node")
        ):
            return None
        # anything else under /minio/* is ordinary S3 traffic ("minio" is
        # a reserved bucket name, but pre-existing data must not ride an
        # unthrottled lane)
        return CLASS_S3
    return CLASS_S3


def _parse_form_data(body: bytes, boundary: bytes) -> tuple[dict[str, str], bytes]:
    """Minimal multipart/form-data parser for POST-policy uploads.

    Returns (fields, file_bytes); the file part's filename lands in
    fields['__filename'].
    """
    fields: dict[str, str] = {}
    file_data = b""
    delim = b"--" + boundary
    chunks = body.split(delim)
    for part in chunks[1:]:  # [0] is the preamble
        if part.startswith(b"--"):
            break  # closing boundary
        # strip EXACTLY the framing CRLFs — file payloads may legitimately
        # begin/end with newline bytes that must survive
        if part.startswith(b"\r\n"):
            part = part[2:]
        if part.endswith(b"\r\n"):
            part = part[:-2]
        head, _, content = part.partition(b"\r\n\r\n")
        disp = ""
        for line in head.split(b"\r\n"):
            if line.lower().startswith(b"content-disposition"):
                disp = line.decode("utf-8", "replace")
        name = ""
        filename = None
        for tok in disp.split(";"):
            tok = tok.strip()
            if tok.startswith("name="):
                name = tok[5:].strip('"')
            elif tok.startswith("filename="):
                filename = tok[9:].strip('"')
        if not name:
            continue
        if name == "file":
            file_data = content
            if filename:
                fields["__filename"] = filename.rsplit("/", 1)[-1]
        else:
            fields[name] = content.decode("utf-8", "replace")
    return fields, file_data


def _verify_checksum_headers(headers, body: bytes) -> dict[str, str]:
    """AWS flexible-checksums: verify x-amz-checksum-* when present and
    return internal metadata recording them (reference internal/hash/
    checksum.go readers). All five algorithms (CRC32, CRC32C, SHA1,
    SHA256, CRC64NVME) are verified, none stored blind."""
    from ..utils import checksum as cks

    out: dict[str, str] = {}
    for algo in cks.ALGOS:
        v = headers.get(f"{cks.HEADER}{algo}")
        if not v:
            continue
        if cks.compute(algo, body) != v:
            raise s3err.InvalidDigest
        out[f"{cks.META_PREFIX}{algo}"] = v
    return out


class _AwsChunkedDecoder:
    """Incremental aws-chunked decoder for STREAMING-UNSIGNED-PAYLOAD-TRAILER
    bodies (reference cmd/streaming-v4-unsigned.go): yields payload bytes,
    captures the trailing checksum headers."""

    def __init__(self):
        self._buf = bytearray()
        self._state = "size"  # size | data | crlf | trailer
        self._remaining = 0
        self.trailers: dict[str, str] = {}

    def feed(self, chunk: bytes) -> bytes:
        self._buf += chunk
        out = bytearray()
        while True:
            if self._state == "size":
                nl = self._buf.find(b"\r\n")
                if nl < 0:
                    break
                line = bytes(self._buf[:nl])
                del self._buf[: nl + 2]
                size_hex = line.split(b";", 1)[0].strip()
                try:
                    self._remaining = int(size_hex, 16)
                except ValueError:
                    raise s3err.IncompleteBody from None
                self._state = "data" if self._remaining else "trailer"
            elif self._state == "data":
                take = min(self._remaining, len(self._buf))
                if take:
                    out += self._buf[:take]
                    del self._buf[:take]
                    self._remaining -= take
                if self._remaining:
                    break
                self._state = "crlf"
            elif self._state == "crlf":
                if len(self._buf) < 2:
                    break
                del self._buf[:2]
                self._state = "size"
            else:  # trailer: lines until blank
                nl = self._buf.find(b"\r\n")
                if nl < 0:
                    break
                line = bytes(self._buf[:nl])
                del self._buf[: nl + 2]
                if not line:
                    continue  # final blank line
                if b":" in line:
                    k, v = line.split(b":", 1)
                    self.trailers[k.decode().strip().lower()] = v.decode().strip()
        return bytes(out)


def _bucket_sse_algo(encryption_xml: str | None) -> str | None:
    """SSEAlgorithm from a bucket's default-encryption config XML."""
    if not encryption_xml:
        return None
    try:
        root = ET.fromstring(encryption_xml)
        for el in root.iter():
            if el.tag.endswith("SSEAlgorithm"):
                return el.text or None
    except ET.ParseError:
        return None
    return None


def _iso8601(ns: int) -> str:
    return datetime.fromtimestamp(ns / 1e9, tz=timezone.utc).strftime(
        "%Y-%m-%dT%H:%M:%S.%f"
    )[:-3] + "Z"


def _http_date(ns: int) -> str:
    return format_datetime(
        datetime.fromtimestamp(ns / 1e9, tz=timezone.utc), usegmt=True
    )
