"""Prometheus metrics + request tracing pubsub.

Mirrors the reference's observability plane: metrics v2/v3 endpoints
(/root/reference/cmd/metrics-v2.go, metrics-v3*.go) exposing request,
storage, heal, and usage series in Prometheus text format; and the
zero-cost-when-idle trace pubsub behind `mc admin trace`
(/root/reference/cmd/http-tracer.go + internal/pubsub).
"""

from __future__ import annotations

import json
import threading
import time
from collections import defaultdict


class Metrics:
    def __init__(self):
        self._mu = threading.Lock()
        self.requests_total: dict[str, int] = defaultdict(int)  # by api
        self.errors_total: dict[str, int] = defaultdict(int)  # by api
        self.errors_4xx: int = 0
        self.errors_5xx: int = 0
        self.rx_bytes = 0
        self.tx_bytes = 0
        self.request_seconds: dict[str, float] = defaultdict(float)
        self.inflight = 0

    def observe(self, api: str, status: int, dur: float, rx: int, tx: int) -> None:
        with self._mu:
            self.requests_total[api] += 1
            self.request_seconds[api] += dur
            self.rx_bytes += rx
            self.tx_bytes += tx
            if status >= 500:
                self.errors_5xx += 1
                self.errors_total[api] += 1
            elif status >= 400:
                self.errors_4xx += 1
                self.errors_total[api] += 1

    def render(self, server) -> str:
        """Prometheus text exposition for the cluster endpoint."""
        lines = [
            "# HELP minio_s3_requests_total Total S3 requests by API.",
            "# TYPE minio_s3_requests_total counter",
        ]
        with self._mu:
            for api, n in sorted(self.requests_total.items()):
                lines.append(f'minio_s3_requests_total{{api="{api}"}} {n}')
            lines += [
                "# TYPE minio_s3_requests_errors_total counter",
            ]
            for api, n in sorted(self.errors_total.items()):
                lines.append(f'minio_s3_requests_errors_total{{api="{api}"}} {n}')
            lines += [
                "# TYPE minio_s3_requests_4xx_errors_total counter",
                f"minio_s3_requests_4xx_errors_total {self.errors_4xx}",
                "# TYPE minio_s3_requests_5xx_errors_total counter",
                f"minio_s3_requests_5xx_errors_total {self.errors_5xx}",
                "# TYPE minio_s3_traffic_received_bytes counter",
                f"minio_s3_traffic_received_bytes {self.rx_bytes}",
                "# TYPE minio_s3_traffic_sent_bytes counter",
                f"minio_s3_traffic_sent_bytes {self.tx_bytes}",
                "# TYPE minio_s3_request_seconds_total counter",
            ]
            for api, s in sorted(self.request_seconds.items()):
                lines.append(f'minio_s3_request_seconds_total{{api="{api}"}} {s:.6f}')
        # storage series
        store = server.store
        if store is not None:
            online, offline, total_b, free_b = 0, 0, 0, 0
            for d in store.disks:
                try:
                    di = d.disk_info()
                    online += 1
                    total_b += di.total
                    free_b += di.free
                except Exception:  # noqa: BLE001
                    offline += 1
            lines += [
                "# TYPE minio_cluster_drive_online_total gauge",
                f"minio_cluster_drive_online_total {online}",
                "# TYPE minio_cluster_drive_offline_total gauge",
                f"minio_cluster_drive_offline_total {offline}",
                "# TYPE minio_cluster_capacity_raw_total_bytes gauge",
                f"minio_cluster_capacity_raw_total_bytes {total_b}",
                "# TYPE minio_cluster_capacity_raw_free_bytes gauge",
                f"minio_cluster_capacity_raw_free_bytes {free_b}",
            ]
        bg = getattr(server, "background", None)
        if bg is not None:
            lines += [
                "# TYPE minio_heal_objects_healed_total counter",
                f"minio_heal_objects_healed_total {bg.stats['heals_done']}",
                "# TYPE minio_heal_objects_queued_total counter",
                f"minio_heal_objects_queued_total {bg.stats['heals_queued']}",
                "# TYPE minio_heal_objects_errors_total counter",
                f"minio_heal_objects_errors_total {bg.stats['heals_failed']}",
                "# TYPE minio_scanner_objects_scanned_total counter",
                f"minio_scanner_objects_scanned_total {bg.stats['objects_scanned']}",
                "# TYPE minio_bucket_usage_total_bytes gauge",
            ]
            for b, u in sorted(bg.usage.buckets.items()):
                lines.append(f'minio_bucket_usage_total_bytes{{bucket="{b}"}} {u["size"]}')
                lines.append(
                    f'minio_bucket_usage_object_total{{bucket="{b}"}} {u["objects"]}'
                )
        lines += [
            "# TYPE minio_node_uptime_seconds gauge",
            f"minio_node_uptime_seconds {time.time() - server.started_at:.0f}",
        ]
        return "\n".join(lines) + "\n"


class TracePubSub:
    """Fan-out of request trace records; zero-cost with no subscribers
    (the reference checks NumSubscribers before building the record)."""

    def __init__(self):
        self._mu = threading.Lock()
        self._subs: list = []

    @property
    def active(self) -> bool:
        return bool(self._subs)

    def subscribe(self):
        import queue

        q = queue.Queue(maxsize=1000)
        with self._mu:
            self._subs.append(q)
        return q

    def unsubscribe(self, q) -> None:
        with self._mu:
            if q in self._subs:
                self._subs.remove(q)

    def publish(self, record: dict) -> None:
        with self._mu:
            subs = list(self._subs)
        for q in subs:
            try:
                q.put_nowait(record)
            except Exception:  # noqa: BLE001 — slow subscriber drops records
                pass


def trace_record(request, status: int, dur: float, rx: int, tx: int) -> dict:
    return {
        "time": time.time(),
        "type": "s3",
        "method": request.method,
        "path": request.path,
        "query": request.rel_url.raw_query_string,
        "statusCode": status,
        "durationNs": int(dur * 1e9),
        "rx": rx,
        "tx": tx,
        "remote": request.remote or "",
    }


def classify_api(method: str, bucket: str, key: str, query) -> str:
    """Request -> metrics API label (coarse version of the reference's
    api names in cmd/metrics-v2.go)."""
    if not bucket:
        return "ListBuckets" if method == "GET" else "STS"
    if not key:
        if method == "GET":
            if "versions" in query:
                return "ListObjectVersions"
            return "ListObjectsV2" if query.get("list-type") == "2" else "ListObjectsV1"
        return {
            "PUT": "PutBucket", "DELETE": "DeleteBucket", "HEAD": "HeadBucket",
            "POST": "DeleteMultipleObjects",
        }.get(method, method)
    if "uploadId" in query or "uploads" in query:
        return "Multipart"
    return {
        "GET": "GetObject", "PUT": "PutObject", "HEAD": "HeadObject",
        "DELETE": "DeleteObject", "POST": "PostObject",
    }.get(method, method)


def dump_json(obj) -> bytes:
    return json.dumps(obj).encode()
