"""Prometheus metrics + request tracing pubsub.

Mirrors the reference's observability plane: metrics v2/v3 endpoints
(/root/reference/cmd/metrics-v2.go, metrics-v3*.go) exposing request,
storage, heal, and usage series in Prometheus text format; and the
zero-cost-when-idle trace pubsub behind `mc admin trace`
(/root/reference/cmd/http-tracer.go + internal/pubsub).
"""

from __future__ import annotations

import json
import os
import threading
import time
from collections import defaultdict


MAX_BUCKET_SERIES = 1000  # bound per-bucket label cardinality


# TTFB distribution buckets, matching the reference's
# minio_api_requests_ttfb_seconds_distribution edges (cmd/metrics-v3-api.go)
TTFB_BUCKETS = (0.05, 0.1, 0.25, 0.5, 1.0, 2.5, 5.0, 10.0)


def ttfb_distribution_rows(hist: dict[str, list[int]]):
    """Cumulative (api, le, count) rows — single source for the v2 and v3
    expositions so the bucket edges and le formatting cannot diverge."""
    for api, h in sorted(hist.items()):
        cum = 0
        for i, edge in enumerate(TTFB_BUCKETS):
            cum += h[i]
            yield api, str(edge), cum
        yield api, "+Inf", cum + h[-1]


class Metrics:
    def __init__(self):
        self._mu = threading.Lock()
        self.requests_total: dict[str, int] = defaultdict(int)  # by api
        self.errors_total: dict[str, int] = defaultdict(int)  # by api
        self.errors_4xx: int = 0
        self.errors_5xx: int = 0
        self.rejected_auth: int = 0  # 401/403: failed authentication/authz
        self.rejected_invalid: int = 0  # 400: malformed requests
        self.rejected_header: int = 0  # malformed Authorization header
        self.rejected_timestamp: int = 0  # x-amz-date outside the skew window
        self.canceled: int = 0  # client went away mid-request
        self.rx_bytes = 0
        self.tx_bytes = 0
        self.request_seconds: dict[str, float] = defaultdict(float)
        # TTFB kept separate from full-request duration: a streamed 10s
        # GET with 20ms TTFB must not skew the TTFB sum
        self.ttfb_seconds: dict[str, float] = defaultdict(float)
        self.ttfb_hist: dict[str, list[int]] = {}  # api -> bucket counts+[+Inf]
        self.inflight = 0
        # per-bucket: bucket -> api -> [requests, errors, rx, tx]
        self.bucket_api: dict[str, dict[str, list]] = {}

    def observe(
        self, api: str, status: int, dur: float, rx: int, tx: int,
        bucket: str = "", ttfb: float | None = None,
    ) -> None:
        with self._mu:
            self.requests_total[api] += 1
            self.request_seconds[api] += dur
            self.rx_bytes += rx
            self.tx_bytes += tx
            h = self.ttfb_hist.get(api)
            if h is None:
                h = self.ttfb_hist[api] = [0] * (len(TTFB_BUCKETS) + 1)
            t = dur if ttfb is None else ttfb
            self.ttfb_seconds[api] += t
            for i, edge in enumerate(TTFB_BUCKETS):
                if t <= edge:
                    h[i] += 1
                    break
            else:
                h[-1] += 1
            err = status >= 400
            if status in (401, 403):
                self.rejected_auth += 1
            elif status == 400:
                self.rejected_invalid += 1
            if status >= 500:
                self.errors_5xx += 1
                self.errors_total[api] += 1
            elif err:
                self.errors_total[api] += 1
                self.errors_4xx += 1
            # series creation rules: never for the /minio/* pseudo-bucket
            # or system paths, and never for a FAILED request on an
            # untracked name — otherwise an unauthenticated scanner
            # walking random paths would mint junk series up to the cap
            # and real buckets could never register
            if (
                bucket
                and bucket != "minio"
                and not bucket.startswith(".minio.sys")
                and (bucket in self.bucket_api or not err)
                and (
                    bucket in self.bucket_api
                    or len(self.bucket_api) < MAX_BUCKET_SERIES
                )
            ):
                rec = self.bucket_api.setdefault(bucket, {}).setdefault(
                    api, [0, 0, 0, 0]
                )
                rec[0] += 1
                rec[1] += 1 if err else 0
                rec[2] += rx
                rec[3] += tx

    def render(self, server) -> str:
        """Prometheus text exposition for the cluster endpoint."""
        lines = [
            "# HELP minio_s3_requests_total Total S3 requests by API.",
            "# TYPE minio_s3_requests_total counter",
        ]
        with self._mu:
            for api, n in sorted(self.requests_total.items()):
                lines.append(f'minio_s3_requests_total{{api="{api}"}} {n}')
            lines += [
                "# TYPE minio_s3_requests_errors_total counter",
            ]
            for api, n in sorted(self.errors_total.items()):
                lines.append(f'minio_s3_requests_errors_total{{api="{api}"}} {n}')
            lines += [
                "# TYPE minio_s3_requests_4xx_errors_total counter",
                f"minio_s3_requests_4xx_errors_total {self.errors_4xx}",
                "# TYPE minio_s3_requests_5xx_errors_total counter",
                f"minio_s3_requests_5xx_errors_total {self.errors_5xx}",
                "# TYPE minio_s3_traffic_received_bytes counter",
                f"minio_s3_traffic_received_bytes {self.rx_bytes}",
                "# TYPE minio_s3_traffic_sent_bytes counter",
                f"minio_s3_traffic_sent_bytes {self.tx_bytes}",
                "# TYPE minio_s3_requests_rejected_auth_total counter",
                f"minio_s3_requests_rejected_auth_total {self.rejected_auth}",
                "# TYPE minio_s3_requests_rejected_invalid_total counter",
                f"minio_s3_requests_rejected_invalid_total {self.rejected_invalid}",
                "# TYPE minio_s3_requests_inflight_total gauge",
                f"minio_s3_requests_inflight_total {self.inflight}",
                "# TYPE minio_s3_request_seconds_total counter",
            ]
            for api, s in sorted(self.request_seconds.items()):
                lines.append(f'minio_s3_request_seconds_total{{api="{api}"}} {s:.6f}')
            lines.append("# TYPE minio_s3_ttfb_seconds_distribution counter")
            for api, le, cum in ttfb_distribution_rows(self.ttfb_hist):
                lines.append(
                    f'minio_s3_ttfb_seconds_distribution{{api="{api}",le="{le}"}} {cum}'
                )
        # storage series
        store = server.store
        if store is not None:
            online, offline, total_b, free_b = 0, 0, 0, 0
            for d in store.disks:
                try:
                    di = d.disk_info()
                    online += 1
                    total_b += di.total
                    free_b += di.free
                except Exception:  # noqa: BLE001
                    offline += 1
            lines += [
                "# TYPE minio_cluster_drive_online_total gauge",
                f"minio_cluster_drive_online_total {online}",
                "# TYPE minio_cluster_drive_offline_total gauge",
                f"minio_cluster_drive_offline_total {offline}",
                "# TYPE minio_cluster_capacity_raw_total_bytes gauge",
                f"minio_cluster_capacity_raw_total_bytes {total_b}",
                "# TYPE minio_cluster_capacity_raw_free_bytes gauge",
                f"minio_cluster_capacity_raw_free_bytes {free_b}",
            ]
        bg = getattr(server, "background", None)
        if bg is not None:
            lines += [
                "# TYPE minio_heal_objects_healed_total counter",
                f"minio_heal_objects_healed_total {bg.stats['heals_done']}",
                "# TYPE minio_heal_objects_queued_total counter",
                f"minio_heal_objects_queued_total {bg.stats['heals_queued']}",
                "# TYPE minio_heal_objects_errors_total counter",
                f"minio_heal_objects_errors_total {bg.stats['heals_failed']}",
                "# TYPE minio_scanner_objects_scanned_total counter",
                f"minio_scanner_objects_scanned_total {bg.stats['objects_scanned']}",
                "# TYPE minio_bucket_usage_total_bytes gauge",
            ]
            for b, u in sorted(bg.usage.buckets.items()):
                eb = _esc_label(b)
                lines.append(f'minio_bucket_usage_total_bytes{{bucket="{eb}"}} {u["size"]}')
                lines.append(
                    f'minio_bucket_usage_object_total{{bucket="{eb}"}} {u["objects"]}'
                )
        lines += [
            "# TYPE minio_node_uptime_seconds gauge",
            f"minio_node_uptime_seconds {time.time() - server.started_at:.0f}",
        ]
        return "\n".join(lines) + "\n"


class TraceSub:
    """One trace subscriber: bounded queue + optional server-side filter
    + drop accounting (a slow consumer loses records, visibly)."""

    __slots__ = ("q", "filter", "dropped", "label")

    def __init__(self, maxsize: int, filter=None, label: str = ""):
        import queue

        self.q = queue.Queue(maxsize=maxsize)
        self.filter = filter
        self.dropped = 0
        self.label = label


class TracePubSub:
    """Fan-out of request trace records; zero-cost with no subscribers
    (the reference checks NumSubscribers before building the record).
    Subscriber filters run at publish time so filtered-out records never
    consume queue space; per-subscriber drops are counted, not silent."""

    def __init__(self):
        self._mu = threading.Lock()
        self._subs: list[TraceSub] = []
        self.dropped_total = 0

    @property
    def active(self) -> bool:
        return bool(self._subs)

    def subscribe(self, filter=None, label: str = "") -> TraceSub:
        maxsize = int(os.environ.get("MINIO_TPU_TRACE_BUFFER", "1000") or 1000)
        sub = TraceSub(maxsize, filter=filter, label=label)
        with self._mu:
            self._subs.append(sub)
        return sub

    def unsubscribe(self, sub: TraceSub) -> None:
        with self._mu:
            if sub in self._subs:
                self._subs.remove(sub)

    def publish(self, record: dict) -> None:
        with self._mu:
            subs = list(self._subs)
        for sub in subs:
            if sub.filter is not None and not sub.filter.match(record):
                continue
            try:
                sub.q.put_nowait(record)
            except Exception:  # noqa: BLE001 — slow subscriber drops records
                # publish() runs on whatever thread produced the record
                # (handlers, dispatcher, watchdog): the drop counters are
                # load/add/store interleaves without the lock (miniovet
                # races pass)
                with self._mu:
                    sub.dropped += 1
                    self.dropped_total += 1

    def subscriber_stats(self) -> list[dict]:
        with self._mu:
            return [
                {"label": s.label or f"sub-{i}", "dropped": s.dropped,
                 "queued": s.q.qsize()}
                for i, s in enumerate(self._subs)
            ]


def trace_record(
    request, status: int, dur: float, rx: int, tx: int,
    req_id: str = "", api: str = "",
) -> dict:
    from .. import obs

    return {
        "time": time.time(),
        "type": "s3",
        "name": api or request.method,
        "reqId": req_id,
        "node": obs.trace.NODE,
        "method": request.method,
        "path": request.path,
        "query": request.rel_url.raw_query_string,
        "statusCode": status,
        "error": "" if status < 400 else f"HTTP {status}",
        "durationNs": int(dur * 1e9),
        "rx": rx,
        "tx": tx,
        "remote": request.remote or "",
    }


def classify_api(method: str, bucket: str, key: str, query) -> str:
    """Request -> metrics API label (coarse version of the reference's
    api names in cmd/metrics-v2.go)."""
    if not bucket:
        return "ListBuckets" if method == "GET" else "STS"
    if not key:
        if method == "GET":
            if "versions" in query:
                return "ListObjectVersions"
            return "ListObjectsV2" if query.get("list-type") == "2" else "ListObjectsV1"
        return {
            "PUT": "PutBucket", "DELETE": "DeleteBucket", "HEAD": "HeadBucket",
            "POST": "DeleteMultipleObjects",
        }.get(method, method)
    if "uploadId" in query or "uploads" in query:
        return "Multipart"
    return {
        "GET": "GetObject", "PUT": "PutObject", "HEAD": "HeadObject",
        "DELETE": "DeleteObject", "POST": "PostObject",
    }.get(method, method)


def dump_json(obj) -> bytes:
    return json.dumps(obj).encode()


# -- metrics v3: grouped registry with path filtering ------------------------
#
# Mirrors /root/reference/cmd/metrics-v3.go: each collector path under
# /minio/metrics/v3 returns one group; /bucket/* paths take a bucket name
# suffix. GET /minio/metrics/v3 (no path) concatenates every non-bucket
# group, /minio/metrics/v3/cluster/... serves one subtree, etc.


def _esc_label(v) -> str:
    """Prometheus text-format label-value escaping (backslash, double
    quote, newline). Bucket/drive/rule labels carry user-chosen names —
    a bucket called `a"b` must not produce an unparseable line."""
    s = str(v)
    if "\\" in s or '"' in s or "\n" in s:
        s = s.replace("\\", "\\\\").replace('"', '\\"').replace("\n", "\\n")
    return s


def _fmt(lines: list[str], name: str, mtype: str, values, help_: str = "") -> None:
    if help_:
        lines.append(f"# HELP {name} {help_}")
    lines.append(f"# TYPE {name} {mtype}")
    for labels, v in values:
        if labels:
            lab = ",".join(
                f'{k}="{_esc_label(v2)}"' for k, v2 in labels.items()
            )
            lines.append(f"{name}{{{lab}}} {v}")
        else:
            lines.append(f"{name} {v}")


def _g_api_requests(server) -> list[str]:
    m = server.metrics
    out: list[str] = []
    with m._mu:
        _fmt(out, "minio_api_requests_total", "counter",
             [({"name": a}, n) for a, n in sorted(m.requests_total.items())],
             "Total requests by API")
        _fmt(out, "minio_api_requests_errors_total", "counter",
             [({"name": a}, n) for a, n in sorted(m.errors_total.items())])
        _fmt(out, "minio_api_requests_4xx_errors_total", "counter", [({}, m.errors_4xx)])
        _fmt(out, "minio_api_requests_5xx_errors_total", "counter", [({}, m.errors_5xx)])
        _fmt(out, "minio_api_requests_incoming_bytes_total", "counter", [({}, m.rx_bytes)])
        _fmt(out, "minio_api_requests_outgoing_bytes_total", "counter", [({}, m.tx_bytes)])
        _fmt(out, "minio_api_requests_ttfb_seconds_total", "counter",
             [({"name": a}, f"{s:.6f}") for a, s in sorted(m.ttfb_seconds.items())])
        _fmt(out, "minio_api_requests_duration_seconds_total", "counter",
             [({"name": a}, f"{s:.6f}") for a, s in sorted(m.request_seconds.items())])
        _fmt(out, "minio_api_requests_inflight_total", "gauge", [({}, m.inflight)])
        _fmt(out, "minio_api_requests_rejected_auth_total", "counter",
             [({}, m.rejected_auth)])
        _fmt(out, "minio_api_requests_rejected_invalid_total", "counter",
             [({}, m.rejected_invalid)])
        _fmt(out, "minio_api_requests_rejected_header_total", "counter",
             [({}, m.rejected_header)],
             "Requests rejected for a malformed Authorization header")
        _fmt(out, "minio_api_requests_rejected_timestamp_total", "counter",
             [({}, m.rejected_timestamp)],
             "Requests rejected for a skewed x-amz-date")
        _fmt(out, "minio_api_requests_canceled_total", "counter",
             [({}, m.canceled)],
             "Requests abandoned by the client before the response")
        _fmt(out, "minio_api_requests_ttfb_seconds_distribution", "counter",
             [({"name": a, "le": le}, cum)
              for a, le, cum in ttfb_distribution_rows(m.ttfb_hist)])
    # QoS admission waits live outside the metrics mutex (qos/admission
    # keeps its own): the reference's waiting_total is the deadline queue
    qos = getattr(server, "qos", None)
    waiting = 0
    if qos is not None:
        waiting = sum(
            s["waiting"] for s in qos.admission.snapshot().values()
        )
    _fmt(out, "minio_api_requests_waiting_total", "gauge", [({}, waiting)],
         "Requests parked on QoS admission across classes")
    return out


def _g_bucket_api(server, bucket: str) -> list[str]:
    m = server.metrics
    out: list[str] = []
    with m._mu:
        apis = m.bucket_api.get(bucket, {})
        _fmt(out, "minio_bucket_api_traffic_received_bytes", "counter",
             [({"bucket": bucket, "name": a}, r[2]) for a, r in sorted(apis.items())])
        _fmt(out, "minio_bucket_api_traffic_sent_bytes", "counter",
             [({"bucket": bucket, "name": a}, r[3]) for a, r in sorted(apis.items())])
        _fmt(out, "minio_bucket_api_requests_total", "counter",
             [({"bucket": bucket, "name": a}, r[0]) for a, r in sorted(apis.items())])
        _fmt(out, "minio_bucket_api_requests_errors_total", "counter",
             [({"bucket": bucket, "name": a}, r[1]) for a, r in sorted(apis.items())])
    return out


def _g_bucket_replication(server, bucket: str) -> list[str]:
    out: list[str] = []
    repl = getattr(server, "replication", None)
    st = (
        dict(repl.bucket_stats.get(bucket, {})) if repl is not None else {}
    )
    _fmt(out, "minio_bucket_replication_total", "counter",
         [({"bucket": bucket}, st.get("replicated", 0))])
    _fmt(out, "minio_bucket_replication_failed_total", "counter",
         [({"bucket": bucket}, st.get("failed", 0))])
    _fmt(out, "minio_bucket_replication_deletes_total", "counter",
         [({"bucket": bucket}, st.get("deletes", 0))])
    return out


_DRIVE_PROBE_TTL = 5.0


def _probe_drives(server) -> dict:
    """One disk_info() sweep shared by every group in a render window —
    in distributed mode each probe of a remote drive is a storage-REST
    RPC, so per-group probing would triple the scrape cost."""
    m = server.metrics
    now = time.monotonic()
    cached = getattr(m, "_drive_probe", None)
    if cached is not None and now - cached[0] < _DRIVE_PROBE_TTL:
        return cached[1]
    per_drive = []
    by_id: dict[int, bool] = {}
    for d in server.store.disks:
        path = getattr(d, "path", getattr(d, "endpoint", "?"))
        try:
            di = d.disk_info()
            per_drive.append({
                "drive": str(path), "total": di.total, "free": di.free,
                "used": di.used or max(di.total - di.free, 0),
                "used_inodes": di.used_inodes,
                "free_inodes": di.free_inodes,
                "healing": 1 if di.healing else 0, "online": 1,
            })
            by_id[id(d)] = True
        except Exception:  # noqa: BLE001
            per_drive.append({
                "drive": str(path), "total": 0, "free": 0, "used": 0,
                "used_inodes": 0, "free_inodes": 0, "healing": 0,
                "online": 0,
            })
            by_id[id(d)] = False
    res = {
        "per_drive": per_drive,
        "online": sum(r["online"] for r in per_drive),
        "offline": sum(1 for r in per_drive if not r["online"]),
        "healing": sum(r["healing"] for r in per_drive),
        "total_bytes": sum(r["total"] for r in per_drive),
        "free_bytes": sum(r["free"] for r in per_drive),
        "by_id": by_id,
    }
    m._drive_probe = (now, res)
    return res


def _g_system_drive(server) -> list[str]:
    from ..storage.health import HealthCheckedDisk

    out: list[str] = []
    pr = _probe_drives(server)
    per_drive = pr["per_drive"]
    _fmt(out, "minio_system_drive_total_bytes", "gauge",
         [({"drive": r["drive"]}, r["total"]) for r in per_drive])
    _fmt(out, "minio_system_drive_used_bytes", "gauge",
         [({"drive": r["drive"]}, r["used"]) for r in per_drive])
    _fmt(out, "minio_system_drive_free_bytes", "gauge",
         [({"drive": r["drive"]}, r["free"]) for r in per_drive])
    _fmt(out, "minio_system_drive_used_inodes", "gauge",
         [({"drive": r["drive"]}, r["used_inodes"]) for r in per_drive])
    _fmt(out, "minio_system_drive_free_inodes", "gauge",
         [({"drive": r["drive"]}, r["free_inodes"]) for r in per_drive])
    _fmt(out, "minio_system_drive_total_inodes", "gauge",
         [({"drive": r["drive"]},
           r["used_inodes"] + r["free_inodes"]) for r in per_drive])
    _fmt(out, "minio_system_drive_online", "gauge",
         [({"drive": r["drive"]}, r["online"]) for r in per_drive])
    _fmt(out, "minio_system_drive_health", "gauge",
         [({"drive": r["drive"]}, r["online"]) for r in per_drive],
         "1 when the drive answers storage calls (breaker closed)")
    _fmt(out, "minio_system_drive_count", "gauge",
         [({"state": "online"}, pr["online"]), ({"state": "offline"}, pr["offline"])])
    _fmt(out, "minio_system_drive_online_count", "gauge", [({}, pr["online"])])
    _fmt(out, "minio_system_drive_offline_count", "gauge", [({}, pr["offline"])])
    _fmt(out, "minio_system_drive_healing_count", "gauge", [({}, pr["healing"])])
    _fmt(out, "minio_system_drive_raw_total_bytes", "gauge", [({}, pr["total_bytes"])])
    _fmt(out, "minio_system_drive_raw_free_bytes", "gauge", [({}, pr["free_bytes"])])
    # breaker-classified error counters (HealthCheckedDisk): timeouts vs
    # any availability fault — the reference's drive error split
    t_rows, a_rows = [], []
    for d in server.store.disks:
        if not isinstance(d, HealthCheckedDisk):
            continue
        ep = str(getattr(d, "endpoint", "?"))
        t_rows.append(({"drive": ep}, d.timeout_faults))
        a_rows.append(({"drive": ep}, d.total_faults))
    _fmt(out, "minio_system_drive_timeout_errors_total", "counter", t_rows,
         "Storage calls that failed with a timeout, per drive")
    _fmt(out, "minio_system_drive_availability_errors_total", "counter",
         a_rows, "Storage calls that failed for any transport reason")
    return out


def _proc_stat() -> dict:
    out = {}
    try:
        with open("/proc/self/stat") as f:
            raw = f.read()
        # comm may contain spaces: fields restart after the last ')'
        parts = raw[raw.rindex(")") + 2 :].split()
        tck = float(os.sysconf("SC_CLK_TCK") or 100)
        page = os.sysconf("SC_PAGE_SIZE") or 4096
        # parts[0] is field 3 (state); utime is field 14 -> index 11
        out["utime_s"] = int(parts[11]) / tck
        out["stime_s"] = int(parts[12]) / tck
        out["threads"] = int(parts[17])
        out["vsize"] = int(parts[20])
        out["rss_bytes"] = int(parts[21]) * page
    except (OSError, IndexError, ValueError):
        pass
    try:
        out["fds"] = len(os.listdir("/proc/self/fd"))
    except OSError:
        pass
    try:
        import resource

        out["fd_limit"] = resource.getrlimit(resource.RLIMIT_NOFILE)[0]
    except (ImportError, OSError, ValueError):
        pass
    try:
        with open("/proc/self/io") as f:
            for line in f:
                k, _, v = line.partition(":")
                if k in ("rchar", "wchar"):
                    out[k] = int(v)
    except (OSError, ValueError):
        pass
    return out


def _g_system_process(server) -> list[str]:
    st = _proc_stat()
    out: list[str] = []
    _fmt(out, "minio_system_process_uptime_seconds", "gauge",
         [({}, f"{time.time() - server.started_at:.0f}")])
    _fmt(out, "minio_system_process_cpu_total_seconds", "counter",
         [({}, f"{st.get('utime_s', 0) + st.get('stime_s', 0):.2f}")])
    _fmt(out, "minio_system_process_resident_memory_bytes", "gauge",
         [({}, st.get("rss_bytes", 0))])
    _fmt(out, "minio_system_process_virtual_memory_bytes", "gauge",
         [({}, st.get("vsize", 0))])
    _fmt(out, "minio_system_process_file_descriptor_open_total", "gauge",
         [({}, st.get("fds", 0))])
    _fmt(out, "minio_system_process_file_descriptor_limit_total", "gauge",
         [({}, st.get("fd_limit", 0))])
    _fmt(out, "minio_system_process_io_rchar_bytes", "counter",
         [({}, st.get("rchar", 0))])
    _fmt(out, "minio_system_process_io_wchar_bytes", "counter",
         [({}, st.get("wchar", 0))])
    _fmt(out, "minio_system_process_threads_total", "gauge",
         [({}, st.get("threads", 0))])
    return out


def _g_system_memory(server) -> list[str]:
    out: list[str] = []
    info = {}
    try:
        with open("/proc/meminfo") as f:
            for line in f:
                k, _, rest = line.partition(":")
                info[k] = int(rest.split()[0]) * 1024
    except (OSError, ValueError, IndexError):
        pass
    _fmt(out, "minio_system_memory_total_bytes", "gauge", [({}, info.get("MemTotal", 0))])
    _fmt(out, "minio_system_memory_available_bytes", "gauge",
         [({}, info.get("MemAvailable", 0))])
    _fmt(out, "minio_system_memory_free_bytes", "gauge", [({}, info.get("MemFree", 0))])
    _fmt(out, "minio_system_memory_buffers_bytes", "gauge", [({}, info.get("Buffers", 0))])
    _fmt(out, "minio_system_memory_cache_bytes", "gauge", [({}, info.get("Cached", 0))])
    _fmt(out, "minio_system_memory_shared_bytes", "gauge",
         [({}, info.get("Shmem", 0))])
    total = info.get("MemTotal", 0)
    used = max(total - info.get("MemAvailable", 0), 0)
    _fmt(out, "minio_system_memory_used_bytes", "gauge", [({}, used)])
    _fmt(out, "minio_system_memory_used_perc", "gauge",
         [({}, f"{100.0 * used / total:.2f}" if total else 0)])
    return out


def _g_system_cpu(server) -> list[str]:
    out: list[str] = []
    try:
        load1, load5, load15 = os.getloadavg()
    except OSError:
        load1 = load5 = load15 = 0.0
    _fmt(out, "minio_system_cpu_load_perc_avg", "gauge", [
        ({"interval": "1m"}, f"{load1:.2f}"),
        ({"interval": "5m"}, f"{load5:.2f}"),
        ({"interval": "15m"}, f"{load15:.2f}"),
    ])
    _fmt(out, "minio_system_cpu_load", "gauge", [({}, f"{load1:.2f}")])
    # host CPU time split since boot (/proc/stat first line, jiffies)
    jif: dict[str, int] = {}
    try:
        with open("/proc/stat") as f:
            first = f.readline().split()
        names = ("user", "nice", "system", "idle", "iowait", "irq",
                 "softirq", "steal")
        jif = dict(zip(names, (int(x) for x in first[1:])))
    except (OSError, ValueError, IndexError):
        pass
    tck = float(os.sysconf("SC_CLK_TCK") or 100)

    def j(field: str) -> str:
        return f"{jif.get(field, 0) / tck:.2f}"

    _fmt(out, "minio_system_cpu_user", "counter", [({}, j("user"))])
    _fmt(out, "minio_system_cpu_system", "counter", [({}, j("system"))])
    _fmt(out, "minio_system_cpu_idle", "counter", [({}, j("idle"))])
    _fmt(out, "minio_system_cpu_iowait", "counter", [({}, j("iowait"))])
    _fmt(out, "minio_system_cpu_nice", "counter", [({}, j("nice"))])
    _fmt(out, "minio_system_cpu_steal", "counter", [({}, j("steal"))])
    _fmt(out, "minio_system_cpu_count", "gauge", [({}, os.cpu_count() or 1)])
    return out


def _g_debug_python(server) -> list[str]:
    import gc

    out: list[str] = []
    counts = gc.get_count()
    _fmt(out, "minio_debug_python_gc_objects", "gauge",
         [({"generation": str(i)}, c) for i, c in enumerate(counts)])
    _fmt(out, "minio_debug_python_threads", "gauge",
         [({}, threading.active_count())])
    return out


def _g_cluster_health(server) -> list[str]:
    out: list[str] = []
    pr = _probe_drives(server)
    _fmt(out, "minio_cluster_health_drives_online_count", "gauge", [({}, pr["online"])])
    _fmt(out, "minio_cluster_health_drives_offline_count", "gauge", [({}, pr["offline"])])
    _fmt(out, "minio_cluster_health_drives_count", "gauge",
         [({}, pr["online"] + pr["offline"])])
    # node view: one "node" per distinct drive host (local paths collapse
    # to the local node); a node is online while ANY of its drives is
    nodes: dict[str, int] = {}
    for r in pr["per_drive"]:
        p = r["drive"]
        host = p.split("://", 1)[1].split("/", 1)[0] if "://" in p else "local"
        nodes[host] = max(nodes.get(host, 0), r["online"])
    n_on = sum(nodes.values())
    _fmt(out, "minio_cluster_health_nodes_online_count", "gauge", [({}, n_on)])
    _fmt(out, "minio_cluster_health_nodes_offline_count", "gauge",
         [({}, len(nodes) - n_on)])
    # usable capacity = raw scaled by the erasure data fraction (parity
    # shards store no user bytes)
    n_tot = d_tot = 0
    for pool in server.store.pools:
        for es in pool.sets:
            n_tot += es.n
            d_tot += es.n - es.default_parity
    frac = d_tot / n_tot if n_tot else 1.0
    _fmt(out, "minio_cluster_health_capacity_raw_total_bytes", "gauge",
         [({}, pr["total_bytes"])])
    _fmt(out, "minio_cluster_health_capacity_raw_free_bytes", "gauge",
         [({}, pr["free_bytes"])])
    _fmt(out, "minio_cluster_health_capacity_usable_total_bytes", "gauge",
         [({}, int(pr["total_bytes"] * frac))])
    _fmt(out, "minio_cluster_health_capacity_usable_free_bytes", "gauge",
         [({}, int(pr["free_bytes"] * frac))])
    _fmt(out, "minio_cluster_health_status", "gauge",
         [({}, 1 if pr["offline"] == 0 else 0)], "1 when every drive is online")
    return out


def _g_cluster_usage(server) -> list[str]:
    out: list[str] = []
    bg = getattr(server, "background", None)
    buckets = bg.usage.buckets if bg is not None else {}
    total_b = sum(u.get("size", 0) for u in buckets.values())
    total_o = sum(u.get("objects", 0) for u in buckets.values())
    _fmt(out, "minio_cluster_usage_total_bytes", "gauge", [({}, total_b)])
    _fmt(out, "minio_cluster_usage_object_total", "gauge", [({}, total_o)])
    _fmt(out, "minio_cluster_usage_buckets_total", "gauge", [({}, len(buckets))])
    return out


def _g_cluster_usage_buckets(server) -> list[str]:
    out: list[str] = []
    bg = getattr(server, "background", None)
    buckets = bg.usage.buckets if bg is not None else {}
    _fmt(out, "minio_cluster_bucket_total_bytes", "gauge",
         [({"bucket": b}, u.get("size", 0)) for b, u in sorted(buckets.items())])
    _fmt(out, "minio_cluster_bucket_object_total", "gauge",
         [({"bucket": b}, u.get("objects", 0)) for b, u in sorted(buckets.items())])
    return out


def _g_cluster_erasure_set(server) -> list[str]:
    out: list[str] = []
    rows = []
    by_id = _probe_drives(server)["by_id"]
    for pi, pool in enumerate(server.store.pools):
        for si, es in enumerate(pool.sets):
            ok = sum(1 for d in es.disks if by_id.get(id(d), False))
            rows.append((pi, si, es.n, ok, es.n - es.default_parity))
    _fmt(out, "minio_cluster_erasure_set_online_drives_count", "gauge",
         [({"pool": str(p), "set": str(s)}, ok) for p, s, _, ok, _ in rows])
    # writeQuorum = data, +1 when data == parity (cmd/erasure-object.go)
    wq = {(p, s): (d + 1 if n == 2 * d else d) for p, s, n, _, d in rows}
    _fmt(out, "minio_cluster_erasure_set_overall_write_quorum", "gauge",
         [({"pool": str(p), "set": str(s)}, wq[(p, s)])
          for p, s, _, _, _ in rows])
    _fmt(out, "minio_cluster_erasure_set_read_quorum", "gauge",
         [({"pool": str(p), "set": str(s)}, d) for p, s, _, _, d in rows])
    _fmt(out, "minio_cluster_erasure_set_write_quorum", "gauge",
         [({"pool": str(p), "set": str(s)}, wq[(p, s)])
          for p, s, _, _, _ in rows])
    # tolerance: drives this set can still lose before losing quorum
    _fmt(out, "minio_cluster_erasure_set_read_tolerance", "gauge",
         [({"pool": str(p), "set": str(s)}, max(ok - d, 0))
          for p, s, _, ok, d in rows])
    _fmt(out, "minio_cluster_erasure_set_write_tolerance", "gauge",
         [({"pool": str(p), "set": str(s)}, max(ok - wq[(p, s)], 0))
          for p, s, _, ok, _ in rows])
    _fmt(out, "minio_cluster_erasure_set_read_health", "gauge",
         [({"pool": str(p), "set": str(s)}, 1 if ok >= d else 0)
          for p, s, _, ok, d in rows])
    _fmt(out, "minio_cluster_erasure_set_write_health", "gauge",
         [({"pool": str(p), "set": str(s)}, 1 if ok >= wq[(p, s)] else 0)
          for p, s, _, ok, _ in rows])
    _fmt(out, "minio_cluster_erasure_set_healing_drives_count", "gauge",
         [({"pool": str(p), "set": str(s)}, 0) for p, s, _, _, _ in rows])
    return out


def _g_cluster_iam(server) -> list[str]:
    out: list[str] = []
    iam = server.iam
    temp = sum(1 for u in iam.users.values() if u.is_temp)
    svc = sum(1 for u in iam.users.values() if u.parent and not u.is_temp)
    _fmt(out, "minio_cluster_iam_users_total", "gauge",
         [({}, len(iam.users) - temp - svc)])
    _fmt(out, "minio_cluster_iam_groups_total", "gauge", [({}, len(iam.groups))])
    _fmt(out, "minio_cluster_iam_policies_total", "gauge", [({}, len(iam.policies))])
    _fmt(out, "minio_cluster_iam_sts_accounts_total", "gauge", [({}, temp)])
    _fmt(out, "minio_cluster_iam_svc_accounts_total", "gauge", [({}, svc)])
    return out


def _g_cluster_config(server) -> list[str]:
    out: list[str] = []
    cfg = getattr(server, "config", None)
    n = 0
    if cfg is not None:
        from .config_kv import DEFAULTS

        n = len(DEFAULTS)
    _fmt(out, "minio_cluster_config_subsystems_total", "gauge", [({}, n)])
    return out


def _bg_stat(server, key: str) -> int:
    bg = getattr(server, "background", None)
    return bg.stats.get(key, 0) if bg is not None else 0


def _g_system_network(server) -> list[str]:
    """Internode (grid + storage REST) transport counters — the analogue
    of the reference's minio_system_network_internode_* group."""
    from ..cluster import grid as gridmod

    out: list[str] = []
    st = dict(gridmod.STATS)
    _fmt(out, "minio_system_network_internode_dials_total", "counter",
         [({}, st["dials"])], "Grid connections dialed")
    _fmt(out, "minio_system_network_internode_dial_errors_total", "counter",
         [({}, st["dial_errors"])])
    _fmt(out, "minio_system_network_internode_disconnects_total", "counter",
         [({}, st["disconnects"])])
    _fmt(out, "minio_system_network_internode_sent_bytes_total", "counter",
         [({}, st["tx_bytes"])])
    _fmt(out, "minio_system_network_internode_recv_bytes_total", "counter",
         [({}, st["rx_bytes"])])
    _fmt(out, "minio_system_network_internode_calls_total", "counter",
         [({}, st["calls"])])
    _fmt(out, "minio_system_network_internode_streams_total", "counter",
         [({}, st["streams"])])
    return out


def _g_ilm(server) -> list[str]:
    out: list[str] = []
    _fmt(out, "minio_ilm_expired_objects_total", "counter",
         [({}, _bg_stat(server, "ilm_expired"))])
    _fmt(out, "minio_ilm_transitioned_objects_total", "counter",
         [({}, _bg_stat(server, "ilm_transitioned"))])
    _fmt(out, "minio_ilm_restores_expired_total", "counter",
         [({}, _bg_stat(server, "ilm_restore_expired"))])
    # orphaned warm-tier sweeps awaiting retry (reference tier journal);
    # cached count — scrapes must not pay a store read each
    try:
        from ..ilm import tier as tiermod

        entries = tiermod.journal_size(server.store)
    except Exception:  # noqa: BLE001 — scrape must not fail on store errors
        entries = 0
    _fmt(out, "minio_ilm_tier_journal_entries", "gauge", [({}, entries)])
    return out


def _g_scanner(server) -> list[str]:
    out: list[str] = []
    _fmt(out, "minio_scanner_objects_scanned_total", "counter",
         [({}, _bg_stat(server, "objects_scanned"))])
    _fmt(out, "minio_scanner_cycles_total", "counter", [({}, _bg_stat(server, "scans"))])
    _fmt(out, "minio_scanner_heals_queued_total", "counter",
         [({}, _bg_stat(server, "heals_queued"))])
    _fmt(out, "minio_scanner_heals_done_total", "counter",
         [({}, _bg_stat(server, "heals_done"))])
    _fmt(out, "minio_scanner_heals_failed_total", "counter",
         [({}, _bg_stat(server, "heals_failed"))])
    return out


def _g_replication(server) -> list[str]:
    out: list[str] = []
    repl = getattr(server, "replication", None)
    st = dict(repl.stats) if repl is not None else {}
    _fmt(out, "minio_replication_total", "counter", [({}, st.get("replicated", 0))])
    _fmt(out, "minio_replication_deletes_total", "counter", [({}, st.get("deletes", 0))])
    _fmt(out, "minio_replication_failed_total", "counter", [({}, st.get("failed", 0))])
    _fmt(out, "minio_replication_queued_total", "counter", [({}, st.get("queued", 0))])
    return out


def _g_notification(server) -> list[str]:
    out: list[str] = []
    noti = getattr(server, "notifier", None)
    st = dict(noti.stats) if noti is not None else {}
    _fmt(out, "minio_notify_events_sent_total", "counter", [({}, st.get("sent", 0))])
    _fmt(out, "minio_notify_events_failed_total", "counter", [({}, st.get("failed", 0))])
    _fmt(out, "minio_notify_events_skipped_total", "counter", [({}, st.get("dropped", 0))])
    return out


def _g_audit(server) -> list[str]:
    out: list[str] = []
    audit = getattr(server, "audit", None)
    st = dict(audit.stats) if audit is not None else {}
    _fmt(out, "minio_audit_total_messages", "counter", [({}, st.get("sent", 0))])
    _fmt(out, "minio_audit_failed_messages", "counter", [({}, st.get("failed", 0))])
    return out


def _g_api_qos(server) -> list[str]:
    """QoS plane: admission-control state per class, last-minute per-API
    latency (qos/lastminute.py ring), dynamic-timeout deadlines, and the
    TPU dispatcher's priority-lane counters. The dispatcher series are
    the wire-visible proof of the batching policy: fg/bg block totals,
    forced (anti-starvation) promotions, and the invariant witness
    ``fg_deferred_behind_bg`` (always 0 when foreground never waits
    behind background batch slots)."""
    out: list[str] = []
    qos = getattr(server, "qos", None)
    if qos is None:
        return out
    snap = qos.admission.snapshot()
    _fmt(out, "minio_api_qos_inflight", "gauge",
         [({"class": c}, s["inflight"]) for c, s in sorted(snap.items())],
         "In-flight requests per admission class")
    _fmt(out, "minio_api_qos_waiting", "gauge",
         [({"class": c}, s["waiting"]) for c, s in sorted(snap.items())])
    _fmt(out, "minio_api_qos_max_inflight", "gauge",
         [({"class": c}, s["maxInflight"]) for c, s in sorted(snap.items())])
    _fmt(out, "minio_api_qos_admitted_total", "counter",
         [({"class": c}, s["admitted"]) for c, s in sorted(snap.items())])
    _fmt(out, "minio_api_qos_rejected_total", "counter",
         [({"class": c, "reason": r}, s[k])
          for c, s in sorted(snap.items())
          for r, k in (("queue_full", "rejectedFull"),
                       ("deadline", "rejectedTimeout"))])
    lm = qos.last_minute.totals()
    _fmt(out, "minio_api_qos_last_minute_requests", "gauge",
         [({"name": a}, v["count"]) for a, v in lm.items()])
    _fmt(out, "minio_api_qos_last_minute_avg_seconds", "gauge",
         [({"name": a}, f"{v['avg_seconds']:.6f}") for a, v in lm.items()])
    _fmt(out, "minio_api_qos_last_minute_max_seconds", "gauge",
         [({"name": a}, f"{v['max_seconds']:.6f}") for a, v in lm.items()])
    _fmt(out, "minio_api_qos_last_minute_ttfb_avg_seconds", "gauge",
         [({"name": a}, f"{v['ttfb_avg_seconds']:.6f}") for a, v in lm.items()])
    from ..qos import dyntimeout

    _fmt(out, "minio_tpu_dynamic_timeout_seconds", "gauge",
         [({"name": n}, f"{v:.3f}")
          for n, v in sorted(dyntimeout.snapshot().items())])
    from ..parallel import dispatcher as dmod

    ds = dmod.aggregate_stats()
    _fmt(out, "minio_tpu_dispatch_blocks_total", "counter",
         [({"class": "foreground"}, ds.get("fg_blocks", 0)),
          ({"class": "background"}, ds.get("bg_blocks", 0))],
         "Stripe blocks dispatched per priority lane")
    _fmt(out, "minio_tpu_dispatch_bg_forced_blocks_total", "counter",
         [({}, ds.get("bg_forced", 0))])
    _fmt(out, "minio_tpu_dispatch_bg_batch_max_blocks", "gauge",
         [({}, ds.get("bg_batch_max", 0))])
    _fmt(out, "minio_tpu_dispatch_fg_deferred_behind_bg_total", "counter",
         [({}, ds.get("fg_deferred_behind_bg", 0))])
    return out


def _hist_rows(edges, hist, label_key="le"):
    """Cumulative (le, count) rows for a fixed-edge histogram list
    (len(edges)+1 buckets, last is the +Inf overflow)."""
    h = list(hist) + [0] * (len(edges) + 1 - len(hist))
    cum = 0
    rows = []
    for i, edge in enumerate(edges):
        cum += h[i]
        rows.append(({label_key: str(edge)}, cum))
    rows.append(({label_key: "+Inf"}, cum + h[len(edges)]))
    return rows


def _g_api_tpu(server) -> list[str]:
    """TPU dispatcher plane: batch occupancy, queue-wait and device-time
    histograms, host-vs-device time split, and the QoS lane counters —
    the series that let the BENCH trajectory separate dispatcher
    efficiency (host orchestration, batching) from raw kernel throughput
    (device execute time)."""
    from ..parallel import dispatcher as dmod

    out: list[str] = []
    ds = dmod.aggregate_stats()
    dispatches = ds.get("dispatches", 0)
    _fmt(out, "minio_tpu_dispatch_total", "counter", [({}, dispatches)],
         "Fused encode dispatches")
    _fmt(out, "minio_tpu_dispatch_blocks_total", "counter",
         [({"class": "foreground"}, ds.get("fg_blocks", 0)),
          ({"class": "background"}, ds.get("bg_blocks", 0))])
    _fmt(out, "minio_tpu_batch_occupancy_avg_pct", "gauge",
         [({}, f"{ds.get('occupancy_pct_sum', 0.0) / max(dispatches, 1):.2f}")],
         "Mean filled fraction of the padded dispatch bucket")
    _fmt(out, "minio_tpu_batch_max_blocks", "gauge", [({}, ds.get("max_batch", 0))])
    _fmt(out, "minio_tpu_host_seconds_total", "counter",
         [({}, f"{ds.get('host_s', 0.0):.6f}")],
         "Host-side batch assembly + fan-out time")
    _fmt(out, "minio_tpu_device_seconds_total", "counter",
         [({}, f"{ds.get('device_s', 0.0):.6f}")],
         "Device execute time (incl. transfers) per dispatch")
    _fmt(out, "minio_tpu_queue_wait_seconds_total", "counter",
         [({}, f"{ds.get('queue_wait_s', 0.0):.6f}")])
    _fmt(out, "minio_tpu_queue_wait_seconds_distribution", "counter",
         _hist_rows(dmod.QUEUE_WAIT_BUCKETS, ds.get("queue_wait_hist", [])),
         "Per-item wait from submit to dispatch start")
    _fmt(out, "minio_tpu_device_time_seconds_distribution", "counter",
         _hist_rows(dmod.DEVICE_TIME_BUCKETS, ds.get("device_time_hist", [])),
         "Per-dispatch device execute time")
    _fmt(out, "minio_tpu_fused_dispatches_total", "counter",
         [({}, ds.get("fused", 0))])
    _fmt(out, "minio_tpu_fused_failures_total", "counter",
         [({}, ds.get("fused_failures", 0))])
    _fmt(out, "minio_tpu_dispatch_bg_forced_blocks_total", "counter",
         [({}, ds.get("bg_forced", 0))])
    _fmt(out, "minio_tpu_dispatch_fg_deferred_behind_bg_total", "counter",
         [({}, ds.get("fg_deferred_behind_bg", 0))])
    # per-code-family plane (erasure/coder.py): encode/decode volume per
    # family plus the repair-bandwidth counters — heal ingress is THE
    # number the cauchy family exists to shrink (BENCH_r09 gate)
    from ..erasure.coder import family_stats_snapshot

    fs = family_stats_snapshot()
    fams = sorted(fs)
    _fmt(out, "minio_tpu_encode_blocks_total", "counter",
         [({"family": f}, fs[f].get("encode_blocks", 0)) for f in fams],
         "Stripe blocks erasure-encoded per code family")
    _fmt(out, "minio_tpu_decode_blocks_total", "counter",
         [({"family": f}, fs[f].get("decode_blocks", 0)) for f in fams],
         "Stripe blocks reconstructed per code family")
    _fmt(out, "minio_heal_ingress_bytes_total", "counter",
         [({"family": f}, fs[f].get("heal_ingress_bytes", 0)) for f in fams],
         "Survivor bytes read into heal reconstructions per family")
    _fmt(out, "minio_tpu_degraded_ingress_bytes_total", "counter",
         [({"family": f}, fs[f].get("degraded_ingress_bytes", 0))
          for f in fams],
         "Survivor bytes fetched for degraded-GET reconstruction")
    _fmt(out, "minio_tpu_repair_partial_blocks_total", "counter",
         [({"family": f}, fs[f].get("repair_partial_blocks", 0))
          for f in fams],
         "Stripe blocks rebuilt via sub-chunk partial repair")
    from ..erasure.coder import decode_matrix_cache_snapshot

    dc = decode_matrix_cache_snapshot()
    _fmt(out, "minio_tpu_decode_matrix_cache_total", "counter",
         [({"family": f, "result": r}, dc["families"][f][k])
          for f in sorted(dc["families"])
          for r, k in (("hit", "hits"), ("miss", "misses"))],
         "Decode-matrix LRU lookups per family (per-failure-pattern "
         "inverses; ops/decode_cache)")
    _fmt(out, "minio_tpu_decode_matrix_cache_entries", "gauge",
         [({}, dc["entries"])],
         "Decode matrices resident in the LRU")
    # zero-copy data plane (erasure/bufpool.py): counted hot-path copies
    # per named site plus stripe-arena pool behaviour — the A/B surface
    # for the MINIO_TPU_ZEROCOPY lever (BENCH_r13 gates staging==0 on
    # aligned streaming PUTs against these exact series)
    from ..erasure import bufpool

    cs = bufpool.copies_snapshot()
    _fmt(out, "minio_tpu_ingest_copies_total", "counter",
         [({"site": s}, cs[s]) for s in sorted(cs)],
         "Full-buffer copies at named data-plane sites (zero at "
         "'staging' under the zero-copy plane on aligned streaming PUTs)")
    ps = bufpool.pool_stats_snapshot()
    _fmt(out, "minio_tpu_pool_acquires_total", "counter",
         [({"result": "hit"}, ps.get("hits", 0)),
          ({"result": "miss"}, ps.get("misses", 0)),
          ({"result": "unpooled"}, ps.get("unpooled", 0))],
         "Stripe-arena pool acquisitions by outcome (unpooled = size "
         "outside the pooled classes, plain allocation)")
    _fmt(out, "minio_tpu_pool_recycled_bytes_total", "counter",
         [({}, ps.get("recycled_bytes", 0))])
    _fmt(out, "minio_tpu_pool_resident_bytes", "gauge",
         [({}, ps.get("resident_bytes", 0))],
         "Recycled arena bytes resident in the pool free lists")
    _fmt(out, "minio_tpu_pool_live_leases", "gauge",
         [({}, ps.get("live_leases", 0))])
    _fmt(out, "minio_tpu_pool_lease_violations_total", "counter",
         [({}, ps.get("violations", 0))],
         "Lease-discipline violations (double-release / retain-dead); "
         "always 0 in a healthy process, sanitizer-witnessed otherwise")
    _fmt(out, "minio_tpu_dispatch_pad_blocks_total", "counter",
         [({}, ds.get("pad_blocks", 0))],
         "Zero-filled pad blocks appended to round batches up to buckets")
    _fmt(out, "minio_tpu_dispatch_arena_direct_total", "counter",
         [({}, ds.get("arena_direct", 0))],
         "Dispatches fed straight from a caller arena (exact bucket fit, "
         "no assembly copy)")
    _fmt(out, "minio_tpu_dispatch_bucket_blocks_distribution", "counter",
         _hist_rows(dmod.BUCKET_BLOCK_BUCKETS, ds.get("bucket_hist", [])),
         "Padded bucket size (blocks) per dispatch")
    return out


def _g_api_trace(server) -> list[str]:
    """Trace pubsub health: subscriber count and per-subscriber dropped
    records (publish never blocks; a slow consumer loses records and
    these series make that visible)."""
    out: list[str] = []
    tr = getattr(server, "trace", None)
    if tr is None:
        return out
    subs = tr.subscriber_stats()
    _fmt(out, "minio_trace_subscribers", "gauge", [({}, len(subs))])
    _fmt(out, "minio_trace_dropped_records_total", "counter",
         [({}, tr.dropped_total)],
         "Records dropped across all subscribers (queue full)")
    _fmt(out, "minio_trace_subscriber_dropped_records", "gauge",
         [({"subscriber": s["label"]}, s["dropped"]) for s in subs])
    _fmt(out, "minio_trace_subscriber_queued_records", "gauge",
         [({"subscriber": s["label"]}, s["queued"]) for s in subs])
    return out


def _g_api_fault(server) -> list[str]:
    """Robustness plane: armed fault-injection rules and their hits, the
    hedged-read win/loss counters (erasure/set.py GET window path), the
    latency-breaker trip count, and the TPU backend degradation ladder
    (2=fused, 1=XLA, 0=numpy) with its demote/promote transitions."""
    from .. import fault
    from ..parallel import dispatcher as dmod
    from ..storage.health import HealthCheckedDisk

    out: list[str] = []
    st = fault.status()
    c = st["counters"]
    _fmt(out, "minio_fault_rules_active", "gauge", [({}, len(st["rules"]))],
         "Armed fault-injection rules on this node")
    _fmt(out, "minio_fault_injected_total", "counter",
         [({"boundary": b}, c.get(b, 0))
          for b in ("storage", "network", "tpu", "topology", "diag")],
         "Injected fault hits per boundary")
    _fmt(out, "minio_fault_hedge_reads_total", "counter",
         [({}, c.get("hedge_reads", 0))],
         "GET windows that fired hedged parity reads past the budget")
    _fmt(out, "minio_fault_hedge_wins_total", "counter",
         [({}, c.get("hedge_wins", 0))],
         "Hedged windows where the parity decode beat the straggler")
    _fmt(out, "minio_fault_hedge_losses_total", "counter",
         [({}, c.get("hedge_losses", 0))])
    _fmt(out, "minio_fault_repair_hedge_reads_total", "counter",
         [({}, c.get("repair_hedge_reads", 0))],
         "Repair-plan windows whose sub-chunk reads blew the hedge "
         "budget and fired the generic full-frame gather as the hedge")
    _fmt(out, "minio_fault_repair_hedge_wins_total", "counter",
         [({}, c.get("repair_hedge_wins", 0))],
         "Hedged repair blocks where the full gather beat the plan")
    _fmt(out, "minio_fault_repair_hedge_losses_total", "counter",
         [({}, c.get("repair_hedge_losses", 0))])
    _fmt(out, "minio_fault_repair_fallback_blocks_total", "counter",
         [({}, c.get("repair_fallback_blocks", 0))],
         "Repair-plan blocks served by the generic full gather "
         "(hedge wins + mid-plan read failures); the plan itself "
         "is never abandoned")
    trips = 0
    for d in getattr(server.store, "disks", []):
        if isinstance(d, HealthCheckedDisk):
            trips += d.latency_trips
    _fmt(out, "minio_fault_drive_latency_trips_total", "counter",
         [({}, trips)],
         "Circuit-breaker opens caused by chronic drive latency")
    ds = dmod.aggregate_stats()
    _fmt(out, "minio_tpu_backend_level", "gauge",
         [({}, ds.get("backend_level", dmod.LEVEL_FUSED))],
         "Encode backend rung: 2=healthy, 1=fused faulted out (XLA), "
         "0=device gone (numpy)")
    _fmt(out, "minio_tpu_backend_demotions_total", "counter",
         [({}, ds.get("demotions", 0))])
    _fmt(out, "minio_tpu_backend_promotions_total", "counter",
         [({}, ds.get("promotions", 0))])
    _fmt(out, "minio_tpu_backend_device_faults_total", "counter",
         [({}, ds.get("device_faults", 0))])
    _fmt(out, "minio_tpu_backend_probe_batches_total", "counter",
         [({}, ds.get("probes", 0))])
    _fmt(out, "minio_tpu_backend_numpy_blocks_total", "counter",
         [({}, ds.get("numpy_blocks", 0))],
         "Stripe blocks served by the degraded numpy rung")
    return out


def _g_api_cache(server) -> list[str]:
    """Caching layer (cache/): per-tier hit/miss/eviction counters, the
    global byte budget's fill, singleflight collapse counts, and the
    write-through invalidation/revalidation activity — the series that
    prove (or disprove) the hot-GET path is actually being served from
    memory."""
    from .. import cache
    from ..cache import coherence as cache_coherence
    from ..storage import xlstorage

    out: list[str] = []
    if server.store is None:
        return out
    st = cache.aggregate_stats(server.store)
    tiers = ("fileinfo", "data", "segments", "listing")

    def rows(key: str):
        return [({"tier": t}, st[t].get(key, 0)) for t in tiers]

    _fmt(out, "minio_cache_enabled", "gauge", [({}, int(st["enabled"]))])
    _fmt(out, "minio_cache_hits_total", "counter", rows("hits"),
         "Cache hits per tier")
    _fmt(out, "minio_cache_misses_total", "counter", rows("misses"))
    _fmt(out, "minio_cache_evictions_total", "counter",
         [({"tier": t}, st[t].get("evictions", 0))
          for t in ("fileinfo", "data", "segments")])
    _fmt(out, "minio_cache_invalidations_total", "counter", rows("invalidations"))
    _fmt(out, "minio_cache_revalidations_total", "counter",
         [({"tier": t}, st[t].get("revalidations", 0))
          for t in ("fileinfo", "data", "segments")])
    _fmt(out, "minio_cache_entries", "gauge", rows("entries"))
    _fmt(out, "minio_cache_bytes", "gauge",
         [({"tier": "data"}, st["data"].get("bytes", 0)),
          ({"tier": "segments"}, st["segments"].get("mem_bytes", 0)),
          ({"tier": "total"}, st["bytesTotal"])],
         "Cached bytes vs the MINIO_TPU_CACHE_MEM_MB budget")
    _fmt(out, "minio_cache_singleflight_shared_total", "counter",
         [({}, st["fileinfo"].get("singleflight_shared", 0))],
         "Concurrent metadata misses that shared one quorum read")
    _fmt(out, "minio_cache_data_fills_total", "counter",
         [({"tier": "data"}, st["data"].get("fills", 0)),
          ({"tier": "segments"}, st["segments"].get("fills", 0))])
    # range-segment tier: per-request range outcomes + the disk/NVMe
    # second tier's movement and integrity counters
    sg = st["segments"]
    _fmt(out, "minio_cache_segment_range_requests_total", "counter",
         [({"result": "hit"}, sg.get("range_hits", 0)),
          ({"result": "miss"}, sg.get("range_misses", 0))],
         "Ranged GETs fully served from cached segments vs fallen "
         "through to the erasure path")
    _fmt(out, "minio_cache_segment_disk_entries", "gauge",
         [({}, sg.get("disk_entries", 0))])
    _fmt(out, "minio_cache_segment_disk_bytes", "gauge",
         [({"kind": "used"}, sg.get("disk_bytes", 0)),
          ({"kind": "budget"}, sg.get("disk_budget", 0))],
         "Disk/NVMe segment tier fill vs MINIO_TPU_CACHE_DISK_MB")
    _fmt(out, "minio_cache_segment_disk_moves_total", "counter",
         [({"dir": "demote"}, sg.get("demotions", 0)),
          ({"dir": "promote"}, sg.get("promotions", 0)),
          ({"dir": "evict"}, sg.get("disk_evictions", 0))])
    _fmt(out, "minio_cache_segment_quarantined_total", "counter",
         [({}, sg.get("quarantined", 0))],
         "Disk-tier entries dropped on failed integrity verification "
         "(torn write / bitrot / read error); reads fell back to the "
         "erasure path")
    pf = st["prefetch"]
    _fmt(out, "minio_cache_prefetch_runs_total", "counter",
         [({"event": "detected"}, pf.get("runs_detected", 0)),
          ({"event": "scheduled"}, pf.get("scheduled", 0)),
          ({"event": "completed"}, pf.get("completed", 0)),
          ({"event": "error"}, pf.get("errors", 0))],
         "Sequential read-ahead activity (cache/prefetch.py)")
    _fmt(out, "minio_cache_prefetch_bytes_total", "counter",
         [({}, pf.get("bytes_read", 0))])
    _fmt(out, "minio_cache_epoch", "gauge", [({}, st["epoch"])],
         "Coherence epoch (bumped on detected lost invalidations)")
    co = cache_coherence.stats()
    _fmt(out, "minio_cache_coherence_broadcasts_total", "counter",
         [({"result": "sent"}, co["sent"]),
          ({"result": "error"}, co["send_errors"])])
    _fmt(out, "minio_cache_coherence_received_total", "counter",
         [({}, co["received"])])
    _fmt(out, "minio_cache_coherence_gen_gaps_total", "counter",
         [({}, co["gen_gaps"])],
         "Generation-sequence gaps observed (lost invalidations healed "
         "via epoch revalidation)")
    # sharded listing metacache: the metadata-plane scale counters —
    # pages-per-walk proves O(1) drive-walks per continuation page, the
    # persisted tier's adopt/fault-in activity proves restart survival
    mc = st["listing"]
    _fmt(out, "minio_cache_metacache_requests_total", "counter",
         [({"result": "hit"}, mc.get("hits", 0)),
          ({"result": "miss"}, mc.get("misses", 0))],
         "Listing metacache lookups (hit = page served without a walk)")
    _fmt(out, "minio_cache_metacache_stores_total", "counter",
         [({}, mc.get("stores", 0))])
    _fmt(out, "minio_cache_metacache_evictions_total", "counter",
         [({}, mc.get("evictions", 0))],
         "Entries dropped by TTL expiry, capacity, or failed fault-in")
    _fmt(out, "minio_cache_metacache_invalidations_total", "counter",
         [({}, mc.get("invalidations", 0))],
         "Entries dropped through the mutation choke point")
    _fmt(out, "minio_cache_metacache_walks_total", "counter",
         [({}, mc.get("walks", 0))],
         "Full merged drive walks started (listing pages that could "
         "not be served from the sharded cache)")
    _fmt(out, "minio_cache_metacache_entries", "gauge",
         [({}, mc.get("entries", 0))])
    _fmt(out, "minio_cache_metacache_shards", "gauge",
         [({}, mc.get("shards", 0))],
         "Loaded key-range shards across in-memory listing entries")
    _fmt(out, "minio_cache_metacache_persisted_total", "counter",
         [({}, mc.get("persisted", 0))],
         "Shard + index docs written under .minio.sys")
    _fmt(out, "minio_cache_metacache_persist_adopts_total", "counter",
         [({}, mc.get("persist_adopts", 0))],
         "Persisted indexes adopted (restarted node or cluster peer)")
    _fmt(out, "minio_cache_metacache_shard_loads_total", "counter",
         [({}, mc.get("shard_loads", 0))],
         "Individual shard docs faulted in on demand")
    # shard-file fan-out: the inline small-object fast path's proof
    # counters — inline PUT/GET/HEAD must leave the user plane flat
    fo = xlstorage.fanout_stats()
    _fmt(out, "minio_storage_shard_io_total", "counter",
         [({"op": "read", "plane": "user"}, fo["shard_reads_user"]),
          ({"op": "read", "plane": "sys"}, fo["shard_reads_sys"]),
          ({"op": "write", "plane": "user"}, fo["shard_writes_user"]),
          ({"op": "write", "plane": "sys"}, fo["shard_writes_sys"]),
          ({"op": "commit", "plane": "user"}, fo["shard_commits_user"]),
          ({"op": "commit", "plane": "sys"}, fo["shard_commits_sys"])],
         "Shard-file opens/commits by plane (user buckets vs "
         ".minio.sys); metadata-only ops never move these")
    return out


def _g_api_sanitizer(server) -> list[str]:
    """Runtime sanitizer (analysis/sanitizer.py): violation counters by
    kind, the attributes under the access witness, and loop-stall
    episodes — chaos/load runs scrape this group to assert a run
    completed with zero race witnesses."""
    from ..analysis import sanitizer

    out: list[str] = []
    st = sanitizer.status()
    _fmt(out, "minio_sanitizer_enabled", "gauge",
         [({}, int(st["enabled"]))],
         "1 when MINIO_TPU_SANITIZE is active in this process")
    _fmt(out, "minio_sanitizer_violations_total", "counter",
         [({"kind": k}, v) for k, v in sorted(st["violations"].items())],
         "Sanitizer violations by kind (lock.order, attr.race, "
         "loop.stall, env.leak, resource.leak)")
    _fmt(out, "minio_sanitizer_witnessed_attributes", "gauge",
         [({}, len(st["witnessedAttrs"]))],
         "Cross-context attributes under the runtime access witness")
    _fmt(out, "minio_sanitizer_static_lock_ranks", "gauge",
         [({}, st["staticLockRanks"])],
         "Lock ids loaded from the static docs/LOCK_ORDER.md ordering")
    _fmt(out, "minio_sanitizer_loop_stall_episodes_total", "counter",
         [({}, st["stallEpisodes"])],
         "Event-loop stall episodes the watchdog reported")
    return out


def _g_api_topology(server) -> list[str]:
    """Elastic-topology plane (placement/): per-pool capacity/objects and
    the usage skew rebalance works down, rebalance/decommission progress
    (moved bytes/objects, throughput, ETA), and the placement engine's
    rule-hit/decision counters — the series the topology harness phase
    gates on."""
    out: list[str] = []
    store = server.store
    pools = getattr(store, "pools", None)
    if not pools:
        return out
    pm = getattr(server, "pool_mgr", None)
    usage = pm.pool_usage() if pm is not None else []
    _fmt(out, "minio_topology_pools", "gauge", [({}, len(pools))],
         "Attached server pools")
    _fmt(out, "minio_topology_pool_bytes", "gauge",
         [({"pool": str(u["pool"]), "kind": k},
           u["total"] if k == "total" else u["total"] - u["free"])
          for u in usage for k in ("total", "used")],
         "Per-pool drive capacity and fill")
    _fmt(out, "minio_topology_pool_used_pct", "gauge",
         [({"pool": str(u["pool"])}, u["usedPct"]) for u in usage])
    if usage:
        skew = max(u["usedPct"] for u in usage) - min(
            u["usedPct"] for u in usage
        )
        _fmt(out, "minio_topology_usage_skew_pct", "gauge",
             [({}, round(skew, 2))],
             "Max-min pool fill spread (continuous rebalance converges "
             "below MINIO_TPU_REBALANCE_THRESHOLD_PCT)")
    if pm is not None:
        # the O(objects) listing walk rides the manager's TTL cache
        data = pm.pool_data_usage_cached()
        _fmt(out, "minio_topology_pool_objects", "gauge",
             [({"pool": str(u["pool"])}, u["objects"]) for u in data],
             "Stored objects per pool (listing walk, cached between "
             "scrapes)")
        _fmt(out, "minio_topology_pool_data_bytes", "gauge",
             [({"pool": str(u["pool"])}, u["bytes"]) for u in data],
             "Stored object bytes per pool — the signal rebalance "
             "equalizes")
        _fmt(out, "minio_topology_data_skew_pct", "gauge",
             [({}, round(pm.data_spread_pct(data), 3))],
             "Max-min stored-byte share spread across pools")
        rb = pm.rebalance_status()
        states = ("idle", "running", "done", "stopped", "failed")
        _fmt(out, "minio_rebalance_state", "gauge",
             [({"state": s}, int(rb.get("state", "idle") == s))
              for s in states])
        _fmt(out, "minio_rebalance_moved_objects_total", "counter",
             [({}, rb.get("moved", 0))])
        _fmt(out, "minio_rebalance_moved_bytes_total", "counter",
             [({}, rb.get("moved_bytes", 0))],
             "Bytes the rebalance mover re-PUT into destination pools")
        _fmt(out, "minio_rebalance_failed_objects_total", "counter",
             [({}, rb.get("failed", 0))])
        _fmt(out, "minio_rebalance_skipped_pinned_total", "counter",
             [({}, rb.get("skipped_pinned", 0))],
             "Moves refused because a placement pin binds the key to "
             "its current pool")
        _fmt(out, "minio_rebalance_throughput_mibps", "gauge",
             [({}, rb.get("throughput_mibps", 0.0))],
             "Mover throughput over the current/last rebalance run")
        eta = rb.get("eta_s")
        _fmt(out, "minio_rebalance_eta_seconds", "gauge",
             [({}, eta if eta is not None else -1)],
             "Estimated seconds to fill-spread convergence (-1 unknown)")
        # in-memory table only: per-scrape checkpoint reads (a quorum
        # get_object per pool ending in ObjectNotFound) are scrape-path
        # poison; a restarted node re-surfaces persisted state the
        # moment its decommission resumes
        decoms = pm.decom_snapshot()
        rows_state, rows_obj, rows_bytes, rows_failed = [], [], [], []
        for i, st in sorted(decoms.items()):
            lbl = {"pool": str(i)}
            rows_state.append(({**lbl, "state": st.state}, 1))
            rows_obj.append((lbl, st.objects_moved))
            rows_bytes.append((lbl, st.bytes_moved))
            rows_failed.append((lbl, st.failed))
        _fmt(out, "minio_decommission_state", "gauge", rows_state)
        _fmt(out, "minio_decommission_moved_objects_total", "counter",
             rows_obj)
        _fmt(out, "minio_decommission_moved_bytes_total", "counter",
             rows_bytes)
        _fmt(out, "minio_decommission_failed_objects_total", "counter",
             rows_failed)
    pl = getattr(store, "placement", None)
    if pl is not None:
        st = pl.status()
        _fmt(out, "minio_placement_enabled", "gauge",
             [({}, int(st["enabled"]))])
        _fmt(out, "minio_placement_rules", "gauge",
             [({}, len(st["rules"]))])
        _fmt(out, "minio_placement_rule_hits_total", "counter",
             [({"rule": r["bucket"] + "/" + r["prefix"],
                "mode": r["mode"]}, r["hits"])
              for r in st["rules"]],
             "PUT placements decided by each rule")
        _fmt(out, "minio_placement_decisions_total", "counter",
             [({"kind": k}, v)
              for k, v in sorted(st["decisions"].items())],
             "Pool decisions by kind (pin/spread rule vs "
             "weight-by-free-space default)")
    return out


def _g_system_drive_latency(server) -> list[str]:
    """Per-drive, per-op latency (HealthCheckedDisk accounting): lets a
    slow p99 GET be attributed to one laggy disk instead of the whole
    quorum."""
    from ..storage.health import HealthCheckedDisk

    out: list[str] = []
    counts, totals = [], []
    for d in server.store.disks:
        if not isinstance(d, HealthCheckedDisk):
            continue
        ep = str(getattr(d, "endpoint", "?"))
        for op, (n, total_s) in sorted(d.op_stats_snapshot().items()):
            counts.append(({"drive": ep, "api": op}, n))
            totals.append(({"drive": ep, "api": op}, f"{total_s:.6f}"))
    _fmt(out, "minio_system_drive_api_calls_total", "counter", counts,
         "Storage API calls per drive and op")
    _fmt(out, "minio_system_drive_api_seconds_total", "counter", totals)
    return out


def _g_api_diag(server) -> list[str]:
    """Self-measurement plane (diag/): run counters, the last
    speedtest/netperf results as gauges (the per-drive and per-peer
    matrices a chaos-injected slow drive or slow peer must stand out
    in), and the continuous profiler's wall-time attribution — where the
    process actually spends its time, by subsystem, without anyone
    having run a profile."""
    from .. import diag

    out: list[str] = []
    st = diag.stats()
    last = diag.last_results()
    _fmt(out, "minio_diag_runs_total", "counter",
         [({"kind": k}, n) for k, n in sorted(st["runs"].items())],
         "Completed self-measurement runs by kind (object/drive/net)")
    _fmt(out, "minio_diag_errors_total", "counter", [({}, st["errors"])])

    obj = last.get("object", {})
    knee = obj.get("knee", {})
    _fmt(out, "minio_diag_speedtest_put_mibps", "gauge",
         [({}, knee["putMiBps"])] if knee else [],
         "Knee-point PUT throughput of the last object speedtest")
    _fmt(out, "minio_diag_speedtest_get_mibps", "gauge",
         [({}, knee["getMiBps"])] if knee else [])
    _fmt(out, "minio_diag_speedtest_knee_concurrency", "gauge",
         [({}, knee["concurrency"])] if knee else [],
         "Concurrency at which the autotune ramp stopped paying")

    drv = last.get("drive", {})
    rows = [d for d in drv.get("drives", ()) if "error" not in d]
    _fmt(out, "minio_diag_drive_write_mibps", "gauge",
         [({"drive": d["endpoint"]}, d["writeMiBps"]) for d in rows],
         "Sequential write MiB/s per drive, last drive speedtest")
    _fmt(out, "minio_diag_drive_read_mibps", "gauge",
         [({"drive": d["endpoint"]}, d["readMiBps"]) for d in rows])
    _fmt(out, "minio_diag_drive_rand_read_p99_ms", "gauge",
         [({"drive": d["endpoint"]}, d["randRead"]["p99Ms"]) for d in rows],
         "Random 4KiB read p99 per drive, last drive speedtest")

    net = last.get("net", {})
    prow = [(p, r) for p, r in sorted(net.get("peers", {}).items())
            if "error" not in r]
    _fmt(out, "minio_diag_net_mibps", "gauge",
         [({"peer": p}, r["throughputMiBps"]) for p, r in prow],
         "Grid echo throughput per peer, last netperf")
    _fmt(out, "minio_diag_net_rtt_p99_ms", "gauge",
         [({"peer": p}, r["rttP99Ms"]) for p, r in prow])

    cp = getattr(server, "cprofiler", None)
    snap = cp.snapshot() if cp is not None else {"samples": 0, "counts": {}}
    _fmt(out, "minio_diag_profile_enabled", "gauge",
         [({}, int(cp is not None))],
         "1 when the continuous ~19Hz profiler is sampling")
    _fmt(out, "minio_diag_profile_samples_total", "counter",
         [({}, snap["samples"])])
    _fmt(out, "minio_diag_profile_thread_samples_total", "counter",
         [({"subsystem": sub, "state": state}, n)
          for (sub, state), n in sorted(snap["counts"].items())],
         "Wall-time attribution: sampled thread stacks by owning "
         "subsystem and running/waiting state")
    return out


def _g_system_selftest(server) -> list[str]:
    """Hardware fingerprint from the last self-measurement runs — the
    series the scenario engine scrapes to stamp every BENCH json, so a
    CPU-shadowed number is self-describing."""
    from .. import diag

    out: list[str] = []
    last = diag.last_results()
    _fmt(out, "minio_system_selftest_cpu_cores", "gauge",
         [({}, os.cpu_count() or 1)],
         "Cores visible to this process")
    _fmt(out, "minio_system_selftest_workers", "gauge",
         [({}, getattr(server, "worker_count", 1))])

    drv = [d for d in last.get("drive", {}).get("drives", ())
           if "error" not in d]
    _fmt(out, "minio_system_selftest_drive_write_mibps", "gauge",
         [({}, max(d["writeMiBps"] for d in drv))] if drv else [],
         "Best sequential drive write MiB/s, last drive speedtest")
    _fmt(out, "minio_system_selftest_drive_read_mibps", "gauge",
         [({}, max(d["readMiBps"] for d in drv))] if drv else [])

    net = [r for r in last.get("net", {}).get("peers", {}).values()
           if "error" not in r]
    _fmt(out, "minio_system_selftest_loopback_mibps", "gauge",
         [({}, max(r["throughputMiBps"] for r in net))] if net else [],
         "Best grid echo throughput (loopback/peer), last netperf")
    _fmt(out, "minio_system_selftest_complete", "gauge",
         [({}, int(bool(drv) and bool(net)))],
         "1 when drive + net selftests have both completed")
    return out


# collector path -> renderer; bucket paths live in V3_BUCKET_GROUPS
V3_GROUPS = {
    "/api/requests": _g_api_requests,
    "/api/qos": _g_api_qos,
    "/api/tpu": _g_api_tpu,
    "/api/trace": _g_api_trace,
    "/api/fault": _g_api_fault,
    "/api/cache": _g_api_cache,
    "/api/sanitizer": _g_api_sanitizer,
    "/api/topology": _g_api_topology,
    "/api/diag": _g_api_diag,
    "/system/drive/latency": _g_system_drive_latency,
    "/system/selftest": _g_system_selftest,
    "/system/network/internode": _g_system_network,
    "/system/drive": _g_system_drive,
    "/system/memory": _g_system_memory,
    "/system/cpu": _g_system_cpu,
    "/system/process": _g_system_process,
    "/debug/python": _g_debug_python,
    "/cluster/health": _g_cluster_health,
    "/cluster/usage/objects": _g_cluster_usage,
    "/cluster/usage/buckets": _g_cluster_usage_buckets,
    "/cluster/erasure-set": _g_cluster_erasure_set,
    "/cluster/iam": _g_cluster_iam,
    "/cluster/config": _g_cluster_config,
    "/ilm": _g_ilm,
    "/scanner": _g_scanner,
    "/replication": _g_replication,
    "/notification": _g_notification,
    "/audit": _g_audit,
}
V3_BUCKET_GROUPS = {
    "/bucket/api": _g_bucket_api,
    "/bucket/replication": _g_bucket_replication,
}


def _worker_relabel(text: str, worker: int, keep_comments: bool) -> list[str]:
    """Stamp every series line with a ``worker="i"`` label. Peer lines
    drop their # HELP/TYPE comments (the serving worker's copy already
    carries them — duplicated TYPE lines are invalid exposition)."""
    out: list[str] = []
    for line in text.splitlines():
        if not line.strip():
            continue
        if line.startswith("#"):
            if keep_comments:
                out.append(line)
            continue
        i = line.rfind("} ")
        if i >= 0:
            out.append(f'{line[:i]},worker="{worker}"{line[i:]}')
        else:
            name, _, rest = line.partition(" ")
            out.append(f'{name}{{worker="{worker}"}} {rest}')
    return out


def render_v3_pool(server, path: str) -> str | None:
    """Pool-aware exposition: the serving worker's groups plus every
    sibling worker's, each series stamped ``worker="i"`` — a scrape of
    the shared SO_REUSEPORT port lands on ONE worker, and without the
    fan-out it would report that worker's QoS/cache/TPU view as if it
    were the node's. Counters aggregate with sum by (series) without the
    worker label; siblings render with ``local=on`` so the fan-out never
    recurses. A dead sibling is a 0 in ``minio_worker_up``, not a scrape
    failure."""
    own = render_v3(server, path)
    if own is None or not server.worker_peers:
        return own
    from concurrent.futures import ThreadPoolExecutor

    sub = "/" + path.strip("/") if path.strip("/") else ""
    base = getattr(server, "worker_port_base", 0)

    def one(peer: str) -> tuple[int, str | None]:
        host, _, p = peer.rpartition(":")
        idx = int(p) - base if base else -1
        try:
            from ..client import S3Client

            r = S3Client(
                peer, access_key=server.root_user,
                secret_key=server.root_pass,
            ).request(
                "GET", f"/minio/metrics/v3{sub}", query={"local": "on"},
                timeout=10,
            )
            if r.status != 200:
                return idx, None
            return idx, r.body.decode()
        except Exception:  # noqa: BLE001 — a dead worker is a 0 gauge
            return idx, None

    with ThreadPoolExecutor(max_workers=min(len(server.worker_peers), 16)) as pool:
        results = list(pool.map(one, server.worker_peers))
    lines = _worker_relabel(own, server.worker_index, keep_comments=True)
    up = [(server.worker_index, 1)]
    for idx, text in results:
        up.append((idx, 1 if text is not None else 0))
        if text is not None:
            lines.extend(_worker_relabel(text, idx, keep_comments=False))
    _fmt(lines, "minio_workers_total", "gauge",
         [({}, len(server.worker_peers) + 1)],
         "SO_REUSEPORT pool size on this node")
    _fmt(lines, "minio_worker_up", "gauge",
         [({"worker": str(i)}, v) for i, v in sorted(up)],
         "1 when the worker answered the pool metrics fan-out")
    return "\n".join(lines) + "\n"


def render_v3(server, path: str) -> str | None:
    """Render the v3 group(s) under `path` ('' = all non-bucket groups).
    Returns None for an unknown path (-> 404)."""
    path = "/" + path.strip("/") if path.strip("/") else ""
    for bpath, fn in V3_BUCKET_GROUPS.items():
        if path.startswith(bpath + "/"):
            bucket = path[len(bpath) + 1 :]
            return "\n".join(fn(server, bucket)) + "\n"
    out: list[str] = []
    matched = False
    for gpath, fn in V3_GROUPS.items():
        if not path or gpath == path or gpath.startswith(path + "/"):
            matched = True
            try:
                out.extend(fn(server))
            except Exception:  # noqa: BLE001 — one broken group must not
                pass  # take down the whole exposition
    if not matched:
        return None
    return "\n".join(out) + "\n"
