"""CORS enforcement — preflight and response headers.

The reference wraps its API router in a CORS middleware driven by the
`api.cors_allow_origin` config (wildcard origins, all methods, S3
headers exposed — /root/reference/cmd/api-router.go:651 corsHandler)
and additionally stores per-bucket CORS rule documents. Here both
layers are enforced: a bucket with a CORS configuration evaluates its
own rules (AllowedOrigin/AllowedMethod/AllowedHeader/ExposeHeader/
MaxAgeSeconds); buckets without one fall back to the global config.
"""

from __future__ import annotations

import fnmatch
import xml.etree.ElementTree as ET

S3_METHODS = ("GET", "PUT", "HEAD", "POST", "DELETE", "OPTIONS", "PATCH")
EXPOSED = (
    "Date, ETag, Server, Connection, Accept-Ranges, Content-Range, "
    "Content-Encoding, Content-Length, Content-Type, Content-Disposition, "
    "Last-Modified, Content-Language, Cache-Control, Retry-After, "
    "X-Amz-Bucket-Region, Expires, X-Amz-Request-Id, x-amz-version-id, "
    "x-amz-delete-marker"
)


def parse_bucket_cors(xml_text: str) -> list[dict]:
    """<CORSConfiguration><CORSRule>... -> rule dicts; raises ValueError
    on malformed documents (PutBucketCors must reject them)."""
    root = ET.fromstring(xml_text)
    if root.tag.rsplit("}", 1)[-1] != "CORSConfiguration":
        raise ValueError("root element must be CORSConfiguration")
    rules = []
    for rule in root:
        # exact localname on DIRECT children only: <MyCORSRule> or nested
        # strays must be rejected, not silently enforced
        if rule.tag.rsplit("}", 1)[-1] != "CORSRule":
            raise ValueError(f"unexpected element {rule.tag!r}")
        r = {
            "origins": [], "methods": [], "headers": [], "expose": [],
            "max_age": "",
        }
        for el in rule:
            tag = el.tag.rsplit("}", 1)[-1]
            text = (el.text or "").strip()
            if tag == "AllowedOrigin":
                r["origins"].append(text)
            elif tag == "AllowedMethod":
                if text.upper() not in S3_METHODS:
                    raise ValueError(f"unsupported CORS method {text!r}")
                r["methods"].append(text.upper())
            elif tag == "AllowedHeader":
                r["headers"].append(text)
            elif tag == "ExposeHeader":
                r["expose"].append(text)
            elif tag == "MaxAgeSeconds":
                r["max_age"] = text
        if not r["origins"] or not r["methods"]:
            raise ValueError("CORSRule needs AllowedOrigin and AllowedMethod")
        rules.append(r)
    if not rules:
        raise ValueError("no CORSRule in configuration")
    return rules


def _origin_matches(patterns: list[str], origin: str) -> bool:
    return any(fnmatch.fnmatchcase(origin, p) for p in patterns)


def match_rule(
    rules: list[dict], origin: str, method: str, req_headers: list[str]
) -> dict | None:
    """First bucket rule admitting (origin, method, requested headers)."""
    for r in rules:
        if not _origin_matches(r["origins"], origin):
            continue
        if method not in r["methods"]:
            continue
        allowed = [h.lower() for h in r["headers"]]
        if req_headers and not all(
            any(fnmatch.fnmatchcase(h.lower(), a) for a in allowed)
            for h in req_headers
        ):
            continue
        return r
    return None


def evaluate(
    origin: str,
    method: str,
    req_headers: list[str],
    bucket_rules: list[dict] | None,
    global_origins: list[str],
) -> dict[str, str] | None:
    """-> CORS response headers, or None when the request is not allowed.
    Bucket rules take precedence when configured; otherwise the global
    `api.cors_allow_origin` list governs with all-methods semantics."""
    if bucket_rules is not None:
        r = match_rule(bucket_rules, origin, method, req_headers)
        if r is None:
            return None
        out = {
            "Access-Control-Allow-Origin": origin,
            "Access-Control-Allow-Methods": ", ".join(r["methods"]),
            "Access-Control-Allow-Credentials": "true",
            "Access-Control-Expose-Headers": ", ".join(r["expose"]) or EXPOSED,
            "Vary": "Origin",
        }
        if r["headers"]:
            out["Access-Control-Allow-Headers"] = ", ".join(r["headers"])
        elif req_headers:
            out["Access-Control-Allow-Headers"] = ", ".join(req_headers)
        if r["max_age"]:
            out["Access-Control-Max-Age"] = r["max_age"]
        return out
    if not _origin_matches(global_origins, origin):
        return None
    return {
        "Access-Control-Allow-Origin": origin,
        "Access-Control-Allow-Methods": ", ".join(S3_METHODS),
        "Access-Control-Allow-Headers": ", ".join(req_headers) or "*",
        "Access-Control-Allow-Credentials": "true",
        "Access-Control-Expose-Headers": EXPOSED,
        "Vary": "Origin",
    }
