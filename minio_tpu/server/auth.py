"""Request authentication + authorization: SigV4/SigV2 dispatch,
session tokens, streaming-payload auth, IAM policy checks.

Split from app.py (the reference's cmd/auth-handler.go)."""

from __future__ import annotations

import asyncio
import hashlib
import os
import urllib.parse

from aiohttp import web

from . import s3err, signature, streaming
from .handler_utils import (
    _ConsumerDone,
    _AwsChunkedDecoder,
)


class RequestAuthMixin:
    async def _authenticate(
        self, request: web.Request, stream_body: bool = False
    ) -> tuple[str, bytes | None]:
        """Verify request auth; returns (access_key, payload bytes).

        stream_body=True leaves the body unread (returned as None) for the
        streaming PUT path — only valid for auth modes that don't hash the
        payload (presigned / UNSIGNED-PAYLOAD), which _streamable_put
        guarantees."""
        headers = {k.lower(): v for k, v in request.headers.items()}
        raw_path = request.rel_url.raw_path
        query = urllib.parse.parse_qsl(
            request.rel_url.raw_query_string, keep_blank_values=True
        )
        if stream_body:
            body = None
        else:
            body = await request.read() if request.body_exists else b""

        qdict = dict(query)
        if "X-Amz-Signature" in qdict:
            ak = self.verifier.verify_presigned(request.method, raw_path, query, headers)
            self._check_session_token(ak, headers, qdict)
            return ak, body
        if (
            "Signature" in qdict
            and "AWSAccessKeyId" in qdict
            and "Expires" in qdict
        ):
            # legacy presigned V2 (reference cmd/signature-v2.go)
            from .signature import SigV2Verifier

            ak = SigV2Verifier(self.iam.lookup_secret).verify_presigned(
                request.method, raw_path, request.rel_url.raw_query_string,
                headers,
            )
            self._check_session_token(ak, headers, qdict)
            return ak, body
        if "authorization" not in headers:
            # anonymous: only bucket policies can authorize it downstream
            return "", body
        if headers["authorization"].startswith("AWS "):
            # legacy header V2: HMAC-SHA1 over the V2 string-to-sign
            from .signature import SigV2Verifier

            ak = SigV2Verifier(self.iam.lookup_secret).verify_header(
                request.method, raw_path, request.rel_url.raw_query_string, headers
            )
            self._check_session_token(ak, headers, {})
            return ak, body

        content_sha = headers.get("x-amz-content-sha256", signature.UNSIGNED_PAYLOAD)
        ak = self.verifier.verify_header_auth(
            request.method, raw_path, query, headers, content_sha
        )
        if content_sha == signature.STREAMING_UNSIGNED_TRAILER:
            if body is not None:  # streamed bodies decode inline in the pump
                body = self._decode_trailer_body(request, body)
        elif content_sha in (
            signature.STREAMING_PAYLOAD,
            signature.STREAMING_PAYLOAD_TRAILER,
        ):
            auth = signature.parse_auth_header(headers["authorization"])
            body = streaming.decode_signed_chunked(
                body,
                auth.signature,
                headers.get("x-amz-date", ""),
                auth.scope,
                self.iam.lookup_secret(ak) or "",
                trailer_mode=content_sha == signature.STREAMING_PAYLOAD_TRAILER,
            )
        elif content_sha not in (signature.UNSIGNED_PAYLOAD,):
            if hashlib.sha256(body).hexdigest() != content_sha:
                raise s3err.XAmzContentSHA256Mismatch
        self._check_session_token(ak, headers, {})
        return ak, body

    @staticmethod
    def _declared_trailer_algo(request) -> str:
        """The x-amz-trailer checksum algorithm, '' if none declared.

        Shared by the buffered and streaming decode paths so the contract
        can't diverge: a declared trailer we can't verify must not be
        accepted silently (integrity was requested) -> InvalidArgument.
        """
        from ..utils import checksum as cks

        t = request.headers.get("x-amz-trailer", "").strip().lower()
        if not t:
            return ""
        if t.startswith(cks.HEADER) and t[len(cks.HEADER):] in cks.ALGOS:
            return t[len(cks.HEADER):]
        raise s3err.InvalidArgument

    def _decode_trailer_body(self, request, body: bytes) -> bytes:
        """Decode a buffered aws-chunked STREAMING-UNSIGNED-PAYLOAD-TRAILER
        body; verify the declared x-amz-checksum trailer against the
        decoded payload and record it for storage — the same integrity
        contract as the streamed path (undeclared extra trailers are
        ignored there too)."""
        from ..utils import checksum as cks

        algo = self._declared_trailer_algo(request)
        dec = _AwsChunkedDecoder()
        data = dec.feed(body)
        expect = request.headers.get("x-amz-decoded-content-length")
        try:
            if expect is not None and len(data) != int(expect):
                raise s3err.IncompleteBody
        except ValueError:
            raise s3err.InvalidArgument from None
        if algo:
            want = dec.trailers.get(f"{cks.HEADER}{algo}")
            if want is None or cks.compute(algo, data) != want:
                raise s3err.InvalidDigest
            request["trailer_checksum_meta"] = {
                f"{cks.META_PREFIX}{algo}": want
            }
        return data

    def _streamable_put(self, request: web.Request) -> bool:
        """True for object PUTs whose body can flow straight into the
        erasure plane without buffering: auth never hashes the payload
        (presigned or UNSIGNED-PAYLOAD), no Content-MD5/checksum headers
        to verify over the whole body, no copy source, and the body is big
        enough for streaming to matter. Transform applicability (SSE,
        compression) is re-checked in the handler, which falls back to the
        buffered path since the body is still unread."""
        if request.method != "PUT":
            return False
        bucket = request.match_info.get("bucket", "")
        key = request.match_info.get("key", "")
        if not bucket or not key or bucket == "minio" or bucket.startswith(".minio.sys"):
            return False
        q = request.rel_url.query
        for sub in ("retention", "legal-hold", "tagging", "acl"):
            if sub in q:
                return False
        headers = {k.lower() for k in request.headers}
        if "x-amz-copy-source" in headers or "content-md5" in headers:
            return False
        sha = request.headers.get("x-amz-content-sha256", signature.UNSIGNED_PAYLOAD)
        trailer_mode = sha == signature.STREAMING_UNSIGNED_TRAILER
        if any(
            h.startswith((
                # full-body checksum headers need the buffered verify path;
                # TRAILER checksums stream (decoded + verified on the fly)
                "x-amz-checksum-",
                # request-level SSE needs the transform pipeline (whole body)
                "x-amz-server-side-encryption",
            ))
            for h in headers
        ):
            return False
        if ("x-amz-trailer" in headers or "x-amz-sdk-checksum-algorithm" in headers) \
                and not trailer_mode:
            return False
        presigned = "X-Amz-Signature" in q
        if not presigned and sha != signature.UNSIGNED_PAYLOAD and not trailer_mode:
            return False
        try:
            cl = int(
                request.headers.get("x-amz-decoded-content-length")
                or request.headers.get("Content-Length", "0")
            )
        except ValueError:
            return False
        return cl >= int(os.environ.get("MINIO_TPU_STREAM_MIN_BYTES", str(8 << 20)))

    async def _run_streaming_put(self, request: web.Request, consume):
        """Run consume(chunk_iterator) in the io pool while pumping the
        request body into it through a bounded queue (~8 MiB of chunks):
        the async HTTP read and the sync erasure encode/write overlap, and
        a part is never fully resident. A short body (client hung up) or
        pump failure raises into the consumer so the put aborts cleanly.
        """
        import queue as _queue

        chunk_sz = int(os.environ.get("MINIO_TPU_PUT_CHUNK_MB", "4")) << 20
        q: _queue.Queue = _queue.Queue(maxsize=max(2, (8 << 20) // chunk_sz))

        def gen():
            while True:
                item = q.get()
                if item is None:
                    return
                if isinstance(item, Exception):
                    raise item
                yield item

        self.streaming_puts += 1
        task = asyncio.ensure_future(self._run(consume, gen()))
        loop = asyncio.get_running_loop()

        def put_item(item):
            while True:
                if task.done():
                    raise _ConsumerDone
                try:
                    q.put(item, timeout=0.25)
                    return
                except _queue.Full:
                    continue

        def inject_error(e: Exception):
            """Guaranteed delivery: drain the queue until the sentinel fits
            so the consumer can never block forever on q.get() (which would
            wedge the namespace write lock and leak the io-pool thread)."""
            while True:
                try:
                    q.put_nowait(e)
                    return
                except _queue.Full:
                    try:
                        q.get_nowait()
                    except _queue.Empty:
                        pass

        # aws-chunked bodies with trailing checksums decode + verify inline
        # (reference cmd/streaming-v4-unsigned.go + internal/hash trailers)
        decoder = None
        hasher = None
        trailer_algo = ""
        if request.headers.get("x-amz-content-sha256") == \
                signature.STREAMING_UNSIGNED_TRAILER:
            from ..utils import checksum as cks

            decoder = _AwsChunkedDecoder()
            trailer_algo = self._declared_trailer_algo(request)
            if trailer_algo:
                hasher = cks.Hasher(trailer_algo)

        expect = int(
            request.headers.get("x-amz-decoded-content-length")
            or request.headers.get("Content-Length", "0")
        )
        got = 0
        try:
            while True:
                chunk = await request.content.read(chunk_sz)
                if not chunk:
                    err: Exception | None = None
                    if got != expect:
                        err = s3err.IncompleteBody
                    elif decoder is not None and hasher is not None:
                        from ..utils import checksum as cks

                        want = decoder.trailers.get(f"{cks.HEADER}{trailer_algo}")
                        if want is None or want != hasher.b64():
                            err = s3err.InvalidDigest
                        else:
                            request["trailer_checksum_meta"] = {
                                f"{cks.META_PREFIX}{trailer_algo}": want
                            }
                    await loop.run_in_executor(self._pump_pool, put_item, err)
                    break
                if decoder is not None:
                    chunk = decoder.feed(chunk)
                    if hasher is not None and chunk:
                        hasher.update(chunk)
                    if not chunk:
                        continue
                got += len(chunk)
                try:
                    # fast path: skip the executor hop when there's room
                    q.put_nowait(chunk)
                except _queue.Full:
                    await loop.run_in_executor(self._pump_pool, put_item, chunk)
        except _ConsumerDone:
            pass  # consumer already finished/failed; its result surfaces below
        except BaseException as e:
            inject_error(e if isinstance(e, Exception) else RuntimeError(str(e)))
            raise
        return await task

    def _check_session_token(self, access_key: str, headers, query) -> None:
        """Temp (STS) credentials must present a valid session token whose
        claims match the signing key (reference: checkClaimsFromToken)."""
        u = self.iam.users.get(access_key)
        if u is None or not u.is_temp:
            return
        token = headers.get("x-amz-security-token", "") or query.get(
            "X-Amz-Security-Token", ""
        )
        claims = self.iam.verify_token(token) if token else None
        if not claims or claims.get("accessKey") != access_key:
            raise s3err.AccessDenied

    # -- dispatch ------------------------------------------------------------

    def _authorize(
        self, access_key: str, action: str, bucket: str, key: str = "",
        conditions: dict[str, str] | None = None,
    ) -> None:
        if not action:
            return  # handler performs its own per-key authorization
        resource = f"{bucket}/{key}" if key else bucket
        bucket_policy = None
        if bucket:
            raw = self.buckets.get(bucket).policy
            if raw:
                from ..iam.policy import Policy

                bucket_policy = Policy.from_dict(raw)
        if not self.iam.is_allowed(
            access_key, action, resource, conditions, bucket_policy
        ):
            raise s3err.AccessDenied
