"""The S3 API server: routing + handlers over the object layer.

Path-style S3 API (the reference's registerAPIRouter,
/root/reference/cmd/api-router.go:255) on aiohttp. Handlers validate auth
(SigV4 header/presigned, streaming payloads), then call the erasure object
layer in worker threads; responses are S3-wire XML/headers.
"""

from __future__ import annotations

import asyncio
import hashlib
import os
import re
import urllib.parse
import xml.etree.ElementTree as ET
from datetime import datetime, timezone
from email.utils import format_datetime, parsedate_to_datetime
from xml.sax.saxutils import escape

from aiohttp import web

from ..erasure import listing, quorum
from ..erasure.set import ErasureSet
from ..erasure.types import ObjectInfo
from ..storage.xlstorage import XLStorage
from . import s3err, signature, streaming
from .buckets import BucketMetadataSys

BUCKET_NAME_RE = re.compile(r"^[a-z0-9][a-z0-9.\-]{1,61}[a-z0-9]$")

# bucket subresource -> (GET action, PUT action)
_SUBRESOURCE_ACTIONS = {
    "policy": ("s3:GetBucketPolicy", "s3:PutBucketPolicy"),
    "lifecycle": ("s3:GetLifecycleConfiguration", "s3:PutLifecycleConfiguration"),
    "tagging": ("s3:GetBucketTagging", "s3:PutBucketTagging"),
    "notification": ("s3:GetBucketNotification", "s3:PutBucketNotification"),
    "encryption": ("s3:GetEncryptionConfiguration", "s3:PutEncryptionConfiguration"),
    "object-lock": (
        "s3:GetBucketObjectLockConfiguration",
        "s3:PutBucketObjectLockConfiguration",
    ),
    "cors": ("s3:GetBucketCORS", "s3:PutBucketCORS"),
    "replication": ("s3:GetReplicationConfiguration", "s3:PutReplicationConfiguration"),
    "versioning": ("s3:GetBucketVersioning", "s3:PutBucketVersioning"),
    "acl": ("s3:GetBucketAcl", "s3:PutBucketAcl"),
    "policyStatus": ("s3:GetBucketPolicyStatus", "s3:PutBucketPolicy"),
    "requestPayment": ("s3:GetBucketRequestPayment", "s3:PutBucketRequestPayment"),
    "logging": ("s3:GetBucketLogging", "s3:PutBucketLogging"),
    "ownershipControls": (
        "s3:GetBucketOwnershipControls", "s3:PutBucketOwnershipControls",
    ),
}


class _ConsumerDone(Exception):
    """Streaming-put pump: the erasure consumer finished before EOF."""


def _restored_locally(oi) -> bool:
    """A transitioned object whose restore window is still open has its
    data back on local drives and serves the normal path."""
    import time as _time

    from ..ilm import tier as tiermod

    exp = oi.user_defined.get(tiermod.RESTORE_EXPIRY_META)
    try:
        return bool(exp) and float(exp) > _time.time()
    except (TypeError, ValueError):
        return False


def _route_action(m: str, bucket: str, key: str, q, headers) -> tuple[str, str, str]:
    """(action, bucket, key) for authorization — the request->policy-action
    mapping the reference does per-handler via checkRequestAuthType."""
    if key:
        if "retention" in q:
            return (
                "s3:GetObjectRetention" if m in ("GET", "HEAD")
                else "s3:PutObjectRetention"
            ), bucket, key
        if "legal-hold" in q:
            return (
                "s3:GetObjectLegalHold" if m in ("GET", "HEAD")
                else "s3:PutObjectLegalHold"
            ), bucket, key
        if "tagging" in q:
            return {
                "GET": "s3:GetObjectTagging",
                "PUT": "s3:PutObjectTagging",
                "DELETE": "s3:DeleteObjectTagging",
            }.get(m, "s3:*"), bucket, key
        if "acl" in q:
            return (
                "s3:GetObjectAcl" if m in ("GET", "HEAD") else "s3:PutObjectAcl"
            ), bucket, key
        if m in ("GET", "HEAD"):
            if "uploadId" in q:
                return "s3:ListMultipartUploadParts", bucket, key
            if "attributes" in q:
                return "s3:GetObjectAttributes", bucket, key
            if "versionId" in q:
                return "s3:GetObjectVersion", bucket, key
            return "s3:GetObject", bucket, key
        if m == "PUT":
            return "s3:PutObject", bucket, key
        if m == "DELETE":
            if "uploadId" in q:
                return "s3:AbortMultipartUpload", bucket, key
            if "versionId" in q:
                return "s3:DeleteObjectVersion", bucket, key
            return "s3:DeleteObject", bucket, key
        if m == "POST":
            if "select" in q:
                return "s3:GetObject", bucket, key  # Select is a READ
            if "restore" in q:
                return "s3:RestoreObject", bucket, key
            return "s3:PutObject", bucket, key
        return "s3:*", bucket, key
    # bucket level
    for sub, (get_a, put_a) in _SUBRESOURCE_ACTIONS.items():
        if sub in q:
            if m in ("GET", "HEAD"):
                return get_a, bucket, ""
            return put_a, bucket, ""
    if m == "PUT":
        return "s3:CreateBucket", bucket, ""
    if m == "DELETE":
        return "s3:DeleteBucket", bucket, ""
    if m == "POST":
        return "", bucket, ""  # multi-delete authorizes PER KEY in its handler
    if "versions" in q:
        return "s3:ListBucketVersions", bucket, ""
    if "location" in q:
        return "s3:GetBucketLocation", bucket, ""
    if "uploads" in q:
        return "s3:ListBucketMultipartUploads", bucket, ""
    return "s3:ListBucket", bucket, ""


def _route_conditions(q) -> dict[str, str]:
    return {"s3:prefix": q.get("prefix", ""), "s3:delimiter": q.get("delimiter", "")}


def _parse_form_data(body: bytes, boundary: bytes) -> tuple[dict[str, str], bytes]:
    """Minimal multipart/form-data parser for POST-policy uploads.

    Returns (fields, file_bytes); the file part's filename lands in
    fields['__filename'].
    """
    fields: dict[str, str] = {}
    file_data = b""
    delim = b"--" + boundary
    chunks = body.split(delim)
    for part in chunks[1:]:  # [0] is the preamble
        if part.startswith(b"--"):
            break  # closing boundary
        # strip EXACTLY the framing CRLFs — file payloads may legitimately
        # begin/end with newline bytes that must survive
        if part.startswith(b"\r\n"):
            part = part[2:]
        if part.endswith(b"\r\n"):
            part = part[:-2]
        head, _, content = part.partition(b"\r\n\r\n")
        disp = ""
        for line in head.split(b"\r\n"):
            if line.lower().startswith(b"content-disposition"):
                disp = line.decode("utf-8", "replace")
        name = ""
        filename = None
        for tok in disp.split(";"):
            tok = tok.strip()
            if tok.startswith("name="):
                name = tok[5:].strip('"')
            elif tok.startswith("filename="):
                filename = tok[9:].strip('"')
        if not name:
            continue
        if name == "file":
            file_data = content
            if filename:
                fields["__filename"] = filename.rsplit("/", 1)[-1]
        else:
            fields[name] = content.decode("utf-8", "replace")
    return fields, file_data


def _verify_checksum_headers(headers, body: bytes) -> dict[str, str]:
    """AWS flexible-checksums: verify x-amz-checksum-* when present and
    return internal metadata recording them (reference internal/hash/
    checksum.go readers). All five algorithms (CRC32, CRC32C, SHA1,
    SHA256, CRC64NVME) are verified, none stored blind."""
    from ..utils import checksum as cks

    out: dict[str, str] = {}
    for algo in cks.ALGOS:
        v = headers.get(f"{cks.HEADER}{algo}")
        if not v:
            continue
        if cks.compute(algo, body) != v:
            raise s3err.InvalidDigest
        out[f"{cks.META_PREFIX}{algo}"] = v
    return out


class _AwsChunkedDecoder:
    """Incremental aws-chunked decoder for STREAMING-UNSIGNED-PAYLOAD-TRAILER
    bodies (reference cmd/streaming-v4-unsigned.go): yields payload bytes,
    captures the trailing checksum headers."""

    def __init__(self):
        self._buf = bytearray()
        self._state = "size"  # size | data | crlf | trailer
        self._remaining = 0
        self.trailers: dict[str, str] = {}

    def feed(self, chunk: bytes) -> bytes:
        self._buf += chunk
        out = bytearray()
        while True:
            if self._state == "size":
                nl = self._buf.find(b"\r\n")
                if nl < 0:
                    break
                line = bytes(self._buf[:nl])
                del self._buf[: nl + 2]
                size_hex = line.split(b";", 1)[0].strip()
                try:
                    self._remaining = int(size_hex, 16)
                except ValueError:
                    raise s3err.IncompleteBody from None
                self._state = "data" if self._remaining else "trailer"
            elif self._state == "data":
                take = min(self._remaining, len(self._buf))
                if take:
                    out += self._buf[:take]
                    del self._buf[:take]
                    self._remaining -= take
                if self._remaining:
                    break
                self._state = "crlf"
            elif self._state == "crlf":
                if len(self._buf) < 2:
                    break
                del self._buf[:2]
                self._state = "size"
            else:  # trailer: lines until blank
                nl = self._buf.find(b"\r\n")
                if nl < 0:
                    break
                line = bytes(self._buf[:nl])
                del self._buf[: nl + 2]
                if not line:
                    continue  # final blank line
                if b":" in line:
                    k, v = line.split(b":", 1)
                    self.trailers[k.decode().strip().lower()] = v.decode().strip()
        return bytes(out)


def _bucket_sse_algo(encryption_xml: str | None) -> str | None:
    """SSEAlgorithm from a bucket's default-encryption config XML."""
    if not encryption_xml:
        return None
    try:
        root = ET.fromstring(encryption_xml)
        for el in root.iter():
            if el.tag.endswith("SSEAlgorithm"):
                return el.text or None
    except ET.ParseError:
        return None
    return None


def _iso8601(ns: int) -> str:
    return datetime.fromtimestamp(ns / 1e9, tz=timezone.utc).strftime(
        "%Y-%m-%dT%H:%M:%S.%f"
    )[:-3] + "Z"


def _http_date(ns: int) -> str:
    return format_datetime(
        datetime.fromtimestamp(ns / 1e9, tz=timezone.utc), usegmt=True
    )


class S3Server:
    def __init__(self, store=None, region: str = "us-east-1"):
        import time as _time

        from ..crypto.sse import KMS
        from .metrics import Metrics, TracePubSub

        from concurrent.futures import ThreadPoolExecutor as _TPE

        self.kms = KMS()
        self.store = None
        self.streaming_puts = 0  # observability: bodies that never buffered
        # dedicated pool for streaming-body pumps: put_item can block on a
        # full queue, and parking it in the default executor would starve
        # the storage-REST plane that shares it
        self._pump_pool = _TPE(
            max_workers=8, thread_name_prefix="body-pump"
        )
        # store I/O runs on an ample dedicated pool: the default executor
        # on small machines has ~cpus+4 workers, and writers blocking on
        # namespace locks inside it can starve the reader that HOLDS the
        # lock out of a thread to finish its stream (deadlock-by-pool)
        io_threads = int(os.environ.get("MINIO_TPU_IO_THREADS", "64"))
        self._io_pool = _TPE(max_workers=io_threads, thread_name_prefix="s3io")
        # long-poll waits (trace/listen subscribers) get their own pool so
        # they can never starve the I/O pool
        self._longpoll_pool = _TPE(max_workers=64, thread_name_prefix="longpoll")
        self.region = region
        self.started_at = _time.time()
        self.metrics = Metrics()
        self.trace = TracePubSub()
        self.background = None
        self.root_user = os.environ.get("MINIO_ROOT_USER", "minioadmin")
        self.root_pass = os.environ.get("MINIO_ROOT_PASSWORD", "minioadmin")
        self.app = web.Application(client_max_size=1 << 30)
        # CORS decoration rides the prepare signal: it must run before
        # headers hit the wire, which for streamed GETs happens INSIDE the
        # handler — a post-dispatch wrapper would be too late
        self.app.on_response_prepare.append(self._ttfb_on_prepare)
        self.app.on_response_prepare.append(self._cors_on_prepare)
        self.app.router.add_route("*", "/", self._entry)
        self.app.router.add_route("*", "/{bucket}", self._entry)
        self.app.router.add_route("*", "/{bucket}/{key:.*}", self._entry)
        if store is not None:
            self.set_store(store)

    def set_store(self, store) -> None:
        """Attach the object layer once bootstrap completes; until then S3
        requests answer 503 (the reference gates on newObjectLayer the
        same way)."""
        from ..erasure.multipart import MultipartRouter
        from ..iam.sys import IAMSys

        self.buckets = BucketMetadataSys(store)
        self.mp = MultipartRouter(store, part_transform=self._mp_part_transform)
        # IAM documents move to etcd when configured, so independent
        # deployments share one identity plane (reference
        # cmd/iam-etcd-store.go; same env variable)
        etcd_eps = os.environ.get("MINIO_ETCD_ENDPOINTS", "")
        if etcd_eps:
            from ..iam.etcd import EtcdIAMStore, EtcdKV

            iam_store = EtcdIAMStore(EtcdKV(etcd_eps))
        else:
            iam_store = store
        self.iam = IAMSys(iam_store, self.root_user, self.root_pass)
        # a real load error must abort boot: running with silently-empty IAM
        # would wipe stored identities on the next persist (first boot is
        # fine — missing documents load as empty)
        self.iam.load()
        self.verifier = signature.SigV4Verifier(self.iam.lookup_secret, self.region)
        from ..batch.jobs import BatchJobPool
        from ..crypto.sse import KMS
        from ..erasure.decommission import PoolManager
        from ..events.notify import EventNotifier
        from ..replication.replicate import ReplicationPool, TargetRegistry
        from .audit import AuditLog
        from .config_kv import ConfigKV

        self.notifier = EventNotifier(self.buckets)
        self.audit = AuditLog()
        self.config = ConfigKV(store)
        from ..crypto.kes import from_env_or_config

        # KES external KMS when configured; builtin persisted key otherwise
        self.kms = from_env_or_config(cfg=self.config, store=store)
        self.repl_targets = TargetRegistry(store)
        from ..ilm.tier import TierRegistry

        self.tiers = TierRegistry(store)

        def _repl_decode(oi, data, bucket, key):
            from ..crypto import sse as ssemod
            from . import transforms

            if not transforms.is_transformed(oi.user_defined):
                return data
            if oi.user_defined.get(ssemod.META_ALGO) == "SSE-C":
                # the server has no customer key; cannot replicate SSE-C
                raise RuntimeError("SSE-C objects cannot be auto-replicated")
            return transforms.decode_full(
                data, oi.user_defined, {}, bucket, key, self.kms
            )

        self.replication = ReplicationPool(
            store, self.buckets, self.repl_targets, decode=_repl_decode
        )
        from ..replication.site import SiteReplicationSys

        self.site = SiteReplicationSys(self)
        self.buckets.on_change = (
            lambda bucket, bm: self.site.sync_bucket_meta(bucket, bm)
        )
        self.iam.on_mutation = self.site.sync_iam
        self.batch = BatchJobPool(store, self.buckets, self.replication, kms=self.kms)
        self.pool_mgr = (
            PoolManager(store) if hasattr(store, "pools") else None
        )
        self.store = store
        self.site.load()  # resume a persisted site group across restarts
        # background durability plane: scanner + MRF heal workers
        from ..erasure.background import BackgroundOps

        interval = float(os.environ.get("MINIO_TPU_SCAN_INTERVAL", "300"))
        self.background = BackgroundOps(
            store, scan_interval=interval, bucket_meta=self.buckets,
            tiers=self.tiers,
        )
        for p in getattr(store, "pools", [store]):
            for s in getattr(p, "sets", [p]):
                s.on_degraded = self.background.mrf.add
        if interval > 0:
            self.background.start()

    # -- plumbing ------------------------------------------------------------

    def _mp_part_transform(self, bucket, obj, up_meta, part_number, data):
        """SSE hook for multipart parts: encrypt each part as its own
        packet stream under the upload's OEK. None = no transform.
        Returns (stored, plain_size | size_getter): streamed parts encrypt
        packet-by-packet and report their plaintext size after the fact."""
        from ..crypto import sse as ssemod
        from . import transforms

        if ssemod.META_ALGO not in up_meta:
            return None
        if isinstance(data, (bytes, bytearray)):
            enc = transforms.encrypt_part(
                bytes(data), up_meta, part_number, self.kms, bucket, obj
            )
            return enc, len(data)
        count = [0]
        gen = transforms.encrypt_part_iter(
            data, up_meta, part_number, self.kms, bucket, obj, count
        )
        return gen, (lambda: count[0])

    def _queue_repl(self, request, bucket, key, version_id, op) -> None:
        """Queue a bucket-replication task unless this write IS a replica
        (the marker header breaks active-active site-replication loops).
        Only cluster owners (site peers authenticate with admin creds) may
        set the marker — an ordinary writer must not be able to opt its
        writes out of replication."""
        from ..replication.replicate import REPLICA_MARKER

        if (
            request.headers.get(REPLICA_MARKER) == "true"
            and self.iam.is_owner(request.get("access_key", ""))
        ):
            return
        self.replication.queue_mutation(bucket, key, version_id, op)

    async def _run(self, fn, *args, **kw):
        return await asyncio.get_running_loop().run_in_executor(
            self._io_pool, lambda: fn(*args, **kw)
        )

    def _prometheus_bearer_ok(self, request) -> bool:
        """Validate a madmin-style prometheus JWT: HS512 signed with the
        subject's secret key, standard base64url framing."""
        import hmac as _hmac
        import json as _json
        import time as _time

        from ..iam.oidc import _b64url as _unb64  # shared padded decoder

        auth = request.headers.get("Authorization", "")
        if not auth.startswith("Bearer "):
            return False

        try:
            h, c, s = auth[7:].split(".")
            claims = _json.loads(_unb64(c))
            ak = claims.get("sub", "")
            secret = self.iam.lookup_secret(ak)
            if not secret:
                return False
            want = _hmac.new(
                secret.encode(), f"{h}.{c}".encode(), hashlib.sha512
            ).digest()
            if not _hmac.compare_digest(_unb64(s), want):
                return False
            exp = claims.get("exp")
            if exp is not None and _time.time() > float(exp):
                return False
        except Exception:  # noqa: BLE001 — any malformed token is a no
            return False
        return self.iam.is_allowed(ak, "admin:Prometheus", "")

    def _err_response(self, request, err: s3err.APIError) -> web.Response:
        headers = {}
        size = request.get("_range_object_size")
        if err.http_status == 416 and size is not None:
            # RFC 7233: unsatisfiable ranges advertise the actual length
            # (the reference sets this on InvalidRange responses too)
            headers["Content-Range"] = f"bytes */{size}"
        return web.Response(
            status=err.http_status,
            body=err.to_xml(resource=request.path),
            content_type="application/xml",
            headers=headers,
        )

    def _apply_vhost_style(self, request: web.Request) -> None:
        """Virtual-host-style addressing (reference MINIO_DOMAIN,
        cmd/generic-handlers.go setBucketForwardingMiddleware): for
        `bucket.domain` hosts the bucket rides the Host header and the
        whole path is the key. SigV4 verification keeps the original
        path — that is what vhost clients sign."""
        domains = os.environ.get("MINIO_DOMAIN", "")
        if not domains:
            return
        host = request.headers.get("Host", "").rsplit(":", 1)[0].lower()
        # longest suffix first: with domains example.test + s3.example.test
        # configured, host b.s3.example.test must parse bucket "b", not
        # the dotted label "b.s3"
        ordered = sorted(
            (d.strip().lower() for d in domains.split(",") if d.strip()),
            key=len, reverse=True,
        )
        for dom in ordered:
            if not host.endswith("." + dom):
                continue
            vb = host[: -len(dom) - 1]
            if not BUCKET_NAME_RE.match(vb):
                return  # not a bucket label (e.g. console.domain)
            # the key is the WHOLE request path (not re-joined match_info
            # segments: that would drop a trailing slash, losing folder
            # markers like "photos/")
            request.match_info["key"] = request.path.lstrip("/")
            request.match_info["bucket"] = vb
            return

    async def _entry(self, request: web.Request) -> web.StreamResponse:
        import time as _time

        from .metrics import classify_api, trace_record

        self._apply_vhost_style(request)
        t0 = _time.perf_counter()
        request["_t0"] = t0  # TTFB measured at response prepare time
        resp: web.StreamResponse | None = None
        self.metrics.inflight += 1  # single-threaded event loop: no race
        try:
            origin = request.headers.get("Origin", "")
            if origin and request.method == "OPTIONS" and request.headers.get(
                "Access-Control-Request-Method"
            ):
                resp = await self._cors_preflight(request, origin)
                return resp
            resp = await self._entry_inner(request)
            return resp
        finally:
            self.metrics.inflight -= 1
            dur = _time.perf_counter() - t0
            status = resp.status if resp is not None else 500
            api = classify_api(
                request.method,
                request.match_info.get("bucket", ""),
                request.match_info.get("key", ""),
                request.rel_url.query,
            )
            rx = int(request.headers.get("Content-Length") or 0)
            tx = getattr(resp, "content_length", None) or 0 if resp else 0
            self.metrics.observe(
                api, status, dur, rx, tx,
                bucket=request.match_info.get("bucket", ""),
                ttfb=request.get("_ttfb"),
            )
            if self.trace.active:
                self.trace.publish(trace_record(request, status, dur, rx, tx))
            audit = getattr(self, "audit", None)
            if audit is not None and audit.enabled:
                from .audit import audit_record

                audit.emit(
                    audit_record(request, status, dur, request.get("access_key", ""))
                )

    @staticmethod
    def _is_user_bucket(bucket: str) -> bool:
        return bool(bucket) and bucket != "minio" and not bucket.startswith(".minio.sys")

    def _cors_rules_for(self, raw: str):
        """Parsed bucket CORS rules, memoized by the raw document — the
        response path must not pay an XML parse per request."""
        from . import cors as corsmod

        cache = getattr(self, "_cors_rule_cache", None)
        if cache is None:
            cache = self._cors_rule_cache = {}
        rules = cache.get(raw)
        if rules is None:
            if len(cache) > 256:
                cache.clear()
            try:
                rules = cache[raw] = corsmod.parse_bucket_cors(raw)
            except ValueError:
                rules = cache[raw] = []
        return rules or None

    def _cors_headers(
        self, bucket: str, origin: str, method: str, req_headers: list[str],
        allow_load: bool = False,
    ) -> dict[str, str] | None:
        """Evaluate bucket CORS rules (when configured) or the global
        api.cors_allow_origin config (reference cmd/api-router.go:651).
        allow_load=False restricts to the metadata CACHE (event-loop
        callers); allow_load=True (executor callers) falls through to a
        bucket_exists-gated metadata load, so attacker-chosen names never
        reach get() (which would cache a default entry per name)."""
        rules = None
        if self._is_user_bucket(bucket):
            bm = self.buckets.peek(bucket)
            if bm is None and allow_load and self.store is not None:
                try:
                    if self.store.bucket_exists(bucket):
                        bm = self.buckets.get(bucket)
                except Exception:  # noqa: BLE001 — degraded metadata reads
                    bm = None     # fall back to global rules
            raw = bm.cors if bm is not None else None
            if raw:
                rules = self._cors_rules_for(raw)
        from . import cors as corsmod

        global_origins = [
            o.strip()
            for o in (self.config.get("api", "cors_allow_origin") or "*").split(",")
            if o.strip()
        ] if self.config is not None else ["*"]
        return corsmod.evaluate(origin, method, req_headers, rules, global_origins)

    async def _ttfb_on_prepare(self, request: web.Request, response) -> None:
        """Metrics TTFB capture: first byte leaves at response-prepare time
        for both buffered and streamed bodies."""
        import time as _time

        t0 = request.get("_t0")
        if t0 is not None and "_ttfb" not in request:
            request["_ttfb"] = _time.perf_counter() - t0

    async def _cors_on_prepare(self, request: web.Request, response) -> None:
        origin = request.headers.get("Origin", "")
        if not origin or request.method == "OPTIONS":
            return
        bucket = request.match_info.get("bucket", "") if request.match_info else ""
        if self._is_user_bucket(bucket) and self.buckets.peek(bucket) is None:
            # uncached bucket (e.g. first GET after restart): its CORS
            # rules are authoritative, so load them off-loop rather than
            # silently falling back to the permissive global default
            hdrs = await self._run(
                self._cors_headers, bucket, origin, request.method, [], True
            )
        else:
            hdrs = self._cors_headers(bucket, origin, request.method, [])
        if hdrs:
            for k, v in hdrs.items():
                response.headers.setdefault(k, v)

    async def _cors_preflight(self, request: web.Request, origin: str) -> web.Response:
        """OPTIONS preflight: unauthenticated by design (browsers send no
        credentials); only reveals whether an origin/method is allowed."""
        method = request.headers.get("Access-Control-Request-Method", "")
        req_headers = [
            h.strip()
            for h in request.headers.get("Access-Control-Request-Headers", "").split(",")
            if h.strip()
        ]
        hdrs = await self._run(
            self._cors_headers, request.match_info.get("bucket", ""), origin,
            method, req_headers, True,
        )
        if hdrs is None:
            return web.Response(status=403, body=b"CORSResponse: origin not allowed")
        return web.Response(status=200, headers=hdrs)

    async def _entry_inner(self, request: web.Request) -> web.StreamResponse:
        # unauthenticated planes: health + metrics
        bucket = request.match_info.get("bucket", "")
        key = request.match_info.get("key", "")
        if bucket == "minio":
            if request.method == "GET" and key == "console/api/users":
                # console backend API (the reference console ships its own
                # REST layer too): same authz as madmin ListUsers, but plain
                # JSON — the browser cannot speak the argon2id-encrypted
                # madmin framing. No secrets travel: status/policies/groups.
                try:
                    ak, _ = await self._authenticate(request)
                except s3err.APIError as e:
                    return self._err_response(request, e)
                if not ak or not self.iam.is_allowed(ak, "admin:ListUsers", ""):
                    return self._err_response(request, s3err.AccessDenied)
                users = await self._run(self.iam.list_users)
                return web.json_response({
                    k: {"status": u.status, "policyName": ",".join(u.policies),
                        "memberOf": u.groups}
                    for k, u in users.items()
                })
            if request.method in ("GET", "HEAD") and (
                key == "console" or key.startswith("console/")
            ):
                # embedded browser console (reference embeds minio/console,
                # cmd/common-main.go:46); static page, data calls signed
                # in-browser
                from .console import handle_console

                return handle_console(request)
            if key.startswith("health/"):
                # disk probes may hit remote drives: stay off the event loop
                return await self._run(self._health, request, key)
            if key in ("v2/metrics/cluster", "v2/metrics/node") or key.startswith(
                "metrics/v3"
            ):
                if self.store is None:
                    return web.Response(status=503)
                if os.environ.get("MINIO_PROMETHEUS_AUTH_TYPE", "jwt") != "public":
                    # scrapers authenticate with the bearer JWT that
                    # `mc admin prometheus generate` mints (HS512 over the
                    # caller's secret key); SigV4 remains accepted for
                    # our own SDK (reference cmd/metrics-router.go)
                    if not self._prometheus_bearer_ok(request):
                        try:
                            ak, _ = await self._authenticate(request)
                        except s3err.APIError as e:
                            return self._err_response(request, e)
                        if not ak or not self.iam.is_allowed(
                            ak, "admin:Prometheus", ""
                        ):
                            return self._err_response(request, s3err.AccessDenied)
                if key.startswith("metrics/v3"):
                    from .metrics import render_v3

                    sub = key[len("metrics/v3"):]
                    text = await self._run(render_v3, self, sub)
                    if text is None:
                        return web.Response(status=404, body=b"unknown metrics path")
                else:
                    text = await self._run(self.metrics.render, self)
                return web.Response(body=text.encode(), content_type="text/plain")
        try:
            if self.store is None:
                return web.Response(
                    status=503, headers={"Retry-After": "1"},
                    body=b"server initializing",
                )
            return await self._dispatch(request)
        except s3err.APIError as e:
            return self._err_response(request, e)
        except quorum.BucketNotFound:
            return self._err_response(request, s3err.NoSuchBucket)
        except quorum.BucketExists:
            return self._err_response(request, s3err.BucketAlreadyOwnedByYou)
        except quorum.BucketNotEmpty:
            return self._err_response(request, s3err.BucketNotEmpty)
        except (quorum.ObjectNotFound,):
            return self._err_response(request, s3err.NoSuchKey)
        except quorum.VersionNotFound:
            return self._err_response(request, s3err.NoSuchVersion)
        except quorum.QuorumError:
            return self._err_response(request, s3err.InternalError)
        except Exception:  # noqa: BLE001
            import traceback

            traceback.print_exc()
            return self._err_response(request, s3err.InternalError)

    async def _authenticate(
        self, request: web.Request, stream_body: bool = False
    ) -> tuple[str, bytes | None]:
        """Verify request auth; returns (access_key, payload bytes).

        stream_body=True leaves the body unread (returned as None) for the
        streaming PUT path — only valid for auth modes that don't hash the
        payload (presigned / UNSIGNED-PAYLOAD), which _streamable_put
        guarantees."""
        headers = {k.lower(): v for k, v in request.headers.items()}
        raw_path = request.rel_url.raw_path
        query = urllib.parse.parse_qsl(
            request.rel_url.raw_query_string, keep_blank_values=True
        )
        if stream_body:
            body = None
        else:
            body = await request.read() if request.body_exists else b""

        qdict = dict(query)
        if "X-Amz-Signature" in qdict:
            ak = self.verifier.verify_presigned(request.method, raw_path, query, headers)
            self._check_session_token(ak, headers, qdict)
            return ak, body
        if (
            "Signature" in qdict
            and "AWSAccessKeyId" in qdict
            and "Expires" in qdict
        ):
            # legacy presigned V2 (reference cmd/signature-v2.go)
            from .signature import SigV2Verifier

            ak = SigV2Verifier(self.iam.lookup_secret).verify_presigned(
                request.method, raw_path, request.rel_url.raw_query_string,
                headers,
            )
            self._check_session_token(ak, headers, qdict)
            return ak, body
        if "authorization" not in headers:
            # anonymous: only bucket policies can authorize it downstream
            return "", body
        if headers["authorization"].startswith("AWS "):
            # legacy header V2: HMAC-SHA1 over the V2 string-to-sign
            from .signature import SigV2Verifier

            ak = SigV2Verifier(self.iam.lookup_secret).verify_header(
                request.method, raw_path, request.rel_url.raw_query_string, headers
            )
            self._check_session_token(ak, headers, {})
            return ak, body

        content_sha = headers.get("x-amz-content-sha256", signature.UNSIGNED_PAYLOAD)
        ak = self.verifier.verify_header_auth(
            request.method, raw_path, query, headers, content_sha
        )
        if content_sha == signature.STREAMING_UNSIGNED_TRAILER:
            if body is not None:  # streamed bodies decode inline in the pump
                body = self._decode_trailer_body(request, body)
        elif content_sha in (
            signature.STREAMING_PAYLOAD,
            signature.STREAMING_PAYLOAD_TRAILER,
        ):
            auth = signature.parse_auth_header(headers["authorization"])
            body = streaming.decode_signed_chunked(
                body,
                auth.signature,
                headers.get("x-amz-date", ""),
                auth.scope,
                self.iam.lookup_secret(ak) or "",
                trailer_mode=content_sha == signature.STREAMING_PAYLOAD_TRAILER,
            )
        elif content_sha not in (signature.UNSIGNED_PAYLOAD,):
            if hashlib.sha256(body).hexdigest() != content_sha:
                raise s3err.XAmzContentSHA256Mismatch
        self._check_session_token(ak, headers, {})
        return ak, body

    def _decode_trailer_body(self, request, body: bytes) -> bytes:
        """Decode a buffered aws-chunked STREAMING-UNSIGNED-PAYLOAD-TRAILER
        body; verify every x-amz-checksum trailer against the decoded
        payload and record it for storage (small uploads must get the
        same integrity behavior as streamed ones)."""
        from ..utils import checksum as cks

        dec = _AwsChunkedDecoder()
        data = dec.feed(body)
        meta: dict[str, str] = {}
        for k, v in dec.trailers.items():
            if k.startswith(cks.HEADER):
                algo = k[len(cks.HEADER):]
                if algo in cks.ALGOS:
                    if cks.compute(algo, data) != v:
                        raise s3err.InvalidDigest
                    meta[f"{cks.META_PREFIX}{algo}"] = v
        if meta:
            request["trailer_checksum_meta"] = meta
        return data

    def _streamable_put(self, request: web.Request) -> bool:
        """True for object PUTs whose body can flow straight into the
        erasure plane without buffering: auth never hashes the payload
        (presigned or UNSIGNED-PAYLOAD), no Content-MD5/checksum headers
        to verify over the whole body, no copy source, and the body is big
        enough for streaming to matter. Transform applicability (SSE,
        compression) is re-checked in the handler, which falls back to the
        buffered path since the body is still unread."""
        if request.method != "PUT":
            return False
        bucket = request.match_info.get("bucket", "")
        key = request.match_info.get("key", "")
        if not bucket or not key or bucket == "minio" or bucket.startswith(".minio.sys"):
            return False
        q = request.rel_url.query
        for sub in ("retention", "legal-hold", "tagging", "acl"):
            if sub in q:
                return False
        headers = {k.lower() for k in request.headers}
        if "x-amz-copy-source" in headers or "content-md5" in headers:
            return False
        sha = request.headers.get("x-amz-content-sha256", signature.UNSIGNED_PAYLOAD)
        trailer_mode = sha == signature.STREAMING_UNSIGNED_TRAILER
        if any(
            h.startswith((
                # full-body checksum headers need the buffered verify path;
                # TRAILER checksums stream (decoded + verified on the fly)
                "x-amz-checksum-",
                # request-level SSE needs the transform pipeline (whole body)
                "x-amz-server-side-encryption",
            ))
            for h in headers
        ):
            return False
        if ("x-amz-trailer" in headers or "x-amz-sdk-checksum-algorithm" in headers) \
                and not trailer_mode:
            return False
        presigned = "X-Amz-Signature" in q
        if not presigned and sha != signature.UNSIGNED_PAYLOAD and not trailer_mode:
            return False
        try:
            cl = int(
                request.headers.get("x-amz-decoded-content-length")
                or request.headers.get("Content-Length", "0")
            )
        except ValueError:
            return False
        return cl >= int(os.environ.get("MINIO_TPU_STREAM_MIN_BYTES", str(8 << 20)))

    async def _run_streaming_put(self, request: web.Request, consume):
        """Run consume(chunk_iterator) in the io pool while pumping the
        request body into it through a bounded queue (~8 MiB of chunks):
        the async HTTP read and the sync erasure encode/write overlap, and
        a part is never fully resident. A short body (client hung up) or
        pump failure raises into the consumer so the put aborts cleanly.
        """
        import queue as _queue

        chunk_sz = int(os.environ.get("MINIO_TPU_PUT_CHUNK_MB", "4")) << 20
        q: _queue.Queue = _queue.Queue(maxsize=max(2, (8 << 20) // chunk_sz))

        def gen():
            while True:
                item = q.get()
                if item is None:
                    return
                if isinstance(item, Exception):
                    raise item
                yield item

        self.streaming_puts += 1
        task = asyncio.ensure_future(self._run(consume, gen()))
        loop = asyncio.get_running_loop()

        def put_item(item):
            while True:
                if task.done():
                    raise _ConsumerDone
                try:
                    q.put(item, timeout=0.25)
                    return
                except _queue.Full:
                    continue

        def inject_error(e: Exception):
            """Guaranteed delivery: drain the queue until the sentinel fits
            so the consumer can never block forever on q.get() (which would
            wedge the namespace write lock and leak the io-pool thread)."""
            while True:
                try:
                    q.put_nowait(e)
                    return
                except _queue.Full:
                    try:
                        q.get_nowait()
                    except _queue.Empty:
                        pass

        # aws-chunked bodies with trailing checksums decode + verify inline
        # (reference cmd/streaming-v4-unsigned.go + internal/hash trailers)
        decoder = None
        hasher = None
        trailer_algo = ""
        if request.headers.get("x-amz-content-sha256") == \
                signature.STREAMING_UNSIGNED_TRAILER:
            from ..utils import checksum as cks

            decoder = _AwsChunkedDecoder()
            t = request.headers.get("x-amz-trailer", "").strip().lower()
            if t.startswith(cks.HEADER) and t[len(cks.HEADER):] in cks.ALGOS:
                trailer_algo = t[len(cks.HEADER):]
                hasher = cks.Hasher(trailer_algo)
            elif t:
                # a declared trailer we can't verify must not be accepted
                # silently (integrity was requested)
                raise s3err.InvalidArgument

        expect = int(
            request.headers.get("x-amz-decoded-content-length")
            or request.headers.get("Content-Length", "0")
        )
        got = 0
        try:
            while True:
                chunk = await request.content.read(chunk_sz)
                if not chunk:
                    err: Exception | None = None
                    if got != expect:
                        err = s3err.IncompleteBody
                    elif decoder is not None and hasher is not None:
                        from ..utils import checksum as cks

                        want = decoder.trailers.get(f"{cks.HEADER}{trailer_algo}")
                        if want is None or want != hasher.b64():
                            err = s3err.InvalidDigest
                        else:
                            request["trailer_checksum_meta"] = {
                                f"{cks.META_PREFIX}{trailer_algo}": want
                            }
                    await loop.run_in_executor(self._pump_pool, put_item, err)
                    break
                if decoder is not None:
                    chunk = decoder.feed(chunk)
                    if hasher is not None and chunk:
                        hasher.update(chunk)
                    if not chunk:
                        continue
                got += len(chunk)
                try:
                    # fast path: skip the executor hop when there's room
                    q.put_nowait(chunk)
                except _queue.Full:
                    await loop.run_in_executor(self._pump_pool, put_item, chunk)
        except _ConsumerDone:
            pass  # consumer already finished/failed; its result surfaces below
        except BaseException as e:
            inject_error(e if isinstance(e, Exception) else RuntimeError(str(e)))
            raise
        return await task

    def _check_session_token(self, access_key: str, headers, query) -> None:
        """Temp (STS) credentials must present a valid session token whose
        claims match the signing key (reference: checkClaimsFromToken)."""
        u = self.iam.users.get(access_key)
        if u is None or not u.is_temp:
            return
        token = headers.get("x-amz-security-token", "") or query.get(
            "X-Amz-Security-Token", ""
        )
        claims = self.iam.verify_token(token) if token else None
        if not claims or claims.get("accessKey") != access_key:
            raise s3err.AccessDenied

    # -- dispatch ------------------------------------------------------------

    def _authorize(
        self, access_key: str, action: str, bucket: str, key: str = "",
        conditions: dict[str, str] | None = None,
    ) -> None:
        if not action:
            return  # handler performs its own per-key authorization
        resource = f"{bucket}/{key}" if key else bucket
        bucket_policy = None
        if bucket:
            raw = self.buckets.get(bucket).policy
            if raw:
                from ..iam.policy import Policy

                bucket_policy = Policy.from_dict(raw)
        if not self.iam.is_allowed(
            access_key, action, resource, conditions, bucket_policy
        ):
            raise s3err.AccessDenied

    async def _dispatch(self, request: web.Request) -> web.StreamResponse:
        ak, body = await self._authenticate(
            request, stream_body=self._streamable_put(request)
        )
        request["access_key"] = ak
        bucket = request.match_info.get("bucket", "")
        # aiohttp match_info is already percent-decoded; decoding again
        # would corrupt keys that legitimately contain %-sequences
        key = request.match_info.get("key", "")
        q = request.rel_url.query
        m = request.method

        # admin + STS + KMS planes
        if bucket == "minio" and key.startswith("kms/"):
            if not ak or not self.iam.is_allowed(ak, "kms:Status", ""):
                raise s3err.AccessDenied
            import json as _json

            return web.Response(
                body=_json.dumps(self.kms.status()).encode(),
                content_type="application/json",
            )
        if bucket == "minio" and key.startswith("admin/"):
            from .admin import handle_admin

            if not ak:
                raise s3err.AccessDenied
            sub = key[len("admin/") :]
            sub = sub.split("/", 1)[1] if "/" in sub else ""  # strip version
            return await handle_admin(self, request, ak, sub, body)
        if not bucket and m == "POST":
            from .sts import handle_sts

            return await handle_sts(self, request, ak, body)

        if not bucket:
            if m == "GET":
                self._authorize(ak, "s3:ListAllMyBuckets", "")
                return await self.list_buckets(request)
            raise s3err.MethodNotAllowed
        if bucket.startswith(".minio.sys"):
            raise s3err.AccessDenied

        self._authorize(ak, *_route_action(m, bucket, key, q, request.headers),
                        conditions=_route_conditions(q))

        if not key:
            if m == "PUT":
                if "versioning" in q:
                    return await self.put_bucket_versioning(request, bucket, body)
                if "policy" in q:
                    return await self.put_bucket_simple(request, bucket, "policy", body)
                if "lifecycle" in q:
                    return await self.put_bucket_simple(request, bucket, "lifecycle", body)
                if "tagging" in q:
                    return await self.put_bucket_simple(request, bucket, "tags", body)
                if "notification" in q:
                    return await self.put_bucket_simple(request, bucket, "notification", body)
                if "encryption" in q:
                    return await self.put_bucket_simple(request, bucket, "encryption", body)
                if "object-lock" in q:
                    return await self.put_bucket_simple(request, bucket, "object_lock", body)
                if "cors" in q:
                    return await self.put_bucket_simple(request, bucket, "cors", body)
                if "replication" in q:
                    return await self.put_bucket_simple(request, bucket, "replication", body)
                if "acl" in q:
                    return await self.put_acl(request, bucket, "", body)
                if "requestPayment" in q:
                    return await self.put_request_payment(request, bucket, body)
                if "ownershipControls" in q:
                    return await self.put_bucket_simple(
                        request, bucket, "ownership", body
                    )
                if "logging" in q or "website" in q or "accelerate" in q:
                    raise s3err.NotImplemented_
                if any(s in q for s in _SUBRESOURCE_ACTIONS):
                    # unhandled method on a known subresource must NOT fall
                    # through to bucket creation (it was authorized for the
                    # SUBRESOURCE action, not s3:CreateBucket)
                    raise s3err.MethodNotAllowed
                return await self.put_bucket(request, bucket)
            if m == "DELETE":
                for sub in ("policy", "lifecycle", "tagging", "notification",
                            "encryption", "cors", "replication",
                            "ownershipControls"):
                    if sub in q:
                        return await self.delete_bucket_simple(request, bucket, sub)
                if any(s in q for s in _SUBRESOURCE_ACTIONS) or any(
                    s in q for s in ("website", "logging", "accelerate")
                ):
                    # e.g. DELETE ?acl or ?versioning was authorized for the
                    # subresource action only — falling through would delete
                    # the BUCKET without s3:DeleteBucket
                    raise s3err.MethodNotAllowed
                return await self.delete_bucket(request, bucket)
            if m == "HEAD":
                return await self.head_bucket(request, bucket)
            if m == "GET":
                if "events" in q:  # MinIO listen-notification extension
                    return await self.listen_events(request, bucket)
                if "location" in q:
                    return await self.get_bucket_location(request, bucket)
                if "versioning" in q:
                    return await self.get_bucket_versioning(request, bucket)
                if "versions" in q:
                    return await self.list_object_versions(request, bucket)
                for sub, attr, missing in (
                    ("policy", "policy", s3err.NoSuchBucketPolicy),
                    ("lifecycle", "lifecycle", s3err.NoSuchLifecycleConfiguration),
                    ("tagging", "tags", s3err.NoSuchTagSet),
                    ("notification", "notification", None),
                    ("encryption", "encryption", s3err.ServerSideEncryptionConfigurationNotFoundError),
                    ("object-lock", "object_lock", s3err.ObjectLockConfigurationNotFoundError),
                    ("cors", "cors", s3err.NoSuchCORSConfiguration),
                    ("replication", "replication", s3err.ReplicationConfigurationNotFoundError),
                ):
                    if sub in q:
                        return await self.get_bucket_simple(request, bucket, attr, missing)
                if "acl" in q:
                    return await self.get_acl(request, bucket, "")
                if "policyStatus" in q:
                    return await self.get_policy_status(request, bucket)
                if "requestPayment" in q:
                    return await self.get_request_payment(request, bucket)
                if "logging" in q:
                    return await self.get_bucket_logging(request, bucket)
                if "ownershipControls" in q:
                    return await self.get_bucket_simple(
                        request, bucket, "ownership",
                        s3err.OwnershipControlsNotFoundError,
                    )
                if "website" in q:
                    if not await self._run(self.store.bucket_exists, bucket):
                        raise s3err.NoSuchBucket
                    raise s3err.NoSuchWebsiteConfiguration
                if "uploads" in q:
                    return await self.list_multipart_uploads(request, bucket)
                return await self.list_objects(request, bucket)
            if m == "POST":
                if "delete" in q:
                    return await self.delete_multiple(request, bucket, body)
                ctype = request.headers.get("Content-Type", "")
                if ctype.startswith("multipart/form-data"):
                    return await self.post_policy_upload(request, bucket, body)
            raise s3err.MethodNotAllowed

        # object-level. Subresource blocks terminate: an unhandled method
        # was authorized for the SUBRESOURCE action and must not fall
        # through to object read/delete (e.g. DELETE ?retention holding
        # only s3:PutObjectRetention must not delete the object).
        if "retention" in q:
            if m == "PUT":
                return await self.put_object_retention(request, bucket, key, body)
            if m == "GET":
                return await self.get_object_retention(request, bucket, key)
            raise s3err.MethodNotAllowed
        if "legal-hold" in q:
            if m == "PUT":
                return await self.put_legal_hold(request, bucket, key, body)
            if m == "GET":
                return await self.get_legal_hold(request, bucket, key)
            raise s3err.MethodNotAllowed
        if "tagging" in q:
            if m == "PUT":
                return await self.put_object_tagging(request, bucket, key, body)
            if m == "GET":
                return await self.get_object_tagging(request, bucket, key)
            if m == "DELETE":
                return await self.delete_object_tagging(request, bucket, key)
            raise s3err.MethodNotAllowed
        if "acl" in q:
            if m == "PUT":
                return await self.put_acl(request, bucket, key, body)
            if m == "GET":
                return await self.get_acl(request, bucket, key)
            raise s3err.MethodNotAllowed
        if m == "PUT":
            if "partNumber" in q and "uploadId" in q:
                if "x-amz-copy-source" in request.headers:
                    return await self.upload_part_copy(request, bucket, key)
                return await self.put_object_part(request, bucket, key, body)
            if "x-amz-copy-source" in request.headers:
                return await self.copy_object(request, bucket, key)
            return await self.put_object(request, bucket, key, body)
        if m == "GET":
            if "uploadId" in q:
                return await self.list_parts(request, bucket, key)
            if "attributes" in q:
                return await self.get_object_attributes(request, bucket, key)
            if "lambdaArn" in q:
                return await self.get_object_lambda(request, bucket, key)
            return await self.get_object(request, bucket, key)
        if m == "HEAD":
            return await self.head_object(request, bucket, key)
        if m == "DELETE":
            if "uploadId" in q:
                return await self.abort_multipart(request, bucket, key)
            return await self.delete_object(request, bucket, key)
        if m == "POST":
            if "uploads" in q:
                return await self.new_multipart(request, bucket, key)
            if "uploadId" in q:
                return await self.complete_multipart(request, bucket, key, body)
            if "restore" in q:
                return await self.restore_object(request, bucket, key, body)
            if "select" in q and q.get("select-type") == "2":
                return await self.select_object_content(request, bucket, key, body)
        raise s3err.MethodNotAllowed

    # -- service -------------------------------------------------------------

    async def list_buckets(self, request) -> web.Response:
        buckets = await self._run(self.store.list_buckets)
        items = "".join(
            f"<Bucket><Name>{escape(b.name)}</Name>"
            f"<CreationDate>{_iso8601(b.created)}</CreationDate></Bucket>"
            for b in buckets
        )
        xml = (
            '<?xml version="1.0" encoding="UTF-8"?>'
            '<ListAllMyBucketsResult xmlns="http://s3.amazonaws.com/doc/2006-03-01/">'
            "<Owner><ID>minio-tpu</ID><DisplayName>minio-tpu</DisplayName></Owner>"
            f"<Buckets>{items}</Buckets></ListAllMyBucketsResult>"
        )
        return web.Response(body=xml.encode(), content_type="application/xml")

    # -- bucket --------------------------------------------------------------

    async def put_bucket(self, request, bucket: str) -> web.Response:
        if not BUCKET_NAME_RE.match(bucket) or ".." in bucket:
            raise s3err.InvalidBucketName
        await self._run(self.store.make_bucket, bucket)
        lock_enabled = request.headers.get("x-amz-bucket-object-lock-enabled", "") == "true"
        if lock_enabled:
            bm = self.buckets.get(bucket)
            bm.versioning = True
            bm.object_lock = "<ObjectLockConfiguration><ObjectLockEnabled>Enabled</ObjectLockEnabled></ObjectLockConfiguration>"
            await self._run(self.buckets.set, bucket, bm)
        if self.site.enabled:
            await self._run(self.site.sync_bucket_create, bucket)
        return web.Response(status=200, headers={"Location": f"/{bucket}"})

    async def head_bucket(self, request, bucket: str) -> web.Response:
        if not await self._run(self.store.bucket_exists, bucket):
            return web.Response(status=404)
        return web.Response(status=200)

    async def delete_bucket(self, request, bucket: str) -> web.Response:
        force = request.headers.get("x-minio-force-delete", "") == "true"
        # refuse non-empty buckets (cheap check: any object at all)
        res = await self._run(
            listing.list_objects, self.store, bucket, "", "", "", 1, True
        )
        if (res.objects or res.prefixes) and not force:
            raise s3err.BucketNotEmpty
        await self._run(self.store.delete_bucket, bucket, force or bool(res.objects))
        self.buckets.drop(bucket)
        if self.site.enabled:
            await self._run(self.site.sync_bucket_delete, bucket)
        return web.Response(status=204)

    async def get_bucket_location(self, request, bucket: str) -> web.Response:
        if not await self._run(self.store.bucket_exists, bucket):
            raise s3err.NoSuchBucket
        xml = (
            '<?xml version="1.0" encoding="UTF-8"?>'
            f'<LocationConstraint xmlns="http://s3.amazonaws.com/doc/2006-03-01/">{self.region}</LocationConstraint>'
        )
        return web.Response(body=xml.encode(), content_type="application/xml")

    async def get_bucket_versioning(self, request, bucket: str) -> web.Response:
        if not await self._run(self.store.bucket_exists, bucket):
            raise s3err.NoSuchBucket
        bm = self.buckets.get(bucket)
        inner = ""
        if bm.versioning:
            inner = "<Status>Enabled</Status>"
        elif bm.versioning_suspended:
            inner = "<Status>Suspended</Status>"
        xml = (
            '<?xml version="1.0" encoding="UTF-8"?>'
            f'<VersioningConfiguration xmlns="http://s3.amazonaws.com/doc/2006-03-01/">{inner}</VersioningConfiguration>'
        )
        return web.Response(body=xml.encode(), content_type="application/xml")

    async def put_bucket_versioning(self, request, bucket: str, body: bytes) -> web.Response:
        if not await self._run(self.store.bucket_exists, bucket):
            raise s3err.NoSuchBucket
        try:
            root = ET.fromstring(body)
            status = ""
            for el in root.iter():
                if el.tag.endswith("Status"):
                    status = el.text or ""
        except ET.ParseError:
            raise s3err.MalformedXML from None
        bm = self.buckets.get(bucket)
        if bm.object_lock and status != "Enabled":
            # AWS: versioning cannot be suspended on object-lock buckets
            # (retention would otherwise guard nothing)
            raise s3err.InvalidBucketState
        bm.versioning = status == "Enabled"
        bm.versioning_suspended = status == "Suspended"
        await self._run(self.buckets.set, bucket, bm)
        return web.Response(status=200)

    async def get_bucket_simple(self, request, bucket, attr, missing_err) -> web.Response:
        if not await self._run(self.store.bucket_exists, bucket):
            raise s3err.NoSuchBucket
        bm = self.buckets.get(bucket)
        val = getattr(bm, attr)
        if not val:
            if missing_err is None:
                val = '<?xml version="1.0" encoding="UTF-8"?><NotificationConfiguration/>'
            else:
                raise missing_err
        if isinstance(val, dict):
            import json

            return web.Response(body=json.dumps(val).encode(), content_type="application/json")
        return web.Response(body=val.encode() if isinstance(val, str) else val,
                            content_type="application/xml")

    async def listen_events(self, request, bucket: str) -> web.StreamResponse:
        """Real-time event firehose (reference
        cmd/listen-notification-handlers.go)."""
        import asyncio as _asyncio
        import json as _json
        import queue as _queue

        q = request.rel_url.query
        events = [e for e in q.get("events", "").split(",") if e]
        ent = self.notifier.subscribe(
            bucket, q.get("prefix", ""), q.get("suffix", ""), events
        )
        resp = web.StreamResponse(headers={"Content-Type": "application/json"})
        await resp.prepare(request)
        loop = _asyncio.get_running_loop()
        try:
            while True:
                try:
                    rec = await loop.run_in_executor(
                        self._longpoll_pool, ent[0].get, True, 1.0
                    )
                except _queue.Empty:
                    await resp.write(b" \n")  # keep-alive, like the reference
                    continue
                await resp.write(
                    _json.dumps({"Records": [rec]}).encode() + b"\n"
                )
        except (ConnectionResetError, _asyncio.CancelledError):
            pass
        finally:
            self.notifier.unsubscribe(ent)
        return resp

    async def put_bucket_simple(self, request, bucket, attr, body: bytes) -> web.Response:
        if not await self._run(self.store.bucket_exists, bucket):
            raise s3err.NoSuchBucket
        bm = self.buckets.get(bucket)
        if attr == "notification":
            try:
                self.notifier.validate_config(body.decode())
            except ValueError:
                raise s3err.InvalidArgument from None
            except ET.ParseError:
                raise s3err.MalformedXML from None
        if attr == "lifecycle":
            from ..ilm.lifecycle import validate_lifecycle

            try:
                validate_lifecycle(body.decode())
            except (ValueError, ET.ParseError):
                raise s3err.MalformedXML from None
        if attr == "cors":
            from . import cors as corsmod

            try:
                corsmod.parse_bucket_cors(body.decode())
            except (ValueError, ET.ParseError):
                raise s3err.MalformedXML from None
        if attr == "policy":
            import json

            from ..iam.policy import Policy

            try:
                doc = json.loads(body)
                pol = Policy.from_dict(doc)
            except ValueError:
                raise s3err.MalformedXML from None
            except (AttributeError, TypeError):
                # valid JSON but not policy-shaped (e.g. a list or scalar)
                raise s3err.MalformedPolicy from None
            # resource policies must name a Resource per statement — an
            # omitted Resource would otherwise match every object
            # (reference validates this at PutBucketPolicy time)
            if not pol.statements or any(not s.resources for s in pol.statements):
                raise s3err.MalformedPolicy
            setattr(bm, attr, doc)
        else:
            setattr(bm, attr, body.decode())
        await self._run(self.buckets.set, bucket, bm)
        return web.Response(status=200 if attr != "policy" else 204)

    # -- ACL / misc compat surface (reference cmd/acl-handlers.go,
    # bucket-handlers.go requestPayment/logging/policyStatus) ----------------

    def _owner_id(self) -> str:
        # deterministic canonical owner id for this deployment (the
        # reference serves a fixed owner id + "minio" display name)
        return hashlib.sha256(self.root_user.encode()).hexdigest()

    def _owner_xml(self) -> str:
        return (
            f"<Owner><ID>{self._owner_id()}</ID>"
            f"<DisplayName>minio</DisplayName></Owner>"
        )

    async def get_acl(self, request, bucket: str, key: str) -> web.Response:
        """Canned-ACL world: everything is owner FULL_CONTROL (reference
        GetBucketACLHandler / GetObjectACLHandler)."""
        if not await self._run(self.store.bucket_exists, bucket):
            raise s3err.NoSuchBucket
        if key:
            # missing objects must 404, same as a GET
            await self._run(
                self.store.get_object_info, bucket,
                listing.encode_dir_object(key),
                request.rel_url.query.get("versionId", ""),
            )
        owner = self._owner_xml()
        oid = self._owner_id()
        xml = (
            '<?xml version="1.0" encoding="UTF-8"?>'
            '<AccessControlPolicy xmlns="http://s3.amazonaws.com/doc/2006-03-01/">'
            f"{owner}<AccessControlList><Grant>"
            '<Grantee xmlns:xsi="http://www.w3.org/2001/XMLSchema-instance" '
            'xsi:type="CanonicalUser">'
            f"<ID>{oid}</ID><DisplayName>minio</DisplayName></Grantee>"
            "<Permission>FULL_CONTROL</Permission></Grant></AccessControlList>"
            "</AccessControlPolicy>"
        )
        return web.Response(body=xml.encode(), content_type="application/xml")

    async def put_acl(self, request, bucket: str, key: str, body: bytes) -> web.Response:
        """Only the private canned ACL (or an equivalent single
        FULL_CONTROL grant document) is accepted; anything else is
        NotImplemented — bucket policies are the access-control system
        (reference PutBucketACLHandler/PutObjectACLHandler)."""
        if not await self._run(self.store.bucket_exists, bucket):
            raise s3err.NoSuchBucket
        if key:
            # a missing object must 404, matching the GET side
            await self._run(
                self.store.get_object_info, bucket,
                listing.encode_dir_object(key),
                request.rel_url.query.get("versionId", ""),
            )
        canned = request.headers.get("x-amz-acl", "")
        if canned:
            if canned != "private":
                raise s3err.NotImplemented_
            return web.Response(status=200)
        try:
            root = ET.fromstring(body)
        except ET.ParseError:
            raise s3err.MalformedXML from None
        grants = [el for el in root.iter() if el.tag.split("}")[-1] == "Grant"]
        if len(grants) != 1:
            raise s3err.NotImplemented_
        perm = next(
            (el.text for el in grants[0] if el.tag.split("}")[-1] == "Permission"),
            "",
        )
        if perm != "FULL_CONTROL":
            raise s3err.NotImplemented_
        return web.Response(status=200)

    async def get_policy_status(self, request, bucket: str) -> web.Response:
        """Whether anonymous requests are allowed by the bucket policy
        (reference GetBucketPolicyStatusHandler)."""
        if not await self._run(self.store.bucket_exists, bucket):
            raise s3err.NoSuchBucket
        bm = self.buckets.get(bucket)
        public = False
        for st in (bm.policy or {}).get("Statement", []):
            principal = st.get("Principal", "")
            aws = principal.get("AWS", "") if isinstance(principal, dict) else principal
            if isinstance(aws, list):
                aws = "*" if "*" in aws else ""
            if st.get("Effect") == "Allow" and aws == "*":
                public = True
        xml = (
            '<?xml version="1.0" encoding="UTF-8"?>'
            '<PolicyStatus xmlns="http://s3.amazonaws.com/doc/2006-03-01/">'
            f"<IsPublic>{'true' if public else 'false'}</IsPublic></PolicyStatus>"
        )
        return web.Response(body=xml.encode(), content_type="application/xml")

    async def get_request_payment(self, request, bucket: str) -> web.Response:
        if not await self._run(self.store.bucket_exists, bucket):
            raise s3err.NoSuchBucket
        xml = (
            '<?xml version="1.0" encoding="UTF-8"?>'
            '<RequestPaymentConfiguration xmlns="http://s3.amazonaws.com/doc/2006-03-01/">'
            "<Payer>BucketOwner</Payer></RequestPaymentConfiguration>"
        )
        return web.Response(body=xml.encode(), content_type="application/xml")

    async def put_request_payment(self, request, bucket: str, body: bytes) -> web.Response:
        if not await self._run(self.store.bucket_exists, bucket):
            raise s3err.NoSuchBucket
        if b"Requester" in body:
            raise s3err.NotImplemented_  # only BucketOwner payment exists
        return web.Response(status=200)

    async def get_bucket_logging(self, request, bucket: str) -> web.Response:
        if not await self._run(self.store.bucket_exists, bucket):
            raise s3err.NoSuchBucket
        # access logging rides the audit/notification planes; the S3 call
        # reports it disabled, like the reference
        xml = (
            '<?xml version="1.0" encoding="UTF-8"?>'
            '<BucketLoggingStatus xmlns="http://s3.amazonaws.com/doc/2006-03-01/" />'
        )
        return web.Response(body=xml.encode(), content_type="application/xml")

    async def delete_bucket_simple(self, request, bucket, sub) -> web.Response:
        attr = {"tagging": "tags", "ownershipControls": "ownership"}.get(sub, sub)
        bm = self.buckets.get(bucket)
        setattr(bm, attr, None if attr != "tags" else {})
        await self._run(self.buckets.set, bucket, bm)
        return web.Response(status=204)

    # -- listing ---------------------------------------------------------------

    async def list_objects(self, request, bucket: str) -> web.Response:
        q = request.rel_url.query
        v2 = q.get("list-type") == "2"
        url_encode = q.get("encoding-type") == "url"
        prefix = q.get("prefix", "")
        delimiter = q.get("delimiter", "")
        try:
            max_keys = int(q.get("max-keys", "1000"))
        except ValueError:
            raise s3err.InvalidMaxKeys from None
        if v2:
            marker = q.get("continuation-token", "") or q.get("start-after", "")
        else:
            marker = q.get("marker", "")
        res = await self._run(
            listing.list_objects, self.store, bucket, prefix, marker, delimiter, max_keys
        )
        def enc(s: str) -> str:
            # encoding-type=url: keys percent-encoded so control chars in
            # names survive XML (reference s3EncodeName)
            return urllib.parse.quote(s, safe="/") if url_encode else escape(s)

        contents = "".join(
            f"<Contents><Key>{enc(o.name)}</Key>"
            f"<LastModified>{_iso8601(o.mod_time)}</LastModified>"
            f'<ETag>"{o.etag}"</ETag><Size>{o.size}</Size>'
            f"<StorageClass>STANDARD</StorageClass></Contents>"
            for o in res.objects
        )
        prefixes = "".join(
            f"<CommonPrefixes><Prefix>{enc(p)}</Prefix></CommonPrefixes>"
            for p in res.prefixes
        )
        common = (
            f"<Name>{escape(bucket)}</Name><Prefix>{enc(prefix)}</Prefix>"
            f"<MaxKeys>{max_keys}</MaxKeys>"
            f"<Delimiter>{escape(delimiter)}</Delimiter>"
            + ("<EncodingType>url</EncodingType>" if url_encode else "")
            + f"<IsTruncated>{'true' if res.is_truncated else 'false'}</IsTruncated>"
        )
        if v2:
            extra = f"<KeyCount>{len(res.objects) + len(res.prefixes)}</KeyCount>"
            if res.is_truncated:
                extra += f"<NextContinuationToken>{enc(res.next_marker)}</NextContinuationToken>"
            xml = (
                '<?xml version="1.0" encoding="UTF-8"?>'
                '<ListBucketResult xmlns="http://s3.amazonaws.com/doc/2006-03-01/">'
                f"{common}{extra}{contents}{prefixes}</ListBucketResult>"
            )
        else:
            extra = ""
            if res.is_truncated:
                extra = f"<NextMarker>{enc(res.next_marker)}</NextMarker>"
            xml = (
                '<?xml version="1.0" encoding="UTF-8"?>'
                '<ListBucketResult xmlns="http://s3.amazonaws.com/doc/2006-03-01/">'
                f"{common}{extra}{contents}{prefixes}</ListBucketResult>"
            )
        return web.Response(body=xml.encode(), content_type="application/xml")

    async def list_object_versions(self, request, bucket: str) -> web.Response:
        q = request.rel_url.query
        prefix = q.get("prefix", "")
        delimiter = q.get("delimiter", "")
        max_keys = int(q.get("max-keys", "1000"))
        marker = q.get("key-marker", "")
        vmarker = q.get("version-id-marker", "")
        res = await self._run(
            listing.list_objects,
            self.store,
            bucket,
            prefix,
            marker,
            delimiter,
            max_keys,
            True,
            vmarker,
        )
        body = []
        for o in res.objects:
            vid = o.version_id or "null"
            tag = "DeleteMarker" if o.delete_marker else "Version"
            entry = (
                f"<{tag}><Key>{escape(o.name)}</Key><VersionId>{vid}</VersionId>"
                f"<IsLatest>{'true' if o.is_latest else 'false'}</IsLatest>"
                f"<LastModified>{_iso8601(o.mod_time)}</LastModified>"
            )
            if not o.delete_marker:
                entry += f'<ETag>"{o.etag}"</ETag><Size>{o.size}</Size><StorageClass>STANDARD</StorageClass>'
            entry += f"</{tag}>"
            body.append(entry)
        prefixes = "".join(
            f"<CommonPrefixes><Prefix>{escape(p)}</Prefix></CommonPrefixes>"
            for p in res.prefixes
        )
        xml = (
            '<?xml version="1.0" encoding="UTF-8"?>'
            '<ListVersionsResult xmlns="http://s3.amazonaws.com/doc/2006-03-01/">'
            f"<Name>{escape(bucket)}</Name><Prefix>{escape(prefix)}</Prefix>"
            f"<MaxKeys>{max_keys}</MaxKeys>"
            f"<IsTruncated>{'true' if res.is_truncated else 'false'}</IsTruncated>"
            f"{''.join(body)}{prefixes}</ListVersionsResult>"
        )
        return web.Response(body=xml.encode(), content_type="application/xml")

    # -- objects ---------------------------------------------------------------

    def _parity_for_storage_class(self, request) -> int | None:
        """Per-request EC parity from x-amz-storage-class (reference
        cmd/erasure-object.go:1299 + internal/config/storageclass):
        STANDARD uses MINIO_STORAGE_CLASS_STANDARD when set,
        REDUCED_REDUNDANCY uses MINIO_STORAGE_CLASS_RRS (default EC:2).
        Unknown classes (e.g. tier names) keep the set default."""
        sc = request.headers.get("x-amz-storage-class", "")
        if not sc or sc == "STANDARD":
            spec = os.environ.get("MINIO_STORAGE_CLASS_STANDARD", "")
        elif sc == "REDUCED_REDUNDANCY":
            spec = os.environ.get("MINIO_STORAGE_CLASS_RRS", "EC:2")
        else:
            return None
        if not spec.startswith("EC:"):
            return None
        try:
            p = int(spec[3:])
        except ValueError:
            return None
        n = getattr(self.store, "n", 0)
        if n < 2:
            return None
        return max(1, min(p, n // 2))

    async def _proxy_get_remote(self, request, bucket, key, vid=""):
        """Serve a not-yet-replicated object from a replication target.

        Returns None when no target has it (or proxying is disabled /
        this request already IS a proxy — loop breaker). Streams the
        remote body chunk by chunk — a lagging multi-GB object must not
        be buffered whole per request."""
        if request.headers.get("x-minio-source-proxy-request") == "true":
            return None
        if os.environ.get("MINIO_TPU_REPLICATION_PROXY", "on") == "off":
            return None
        if not self.buckets.get(bucket).versioning:
            # the reference requires versioning for replication; without it
            # a hard delete leaves no local trace and proxying would
            # resurrect deleted objects
            return None
        targets = self.repl_targets.list(bucket)
        if not targets:
            return None
        # only proxy when the object has NO local trace: a local delete
        # marker (or any version) means the 404 is authoritative — proxying
        # would resurrect deleted objects from a lagging peer
        try:
            if await self._run(self.store.list_object_versions, bucket, key):
                return None
        except Exception:  # noqa: BLE001
            return None
        hdrs = {"x-minio-source-proxy-request": "true"}
        rng = request.headers.get("Range")
        if rng:
            hdrs["Range"] = rng

        import http.client as _hc

        from .signature import sign_request

        def open_remote():
            """(status, resp-headers, http response) from the first target
            that has the object, None otherwise."""
            q = f"?versionId={urllib.parse.quote(vid)}" if vid else ""
            for t in targets:
                try:
                    path = "/" + t.target_bucket + "/" + urllib.parse.quote(key, safe="/~-._") + q
                    url = f"http://{t.endpoint.split('//')[-1]}{path}"
                    signed = sign_request(
                        "GET", url, dict(hdrs), "UNSIGNED-PAYLOAD",
                        t.access_key, t.secret_key, self.region,
                    )
                    host = t.endpoint.split("//")[-1]
                    conn = _hc.HTTPConnection(host, timeout=30)
                    conn.request("GET", path, headers=signed)
                    resp = conn.getresponse()
                    if resp.status in (200, 206):
                        return resp
                    resp.read()
                    conn.close()
                except Exception:  # noqa: BLE001 — peer down: try the next
                    continue
            return None

        resp = await self._run(open_remote)
        if resp is None:
            return None
        out_headers = {
            k.lower(): v for k, v in resp.getheaders()
            if k.lower() in ("etag", "last-modified", "content-type",
                             "content-range", "content-length",
                             "x-amz-version-id")
            or k.lower().startswith("x-amz-meta-")
        }
        sresp = web.StreamResponse(status=resp.status, headers=out_headers)
        await sresp.prepare(request)
        loop = asyncio.get_running_loop()
        try:
            while True:
                chunk = await loop.run_in_executor(
                    self._io_pool, resp.read, 1 << 20
                )
                if not chunk:
                    break
                await sresp.write(chunk)
        finally:
            resp.close()
        await sresp.write_eof()
        return sresp

    async def _get_from_tier(self, request, bucket, key, oi) -> web.StreamResponse:
        """Read-through GET of a transitioned object: bytes come from the
        warm tier (reference streams transitioned objects from the tier
        the same way, cmd/bucket-lifecycle.go getTransitionedObjectReader).
        """
        from ..ilm import tier as tiermod

        tname = oi.user_defined.get(tiermod.TRANSITION_TIER_META, "")
        rkey = oi.user_defined.get(tiermod.TRANSITION_KEY_META, "")
        t = self.tiers.get(tname)
        if t is None:
            raise s3err.InternalError
        self._check_preconditions(request, oi)
        hdrs = {}
        rng = self._parse_range(request, oi.size) if oi.size else None
        if rng:
            hdrs["Range"] = f"bytes={rng[0]}-{rng[1]}"

        def fetch():
            r = t.client().get_object(t.bucket, rkey, headers=hdrs)
            if r.status not in (200, 206):
                raise RuntimeError(f"tier read failed: HTTP {r.status}")
            return r.body

        body = await self._run(fetch)
        headers = self._obj_headers(oi)
        headers["x-amz-storage-class"] = tname
        if rng:
            start, end = rng
            if len(body) == oi.size:
                # tier ignored the Range header: slice locally rather than
                # serving the whole object mislabeled as a range
                body = body[start:end + 1]
            headers["Content-Range"] = f"bytes {start}-{end}/{oi.size}"
            return web.Response(status=206, body=body, headers=headers)
        return web.Response(status=200, body=body, headers=headers)

    async def restore_object(self, request, bucket: str, key: str, body: bytes) -> web.Response:
        """POST /bucket/key?restore — bring a transitioned object's data
        back locally for N days (reference RestoreObjectHandler)."""
        from ..ilm import tier as tiermod

        key = listing.encode_dir_object(key)
        days = 1
        if body:
            try:
                root = ET.fromstring(body)
                for el in root.iter():
                    if el.tag.split("}")[-1] == "Days" and el.text:
                        days = max(1, int(el.text))
            except ET.ParseError:
                raise s3err.MalformedXML from None
        oi = await self._run(self.store.get_object_info, bucket, key)
        if not tiermod.is_transitioned(oi.user_defined):
            raise s3err.InvalidObjectState
        if _restored_locally(oi):
            return web.Response(status=200)  # already restored
        tname = oi.user_defined.get(tiermod.TRANSITION_TIER_META, "")
        rkey = oi.user_defined.get(tiermod.TRANSITION_KEY_META, "")
        t = self.tiers.get(tname)
        if t is None:
            raise s3err.InternalError

        def pull_and_restore():
            r = t.client().get_object(t.bucket, rkey)
            if r.status != 200:
                raise RuntimeError(f"tier read failed: HTTP {r.status}")
            self.store.restore_object(bucket, key, r.body, days)

        await self._run(pull_and_restore)
        return web.Response(status=202)

    def _obj_headers(self, oi: ObjectInfo) -> dict[str, str]:
        from ..crypto import sse as ssemod

        h = {
            "ETag": f'"{oi.etag}"',
            "Last-Modified": _http_date(oi.mod_time),
            "Accept-Ranges": "bytes",
            "Content-Type": oi.content_type or "application/octet-stream",
        }
        if oi.version_id:
            h["x-amz-version-id"] = oi.version_id
        for k, v in oi.user_defined.items():
            if k.startswith("x-amz-meta-") or k in ("cache-control", "content-disposition", "content-encoding", "content-language", "expires"):
                h[k] = v
        from ..utils import checksum as _cks

        for calgo in _cks.ALGOS:
            v = oi.user_defined.get(f"{_cks.META_PREFIX}{calgo}")
            if v:
                h[f"x-amz-checksum-{calgo}"] = v
        raw_tags = oi.user_defined.get(self.TAGS_META)
        if raw_tags:
            h["x-amz-tagging-count"] = str(
                len(urllib.parse.parse_qsl(raw_tags, keep_blank_values=True))
            )
        from ..ilm import tier as tiermod

        tname = oi.user_defined.get(tiermod.TRANSITION_TIER_META)
        if tname:
            h["x-amz-storage-class"] = tname
            if _restored_locally(oi):
                exp = float(oi.user_defined[tiermod.RESTORE_EXPIRY_META])
                h["x-amz-restore"] = (
                    'ongoing-request="false", expiry-date="'
                    + _http_date(int(exp * 1e9)) + '"'
                )
        algo = oi.user_defined.get(ssemod.META_ALGO)
        if algo == "SSE-S3":
            h["x-amz-server-side-encryption"] = "AES256"
        elif algo == "SSE-KMS":
            h["x-amz-server-side-encryption"] = "aws:kms"
            h["x-amz-server-side-encryption-aws-kms-key-id"] = oi.user_defined.get(
                ssemod.META_KMS_KEY_ID, ""
            )
        elif algo == "SSE-C":
            h["x-amz-server-side-encryption-customer-algorithm"] = "AES256"
            h["x-amz-server-side-encryption-customer-key-MD5"] = oi.user_defined.get(
                ssemod.META_SSEC_KEY_MD5, ""
            )
        return h

    @staticmethod
    def _eval_preconditions(headers, oi: ObjectInfo, prefix: str, none_match_err) -> None:
        """Shared If-Match/If-None-Match/If-(Un)Modified-Since evaluation.
        Header precedence follows RFC 7232 (and AWS's documented copy
        combinations): an If-Match that evaluates TRUE suppresses
        If-Unmodified-Since, and a present If-None-Match suppresses
        If-Modified-Since. GET/HEAD use the bare names with 304 on the
        None-Match side; CopyObject/UploadPartCopy use the
        x-amz-copy-source-if-* set where every failure is 412
        (cmd/object-handlers.go checkCopyObjectPreconditions)."""
        etag = f'"{oi.etag}"'
        im = headers.get(f"{prefix}If-Match")
        if im:
            if im.strip() not in (etag, "*", oi.etag):
                raise s3err.PreconditionFailed
        else:
            ius = headers.get(f"{prefix}If-Unmodified-Since")
            if ius:
                try:
                    t = parsedate_to_datetime(ius)
                    if oi.mod_time / 1e9 > t.timestamp():
                        raise s3err.PreconditionFailed
                except (ValueError, TypeError):
                    pass
        inm = headers.get(f"{prefix}If-None-Match")
        if inm:
            if inm.strip() in (etag, "*", oi.etag):
                raise none_match_err
        else:
            ims = headers.get(f"{prefix}If-Modified-Since")
            if ims:
                try:
                    t = parsedate_to_datetime(ims)
                    if oi.mod_time / 1e9 <= t.timestamp():
                        raise none_match_err
                except (ValueError, TypeError):
                    pass

    def _check_preconditions(self, request, oi: ObjectInfo) -> None:
        self._eval_preconditions(request.headers, oi, "", s3err.NotModified)

    @staticmethod
    def _incoming_size(request, body: bytes | None) -> int:
        """Logical size of an incoming write for quota purposes: buffered
        body length, else the decoded payload length for aws-chunked
        streams (the wire Content-Length includes chunk framing), else
        Content-Length."""
        if body is not None:
            return len(body)
        dec = request.headers.get("x-amz-decoded-content-length")
        if dec:
            try:
                return int(dec)
            except ValueError:
                pass
        try:
            return int(request.headers.get("Content-Length", "0") or 0)
        except ValueError:
            return 0

    def _enforce_quota(self, bucket: str, size: int) -> None:
        """Hard bucket quota on the write path (reference
        cmd/bucket-quota.go:103-139 enforceBucketQuotaHard): the incoming
        size plus the scanner-accounted bucket usage must stay under the
        configured quota. Usage freshness matches the reference: the data
        scanner's last crawl."""
        if size < 0:
            return
        q = int(self.buckets.get(bucket).quota or 0)
        if q <= 0:
            return
        if size >= q:
            raise s3err.AdminBucketQuotaExceeded
        bg = getattr(self, "background", None)
        usage = bg.usage.buckets.get(bucket) if bg is not None else None
        if usage and usage.get("size", 0) > 0 and usage["size"] + size >= q:
            raise s3err.AdminBucketQuotaExceeded

    @staticmethod
    def _put_precond(request):
        """Conditional writes (reference checkPreconditionsPUT,
        cmd/object-handlers.go:2017): If-None-Match: * fails when the key
        exists; If-Match: <etag> fails unless the CURRENT etag matches.
        Runs under the namespace write lock inside the erasure layer."""
        inm = request.headers.get("If-None-Match", "").strip()
        im = request.headers.get("If-Match", "").strip()
        if not inm and not im:
            return None

        def check(cur) -> None:
            if inm and cur is not None and (
                inm == "*" or inm in (f'"{cur.etag}"', cur.etag)
            ):
                raise s3err.PreconditionFailed
            if im:
                if cur is None or im not in ("*", f'"{cur.etag}"', cur.etag):
                    raise s3err.PreconditionFailed

        return check

    async def put_object(
        self, request, bucket: str, key: str, body: bytes | None
    ) -> web.Response:
        key = listing.encode_dir_object(key)
        bm = self.buckets.get(bucket)
        precond = self._put_precond(request)
        self._enforce_quota(bucket, self._incoming_size(request, body))
        # overwriting an unversioned transitioned object orphans its warm-
        # tier data unless swept (reference enforces this via objSweeper)
        sweep_ud = None if bm.versioning else await self._run(
            self._tier_sweep_snapshot, bucket, key, ""
        )
        from . import transforms

        ct = request.headers.get("Content-Type")
        if body is None and (
            _bucket_sse_algo(bm.encryption) or transforms.compression_enabled()
        ):
            # a transform needs the whole payload: fall back to buffering
            # (the body is still unread on the socket)
            body = await request.read() if request.body_exists else b""
            if request.headers.get("x-amz-content-sha256") == \
                    signature.STREAMING_UNSIGNED_TRAILER:
                # the wire body is aws-chunked: decode + verify trailers
                # before transforming, or the framing would be stored
                body = self._decode_trailer_body(request, body)
        md5_hdr = request.headers.get("Content-MD5")
        if md5_hdr:
            import base64

            if base64.b64encode(hashlib.md5(body).digest()).decode() != md5_hdr:
                raise s3err.BadDigest
        checksum_meta = _verify_checksum_headers(request.headers, body or b"")
        # trailers verified during buffered aws-chunked decode persist too
        checksum_meta.update(request.get("trailer_checksum_meta") or {})
        user_defined = {}
        if ct:
            user_defined["content-type"] = ct
        for k, v in request.headers.items():
            lk = k.lower()
            if lk.startswith("x-amz-meta-") or lk in (
                "cache-control", "content-disposition", "content-encoding",
                "content-language", "expires", "x-amz-storage-class",
            ):
                user_defined[lk] = v
        if request.headers.get("x-amz-tagging"):
            # tag set supplied at PUT time (reference PutObjectHandler
            # parses x-amz-tagging into the version's tag metadata)
            user_defined[self.TAGS_META] = self._tagging_header_meta(
                request.headers["x-amz-tagging"]
            )
        if body is None:
            # streaming path: body flows HTTP -> erasure encode -> drives
            user_defined.update(checksum_meta)
            sc_parity = self._parity_for_storage_class(request)
            oi = await self._run_streaming_put(
                request,
                lambda rd: self.store.put_object(
                    bucket, key, rd, user_defined, None, bm.versioning,
                    parity=sc_parity, check_precond=precond,
                ),
            )
            headers = {"ETag": f'"{oi.etag}"'}
            tr = request.get("trailer_checksum_meta")
            if tr:
                # verified trailer checksum: persist + echo (reference
                # internal/hash checksum trailers)
                await self._run(
                    self.store.update_object_metadata, bucket, key,
                    oi.version_id, lambda md: md.update(tr),
                )
                for mk, mv in tr.items():
                    headers[mk.replace("x-minio-internal-", "x-amz-")] = mv
            if oi.version_id:
                headers["x-amz-version-id"] = oi.version_id
            from ..events import notify as ev

            self.notifier.notify(
                ev.OBJECT_CREATED_PUT, bucket, listing.decode_dir_object(key),
                oi.size, oi.etag, oi.version_id, request.get("access_key", ""),
            )
            self._queue_repl(request, bucket, key, oi.version_id, "put")
            await self._tier_sweep(sweep_ud)
            return web.Response(status=200, headers=headers)
        # transparent compression + server-side encryption
        req_headers = {k.lower(): v for k, v in request.headers.items()}
        try:
            tr = transforms.encode_for_store(
                body, key, ct or "", req_headers,
                _bucket_sse_algo(bm.encryption), self.kms, bucket,
            )
        except Exception as e:
            from ..crypto.sse import CryptoError

            if isinstance(e, CryptoError):
                raise s3err.InvalidArgument from None
            raise
        if tr.metadata:
            user_defined.update(tr.metadata)
            body = tr.data
        user_defined.update(checksum_meta)
        oi = await self._run(
            lambda: self.store.put_object(
                bucket, key, body, user_defined, None, bm.versioning,
                parity=self._parity_for_storage_class(request),
                check_precond=precond,
            )
        )
        headers = {"ETag": f'"{oi.etag}"'}
        headers.update(tr.response_headers)
        for k, v in checksum_meta.items():
            headers[k.replace("x-minio-internal-", "x-amz-")] = v
        if oi.version_id:
            headers["x-amz-version-id"] = oi.version_id
        from ..events import notify as ev

        self.notifier.notify(
            ev.OBJECT_CREATED_PUT, bucket, listing.decode_dir_object(key),
            oi.size, oi.etag, oi.version_id, request.get("access_key", ""),
        )
        self._queue_repl(request, bucket, key, oi.version_id, "put")
        await self._tier_sweep(sweep_ud)
        return web.Response(status=200, headers=headers)

    def _tier_sweep_snapshot(self, bucket: str, key: str, vid: str) -> dict | None:
        """Pre-delete/overwrite snapshot of a transitioned version's tier
        pointers (reference cmd/tier-sweeper.go newObjSweeper +
        SetTransitionState): returns the metadata needed to sweep the
        warm tier after the local version goes away, or None.

        vid == "" means the NULL version (what an unversioned/suspended
        write or delete actually replaces) — NOT the latest: on a
        versioning-suspended bucket the latest may be a surviving named
        version whose warm data must not be swept."""
        from ..ilm import tier as tiermod

        if not self.tiers.list():
            return None  # no tiers configured: nothing to sweep, zero cost
        try:
            if vid:
                oi = self.store.get_object_info(bucket, key, vid)
            else:
                oi = next(
                    (v for v in self.store.list_object_versions(bucket, key)
                     if not v.version_id),
                    None,
                )
                if oi is None:
                    return None  # no null version to replace
        except Exception:  # noqa: BLE001 — no prior version
            return None
        if getattr(oi, "delete_marker", False) or not tiermod.is_transitioned(
            oi.user_defined
        ):
            return None
        return dict(oi.user_defined)

    async def _tier_sweep(self, sweep_ud: dict | None) -> None:
        """Fire-and-forget: the remote delete (5s timeouts when the tier is
        down) must not hold up the S3 response; failures land in the
        persisted journal the scanner retries (the reference routes all
        sweeps through its async tier journal for the same reason)."""
        if sweep_ud:
            from ..ilm import tier as tiermod

            asyncio.get_running_loop().run_in_executor(
                self._io_pool, tiermod.sweep_remote, self.tiers, sweep_ud
            )

    def _parse_copy_source(self, request, access_key: str) -> tuple[str, str, str]:
        """Parse x-amz-copy-source and AUTHORIZE the read on it — the
        destination PutObject grant must not leak other buckets (or IAM
        records under .minio.sys) through the copy path."""
        src = urllib.parse.unquote(request.headers["x-amz-copy-source"])
        if src.startswith("/"):
            src = src[1:]
        src_vid = ""
        if "?versionId=" in src:
            src, src_vid = src.split("?versionId=", 1)
        if "/" not in src:
            raise s3err.InvalidArgument
        src_bucket, src_key = src.split("/", 1)
        if src_bucket.startswith(".minio.sys") or not src_key:
            raise s3err.AccessDenied
        src_key = listing.encode_dir_object(src_key)
        action = "s3:GetObjectVersion" if src_vid else "s3:GetObject"
        self._authorize(access_key, action, src_bucket, src_key)
        return src_bucket, src_key, src_vid

    def _check_copy_preconditions(self, request, oi: ObjectInfo) -> None:
        self._eval_preconditions(
            request.headers, oi, "x-amz-copy-source-", s3err.PreconditionFailed
        )

    async def copy_object(self, request, bucket: str, key: str) -> web.Response:
        from ..crypto.sse import CryptoError
        from . import transforms

        src_bucket, src_key, src_vid = self._parse_copy_source(
            request, request.get("access_key", "")
        )
        oi, handle = await self._run(
            self.store.open_object, src_bucket, src_key, src_vid
        )
        from .transforms import logical_size as _logical

        try:
            # pre-read failures (412, quota) must release the source
            # namespace read lock immediately, not wait out the lock TTL
            self._check_copy_preconditions(request, oi)
            self._enforce_quota(bucket, _logical(oi.user_defined, oi.size))
            data = await self._run(lambda: b"".join(handle.read(0, -1)))
        finally:
            handle.close()
        req_headers = {k.lower(): v for k, v in request.headers.items()}
        # decode the SOURCE pipeline: sealed keys are bound to the source
        # bucket/key context and must never be copied verbatim
        if transforms.is_transformed(oi.user_defined):
            src_headers = dict(req_headers)
            # SSE-C sources present their key under the copy-source header set
            from ..crypto import sse as ssemod

            for h in ("algorithm", "key", "key-md5"):
                v = req_headers.get(
                    f"x-amz-copy-source-server-side-encryption-customer-{h}"
                )
                if v:
                    src_headers[
                        f"x-amz-server-side-encryption-customer-{h}"
                    ] = v
            try:
                data = await self._run(
                    transforms.decode_full, data, oi.user_defined, src_headers,
                    src_bucket, src_key, self.kms,
                )
            except CryptoError:
                raise s3err.AccessDenied from None
        directive = request.headers.get("x-amz-metadata-directive", "COPY")
        # copying an object onto itself without changing anything is an
        # error (reference cmd/object-handlers.go isTargetSameAsSource):
        # REPLACE directives, new SSE attributes, or a storage-class change
        # make it a legal metadata update
        if (
            src_bucket == bucket
            and src_key == listing.encode_dir_object(key)
            and not src_vid
            and directive != "REPLACE"
            and request.headers.get("x-amz-tagging-directive", "COPY") != "REPLACE"
            and not request.headers.get("x-amz-server-side-encryption")
            and not request.headers.get(
                "x-amz-server-side-encryption-customer-algorithm"
            )
            and not request.headers.get("x-amz-storage-class")
        ):
            raise s3err.InvalidCopyDest
        user_defined = {
            k: v for k, v in oi.user_defined.items()
            if not k.startswith("x-minio-internal-")
        }
        user_defined["content-type"] = oi.content_type
        if directive == "REPLACE":
            user_defined = {
                k.lower(): v
                for k, v in request.headers.items()
                if k.lower().startswith("x-amz-meta-")
            }
            if request.headers.get("Content-Type"):
                user_defined["content-type"] = request.headers["Content-Type"]
        # tag set travels by its OWN directive, independent of metadata
        # (reference: x-amz-tagging-directive on CopyObject)
        if request.headers.get("x-amz-tagging-directive", "COPY") == "REPLACE":
            user_defined.pop(self.TAGS_META, None)
            if request.headers.get("x-amz-tagging"):
                user_defined[self.TAGS_META] = self._tagging_header_meta(
                    request.headers["x-amz-tagging"]
                )
        elif oi.user_defined.get(self.TAGS_META):
            user_defined[self.TAGS_META] = oi.user_defined[self.TAGS_META]
        bm = self.buckets.get(bucket)
        # re-encode for the destination (its SSE headers / bucket default)
        try:
            tr = transforms.encode_for_store(
                data, key, user_defined.get("content-type", ""), req_headers,
                _bucket_sse_algo(bm.encryption), self.kms, bucket,
            )
        except CryptoError:
            raise s3err.InvalidArgument from None
        if tr.metadata:
            user_defined.update(tr.metadata)
            data = tr.data
        new_oi = await self._run(
            self.store.put_object,
            bucket,
            listing.encode_dir_object(key),
            data,
            user_defined,
            None,
            bm.versioning,
        )
        xml = (
            '<?xml version="1.0" encoding="UTF-8"?>'
            f'<CopyObjectResult><ETag>"{new_oi.etag}"</ETag>'
            f"<LastModified>{_iso8601(new_oi.mod_time)}</LastModified></CopyObjectResult>"
        )
        headers = {}
        if new_oi.version_id:
            headers["x-amz-version-id"] = new_oi.version_id
        from ..events import notify as ev

        self.notifier.notify(
            ev.OBJECT_CREATED_COPY, bucket, listing.decode_dir_object(key),
            new_oi.size, new_oi.etag, new_oi.version_id,
        )
        self._queue_repl(request, 
            bucket, listing.encode_dir_object(key), new_oi.version_id, "put"
        )
        return web.Response(body=xml.encode(), content_type="application/xml", headers=headers)

    def _parse_range(self, request, size: int) -> tuple[int, int] | None:
        rng = request.headers.get("Range")
        if not rng or not rng.startswith("bytes="):
            return None
        request["_range_object_size"] = size  # for the 416 Content-Range
        spec = rng[len("bytes=") :]
        if "," in spec:
            raise s3err.NotImplemented_
        start_s, _, end_s = spec.partition("-")
        try:
            if start_s == "":
                n = int(end_s)
                if n == 0:
                    raise s3err.InvalidRange
                start = max(size - n, 0)
                end = size - 1
            else:
                start = int(start_s)
                end = int(end_s) if end_s else size - 1
        except ValueError:
            return None  # malformed range is ignored per RFC
        if start >= size or start > end:
            raise s3err.InvalidRange
        return start, min(end, size - 1)

    async def get_object(self, request, bucket: str, key: str) -> web.StreamResponse:
        key = listing.encode_dir_object(key)
        vid = request.rel_url.query.get("versionId", "")
        if vid == "null":
            vid = ""
        try:
            oi, handle = await self._run(self.store.open_object, bucket, key, vid)
        except (quorum.ObjectNotFound, quorum.VersionNotFound):
            # not (yet) here: replication lag in an active-active pair —
            # proxy the read to a remote target rather than 404ing
            # (reference cmd/bucket-replication.go:2334 proxyGetToReplicationTarget)
            resp = await self._proxy_get_remote(request, bucket, key, vid)
            if resp is not None:
                return resp
            raise
        from ..ilm import tier as tiermod
        from . import transforms

        if tiermod.is_transitioned(oi.user_defined) and not _restored_locally(oi):
            handle.close()
            return await self._get_from_tier(request, bucket, key, oi)
        if transforms.is_transformed(oi.user_defined):
            return await self._get_transformed(request, bucket, key, oi, handle)
        try:
            self._check_preconditions(request, oi)
            rng = self._parse_range(request, oi.size) if oi.size else None
            headers = self._obj_headers(oi)
            if rng:
                start, end = rng
                it = handle.read(start, end - start + 1)
                headers["Content-Range"] = f"bytes {start}-{end}/{oi.size}"
                resp = web.StreamResponse(status=206, headers=headers)
                resp.content_length = end - start + 1
            else:
                it = handle.read()
                resp = web.StreamResponse(status=200, headers=headers)
                resp.content_length = oi.size
        except BaseException:
            handle.close()  # preconditions/range failures must not leak the rlock
            raise
        await resp.prepare(request)
        loop = asyncio.get_running_loop()
        sentinel = object()
        nxt = lambda: next(it, sentinel)  # noqa: E731
        try:
            while True:
                chunk = await loop.run_in_executor(self._io_pool, nxt)
                if chunk is sentinel:
                    break
                await resp.write(chunk)
        finally:
            handle.close()  # release the namespace read lock promptly
        await resp.write_eof()
        return resp

    async def get_object_attributes(self, request, bucket, key) -> web.Response:
        """GetObjectAttributes (reference cmd/object-handlers.go:988):
        ETag/Checksum/ObjectParts/StorageClass/ObjectSize, filtered by the
        x-amz-object-attributes header."""
        import json as _json

        from ..utils import checksum as _cks

        key = listing.encode_dir_object(key)
        vid = request.rel_url.query.get("versionId", "")
        if vid == "null":
            vid = ""
        want = {
            a.strip() for a in
            request.headers.get("x-amz-object-attributes", "").split(",") if a.strip()
        }
        if not want:
            raise s3err.InvalidArgument
        try:
            oi = await self._run(self.store.get_object_info, bucket, key, vid)
        except (quorum.ObjectNotFound, quorum.VersionNotFound):
            raise s3err.NoSuchKey from None
        if oi.delete_marker:
            raise s3err.NoSuchKey
        self._check_preconditions(request, oi)
        from . import transforms
        from ..ilm import tier as tiermod

        parts_xml = ""
        if "ObjectParts" in want:
            stored = oi.user_defined.get(_cks.PART_CHECKSUMS_META)
            per_part = _json.loads(stored) if stored else {}
            if "-" in oi.etag:  # multipart object
                try:
                    max_parts = int(
                        request.rel_url.query.get("max-parts", "1000") or 1000
                    )
                    marker = int(
                        request.rel_url.query.get("part-number-marker", "0") or 0
                    )
                except ValueError:
                    raise s3err.InvalidArgument from None
                nparts = int(oi.etag.rsplit("-", 1)[-1])
                body_parts = []
                emitted = 0
                for pn in range(1, nparts + 1):
                    if pn <= marker:
                        continue
                    if emitted >= max_parts:
                        break
                    cx = "".join(
                        f"<Checksum{a.upper()}>{escape(v)}</Checksum{a.upper()}>"
                        for a, v in per_part.get(str(pn), {}).items()
                    )
                    body_parts.append(f"<Part><PartNumber>{pn}</PartNumber>{cx}</Part>")
                    emitted += 1
                parts_xml = (
                    f"<ObjectParts><TotalPartsCount>{nparts}</TotalPartsCount>"
                    f"<PartNumberMarker>{marker}</PartNumberMarker>"
                    f"<MaxParts>{max_parts}</MaxParts>"
                    f"<IsTruncated>{'true' if marker + emitted < nparts else 'false'}"
                    f"</IsTruncated>" + "".join(body_parts) + "</ObjectParts>"
                )
        cks_xml = ""
        if "Checksum" in want:
            fields = []
            for algo in _cks.ALGOS:
                v = oi.user_defined.get(f"{_cks.META_PREFIX}{algo}")
                if v:
                    tag = "Checksum" + algo.upper()
                    fields.append(f"<{tag}>{escape(v)}</{tag}>")
            if fields:
                cks_xml = "<Checksum>" + "".join(fields) + "</Checksum>"
        etag_xml = f"<ETag>{escape(oi.etag)}</ETag>" if "ETag" in want else ""
        size_xml = (
            f"<ObjectSize>{transforms.logical_size(oi.user_defined, oi.size)}"
            "</ObjectSize>" if "ObjectSize" in want else ""
        )
        sc = oi.user_defined.get(tiermod.TRANSITION_TIER_META) or \
            oi.user_defined.get("x-amz-storage-class", "STANDARD")
        sc_xml = (
            f"<StorageClass>{escape(sc)}</StorageClass>"
            if "StorageClass" in want else ""
        )
        xml = (
            '<?xml version="1.0" encoding="UTF-8"?>'
            '<GetObjectAttributesResponse xmlns='
            '"http://s3.amazonaws.com/doc/2006-03-01/">'
            + etag_xml + cks_xml + parts_xml + sc_xml + size_xml
            + "</GetObjectAttributesResponse>"
        )
        headers = {"Last-Modified": _http_date(oi.mod_time)}
        if oi.version_id:
            headers["x-amz-version-id"] = oi.version_id
        return web.Response(
            body=xml.encode(), content_type="application/xml", headers=headers
        )

    async def _get_transformed(self, request, bucket, key, oi, handle) -> web.Response:
        """GET for compressed/encrypted objects: decode through the
        transform pipeline (ranges map to packets for SSE-only)."""
        from ..crypto.sse import CryptoError
        from . import transforms

        try:
            self._check_preconditions(request, oi)
            logical = transforms.logical_size(oi.user_defined, oi.size)
            rng = self._parse_range(request, logical) if logical else None
            req_headers = {k.lower(): v for k, v in request.headers.items()}

            def read_fn(off, ln):
                # multiple per-part range reads over ONE handle: the outer
                # finally owns the close, each read must keep the lock
                return b"".join(handle.read(off, ln, close_when_done=False))

            def decode():
                if rng:
                    start, end = rng
                    return transforms.decode_range(
                        read_fn, oi.size, oi.user_defined, req_headers,
                        bucket, key, self.kms, start, end - start + 1,
                    )
                return transforms.decode_full(
                    read_fn(0, oi.size), oi.user_defined, req_headers,
                    bucket, key, self.kms,
                )

            try:
                data = await self._run(decode)
            except CryptoError:
                raise s3err.AccessDenied from None
            headers = self._obj_headers(oi)
            if rng:
                start, end = rng
                headers["Content-Range"] = f"bytes {start}-{end}/{logical}"
                return web.Response(status=206, headers=headers, body=data)
            return web.Response(status=200, headers=headers, body=data)
        finally:
            handle.close()

    async def head_object(self, request, bucket: str, key: str) -> web.Response:
        key = listing.encode_dir_object(key)
        vid = request.rel_url.query.get("versionId", "")
        if vid == "null":
            vid = ""
        oi = await self._run(self.store.get_object_info, bucket, key, vid)
        if oi.delete_marker:
            return web.Response(status=405, headers={"x-amz-delete-marker": "true"})
        self._check_preconditions(request, oi)
        from . import transforms

        headers = self._obj_headers(oi)
        headers["Content-Length"] = str(transforms.logical_size(oi.user_defined, oi.size))
        return web.Response(status=200, headers=headers)

    async def delete_object(self, request, bucket: str, key: str) -> web.Response:
        key = listing.encode_dir_object(key)
        vid = request.rel_url.query.get("versionId", "")
        if vid == "null":
            vid = ""
        bm = self.buckets.get(bucket)
        headers = {}
        await self._run(
            self._check_object_lock, bucket, key, vid,
            # the IAM resource must use the CLIENT's key form, matching the
            # raw key the multi-delete path passes
            self._bypass_governance(
                request, bucket, listing.decode_dir_object(key)
            ),
        )
        # deleting a version (or the sole unversioned copy) of a
        # transitioned object must sweep its warm-tier data (tier GC)
        sweep_ud = None
        if vid or not bm.versioning:
            sweep_ud = await self._run(self._tier_sweep_snapshot, bucket, key, vid)
        try:
            oi = await self._run(
                self.store.delete_object, bucket, key, vid, bm.versioning
            )
            if not oi.delete_marker:
                await self._tier_sweep(sweep_ud)
            if oi.delete_marker:
                headers["x-amz-delete-marker"] = "true"
            if oi.version_id:
                headers["x-amz-version-id"] = oi.version_id
            from ..events import notify as ev

            self.notifier.notify(
                ev.OBJECT_REMOVED_MARKER if oi.delete_marker else ev.OBJECT_REMOVED_DELETE,
                bucket, listing.decode_dir_object(key),
                version_id=oi.version_id, user=request.get("access_key", ""),
            )
            if not vid:
                # only logical deletes replicate; removing a SPECIFIC old
                # version must never delete the replica's live object
                self._queue_repl(request, bucket, key, "", "delete")
        except (quorum.ObjectNotFound, quorum.VersionNotFound):
            pass  # S3 deletes are idempotent
        return web.Response(status=204, headers=headers)

    async def delete_multiple(self, request, bucket: str, body: bytes) -> web.Response:
        try:
            root = ET.fromstring(body)
        except ET.ParseError:
            raise s3err.MalformedXML from None
        quiet = False
        targets = []
        for el in root:
            tag = el.tag.split("}")[-1]
            if tag == "Quiet":
                quiet = (el.text or "").lower() == "true"
            elif tag == "Object":
                k, v = "", ""
                for sub in el:
                    stag = sub.tag.split("}")[-1]
                    if stag == "Key":
                        k = sub.text or ""
                    elif stag == "VersionId":
                        v = sub.text or ""
                targets.append((k, v))
        bm = self.buckets.get(bucket)
        ak = request.get("access_key", "")
        results = []
        for k, v in targets[:1000]:
            # per-object authorization: a Deny on a key prefix must hold
            # through multi-delete exactly as through single DELETE
            try:
                self._authorize(
                    ak,
                    "s3:DeleteObjectVersion" if v else "s3:DeleteObject",
                    bucket,
                    k,
                )
            except s3err.APIError:
                results.append((k, v, s3err.AccessDenied, None))
                continue
            try:
                # retention/legal hold protects versions through
                # multi-delete exactly as through single DELETE
                # (including the governance-bypass header)
                await self._run(
                    self._check_object_lock, bucket,
                    listing.encode_dir_object(k), "" if v == "null" else v,
                    self._bypass_governance(request, bucket, k),
                )
                vv = "" if v == "null" else v
                sweep_ud = None
                if vv or not bm.versioning:  # this delete removes data
                    sweep_ud = await self._run(
                        self._tier_sweep_snapshot, bucket,
                        listing.encode_dir_object(k), vv,
                    )
                oi = await self._run(
                    self.store.delete_object,
                    bucket,
                    listing.encode_dir_object(k),
                    vv,
                    bm.versioning,
                )
                if not oi.delete_marker:
                    await self._tier_sweep(sweep_ud)
                results.append((k, v, None, oi))
            except (quorum.ObjectNotFound, quorum.VersionNotFound):
                results.append((k, v, None, None))
            except s3err.APIError as e:
                results.append((k, v, e, None))  # e.g. retention AccessDenied
            except Exception:  # noqa: BLE001
                results.append((k, v, s3err.InternalError, None))
        parts = []
        for k, v, err, oi in results:
            if err is None:
                if not quiet:
                    e = f"<Deleted><Key>{escape(k)}</Key>"
                    if v:
                        e += f"<VersionId>{escape(v)}</VersionId>"
                    if oi is not None and oi.delete_marker and oi.version_id:
                        e += f"<DeleteMarker>true</DeleteMarker><DeleteMarkerVersionId>{oi.version_id}</DeleteMarkerVersionId>"
                    parts.append(e + "</Deleted>")
            else:
                parts.append(
                    f"<Error><Key>{escape(k)}</Key><Code>{err.code}</Code>"
                    f"<Message>{escape(err.description)}</Message></Error>"
                )
        xml = (
            '<?xml version="1.0" encoding="UTF-8"?>'
            '<DeleteResult xmlns="http://s3.amazonaws.com/doc/2006-03-01/">'
            f"{''.join(parts)}</DeleteResult>"
        )
        return web.Response(body=xml.encode(), content_type="application/xml")

    # -- multipart -------------------------------------------------------------

    async def new_multipart(self, request, bucket, key) -> web.Response:
        from ..crypto.sse import CryptoError
        from . import transforms

        bm = self.buckets.get(bucket)
        key = listing.encode_dir_object(key)
        user_defined = {}
        if request.headers.get("Content-Type"):
            user_defined["content-type"] = request.headers["Content-Type"]
        for k, v in request.headers.items():
            if k.lower().startswith("x-amz-meta-"):
                user_defined[k.lower()] = v
        if request.headers.get("x-amz-tagging"):
            user_defined[self.TAGS_META] = self._tagging_header_meta(
                request.headers["x-amz-tagging"]
            )
        sse_resp: dict[str, str] = {}
        try:
            req_headers = {k.lower(): v for k, v in request.headers.items()}
            sse = transforms.multipart_sse_init(
                req_headers, _bucket_sse_algo(bm.encryption), self.kms,
                bucket, key,
            )
        except CryptoError:
            # SSE-C multipart needs the customer key on every part read —
            # refuse loudly rather than silently storing plaintext
            raise s3err.NotImplemented_ from None
        if sse is not None:
            sse_meta, sse_resp = sse
            user_defined.update(sse_meta)
        upload_id = await self._run(
            self.mp.new_upload, bucket, key, user_defined,
            self._parity_for_storage_class(request)
        )
        xml = (
            '<?xml version="1.0" encoding="UTF-8"?>'
            '<InitiateMultipartUploadResult xmlns="http://s3.amazonaws.com/doc/2006-03-01/">'
            f"<Bucket>{escape(bucket)}</Bucket><Key>{escape(key)}</Key>"
            f"<UploadId>{upload_id}</UploadId></InitiateMultipartUploadResult>"
        )
        return web.Response(
            body=xml.encode(), content_type="application/xml", headers=sse_resp
        )

    async def put_object_part(self, request, bucket, key, body) -> web.Response:
        from ..erasure import multipart as mp_mod

        key = listing.encode_dir_object(key)
        q = request.rel_url.query
        try:
            part_number = int(q["partNumber"])
        except (KeyError, ValueError):
            raise s3err.InvalidArgument from None
        upload_id = q.get("uploadId", "")
        self._enforce_quota(bucket, self._incoming_size(request, body))
        try:
            if body is None:
                # streaming part upload (multipart is how huge objects
                # arrive: each part flows straight into its erasure stream)
                etag = await self._run_streaming_put(
                    request,
                    lambda rd: self.mp.put_part(
                        bucket, key, upload_id, part_number, rd
                    ),
                )
                tr = request.get("trailer_checksum_meta")
                if tr:
                    await self._run(
                        self.mp.update_part_metadata, bucket, key,
                        upload_id, part_number, tr,
                    )
            else:
                checksum_meta = _verify_checksum_headers(request.headers, body)
                checksum_meta.update(request.get("trailer_checksum_meta") or {})
                etag = await self._run(
                    self.mp.put_part, bucket, key, upload_id, part_number, body,
                    checksum_meta or None,
                )
        except mp_mod.UploadNotFound:
            raise s3err.NoSuchUpload from None
        except mp_mod.InvalidPart:
            raise s3err.InvalidPart from None
        headers = {"ETag": f'"{etag}"'}
        for hk in request.headers:
            if hk.lower().startswith("x-amz-checksum-"):
                headers[hk] = request.headers[hk]
        # trailer-mode uploads carry the checksum in the trailer, not a
        # header: echo the VERIFIED value so SDK response validation sees it
        from ..utils import checksum as _cks

        for mk, mv in (request.get("trailer_checksum_meta") or {}).items():
            algo = mk[len(_cks.META_PREFIX):]
            headers.setdefault(f"x-amz-checksum-{algo}", mv)
        return web.Response(status=200, headers=headers)

    async def upload_part_copy(self, request, bucket, key) -> web.Response:
        from ..erasure import multipart as mp_mod

        key = listing.encode_dir_object(key)
        q = request.rel_url.query
        try:
            part_number = int(q["partNumber"])
        except (KeyError, ValueError):
            raise s3err.InvalidArgument from None
        upload_id = q.get("uploadId", "")
        src_bucket, src_key, src_vid = self._parse_copy_source(
            request, request.get("access_key", "")
        )
        oi, handle = await self._run(
            self.store.open_object, src_bucket, src_key, src_vid
        )
        from . import transforms

        try:
            # any pre-read failure (412, quota) must release the source
            # namespace read lock, not wait out the 120s TTL
            self._check_copy_preconditions(request, oi)
            self._enforce_quota(
                bucket, transforms.logical_size(oi.user_defined, oi.size)
            )
            # transformed (SSE/compressed) sources must decode to logical
            # bytes: ranges apply to plaintext, and the destination part
            # re-transforms for its own upload
            logical = transforms.logical_size(oi.user_defined, oi.size)
            offset, length = 0, logical
            crange = request.headers.get("x-amz-copy-source-range", "")
            if crange.startswith("bytes="):
                try:
                    a, _, b = crange[len("bytes=") :].partition("-")
                    offset = int(a)
                    length = int(b) - offset + 1
                except ValueError:
                    raise s3err.InvalidArgument from None
                if offset < 0 or length <= 0 or offset + length > logical:
                    raise s3err.InvalidRange
            if transforms.is_transformed(oi.user_defined):
                req_headers = {k.lower(): v for k, v in request.headers.items()}

                def read_fn(off, ln):
                    return b"".join(handle.read(off, ln, close_when_done=False))

                data = await self._run(
                    transforms.decode_range, read_fn, oi.size,
                    oi.user_defined, req_headers, src_bucket, src_key,
                    self.kms, offset, length,
                )
            else:
                data = await self._run(
                    lambda: b"".join(handle.read(offset, length))
                )
        finally:
            handle.close()
        try:
            etag = await self._run(
                self.mp.put_part, bucket, key, upload_id, part_number, data
            )
        except mp_mod.UploadNotFound:
            raise s3err.NoSuchUpload from None
        xml = (
            '<?xml version="1.0" encoding="UTF-8"?>'
            f'<CopyPartResult><ETag>"{etag}"</ETag>'
            f"<LastModified>{_iso8601(oi.mod_time)}</LastModified></CopyPartResult>"
        )
        return web.Response(body=xml.encode(), content_type="application/xml")

    async def complete_multipart(self, request, bucket, key, body) -> web.Response:
        from ..erasure import multipart as mp_mod

        key = listing.encode_dir_object(key)
        upload_id = request.rel_url.query.get("uploadId", "")
        try:
            root = ET.fromstring(body)
        except ET.ParseError:
            raise s3err.MalformedXML from None
        parts = []
        part_checksums: dict[int, dict[str, str]] = {}
        for el in root:
            if el.tag.split("}")[-1] == "Part":
                n, etag = 0, ""
                cks_vals: dict[str, str] = {}
                for sub in el:
                    t = sub.tag.split("}")[-1]
                    if t == "PartNumber":
                        n = int(sub.text or "0")
                    elif t == "ETag":
                        etag = (sub.text or "").strip()
                    elif t.startswith("Checksum"):
                        cks_vals[t[len("Checksum"):].lower()] = (sub.text or "").strip()
                parts.append((n, etag))
                if cks_vals:
                    part_checksums[n] = cks_vals
        bm = self.buckets.get(bucket)
        try:
            oi = await self._run(
                self.mp.complete, bucket, key, upload_id, parts, bm.versioning,
                part_checksums or None, self._put_precond(request),
            )
        except mp_mod.UploadNotFound:
            raise s3err.NoSuchUpload from None
        except mp_mod.InvalidPartOrder:
            raise s3err.InvalidPartOrder from None
        except mp_mod.InvalidPart:
            raise s3err.InvalidPart from None
        xml = (
            '<?xml version="1.0" encoding="UTF-8"?>'
            '<CompleteMultipartUploadResult xmlns="http://s3.amazonaws.com/doc/2006-03-01/">'
            f"<Location>/{escape(bucket)}/{escape(key)}</Location>"
            f"<Bucket>{escape(bucket)}</Bucket><Key>{escape(key)}</Key>"
            f'<ETag>"{oi.etag}"</ETag></CompleteMultipartUploadResult>'
        )
        headers = {}
        if oi.version_id:
            headers["x-amz-version-id"] = oi.version_id
        from ..events import notify as ev

        self.notifier.notify(
            ev.OBJECT_CREATED_MULTIPART, bucket, listing.decode_dir_object(key),
            oi.size, oi.etag, oi.version_id, request.get("access_key", ""),
        )
        self._queue_repl(request, bucket, key, oi.version_id, "put")
        return web.Response(body=xml.encode(), content_type="application/xml", headers=headers)

    async def abort_multipart(self, request, bucket, key) -> web.Response:
        from ..erasure import multipart as mp_mod

        key = listing.encode_dir_object(key)
        upload_id = request.rel_url.query.get("uploadId", "")
        try:
            await self._run(self.mp.abort, bucket, key, upload_id)
        except mp_mod.UploadNotFound:
            raise s3err.NoSuchUpload from None
        return web.Response(status=204)

    async def list_parts(self, request, bucket, key) -> web.Response:
        from ..erasure import multipart as mp_mod

        key = listing.encode_dir_object(key)
        q = request.rel_url.query
        upload_id = q.get("uploadId", "")
        try:
            max_parts = int(q.get("max-parts", "1000"))
            marker = int(q.get("part-number-marker", "0"))
        except ValueError:
            raise s3err.InvalidArgument from None
        if max_parts < 0 or marker < 0:
            raise s3err.InvalidArgument
        max_parts = min(max_parts, 1000)
        try:
            parts, truncated = await self._run(
                self.mp.list_parts, bucket, key, upload_id, max_parts, marker
            )
        except mp_mod.UploadNotFound:
            raise s3err.NoSuchUpload from None
        items = "".join(
            f"<Part><PartNumber>{p.number}</PartNumber>"
            f'<ETag>"{p.etag}"</ETag><Size>{p.size}</Size>'
            f"<LastModified>{_iso8601(p.mod_time)}</LastModified></Part>"
            for p in parts
        )
        next_marker = (
            f"<NextPartNumberMarker>{parts[-1].number}</NextPartNumberMarker>"
            if truncated and parts
            else ""
        )
        xml = (
            '<?xml version="1.0" encoding="UTF-8"?>'
            '<ListPartsResult xmlns="http://s3.amazonaws.com/doc/2006-03-01/">'
            f"<Bucket>{escape(bucket)}</Bucket><Key>{escape(key)}</Key>"
            f"<UploadId>{upload_id}</UploadId><MaxParts>{max_parts}</MaxParts>"
            f"<PartNumberMarker>{marker}</PartNumberMarker>{next_marker}"
            f"<IsTruncated>{'true' if truncated else 'false'}</IsTruncated>"
            f"{items}</ListPartsResult>"
        )
        return web.Response(body=xml.encode(), content_type="application/xml")

    def _health(self, request, key: str) -> web.Response:
        """Liveness/readiness/cluster health
        (reference cmd/healthcheck-handler.go)."""
        if key == "health/live":
            return web.Response(status=200)
        if key in ("health/ready", "health/cluster"):
            if self.store is None:
                return web.Response(status=503)
            if key == "health/cluster":
                online = 0
                for d in self.store.disks:
                    try:
                        d.disk_info()
                        online += 1
                    except Exception:  # noqa: BLE001
                        pass
                quorum = len(self.store.disks) // 2 + 1
                if online < quorum:
                    return web.Response(
                        status=503, headers={"X-Minio-Write-Quorum": str(quorum)}
                    )
            return web.Response(status=200)
        return web.Response(status=404)

    async def get_object_lambda(self, request, bucket, key) -> web.Response:
        """Object lambda: transform a GET through a user webhook
        (reference cmd/object-lambda-handlers.go). Targets come from
        MINIO_LAMBDA_WEBHOOK_ENABLE_<ID>/..._ENDPOINT_<ID>."""
        import base64
        import urllib.request as _ur

        arn = request.rel_url.query.get("lambdaArn", "")
        ident = arn.rsplit(":", 2)[-2] if arn.count(":") >= 2 else arn
        endpoint = os.environ.get(f"MINIO_LAMBDA_WEBHOOK_ENDPOINT_{ident.upper()}", "")
        enabled = os.environ.get(
            f"MINIO_LAMBDA_WEBHOOK_ENABLE_{ident.upper()}", ""
        ) in ("on", "true", "1")
        if not endpoint or not enabled:
            raise s3err.InvalidArgument
        key_enc = listing.encode_dir_object(key)
        oi, it = await self._run(self.store.get_object, bucket, key_enc)
        payload = {
            "getObjectContext": {
                "inputS3Url": f"/{bucket}/{key}",
                "bucket": bucket,
                "key": key,
                "content": base64.b64encode(b"".join(it)).decode(),
            },
            "userRequest": {"headers": dict(request.headers)},
        }
        import json as _json

        def call():
            req = _ur.Request(
                endpoint, data=_json.dumps(payload).encode(),
                headers={"Content-Type": "application/json"},
            )
            return _ur.urlopen(req, timeout=30).read()

        try:
            out = await self._run(call)
        except Exception:  # noqa: BLE001
            raise s3err.InternalError from None
        try:
            body = base64.b64decode(_json.loads(out)["content"])
        except (ValueError, KeyError):
            body = out  # raw transformed bytes are accepted too
        return web.Response(body=body, content_type=oi.content_type)

    async def post_policy_upload(self, request, bucket: str, body: bytes) -> web.Response:
        """POST object (browser form upload) with V4 POST-policy signature
        (reference cmd/post-policy.go)."""
        import base64
        import hmac as _hmac
        import json as _json

        ctype = request.headers.get("Content-Type", "")
        if "boundary=" not in ctype:
            raise s3err.MalformedXML
        boundary = (
            ctype.split("boundary=", 1)[1].split(";", 1)[0].strip().strip('"').encode()
        )
        fields, file_data = _parse_form_data(body, boundary)
        key = fields.get("key", "")
        if not key:
            raise s3err.InvalidArgument
        if "${filename}" in key:
            key = key.replace("${filename}", fields.get("__filename", "upload"))

        policy_b64 = fields.get("policy", "")
        ak = ""
        if policy_b64:
            cred = fields.get("x-amz-credential", "")
            sig = fields.get("x-amz-signature", "")
            parts = cred.split("/")
            if len(parts) < 5 or parts[-1] != "aws4_request":
                raise s3err.AccessDenied
            ak = "/".join(parts[:-4])
            secret = self.iam.lookup_secret(ak)
            if secret is None:
                raise s3err.InvalidAccessKeyId
            skey = signature.signing_key(secret, parts[-4], parts[-3], parts[-2])
            want = _hmac.new(skey, policy_b64.encode(), hashlib.sha256).hexdigest()
            if not _hmac.compare_digest(want, sig):
                raise s3err.SignatureDoesNotMatch
            try:
                pol = _json.loads(base64.b64decode(policy_b64))
            except ValueError:
                raise s3err.AccessDenied from None
            import datetime as _dt

            exp = pol.get("expiration", "")
            if exp:
                try:
                    t = _dt.datetime.fromisoformat(exp.replace("Z", "+00:00"))
                except ValueError:
                    raise s3err.AccessDenied from None
                if _dt.datetime.now(_dt.timezone.utc) > t:
                    raise s3err.AccessDenied
            for cond in pol.get("conditions", []):
                if isinstance(cond, dict):
                    for ck, cv in cond.items():
                        if ck == "bucket" and cv != bucket:
                            raise s3err.AccessDenied
                        if ck == "key" and cv != key:
                            raise s3err.AccessDenied
                elif isinstance(cond, list) and len(cond) == 3:
                    op, name, val = cond
                    if str(op) == "content-length-range":
                        try:
                            lo, hi = int(name), int(val)
                        except (TypeError, ValueError):
                            raise s3err.AccessDenied from None
                        if not lo <= len(file_data) <= hi:
                            raise s3err.EntityTooLarge
                        continue
                    name = str(name).lstrip("$")
                    have = {"bucket": bucket, "key": key}.get(name, fields.get(name, ""))
                    if op == "eq" and have != val:
                        raise s3err.AccessDenied
                    if op == "starts-with" and not str(have).startswith(str(val)):
                        raise s3err.AccessDenied
        self._authorize(ak, "s3:PutObject", bucket, key)
        user_defined = {
            k: v for k, v in fields.items() if k.startswith("x-amz-meta-")
        }
        ct = fields.get("Content-Type") or fields.get("content-type") or ""
        if ct:
            user_defined["content-type"] = ct
        bm = self.buckets.get(bucket)
        # same pipeline as PUT: bucket-default SSE/compression apply here too
        from ..crypto.sse import CryptoError
        from . import transforms

        try:
            tr = transforms.encode_for_store(
                file_data, key, ct, {}, _bucket_sse_algo(bm.encryption),
                self.kms, bucket,
            )
        except CryptoError:
            raise s3err.InvalidArgument from None
        if tr.metadata:
            user_defined.update(tr.metadata)
            file_data = tr.data
        oi = await self._run(
            self.store.put_object, bucket, listing.encode_dir_object(key),
            file_data, user_defined, None, bm.versioning,
        )
        from ..events import notify as ev

        self.notifier.notify(
            "s3:ObjectCreated:Post", bucket, key, oi.size, oi.etag,
            oi.version_id, ak,
        )
        self._queue_repl(request, 
            bucket, listing.encode_dir_object(key), oi.version_id, "put"
        )
        try:
            status = int(fields.get("success_action_status", "204"))
        except ValueError:
            status = 204
        if status not in (200, 201, 204):
            status = 204
        headers = {"ETag": f'"{oi.etag}"'}
        if status == 201:
            xml = (
                '<?xml version="1.0" encoding="UTF-8"?>'
                f"<PostResponse><Bucket>{escape(bucket)}</Bucket>"
                f"<Key>{escape(key)}</Key><ETag>&quot;{oi.etag}&quot;</ETag>"
                "</PostResponse>"
            )
            return web.Response(
                status=201, body=xml.encode(), content_type="application/xml",
                headers=headers,
            )
        return web.Response(status=status, headers=headers)

    # -- object lock: retention + legal hold ----------------------------------

    RETENTION_META = "x-minio-internal-retention"  # "<mode>|<iso-until>"
    LEGALHOLD_META = "x-minio-internal-legalhold"

    def _require_lock_bucket(self, bucket: str) -> None:
        if not self.buckets.get(bucket).object_lock:
            raise s3err.InvalidArgument  # lock config required on bucket

    @staticmethod
    def _parse_retain_until(until: str):
        """Aware datetime or raises MalformedXML (naive/garbage dates must
        never be stored: they'd poison every later delete)."""
        import datetime as _dt

        try:
            t = _dt.datetime.fromisoformat(until.replace("Z", "+00:00"))
        except ValueError:
            raise s3err.MalformedXML from None
        if t.tzinfo is None:
            raise s3err.MalformedXML
        return t

    async def put_object_retention(self, request, bucket, key, body) -> web.Response:
        import datetime as _dt

        self._require_lock_bucket(bucket)
        key = listing.encode_dir_object(key)
        vid = request.rel_url.query.get("versionId", "")
        try:
            root = ET.fromstring(body)
            mode = until = ""
            for el in root.iter():
                if el.tag.endswith("Mode"):
                    mode = el.text or ""
                elif el.tag.endswith("RetainUntilDate"):
                    until = (el.text or "").strip()
            if mode not in ("GOVERNANCE", "COMPLIANCE") or not until:
                raise s3err.MalformedXML
        except ET.ParseError:
            raise s3err.MalformedXML from None
        new_until = self._parse_retain_until(until)
        # COMPLIANCE retention can never be shortened or weakened
        oi = await self._run(self.store.get_object_info, bucket, key, vid)
        existing = oi.user_defined.get(self.RETENTION_META, "")
        if existing:
            old_mode, old_until_s = existing.split("|", 1)
            try:
                old_until = self._parse_retain_until(old_until_s)
            except s3err.APIError:
                old_until = None
            if (
                old_mode == "COMPLIANCE"
                and old_until is not None
                and _dt.datetime.now(_dt.timezone.utc) < old_until
                and (mode != "COMPLIANCE" or new_until < old_until)
            ):
                raise s3err.AccessDenied
        val = "{}|{}".format(
            mode,
            new_until.astimezone(_dt.timezone.utc).strftime("%Y-%m-%dT%H:%M:%SZ"),
        )
        await self._run(
            self.store.update_object_metadata, bucket, key, vid,
            lambda md: md.__setitem__(self.RETENTION_META, val),
        )
        return web.Response(status=200)

    async def get_object_retention(self, request, bucket, key) -> web.Response:
        key = listing.encode_dir_object(key)
        vid = request.rel_url.query.get("versionId", "")
        oi = await self._run(self.store.get_object_info, bucket, key, vid)
        raw = oi.user_defined.get(self.RETENTION_META, "")
        if not raw:
            raise s3err.ObjectLockConfigurationNotFoundError
        mode, until = raw.split("|", 1)
        xml = (
            '<?xml version="1.0" encoding="UTF-8"?>'
            f"<Retention><Mode>{escape(mode)}</Mode>"
            f"<RetainUntilDate>{escape(until)}</RetainUntilDate></Retention>"
        )
        return web.Response(body=xml.encode(), content_type="application/xml")

    async def put_legal_hold(self, request, bucket, key, body) -> web.Response:
        self._require_lock_bucket(bucket)
        key = listing.encode_dir_object(key)
        vid = request.rel_url.query.get("versionId", "")
        try:
            root = ET.fromstring(body)
            status = ""
            for el in root.iter():
                if el.tag.endswith("Status"):
                    status = (el.text or "").strip()
        except ET.ParseError:
            raise s3err.MalformedXML from None
        if status not in ("ON", "OFF"):
            # malformed input must never silently CLEAR an active hold
            raise s3err.MalformedXML
        await self._run(
            self.store.update_object_metadata, bucket, key, vid,
            lambda md: md.__setitem__(self.LEGALHOLD_META, status),
        )
        return web.Response(status=200)

    async def get_legal_hold(self, request, bucket, key) -> web.Response:
        key = listing.encode_dir_object(key)
        vid = request.rel_url.query.get("versionId", "")
        oi = await self._run(self.store.get_object_info, bucket, key, vid)
        status = oi.user_defined.get(self.LEGALHOLD_META, "OFF")
        xml = (
            '<?xml version="1.0" encoding="UTF-8"?>'
            f"<LegalHold><Status>{status}</Status></LegalHold>"
        )
        return web.Response(body=xml.encode(), content_type="application/xml")

    def _check_object_lock(self, bucket: str, key: str, vid: str,
                           bypass_governance: bool = False) -> None:
        """Block data-destroying deletes while retention/legal hold is
        active (reference: enforceRetentionForDeletion). GOVERNANCE
        retention may be bypassed by a caller holding
        s3:BypassGovernanceRetention who sent the bypass header;
        COMPLIANCE and legal hold can never be bypassed."""
        if not vid:
            # on a VERSIONED bucket this only adds a marker; on an
            # unversioned one it destroys the latest version — guard it
            if self.buckets.get(bucket).versioning:
                return
        try:
            oi = self.store.get_object_info(bucket, key, vid)
        except Exception:  # noqa: BLE001 — missing version: nothing to guard
            return
        if oi.user_defined.get(self.LEGALHOLD_META) == "ON":
            raise s3err.AccessDenied
        raw = oi.user_defined.get(self.RETENTION_META, "")
        if raw:
            import datetime as _dt

            mode, until = raw.split("|", 1)
            if mode == "GOVERNANCE" and bypass_governance:
                return
            try:
                t = _dt.datetime.fromisoformat(until.replace("Z", "+00:00"))
            except ValueError:
                raise s3err.AccessDenied from None
            if t.tzinfo is None or _dt.datetime.now(_dt.timezone.utc) < t:
                raise s3err.AccessDenied

    def _bypass_governance(self, request, bucket: str, key: str) -> bool:
        """True iff the caller asked to bypass GOVERNANCE retention and
        holds s3:BypassGovernanceRetention (reference
        cmd/object-handlers.go x-amz-bypass-governance-retention)."""
        if request.headers.get(
            "x-amz-bypass-governance-retention", ""
        ).lower() != "true":
            return False
        ak = request.get("access_key", "")
        if not ak:
            return False
        return self.iam.is_allowed(
            ak, "s3:BypassGovernanceRetention", f"{bucket}/{key}"
        )

    # -- object tagging --------------------------------------------------------

    from ..erasure.set import TAGS_META_KEY as TAGS_META

    @staticmethod
    def _validate_tags(pairs) -> dict[str, str]:
        """Enforce the S3 tag-set rules on (key, value) pairs (reference
        pkg tags.ParseObjectTags): <=10 tags, unique keys, key 1-128
        chars, value <=256 chars."""
        if len(pairs) > 10:
            raise s3err.InvalidTag
        tags: dict[str, str] = {}
        for k, v in pairs:
            if not k or len(k) > 128 or len(v) > 256 or k in tags:
                raise s3err.InvalidTag
            tags[k] = v
        return tags

    @classmethod
    def _tagging_header_meta(cls, header_value: str) -> str:
        """x-amz-tagging header (urlencoded) -> validated stored form."""
        pairs = urllib.parse.parse_qsl(header_value, keep_blank_values=True)
        return urllib.parse.urlencode(cls._validate_tags(pairs))

    async def put_object_tagging(self, request, bucket, key, body) -> web.Response:
        key = listing.encode_dir_object(key)
        vid = request.rel_url.query.get("versionId", "")
        try:
            root = ET.fromstring(body)
        except ET.ParseError:
            raise s3err.MalformedXML from None
        pairs = []
        for el in root.iter():
            if el.tag.endswith("Tag"):
                k = v = ""
                for sub in el:
                    if sub.tag.endswith("Key"):
                        k = sub.text or ""
                    elif sub.tag.endswith("Value"):
                        v = sub.text or ""
                pairs.append((k, v))
        tags = self._validate_tags(pairs)
        await self._run(self.store.set_object_tags, bucket, key, tags, vid)
        return web.Response(status=200)

    async def get_object_tagging(self, request, bucket, key) -> web.Response:
        key = listing.encode_dir_object(key)
        vid = request.rel_url.query.get("versionId", "")
        tags = await self._run(self.store.get_object_tags, bucket, key, vid)
        items = "".join(
            f"<Tag><Key>{escape(k)}</Key><Value>{escape(v)}</Value></Tag>"
            for k, v in tags.items()
        )
        xml = (
            '<?xml version="1.0" encoding="UTF-8"?>'
            f"<Tagging><TagSet>{items}</TagSet></Tagging>"
        )
        return web.Response(body=xml.encode(), content_type="application/xml")

    async def delete_object_tagging(self, request, bucket, key) -> web.Response:
        key = listing.encode_dir_object(key)
        vid = request.rel_url.query.get("versionId", "")
        await self._run(self.store.set_object_tags, bucket, key, {}, vid)
        return web.Response(status=204)

    async def select_object_content(self, request, bucket, key, body) -> web.Response:
        """SelectObjectContent: SQL over CSV/JSON objects
        (reference cmd/object-handlers.go:105 + internal/s3select)."""
        from ..s3select import engine
        from . import transforms

        key = listing.encode_dir_object(key)
        oi, handle = await self._run(self.store.open_object, bucket, key, "")
        try:
            req_headers = {k.lower(): v for k, v in request.headers.items()}

            def load() -> bytes:
                raw = b"".join(handle.read())
                if transforms.is_transformed(oi.user_defined):
                    return transforms.decode_full(
                        raw, oi.user_defined, req_headers, bucket, key, self.kms
                    )
                return raw

            data = await self._run(load)
        finally:
            handle.close()
        try:
            stream = await self._run(engine.run_select, body, data)
        except engine.SelectError:
            raise s3err.InvalidArgument from None
        return web.Response(
            body=stream, content_type="application/octet-stream"
        )

    # -- admin helpers ---------------------------------------------------------

    def server_info(self) -> dict:
        from .admin import server_info_payload

        return server_info_payload(self)

    def storage_info(self) -> dict:
        from .admin import storage_info_payload

        return storage_info_payload(self)

    def heal_sweep(self, bucket: str = "", prefix: str = "") -> dict:
        """Synchronous heal sweep over bucket/prefix (admin heal trigger;
        the background scanner drives the same per-object heal)."""
        healed, scanned, failed = [], 0, 0
        buckets = [bucket] if bucket else [b.name for b in self.store.list_buckets()]
        for b in buckets:
            for raw in self.store.walk_objects(b, prefix):
                scanned += 1
                try:
                    res = self.store.heal_object(b, raw)
                    for ep in res.get("healed", []):
                        healed.append(f"{b}/{raw}@{ep}")
                except Exception:  # noqa: BLE001
                    failed += 1
        return {"scanned": scanned, "healed": healed, "failed": failed}

    async def list_multipart_uploads(self, request, bucket) -> web.Response:
        q = request.rel_url.query
        prefix = q.get("prefix", "")
        key_marker = q.get("key-marker", "")
        uid_marker = q.get("upload-id-marker", "")
        try:
            max_uploads = min(max(int(q.get("max-uploads", "1000")), 0), 1000)
        except ValueError:
            raise s3err.InvalidArgument from None
        if max_uploads == 0:
            # an empty page with no next marker cannot progress: report it
            # as NON-truncated (same discipline as ListParts max-parts=0)
            return web.Response(
                body=(
                    '<?xml version="1.0" encoding="UTF-8"?>'
                    '<ListMultipartUploadsResult xmlns="http://s3.amazonaws.com/doc/2006-03-01/">'
                    f"<Bucket>{escape(bucket)}</Bucket><Prefix>{escape(prefix)}</Prefix>"
                    "<MaxUploads>0</MaxUploads>"
                    "<IsTruncated>false</IsTruncated></ListMultipartUploadsResult>"
                ).encode(),
                content_type="application/xml",
            )
        uploads = sorted(await self._run(self.mp.list_uploads, bucket, prefix))
        if key_marker:
            # marker semantics (cmd/erasure-multipart.go ListMultipartUploads):
            # strictly after (key_marker, uid_marker)
            uploads = [
                (k, u) for k, u in uploads
                if k > key_marker or (k == key_marker and uid_marker and u > uid_marker)
            ]
        page = uploads[:max_uploads]
        truncated = len(uploads) > len(page)
        items = "".join(
            f"<Upload><Key>{escape(k)}</Key><UploadId>{uid}</UploadId></Upload>"
            for k, uid in page
        )
        next_markers = (
            f"<NextKeyMarker>{escape(page[-1][0])}</NextKeyMarker>"
            f"<NextUploadIdMarker>{page[-1][1]}</NextUploadIdMarker>"
            if truncated and page
            else ""
        )
        xml = (
            '<?xml version="1.0" encoding="UTF-8"?>'
            '<ListMultipartUploadsResult xmlns="http://s3.amazonaws.com/doc/2006-03-01/">'
            f"<Bucket>{escape(bucket)}</Bucket><Prefix>{escape(prefix)}</Prefix>"
            f"<KeyMarker>{escape(key_marker)}</KeyMarker>"
            f"<MaxUploads>{max_uploads}</MaxUploads>{next_markers}"
            f"<IsTruncated>{'true' if truncated else 'false'}</IsTruncated>"
            f"{items}</ListMultipartUploadsResult>"
        )
        return web.Response(body=xml.encode(), content_type="application/xml")


def make_object_layer(
    drive_specs: list[str],
    set_size: int = 0,
    my_port: int = 0,
    internode_token_value: str = "",
    local_drive_registry: dict[int, XLStorage] | None = None,
    ns_lock=None,
):
    """Build the full L3 topology from drive specs (ellipses expanded):
    endpoints -> local XLStorage / remote StorageRESTClient -> format.json
    bootstrap -> ErasureSets per pool -> ServerPools.

    Each spec is one pool (reference: each `minio server` arg group is a
    pool); 'path{0...15}' and 'http://host{1...2}:9000/d{1...4}' patterns
    expand to drives. All nodes pass identical specs; global drive indexes
    address remote drives (filled into local_drive_registry for the node's
    own storage RPC server).
    """
    from ..cluster.endpoint import parse_endpoint
    from ..cluster.storage_rest import StorageRESTClient
    from ..erasure.pools import ServerPools
    from ..erasure.sets import ErasureSets
    from ..storage.format_erasure import init_or_load_formats
    from ..storage.offline import OfflineDisk
    from ..utils import ellipses

    # args with ellipses each form a pool; bare dirs combine into one pool
    # (reference: each ellipses arg group is a serverPool)
    pool_specs: list[list[str]] = []
    bare: list[str] = []
    for spec in drive_specs:
        if ellipses.has_ellipses(spec):
            pool_specs.append(ellipses.expand(spec))
        else:
            bare.append(spec)
    if bare:
        pool_specs.insert(0, bare)

    # bootstrap-leader rule: only the node owning the very first endpoint
    # may mint a fresh cluster layout
    leader = parse_endpoint(pool_specs[0][0], my_port).is_local
    allow_mint = leader if local_drive_registry is not None else True

    pools = []
    global_idx = 0
    for pool_idx, paths in enumerate(pool_specs):
        disks = []
        any_local = False
        from ..storage.health import HealthCheckedDisk

        for p in paths:
            ep = parse_endpoint(p, my_port)
            if ep.is_local:
                d = XLStorage(ep.path, endpoint=p)
                if local_drive_registry is not None:
                    # the RPC server serves the RAW drive; health wrapping
                    # happens on the calling side
                    local_drive_registry[global_idx] = d
                any_local = True
            else:
                d = StorageRESTClient(
                    ep.host, ep.port, global_idx, internode_token_value, endpoint=p
                )
            # circuit breaker: a dead drive fails fast instead of adding
            # its timeout to every quorum operation
            disks.append(HealthCheckedDisk(d))
            global_idx += 1
        if not any_local and local_drive_registry is not None:
            raise ValueError(f"pool {pool_idx}: no local drives for this node")
        size = ellipses.choose_set_size(len(disks), set_size)
        dep_id, grouped = init_or_load_formats(disks, size, allow_mint=allow_mint)
        grouped = [
            [d if d is not None else OfflineDisk() for d in row] for row in grouped
        ]
        pools.append(
            ErasureSets(grouped, dep_id, pool_index=pool_idx, ns_lock=ns_lock)
        )
    return ServerPools(pools)


def make_server(
    drive_paths: list[str], region: str = "us-east-1", set_size: int = 0
) -> S3Server:
    return S3Server(make_object_layer(drive_paths, set_size), region)


def main(argv: list[str] | None = None) -> None:
    import argparse

    from ..cluster.endpoint import parse_endpoints, remote_nodes
    from ..cluster.locks import LocalLocker, LockRESTServer, NamespaceLock, _RemoteLocker
    from ..cluster.storage_rest import StorageRESTServer, internode_token
    from ..utils import ellipses

    ap = argparse.ArgumentParser(description="minio_tpu S3 server")
    ap.add_argument(
        "drives", nargs="+",
        help="drive dirs, ellipses patterns, or http://host:port/path "
        "endpoints; each ellipses arg is one pool",
    )
    ap.add_argument("--address", default="0.0.0.0:9000")
    ap.add_argument("--set-size", type=int, default=0, help="drives per erasure set")
    ap.add_argument("--ftp", type=int, default=0, help="FTP gateway port (0=off)")
    ap.add_argument("--sftp", type=int, default=0, help="SFTP gateway port (0=off)")
    ap.add_argument(
        "--certs-dir",
        default=os.environ.get("MINIO_TPU_CERTS_DIR", ""),
        help="directory with public.crt/private.key (+ CAs/); enables TLS "
        "for the listener and all internode planes when the pair exists",
    )
    args = ap.parse_args(argv)
    host, _, port = args.address.rpartition(":")
    my_port = int(port)

    # TLS: certs-dir with a keypair turns on https + wss everywhere, with
    # in-place hot reload (reference cmd/common-main.go:942 getTLSConfig)
    from ..crypto import tlsconf

    cert_mgr = None
    if args.certs_dir:
        have_cert = os.path.isfile(os.path.join(args.certs_dir, tlsconf.CERT_FILE))
        have_key = os.path.isfile(os.path.join(args.certs_dir, tlsconf.KEY_FILE))
        if have_cert and have_key:
            cert_mgr = tlsconf.GLOBAL.enable(args.certs_dir)
        elif have_cert or have_key:
            # half a keypair is a misconfiguration, not a plain-HTTP
            # deployment; refuse rather than silently serving cleartext
            raise SystemExit(
                f"certs-dir {args.certs_dir}: need BOTH {tlsconf.CERT_FILE} "
                f"and {tlsconf.KEY_FILE} (found only one)"
            )
        else:
            print(
                f"certs-dir {args.certs_dir}: no {tlsconf.CERT_FILE}/"
                f"{tlsconf.KEY_FILE}; serving plain HTTP", flush=True,
            )

    root_user = os.environ.get("MINIO_ROOT_USER", "minioadmin")
    root_pass = os.environ.get("MINIO_ROOT_PASSWORD", "minioadmin")
    token = internode_token(root_user, root_pass)

    all_eps = parse_endpoints(
        [p for spec in args.drives for p in ellipses.expand(spec)], my_port
    )
    peers = remote_nodes(all_eps)
    distributed = bool(peers)

    registry: dict[int, XLStorage] = {}
    local_locker = LocalLocker()
    lockers = [local_locker] + [
        _RemoteLocker(n.split(":")[0], int(n.split(":")[1]), token) for n in peers
    ]
    ns_lock = NamespaceLock(lockers)

    srv = S3Server(None)
    srv.peers = peers  # cluster peers, for admin profile/pprof fan-out
    from ..cluster.grid import GridServer

    storage_srv = StorageRESTServer(registry, token)
    lock_srv = LockRESTServer(local_locker, token)
    storage_srv.register(srv.app)
    lock_srv.register(srv.app)
    # muxed internode RPC: small storage ops + lock ops share one
    # websocket per (peer, plane); HTTP routes above stay as fallback
    grid = GridServer(token)
    storage_srv.register_grid(grid)
    lock_srv.register_grid(grid)
    grid.register(srv.app)
    from ..cluster import bootstrap as bootmod

    my_syscfg = bootmod.system_config(sorted(str(e) for e in all_eps), salt=token)
    bootmod.BootstrapRESTServer(my_syscfg, token).register(srv.app)

    async def bootstrap():
        import asyncio

        loop = asyncio.get_running_loop()

        def build():
            return make_object_layer(
                args.drives, args.set_size, my_port, token, registry, ns_lock
            )

        if peers:
            # cross-node config consistency check (reference
            # cmd/bootstrap-peer-server.go verifyServerSystemConfig):
            # catches divergent drive lists / MINIO_* env before serving
            problems = await loop.run_in_executor(
                None, bootmod.verify_peers, my_syscfg, peers, token
            )
            for p in problems:
                print(f"bootstrap config check: {p}", flush=True)

        last = None
        for _ in range(180):
            try:
                store = await loop.run_in_executor(None, build)
                # set_store does storage IO (IAM/bucket-config loads, incl.
                # remote RPC) — keep it off the event loop, which must stay
                # responsive for peers' storage/lock RPCs
                await loop.run_in_executor(None, srv.set_store, store)
                print(
                    f"object layer online: {len(store.pools)} pool(s), "
                    f"{len(store.disks)} drives, distributed={distributed}",
                    flush=True,
                )
                return
            except Exception as e:  # noqa: BLE001 — peers may still be booting
                last = e
                await asyncio.sleep(1)
        print(f"bootstrap failed: {last}", flush=True)
        os._exit(1)  # a task-level SystemExit would leave run_app serving 503s

    async def on_start(app):
        # background task: peers bootstrap against each other's storage
        # RPC, so the listener must come up FIRST (on_startup blocks it)
        import asyncio

        async def boot_then_gateways():
            await bootstrap()
            if args.ftp:
                from .ftp import FTPGateway

                await FTPGateway(srv).serve(host or "0.0.0.0", args.ftp)
                print(f"FTP gateway on port {args.ftp}", flush=True)
            if args.sftp:
                from .sftp import SFTPGateway, load_authorized_keys

                SFTPGateway(
                    srv,
                    authorized_keys=load_authorized_keys(
                        os.environ.get("MINIO_SFTP_AUTHORIZED_KEYS")
                    ),
                ).listen(host or "0.0.0.0", args.sftp)
                print(f"SFTP gateway on port {args.sftp}", flush=True)

        app["bootstrap"] = asyncio.create_task(boot_then_gateways())

    srv.app.on_startup.append(on_start)
    # explicit runner instead of run_app: read_bufsize lifts aiohttp's
    # 64 KiB StreamReader watermark, which otherwise pause/resumes the
    # transport 16x per MiB on large streaming PUTs (hot-path cost on the
    # single-core bench host)
    import asyncio as _asyncio
    import signal as _signal

    async def _serve():
        runner = web.AppRunner(
            srv.app, read_bufsize=int(
                os.environ.get("MINIO_TPU_HTTP_READBUF", str(4 << 20))
            ),
        )
        await runner.setup()
        site = web.TCPSite(
            runner, host or "0.0.0.0", my_port,
            ssl_context=cert_mgr.ctx if cert_mgr else None,
        )
        await site.start()
        cert_watcher = None
        if cert_mgr is not None:
            print(f"serving https on {args.address}", flush=True)

            async def _watch_certs():
                while True:
                    await _asyncio.sleep(2.0)
                    if cert_mgr.maybe_reload(min_interval=0.0):
                        # internode dialers must re-anchor trust too when
                        # the deployment pins the shared public.crt
                        tlsconf.GLOBAL.refresh_client_context()
                        print("TLS certificate reloaded", flush=True)

            # keep a strong reference: asyncio tasks are weakly held and
            # an unreferenced watcher would be GC-collected mid-flight
            cert_watcher = _asyncio.get_running_loop().create_task(
                _watch_certs()
            )
        stop = _asyncio.Event()
        loop = _asyncio.get_running_loop()
        for sig in (_signal.SIGINT, _signal.SIGTERM):
            try:
                loop.add_signal_handler(sig, stop.set)
            except NotImplementedError:  # non-unix
                pass
        await stop.wait()
        if cert_watcher is not None:
            cert_watcher.cancel()
        await runner.cleanup()  # close listeners, drain in-flight requests

    try:
        _asyncio.run(_serve())
    except KeyboardInterrupt:
        pass


if __name__ == "__main__":
    main()
