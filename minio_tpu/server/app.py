"""The S3 API server: routing + handlers over the object layer.

Path-style S3 API (the reference's registerAPIRouter,
/root/reference/cmd/api-router.go:255) on aiohttp. Handlers validate auth
(SigV4 header/presigned, streaming payloads), then call the erasure object
layer in worker threads; responses are S3-wire XML/headers.
"""

from __future__ import annotations

import asyncio
import hashlib
import os

from aiohttp import web

from ..erasure import quorum
from ..storage.xlstorage import XLStorage
from . import s3err, signature
from .buckets import BucketMetadataSys

from .auth import RequestAuthMixin
from .bucket_handlers import BucketHandlersMixin
from .handler_utils import (
    BUCKET_NAME_RE,
    _SUBRESOURCE_ACTIONS,
    _route_action,
    _route_conditions,
)
from .multipart_handlers import MultipartHandlersMixin
from .object_handlers import ObjectHandlersMixin
from .postpolicy import PostPolicyMixin


class S3Server(
    RequestAuthMixin,
    BucketHandlersMixin,
    ObjectHandlersMixin,
    MultipartHandlersMixin,
    PostPolicyMixin,
):
    def __init__(self, store=None, region: str = "us-east-1"):
        import time as _time

        from ..crypto.sse import KMS
        from .metrics import Metrics, TracePubSub

        from concurrent.futures import ThreadPoolExecutor as _TPE

        from ..obs import ContextPool as _CtxTPE

        self.kms = KMS()
        self.store = None
        self.streaming_puts = 0  # observability: bodies that never buffered
        # dedicated pool for streaming-body pumps: put_item can block on a
        # full queue, and parking it in the default executor would starve
        # the storage-REST plane that shares it
        self._pump_pool = _CtxTPE(
            max_workers=8, thread_name_prefix="body-pump"
        )
        # store I/O runs on an ample dedicated pool: the default executor
        # on small machines has ~cpus+4 workers, and writers blocking on
        # namespace locks inside it can starve the reader that HOLDS the
        # lock out of a thread to finish its stream (deadlock-by-pool).
        # Context-propagating: the trace request id must survive the
        # event-loop -> worker hop (run_in_executor drops contextvars)
        io_threads = int(os.environ.get("MINIO_TPU_IO_THREADS", "64"))
        self._io_pool = _CtxTPE(max_workers=io_threads, thread_name_prefix="s3io")
        # long-poll waits (trace/listen subscribers) get their own pool so
        # they can never starve the I/O pool
        self._longpoll_pool = _TPE(max_workers=64, thread_name_prefix="longpoll")
        # admission waits get a small dedicated pool: a class at its cap
        # must not occupy long-poll or I/O threads, and since begin_wait
        # starts the deadline clock on the event loop, tasks that outwait
        # their deadline in this pool's queue reject instantly on start
        self._admit_pool = _TPE(max_workers=16, thread_name_prefix="qos-admit")
        self.region = region
        self.started_at = _time.time()
        self.metrics = Metrics()
        self.trace = TracePubSub()
        # worker-pool identity (server/worker.py): single-process serving
        # is worker 0 of 1 with no siblings; main() overwrites these when
        # the process is part of an SO_REUSEPORT pool. worker_peers are
        # loopback control endpoints of the SIBLING workers — they ride
        # `peers` for admin/trace fan-out but stay separately addressable
        # for metrics aggregation (a scrape must merge workers, not
        # cluster nodes, which scrape themselves).
        self.worker_index = 0
        self.worker_count = 1
        self.worker_peers: list[str] = []
        # deep-tracing spans (obs/) publish through this server's pubsub;
        # module-level registration because spans open in layers with no
        # server reference (dispatcher, storage wrappers) — one process
        # serves one node
        from .. import obs

        obs.set_publisher(self.trace)
        from ..qos import QoS

        # QoS plane: admission control (per-class inflight caps -> 503
        # SlowDown on overflow) + last-minute per-API latency ring
        self.qos = QoS()
        self.background = None
        # continuous wall-time profiler (server/profiling.py): main()
        # starts it knob-gated; in-process test servers leave it off
        self.cprofiler = None
        self.root_user = os.environ.get("MINIO_ROOT_USER", "minioadmin")
        self.root_pass = os.environ.get("MINIO_ROOT_PASSWORD", "minioadmin")
        self.app = web.Application(client_max_size=1 << 30)
        # CORS decoration rides the prepare signal: it must run before
        # headers hit the wire, which for streamed GETs happens INSIDE the
        # handler — a post-dispatch wrapper would be too late
        self.app.on_response_prepare.append(self._ttfb_on_prepare)
        self.app.on_response_prepare.append(self._cors_on_prepare)
        self.app.router.add_route("*", "/", self._entry)
        self.app.router.add_route("*", "/{bucket}", self._entry)
        self.app.router.add_route("*", "/{bucket}/{key:.*}", self._entry)
        if store is not None:
            self.set_store(store)

    def set_store(self, store) -> None:
        """Attach the object layer once bootstrap completes; until then S3
        requests answer 503 (the reference gates on newObjectLayer the
        same way)."""
        from ..erasure.multipart import MultipartRouter
        from ..iam.sys import IAMSys

        self.buckets = BucketMetadataSys(store)
        self.mp = MultipartRouter(store, part_transform=self._mp_part_transform)
        # IAM documents move to etcd when configured, so independent
        # deployments share one identity plane (reference
        # cmd/iam-etcd-store.go; same env variable)
        etcd_eps = os.environ.get("MINIO_ETCD_ENDPOINTS", "")
        if etcd_eps:
            from ..iam.etcd import EtcdIAMStore, EtcdKV

            iam_store = EtcdIAMStore(EtcdKV(etcd_eps))
        else:
            iam_store = store
        self.iam = IAMSys(iam_store, self.root_user, self.root_pass)
        # a real load error must abort boot: running with silently-empty IAM
        # would wipe stored identities on the next persist (first boot is
        # fine — missing documents load as empty)
        self.iam.load()
        # periodic refresh + etcd watch: IAM writes from peer nodes and
        # etcd-sharing clusters converge without restart (cmd/iam.go:246)
        _refresh_raw = os.environ.get("MINIO_TPU_IAM_REFRESH", "120")
        try:
            _refresh = float(_refresh_raw)
        except ValueError:
            raise SystemExit(
                f"MINIO_TPU_IAM_REFRESH={_refresh_raw!r}: want seconds "
                "as a number (0 disables the periodic refresh)"
            ) from None
        self.iam.start_refresh(_refresh)
        self.verifier = signature.SigV4Verifier(self.iam.lookup_secret, self.region)
        from ..batch.jobs import BatchJobPool
        from ..crypto.sse import KMS
        from ..erasure.decommission import PoolManager
        from ..events.notify import EventNotifier
        from ..replication.replicate import ReplicationPool, TargetRegistry
        from .audit import AuditLog
        from .config_kv import ConfigKV

        self.notifier = EventNotifier(self.buckets)
        self.audit = AuditLog()
        self.config = ConfigKV(store)
        from ..crypto.kes import from_env_or_config

        # KES external KMS when configured; builtin persisted key otherwise
        self.kms = from_env_or_config(cfg=self.config, store=store)
        self.repl_targets = TargetRegistry(store)
        from ..ilm.tier import TierRegistry

        self.tiers = TierRegistry(store)

        def _repl_decode(oi, data, bucket, key):
            from ..crypto import sse as ssemod
            from . import transforms

            if not transforms.is_transformed(oi.user_defined):
                return data
            if oi.user_defined.get(ssemod.META_ALGO) == "SSE-C":
                # the server has no customer key; cannot replicate SSE-C
                raise RuntimeError("SSE-C objects cannot be auto-replicated")
            return transforms.decode_full(
                data, oi.user_defined, {}, bucket, key, self.kms
            )

        self.replication = ReplicationPool(
            store, self.buckets, self.repl_targets, decode=_repl_decode
        )
        from ..replication.site import SiteReplicationSys

        self.site = SiteReplicationSys(self)
        # miniovet: ignore[races] -- set_store runs exactly once at
        # bootstrap, before the server accepts traffic; the callback
        # wiring cannot be re-entered concurrently
        self.buckets.on_change = (
            lambda bucket, bm: self.site.sync_bucket_meta(bucket, bm)
        )
        self.iam.on_mutation = self.site.sync_iam
        self.batch = BatchJobPool(store, self.buckets, self.replication, kms=self.kms)
        self.pool_mgr = (
            PoolManager(store) if hasattr(store, "pools") else None
        )
        self.store = store
        # cache coherence: received grid invalidations apply to THIS
        # store's per-set caches (cache/coherence.py)
        from ..cache import coherence as cache_coherence

        cache_coherence.attach(store)
        self.site.load()  # resume a persisted site group across restarts
        # background durability plane: scanner + MRF heal workers
        from ..erasure.background import BackgroundOps

        interval = float(os.environ.get("MINIO_TPU_SCAN_INTERVAL", "300"))
        self.background = BackgroundOps(
            store, scan_interval=interval, bucket_meta=self.buckets,
            tiers=self.tiers,
        )
        for p in getattr(store, "pools", [store]):
            for s in getattr(p, "sets", [p]):
                s.on_degraded = self.background.mrf.add
        if interval > 0:
            # pool workers past index 0 run heal (their own MRF queue)
            # but not the scanner/ILM/fresh-disk plane: those walk the
            # SHARED drives and would duplicate bg work N× per node
            self.background.start(scanner=self.worker_index == 0)

    # -- plumbing ------------------------------------------------------------

    def _mp_part_transform(self, bucket, obj, up_meta, part_number, data,
                           ctx=None):
        """SSE hook for multipart parts: encrypt each part as its own
        packet stream under the upload's OEK. None = no transform.
        Returns (stored, plain_size | size_getter): streamed parts encrypt
        packet-by-packet and report their plaintext size after the fact.
        `ctx` carries the part request's headers — SSE-C uploads re-present
        the customer key on every part (cmd/erasure-multipart.go:575)."""
        from ..crypto import sse as ssemod
        from . import transforms

        if ssemod.META_ALGO not in up_meta:
            return None
        # SSE-C validation (key present + MD5 match vs the upload's) happens
        # inside _unseal_oek, which both encrypt paths invoke eagerly — a
        # missing/mismatched customer key raises before any data is stored
        headers = ctx or {}
        if isinstance(data, (bytes, bytearray)):
            enc = transforms.encrypt_part(
                bytes(data), up_meta, part_number, self.kms, bucket, obj,
                headers,
            )
            return enc, len(data)
        count = [0]
        gen = transforms.encrypt_part_iter(
            data, up_meta, part_number, self.kms, bucket, obj, count, headers
        )
        return gen, (lambda: count[0])

    def close(self) -> None:
        """Stop background workers (IAM refresh/watch, scanner) — for
        embedders and tests that start/stop servers within one process;
        without this, watcher threads keep dialing dead backends."""
        iam = getattr(self, "iam", None)
        if iam is not None:
            iam.stop_refresh()
        if self.background is not None:
            try:
                self.background.stop()
            except Exception:  # noqa: BLE001 — best-effort teardown
                pass

    def _queue_repl(self, request, bucket, key, version_id, op) -> None:
        """Queue a bucket-replication task unless this write IS a replica
        (the marker header breaks active-active site-replication loops).
        Only cluster owners (site peers authenticate with admin creds) may
        set the marker — an ordinary writer must not be able to opt its
        writes out of replication."""
        from ..replication.replicate import REPLICA_MARKER

        if (
            request.headers.get(REPLICA_MARKER) == "true"
            and self.iam.is_owner(request.get("access_key", ""))
        ):
            return
        self.replication.queue_mutation(bucket, key, version_id, op)

    async def _run(self, fn, *args, **kw):
        return await asyncio.get_running_loop().run_in_executor(
            self._io_pool, lambda: fn(*args, **kw)
        )

    def _prometheus_bearer_ok(self, request) -> bool:
        """Validate a madmin-style prometheus JWT: HS512 signed with the
        subject's secret key, standard base64url framing."""
        import hmac as _hmac
        import json as _json
        import time as _time

        from ..iam.oidc import _b64url as _unb64  # shared padded decoder

        auth = request.headers.get("Authorization", "")
        if not auth.startswith("Bearer "):
            return False

        try:
            h, c, s = auth[7:].split(".")
            claims = _json.loads(_unb64(c))
            ak = claims.get("sub", "")
            secret = self.iam.lookup_secret(ak)
            if not secret:
                return False
            want = _hmac.new(
                secret.encode(), f"{h}.{c}".encode(), hashlib.sha512
            ).digest()
            if not _hmac.compare_digest(_unb64(s), want):
                return False
            exp = claims.get("exp")
            if exp is not None and _time.time() > float(exp):
                return False
        except Exception:  # noqa: BLE001 — any malformed token is a no
            return False
        return self.iam.is_allowed(ak, "admin:Prometheus", "")

    def _err_response(self, request, err: s3err.APIError) -> web.Response:
        # rejection split the status-code classifier in Metrics.observe
        # can't see: malformed auth headers vs clock skew (both 4xx)
        if err.code == "RequestTimeTooSkewed":
            self.metrics.rejected_timestamp += 1
        elif err.code == "AuthorizationHeaderMalformed":
            self.metrics.rejected_header += 1
        headers = {}
        size = request.get("_range_object_size")
        if err.http_status == 416 and size is not None:
            # RFC 7233: unsatisfiable ranges advertise the actual length
            # (the reference sets this on InvalidRange responses too)
            headers["Content-Range"] = f"bytes */{size}"
        return web.Response(
            status=err.http_status,
            body=err.to_xml(
                resource=request.path,
                request_id=request.get("_reqid", ""),
            ),
            content_type="application/xml",
            headers=headers,
        )

    def _apply_vhost_style(self, request: web.Request) -> None:
        """Virtual-host-style addressing (reference MINIO_DOMAIN,
        cmd/generic-handlers.go setBucketForwardingMiddleware): for
        `bucket.domain` hosts the bucket rides the Host header and the
        whole path is the key. SigV4 verification keeps the original
        path — that is what vhost clients sign."""
        domains = os.environ.get("MINIO_DOMAIN", "")
        if not domains:
            return
        host = request.headers.get("Host", "").rsplit(":", 1)[0].lower()
        # longest suffix first: with domains example.test + s3.example.test
        # configured, host b.s3.example.test must parse bucket "b", not
        # the dotted label "b.s3"
        ordered = sorted(
            (d.strip().lower() for d in domains.split(",") if d.strip()),
            key=len, reverse=True,
        )
        for dom in ordered:
            if not host.endswith("." + dom):
                continue
            vb = host[: -len(dom) - 1]
            if not BUCKET_NAME_RE.match(vb):
                return  # not a bucket label (e.g. console.domain)
            # the key is the WHOLE request path (not re-joined match_info
            # segments: that would drop a trailing slash, losing folder
            # markers like "photos/")
            request.match_info["key"] = request.path.lstrip("/")
            request.match_info["bucket"] = vb
            return

    async def _admit(self, qos_class: str) -> bool:
        """Admission control for one request: lock-only fast path on the
        event loop; contended classes reserve a waiter slot (bounded —
        queue-full rejects here, before any thread is consumed) and park
        the blocking deadline wait on the dedicated admission pool.
        Cancellation-safe: a client that disconnects mid-wait hands any
        slot the worker still grants straight back, so caps never leak."""
        from .. import obs

        adm = self.qos.admission
        if adm.try_acquire(qos_class):
            return True
        # contended: the parked wait is an `internal` span — attributes a
        # slow p99 to admission queueing vs. actual work
        with obs.span(
            obs.TYPE_INTERNAL, "qos.admission-wait", **{"class": qos_class}
        ) as sp:
            deadline = adm.begin_wait(qos_class)
            if deadline is None:
                sp.set(rejected="queue_full")
                return False  # wait queue full: SlowDown immediately
            # submit + wrap (not run_in_executor): on cancellation the asyncio
            # wrapper is marked cancelled even while the worker keeps running,
            # so the reclaim callback must ride the CONCURRENT future, whose
            # terminal state says what finish_wait actually did
            cf = self._admit_pool.submit(adm.finish_wait, qos_class, deadline)
            try:
                granted = await asyncio.wrap_future(cf)
                sp.set(granted=granted)
                return granted
            except asyncio.CancelledError:
                def _reclaim(f):
                    try:
                        if f.cancelled():
                            # finish_wait never ran: undo the reservation
                            adm.abort_wait(qos_class)
                        elif f.exception() is None and f.result():
                            adm.release(qos_class)  # granted to a dead request
                    except Exception:  # noqa: BLE001 — teardown best-effort
                        pass

                cf.add_done_callback(_reclaim)
                raise

    async def _entry(self, request: web.Request) -> web.StreamResponse:
        import time as _time

        from .. import obs
        from .handler_utils import classify_qos_class
        from .metrics import classify_api, trace_record

        self._apply_vhost_style(request)
        t0 = _time.perf_counter()
        request["_t0"] = t0  # TTFB measured at response prepare time
        # per-request trace context: the generated x-amz-request-id rides a
        # contextvar through every layer below (and the response header —
        # set at prepare time so streamed bodies get it too)
        req_id = obs.new_request_id()
        request["_reqid"] = req_id
        obs_token = obs.set_request(req_id)
        resp: web.StreamResponse | None = None
        qos_class: str | None = None
        self.metrics.inflight += 1  # single-threaded event loop: no race
        try:
            origin = request.headers.get("Origin", "")
            if origin and request.method == "OPTIONS" and request.headers.get(
                "Access-Control-Request-Method"
            ):
                resp = await self._cors_preflight(request, origin)
                return resp
            cls = classify_qos_class(
                request.match_info.get("bucket", ""),
                request.match_info.get("key", ""),
                request.headers,
            )
            if cls is not None:
                if not await self._admit(cls):
                    # over the class cap past the bounded wait deadline:
                    # S3 SlowDown (503), never unbounded queueing
                    resp = self._err_response(request, s3err.SlowDown)
                    resp.headers["Retry-After"] = "1"
                    return resp
                qos_class = cls  # acquired: release in finally
            resp = await self._entry_inner(request)
            return resp
        except asyncio.CancelledError:
            # client went away: count it (metrics-v3 canceled_total) and
            # propagate so aiohttp abandons the request
            self.metrics.canceled += 1
            raise
        finally:
            obs.trace.reset_request(obs_token)
            if qos_class is not None:
                self.qos.admission.release(qos_class)
            self.metrics.inflight -= 1
            dur = _time.perf_counter() - t0
            status = resp.status if resp is not None else 500
            api = classify_api(
                request.method,
                request.match_info.get("bucket", ""),
                request.match_info.get("key", ""),
                request.rel_url.query,
            )
            rx = int(request.headers.get("Content-Length") or 0)
            # bytes counted at write time win: streamed responses (tier
            # read-through, transformed GETs, proxies) have no (or a lying)
            # content_length, and would otherwise meter as 0 bytes sent.
            # `is not None`, NOT truthiness: StreamResponse is a Mapping,
            # so a response with empty per-request storage is falsy — the
            # old `if resp` zeroed tx for nearly every response
            tx = request.get("_tx")
            if tx is None and resp is not None:
                tx = getattr(resp, "content_length", None) or 0
            tx = tx or 0
            self.metrics.observe(
                api, status, dur, rx, tx,
                bucket=request.match_info.get("bucket", ""),
                ttfb=request.get("_ttfb"),
            )
            self.qos.last_minute.add(api, dur, ttfb=request.get("_ttfb"))
            if self.trace.active:
                self.trace.publish(
                    trace_record(request, status, dur, rx, tx,
                                 req_id=req_id, api=api)
                )
            audit = getattr(self, "audit", None)
            if audit is not None and audit.enabled:
                from .audit import audit_record

                audit.emit(
                    audit_record(request, status, dur,
                                 request.get("access_key", ""),
                                 rx=rx, tx=tx)
                )

    @staticmethod
    def _is_user_bucket(bucket: str) -> bool:
        return bool(bucket) and bucket != "minio" and not bucket.startswith(".minio.sys")

    def _cors_rules_for(self, raw: str):
        """Parsed bucket CORS rules, memoized by the raw document — the
        response path must not pay an XML parse per request."""
        from . import cors as corsmod

        cache = getattr(self, "_cors_rule_cache", None)
        if cache is None:
            cache = self._cors_rule_cache = {}
        rules = cache.get(raw)
        if rules is None:
            if len(cache) > 256:
                cache.clear()
            try:
                rules = cache[raw] = corsmod.parse_bucket_cors(raw)
            except ValueError:
                rules = cache[raw] = []
        return rules or None

    def _cors_headers(
        self, bucket: str, origin: str, method: str, req_headers: list[str],
        allow_load: bool = False,
    ) -> dict[str, str] | None:
        """Evaluate bucket CORS rules (when configured) or the global
        api.cors_allow_origin config (reference cmd/api-router.go:651).
        allow_load=False restricts to the metadata CACHE (event-loop
        callers); allow_load=True (executor callers) falls through to a
        bucket_exists-gated metadata load, so attacker-chosen names never
        reach get() (which would cache a default entry per name)."""
        rules = None
        if self._is_user_bucket(bucket):
            bm = self.buckets.peek(bucket)
            if bm is None and allow_load and self.store is not None:
                try:
                    if self.store.bucket_exists(bucket):
                        bm = self.buckets.get(bucket)
                except Exception:  # noqa: BLE001 — degraded metadata reads
                    bm = None     # fall back to global rules
            raw = bm.cors if bm is not None else None
            if raw:
                rules = self._cors_rules_for(raw)
        from . import cors as corsmod

        global_origins = [
            o.strip()
            for o in (self.config.get("api", "cors_allow_origin") or "*").split(",")
            if o.strip()
        ] if self.config is not None else ["*"]
        return corsmod.evaluate(origin, method, req_headers, rules, global_origins)

    async def _ttfb_on_prepare(self, request: web.Request, response) -> None:
        """Metrics TTFB capture: first byte leaves at response-prepare time
        for both buffered and streamed bodies. The generated request id
        rides the same hook so EVERY response carries it (S3 clients
        correlate errors by x-amz-request-id)."""
        import time as _time

        t0 = request.get("_t0")
        if t0 is not None and "_ttfb" not in request:
            request["_ttfb"] = _time.perf_counter() - t0
        req_id = request.get("_reqid")
        if req_id:
            response.headers.setdefault("x-amz-request-id", req_id)

    async def _cors_on_prepare(self, request: web.Request, response) -> None:
        origin = request.headers.get("Origin", "")
        if not origin or request.method == "OPTIONS":
            return
        bucket = request.match_info.get("bucket", "") if request.match_info else ""
        if self._is_user_bucket(bucket) and self.buckets.peek(bucket) is None:
            # uncached bucket (e.g. first GET after restart): its CORS
            # rules are authoritative, so load them off-loop rather than
            # silently falling back to the permissive global default
            hdrs = await self._run(
                self._cors_headers, bucket, origin, request.method, [], True
            )
        else:
            hdrs = self._cors_headers(bucket, origin, request.method, [])
        if hdrs:
            for k, v in hdrs.items():
                response.headers.setdefault(k, v)

    async def _cors_preflight(self, request: web.Request, origin: str) -> web.Response:
        """OPTIONS preflight: unauthenticated by design (browsers send no
        credentials); only reveals whether an origin/method is allowed."""
        method = request.headers.get("Access-Control-Request-Method", "")
        req_headers = [
            h.strip()
            for h in request.headers.get("Access-Control-Request-Headers", "").split(",")
            if h.strip()
        ]
        hdrs = await self._run(
            self._cors_headers, request.match_info.get("bucket", ""), origin,
            method, req_headers, True,
        )
        if hdrs is None:
            return web.Response(status=403, body=b"CORSResponse: origin not allowed")
        return web.Response(status=200, headers=hdrs)

    async def _entry_inner(self, request: web.Request) -> web.StreamResponse:
        # unauthenticated planes: health + metrics
        bucket = request.match_info.get("bucket", "")
        key = request.match_info.get("key", "")
        if bucket == "minio":
            if request.method == "GET" and key == "console/api/users":
                # console backend API (the reference console ships its own
                # REST layer too): same authz as madmin ListUsers, but plain
                # JSON — the browser cannot speak the argon2id-encrypted
                # madmin framing. No secrets travel: status/policies/groups.
                try:
                    ak, _ = await self._authenticate(request)
                except s3err.APIError as e:
                    return self._err_response(request, e)
                if not ak or not self.iam.is_allowed(ak, "admin:ListUsers", ""):
                    return self._err_response(request, s3err.AccessDenied)
                users = await self._run(self.iam.list_users)
                return web.json_response({
                    k: {"status": u.status, "policyName": ",".join(u.policies),
                        "memberOf": u.groups}
                    for k, u in users.items()
                })
            if request.method in ("GET", "HEAD") and (
                key == "console" or key.startswith("console/")
            ):
                # embedded browser console (reference embeds minio/console,
                # cmd/common-main.go:46); static page, data calls signed
                # in-browser
                from .console import handle_console

                return handle_console(request)
            if key.startswith("health/"):
                # disk probes may hit remote drives: stay off the event loop
                return await self._run(self._health, request, key)
            if key in ("v2/metrics/cluster", "v2/metrics/node") or key.startswith(
                "metrics/v3"
            ):
                if self.store is None:
                    return web.Response(status=503)
                if os.environ.get("MINIO_PROMETHEUS_AUTH_TYPE", "jwt") != "public":
                    # scrapers authenticate with the bearer JWT that
                    # `mc admin prometheus generate` mints (HS512 over the
                    # caller's secret key); SigV4 remains accepted for
                    # our own SDK (reference cmd/metrics-router.go)
                    if not self._prometheus_bearer_ok(request):
                        try:
                            ak, _ = await self._authenticate(request)
                        except s3err.APIError as e:
                            return self._err_response(request, e)
                        if not ak or not self.iam.is_allowed(
                            ak, "admin:Prometheus", ""
                        ):
                            return self._err_response(request, s3err.AccessDenied)
                if key.startswith("metrics/v3"):
                    from .metrics import render_v3, render_v3_pool

                    sub = key[len("metrics/v3"):]
                    # worker pool: a scrape landing on this worker merges
                    # every sibling's series (worker-labelled) unless the
                    # caller opted out with local=on (the fan-out itself
                    # uses local=on, so recursion stops after one hop)
                    local_only = request.rel_url.query.get(
                        "local", ""
                    ).lower() in ("on", "true", "1")
                    render = (
                        render_v3 if local_only or not self.worker_peers
                        else render_v3_pool
                    )
                    text = await self._run(render, self, sub)
                    if text is None:
                        return web.Response(status=404, body=b"unknown metrics path")
                else:
                    text = await self._run(self.metrics.render, self)
                return web.Response(body=text.encode(), content_type="text/plain")
        try:
            if self.store is None:
                return web.Response(
                    status=503, headers={"Retry-After": "1"},
                    body=b"server initializing",
                )
            return await self._dispatch(request)
        except s3err.APIError as e:
            return self._err_response(request, e)
        except quorum.BucketNotFound:
            return self._err_response(request, s3err.NoSuchBucket)
        except quorum.BucketExists:
            return self._err_response(request, s3err.BucketAlreadyOwnedByYou)
        except quorum.BucketNotEmpty:
            return self._err_response(request, s3err.BucketNotEmpty)
        except (quorum.ObjectNotFound,):
            return self._err_response(request, s3err.NoSuchKey)
        except quorum.VersionNotFound:
            return self._err_response(request, s3err.NoSuchVersion)
        except quorum.QuorumError:
            return self._err_response(request, s3err.InternalError)
        except asyncio.CancelledError:
            # client disconnect: propagate so aiohttp abandons the request
            # instead of logging a 500 for work nobody is waiting on
            raise
        except Exception:  # noqa: BLE001
            import traceback

            traceback.print_exc()
            return self._err_response(request, s3err.InternalError)
    async def _dispatch(self, request: web.Request) -> web.StreamResponse:
        ak, body = await self._authenticate(
            request, stream_body=self._streamable_put(request)
        )
        request["access_key"] = ak
        bucket = request.match_info.get("bucket", "")
        # aiohttp match_info is already percent-decoded; decoding again
        # would corrupt keys that legitimately contain %-sequences
        key = request.match_info.get("key", "")
        q = request.rel_url.query
        m = request.method

        # admin + STS + KMS planes
        if bucket == "minio" and key.startswith("kms/"):
            from .kms_handlers import handle_kms

            return await handle_kms(
                self, request, ak, key[len("kms/"):], body
            )
        if bucket == "minio" and key.startswith("admin/"):
            from .admin import handle_admin

            if not ak:
                raise s3err.AccessDenied
            sub = key[len("admin/") :]
            sub = sub.split("/", 1)[1] if "/" in sub else ""  # strip version
            return await handle_admin(self, request, ak, sub, body)
        if not bucket and m == "POST":
            from .sts import handle_sts

            return await handle_sts(self, request, ak, body)

        if not bucket:
            if m == "GET":
                self._authorize(ak, "s3:ListAllMyBuckets", "")
                return await self.list_buckets(request)
            raise s3err.MethodNotAllowed
        if bucket.startswith(".minio.sys"):
            raise s3err.AccessDenied

        self._authorize(ak, *_route_action(m, bucket, key, q, request.headers),
                        conditions=_route_conditions(q))

        if not key:
            if m == "PUT":
                if "versioning" in q:
                    return await self.put_bucket_versioning(request, bucket, body)
                if "policy" in q:
                    return await self.put_bucket_simple(request, bucket, "policy", body)
                if "lifecycle" in q:
                    return await self.put_bucket_simple(request, bucket, "lifecycle", body)
                if "tagging" in q:
                    return await self.put_bucket_simple(request, bucket, "tags", body)
                if "notification" in q:
                    return await self.put_bucket_simple(request, bucket, "notification", body)
                if "encryption" in q:
                    return await self.put_bucket_simple(request, bucket, "encryption", body)
                if "object-lock" in q:
                    return await self.put_bucket_simple(request, bucket, "object_lock", body)
                if "cors" in q:
                    return await self.put_bucket_simple(request, bucket, "cors", body)
                if "replication" in q:
                    return await self.put_bucket_simple(request, bucket, "replication", body)
                if "acl" in q:
                    return await self.put_acl(request, bucket, "", body)
                if "requestPayment" in q:
                    return await self.put_request_payment(request, bucket, body)
                if "ownershipControls" in q:
                    return await self.put_bucket_simple(
                        request, bucket, "ownership", body
                    )
                if "logging" in q or "website" in q or "accelerate" in q:
                    raise s3err.NotImplemented_
                if any(s in q for s in _SUBRESOURCE_ACTIONS):
                    # unhandled method on a known subresource must NOT fall
                    # through to bucket creation (it was authorized for the
                    # SUBRESOURCE action, not s3:CreateBucket)
                    raise s3err.MethodNotAllowed
                return await self.put_bucket(request, bucket)
            if m == "DELETE":
                for sub in ("policy", "lifecycle", "tagging", "notification",
                            "encryption", "cors", "replication",
                            "ownershipControls"):
                    if sub in q:
                        return await self.delete_bucket_simple(request, bucket, sub)
                if any(s in q for s in _SUBRESOURCE_ACTIONS) or any(
                    s in q for s in ("website", "logging", "accelerate")
                ):
                    # e.g. DELETE ?acl or ?versioning was authorized for the
                    # subresource action only — falling through would delete
                    # the BUCKET without s3:DeleteBucket
                    raise s3err.MethodNotAllowed
                return await self.delete_bucket(request, bucket)
            if m == "HEAD":
                return await self.head_bucket(request, bucket)
            if m == "GET":
                if "events" in q:  # MinIO listen-notification extension
                    return await self.listen_events(request, bucket)
                if "location" in q:
                    return await self.get_bucket_location(request, bucket)
                if "versioning" in q:
                    return await self.get_bucket_versioning(request, bucket)
                if "versions" in q:
                    return await self.list_object_versions(request, bucket)
                for sub, attr, missing in (
                    ("policy", "policy", s3err.NoSuchBucketPolicy),
                    ("lifecycle", "lifecycle", s3err.NoSuchLifecycleConfiguration),
                    ("tagging", "tags", s3err.NoSuchTagSet),
                    ("notification", "notification", None),
                    ("encryption", "encryption", s3err.ServerSideEncryptionConfigurationNotFoundError),
                    ("object-lock", "object_lock", s3err.ObjectLockConfigurationNotFoundError),
                    ("cors", "cors", s3err.NoSuchCORSConfiguration),
                    ("replication", "replication", s3err.ReplicationConfigurationNotFoundError),
                ):
                    if sub in q:
                        return await self.get_bucket_simple(request, bucket, attr, missing)
                if "acl" in q:
                    return await self.get_acl(request, bucket, "")
                if "policyStatus" in q:
                    return await self.get_policy_status(request, bucket)
                if "requestPayment" in q:
                    return await self.get_request_payment(request, bucket)
                if "logging" in q:
                    return await self.get_bucket_logging(request, bucket)
                if "ownershipControls" in q:
                    return await self.get_bucket_simple(
                        request, bucket, "ownership",
                        s3err.OwnershipControlsNotFoundError,
                    )
                if "website" in q:
                    if not await self._run(self.store.bucket_exists, bucket):
                        raise s3err.NoSuchBucket
                    raise s3err.NoSuchWebsiteConfiguration
                if "uploads" in q:
                    return await self.list_multipart_uploads(request, bucket)
                return await self.list_objects(request, bucket)
            if m == "POST":
                if "delete" in q:
                    return await self.delete_multiple(request, bucket, body)
                ctype = request.headers.get("Content-Type", "")
                if ctype.startswith("multipart/form-data"):
                    return await self.post_policy_upload(request, bucket, body)
            raise s3err.MethodNotAllowed

        # object-level. Subresource blocks terminate: an unhandled method
        # was authorized for the SUBRESOURCE action and must not fall
        # through to object read/delete (e.g. DELETE ?retention holding
        # only s3:PutObjectRetention must not delete the object).
        if "retention" in q:
            if m == "PUT":
                return await self.put_object_retention(request, bucket, key, body)
            if m == "GET":
                return await self.get_object_retention(request, bucket, key)
            raise s3err.MethodNotAllowed
        if "legal-hold" in q:
            if m == "PUT":
                return await self.put_legal_hold(request, bucket, key, body)
            if m == "GET":
                return await self.get_legal_hold(request, bucket, key)
            raise s3err.MethodNotAllowed
        if "tagging" in q:
            if m == "PUT":
                return await self.put_object_tagging(request, bucket, key, body)
            if m == "GET":
                return await self.get_object_tagging(request, bucket, key)
            if m == "DELETE":
                return await self.delete_object_tagging(request, bucket, key)
            raise s3err.MethodNotAllowed
        if "acl" in q:
            if m == "PUT":
                return await self.put_acl(request, bucket, key, body)
            if m == "GET":
                return await self.get_acl(request, bucket, key)
            raise s3err.MethodNotAllowed
        if m == "PUT":
            if "partNumber" in q and "uploadId" in q:
                if "x-amz-copy-source" in request.headers:
                    return await self.upload_part_copy(request, bucket, key)
                return await self.put_object_part(request, bucket, key, body)
            if "x-amz-copy-source" in request.headers:
                return await self.copy_object(request, bucket, key)
            return await self.put_object(request, bucket, key, body)
        if m == "GET":
            if "uploadId" in q:
                return await self.list_parts(request, bucket, key)
            if "attributes" in q:
                return await self.get_object_attributes(request, bucket, key)
            if "lambdaArn" in q:
                return await self.get_object_lambda(request, bucket, key)
            return await self.get_object(request, bucket, key)
        if m == "HEAD":
            return await self.head_object(request, bucket, key)
        if m == "DELETE":
            if "uploadId" in q:
                return await self.abort_multipart(request, bucket, key)
            return await self.delete_object(request, bucket, key)
        if m == "POST":
            if "uploads" in q:
                return await self.new_multipart(request, bucket, key)
            if "uploadId" in q:
                return await self.complete_multipart(request, bucket, key, body)
            if "restore" in q:
                return await self.restore_object(request, bucket, key, body)
            if "select" in q and q.get("select-type") == "2":
                return await self.select_object_content(request, bucket, key, body)
        raise s3err.MethodNotAllowed

    # -- service -------------------------------------------------------------
    def _health(self, request, key: str) -> web.Response:
        """Liveness/readiness/cluster health
        (reference cmd/healthcheck-handler.go)."""
        if key == "health/live":
            return web.Response(status=200)
        if key in ("health/ready", "health/cluster"):
            if self.store is None:
                return web.Response(status=503)
            if key == "health/cluster":
                online = 0
                for d in self.store.disks:
                    try:
                        d.disk_info()
                        online += 1
                    except Exception:  # noqa: BLE001
                        pass
                quorum = len(self.store.disks) // 2 + 1
                if online < quorum:
                    return web.Response(
                        status=503, headers={"X-Minio-Write-Quorum": str(quorum)}
                    )
            return web.Response(status=200)
        return web.Response(status=404)
    # -- admin helpers ---------------------------------------------------------

    def server_info(self) -> dict:
        from .admin import server_info_payload

        return server_info_payload(self)

    def storage_info(self) -> dict:
        from .admin import storage_info_payload

        return storage_info_payload(self)

    def heal_sweep(self, bucket: str = "", prefix: str = "") -> dict:
        """Synchronous heal sweep over bucket/prefix (admin heal trigger;
        the background scanner drives the same per-object heal)."""
        healed, scanned, failed = [], 0, 0
        buckets = [bucket] if bucket else [b.name for b in self.store.list_buckets()]
        for b in buckets:
            for raw in self.store.walk_objects(b, prefix):
                scanned += 1
                try:
                    res = self.store.heal_object(b, raw)
                    for ep in res.get("healed", []):
                        healed.append(f"{b}/{raw}@{ep}")
                except Exception:  # noqa: BLE001
                    failed += 1
        return {"scanned": scanned, "healed": healed, "failed": failed}


def make_object_layer(
    drive_specs: list[str],
    set_size: int = 0,
    my_port: int = 0,
    internode_token_value: str = "",
    local_drive_registry: dict[int, XLStorage] | None = None,
    ns_lock=None,
    allow_mint: bool | None = None,
):
    """Build the full L3 topology from drive specs (ellipses expanded):
    endpoints -> local XLStorage / remote StorageRESTClient -> format.json
    bootstrap -> ErasureSets per pool -> ServerPools.

    Each spec is one pool (reference: each `minio server` arg group is a
    pool); 'path{0...15}' and 'http://host{1...2}:9000/d{1...4}' patterns
    expand to drives. All nodes pass identical specs; global drive indexes
    address remote drives (filled into local_drive_registry for the node's
    own storage RPC server).
    """
    from ..cluster.endpoint import parse_endpoint
    from ..cluster.storage_rest import StorageRESTClient
    from ..erasure.pools import ServerPools
    from ..erasure.sets import ErasureSets
    from ..storage.format_erasure import init_or_load_formats
    from ..storage.offline import OfflineDisk
    from ..utils import ellipses

    # args with ellipses each form a pool; bare dirs combine into one pool
    # (reference: each ellipses arg group is a serverPool)
    pool_specs: list[list[str]] = []
    bare: list[str] = []
    for spec in drive_specs:
        if ellipses.has_ellipses(spec):
            pool_specs.append(ellipses.expand(spec))
        else:
            bare.append(spec)
    if bare:
        pool_specs.insert(0, bare)

    # bootstrap-leader rule: only the node owning the very first endpoint
    # may mint a fresh cluster layout; in an SO_REUSEPORT worker pool the
    # caller narrows this further (only worker 0 mints — two workers
    # racing init_or_load_formats over the same empty drives would both
    # try to write format.json)
    if allow_mint is None:
        leader = parse_endpoint(pool_specs[0][0], my_port).is_local
        allow_mint = leader if local_drive_registry is not None else True

    pools = []
    global_idx = 0
    for pool_idx, paths in enumerate(pool_specs):
        disks = []
        any_local = False
        from ..fault.storage import FaultInjectedDisk
        from ..storage.health import HealthCheckedDisk

        for p in paths:
            ep = parse_endpoint(p, my_port)
            if ep.is_local:
                d = XLStorage(ep.path, endpoint=p)
                if local_drive_registry is not None:
                    # the RPC server serves the RAW drive; health wrapping
                    # happens on the calling side
                    local_drive_registry[global_idx] = d
                any_local = True
            else:
                d = StorageRESTClient(
                    ep.host, ep.port, global_idx, internode_token_value, endpoint=p
                )
            # circuit breaker: a dead drive fails fast instead of adding
            # its timeout to every quorum operation. The fault-injection
            # wrapper sits UNDER it so admin-injected chaos (fault/) hits
            # the same breaker/latency accounting real faults do; it costs
            # one flag read per op while no rules are armed.
            disks.append(HealthCheckedDisk(FaultInjectedDisk(d)))
            global_idx += 1
        if not any_local and local_drive_registry is not None:
            raise ValueError(f"pool {pool_idx}: no local drives for this node")
        size = ellipses.choose_set_size(len(disks), set_size)
        dep_id, grouped = init_or_load_formats(disks, size, allow_mint=allow_mint)
        grouped = [
            [d if d is not None else OfflineDisk() for d in row] for row in grouped
        ]
        pools.append(
            ErasureSets(grouped, dep_id, pool_index=pool_idx, ns_lock=ns_lock)
        )
    return ServerPools(pools)


def make_server(
    drive_paths: list[str], region: str = "us-east-1", set_size: int = 0
) -> S3Server:
    return S3Server(make_object_layer(drive_paths, set_size), region)


def main(argv: list[str] | None = None) -> None:
    import argparse

    from ..analysis import sanitizer

    if sanitizer.enabled():
        # before any object-layer construction so instance locks created
        # from here on are witnessed against docs/LOCK_ORDER.md
        sanitizer.install()

    from ..cluster.endpoint import parse_endpoints, remote_nodes
    from ..cluster.locks import LocalLocker, LockRESTServer, NamespaceLock, _RemoteLocker
    from ..cluster.storage_rest import StorageRESTServer, internode_token
    from ..utils import ellipses

    ap = argparse.ArgumentParser(description="minio_tpu S3 server")
    ap.add_argument(
        "drives", nargs="+",
        help="drive dirs, ellipses patterns, or http://host:port/path "
        "endpoints; each ellipses arg is one pool",
    )
    ap.add_argument("--address", default="0.0.0.0:9000")
    ap.add_argument("--set-size", type=int, default=0, help="drives per erasure set")
    ap.add_argument("--ftp", type=int, default=0, help="FTP gateway port (0=off)")
    ap.add_argument("--sftp", type=int, default=0, help="SFTP gateway port (0=off)")
    ap.add_argument(
        "--certs-dir",
        default=os.environ.get("MINIO_TPU_CERTS_DIR", ""),
        help="directory with public.crt/private.key (+ CAs/); enables TLS "
        "for the listener and all internode planes when the pair exists",
    )
    args = ap.parse_args(argv)
    host, _, port = args.address.rpartition(":")
    my_port = int(port)

    # -- SO_REUSEPORT worker pool (server/worker.py) ----------------------
    # The supervisor path never builds a server: it herds N re-executed
    # children, each of which lands here again WITH a worker identity.
    from . import worker as workermod

    wid = workermod.worker_identity()
    if wid is None:
        n_workers = workermod.resolve_worker_count()
        if n_workers > 1:
            import sys

            probe_eps = parse_endpoints(
                [p for spec in args.drives for p in ellipses.expand(spec)],
                my_port,
            )
            raise SystemExit(
                workermod.supervise(
                    list(argv) if argv is not None else sys.argv[1:],
                    n_workers, my_port,
                    distributed=bool(remote_nodes(probe_eps)),
                )
            )
        worker_index, worker_count, worker_port_base = 0, 1, 0
    else:
        worker_index, worker_count, worker_port_base = wid
    worker_siblings = (
        workermod.sibling_peers(worker_index, worker_count, worker_port_base)
        if worker_count > 1
        else []
    )

    # TLS: certs-dir with a keypair turns on https + wss everywhere, with
    # in-place hot reload (reference cmd/common-main.go:942 getTLSConfig)
    from ..crypto import tlsconf

    cert_mgr = None
    if args.certs_dir:
        have_cert = os.path.isfile(os.path.join(args.certs_dir, tlsconf.CERT_FILE))
        have_key = os.path.isfile(os.path.join(args.certs_dir, tlsconf.KEY_FILE))
        if have_cert and have_key:
            cert_mgr = tlsconf.GLOBAL.enable(args.certs_dir)
        elif have_cert or have_key:
            # half a keypair is a misconfiguration, not a plain-HTTP
            # deployment; refuse rather than silently serving cleartext
            raise SystemExit(
                f"certs-dir {args.certs_dir}: need BOTH {tlsconf.CERT_FILE} "
                f"and {tlsconf.KEY_FILE} (found only one)"
            )
        else:
            print(
                f"certs-dir {args.certs_dir}: no {tlsconf.CERT_FILE}/"
                f"{tlsconf.KEY_FILE}; serving plain HTTP", flush=True,
            )

    root_user = os.environ.get("MINIO_ROOT_USER", "minioadmin")
    root_pass = os.environ.get("MINIO_ROOT_PASSWORD", "minioadmin")
    token = internode_token(root_user, root_pass)

    all_eps = parse_endpoints(
        [p for spec in args.drives for p in ellipses.expand(spec)], my_port
    )
    peers = remote_nodes(all_eps)
    distributed = bool(peers)

    registry: dict[int, XLStorage] = {}
    local_locker = LocalLocker()
    # sibling workers are lock peers: a write lock needs a quorum of ALL
    # workers' tables (n/2+1), so two workers mutating the same object
    # serialize exactly like two cluster nodes would (dsync semantics,
    # jittered-retry tie-break and all)
    lockers = [local_locker] + [
        _RemoteLocker(n.split(":")[0], int(n.split(":")[1]), token)
        for n in (*worker_siblings, *peers)
    ]
    ns_lock = NamespaceLock(lockers)

    srv = S3Server(None)
    # cluster peers + sibling workers, for admin/trace/profile fan-out
    # (a worker is just another peer for those planes)
    srv.peers = worker_siblings + peers
    srv.worker_index = worker_index
    srv.worker_count = worker_count
    srv.worker_peers = worker_siblings
    srv.worker_port_base = worker_port_base
    # continuous wall-time attribution (knob-gated, ~19 Hz): scraped as
    # the /api/diag attribution series; None when MINIO_TPU_PROFILE_CONTINUOUS=0
    from . import profiling as _profiling

    srv.cprofiler = _profiling.start_continuous_from_env()
    from ..cluster.grid import GridServer

    storage_srv = StorageRESTServer(registry, token)
    lock_srv = LockRESTServer(local_locker, token)
    storage_srv.register(srv.app)
    lock_srv.register(srv.app)
    # muxed internode RPC: small storage ops + lock ops share one
    # websocket per (peer, plane); HTTP routes above stay as fallback
    grid = GridServer(token)
    storage_srv.register_grid(grid)
    lock_srv.register_grid(grid)
    # cache-invalidation broadcasts ride the same muxed storage plane
    from ..cache import coherence as cache_coherence

    cache_coherence.register_grid(grid)
    # sibling workers receive the same synchronous invalidation
    # broadcasts cluster peers do: a PUT on worker A drops the object
    # from B's and C's caches before the client sees its 200 (loopback
    # siblings get a tighter deadline — a crashed worker must not cost
    # every mutation the cross-node timeout while it restarts)
    cache_coherence.configure(
        worker_siblings + peers, token, worker_peers=worker_siblings
    )
    # netperf echoes ride the same muxed storage plane; the loopback row
    # (this node calling itself over the grid) is the stack floor every
    # peer row is read against
    from ..diag import netperf as diag_netperf

    diag_netperf.register_grid(grid)
    diag_netperf.configure(
        worker_siblings + peers, token, self_addr=f"127.0.0.1:{my_port}"
    )
    grid.register(srv.app)
    from ..cluster import bootstrap as bootmod

    my_syscfg = bootmod.system_config(sorted(str(e) for e in all_eps), salt=token)
    bootmod.BootstrapRESTServer(my_syscfg, token).register(srv.app)

    async def bootstrap():
        import asyncio

        loop = asyncio.get_running_loop()

        def build():
            # in a worker pool only worker 0 may mint a fresh format.json
            # (the others retry below until the layout exists on disk)
            return make_object_layer(
                args.drives, args.set_size, my_port, token, registry, ns_lock,
                allow_mint=None if worker_count == 1 else worker_index == 0,
            )

        if peers:
            # cross-node config consistency check (reference
            # cmd/bootstrap-peer-server.go verifyServerSystemConfig):
            # catches divergent drive lists / MINIO_* env before serving
            problems = await loop.run_in_executor(
                None, bootmod.verify_peers, my_syscfg, peers, token
            )
            for p in problems:
                print(f"bootstrap config check: {p}", flush=True)

        last = None
        for _ in range(180):
            try:
                store = await loop.run_in_executor(None, build)
                # set_store does storage IO (IAM/bucket-config loads, incl.
                # remote RPC) — keep it off the event loop, which must stay
                # responsive for peers' storage/lock RPCs
                await loop.run_in_executor(None, srv.set_store, store)
                print(
                    f"object layer online: {len(store.pools)} pool(s), "
                    f"{len(store.disks)} drives, distributed={distributed}",
                    flush=True,
                )
                return
            except asyncio.CancelledError:
                raise  # server shutdown mid-bootstrap
            except Exception as e:  # noqa: BLE001 — peers may still be booting
                last = e
                await asyncio.sleep(1)
        print(f"bootstrap failed: {last}", flush=True)
        os._exit(1)  # a task-level SystemExit would leave run_app serving 503s

    async def on_start(app):
        # background task: peers bootstrap against each other's storage
        # RPC, so the listener must come up FIRST (on_startup blocks it)
        import asyncio

        async def boot_then_gateways():
            await bootstrap()
            # gateway ports don't SO_REUSEPORT: in a pool only worker 0
            # binds them (a second binder would EADDRINUSE-crash, and
            # the supervisor's crash budget would take the whole pool
            # down over a gateway flag)
            if worker_index > 0 and (args.ftp or args.sftp):
                print(
                    f"worker {worker_index}: FTP/SFTP gateways served by "
                    "worker 0 only", flush=True,
                )
                return
            if args.ftp:
                from .ftp import FTPGateway

                await FTPGateway(srv).serve(host or "0.0.0.0", args.ftp)
                print(f"FTP gateway on port {args.ftp}", flush=True)
            if args.sftp:
                from .sftp import SFTPGateway, load_authorized_keys

                SFTPGateway(
                    srv,
                    authorized_keys=load_authorized_keys(
                        os.environ.get("MINIO_SFTP_AUTHORIZED_KEYS")
                    ),
                ).listen(host or "0.0.0.0", args.sftp)
                print(f"SFTP gateway on port {args.sftp}", flush=True)

        app["bootstrap"] = asyncio.create_task(boot_then_gateways())

        if sanitizer.enabled():
            # stall watchdog on the serving loop: blocking work that the
            # static blocking-reachable pass could not name shows up as
            # obs `type=sanitizer` loop.stall records with the stack
            app["sanitize_watchdog"] = sanitizer.watch_loop(
                asyncio.get_running_loop()
            )
            # access witness: every serving module is imported by now,
            # so the cross-context attributes docs/CONCURRENCY.md names
            # (static races pass) get their touch-recording descriptors
            armed = sanitizer.arm_access_witness()
            if armed:
                print(
                    f"sanitizer: access witness armed on {armed} "
                    "attributes", flush=True,
                )
            # leak witness: resource classes from the static ownership
            # table (docs/RESOURCES.md) get weakref finalizers — a
            # handle collected unreleased reports `resource.leak`
            leak_armed = sanitizer.arm_leak_witness()
            if leak_armed:
                print(
                    f"sanitizer: leak witness armed on {leak_armed} "
                    "resource classes", flush=True,
                )

    async def on_stop(app):
        wd = app.get("sanitize_watchdog")
        if wd is not None:
            wd.stop()

    srv.app.on_startup.append(on_start)
    srv.app.on_cleanup.append(on_stop)
    # explicit runner instead of run_app: read_bufsize lifts aiohttp's
    # 64 KiB StreamReader watermark, which otherwise pause/resumes the
    # transport 16x per MiB on large streaming PUTs (hot-path cost on the
    # single-core bench host)
    import asyncio as _asyncio
    import signal as _signal

    async def _serve():
        runner = web.AppRunner(
            srv.app, read_bufsize=int(
                os.environ.get("MINIO_TPU_HTTP_READBUF", str(4 << 20))
            ),
        )
        await runner.setup()
        site = web.TCPSite(
            runner, host or "0.0.0.0", my_port,
            ssl_context=cert_mgr.ctx if cert_mgr else None,
            # worker pool: every worker binds the SAME port; the kernel
            # load-balances accepted connections across them
            reuse_port=True if worker_count > 1 else None,
        )
        await site.start()
        if worker_count > 1:
            # per-worker loopback control listener: SO_REUSEPORT makes
            # the shared port land on an ARBITRARY worker, so siblings
            # (coherence broadcasts, lock RPCs, admin/metrics fan-out)
            # address each worker here. Same app, same auth.
            ctrl = web.TCPSite(
                runner, "127.0.0.1",
                workermod.control_port(worker_port_base, worker_index),
                ssl_context=cert_mgr.ctx if cert_mgr else None,
            )
            await ctrl.start()
            print(
                f"worker {worker_index}/{worker_count} serving "
                f"{args.address} (shared), control port "
                f"{workermod.control_port(worker_port_base, worker_index)}",
                flush=True,
            )
        cert_watcher = None
        if cert_mgr is not None:
            print(f"serving https on {args.address}", flush=True)

            async def _watch_certs():
                while True:
                    await _asyncio.sleep(2.0)
                    if cert_mgr.maybe_reload(min_interval=0.0):
                        # internode dialers must re-anchor trust too when
                        # the deployment pins the shared public.crt
                        tlsconf.GLOBAL.refresh_client_context()
                        print("TLS certificate reloaded", flush=True)

            # keep a strong reference: asyncio tasks are weakly held and
            # an unreferenced watcher would be GC-collected mid-flight
            cert_watcher = _asyncio.get_running_loop().create_task(
                _watch_certs()
            )
        stop = _asyncio.Event()
        loop = _asyncio.get_running_loop()
        for sig in (_signal.SIGINT, _signal.SIGTERM):
            try:
                loop.add_signal_handler(sig, stop.set)
            except NotImplementedError:  # non-unix
                pass
        await stop.wait()
        if cert_watcher is not None:
            cert_watcher.cancel()
        # teardown order matters: stop the background planes FIRST (the
        # scanner/heal threads broadcast invalidations, which would
        # re-dial the grid right after we close it), THEN close our
        # OUTGOING grid connections — the sibling/peer server holds a
        # parked websocket handler per connection and its graceful drain
        # waits for ours to close (two pool workers stopping together
        # would otherwise stall each other's cleanup for the full
        # shutdown timeout; the supervisor's SIGKILL grace is the
        # backstop for a mid-sweep straggler that re-dials anyway)
        srv.close()  # stop IAM refresh/watch + scanner threads
        from ..cluster import grid as gridmod

        gridmod.close_shared_clients()
        await runner.cleanup()  # close listeners, drain in-flight requests

    try:
        _asyncio.run(_serve())
    except KeyboardInterrupt:
        pass


if __name__ == "__main__":
    main()
