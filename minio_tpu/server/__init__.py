"""S3-compatible HTTP API surface (L5/L6): auth, routing, handlers."""
