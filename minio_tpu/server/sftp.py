"""SFTP frontend — the second protocol gateway over the object layer.

Mirrors the reference's SFTP server (/root/reference/cmd/sftp-server.go:
an x/crypto/ssh server whose handlers drive the ObjectLayer): buckets are
top-level directories, objects are files, IAM credentials authenticate
(username = access key, password = secret key) and the caller's policies
govern every operation — the same checks the S3 API applies. Runs on the
from-scratch SSH transport in server/ssh.py (SFTP protocol version 3).

Reads are served as true ranged reads against the erasure layer; writes
spool to a temp file and commit as one object PUT on close (SFTP write
offsets are not guaranteed sequential). Enable with --sftp <port>.
"""

from __future__ import annotations

import io
import posixpath
import socket
import stat as stat_mod
import struct
import threading

from ..erasure import listing, quorum
from .ssh import (
    MSG_CHANNEL_CLOSE,
    MSG_CHANNEL_DATA,
    MSG_CHANNEL_EOF,
    MSG_CHANNEL_OPEN,
    MSG_CHANNEL_OPEN_CONFIRMATION,
    MSG_CHANNEL_OPEN_FAILURE,
    MSG_CHANNEL_REQUEST,
    MSG_CHANNEL_SUCCESS,
    MSG_CHANNEL_WINDOW_ADJUST,
    MSG_SERVICE_ACCEPT,
    MSG_SERVICE_REQUEST,
    MSG_USERAUTH_FAILURE,
    MSG_USERAUTH_REQUEST,
    MSG_USERAUTH_SUCCESS,
    Reader,
    SSHError,
    SSHTransport,
    wstr,
    wu32,
)

# SFTP v3 (draft-ietf-secsh-filexfer-02) packet types
FXP_INIT, FXP_VERSION = 1, 2
FXP_OPEN, FXP_CLOSE, FXP_READ, FXP_WRITE = 3, 4, 5, 6
FXP_LSTAT, FXP_FSTAT, FXP_SETSTAT, FXP_FSETSTAT = 7, 8, 9, 10
FXP_OPENDIR, FXP_READDIR, FXP_REMOVE, FXP_MKDIR, FXP_RMDIR = 11, 12, 13, 14, 15
FXP_REALPATH, FXP_STAT, FXP_RENAME = 16, 17, 18
FXP_STATUS, FXP_HANDLE, FXP_DATA, FXP_NAME, FXP_ATTRS = 101, 102, 103, 104, 105

FX_OK, FX_EOF, FX_NO_SUCH_FILE, FX_PERMISSION_DENIED = 0, 1, 2, 3
FX_FAILURE, FX_BAD_MESSAGE, FX_OP_UNSUPPORTED = 4, 5, 8

PF_READ, PF_WRITE, PF_APPEND, PF_CREAT, PF_TRUNC, PF_EXCL = 1, 2, 4, 8, 16, 32

ATTR_SIZE, ATTR_UIDGID, ATTR_PERMISSIONS, ATTR_ACMODTIME = 0x1, 0x2, 0x4, 0x8


def _attrs(size: int = 0, is_dir: bool = False, mtime: int = 0) -> bytes:
    perms = (stat_mod.S_IFDIR | 0o755) if is_dir else (stat_mod.S_IFREG | 0o644)
    return (
        wu32(ATTR_SIZE | ATTR_PERMISSIONS | ATTR_ACMODTIME)
        + struct.pack(">Q", size)
        + wu32(perms)
        + wu32(mtime)
        + wu32(mtime)
    )


def _skip_attrs(r: Reader) -> None:
    flags = r.u32()
    if flags & ATTR_SIZE:
        r.u64()
    if flags & ATTR_UIDGID:
        r.u32(), r.u32()
    if flags & ATTR_PERMISSIONS:
        r.u32()
    if flags & ATTR_ACMODTIME:
        r.u32(), r.u32()


class _ReadHandle:
    def __init__(self, oi, handle):
        self.oi = oi
        self.handle = handle  # erasure ObjectHandle

    def read(self, off: int, n: int) -> bytes:
        if off >= self.oi.size:
            return b""
        n = min(n, self.oi.size - off)
        return b"".join(self.handle.read(off, n, close_when_done=False))

    def close(self):
        self.handle.close()


class _WriteHandle:
    """Random-offset writes spool to a temp file (memory only while small)
    and commit as one object PUT on close; opening an existing object
    without TRUNC preloads its bytes so append/resume does not zero-fill
    the prefix."""

    def __init__(self, bucket: str, key: str, initial: bytes = b""):
        import tempfile

        self.bucket = bucket
        self.key = key
        self.spool = tempfile.SpooledTemporaryFile(max_size=8 << 20)
        if initial:
            self.spool.write(initial)

    def write(self, off: int, data: bytes) -> None:
        self.spool.seek(off)
        self.spool.write(data)

    def getvalue(self) -> bytes:
        self.spool.seek(0)
        return self.spool.read()

    def size(self) -> int:
        self.spool.seek(0, 2)
        return self.spool.tell()

    def close(self):
        self.spool.close()


class _DirHandle:
    def __init__(self, entries: list[tuple[str, int, bool, int]]):
        self.entries = entries
        self.pos = 0


def load_authorized_keys(path: str | None) -> dict[str, set[bytes]]:
    """Parse an authorized-keys map: one `<access_key> ssh-ed25519 <b64>`
    per line (set MINIO_SFTP_AUTHORIZED_KEYS to the file path)."""
    import base64

    out: dict[str, set[bytes]] = {}
    if not path:
        return out
    try:
        lines = open(path, encoding="utf-8").read().splitlines()
    except OSError:
        return out
    for line in lines:
        parts = line.split()
        if len(parts) >= 3 and parts[1] == "ssh-ed25519":
            try:
                # the base64 field IS the wire blob: string "ssh-ed25519"
                # + string raw-key (standard OpenSSH public key encoding)
                out.setdefault(parts[0], set()).add(base64.b64decode(parts[2]))
            except ValueError:
                continue
    return out


class SFTPGateway:
    """Accept loop + per-connection SSH/SFTP service."""

    def __init__(self, server, host_key=None, authorized_keys=None):
        from . import ssh as sshmod

        self.server = server  # S3Server (store, iam, ...)
        self.host_key = host_key or sshmod.generate_host_key()
        # user -> set of ssh-ed25519 public key blobs trusted for key auth
        # (the reference trusts keys via its user-CA; ours are registered
        # directly, e.g. loaded from MINIO_SFTP_AUTHORIZED_KEYS)
        self.authorized_keys: dict[str, set[bytes]] = {
            u: set(ks) for u, ks in (authorized_keys or {}).items()
        }
        self._sock: socket.socket | None = None
        self._stopped = False

    @property
    def store(self):
        return self.server.store

    def listen(self, host: str, port: int) -> int:
        self._sock = socket.socket()
        self._sock.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        self._sock.bind((host, port))
        self._sock.listen(16)
        t = threading.Thread(target=self._accept_loop, daemon=True)
        t.start()
        return self._sock.getsockname()[1]

    def close(self) -> None:
        self._stopped = True
        if self._sock is not None:
            try:
                self._sock.close()
            except OSError:
                pass

    def _accept_loop(self) -> None:
        while not self._stopped:
            try:
                conn, _ = self._sock.accept()
            except OSError:
                return
            threading.Thread(
                target=self._serve_conn, args=(conn,), daemon=True
            ).start()

    # -- auth --------------------------------------------------------------

    def _check_password(self, user: str, password: str) -> bool:
        iam = self.server.iam
        secret = iam.lookup_secret(user)
        if secret is None or not password:
            return False
        import hmac as _h

        return _h.compare_digest(secret, password)

    def _allowed(self, user: str, action: str, bucket: str, key: str = "") -> bool:
        """Same decision path as the S3 API (server._authorize): identity
        policies AND bucket policies, so a bucket-policy Deny binds SFTP
        exactly as it binds S3/FTP."""
        from . import s3err

        try:
            self.server._authorize(user, action, bucket, key)
            return True
        except s3err.APIError:
            return False

    # -- SSH connection service -------------------------------------------

    def _serve_conn(self, sock: socket.socket) -> None:
        sock.settimeout(300)
        tr = SSHTransport(sock, "server", host_key=self.host_key)
        sftp_box: list = [None]
        try:
            tr.handshake()
            user = self._userauth(tr)
            if user is None:
                return
            self._connection_loop(tr, user, sftp_box)
        except Exception:  # noqa: BLE001 — per-connection isolation: a bad
            pass  # client must never take down the gateway
        finally:
            # abrupt disconnects must still release read handles (each
            # holds a namespace read lock until closed)
            if sftp_box[0] is not None:
                sftp_box[0].shutdown()
            try:
                sock.close()
            except OSError:
                pass

    def _userauth(self, tr: SSHTransport) -> str | None:
        t, r = tr.read_msg()
        if t != MSG_SERVICE_REQUEST or r.str_() != b"ssh-userauth":
            raise SSHError("expected ssh-userauth service request")
        tr.send_packet(bytes([MSG_SERVICE_ACCEPT]) + wstr("ssh-userauth"))
        from cryptography.exceptions import InvalidSignature
        from cryptography.hazmat.primitives.asymmetric import ed25519

        from . import ssh as sshmod

        for _ in range(8):  # bounded attempts
            t, r = tr.read_msg()
            if t != MSG_USERAUTH_REQUEST:
                raise SSHError(f"expected USERAUTH_REQUEST, got {t}")
            user = r.str_().decode()
            r.str_()  # service
            method = r.str_()
            if method == b"password":
                r.bool_()
                password = r.str_().decode()
                if self._check_password(user, password):
                    tr.send_packet(bytes([MSG_USERAUTH_SUCCESS]))
                    return user
            elif method == b"publickey":
                has_sig = r.bool_()
                algo = r.str_()
                blob = r.str_()
                trusted = (
                    algo == b"ssh-ed25519"
                    and blob in self.authorized_keys.get(user, ())
                )
                if trusted and not has_sig:
                    # probe phase (RFC 4252 §7): tell the client this key
                    # would be accepted
                    tr.send_packet(
                        bytes([sshmod.MSG_USERAUTH_PK_OK]) + wstr(algo) + wstr(blob)
                    )
                    continue
                if trusted and has_sig:
                    sig_blob = r.str_()
                    sr = Reader(sig_blob)
                    try:
                        if sr.str_() != b"ssh-ed25519":
                            raise InvalidSignature
                        kr = Reader(blob)
                        if kr.str_() != b"ssh-ed25519":
                            raise InvalidSignature
                        pub = ed25519.Ed25519PublicKey.from_public_bytes(kr.str_())
                        pub.verify(
                            sr.str_(),
                            sshmod.publickey_auth_blob(
                                tr.session_id, user, algo, blob
                            ),
                        )
                        tr.send_packet(bytes([MSG_USERAUTH_SUCCESS]))
                        return user
                    except (InvalidSignature, SSHError, ValueError):
                        pass
            tr.send_packet(
                bytes([MSG_USERAUTH_FAILURE])
                + wstr(b"password,publickey") + b"\x00"
            )
        return None

    def _connection_loop(self, tr: SSHTransport, user: str, sftp_box: list) -> None:
        sftp: _SFTPSession | None = None
        chan_id = None
        peer_window = 0
        out_max = 32768

        def send_data(data: bytes) -> None:
            nonlocal peer_window
            # window handling: block-free best effort — standard clients
            # grant multi-MB windows up front
            for i in range(0, len(data), out_max):
                chunk = data[i : i + out_max]
                peer_window -= len(chunk)
                tr.send_packet(
                    bytes([MSG_CHANNEL_DATA]) + wu32(chan_id) + wstr(chunk)
                )

        consumed = 0
        while True:
            t, r = tr.read_msg()
            if t == MSG_CHANNEL_OPEN:
                ctype = r.str_()
                sender = r.u32()
                init_win = r.u32()
                r.u32()  # max packet
                if ctype != b"session" or chan_id is not None:
                    tr.send_packet(
                        bytes([MSG_CHANNEL_OPEN_FAILURE])
                        + wu32(sender) + wu32(4) + wstr("only one session") + wstr("")
                    )
                    continue
                chan_id = sender
                peer_window = init_win
                tr.send_packet(
                    bytes([MSG_CHANNEL_OPEN_CONFIRMATION])
                    + wu32(sender) + wu32(0) + wu32(1 << 30) + wu32(out_max)
                )
            elif t == MSG_CHANNEL_REQUEST:
                r.u32()
                rtype = r.str_()
                want_reply = r.bool_()
                ok = rtype == b"subsystem" and r.str_() == b"sftp"
                if ok:
                    sftp = _SFTPSession(self, user, send_data)
                    sftp_box[0] = sftp
                if want_reply:
                    tr.send_packet(
                        bytes([MSG_CHANNEL_SUCCESS if ok else MSG_CHANNEL_FAILURE])
                        + wu32(chan_id)
                    )
            elif t == MSG_CHANNEL_DATA:
                r.u32()
                data = r.str_()
                consumed += len(data)
                if sftp is not None:
                    sftp.feed(data)
                if consumed > 1 << 29:  # replenish our receive window
                    tr.send_packet(
                        bytes([MSG_CHANNEL_WINDOW_ADJUST]) + wu32(chan_id) + wu32(consumed)
                    )
                    consumed = 0
            elif t == MSG_CHANNEL_WINDOW_ADJUST:
                r.u32()
                peer_window += r.u32()
            elif t in (MSG_CHANNEL_EOF, MSG_CHANNEL_CLOSE):
                if sftp is not None:
                    sftp.shutdown()
                if t == MSG_CHANNEL_CLOSE:
                    tr.send_packet(bytes([MSG_CHANNEL_CLOSE]) + wu32(chan_id))
                    return
            else:
                pass  # ignore global requests etc.


class _SFTPSession:
    """SFTP v3 packet handler over one channel."""

    def __init__(self, gw: SFTPGateway, user: str, send):
        self.gw = gw
        self.user = user
        self.send = send
        self.buf = b""
        self.handles: dict[bytes, object] = {}
        self.hseq = 0

    # -- plumbing ----------------------------------------------------------

    def feed(self, data: bytes) -> None:
        self.buf += data
        while len(self.buf) >= 4:
            n = struct.unpack(">I", self.buf[:4])[0]
            if len(self.buf) < 4 + n:
                return
            pkt = self.buf[4 : 4 + n]
            self.buf = self.buf[4 + n :]
            self._dispatch(pkt)

    def shutdown(self) -> None:
        for h in list(self.handles.values()):
            try:
                if hasattr(h, "close"):
                    h.close()
            except Exception:  # noqa: BLE001
                pass
        self.handles.clear()

    def _reply(self, payload: bytes) -> None:
        self.send(struct.pack(">I", len(payload)) + payload)

    def _status(self, rid: int, code: int, msg: str = "") -> None:
        self._reply(
            bytes([FXP_STATUS]) + wu32(rid) + wu32(code) + wstr(msg) + wstr("")
        )

    def _new_handle(self, obj) -> bytes:
        self.hseq += 1
        h = b"h%d" % self.hseq
        self.handles[h] = obj
        return h

    # -- path mapping ------------------------------------------------------

    @staticmethod
    def _norm(path: str) -> str:
        p = posixpath.normpath("/" + path.strip())
        return "/" if p in (".", "//") else p

    @staticmethod
    def _split(path: str) -> tuple[str, str]:
        parts = path.strip("/").split("/", 1)
        return (parts[0] if parts[0] else ""), (parts[1] if len(parts) > 1 else "")

    # -- dispatch ----------------------------------------------------------

    def _dispatch(self, pkt: bytes) -> None:
        t = pkt[0]
        r = Reader(pkt[1:])
        if t == FXP_INIT:
            self._reply(bytes([FXP_VERSION]) + wu32(3))
            return
        rid = r.u32()
        try:
            handler = {
                FXP_REALPATH: self._realpath,
                FXP_STAT: self._stat,
                FXP_LSTAT: self._stat,
                FXP_FSTAT: self._fstat,
                FXP_OPENDIR: self._opendir,
                FXP_READDIR: self._readdir,
                FXP_OPEN: self._open,
                FXP_CLOSE: self._close,
                FXP_READ: self._read,
                FXP_WRITE: self._write,
                FXP_REMOVE: self._remove,
                FXP_MKDIR: self._mkdir,
                FXP_RMDIR: self._rmdir,
                FXP_RENAME: self._rename,
                FXP_SETSTAT: self._setstat,
                FXP_FSETSTAT: self._fsetstat,
            }.get(t)
            if handler is None:
                self._status(rid, FX_OP_UNSUPPORTED, "unsupported")
                return
            handler(rid, r)
        except (quorum.ObjectNotFound, quorum.VersionNotFound, quorum.BucketNotFound):
            self._status(rid, FX_NO_SUCH_FILE, "not found")
        except PermissionError:
            self._status(rid, FX_PERMISSION_DENIED, "access denied")
        except Exception as e:  # noqa: BLE001 — protocol must answer
            self._status(rid, FX_FAILURE, str(e)[:200])

    def _authz(self, action: str, bucket: str, key: str = "") -> None:
        if not self.gw._allowed(self.user, action, bucket, key):
            raise PermissionError(action)

    # -- handlers ----------------------------------------------------------

    def _realpath(self, rid: int, r: Reader) -> None:
        p = self._norm(r.str_().decode())
        self._reply(
            bytes([FXP_NAME]) + wu32(rid) + wu32(1)
            + wstr(p) + wstr(p) + _attrs(is_dir=True)
        )

    def _stat(self, rid: int, r: Reader) -> None:
        p = self._norm(r.str_().decode())
        bucket, key = self._split(p)
        if not bucket:
            self._reply(bytes([FXP_ATTRS]) + wu32(rid) + _attrs(is_dir=True))
            return
        if not key:
            if not self.gw.store.bucket_exists(bucket):
                self._status(rid, FX_NO_SUCH_FILE, "no such bucket")
                return
            self._reply(bytes([FXP_ATTRS]) + wu32(rid) + _attrs(is_dir=True))
            return
        self._authz("s3:GetObject", bucket, key)
        try:
            oi = self.gw.store.get_object_info(bucket, key)
            self._reply(
                bytes([FXP_ATTRS]) + wu32(rid)
                + _attrs(oi.size, False, int(oi.mod_time / 1e9))
            )
            return
        except (quorum.ObjectNotFound, quorum.VersionNotFound):
            pass
        # a prefix with content is a directory
        res = self._list(bucket, key.rstrip("/") + "/", max_keys=1)
        if res.objects or res.prefixes:
            self._reply(bytes([FXP_ATTRS]) + wu32(rid) + _attrs(is_dir=True))
        else:
            self._status(rid, FX_NO_SUCH_FILE, "no such key")

    def _list(self, bucket: str, prefix: str, max_keys: int = 1000,
              delimiter: str = "/", marker: str = ""):
        return listing.list_objects(
            self.gw.store, bucket, prefix=prefix, marker=marker,
            delimiter=delimiter, max_keys=max_keys,
        )

    def _fstat(self, rid: int, r: Reader) -> None:
        h = self.handles.get(r.str_())
        if isinstance(h, _ReadHandle):
            self._reply(
                bytes([FXP_ATTRS]) + wu32(rid)
                + _attrs(h.oi.size, False, int(h.oi.mod_time / 1e9))
            )
        elif isinstance(h, _WriteHandle):
            self._reply(bytes([FXP_ATTRS]) + wu32(rid) + _attrs(h.size()))
        else:
            self._status(rid, FX_BAD_MESSAGE, "bad handle")

    def _opendir(self, rid: int, r: Reader) -> None:
        p = self._norm(r.str_().decode())
        bucket, key = self._split(p)
        entries: list[tuple[str, int, bool, int]] = []
        if not bucket:
            self._authz("s3:ListAllMyBuckets", "*")
            for b in self.gw.store.list_buckets():
                entries.append((b.name, 0, True, b.created // 10**9))
        else:
            self._authz("s3:ListBucket", bucket)
            prefix = key.rstrip("/") + "/" if key else ""
            marker = ""
            while len(entries) < 200_000:  # paginate; bound a runaway dir
                res = self._list(bucket, prefix, marker=marker)
                for o in res.objects:
                    name = o.name[len(prefix):]
                    if name:
                        entries.append((name, o.size, False, int(o.mod_time / 1e9)))
                for pfx in res.prefixes:
                    name = pfx[len(prefix):].rstrip("/")
                    if name:
                        entries.append((name, 0, True, 0))
                if not res.is_truncated:
                    break
                marker = res.next_marker
        self._reply(
            bytes([FXP_HANDLE]) + wu32(rid) + wstr(self._new_handle(_DirHandle(entries)))
        )

    def _readdir(self, rid: int, r: Reader) -> None:
        h = self.handles.get(r.str_())
        if not isinstance(h, _DirHandle):
            self._status(rid, FX_BAD_MESSAGE, "bad handle")
            return
        if h.pos >= len(h.entries):
            self._status(rid, FX_EOF)
            return
        batch = h.entries[h.pos : h.pos + 100]
        h.pos += len(batch)
        out = bytes([FXP_NAME]) + wu32(rid) + wu32(len(batch))
        for name, size, is_dir, mtime in batch:
            longname = "%s %12d %s" % ("drwxr-xr-x" if is_dir else "-rw-r--r--", size, name)
            out += wstr(name) + wstr(longname) + _attrs(size, is_dir, mtime)
        self._reply(out)

    def _open(self, rid: int, r: Reader) -> None:
        p = self._norm(r.str_().decode())
        flags = 0
        try:
            flags = r.u32()
            _skip_attrs(r)
        except (IndexError, SSHError):
            pass
        bucket, key = self._split(p)
        if not bucket or not key:
            self._status(rid, FX_FAILURE, "not a file path")
            return
        if flags & PF_WRITE:
            self._authz("s3:PutObject", bucket, key)
            initial = b""
            exists = False
            try:
                self.gw.store.get_object_info(bucket, key)
                exists = True
            except (quorum.ObjectNotFound, quorum.VersionNotFound):
                pass
            if exists and flags & PF_EXCL:
                self._status(rid, FX_FAILURE, "exists")
                return
            if exists and not flags & PF_TRUNC:
                # append/resume semantics: start from the current bytes,
                # otherwise offset writes would zero-fill the prefix
                self._authz("s3:GetObject", bucket, key)
                _, it = self.gw.store.get_object(bucket, key)
                initial = b"".join(it)
            self._reply(
                bytes([FXP_HANDLE]) + wu32(rid)
                + wstr(self._new_handle(_WriteHandle(bucket, key, initial)))
            )
            return
        self._authz("s3:GetObject", bucket, key)
        oi, handle = self.gw.store.open_object(bucket, key)
        self._reply(
            bytes([FXP_HANDLE]) + wu32(rid)
            + wstr(self._new_handle(_ReadHandle(oi, handle)))
        )

    def _close(self, rid: int, r: Reader) -> None:
        hid = r.str_()
        h = self.handles.pop(hid, None)
        if h is None:
            self._status(rid, FX_BAD_MESSAGE, "bad handle")
            return
        if isinstance(h, _WriteHandle):
            try:
                self.gw.store.put_object(h.bucket, h.key, h.getvalue())
            finally:
                h.close()
        elif isinstance(h, _ReadHandle):
            h.close()
        self._status(rid, FX_OK)

    def _read(self, rid: int, r: Reader) -> None:
        h = self.handles.get(r.str_())
        off = r.u64()
        n = min(r.u32(), 1 << 20)
        if not isinstance(h, _ReadHandle):
            self._status(rid, FX_BAD_MESSAGE, "bad handle")
            return
        data = h.read(off, n)
        if not data:
            self._status(rid, FX_EOF)
        else:
            self._reply(bytes([FXP_DATA]) + wu32(rid) + wstr(data))

    def _write(self, rid: int, r: Reader) -> None:
        h = self.handles.get(r.str_())
        off = r.u64()
        data = r.str_()
        if not isinstance(h, _WriteHandle):
            self._status(rid, FX_BAD_MESSAGE, "bad handle")
            return
        if off + len(data) > 5 << 30:
            self._status(rid, FX_FAILURE, "too large for spooled write")
            return
        h.write(off, data)
        self._status(rid, FX_OK)

    def _remove(self, rid: int, r: Reader) -> None:
        bucket, key = self._split(self._norm(r.str_().decode()))
        if not bucket or not key:
            self._status(rid, FX_FAILURE, "not a file path")
            return
        self._authz("s3:DeleteObject", bucket, key)
        self.gw.store.get_object_info(bucket, key)  # 404 if absent
        self.gw.store.delete_object(bucket, key)
        self._status(rid, FX_OK)

    def _mkdir(self, rid: int, r: Reader) -> None:
        bucket, key = self._split(self._norm(r.str_().decode()))
        if not bucket:
            self._status(rid, FX_FAILURE, "mkdir /: invalid")
            return
        if not key:
            self._authz("s3:CreateBucket", bucket)
            self.gw.store.make_bucket(bucket)
        else:
            self._authz("s3:PutObject", bucket, key)
            self.gw.store.put_object(
                bucket, listing.encode_dir_object(key.rstrip("/") + "/"), b""
            )
        self._status(rid, FX_OK)

    def _rmdir(self, rid: int, r: Reader) -> None:
        bucket, key = self._split(self._norm(r.str_().decode()))
        if not bucket:
            self._status(rid, FX_FAILURE, "rmdir /: invalid")
            return
        if not key:
            self._authz("s3:DeleteBucket", bucket)
            self.gw.store.delete_bucket(bucket)
        else:
            self._authz("s3:DeleteObject", bucket, key)
            try:
                self.gw.store.delete_object(
                    bucket, listing.encode_dir_object(key.rstrip("/") + "/")
                )
            except (quorum.ObjectNotFound, quorum.VersionNotFound):
                pass
        self._status(rid, FX_OK)

    def _rename(self, rid: int, r: Reader) -> None:
        src = self._split(self._norm(r.str_().decode()))
        dst = self._split(self._norm(r.str_().decode()))
        if not all([src[0], src[1], dst[0], dst[1]]):
            self._status(rid, FX_OP_UNSUPPORTED, "bucket rename unsupported")
            return
        self._authz("s3:GetObject", src[0], src[1])
        self._authz("s3:PutObject", dst[0], dst[1])
        self._authz("s3:DeleteObject", src[0], src[1])
        oi, it = self.gw.store.get_object(src[0], src[1])
        data = b"".join(it)
        self.gw.store.put_object(dst[0], dst[1], data, user_defined=dict(oi.user_defined))
        self.gw.store.delete_object(src[0], src[1])
        self._status(rid, FX_OK)

    def _setstat(self, rid: int, r: Reader) -> None:
        self._status(rid, FX_OK)  # chmod/utime have no object-store meaning

    def _fsetstat(self, rid: int, r: Reader) -> None:
        self._status(rid, FX_OK)
