"""aws-chunked payload decoding (SigV4 streaming uploads).

Mirrors /root/reference/cmd/streaming-signature-v4.go (signed chunks) and
streaming-v4-unsigned.go (unsigned trailer chunks): bodies arrive as
    <hex-size>[;chunk-signature=<sig>]\r\n<bytes>\r\n ... 0[;...]\r\n[trailers]
Signed mode verifies the per-chunk signature chain seeded by the request
signature.
"""

from __future__ import annotations

import hashlib
import hmac

from . import s3err
from .signature import SIGN_V4_ALGORITHM, signing_key

EMPTY_SHA = hashlib.sha256(b"").hexdigest()


def decode_signed_chunked(
    body: bytes,
    seed_signature: str,
    amz_date: str,
    scope: str,
    secret_key: str,
    trailer_mode: bool = False,
) -> bytes:
    """Decode + verify STREAMING-AWS4-HMAC-SHA256-PAYLOAD bodies.

    Chunk signature chain: each chunk's string-to-sign commits to the
    previous signature and the chunk hash; the seed is the request
    signature (reference: buildChunkStringToSign).
    """
    scope_date, region, service, _ = scope.split("/")
    key = signing_key(secret_key, scope_date, region, service)
    prev = seed_signature
    out = bytearray()
    pos = 0
    while True:
        nl = body.find(b"\r\n", pos)
        if nl < 0:
            raise s3err.IncompleteBody
        header = body[pos:nl].decode("latin1")
        parts = header.split(";")
        try:
            size = int(parts[0].strip(), 16)
        except ValueError:
            raise s3err.IncompleteBody from None
        sig = ""
        for p in parts[1:]:
            if p.startswith("chunk-signature="):
                sig = p[len("chunk-signature=") :].strip()
        pos = nl + 2
        chunk = body[pos : pos + size]
        if len(chunk) != size:
            raise s3err.IncompleteBody
        if trailer_mode and size == 0 and not sig:
            # trailer mode: the final 0-chunk carries no chunk-signature;
            # integrity of the trailers rides x-amz-trailer-signature
            # (content already chain-verified chunk by chunk)
            return bytes(out)
        sts = "\n".join(
            [
                f"{SIGN_V4_ALGORITHM}-PAYLOAD",
                amz_date,
                scope,
                prev,
                EMPTY_SHA,
                hashlib.sha256(chunk).hexdigest(),
            ]
        )
        want = hmac.new(key, sts.encode(), hashlib.sha256).hexdigest()
        if not hmac.compare_digest(want, sig):
            raise s3err.SignatureDoesNotMatch
        prev = want
        if size == 0:
            return bytes(out)
        out += chunk
        pos += size + 2
