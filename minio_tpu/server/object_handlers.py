"""Object-level S3 handlers: put/get/head/delete/copy, ranges and
preconditions, tiering restore, retention/legal-hold/tagging, Select,
object lambda, multi-delete.

Split from app.py (the reference's cmd/object-handlers.go)."""

from __future__ import annotations

import asyncio
import hashlib
import os
import urllib.parse
import xml.etree.ElementTree as ET
from email.utils import parsedate_to_datetime
from xml.sax.saxutils import escape

from aiohttp import web

from ..erasure import listing, quorum
from ..erasure.types import ObjectInfo
from . import s3err, signature
from .handler_utils import (
    _restored_locally,
    _verify_checksum_headers,
    _bucket_sse_algo,
    _iso8601,
    _http_date,
)


class ObjectHandlersMixin:
    def _parity_for_storage_class(self, request) -> int | None:
        """Per-request EC parity from x-amz-storage-class (reference
        cmd/erasure-object.go:1299 + internal/config/storageclass):
        STANDARD uses MINIO_STORAGE_CLASS_STANDARD when set,
        REDUCED_REDUNDANCY uses MINIO_STORAGE_CLASS_RRS (default EC:2).
        Unknown classes (e.g. tier names) keep the set default."""
        sc = request.headers.get("x-amz-storage-class", "")
        if not sc or sc == "STANDARD":
            spec = os.environ.get("MINIO_STORAGE_CLASS_STANDARD", "")
        elif sc == "REDUCED_REDUNDANCY":
            spec = os.environ.get("MINIO_STORAGE_CLASS_RRS", "EC:2")
        else:
            return None
        if not spec.startswith("EC:"):
            return None
        try:
            p = int(spec[3:])
        except ValueError:
            return None
        n = getattr(self.store, "n", 0)
        if n < 2:
            return None
        return max(1, min(p, n // 2))

    def _family_for_storage_class(self, request) -> str | None:
        """Per-request erasure code family from x-amz-storage-class:
        MINIO_TPU_EC_FAMILY_STANDARD / MINIO_TPU_EC_FAMILY_RRS override
        the node-wide MINIO_TPU_EC_FAMILY for their class; the family is
        recorded in xl.meta so reads/heals of existing objects never
        depend on these knobs. None defers to the erasure layer default
        (which reads MINIO_TPU_EC_FAMILY itself)."""
        from ..erasure.bitrot_io import FAMILIES

        sc = request.headers.get("x-amz-storage-class", "")
        if not sc or sc == "STANDARD":
            fam = os.environ.get("MINIO_TPU_EC_FAMILY_STANDARD", "")
        elif sc == "REDUCED_REDUNDANCY":
            fam = os.environ.get("MINIO_TPU_EC_FAMILY_RRS", "")
        else:
            fam = ""
        return fam if fam in FAMILIES else None

    async def _proxy_get_remote(self, request, bucket, key, vid=""):
        """Serve a not-yet-replicated object from a replication target.

        Returns None when no target has it (or proxying is disabled /
        this request already IS a proxy — loop breaker). Streams the
        remote body chunk by chunk — a lagging multi-GB object must not
        be buffered whole per request."""
        if request.headers.get("x-minio-source-proxy-request") == "true":
            return None
        if os.environ.get("MINIO_TPU_REPLICATION_PROXY", "on") == "off":
            return None
        if not self.buckets.get(bucket).versioning:
            # the reference requires versioning for replication; without it
            # a hard delete leaves no local trace and proxying would
            # resurrect deleted objects
            return None
        targets = self.repl_targets.list(bucket)
        if not targets:
            return None
        # only proxy when the object has NO local trace: a local delete
        # marker (or any version) means the 404 is authoritative — proxying
        # would resurrect deleted objects from a lagging peer
        try:
            if await self._run(self.store.list_object_versions, bucket, key):
                return None
        except asyncio.CancelledError:
            raise
        except Exception:  # noqa: BLE001 — degraded listing: don't proxy
            return None
        hdrs = {"x-minio-source-proxy-request": "true"}
        rng = request.headers.get("Range")
        if rng:
            hdrs["Range"] = rng

        import http.client as _hc

        from .signature import sign_request

        def open_remote():
            """(status, resp-headers, http response) from the first target
            that has the object, None otherwise."""
            q = f"?versionId={urllib.parse.quote(vid)}" if vid else ""
            for t in targets:
                try:
                    path = "/" + t.target_bucket + "/" + urllib.parse.quote(key, safe="/~-._") + q
                    url = f"http://{t.endpoint.split('//')[-1]}{path}"
                    signed = sign_request(
                        "GET", url, dict(hdrs), "UNSIGNED-PAYLOAD",
                        t.access_key, t.secret_key, self.region,
                    )
                    host = t.endpoint.split("//")[-1]
                    conn = _hc.HTTPConnection(host, timeout=30)
                    conn.request("GET", path, headers=signed)
                    resp = conn.getresponse()
                    if resp.status in (200, 206):
                        return resp
                    resp.read()
                    conn.close()
                except Exception:  # noqa: BLE001 — peer down: try the next
                    continue
            return None

        resp = await self._run(open_remote)
        if resp is None:
            return None
        out_headers = {
            k.lower(): v for k, v in resp.getheaders()
            if k.lower() in ("etag", "last-modified", "content-type",
                             "content-range", "content-length",
                             "x-amz-version-id")
            or k.lower().startswith("x-amz-meta-")
        }
        sresp = web.StreamResponse(status=resp.status, headers=out_headers)
        await sresp.prepare(request)
        loop = asyncio.get_running_loop()
        request["_tx"] = 0
        try:
            while True:
                chunk = await loop.run_in_executor(
                    self._io_pool, resp.read, 1 << 20
                )
                if not chunk:
                    break
                await sresp.write(chunk)
                request["_tx"] += len(chunk)
        finally:
            resp.close()
        await sresp.write_eof()
        return sresp

    async def _get_from_tier(self, request, bucket, key, oi) -> web.StreamResponse:
        """Read-through GET of a transitioned object: bytes come from the
        warm tier (reference streams transitioned objects from the tier
        the same way, cmd/bucket-lifecycle.go getTransitionedObjectReader).
        """
        from ..ilm import tier as tiermod

        tname = oi.user_defined.get(tiermod.TRANSITION_TIER_META, "")
        rkey = oi.user_defined.get(tiermod.TRANSITION_KEY_META, "")
        t = self.tiers.get(tname)
        if t is None:
            raise s3err.InternalError
        self._check_preconditions(request, oi)
        hdrs = {}
        rng = self._parse_range(request, oi.size) if oi.size else None
        if rng:
            hdrs["Range"] = f"bytes={rng[0]}-{rng[1]}"

        def fetch():
            r = t.client().get_object(t.bucket, rkey, headers=hdrs)
            if r.status not in (200, 206):
                raise RuntimeError(f"tier read failed: HTTP {r.status}")
            return r.body

        body = await self._run(fetch)
        headers = self._obj_headers(oi)
        headers["x-amz-storage-class"] = tname
        if rng:
            start, end = rng
            if len(body) == oi.size:
                # tier ignored the Range header: slice locally rather than
                # serving the whole object mislabeled as a range
                body = body[start:end + 1]
            headers["Content-Range"] = f"bytes {start}-{end}/{oi.size}"
            return web.Response(status=206, body=body, headers=headers)
        return web.Response(status=200, body=body, headers=headers)

    async def restore_object(self, request, bucket: str, key: str, body: bytes) -> web.Response:
        """POST /bucket/key?restore — bring a transitioned object's data
        back locally for N days (reference RestoreObjectHandler)."""
        from ..ilm import tier as tiermod

        key = listing.encode_dir_object(key)
        days = 1
        if body:
            try:
                root = ET.fromstring(body)
                for el in root.iter():
                    if el.tag.split("}")[-1] == "Days" and el.text:
                        days = max(1, int(el.text))
            except ET.ParseError:
                raise s3err.MalformedXML from None
        oi = await self._run(self.store.get_object_info, bucket, key)
        if not tiermod.is_transitioned(oi.user_defined):
            raise s3err.InvalidObjectState
        if _restored_locally(oi):
            return web.Response(status=200)  # already restored
        tname = oi.user_defined.get(tiermod.TRANSITION_TIER_META, "")
        rkey = oi.user_defined.get(tiermod.TRANSITION_KEY_META, "")
        t = self.tiers.get(tname)
        if t is None:
            raise s3err.InternalError

        def pull_and_restore():
            from ..qos.context import background_context

            # QoS: a restore re-encodes the whole object from the warm
            # tier (202 Accepted semantics) — its stripe blocks ride the
            # TPU dispatcher's background lane, not the foreground window
            with background_context():
                r = t.client().get_object(t.bucket, rkey)
                if r.status != 200:
                    raise RuntimeError(f"tier read failed: HTTP {r.status}")
                self.store.restore_object(bucket, key, r.body, days)

        await self._run(pull_and_restore)
        return web.Response(status=202)

    def _obj_headers(self, oi: ObjectInfo) -> dict[str, str]:
        from ..crypto import sse as ssemod

        h = {
            "ETag": f'"{oi.etag}"',
            "Last-Modified": _http_date(oi.mod_time),
            "Accept-Ranges": "bytes",
            "Content-Type": oi.content_type or "application/octet-stream",
        }
        if oi.version_id:
            h["x-amz-version-id"] = oi.version_id
        for k, v in oi.user_defined.items():
            if k.startswith("x-amz-meta-") or k in ("cache-control", "content-disposition", "content-encoding", "content-language", "expires"):
                h[k] = v
        from ..utils import checksum as _cks

        for calgo in _cks.ALGOS:
            v = oi.user_defined.get(f"{_cks.META_PREFIX}{calgo}")
            if v:
                h[f"x-amz-checksum-{calgo}"] = v
        raw_tags = oi.user_defined.get(self.TAGS_META)
        if raw_tags:
            h["x-amz-tagging-count"] = str(
                len(urllib.parse.parse_qsl(raw_tags, keep_blank_values=True))
            )
        from ..ilm import tier as tiermod

        tname = oi.user_defined.get(tiermod.TRANSITION_TIER_META)
        if tname:
            h["x-amz-storage-class"] = tname
            if _restored_locally(oi):
                exp = float(oi.user_defined[tiermod.RESTORE_EXPIRY_META])
                h["x-amz-restore"] = (
                    'ongoing-request="false", expiry-date="'
                    + _http_date(int(exp * 1e9)) + '"'
                )
        algo = oi.user_defined.get(ssemod.META_ALGO)
        if algo == "SSE-S3":
            h["x-amz-server-side-encryption"] = "AES256"
        elif algo == "SSE-KMS":
            h["x-amz-server-side-encryption"] = "aws:kms"
            h["x-amz-server-side-encryption-aws-kms-key-id"] = oi.user_defined.get(
                ssemod.META_KMS_KEY_ID, ""
            )
        elif algo == "SSE-C":
            h["x-amz-server-side-encryption-customer-algorithm"] = "AES256"
            h["x-amz-server-side-encryption-customer-key-MD5"] = oi.user_defined.get(
                ssemod.META_SSEC_KEY_MD5, ""
            )
        return h

    @staticmethod
    def _eval_preconditions(headers, oi: ObjectInfo, prefix: str, none_match_err) -> None:
        """Shared If-Match/If-None-Match/If-(Un)Modified-Since evaluation.
        Header precedence follows RFC 7232 (and AWS's documented copy
        combinations): an If-Match that evaluates TRUE suppresses
        If-Unmodified-Since, and a present If-None-Match suppresses
        If-Modified-Since. GET/HEAD use the bare names with 304 on the
        None-Match side; CopyObject/UploadPartCopy use the
        x-amz-copy-source-if-* set where every failure is 412
        (cmd/object-handlers.go checkCopyObjectPreconditions)."""
        etag = f'"{oi.etag}"'
        im = headers.get(f"{prefix}If-Match")
        if im:
            if im.strip() not in (etag, "*", oi.etag):
                raise s3err.PreconditionFailed
        else:
            ius = headers.get(f"{prefix}If-Unmodified-Since")
            if ius:
                try:
                    t = parsedate_to_datetime(ius)
                    if oi.mod_time / 1e9 > t.timestamp():
                        raise s3err.PreconditionFailed
                except (ValueError, TypeError):
                    pass
        inm = headers.get(f"{prefix}If-None-Match")
        if inm:
            if inm.strip() in (etag, "*", oi.etag):
                raise none_match_err
        else:
            ims = headers.get(f"{prefix}If-Modified-Since")
            if ims:
                try:
                    t = parsedate_to_datetime(ims)
                    if oi.mod_time / 1e9 <= t.timestamp():
                        raise none_match_err
                except (ValueError, TypeError):
                    pass

    def _check_preconditions(self, request, oi: ObjectInfo) -> None:
        self._eval_preconditions(request.headers, oi, "", s3err.NotModified)

    @staticmethod
    def _incoming_size(request, body: bytes | None) -> int:
        """Logical size of an incoming write for quota purposes: buffered
        body length, else the decoded payload length for aws-chunked
        streams (the wire Content-Length includes chunk framing), else
        Content-Length."""
        if body is not None:
            return len(body)
        dec = request.headers.get("x-amz-decoded-content-length")
        if dec:
            try:
                return int(dec)
            except ValueError:
                pass
        try:
            return int(request.headers.get("Content-Length", "0") or 0)
        except ValueError:
            return 0

    def _enforce_quota(self, bucket: str, size: int) -> None:
        """Hard bucket quota on the write path (reference
        cmd/bucket-quota.go:103-139 enforceBucketQuotaHard): the incoming
        size plus the scanner-accounted bucket usage must stay under the
        configured quota. Usage freshness matches the reference: the data
        scanner's last crawl."""
        if size < 0:
            return
        q = int(self.buckets.get(bucket).quota or 0)
        if q <= 0:
            return
        if size >= q:
            raise s3err.AdminBucketQuotaExceeded
        bg = getattr(self, "background", None)
        usage = bg.usage.buckets.get(bucket) if bg is not None else None
        if usage and usage.get("size", 0) > 0 and usage["size"] + size >= q:
            raise s3err.AdminBucketQuotaExceeded

    @staticmethod
    def _put_precond(request):
        """Conditional writes (reference checkPreconditionsPUT,
        cmd/object-handlers.go:2017): If-None-Match: * fails when the key
        exists; If-Match: <etag> fails unless the CURRENT etag matches.
        Runs under the namespace write lock inside the erasure layer."""
        inm = request.headers.get("If-None-Match", "").strip()
        im = request.headers.get("If-Match", "").strip()
        if not inm and not im:
            return None

        def check(cur) -> None:
            if inm and cur is not None and (
                inm == "*" or inm in (f'"{cur.etag}"', cur.etag)
            ):
                raise s3err.PreconditionFailed
            if im:
                if cur is None or im not in ("*", f'"{cur.etag}"', cur.etag):
                    raise s3err.PreconditionFailed

        return check

    async def put_object(
        self, request, bucket: str, key: str, body: bytes | None
    ) -> web.Response:
        key = listing.encode_dir_object(key)
        bm = self.buckets.get(bucket)
        precond = self._put_precond(request)
        self._enforce_quota(bucket, self._incoming_size(request, body))
        # overwriting an unversioned transitioned object orphans its warm-
        # tier data unless swept (reference enforces this via objSweeper)
        sweep_ud = None if bm.versioning else await self._run(
            self._tier_sweep_snapshot, bucket, key, ""
        )
        from . import transforms

        ct = request.headers.get("Content-Type")
        if body is None and (
            _bucket_sse_algo(bm.encryption) or transforms.compression_enabled()
        ):
            # a transform needs the whole payload: fall back to buffering
            # (the body is still unread on the socket)
            body = await request.read() if request.body_exists else b""
            if request.headers.get("x-amz-content-sha256") == \
                    signature.STREAMING_UNSIGNED_TRAILER:
                # the wire body is aws-chunked: decode + verify trailers
                # before transforming, or the framing would be stored
                body = self._decode_trailer_body(request, body)
        md5_hdr = request.headers.get("Content-MD5")
        if md5_hdr:
            import base64

            if base64.b64encode(hashlib.md5(body).digest()).decode() != md5_hdr:
                raise s3err.BadDigest
        checksum_meta = _verify_checksum_headers(request.headers, body or b"")
        # trailers verified during buffered aws-chunked decode persist too
        checksum_meta.update(request.get("trailer_checksum_meta") or {})
        user_defined = {}
        if ct:
            user_defined["content-type"] = ct
        for k, v in request.headers.items():
            lk = k.lower()
            if lk.startswith("x-amz-meta-") or lk in (
                "cache-control", "content-disposition", "content-encoding",
                "content-language", "expires", "x-amz-storage-class",
            ):
                user_defined[lk] = v
        if request.headers.get("x-amz-tagging"):
            # tag set supplied at PUT time (reference PutObjectHandler
            # parses x-amz-tagging into the version's tag metadata)
            user_defined[self.TAGS_META] = self._tagging_header_meta(
                request.headers["x-amz-tagging"]
            )
        if body is None:
            # streaming path: body flows HTTP -> erasure encode -> drives
            user_defined.update(checksum_meta)
            sc_parity = self._parity_for_storage_class(request)
            sc_family = self._family_for_storage_class(request)
            oi = await self._run_streaming_put(
                request,
                lambda rd: self.store.put_object(
                    bucket, key, rd, user_defined, None, bm.versioning,
                    parity=sc_parity, check_precond=precond,
                    family=sc_family,
                ),
            )
            headers = {"ETag": f'"{oi.etag}"'}
            tr = request.get("trailer_checksum_meta")
            if tr:
                # verified trailer checksum: persist + echo (reference
                # internal/hash checksum trailers)
                await self._run(
                    self.store.update_object_metadata, bucket, key,
                    oi.version_id, lambda md: md.update(tr),
                )
                for mk, mv in tr.items():
                    headers[mk.replace("x-minio-internal-", "x-amz-")] = mv
            if oi.version_id:
                headers["x-amz-version-id"] = oi.version_id
            from ..events import notify as ev

            self.notifier.notify(
                ev.OBJECT_CREATED_PUT, bucket, listing.decode_dir_object(key),
                oi.size, oi.etag, oi.version_id, request.get("access_key", ""),
            )
            self._queue_repl(request, bucket, key, oi.version_id, "put")
            await self._tier_sweep(sweep_ud)
            return web.Response(status=200, headers=headers)
        # transparent compression + server-side encryption
        req_headers = {k.lower(): v for k, v in request.headers.items()}
        try:
            tr = transforms.encode_for_store(
                body, key, ct or "", req_headers,
                _bucket_sse_algo(bm.encryption), self.kms, bucket,
            )
        except Exception as e:
            from ..crypto.sse import CryptoError

            if isinstance(e, CryptoError):
                raise s3err.InvalidArgument from None
            raise
        if tr.metadata:
            user_defined.update(tr.metadata)
            body = tr.data
        user_defined.update(checksum_meta)
        oi = await self._run(
            lambda: self.store.put_object(
                bucket, key, body, user_defined, None, bm.versioning,
                parity=self._parity_for_storage_class(request),
                check_precond=precond,
                family=self._family_for_storage_class(request),
            )
        )
        headers = {"ETag": f'"{oi.etag}"'}
        headers.update(tr.response_headers)
        for k, v in checksum_meta.items():
            headers[k.replace("x-minio-internal-", "x-amz-")] = v
        if oi.version_id:
            headers["x-amz-version-id"] = oi.version_id
        from ..events import notify as ev

        self.notifier.notify(
            ev.OBJECT_CREATED_PUT, bucket, listing.decode_dir_object(key),
            oi.size, oi.etag, oi.version_id, request.get("access_key", ""),
        )
        self._queue_repl(request, bucket, key, oi.version_id, "put")
        await self._tier_sweep(sweep_ud)
        return web.Response(status=200, headers=headers)

    def _tier_sweep_snapshot(self, bucket: str, key: str, vid: str) -> dict | None:
        """Pre-delete/overwrite snapshot of a transitioned version's tier
        pointers (reference cmd/tier-sweeper.go newObjSweeper +
        SetTransitionState): returns the metadata needed to sweep the
        warm tier after the local version goes away, or None.

        vid == "" means the NULL version (what an unversioned/suspended
        write or delete actually replaces) — NOT the latest: on a
        versioning-suspended bucket the latest may be a surviving named
        version whose warm data must not be swept."""
        from ..ilm import tier as tiermod

        if not self.tiers.list():
            return None  # no tiers configured: nothing to sweep, zero cost
        try:
            if vid:
                oi = self.store.get_object_info(bucket, key, vid)
            else:
                oi = next(
                    (v for v in self.store.list_object_versions(bucket, key)
                     if not v.version_id),
                    None,
                )
                if oi is None:
                    return None  # no null version to replace
        except Exception:  # noqa: BLE001 — no prior version
            return None
        if getattr(oi, "delete_marker", False) or not tiermod.is_transitioned(
            oi.user_defined
        ):
            return None
        return dict(oi.user_defined)

    async def _tier_sweep(self, sweep_ud: dict | None) -> None:
        """Fire-and-forget: the remote delete (5s timeouts when the tier is
        down) must not hold up the S3 response; failures land in the
        persisted journal the scanner retries (the reference routes all
        sweeps through its async tier journal for the same reason)."""
        if sweep_ud:
            from ..ilm import tier as tiermod

            asyncio.get_running_loop().run_in_executor(
                self._io_pool, tiermod.sweep_remote, self.tiers, sweep_ud
            )

    def _parse_copy_source(self, request, access_key: str) -> tuple[str, str, str]:
        """Parse x-amz-copy-source and AUTHORIZE the read on it — the
        destination PutObject grant must not leak other buckets (or IAM
        records under .minio.sys) through the copy path."""
        src = urllib.parse.unquote(request.headers["x-amz-copy-source"])
        if src.startswith("/"):
            src = src[1:]
        src_vid = ""
        if "?versionId=" in src:
            src, src_vid = src.split("?versionId=", 1)
        if "/" not in src:
            raise s3err.InvalidArgument
        src_bucket, src_key = src.split("/", 1)
        if src_bucket.startswith(".minio.sys") or not src_key:
            raise s3err.AccessDenied
        src_key = listing.encode_dir_object(src_key)
        action = "s3:GetObjectVersion" if src_vid else "s3:GetObject"
        self._authorize(access_key, action, src_bucket, src_key)
        return src_bucket, src_key, src_vid

    def _check_copy_preconditions(self, request, oi: ObjectInfo) -> None:
        self._eval_preconditions(
            request.headers, oi, "x-amz-copy-source-", s3err.PreconditionFailed
        )

    async def copy_object(self, request, bucket: str, key: str) -> web.Response:
        from ..crypto.sse import CryptoError
        from . import transforms

        src_bucket, src_key, src_vid = self._parse_copy_source(
            request, request.get("access_key", "")
        )
        oi, handle = await self._run(
            self.store.open_object, src_bucket, src_key, src_vid
        )
        from .transforms import logical_size as _logical

        try:
            # pre-read failures (412, quota) must release the source
            # namespace read lock immediately, not wait out the lock TTL
            self._check_copy_preconditions(request, oi)
            self._enforce_quota(bucket, _logical(oi.user_defined, oi.size))
            data = await self._run(lambda: b"".join(handle.read(0, -1)))
        finally:
            handle.close()
        req_headers = {k.lower(): v for k, v in request.headers.items()}
        # decode the SOURCE pipeline: sealed keys are bound to the source
        # bucket/key context and must never be copied verbatim
        if transforms.is_transformed(oi.user_defined):
            src_headers = dict(req_headers)
            # SSE-C sources present their key under the copy-source header set
            from ..crypto import sse as ssemod

            for h in ("algorithm", "key", "key-md5"):
                v = req_headers.get(
                    f"x-amz-copy-source-server-side-encryption-customer-{h}"
                )
                if v:
                    src_headers[
                        f"x-amz-server-side-encryption-customer-{h}"
                    ] = v
            try:
                data = await self._run(
                    transforms.decode_full, data, oi.user_defined, src_headers,
                    src_bucket, src_key, self.kms,
                )
            except CryptoError:
                raise s3err.AccessDenied from None
        directive = request.headers.get("x-amz-metadata-directive", "COPY")
        # copying an object onto itself without changing anything is an
        # error (reference cmd/object-handlers.go isTargetSameAsSource):
        # REPLACE directives, new SSE attributes, or a storage-class change
        # make it a legal metadata update
        if (
            src_bucket == bucket
            and src_key == listing.encode_dir_object(key)
            and not src_vid
            and directive != "REPLACE"
            and request.headers.get("x-amz-tagging-directive", "COPY") != "REPLACE"
            and not request.headers.get("x-amz-server-side-encryption")
            and not request.headers.get(
                "x-amz-server-side-encryption-customer-algorithm"
            )
            and not request.headers.get("x-amz-storage-class")
        ):
            raise s3err.InvalidCopyDest
        user_defined = {
            k: v for k, v in oi.user_defined.items()
            if not k.startswith("x-minio-internal-")
        }
        user_defined["content-type"] = oi.content_type
        if directive == "REPLACE":
            user_defined = {
                k.lower(): v
                for k, v in request.headers.items()
                if k.lower().startswith("x-amz-meta-")
            }
            if request.headers.get("Content-Type"):
                user_defined["content-type"] = request.headers["Content-Type"]
        # tag set travels by its OWN directive, independent of metadata
        # (reference: x-amz-tagging-directive on CopyObject)
        if request.headers.get("x-amz-tagging-directive", "COPY") == "REPLACE":
            user_defined.pop(self.TAGS_META, None)
            if request.headers.get("x-amz-tagging"):
                user_defined[self.TAGS_META] = self._tagging_header_meta(
                    request.headers["x-amz-tagging"]
                )
        elif oi.user_defined.get(self.TAGS_META):
            user_defined[self.TAGS_META] = oi.user_defined[self.TAGS_META]
        bm = self.buckets.get(bucket)
        # re-encode for the destination (its SSE headers / bucket default)
        try:
            tr = transforms.encode_for_store(
                data, key, user_defined.get("content-type", ""), req_headers,
                _bucket_sse_algo(bm.encryption), self.kms, bucket,
            )
        except CryptoError:
            raise s3err.InvalidArgument from None
        if tr.metadata:
            user_defined.update(tr.metadata)
            data = tr.data
        new_oi = await self._run(
            self.store.put_object,
            bucket,
            listing.encode_dir_object(key),
            data,
            user_defined,
            None,
            bm.versioning,
        )
        xml = (
            '<?xml version="1.0" encoding="UTF-8"?>'
            f'<CopyObjectResult><ETag>"{new_oi.etag}"</ETag>'
            f"<LastModified>{_iso8601(new_oi.mod_time)}</LastModified></CopyObjectResult>"
        )
        headers = {}
        if new_oi.version_id:
            headers["x-amz-version-id"] = new_oi.version_id
        from ..events import notify as ev

        self.notifier.notify(
            ev.OBJECT_CREATED_COPY, bucket, listing.decode_dir_object(key),
            new_oi.size, new_oi.etag, new_oi.version_id,
        )
        self._queue_repl(request, 
            bucket, listing.encode_dir_object(key), new_oi.version_id, "put"
        )
        return web.Response(body=xml.encode(), content_type="application/xml", headers=headers)

    @staticmethod
    def _range_hint(request):
        """Syntactic parse of the Range header — no object size needed,
        so it can run BEFORE any metadata read: the cache's range-segment
        tier resolves it against the cached FileInfo and a full-coverage
        hit skips open_object's lock + fan-out entirely. Anything
        unusual (multi-range, malformed) -> None, the real path decides."""
        rng = request.headers.get("Range")
        if not rng or not rng.startswith("bytes="):
            return None
        spec = rng[len("bytes=") :]
        if "," in spec:
            return None
        start_s, _, end_s = spec.partition("-")
        try:
            if start_s == "":
                return ("suffix", int(end_s))
            return ("abs", int(start_s), int(end_s) if end_s else None)
        except ValueError:
            return None

    def _parse_range(self, request, size: int) -> tuple[int, int] | None:
        rng = request.headers.get("Range")
        if not rng or not rng.startswith("bytes="):
            return None
        request["_range_object_size"] = size  # for the 416 Content-Range
        spec = rng[len("bytes=") :]
        if "," in spec:
            raise s3err.NotImplemented_
        start_s, _, end_s = spec.partition("-")
        try:
            if start_s == "":
                n = int(end_s)
                if n == 0:
                    raise s3err.InvalidRange
                start = max(size - n, 0)
                end = size - 1
            else:
                start = int(start_s)
                end = int(end_s) if end_s else size - 1
        except ValueError:
            return None  # malformed range is ignored per RFC
        if start >= size or start > end:
            raise s3err.InvalidRange
        return start, min(end, size - 1)

    async def get_object(self, request, bucket: str, key: str) -> web.StreamResponse:
        key = listing.encode_dir_object(key)
        vid = request.rel_url.query.get("versionId", "")
        if vid == "null":
            vid = ""
        try:
            oi, handle = await self._run(
                self.store.open_object, bucket, key, vid,
                self._range_hint(request),
            )
        except (quorum.ObjectNotFound, quorum.VersionNotFound):
            # not (yet) here: replication lag in an active-active pair —
            # proxy the read to a remote target rather than 404ing
            # (reference cmd/bucket-replication.go:2334 proxyGetToReplicationTarget)
            resp = await self._proxy_get_remote(request, bucket, key, vid)
            if resp is not None:
                return resp
            raise
        from ..ilm import tier as tiermod
        from . import transforms

        if tiermod.is_transitioned(oi.user_defined) and not _restored_locally(oi):
            handle.close()
            return await self._get_from_tier(request, bucket, key, oi)
        if transforms.is_transformed(oi.user_defined):
            return await self._get_transformed(request, bucket, key, oi, handle)
        try:
            self._check_preconditions(request, oi)
            rng = self._parse_range(request, oi.size) if oi.size else None
            headers = self._obj_headers(oi)
            if rng:
                start, end = rng
                it = handle.read(start, end - start + 1)
                headers["Content-Range"] = f"bytes {start}-{end}/{oi.size}"
                resp = web.StreamResponse(status=206, headers=headers)
                resp.content_length = end - start + 1
            else:
                it = handle.read()
                resp = web.StreamResponse(status=200, headers=headers)
                resp.content_length = oi.size
        except BaseException:
            handle.close()  # preconditions/range failures must not leak the rlock
            raise
        await resp.prepare(request)
        loop = asyncio.get_running_loop()
        sentinel = object()
        nxt = lambda: next(it, sentinel)  # noqa: E731
        # bytes metered at write time: a client that disconnects mid-stream
        # must be traced/audited with what actually left, not content_length
        request["_tx"] = 0
        try:
            while True:
                chunk = await loop.run_in_executor(self._io_pool, nxt)
                if chunk is sentinel:
                    break
                await resp.write(chunk)
                request["_tx"] += len(chunk)
        finally:
            handle.close()  # release the namespace read lock promptly
        await resp.write_eof()
        return resp

    async def get_object_attributes(self, request, bucket, key) -> web.Response:
        """GetObjectAttributes (reference cmd/object-handlers.go:988):
        ETag/Checksum/ObjectParts/StorageClass/ObjectSize, filtered by the
        x-amz-object-attributes header."""
        import json as _json

        from ..utils import checksum as _cks

        key = listing.encode_dir_object(key)
        vid = request.rel_url.query.get("versionId", "")
        if vid == "null":
            vid = ""
        want = {
            a.strip() for a in
            request.headers.get("x-amz-object-attributes", "").split(",") if a.strip()
        }
        if not want:
            raise s3err.InvalidArgument
        try:
            oi = await self._run(self.store.get_object_info, bucket, key, vid)
        except (quorum.ObjectNotFound, quorum.VersionNotFound):
            raise s3err.NoSuchKey from None
        if oi.delete_marker:
            raise s3err.NoSuchKey
        self._check_preconditions(request, oi)
        from . import transforms
        from ..ilm import tier as tiermod

        parts_xml = ""
        if "ObjectParts" in want:
            stored = oi.user_defined.get(_cks.PART_CHECKSUMS_META)
            per_part = _json.loads(stored) if stored else {}
            if "-" in oi.etag:  # multipart object
                try:
                    max_parts = int(
                        request.rel_url.query.get("max-parts", "1000") or 1000
                    )
                    marker = int(
                        request.rel_url.query.get("part-number-marker", "0") or 0
                    )
                except ValueError:
                    raise s3err.InvalidArgument from None
                nparts = int(oi.etag.rsplit("-", 1)[-1])
                body_parts = []
                emitted = 0
                for pn in range(1, nparts + 1):
                    if pn <= marker:
                        continue
                    if emitted >= max_parts:
                        break
                    cx = "".join(
                        f"<Checksum{a.upper()}>{escape(v)}</Checksum{a.upper()}>"
                        for a, v in per_part.get(str(pn), {}).items()
                    )
                    body_parts.append(f"<Part><PartNumber>{pn}</PartNumber>{cx}</Part>")
                    emitted += 1
                parts_xml = (
                    f"<ObjectParts><TotalPartsCount>{nparts}</TotalPartsCount>"
                    f"<PartNumberMarker>{marker}</PartNumberMarker>"
                    f"<MaxParts>{max_parts}</MaxParts>"
                    f"<IsTruncated>{'true' if marker + emitted < nparts else 'false'}"
                    f"</IsTruncated>" + "".join(body_parts) + "</ObjectParts>"
                )
        cks_xml = ""
        if "Checksum" in want:
            fields = []
            for algo in _cks.ALGOS:
                v = oi.user_defined.get(f"{_cks.META_PREFIX}{algo}")
                if v:
                    tag = "Checksum" + algo.upper()
                    fields.append(f"<{tag}>{escape(v)}</{tag}>")
            if fields:
                cks_xml = "<Checksum>" + "".join(fields) + "</Checksum>"
        etag_xml = f"<ETag>{escape(oi.etag)}</ETag>" if "ETag" in want else ""
        size_xml = (
            f"<ObjectSize>{transforms.logical_size(oi.user_defined, oi.size)}"
            "</ObjectSize>" if "ObjectSize" in want else ""
        )
        sc = oi.user_defined.get(tiermod.TRANSITION_TIER_META) or \
            oi.user_defined.get("x-amz-storage-class", "STANDARD")
        sc_xml = (
            f"<StorageClass>{escape(sc)}</StorageClass>"
            if "StorageClass" in want else ""
        )
        xml = (
            '<?xml version="1.0" encoding="UTF-8"?>'
            '<GetObjectAttributesResponse xmlns='
            '"http://s3.amazonaws.com/doc/2006-03-01/">'
            + etag_xml + cks_xml + parts_xml + sc_xml + size_xml
            + "</GetObjectAttributesResponse>"
        )
        headers = {"Last-Modified": _http_date(oi.mod_time)}
        if oi.version_id:
            headers["x-amz-version-id"] = oi.version_id
        return web.Response(
            body=xml.encode(), content_type="application/xml", headers=headers
        )

    async def _get_transformed(self, request, bucket, key, oi, handle) -> web.Response:
        """GET for compressed/encrypted objects: decode through the
        transform pipeline (ranges map to packets for SSE-only)."""
        from ..crypto.sse import CryptoError
        from . import transforms

        try:
            self._check_preconditions(request, oi)
            logical = transforms.logical_size(oi.user_defined, oi.size)
            rng = self._parse_range(request, logical) if logical else None
            req_headers = {k.lower(): v for k, v in request.headers.items()}

            def read_fn(off, ln):
                # multiple per-part range reads over ONE handle: the outer
                # finally owns the close, each read must keep the lock
                return b"".join(handle.read(off, ln, close_when_done=False))

            def decode():
                if rng:
                    start, end = rng
                    return transforms.decode_range(
                        read_fn, oi.size, oi.user_defined, req_headers,
                        bucket, key, self.kms, start, end - start + 1,
                    )
                return transforms.decode_full(
                    read_fn(0, oi.size), oi.user_defined, req_headers,
                    bucket, key, self.kms,
                )

            try:
                data = await self._run(decode)
            except CryptoError:
                raise s3err.AccessDenied from None
            headers = self._obj_headers(oi)
            if rng:
                start, end = rng
                headers["Content-Range"] = f"bytes {start}-{end}/{logical}"
                return web.Response(status=206, headers=headers, body=data)
            return web.Response(status=200, headers=headers, body=data)
        finally:
            handle.close()

    async def head_object(self, request, bucket: str, key: str) -> web.Response:
        key = listing.encode_dir_object(key)
        vid = request.rel_url.query.get("versionId", "")
        if vid == "null":
            vid = ""
        oi = await self._run(self.store.get_object_info, bucket, key, vid)
        if oi.delete_marker:
            return web.Response(status=405, headers={"x-amz-delete-marker": "true"})
        self._check_preconditions(request, oi)
        from . import transforms

        headers = self._obj_headers(oi)
        headers["Content-Length"] = str(transforms.logical_size(oi.user_defined, oi.size))
        return web.Response(status=200, headers=headers)

    async def delete_object(self, request, bucket: str, key: str) -> web.Response:
        key = listing.encode_dir_object(key)
        vid = request.rel_url.query.get("versionId", "")
        if vid == "null":
            vid = ""
        bm = self.buckets.get(bucket)
        headers = {}
        await self._run(
            self._check_object_lock, bucket, key, vid,
            # the IAM resource must use the CLIENT's key form, matching the
            # raw key the multi-delete path passes
            self._bypass_governance(
                request, bucket, listing.decode_dir_object(key)
            ),
        )
        # deleting a version (or the sole unversioned copy) of a
        # transitioned object must sweep its warm-tier data (tier GC)
        sweep_ud = None
        if vid or not bm.versioning:
            sweep_ud = await self._run(self._tier_sweep_snapshot, bucket, key, vid)
        try:
            oi = await self._run(
                self.store.delete_object, bucket, key, vid, bm.versioning
            )
            if not oi.delete_marker:
                await self._tier_sweep(sweep_ud)
            if oi.delete_marker:
                headers["x-amz-delete-marker"] = "true"
            if oi.version_id:
                headers["x-amz-version-id"] = oi.version_id
            from ..events import notify as ev

            self.notifier.notify(
                ev.OBJECT_REMOVED_MARKER if oi.delete_marker else ev.OBJECT_REMOVED_DELETE,
                bucket, listing.decode_dir_object(key),
                version_id=oi.version_id, user=request.get("access_key", ""),
            )
            if not vid:
                # only logical deletes replicate; removing a SPECIFIC old
                # version must never delete the replica's live object
                self._queue_repl(request, bucket, key, "", "delete")
        except (quorum.ObjectNotFound, quorum.VersionNotFound):
            pass  # S3 deletes are idempotent
        return web.Response(status=204, headers=headers)

    async def delete_multiple(self, request, bucket: str, body: bytes) -> web.Response:
        try:
            root = ET.fromstring(body)
        except ET.ParseError:
            raise s3err.MalformedXML from None
        quiet = False
        targets = []
        for el in root:
            tag = el.tag.split("}")[-1]
            if tag == "Quiet":
                quiet = (el.text or "").lower() == "true"
            elif tag == "Object":
                k, v = "", ""
                for sub in el:
                    stag = sub.tag.split("}")[-1]
                    if stag == "Key":
                        k = sub.text or ""
                    elif stag == "VersionId":
                        v = sub.text or ""
                targets.append((k, v))
        bm = self.buckets.get(bucket)
        ak = request.get("access_key", "")
        results = []
        for k, v in targets[:1000]:
            # per-object authorization: a Deny on a key prefix must hold
            # through multi-delete exactly as through single DELETE
            try:
                self._authorize(
                    ak,
                    "s3:DeleteObjectVersion" if v else "s3:DeleteObject",
                    bucket,
                    k,
                )
            except s3err.APIError:
                results.append((k, v, s3err.AccessDenied, None))
                continue
            try:
                # retention/legal hold protects versions through
                # multi-delete exactly as through single DELETE
                # (including the governance-bypass header)
                await self._run(
                    self._check_object_lock, bucket,
                    listing.encode_dir_object(k), "" if v == "null" else v,
                    self._bypass_governance(request, bucket, k),
                )
                vv = "" if v == "null" else v
                sweep_ud = None
                if vv or not bm.versioning:  # this delete removes data
                    sweep_ud = await self._run(
                        self._tier_sweep_snapshot, bucket,
                        listing.encode_dir_object(k), vv,
                    )
                oi = await self._run(
                    self.store.delete_object,
                    bucket,
                    listing.encode_dir_object(k),
                    vv,
                    bm.versioning,
                )
                if not oi.delete_marker:
                    await self._tier_sweep(sweep_ud)
                results.append((k, v, None, oi))
            except (quorum.ObjectNotFound, quorum.VersionNotFound):
                results.append((k, v, None, None))
            except s3err.APIError as e:
                results.append((k, v, e, None))  # e.g. retention AccessDenied
            except asyncio.CancelledError:
                raise
            except Exception:  # noqa: BLE001
                results.append((k, v, s3err.InternalError, None))
        parts = []
        for k, v, err, oi in results:
            if err is None:
                if not quiet:
                    e = f"<Deleted><Key>{escape(k)}</Key>"
                    if v:
                        e += f"<VersionId>{escape(v)}</VersionId>"
                    if oi is not None and oi.delete_marker and oi.version_id:
                        e += f"<DeleteMarker>true</DeleteMarker><DeleteMarkerVersionId>{oi.version_id}</DeleteMarkerVersionId>"
                    parts.append(e + "</Deleted>")
            else:
                parts.append(
                    f"<Error><Key>{escape(k)}</Key><Code>{err.code}</Code>"
                    f"<Message>{escape(err.description)}</Message></Error>"
                )
        xml = (
            '<?xml version="1.0" encoding="UTF-8"?>'
            '<DeleteResult xmlns="http://s3.amazonaws.com/doc/2006-03-01/">'
            f"{''.join(parts)}</DeleteResult>"
        )
        return web.Response(body=xml.encode(), content_type="application/xml")

    # -- multipart -------------------------------------------------------------
    async def get_object_lambda(self, request, bucket, key) -> web.Response:
        """Object lambda: transform a GET through a user webhook
        (reference cmd/object-lambda-handlers.go). Targets come from
        MINIO_LAMBDA_WEBHOOK_ENABLE_<ID>/..._ENDPOINT_<ID>."""
        import base64
        import urllib.request as _ur

        arn = request.rel_url.query.get("lambdaArn", "")
        ident = arn.rsplit(":", 2)[-2] if arn.count(":") >= 2 else arn
        endpoint = os.environ.get(f"MINIO_LAMBDA_WEBHOOK_ENDPOINT_{ident.upper()}", "")
        enabled = os.environ.get(
            f"MINIO_LAMBDA_WEBHOOK_ENABLE_{ident.upper()}", ""
        ) in ("on", "true", "1")
        if not endpoint or not enabled:
            raise s3err.InvalidArgument
        key_enc = listing.encode_dir_object(key)
        oi, it = await self._run(self.store.get_object, bucket, key_enc)
        payload = {
            "getObjectContext": {
                "inputS3Url": f"/{bucket}/{key}",
                "bucket": bucket,
                "key": key,
                "content": base64.b64encode(b"".join(it)).decode(),
            },
            "userRequest": {"headers": dict(request.headers)},
        }
        import json as _json

        def call():
            req = _ur.Request(
                endpoint, data=_json.dumps(payload).encode(),
                headers={"Content-Type": "application/json"},
            )
            return _ur.urlopen(req, timeout=30).read()

        try:
            out = await self._run(call)
        except asyncio.CancelledError:
            raise
        except Exception:  # noqa: BLE001 — lambda endpoint down/unreachable
            raise s3err.InternalError from None
        try:
            body = base64.b64decode(_json.loads(out)["content"])
        except (ValueError, KeyError):
            body = out  # raw transformed bytes are accepted too
        return web.Response(body=body, content_type=oi.content_type)
    def _require_lock_bucket(self, bucket: str) -> None:
        if not self.buckets.get(bucket).object_lock:
            raise s3err.InvalidArgument  # lock config required on bucket

    @staticmethod
    def _parse_retain_until(until: str):
        """Aware datetime or raises MalformedXML (naive/garbage dates must
        never be stored: they'd poison every later delete)."""
        import datetime as _dt

        try:
            t = _dt.datetime.fromisoformat(until.replace("Z", "+00:00"))
        except ValueError:
            raise s3err.MalformedXML from None
        if t.tzinfo is None:
            raise s3err.MalformedXML
        return t

    async def put_object_retention(self, request, bucket, key, body) -> web.Response:
        import datetime as _dt

        self._require_lock_bucket(bucket)
        key = listing.encode_dir_object(key)
        vid = request.rel_url.query.get("versionId", "")
        try:
            root = ET.fromstring(body)
            mode = until = ""
            for el in root.iter():
                if el.tag.endswith("Mode"):
                    mode = el.text or ""
                elif el.tag.endswith("RetainUntilDate"):
                    until = (el.text or "").strip()
            if mode not in ("GOVERNANCE", "COMPLIANCE") or not until:
                raise s3err.MalformedXML
        except ET.ParseError:
            raise s3err.MalformedXML from None
        new_until = self._parse_retain_until(until)
        # COMPLIANCE retention can never be shortened or weakened
        oi = await self._run(self.store.get_object_info, bucket, key, vid)
        existing = oi.user_defined.get(self.RETENTION_META, "")
        if existing:
            old_mode, old_until_s = existing.split("|", 1)
            try:
                old_until = self._parse_retain_until(old_until_s)
            except s3err.APIError:
                old_until = None
            if (
                old_mode == "COMPLIANCE"
                and old_until is not None
                and _dt.datetime.now(_dt.timezone.utc) < old_until
                and (mode != "COMPLIANCE" or new_until < old_until)
            ):
                raise s3err.AccessDenied
        val = "{}|{}".format(
            mode,
            new_until.astimezone(_dt.timezone.utc).strftime("%Y-%m-%dT%H:%M:%SZ"),
        )
        await self._run(
            self.store.update_object_metadata, bucket, key, vid,
            lambda md: md.__setitem__(self.RETENTION_META, val),
        )
        return web.Response(status=200)

    async def get_object_retention(self, request, bucket, key) -> web.Response:
        key = listing.encode_dir_object(key)
        vid = request.rel_url.query.get("versionId", "")
        oi = await self._run(self.store.get_object_info, bucket, key, vid)
        raw = oi.user_defined.get(self.RETENTION_META, "")
        if not raw:
            raise s3err.ObjectLockConfigurationNotFoundError
        mode, until = raw.split("|", 1)
        xml = (
            '<?xml version="1.0" encoding="UTF-8"?>'
            f"<Retention><Mode>{escape(mode)}</Mode>"
            f"<RetainUntilDate>{escape(until)}</RetainUntilDate></Retention>"
        )
        return web.Response(body=xml.encode(), content_type="application/xml")

    async def put_legal_hold(self, request, bucket, key, body) -> web.Response:
        self._require_lock_bucket(bucket)
        key = listing.encode_dir_object(key)
        vid = request.rel_url.query.get("versionId", "")
        try:
            root = ET.fromstring(body)
            status = ""
            for el in root.iter():
                if el.tag.endswith("Status"):
                    status = (el.text or "").strip()
        except ET.ParseError:
            raise s3err.MalformedXML from None
        if status not in ("ON", "OFF"):
            # malformed input must never silently CLEAR an active hold
            raise s3err.MalformedXML
        await self._run(
            self.store.update_object_metadata, bucket, key, vid,
            lambda md: md.__setitem__(self.LEGALHOLD_META, status),
        )
        return web.Response(status=200)

    async def get_legal_hold(self, request, bucket, key) -> web.Response:
        key = listing.encode_dir_object(key)
        vid = request.rel_url.query.get("versionId", "")
        oi = await self._run(self.store.get_object_info, bucket, key, vid)
        status = oi.user_defined.get(self.LEGALHOLD_META, "OFF")
        xml = (
            '<?xml version="1.0" encoding="UTF-8"?>'
            f"<LegalHold><Status>{status}</Status></LegalHold>"
        )
        return web.Response(body=xml.encode(), content_type="application/xml")

    def _check_object_lock(self, bucket: str, key: str, vid: str,
                           bypass_governance: bool = False) -> None:
        """Block data-destroying deletes while retention/legal hold is
        active (reference: enforceRetentionForDeletion). GOVERNANCE
        retention may be bypassed by a caller holding
        s3:BypassGovernanceRetention who sent the bypass header;
        COMPLIANCE and legal hold can never be bypassed."""
        if not vid:
            # on a VERSIONED bucket this only adds a marker; on an
            # unversioned one it destroys the latest version — guard it
            if self.buckets.get(bucket).versioning:
                return
        try:
            oi = self.store.get_object_info(bucket, key, vid)
        except Exception:  # noqa: BLE001 — missing version: nothing to guard
            return
        if oi.user_defined.get(self.LEGALHOLD_META) == "ON":
            raise s3err.AccessDenied
        raw = oi.user_defined.get(self.RETENTION_META, "")
        if raw:
            import datetime as _dt

            mode, until = raw.split("|", 1)
            if mode == "GOVERNANCE" and bypass_governance:
                return
            try:
                t = _dt.datetime.fromisoformat(until.replace("Z", "+00:00"))
            except ValueError:
                raise s3err.AccessDenied from None
            if t.tzinfo is None or _dt.datetime.now(_dt.timezone.utc) < t:
                raise s3err.AccessDenied

    def _bypass_governance(self, request, bucket: str, key: str) -> bool:
        """True iff the caller asked to bypass GOVERNANCE retention and
        holds s3:BypassGovernanceRetention (reference
        cmd/object-handlers.go x-amz-bypass-governance-retention)."""
        if request.headers.get(
            "x-amz-bypass-governance-retention", ""
        ).lower() != "true":
            return False
        ak = request.get("access_key", "")
        if not ak:
            return False
        return self.iam.is_allowed(
            ak, "s3:BypassGovernanceRetention", f"{bucket}/{key}"
        )

    # -- object tagging --------------------------------------------------------

    from ..erasure.set import TAGS_META_KEY as TAGS_META

    @staticmethod
    def _validate_tags(pairs) -> dict[str, str]:
        """Enforce the S3 tag-set rules on (key, value) pairs (reference
        pkg tags.ParseObjectTags): <=10 tags, unique keys, key 1-128
        chars, value <=256 chars."""
        if len(pairs) > 10:
            raise s3err.InvalidTag
        tags: dict[str, str] = {}
        for k, v in pairs:
            if not k or len(k) > 128 or len(v) > 256 or k in tags:
                raise s3err.InvalidTag
            tags[k] = v
        return tags

    @classmethod
    def _tagging_header_meta(cls, header_value: str) -> str:
        """x-amz-tagging header (urlencoded) -> validated stored form."""
        pairs = urllib.parse.parse_qsl(header_value, keep_blank_values=True)
        return urllib.parse.urlencode(cls._validate_tags(pairs))

    async def put_object_tagging(self, request, bucket, key, body) -> web.Response:
        key = listing.encode_dir_object(key)
        vid = request.rel_url.query.get("versionId", "")
        try:
            root = ET.fromstring(body)
        except ET.ParseError:
            raise s3err.MalformedXML from None
        pairs = []
        for el in root.iter():
            if el.tag.endswith("Tag"):
                k = v = ""
                for sub in el:
                    if sub.tag.endswith("Key"):
                        k = sub.text or ""
                    elif sub.tag.endswith("Value"):
                        v = sub.text or ""
                pairs.append((k, v))
        tags = self._validate_tags(pairs)
        await self._run(self.store.set_object_tags, bucket, key, tags, vid)
        return web.Response(status=200)

    async def get_object_tagging(self, request, bucket, key) -> web.Response:
        key = listing.encode_dir_object(key)
        vid = request.rel_url.query.get("versionId", "")
        tags = await self._run(self.store.get_object_tags, bucket, key, vid)
        items = "".join(
            f"<Tag><Key>{escape(k)}</Key><Value>{escape(v)}</Value></Tag>"
            for k, v in tags.items()
        )
        xml = (
            '<?xml version="1.0" encoding="UTF-8"?>'
            f"<Tagging><TagSet>{items}</TagSet></Tagging>"
        )
        return web.Response(body=xml.encode(), content_type="application/xml")

    async def delete_object_tagging(self, request, bucket, key) -> web.Response:
        key = listing.encode_dir_object(key)
        vid = request.rel_url.query.get("versionId", "")
        await self._run(self.store.set_object_tags, bucket, key, {}, vid)
        return web.Response(status=204)

    async def select_object_content(self, request, bucket, key, body) -> web.Response:
        """SelectObjectContent: SQL over CSV/JSON objects
        (reference cmd/object-handlers.go:105 + internal/s3select)."""
        from ..s3select import engine
        from . import transforms

        key = listing.encode_dir_object(key)
        oi, handle = await self._run(self.store.open_object, bucket, key, "")
        try:
            req_headers = {k.lower(): v for k, v in request.headers.items()}

            def load() -> bytes:
                raw = b"".join(handle.read())
                if transforms.is_transformed(oi.user_defined):
                    return transforms.decode_full(
                        raw, oi.user_defined, req_headers, bucket, key, self.kms
                    )
                return raw

            data = await self._run(load)
        finally:
            handle.close()
        try:
            stream = await self._run(engine.run_select, body, data)
        except engine.SelectError:
            raise s3err.InvalidArgument from None
        return web.Response(
            body=stream, content_type="application/octet-stream"
        )
