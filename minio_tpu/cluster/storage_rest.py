"""Storage RPC: every drive served over HTTP, consumed via StorageAPI.

Mirrors the reference's storage REST pair
(/root/reference/cmd/storage-rest-server.go, storage-rest-client.go): small
metadata ops as msgpack request/response, bulk shard data as raw HTTP
bodies. Internode auth is an HMAC token derived from the root credentials
(the reference signs internode requests the same way). The reference
splits small RPCs onto a muxed websocket grid — here both planes ride
HTTP/1.1 keep-alive connections, one pool per peer.
"""

from __future__ import annotations

import hashlib
import hmac
import http.client
import threading
import urllib.parse
from typing import BinaryIO, Iterator

import msgpack
from aiohttp import web

from .. import obs
from ..fault import registry as fault_registry
from ..fault import retry as retry_mod
from ..storage import errors
from ..storage.datatypes import DiskInfo, FileInfo, VolInfo
from ..storage.interface import StorageAPI
from ..storage.xlstorage import XLStorage

STORAGE_PREFIX = "/minio/storage/v1"

_ERR_TYPES = {
    "DiskNotFound": errors.DiskNotFound,
    "VolumeNotFound": errors.VolumeNotFound,
    "VolumeExists": errors.VolumeExists,
    "VolumeNotEmpty": errors.VolumeNotEmpty,
    "FileNotFound": errors.FileNotFound,
    "FileVersionNotFound": errors.FileVersionNotFound,
    "FileAccessDenied": errors.FileAccessDenied,
    "FileCorrupt": errors.FileCorrupt,
    "IsNotRegular": errors.IsNotRegular,
    "DiskFull": errors.DiskFull,
}


def internode_token(root_user: str, root_password: str) -> str:
    return hmac.new(
        f"{root_user}:{root_password}".encode(), b"minio-tpu-internode", hashlib.sha256
    ).hexdigest()


def _pack_err(e: Exception) -> web.Response:
    return web.Response(
        status=460,  # app-level error channel; type travels in headers
        headers={"x-storage-err": type(e).__name__},
        body=str(e).encode(),
    )


class StorageRESTServer:
    """Serves a node's local drives; attach to the node's aiohttp app.

    `drives` maps GLOBAL endpoint index -> local XLStorage (all nodes share
    the same argument list, so global indexes address drives cluster-wide).
    The dict may be filled after registration (bootstrap order)."""

    def __init__(self, drives: dict[int, XLStorage] | list[XLStorage], token: str):
        self.drives = (
            drives if isinstance(drives, dict) else {i: d for i, d in enumerate(drives)}
        )
        self.token = token

    def register(self, app: web.Application) -> None:
        app.router.add_route(
            "POST", STORAGE_PREFIX + "/{drive:\\d+}/{op}", self.handle
        )

    def register_grid(self, grid) -> None:
        """Expose the same ops over the muxed grid: small RPCs as
        `storage.call` single requests, walkdir as a credit-controlled
        stream (the reference moved exactly this class of traffic onto
        internal/grid; bulk shard bodies stay on HTTP)."""

        def call(payload: bytes) -> bytes:
            parts = msgpack.unpackb(payload, raw=False)
            drive_idx, op, body = parts[0], parts[1], parts[2]
            # 4th element (optional, newer callers): trace request id —
            # the span context crosses the grid hop with the payload
            req_id = parts[3] if len(parts) > 3 else ""
            drive = self.drives.get(drive_idx)
            if drive is None:
                raise errors.DiskNotFound("bad drive index")
            if req_id:
                with obs.request_context(req_id):
                    return self._call(drive, op, body)
            return self._call(drive, op, body)

        async def walkdir(payload: bytes, stream) -> None:
            import asyncio

            drive_idx, volume, base, after = msgpack.unpackb(payload, raw=False)
            drive = self.drives.get(drive_idx)
            if drive is None:
                raise errors.DiskNotFound("bad drive index")
            it = drive.walk_dir(volume, base)
            loop = asyncio.get_running_loop()

            def next_batch() -> list[str]:
                out: list[str] = []
                for key in it:
                    if after and key <= after:
                        continue
                    out.append(key)
                    if len(out) >= 512:
                        break
                return out

            while True:
                batch = await loop.run_in_executor(None, next_batch)
                if not batch:
                    return
                await stream.send(msgpack.packb(batch))

        grid.register_single("storage.call", call)
        grid.register_stream("storage.walkdir", walkdir)

    async def handle(self, request: web.Request) -> web.Response:
        if request.headers.get("x-minio-token") != self.token:
            return web.Response(status=403)
        try:
            drive = self.drives[int(request.match_info["drive"])]
        except (KeyError, ValueError):
            return _pack_err(errors.DiskNotFound("bad drive index"))
        op = request.match_info["op"]
        body = await request.read()
        import asyncio

        # the caller's trace request id rides an internode header so the
        # serving node's spans join the same tree
        req_id = request.headers.get("x-minio-reqid", "")

        def run():
            if req_id:
                with obs.request_context(req_id):
                    return self._call(drive, op, body)
            return self._call(drive, op, body)

        loop = asyncio.get_running_loop()
        try:
            result = await loop.run_in_executor(None, run)
            return web.Response(body=result)
        except asyncio.CancelledError:
            raise
        except Exception as e:  # noqa: BLE001 — typed errors cross the wire
            return _pack_err(e)

    def _call(self, drive: XLStorage, op: str, body: bytes) -> bytes:
        # serving-node storage span: the registry serves RAW drives (the
        # calling side owns the HealthCheckedDisk wrapper), so this is
        # where remote ops become visible on the node that executes them
        with obs.span(
            obs.TYPE_STORAGE, f"rpc.{op}",
            drive=getattr(drive, "endpoint", ""),
        ):
            return self._call_inner(drive, op, body)

    def _call_inner(self, drive: XLStorage, op: str, body: bytes) -> bytes:
        args = msgpack.unpackb(body, raw=False) if body else {}

        if op == "diskinfo":
            di = drive.disk_info()
            return msgpack.packb(di.__dict__)
        if op == "makevol":
            drive.make_vol(args["volume"])
            return b""
        if op == "listvols":
            return msgpack.packb([[v.name, v.created] for v in drive.list_vols()])
        if op == "statvol":
            v = drive.stat_vol(args["volume"])
            return msgpack.packb([v.name, v.created])
        if op == "deletevol":
            drive.delete_vol(args["volume"], args.get("force", False))
            return b""
        if op == "writemetadata":
            drive.write_metadata(
                args["volume"], args["path"], FileInfo.from_dict(args["fi"])
            )
            return b""
        if op == "updatemetadata":
            drive.update_metadata(
                args["volume"], args["path"], FileInfo.from_dict(args["fi"])
            )
            return b""
        if op == "readversion":
            fi = drive.read_version(
                args["volume"], args["path"], args.get("version_id", ""),
                args.get("read_data", False),
            )
            return msgpack.packb(_fi_wire(fi))
        if op == "readversions":
            out = [_fi_wire(fi) for fi in drive.read_versions(args["volume"], args["path"])]
            return msgpack.packb(out)
        if op == "deleteversion":
            drive.delete_version(
                args["volume"], args["path"], FileInfo.from_dict(args["fi"])
            )
            return b""
        if op == "renamedata":
            drive.rename_data(
                args["src_volume"], args["src_path"], FileInfo.from_dict(args["fi"]),
                args["dst_volume"], args["dst_path"],
            )
            return b""
        if op == "createfile":
            drive.create_file(args["volume"], args["path"], args["data"])
            return b""
        if op == "appendfile":
            drive.append_file(args["volume"], args["path"], args["data"])
            return b""
        if op == "readfile":
            return drive.read_file(
                args["volume"], args["path"], args.get("offset", 0), args.get("length", -1)
            )
        if op == "renamefile":
            drive.rename_file(
                args["src_volume"], args["src_path"], args["dst_volume"], args["dst_path"]
            )
            return b""
        if op == "delete":
            drive.delete(args["volume"], args["path"], args.get("recursive", False))
            return b""
        if op == "listdir":
            return msgpack.packb(
                drive.list_dir(args["volume"], args["path"], args.get("count", -1))
            )
        if op == "walkdir":
            # paged: never materialize a whole namespace in one response
            limit = args.get("limit", 10000)
            after = args.get("after", "")
            out = []
            for key in drive.walk_dir(args["volume"], args.get("base", "")):
                if after and key <= after:
                    continue
                out.append(key)
                if len(out) >= limit:
                    break
            return msgpack.packb(out)
        if op == "statinfofile":
            return msgpack.packb(drive.stat_info_file(args["volume"], args["path"]))
        if op == "verifyfile":
            drive.verify_file(args["volume"], args["path"], FileInfo.from_dict(args["fi"]))
            return b""
        raise errors.StorageError(f"unknown storage op {op}")


def _fi_wire(fi: FileInfo) -> dict:
    # to_dict/from_dict carry everything except the read-side annotations
    d = fi.to_dict()
    d["_latest"] = fi.is_latest
    d["_nv"] = fi.num_versions
    d["_smt"] = fi.successor_mod_time
    return d


def _fi_unwire(d: dict) -> FileInfo:
    fi = FileInfo.from_dict(d)
    fi.is_latest = d.get("_latest", True)
    fi.num_versions = d.get("_nv", 0)
    fi.successor_mod_time = d.get("_smt", 0)
    return fi


class StorageRESTClient(StorageAPI):
    """StorageAPI over HTTP to a peer's drive (keep-alive pooled)."""

    def __init__(self, host: str, port: int, drive_index: int, token: str, endpoint: str = ""):
        self.host, self.port = host, port
        self.drive_index = drive_index
        self.token = token
        self.endpoint = endpoint or f"http://{host}:{port}/#{drive_index}"
        self.disk_id = ""
        self._local = threading.local()
        # small metadata RPCs ride the muxed grid connection shared by all
        # drives pointing at this peer; bulk shard bodies stay on HTTP
        from .grid import GridGate

        self._gate = GridGate(host, port, token, "storage")

    def _conn(self) -> http.client.HTTPConnection:
        c = getattr(self._local, "conn", None)
        if c is None:
            from ..crypto import tlsconf

            c = tlsconf.http_connection(self.host, self.port, timeout=30)
            self._local.conn = c
        return c

    # per-op idempotency class (fault/retry.py is the single source):
    # only these ops may be resent after a dropped connection or timeout
    _RETRYABLE = retry_mod.IDEMPOTENT_STORAGE_OPS

    # bulk shard payloads: per the grid design (reference grid README) these
    # stay on their own HTTP bodies so one large transfer can't stall every
    # muxed RPC behind it
    _BULK_OPS = frozenset({"createfile", "appendfile", "readfile"})

    def _check_net_fault(self, op: str) -> None:
        """Injected network faults (fault/ registry): delay stalls the
        call; everything else raises the same OS-class error a real
        transport failure would, so the unified retry policy absorbs
        transient rules and the circuit breaker (HealthCheckedDisk wraps
        this client) counts persistent ones."""
        rule = fault_registry.check("network", f"{self.host}:{self.port}", op)
        if rule is not None:
            if rule.mode == "delay":
                fault_registry.sleep_latency(rule)
            else:
                raise OSError(
                    f"{self.endpoint}: injected network fault ({rule.mode})"
                )

    def _rpc(self, op: str, args: dict | None = None) -> bytes:
        body = msgpack.packb(args or {})
        # trace context crosses the internode hop: as a 4th payload element
        # on the grid, as a header on HTTP. The server accepts both payload
        # arities, but a tracing caller does require a server that knows the
        # 4-element form — all internode planes already assume one code
        # version cluster-wide (bootstrap verifies config consistency)
        req_id = obs.current_request_id()
        if op not in self._BULK_OPS:
            g = self._gate.client()
            if g is not None:
                from .grid import GridConnectError, GridError, RemoteError

                payload = (
                    [self.drive_index, op, body, req_id]
                    if req_id else [self.drive_index, op, body]
                )
                try:
                    return g.call(
                        "storage.call",
                        msgpack.packb(payload),
                        retry=op in self._RETRYABLE,
                    )
                except RemoteError as e:
                    err_type = _ERR_TYPES.get(e.err_type, errors.StorageError)
                    raise err_type(str(e)) from None
                except GridConnectError:
                    # never sent: safe to fall back to HTTP for any op
                    self._gate.failed()
                except GridError:
                    self._gate.failed()
                    if op not in self._RETRYABLE:
                        # may have been applied remotely; resending over
                        # HTTP would violate the no-replay discipline
                        raise errors.DiskNotFound(
                            f"{self.endpoint} grid rpc {op} failed mid-flight"
                        ) from None
        path = f"{STORAGE_PREFIX}/{self.drive_index}/{op}"

        def attempt() -> tuple:
            # inside the retry loop: a transient injected fault (count- or
            # prob-limited) is absorbed exactly like a real blip would be
            self._check_net_fault(op)
            conn = self._conn()
            try:
                hdrs = {"x-minio-token": self.token,
                        "Content-Type": "application/msgpack"}
                if req_id:
                    hdrs["x-minio-reqid"] = req_id
                conn.request("POST", path, body=body, headers=hdrs)
                r = conn.getresponse()
                d = r.read()
            except (http.client.HTTPException, OSError):
                self._local.conn = None
                raise
            # internode accounting covers the HTTP plane too (bulk
            # shard bodies + grid fallback), not just the mux
            from .grid import stats_add

            stats_add("calls")
            stats_add("tx_bytes", len(body))
            stats_add("rx_bytes", len(d))
            return r, d

        # unified retry (fault/retry.py): transport failures resend only
        # for the idempotent op class, with jittered backoff
        policy = retry_mod.shared_policy(idempotent=op in self._RETRYABLE)
        try:
            resp, data = policy.run(
                attempt,
                retryable=lambda e: isinstance(
                    e, (http.client.HTTPException, OSError)
                ),
            )
        except (http.client.HTTPException, OSError):
            raise errors.DiskNotFound(f"{self.endpoint} unreachable") from None
        if resp.status == 460:
            err_type = _ERR_TYPES.get(
                resp.headers.get("x-storage-err", ""), errors.StorageError
            )
            raise err_type(data.decode("utf-8", "replace"))
        if resp.status == 403:
            raise errors.FileAccessDenied("internode auth failed")
        if resp.status != 200:
            raise errors.StorageError(f"storage rpc {op}: HTTP {resp.status}")
        return data

    # -- StorageAPI --------------------------------------------------------

    def disk_info(self) -> DiskInfo:
        d = msgpack.unpackb(self._rpc("diskinfo"), raw=False)
        di = DiskInfo()
        di.__dict__.update(d)
        return di

    def make_vol(self, volume: str) -> None:
        self._rpc("makevol", {"volume": volume})

    def list_vols(self) -> list[VolInfo]:
        return [
            VolInfo(n, c) for n, c in msgpack.unpackb(self._rpc("listvols"), raw=False)
        ]

    def stat_vol(self, volume: str) -> VolInfo:
        n, c = msgpack.unpackb(self._rpc("statvol", {"volume": volume}), raw=False)
        return VolInfo(n, c)

    def delete_vol(self, volume: str, force: bool = False) -> None:
        self._rpc("deletevol", {"volume": volume, "force": force})

    def write_metadata(self, volume: str, path: str, fi: FileInfo) -> None:
        self._rpc("writemetadata", {"volume": volume, "path": path, "fi": fi.to_dict()})

    def update_metadata(self, volume: str, path: str, fi: FileInfo) -> None:
        self._rpc("updatemetadata", {"volume": volume, "path": path, "fi": fi.to_dict()})

    def read_version(
        self, volume: str, path: str, version_id: str = "", read_data: bool = False
    ) -> FileInfo:
        d = msgpack.unpackb(
            self._rpc(
                "readversion",
                {"volume": volume, "path": path, "version_id": version_id,
                 "read_data": read_data},
            ),
            raw=False,
        )
        fi = _fi_unwire(d)
        fi.volume, fi.name = volume, path
        return fi

    def read_versions(self, volume: str, path: str) -> list[FileInfo]:
        out = msgpack.unpackb(
            self._rpc("readversions", {"volume": volume, "path": path}), raw=False
        )
        fis = [_fi_unwire(d) for d in out]
        for fi in fis:
            fi.volume, fi.name = volume, path
        return fis

    def delete_version(self, volume: str, path: str, fi: FileInfo) -> None:
        self._rpc("deleteversion", {"volume": volume, "path": path, "fi": fi.to_dict()})

    def delete_versions(self, volume, path, versions):
        out = []
        for fi in versions:
            try:
                self.delete_version(volume, path, fi)
                out.append(None)
            except Exception as e:  # noqa: BLE001
                out.append(e)
        return out

    def rename_data(
        self, src_volume: str, src_path: str, fi: FileInfo, dst_volume: str, dst_path: str
    ) -> None:
        self._rpc(
            "renamedata",
            {"src_volume": src_volume, "src_path": src_path, "fi": fi.to_dict(),
             "dst_volume": dst_volume, "dst_path": dst_path},
        )

    def create_file(self, volume: str, path: str, data: bytes | BinaryIO) -> None:
        if not isinstance(data, (bytes, bytearray, memoryview)):
            data = data.read()
        self._rpc("createfile", {"volume": volume, "path": path, "data": bytes(data)})

    def append_file(self, volume: str, path: str, data) -> None:
        if not isinstance(data, (bytes, bytearray, memoryview)):
            # writev vectors serialize at the RPC boundary — the one
            # legitimate copy on a remote-drive append, counted so the
            # zero-copy claim stays enumerable
            from ..erasure import bufpool

            bufpool.count_copy("append-rpc")
            data = b"".join(data)
        self._rpc("appendfile", {"volume": volume, "path": path, "data": data})

    def read_file(self, volume: str, path: str, offset: int = 0, length: int = -1) -> bytes:
        return self._rpc(
            "readfile", {"volume": volume, "path": path, "offset": offset, "length": length}
        )

    def read_file_stream(self, volume: str, path: str, offset: int, length: int):
        import io

        return io.BytesIO(self.read_file(volume, path, offset, length))

    def rename_file(self, src_volume, src_path, dst_volume, dst_path) -> None:
        self._rpc(
            "renamefile",
            {"src_volume": src_volume, "src_path": src_path,
             "dst_volume": dst_volume, "dst_path": dst_path},
        )

    def delete(self, volume: str, path: str, recursive: bool = False) -> None:
        self._rpc("delete", {"volume": volume, "path": path, "recursive": recursive})

    def list_dir(self, volume: str, path: str, count: int = -1) -> list[str]:
        return msgpack.unpackb(
            self._rpc("listdir", {"volume": volume, "path": path, "count": count}),
            raw=False,
        )

    def walk_dir(self, volume: str, base: str = "") -> Iterator[str]:
        after = ""
        g = self._gate.client()
        if g is not None:
            from .grid import GridError, RemoteError

            st = None
            try:
                st = g.stream(
                    "storage.walkdir",
                    msgpack.packb([self.drive_index, volume, base, after]),
                )
                while True:
                    item = st.recv()
                    if item is None:
                        return
                    for key in msgpack.unpackb(item, raw=False):
                        yield key
                        after = key
            except RemoteError as e:
                err_type = _ERR_TYPES.get(e.err_type, errors.StorageError)
                raise err_type(str(e)) from None
            except GridError:
                # keys stream in sorted walk order, so the HTTP pager below
                # resumes exactly after the last delivered key
                self._gate.failed()
            finally:
                # listings abandon per-drive walks early (k-way merge stops
                # at the prefix end); cancel tells the server to release
                # the handler parked on credits instead of leaking it
                if st is not None:
                    st.cancel()
        limit = 10000
        while True:
            page = msgpack.unpackb(
                self._rpc(
                    "walkdir",
                    {"volume": volume, "base": base, "after": after, "limit": limit},
                ),
                raw=False,
            )
            yield from page
            if len(page) < limit:
                return
            after = page[-1]

    def stat_info_file(self, volume: str, path: str) -> int:
        return msgpack.unpackb(
            self._rpc("statinfofile", {"volume": volume, "path": path}), raw=False
        )

    def verify_file(self, volume: str, path: str, fi: FileInfo) -> None:
        self._rpc("verifyfile", {"volume": volume, "path": path, "fi": fi.to_dict()})
